#!/usr/bin/env python
"""bench_trend — diff the latest two comparable bench runs per rung.

``bench.py`` appends one platform-tagged JSONL record per completed rung
to ``BENCH_HISTORY.jsonl`` (round 16 — before that nothing persisted
across runs and the perf trajectory was empty). This CLI pairs, for each
(rung, platform), the newest record with the newest EARLIER-run record
on the SAME platform (a cpu smoke never diffs against a tpu capture),
diffs every shared numeric metric, and flags moves past the threshold
(default 10%) in the metric's bad direction:

  * higher-is-better (tok/s, goodput, utilization, hit counts):
    a drop > threshold is a REGRESSION;
  * lower-is-better (latency ms/seconds, TTFT, walls, bytes):
    a rise > threshold is a REGRESSION.

Bookkeeping fields (wall_s, timestamps, compile counts) are skipped —
they vary run to run by design. Exit code: 0 by default (the trend is a
report); ``--fail-on-regress`` exits 1 when any regression is flagged
(the opt-in CI gate shape, like check_scoreboard's).

Usage:
    python tools/bench_trend.py                      # report all rungs
    python tools/bench_trend.py --rung llama_serving
    python tools/bench_trend.py --threshold 5 --fail-on-regress
    python tools/bench_trend.py --json
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_HISTORY = os.path.join(REPO, "BENCH_HISTORY.jsonl")

#: metric-name COMPONENTS (underscore-split) that mean LOWER is better;
#: everything else numeric defaults to higher-is-better (tok/s, goodput,
#: utilization). Whole-component match, not substring — "programs" or
#: "num_streams" must not match "ms"
LOWER_IS_BETTER = {"ms", "us", "s", "seconds", "latency", "ttft", "tpot",
                   "wall", "bytes", "stall", "p50", "p95", "p99",
                   "blocking", "mb", "hbm"}

#: components that FORCE higher-is-better even next to a lower-better
#: component (round 16: speculative acceptance rate — a metric like
#: "accept" must trend up no matter how a rung spells its neighbors)
HIGHER_IS_BETTER = {"accept", "goodput"}

#: bookkeeping keys never trended (vary run-to-run by design)
SKIP_KEYS = {"wall_s", "t", "rc", "platform", "note", "steps", "iters",
             "warmup", "batch", "seq_len", "obs"}


def _numeric_metrics(record: dict, prefix="") -> dict:
    """Flatten one rung record's top-level numeric fields (nested dicts
    one level deep, e.g. serving stats blocks)."""
    out = {}
    for k, v in record.items():
        if k in SKIP_KEYS:
            continue
        name = f"{prefix}{k}"
        if isinstance(v, bool):
            continue
        if isinstance(v, (int, float)):
            out[name] = float(v)
        elif isinstance(v, dict) and not prefix:
            out.update(_numeric_metrics(v, prefix=f"{k}."))
    return out


def lower_is_better(name: str) -> bool:
    leaf = name.rsplit(".", 1)[-1].lower()
    parts = leaf.split("_")
    if set(parts) & HIGHER_IS_BETTER:
        return False
    if "per" in parts:
        # a rate: judged by its NUMERATOR — time/bytes per item
        # ("us_per_op", "ms_per_token_step", "bytes_per_step") is
        # lower-better, items per time ("tokens_per_sec") higher-better
        parts = parts[: parts.index("per")]
    return bool(set(parts) & LOWER_IS_BETTER)


def load_history(path):
    rows = []
    with open(path) as fh:
        for ln in fh:
            ln = ln.strip()
            if not ln:
                continue
            try:
                rows.append(json.loads(ln))
            except json.JSONDecodeError:
                continue   # a torn tail line must not kill the report
    return rows


def latest_pairs(rows, rung=None):
    """For each (rung, platform): (previous, latest) records from two
    DIFFERENT runs, newest first — or None when only one run exists."""
    by_key: dict = {}
    for r in rows:
        if not isinstance(r, dict) or "rung" not in r:
            continue
        if rung and r["rung"] != rung:
            continue
        by_key.setdefault((r["rung"], r.get("platform")), []).append(r)
    pairs = {}
    for key, group in sorted(by_key.items()):
        group.sort(key=lambda r: r.get("t", 0.0))
        latest = group[-1]
        prev = next((r for r in reversed(group[:-1])
                     if r.get("run") != latest.get("run")), None)
        pairs[key] = (prev, latest)
    return pairs


def diff_pair(prev, latest, threshold_pct=10.0):
    """Per-metric deltas between two comparable records. Returns rows of
    {metric, before, after, delta_pct, direction, regression}."""
    a = _numeric_metrics(prev["record"])
    b = _numeric_metrics(latest["record"])
    out = []
    for name in sorted(set(a) & set(b)):
        before, after = a[name], b[name]
        if before == 0:
            continue
        delta = (after - before) / abs(before) * 100.0
        lib = lower_is_better(name)
        regressed = (delta > threshold_pct) if lib \
            else (delta < -threshold_pct)
        out.append({"metric": name, "before": before, "after": after,
                    "delta_pct": round(delta, 2),
                    "direction": "lower-better" if lib else
                    "higher-better",
                    "regression": bool(regressed)})
    return out


def trend(path=DEFAULT_HISTORY, rung=None, threshold_pct=10.0):
    rows = load_history(path)
    report = []
    for (name, platform), (prev, latest) in \
            latest_pairs(rows, rung=rung).items():
        entry = {"rung": name, "platform": platform,
                 "latest_run": latest.get("run")}
        if prev is None:
            entry["status"] = "single-run (nothing to diff yet)"
            entry["diffs"] = []
        else:
            entry["previous_run"] = prev.get("run")
            entry["diffs"] = diff_pair(prev, latest,
                                       threshold_pct=threshold_pct)
            regs = [d for d in entry["diffs"] if d["regression"]]
            entry["status"] = (f"{len(regs)} regression(s) past "
                               f"{threshold_pct:g}%" if regs else "ok")
        report.append(entry)
    return report


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--history", default=DEFAULT_HISTORY,
                    help=f"history file (default {DEFAULT_HISTORY})")
    ap.add_argument("--rung", default=None, help="only this rung")
    ap.add_argument("--threshold", type=float, default=10.0,
                    help="regression threshold in percent (default 10)")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--fail-on-regress", action="store_true",
                    help="exit 1 when any regression is flagged")
    args = ap.parse_args(argv)

    if not os.path.exists(args.history):
        print(f"no history at {args.history} — run bench.py first "
              "(every completed rung appends a record)")
        return 0
    report = trend(args.history, rung=args.rung,
                   threshold_pct=args.threshold)
    regressions = sum(
        1 for e in report for d in e["diffs"] if d["regression"])
    if args.as_json:
        print(json.dumps({"threshold_pct": args.threshold,
                          "regressions": regressions,
                          "rungs": report}, indent=2))
    else:
        for e in report:
            plat = e["platform"] or "?"
            print(f"{e['rung']} [{plat}]: {e['status']}")
            for d in e["diffs"]:
                flag = " <-- REGRESSION" if d["regression"] else ""
                print(f"    {d['metric']:<40} {d['before']:>12.4g} -> "
                      f"{d['after']:>12.4g}  ({d['delta_pct']:+.1f}%, "
                      f"{d['direction']}){flag}")
        print(f"\n{regressions} regression(s) past "
              f"{args.threshold:g}% across {len(report)} rung(s)")
    return 1 if (args.fail_on_regress and regressions) else 0


if __name__ == "__main__":
    raise SystemExit(main())
