"""Coverage report: paddle_tpu op surface vs the reference op registry.

Reference parity: /root/reference/paddle/phi/ops/yaml/ops.yaml is the
reference's single source of op truth (SURVEY §2 L4). This tool parses its
op names and checks each against paddle_tpu's public surface (top-level,
Tensor methods, nn.functional, linalg/fft/sparse namespaces) and the
single-source op table, writing OP_COVERAGE.md.

Usage: python tools/op_coverage.py [--yaml PATH]
"""
from __future__ import annotations

import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

YAML_DEFAULT = "/root/reference/paddle/phi/ops/yaml/ops.yaml"


def parse_op_names(path):
    names = []
    with open(path) as f:
        for ln in f:
            m = re.match(r"^- op\s*:\s*([a-zA-Z0-9_]+)", ln)
            if m:
                names.append(m.group(1))
    return names


def build_surface():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.ops import op_table

    op_table.ensure_populated()
    surface = {}
    for name in dir(paddle):
        if not name.startswith("_"):
            surface.setdefault(name, "paddle")
    for name in dir(Tensor):
        if not name.startswith("_"):
            surface.setdefault(name, "Tensor")
    import paddle_tpu.nn.functional as F

    for name in dir(F):
        if not name.startswith("_"):
            surface.setdefault(name, "F")
    import paddle_tpu.incubate.nn.functional as IF
    import paddle_tpu.nn as NN

    for name in dir(NN):
        if not name.startswith("_"):
            surface.setdefault(name, "nn")

    for name in dir(IF):
        if not name.startswith("_"):
            surface.setdefault(name, "incubate.F")
    for modname in ("linalg", "fft", "sparse", "signal", "geometric",
                    "incubate", "distributed", "distribution", "optimizer",
                    "metric", "vision", "text", "audio"):
        mod = getattr(paddle, modname, None)
        if mod is None:
            continue
        for name in dir(mod):
            if not name.startswith("_"):
                surface.setdefault(name, modname)
    for name in dir(paddle.vision.ops):
        if not name.startswith("_"):
            surface.setdefault(name, "vision.ops")
    # deep namespaces the shallow getattr loop can't reach
    import paddle_tpu.amp as _amp
    import paddle_tpu.device as _device
    import paddle_tpu.nn.utils as _nnutils
    import paddle_tpu.quantization as _quant
    from paddle_tpu.incubate.distributed.models.moe import moe_layer as _moe
    from paddle_tpu.quantization import ptq as _ptq

    _ampdbg = _amp.debugging
    for mod, tag in ((_quant, "quantization"), (_ptq, "quantization.ptq"),
                     (_amp, "amp"), (_ampdbg, "amp.debugging"),
                     (_device, "device"), (_nnutils, "nn.utils"),
                     (_moe, "incubate.moe")):
        for name in dir(mod):
            if not name.startswith("_"):
                surface.setdefault(name, tag)
    # case-insensitive view: reference op names are snake_case while e.g.
    # optimizers surface as classes (adamw_ -> AdamW)
    lower = {}
    for name, where in surface.items():
        lower.setdefault(name.lower().replace("_", ""), where)
    table = set(op_table.OPS)
    return surface, lower, table


#: reference-name -> our-name renames (op_compat.yaml-style)
RENAMES = {
    "elementwise_add": "add", "elementwise_sub": "subtract",
    "elementwise_mul": "multiply", "elementwise_div": "divide",
    "reduce_sum": "sum", "reduce_mean": "mean", "reduce_max": "max",
    "reduce_min": "min", "reduce_prod": "prod", "reduce_all": "all",
    "reduce_any": "any", "arg_max": "argmax", "arg_min": "argmin",
    "top_k": "topk", "fill_constant": "full", "lookup_table_v2": "embedding",
    "softmax_with_cross_entropy": "cross_entropy", "transpose2": "transpose",
    "reshape2": "reshape", "expand_v2": "expand", "sum_op": "add_n",
    "matmul_v2": "matmul", "elementwise_pow": "pow",
    "elementwise_mod": "mod", "elementwise_max": "maximum",
    "elementwise_min": "minimum", "hard_swish": "hardswish",
    "hard_sigmoid": "hardsigmoid", "hard_shrink": "hardshrink",
    "soft_shrink": "softshrink", "grid_sampler": "grid_sample",
    "bilinear_interp": "interpolate", "nearest_interp": "interpolate",
    "bce_loss": "binary_cross_entropy", "huber_loss": "smooth_l1_loss",
    "kldiv_loss": "kl_div", "frobenius_norm": "norm",
    "cross_entropy_with_softmax": "cross_entropy",
    "flash_attn": "flash_attention", "fft_c2c": "fft", "fft_r2c": "rfft",
    "fft_c2r": "irfft", "deformable_conv": "deform_conv2d",
    "depthwise_conv2d": "conv2d", "crf_decoding": "viterbi_decode",
    "clip_by_norm": "ClipGradByNorm",
    "check_finite_and_unscale_": "GradScaler",
    "global_gather": "MoELayer", "global_scatter": "MoELayer",
    "linear_interp": "interpolate", "bicubic_interp": "interpolate",
    "trilinear_interp": "interpolate", "dirichlet": "Dirichlet",
    "fill_diagonal": "fill_diagonal_", "gaussian_inplace": "normal_",
    "cudnn_lstm": "LSTM", "beam_search": "gather_tree",
    "fused_softmax_mask": "softmax", "matrix_rank_tol": "matrix_rank",
    "memcpy_d2h": "cpu", "memcpy_h2d": "cuda", "share_buffer": "clone",
    "depthwise_conv2d_transpose": "conv2d_transpose",
    "embedding_with_scaled_gradient": "embedding",
    "repeat_interleave_with_tensor_index": "repeat_interleave",
    "sigmoid_cross_entropy_with_logits": "binary_cross_entropy_with_logits",
    # ---- round-4 additions: same functionality under this framework's name
    "unpool": "max_unpool2d", "unpool3d": "max_unpool3d",
    "max_pool2d_with_index": "max_pool2d",   # return_mask=True path
    "max_pool3d_with_index": "max_pool3d",
    "pool2d": "max_pool2d", "pool3d": "max_pool3d",
    "p_norm": "norm", "l1_norm": "norm", "squared_l2_norm": "norm",
    "split_with_num": "split",
    "truncated_gaussian_random": "truncated_gaussian_random",
    "uniform_inplace": "uniform_",
    "uniform_random_batch_size_like": "uniform",
    "full_batch_size_like": "full_like", "full_int_array": "full",
    "full_with_tensor": "full", "shape64": "shape",
    "view_dtype": "view", "view_shape": "view", "view_slice": "as_strided",
    "copy_to": "to", "share_data": "detach",
    "assign_out_": "assign", "assign_value_": "assign",
    "trans_layout": "transpose",
    "memory_efficient_attention": "scaled_dot_product_attention",
    "calc_reduced_attn_scores": "scaled_dot_product_attention",
    "merged_adam_": "Adam",        # use_multi_tensor fused path
    "merged_momentum_": "Momentum",
    "coalesce_tensor": "Adam",     # multi-tensor buffer fusion lives there
    "update_loss_scaling_": "GradScaler",
    "average_accumulates_": "ModelAverage",
    "c_allreduce_sum": "all_reduce", "mp_allreduce_sum": "all_reduce",
    "c_concat": "all_gather", "c_scatter": "scatter", "c_split": "split",
    "c_identity": "identity",
    "partial_allgather": "all_gather", "partial_concat": "concat",
    "partial_sum": "add_n", "sync_calc_stream": "synchronize",
    "warpctc": "ctc_loss", "warprnnt": "rnnt_loss",
    "im2sequence": "unfold", "gru_unit": "GRUCell",
    "attention_lstm": "LSTM",
    "fused_batch_norm_act": "batch_norm",
    "fused_bn_add_activation": "batch_norm",
    "fused_softmax_mask_upper_triangle": "softmax",
    "conv2d_transpose_bias": "conv2d_transpose",
    "matrix_rank_atol_rtol": "matrix_rank",
    "set_value_with_tensor": "set_value",
    "index_select_strided": "index_select",
    "accuracy_check": "allclose",
    "check_numerics": "check_numerics",
    "disable_check_model_nan_inf": "check_numerics",
    "enable_check_model_nan_inf": "check_numerics",
    "segment_pool": "segment_sum",
    "shuffle_channel": "channel_shuffle", "shuffle_batch": "shuffle_batch",
    "multiclass_nms3": "matrix_nms",
    "yolo_box_head": "yolo_box", "yolo_box_post": "yolo_box",
    "collect_fpn_proposals": "distribute_fpn_proposals",
    "data": "to_tensor", "depend": "to_tensor",
    "fill_diagonal": "fill_diagonal_",
    "fill_diagonal_tensor": "fill_diagonal_tensor",
    # quantization framework covers the fake-quant kernel family
    "fake_quantize_abs_max": "FakeQuanterWithAbsMax",
    "fake_quantize_dequantize_abs_max": "FakeQuanterWithAbsMax",
    "fake_quantize_dequantize_moving_average_abs_max":
        "FakeQuanterWithAbsMax",
    "fake_quantize_moving_average_abs_max": "FakeQuanterWithAbsMax",
    "fake_quantize_range_abs_max": "FakeQuanterWithAbsMax",
    "fake_channel_wise_quantize_abs_max": "FakeQuanterWithAbsMax",
    "fake_channel_wise_quantize_dequantize_abs_max":
        "FakeQuanterWithAbsMax",
    "fake_channel_wise_dequantize_max_abs": "QuantizedLinear",
    "fake_dequantize_max_abs": "QuantizedLinear",
    "dequantize_abs_max": "QuantizedLinear",
    "weight_only_linear": "QuantizedLinear",
    "weight_quantize": "QuantizedLinear",
    "weight_dequantize": "QuantizedLinear",
    "llm_int8_linear": "QuantizedLinear",
    "apply_per_channel_scale": "QuantizedLinear",
    # MoE routing machinery lives inside the gates / EP layer
    "number_count": "MoELayer", "limit_by_capacity": "MoELayer",
    "prune_gate_by_capacity": "MoELayer", "assign_pos": "MoELayer",
    "random_routing": "MoELayer",
}


def _norm(key: str) -> str:
    """Normalize a table spec key / reference op name for matching: table
    specs are namespaced (act_relu, conv2d_op, softmax_axis0) while
    reference names are bare."""
    k = key.lower()
    for pre in ("act_",):
        if k.startswith(pre):
            k = k[len(pre):]
    for suf in ("_op", "_rev_axis", "_axis0", "_axis1", "_axis"):
        if k.endswith(suf):
            k = k[: -len(suf)]
    return k.replace("_", "")


def main(argv):
    path = YAML_DEFAULT
    if "--yaml" in argv:
        path = argv[argv.index("--yaml") + 1]
    ref_ops = parse_op_names(path)
    surface, lower, table = build_surface()
    table_norm = {_norm(t) for t in table}

    covered, missing = [], []
    for op in ref_ops:
        base = op[:-1] if op.endswith("_") else op  # inplace twins
        cands = [op, base, RENAMES.get(op), RENAMES.get(base),
                 base.replace("_grad", "")]
        where = None
        for c in cands:
            if c and c in surface:
                where = surface[c]
                break
        if where is None:
            for c in cands:
                if c and c.lower().replace("_", "") in lower:
                    where = lower[c.lower().replace("_", "")]
                    break
        if where:
            # in-table check uses the SAME candidate list as the surface
            # check (incl. renames) plus table-key normalization (specs are
            # namespaced act_*/..._op/..._axisN) — the pre-round-5 report
            # compared only the literal reference name and under-counted by
            # ~100 ops
            in_tab = any(c and _norm(c) in table_norm for c in cands)
            covered.append((op, where, in_tab))
        else:
            missing.append(op)

    from paddle_tpu.ops.op_table import SWEEP_WAIVERS

    pct = 100.0 * len(covered) / max(len(ref_ops), 1)
    in_table = sum(1 for _, _, t in covered if t)
    unaccounted = []
    waived = []
    for op, where, t in covered:
        if t:
            continue
        base = op[:-1] if op.endswith("_") else op
        w = None
        for c in (op, base, RENAMES.get(op), RENAMES.get(base)):
            if c and c in SWEEP_WAIVERS:
                w = (op, SWEEP_WAIVERS[c])
                break
        if w is not None:
            waived.append(w)
        else:
            unaccounted.append((op, where))
    lines = [
        "# OP_COVERAGE — paddle_tpu surface vs reference ops.yaml",
        "",
        f"Reference registry: `{path}` — **{len(ref_ops)} ops**.",
        f"Covered by paddle_tpu public surface: **{len(covered)} "
        f"({pct:.1f}%)**; of those, {in_table} are registered in the "
        "single-source op table (`paddle_tpu/ops/op_table.py` + "
        "`op_table_ext.py`) with auto-generated OpTest sweeps, and "
        f"{len(waived)} carry a written sweep waiver "
        "(`SWEEP_WAIVERS`: layer/optimizer/framework surfaces that are "
        "exercised by dedicated tests instead of the generic sweep).",
        f"Unaccounted (neither swept nor waived): {len(unaccounted)}.",
        "",
        f"## Missing ({len(missing)})",
        "",
        "Uncovered reference ops (mostly fused/hardware-specific kernels "
        "whose role XLA fusion already fills, legacy/deprecated ops, or "
        "framework-internal ops with no python surface):",
        "",
    ]
    for i in range(0, len(missing), 8):
        lines.append("  " + ", ".join(f"`{m}`" for m in missing[i:i + 8]))
    if unaccounted:
        lines += ["", f"## Covered but neither swept nor waived "
                  f"({len(unaccounted)})", ""]
        for i in range(0, len(unaccounted), 6):
            lines.append("  " + ", ".join(
                f"`{o}` ({w})" for o, w in unaccounted[i:i + 6]))
    if waived:
        lines += ["", f"## Sweep waivers ({len(waived)})", "",
                  "Reference ops whose surface is a layer/optimizer/"
                  "framework API (not a pure tensor-in/tensor-out op): the "
                  "generic grad-checked sweep cannot drive them; each names "
                  "the dedicated test that does.", ""]
        for op, why in sorted(waived):
            lines.append(f"- `{op}` — {why}")
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "OP_COVERAGE.md")
    with open(out, "w") as f:
        f.write("\n".join(lines) + "\n")
    print(f"{len(covered)}/{len(ref_ops)} covered ({pct:.1f}%), "
          f"{in_table} in op table, {len(waived)} waived, "
          f"{len(unaccounted)} unaccounted -> {out}")


if __name__ == "__main__":
    main(sys.argv[1:])
