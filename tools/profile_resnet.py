"""ResNet-50 conv-perf decomposition on the real chip (VERDICT r3 Weak #2:
'ResNet-50 MFU ~8%; do for config 2 what round 3 did for LLaMA').

Probes, each as an isolated jitted program (one JSON line each):
  conv_peak   — one big NHWC conv (the chip's conv roofline)
  fwd         — resnet50 forward only
  fwd_bwd     — forward + gradients
  train       — full train step (grads + momentum update + BN stats)
  train_nhwc  — same but with images fed NHWC (conversion cost probe)
  pieces      — stem / stages / head timed separately
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def _sync(t):
    jax.device_get(jnp.ravel(t._data if hasattr(t, "_data") else t)[0])


def timeit(f, iters=6, warmup=3):
    for _ in range(warmup):
        _sync(f())
    t0 = time.perf_counter()
    for _ in range(iters):
        out = f()
    _sync(out)
    return (time.perf_counter() - t0) / iters


def emit(name, ms, extra=None):
    rec = {"probe": name, "ms": round(ms * 1e3, 3)}
    rec.update(extra or {})
    print(json.dumps(rec), flush=True)


def main(batch=256):
    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_ccache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    # --- conv roofline: 3x3 conv on a mid-stage shape, bf16
    rs = np.random.RandomState(0)
    for (n, h, c_in, c_out, k) in [(batch, 28, 128, 128, 3),
                                   (batch, 14, 256, 256, 3),
                                   (batch, 56, 64, 64, 3)]:
        x = jnp.asarray(rs.randn(n, h, h, c_in), jnp.bfloat16)
        w = jnp.asarray(rs.randn(k, k, c_in, c_out), jnp.bfloat16)

        @jax.jit
        def conv(x, w):
            return jax.lax.conv_general_dilated(
                x, w, (1, 1), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))

        dt = timeit(lambda: conv(x, w))
        flops = 2 * n * h * h * c_in * c_out * k * k
        emit(f"conv_peak_{h}x{h}x{c_in}", dt,
             {"tflops": round(flops / dt / 1e12, 1)})

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.vision.models import resnet50

    paddle.seed(0)
    model = resnet50(num_classes=1000)
    model.train()
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=model.parameters())
    X = paddle.to_tensor(rs.randn(batch, 3, 224, 224).astype("float32"))
    Y = paddle.to_tensor(rs.randint(0, 1000, (batch,)).astype("int64"))

    @paddle.jit.to_static(share_discovery=True)
    def fwd(x):
        with paddle.amp.auto_cast(enable=True, dtype="bfloat16", level="O1"):
            return model(x)

    Xs = paddle.to_tensor(rs.randn(4, 3, 224, 224).astype("float32"))
    _sync(fwd(Xs)); _sync(fwd(Xs))
    dt = timeit(lambda: fwd(X))
    fwd_flops = 4.1e9 * batch
    emit("fwd", dt, {"imgs_per_sec": round(batch / dt, 1),
                     "tflops": round(fwd_flops / dt / 1e12, 1)})

    @paddle.jit.to_static(share_discovery=True)
    def fwd_bwd(x, y):
        with paddle.amp.auto_cast(enable=True, dtype="bfloat16", level="O1"):
            logits = model(x)
        loss = F.cross_entropy(logits.astype("float32"), y)
        loss.backward()
        opt.clear_grad()
        return loss

    Ys = paddle.to_tensor(rs.randint(0, 1000, (4,)).astype("int64"))
    _sync(fwd_bwd(Xs, Ys)); _sync(fwd_bwd(Xs, Ys))
    dt = timeit(lambda: fwd_bwd(X, Y))
    emit("fwd_bwd", dt, {"imgs_per_sec": round(batch / dt, 1),
                         "tflops": round(3 * fwd_flops / dt / 1e12, 1)})

    @paddle.jit.to_static(share_discovery=True)
    def train(x, y):
        with paddle.amp.auto_cast(enable=True, dtype="bfloat16", level="O1"):
            logits = model(x)
        loss = F.cross_entropy(logits.astype("float32"), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    _sync(train(Xs, Ys)); _sync(train(Xs, Ys))
    dt = timeit(lambda: train(X, Y))
    emit("train", dt, {"imgs_per_sec": round(batch / dt, 1),
                       "tflops": round(3 * fwd_flops / dt / 1e12, 1)})


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 256)
