#!/usr/bin/env python
"""Per-model graph-break report for to_static capture coverage.

Runs a callable under `paddle.jit.to_static` through all capture phases and
prints every site that prevented (or would prevent) whole-graph capture,
with file:line and a category:

  * transform-time sites — constructs the dy2static AST pass left as plain
    Python (return/break in a tensor branch, attribute stores, ...);
  * the capture outcome — ONE compiled program, or the fallback reason
    (branch shape mismatch, grad-through-while, raw bool()/.numpy() ...);
  * segmented-mode concretization sites — the user lines whose float()/
    bool()/.numpy() force each segment flush.

Usage:
    python tools/report_graph_breaks.py demo          # worked examples
    python tools/report_graph_breaks.py llama gpt bert  # model smoke
    python tools/report_graph_breaks.py --metrics-json llama
        # append one JSON object: per-model break/segment counts plus the
        # obs registry snapshot, compile-event counts per site and the
        # eager/segment cache stats (scoreboard- and dashboard-readable)
    # library:
    from report_graph_breaks import report, format_report
    rep = report(fn, args=(x,))

Capture-coverage regressions show up as new lines in this report — CI can
diff it per model (VERDICT r5: make graph breaks visible per-model).
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def report(fn, args=(), kwargs=None, calls=4, full_graph=False):
    """Run `fn` under to_static and collect its graph-break report dict
    (see CompiledFunction.graph_break_report)."""
    import warnings

    import paddle_tpu as paddle
    from paddle_tpu.jit.api import CompiledFunction

    kwargs = kwargs or {}
    sf = fn if isinstance(fn, CompiledFunction) \
        else paddle.jit.to_static(fn, full_graph=full_graph)
    warns = []
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        for _ in range(calls):
            sf(*args, **kwargs)
        warns = [str(m.message) for m in w
                 if "graph break" in str(m.message)]
    rep = sf.graph_break_report()
    rep["warnings"] = warns
    return rep


def format_report(rep) -> str:
    tr = rep["transform"]
    lines = [f"== {rep['function']} =="]
    if rep["compiled"]:
        lines.append("  capture: COMPILED — one XLA program, no graph "
                     "breaks")
    elif rep["segmented"]:
        lines.append(f"  capture: SEGMENTED ({rep['segments']} segment(s) "
                     "per call)")
    elif rep["eager"]:
        lines.append("  capture: EAGER fallback")
    else:
        lines.append("  capture: (not compiled yet — still warming up?)")
    if rep["break_reason"]:
        lines.append(f"  reason:  {rep['break_reason']}")
    if tr is not None:
        state = "transformed" if tr.transformed else \
            f"not transformed ({tr.skip_reason})"
        lines.append(f"  dy2static: {state}, {tr.converted} construct(s) "
                     "converted")
        for s in tr.sites:
            lines.append(f"    untransformed {s.kind} @ {s.loc} "
                         f"[{s.category}]: {s.reason}")
    for s in rep["break_sites"]:
        lines.append(f"    segment flush @ {s['loc']} in {s['in']} "
                     f"({s['kind']}, {s['ops_in_segment']} staged ops)")
    return "\n".join(lines)


# ------------------------------------------------------------- model smoke
def _smoke_llama():
    import paddle_tpu as paddle
    from paddle_tpu.text.models.llama import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(vocab_size=256, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      max_position_embeddings=128)
    model = LlamaForCausalLM(cfg)
    ids = paddle.to_tensor(
        np.random.randint(0, 256, (2, 16)).astype("int64"))
    labels = paddle.to_tensor(
        np.random.randint(0, 256, (2, 16)).astype("int64"))
    return model.forward, (ids, labels)


def _smoke_gpt():
    import paddle_tpu as paddle
    from paddle_tpu.text.models.gpt import GPTConfig, GPTForCausalLM

    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                    num_attention_heads=4, max_position_embeddings=128)
    model = GPTForCausalLM(cfg)
    ids = paddle.to_tensor(
        np.random.randint(0, 256, (2, 16)).astype("int64"))
    labels = paddle.to_tensor(
        np.random.randint(0, 256, (2, 16)).astype("int64"))
    return model.forward, (ids, labels)


def _smoke_bert():
    import paddle_tpu as paddle
    from paddle_tpu.text.models.bert import (BertConfig,
                                             BertForSequenceClassification)

    cfg = BertConfig(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                     num_attention_heads=4, intermediate_size=128,
                     max_position_embeddings=64)
    model = BertForSequenceClassification(cfg)
    ids = paddle.to_tensor(
        np.random.randint(0, 256, (2, 16)).astype("int64"))
    labels = paddle.to_tensor(np.random.randint(0, 2, (2,)).astype("int64"))
    return model.forward, (ids, None, labels)


def _smoke_demo():
    """Worked examples: one capturable, one with a known fallback."""
    import paddle_tpu as paddle

    def captured(x):
        if x.sum() > 0:
            y = x * 2
        else:
            y = x * 3
        i = paddle.to_tensor(0)
        s = paddle.zeros([], dtype="float32")
        while i < 4:
            i = i + 1
            s = s + y.sum()
        return s

    def breaker(x):
        # `return` inside a tensor branch: left untransformed, predicate
        # concretization then splits segments
        if float(x.sum().numpy()) > 0:
            return x * 2
        return x * 3

    x = np.ones((3,), "float32")
    import paddle_tpu as p

    return [("captured", captured, (p.to_tensor(x),)),
            ("breaker", breaker, (p.to_tensor(x),))]


SMOKES = {"llama": _smoke_llama, "gpt": _smoke_gpt, "bert": _smoke_bert}


def metrics_snapshot(reports=None) -> dict:
    """Registry + watchdog + cache telemetry for --metrics-json: what a
    dashboard needs to see capture-coverage / retrace regressions
    without parsing the text report."""
    import paddle_tpu  # noqa: F401 (registries live under it)
    from paddle_tpu import obs
    from paddle_tpu.core.dispatch import eager_cache_info
    from paddle_tpu.core.lazy import flush_info

    out = {
        "compile_events": obs.compile_counts(),
        "post_warmup_compiles": obs.post_warmup_compiles(),
        "eager_cache": eager_cache_info(),
        "lazy_segments": flush_info(),
        "registry": obs.default_registry().to_dict(),
    }
    if reports is not None:
        out["models"] = {
            name: {"compiled": rep["compiled"],
                   "segmented": rep["segmented"],
                   "eager": rep["eager"],
                   "segments": rep["segments"],
                   "break_sites": len(rep["break_sites"]),
                   "untransformed": (len(rep["transform"].sites)
                                     if rep["transform"] is not None
                                     else None)}
            for name, rep in reports.items()}
    return out


def main(argv):
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    as_json = "--metrics-json" in argv
    argv = [a for a in argv if a != "--metrics-json"]
    names = argv or ["demo", "llama", "gpt", "bert"]
    ok = True
    reports = {}
    for name in names:
        if name == "demo":
            for tag, fn, args in _smoke_demo():
                rep = report(fn, args)
                print(format_report(rep))
        elif name in SMOKES:
            fn, args = SMOKES[name]()
            rep = report(fn, args)
            reports[name] = rep
            print(format_report(rep))
            ok = ok and (rep["compiled"] or rep["segmented"])
        else:
            print(f"unknown target '{name}' (choose from demo, "
                  f"{', '.join(SMOKES)})")
            ok = False
    if as_json:
        import json

        print("METRICS_JSON " + json.dumps(metrics_snapshot(reports)))
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
