"""Chunk-size sweep for the fused linear+cross-entropy tail (round 6).

Measures, ON THE CHIP, the flagship lm_head+CE configuration ([tokens, H] @
[H, 32000] + CE, fwd+bwd) across:

  - the unfused full-logits baseline,
  - the vocab-chunked path at several chunk sizes,
  - the token(sequence)-chunked path at several chunk sizes,

each as ONE jitted program chained over `reps` iterations so the ~13-17 ms
tunnel invocation overhead amortizes (the protocol PERF.md mandates).
Prints a JSON table for PERF.md; pick the winner via FLAGS_flce_chunk_axis
/ FLAGS_flce_token_chunk.

Usage: python tools/sweep_ce_chunk.py [tokens] [hidden] [vocab]
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from paddle_tpu.incubate.nn.functional.fused_loss import (  # noqa: E402
    _best_chunk, _flce, _flce_tok)


def _time(fn, *args, iters=6, warmup=2):
    for _ in range(warmup):
        out = fn(*args)
    jax.device_get(jnp.ravel(out[0] if isinstance(out, tuple) else out)[0])
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.device_get(jnp.ravel(out[0] if isinstance(out, tuple) else out)[0])
    return (time.perf_counter() - t0) / iters


def main(n=4096, hid=2048, v=32000, dtype="bfloat16"):
    rs = np.random.RandomState(0)
    dt = jnp.dtype(dtype)
    h = jnp.asarray(rs.randn(n, hid).astype("float32") * 0.1, dt)
    w = jnp.asarray(rs.randn(hid, v).astype("float32") * 0.02, dt)
    lab = jnp.asarray(rs.randint(0, v, (n,)).astype("int32"))

    rows = []

    def grad_of(loss_fn):
        return jax.jit(jax.grad(loss_fn, argnums=(0, 1)))

    def plain(hh, ww):
        logits = (hh.astype(jnp.float32) @ ww.astype(jnp.float32))
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(logits, lab[:, None], axis=1)[:, 0]
        return jnp.mean(lse - picked)

    dt_s = _time(grad_of(plain), h, w)
    rows.append({"path": "unfused_full_logits", "ms": dt_s * 1e3})

    for chunk in (1600, 3200, 6400, 8000, 16000):
        c = _best_chunk(v, chunk)
        if not c or any(r.get("chunk") == c and r["path"] == "vocab"
                        for r in rows):
            continue
        fn = grad_of(lambda hh, ww, c=c: _flce(hh, ww, lab, c, -100))
        rows.append({"path": "vocab", "chunk": c, "ms": _time(fn, h, w) * 1e3})

    for cn in (256, 512, 1024, 2048, 4096):
        if cn > n:
            continue
        # ragged n: pad with ignored labels (like the public wrapper) so
        # every row processes ALL n tokens and timings stay comparable
        pad = (-n) % cn

        def loss_fn(hh, ww, cn=cn, pad=pad):
            if pad:
                hh = jnp.pad(hh, ((0, pad), (0, 0)))
            lp = jnp.pad(lab, (0, pad), constant_values=-1)
            return _flce_tok(hh, ww, lp, cn, -100)

        rows.append({"path": "tokens", "chunk": cn,
                     "ms": _time(grad_of(loss_fn), h, w) * 1e3})

    base = rows[0]["ms"]
    for r in rows:
        r["vs_unfused"] = round(base / r["ms"], 2)
        r["ms"] = round(r["ms"], 2)
    out = {"shape": [n, hid, v], "dtype": dtype,
           "platform": jax.devices()[0].platform, "rows": rows}
    print(json.dumps(out, indent=2))


if __name__ == "__main__":
    args = [int(a) for a in sys.argv[1:4]]
    main(*args)
