#!/usr/bin/env python
"""autoplan_report — rank MeshConfigs for a model BEFORE anything runs.

Drives `paddle_tpu.distributed.partitioner.autoplan.search`: one
abstract lowering of the model's train step (jax.make_jaxpr — nothing
executes, no devices are touched), then every MeshConfig that survives
the rule-table guards is scored by the static cost model
(paddle_tpu/analysis/costmodel.py): roofline compute/HBM at
FLAGS_obs_peak_tflops / FLAGS_obs_peak_gbps, an alpha-beta ICI/DCN
collective bill (FLAGS_analysis_ici_gbps / FLAGS_analysis_dcn_gbps and
their alpha flags; axis→fabric per MeshConfig.dcn_axes), and a
liveness peak-HBM pass honoring donation and per-device shard sizes.
Candidates over FLAGS_analysis_hbm_limit_mb are rejected statically
with a named `plan-hbm` Finding — an OOM caught here, not on the pod.

The table is the same PlanReport the graft_lint `plan` smoke and the
bench `autoplan` rung gate with D18 (audit_plan) / D19
(audit_cost_model_calibration).

Usage:
    python tools/autoplan_report.py                    # tiny-LLaMA, 8 dev
    python tools/autoplan_report.py --devices 16 --batch 16 --seq 256
    python tools/autoplan_report.py --hidden 2048 --layers 22 --heads 16
    python tools/autoplan_report.py --hbm-limit-mb 96 --json
    python tools/autoplan_report.py --dcn-axes data     # data axis on DCN
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--devices", type=int, default=8,
                    help="pod size to plan for (default 8 — matches the "
                         "virtual CPU mesh this tool forces off-chip)")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--hidden", type=int, default=128,
                    help="model width (tiny-LLaMA geometry flags — the "
                         "plan is a function of shapes only)")
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--intermediate", type=int, default=0,
                    help="MLP width (default 2*hidden)")
    ap.add_argument("--no-sep", action="store_true",
                    help="skip sep (context-parallel) candidates")
    ap.add_argument("--dcn-axes", default="",
                    help="comma-separated mesh axes that cross the DCN "
                         "(slow fabric) instead of ICI")
    ap.add_argument("--hbm-limit-mb", type=float, default=None,
                    help="reject candidates whose predicted peak HBM "
                         "exceeds this (default "
                         "FLAGS_analysis_hbm_limit_mb; 0 = off)")
    ap.add_argument("--top", type=int, default=0,
                    help="print only the best N candidates (0 = all)")
    ap.add_argument("--json", dest="as_json", action="store_true")
    args = ap.parse_args(argv)

    # planning is abstract, but building the model needs a backend —
    # force the same virtual CPU platform the test suite / lint smokes
    # use so this tool runs identically on a dev box and on the pod host
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    xla_flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in xla_flags:
        os.environ["XLA_FLAGS"] = (
            xla_flags + " --xla_force_host_platform_device_count=8").strip()
    if os.environ["JAX_PLATFORMS"] == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as paddle
    from paddle_tpu.distributed.partitioner import autoplan
    from paddle_tpu.text.models import LlamaForCausalLM, llama_tiny_config

    paddle.seed(0)
    cfg = llama_tiny_config(
        vocab_size=args.vocab, hidden_size=args.hidden,
        intermediate_size=args.intermediate or 2 * args.hidden,
        num_hidden_layers=args.layers, num_attention_heads=args.heads,
        max_position_embeddings=max(args.seq, 128))
    model = LlamaForCausalLM(cfg)
    dcn = tuple(a for a in args.dcn_axes.split(",") if a)
    report = autoplan.search(model, args.devices, batch=args.batch,
                             seq=args.seq, include_sep=not args.no_sep,
                             hbm_limit_mb=args.hbm_limit_mb,
                             dcn_axes=dcn)
    if args.top > 0:
        report.candidates = report.top(args.top)
    if args.as_json:
        print(json.dumps(report.to_dict(), indent=2))
    else:
        print(report.format_text())
        for f in report.findings:
            print(f"[{f.severity}/{f.detector}] {f.loc}: {f.message}")
    return 0 if report.candidates else 1


if __name__ == "__main__":
    raise SystemExit(main())
