"""Capture an xplane trace of the LLaMA train step on the real chip and
print the device op-time breakdown (VERDICT r2 item 2 'committed breakdown').

Uses paddle_tpu.profiler's jax.profiler bridge + tensorboard_plugin_profile
to parse the xplane into per-op totals.
"""
from __future__ import annotations

import glob
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def _sync(t):
    jax.device_get(jnp.ravel(t._data if hasattr(t, "_data") else t)[0])


def main(batch=8, seq=1024, logdir="/tmp/llama_trace", config="168m",
         remat="mlp"):
    """config="168m" (default) profiles the proxy; config="1b" profiles the
    REAL 1.14B flagship step (pass batch/remat to match the bench row, e.g.
    `python tools/profile_llama.py 4 1024 /tmp/t 1b flash_resident`) — the
    round-6 xplane capture that drives the PERF.md breakdown."""
    import paddle_tpu as paddle
    from paddle_tpu.text.models import LlamaConfig, LlamaForCausalLM

    paddle.seed(0)
    if config == "1b":
        cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                          intermediate_size=5504, num_hidden_layers=20,
                          num_attention_heads=16,
                          max_position_embeddings=seq,
                          use_recompute=True, recompute_granularity=remat)
    else:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=1024,
                          intermediate_size=2816, num_hidden_layers=8,
                          num_attention_heads=16,
                          max_position_embeddings=seq)
    model = LlamaForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    if config == "1b":
        # match the bench_llama_1b row: bf16 params + bf16 AdamW moments
        model, opt = paddle.amp.decorate(model, opt, level="O2",
                                         dtype="bfloat16",
                                         master_weight=False)
    rs = np.random.RandomState(0)
    ids = paddle.to_tensor(rs.randint(0, 32000, (batch, seq)).astype("int64"))
    small = paddle.to_tensor(rs.randint(0, 32000, (1, 128)).astype("int64"))

    @paddle.jit.to_static(share_discovery=True)
    def train_step(x):
        with paddle.amp.auto_cast(enable=True, dtype="bfloat16", level="O2"):
            loss = model(x, x)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    _sync(train_step(small))
    _sync(train_step(small))
    for _ in range(3):
        _sync(train_step(ids))

    os.makedirs(logdir, exist_ok=True)
    with jax.profiler.trace(logdir):
        for _ in range(4):
            out = train_step(ids)
        _sync(out)

    xs = sorted(glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                          recursive=True), key=os.path.getmtime)
    if not xs:
        print("no xplane captured", file=sys.stderr)
        return
    from tensorboard_plugin_profile.convert import raw_to_tool_data

    data, _ = raw_to_tool_data.xspace_to_tool_data(
        [xs[-1]], "framework_op_stats", params={})
    rows = json.loads(data) if isinstance(data, (str, bytes)) else data
    print(json.dumps(rows)[:200], file=sys.stderr)
    # framework_op_stats returns a list-of-dicts table; fall back to raw dump
    with open("/tmp/op_stats.json", "w") as f:
        json.dump(rows, f, indent=1)
    print("wrote /tmp/op_stats.json")


if __name__ == "__main__":
    a = sys.argv[1:]
    main(batch=int(a[0]) if len(a) > 0 else 8,
         seq=int(a[1]) if len(a) > 1 else 1024,
         logdir=a[2] if len(a) > 2 else "/tmp/llama_trace",
         config=a[3] if len(a) > 3 else "168m",
         remat=a[4] if len(a) > 4 else "mlp")
