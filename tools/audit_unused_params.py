"""AST audit: public functions/methods accepting parameters they never read.

VERDICT r3 Weak #5 follow-up: accepted-but-ignored arguments must either
work or raise — a silently dropped kwarg (`return_mask`, `divisor_override`,
`ceil_mode`...) produces silently wrong results. This tool walks every
function in paddle_tpu and flags parameters that are never referenced in the
body (including nested functions/lambdas/comprehensions).

Allowlisted-by-convention names (reported separately, not counted):
  - `name`   — paddle's op-name kwarg, a no-op in dygraph in the reference too
  - `*args`/`**kwargs` pass-through catch-alls

Usage: python tools/audit_unused_params.py [--all]  (writes PARAM_AUDIT.md)
"""
from __future__ import annotations

import ast
import os
import sys

ROOT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "paddle_tpu")

# Conventional no-op parameter names: `name` mirrors the reference's dygraph
# behavior (ignored there as well); dtype-style hints on wrappers that
# delegate dtype handling are individually justified below.
CONVENTIONAL = {"name", "self", "cls"}

# file-prefix waivers: whole compat/config surfaces documented as
# accepted-no-effect (VERDICT r3 "padded files" list — config, not logic)
FILE_WAIVERS = {
    "core/flags_compat.py": "documented accepted-no-effect flag table",
    "static/__init__.py": "by-design NotImplementedError stubs (SURVEY §7)",
    "tensorrt.py": "by-design stub namespace",
    "onnx/__init__.py": "by-design stub namespace",
    # base Callback's on_* methods are abstract hook signatures — their
    # params exist for subclasses to read
    "hapi/callbacks.py": "abstract hook signatures / veneer params",
}

# parameter-name waivers: names whose no-op IS the correct TPU-native
# behavior, reviewed once and justified here (applies repo-wide)
PARAM_WAIVERS = {
    "sync_op": "XLA collectives are synchronous in-program; there is no "
               "async comm queue in the mesh design (SURVEY §2.4)",
    "use_calc_stream": "same: no separate comm stream under XLA",
    "group": "guarded-eager paths resolve communication through the global "
             "mesh/topology in the single-controller design",
    "mp_group": "model-parallel group comes from the global topology (mesh "
                "axes), not a per-layer handle",
    "dp_group": "same — data-parallel axis comes from the mesh",
    "ring_id": "legacy NCCL ring selector; XLA picks collective routes",
    "src": "single-controller SPMD: one logical buffer per rank-set, the "
           "source rank is implicit (documented deviation in reduce/scatter)",
    "dst": "same single-controller semantics (reduce delivers everywhere, "
           "documented superset)",
    "blocking": "device transfers are async under XLA dependency tracking; "
                "there is no blocking copy to request",
    "device": "one logical device per process; placement is runtime-owned",
    "stream": "XLA runtime owns streams; no user-visible stream objects",
    "event": "same — event sync is implicit in the dataflow",
    "priority": "no user-schedulable streams",
    "interprocess": "no CUDA IPC analog",
    "enable_timing": "events carry no timing; use the profiler subsystem",
    "fuse_matmul_bias": "XLA fuses bias adds into GEMMs unconditionally",
    "find_unused_parameters": "no reducer buckets — grads come from one "
                              "compiled backward, unused params get zeros",
    "comm_buffer_size": "no gradient bucketing: ZeRO/allreduce ride "
                        "compiled collectives",
    "last_comm_buffer_size": "same",
    "strategy": "legacy fleet strategy objects; mesh config supersedes",
    "sparse": "sparse-gradient embedding is a CUDA memory optimization; "
              "XLA scatter-adds dense grads",
    "is_sparse": "same",
    "is_custom": "same legacy hsigmoid knob",
    "lazy_mode": "sparse adam rows don't exist — dense fused update",
    "use_reentrant": "single recompute mechanism (jax.checkpoint)",
    "numeric_stable_mode": "the TPU softmax-CE path is always the stable "
                           "log-sum-exp formulation",
    "use_promote": "O2 promote rules are always on in the dispatch caster",
    "debug_mode": "checker runs synchronously; no async debug pipeline",
    "force_reload": "hub entry modules are re-imported on every load call "
                    "(no cache to invalidate)",
    "persistent_workers": "worker pool lifetime is managed by the loader",
    "use_buffer_reader": "prefetch is always on (shm ring)",
    "places": "static-graph executor placement; single logical device",
    "feed_list": "static-graph feed vars; dygraph loader needs none",
    "return_list": "always returns lists in dygraph (reference does too)",
    "use_pipe": "shared-memory ring is the only transport",
    "sorted_eids": "sampler output order is deterministic already",
    "perm_buffer": "no preallocated permutation buffers needed under XLA",
    "index_buffer": "same",
    "value_buffer": "same",
    "assume_unique": "jnp.isin has no fast-path toggle; result identical",
    "is_arithmetic": "arithmetic and logical LEFT shifts are identical",
    "driver": "LAPACK driver choice; XLA picks its own lstsq lowering",
    "hermitian": "rank via SVD is exact for hermitian inputs too",
    "niter": "exact SVD beats randomized iterations for accuracy",
    "stable": "jnp sort/argsort here always run stable (superset); "
              "descending+stable handled explicitly",
    "sorted": "topk always returns sorted results (valid superset)",
    "fixed_seed_offset": "dropout keys come from the global threaded PRNG",
    "rng_name": "same",
    "do_model_average": "model-average optimizer path is explicit "
                        "(incubate ModelAverage), not a per-param flag",
    "auto_skip_clip": "clip always validates finiteness explicitly",
    "group_name": "legacy static-graph clip grouping",
    "error_if_nonfinite": "implemented (raises)",  # safety: used now
    "curve": "validated (raises on non-ROC)",
    "executor": "static-graph executors don't exist; jit/XLA runtime",
    "main_program": "same — no ProgramDesc",
    "startup_program": "same",
    "no_grad_set": "tape computes exactly the requested grads",
    "batch_size": "shape comes from the tensors themselves",
    "correct": "legacy out-param (now filled when passed)",
    "total": "same",
    "skip_mismatch": "implemented",
    "include_sublayers": "implemented",
    "use_hook": "implemented",
    "use_structured_name": "implemented",
    "second_policy": "implemented (all/none/random)",
    "backend": "validated; PIL is the only decoder in this build / gloo-era "
               "comm backend selectors resolve to the mesh",
    "download": "validated; raises when True (no network)",
    "timeout": "collective timeouts are watchdog-level (comm_watchdog), "
               "not per-group",
    "key": "subm conv rulebook reuse is an identity-hash cache internally",
    "data_format": "validated or transposed where it changes results; "
                   "sparse conv is channel-last-only like the reference",
    "padding_mode": "implemented where it changes results (RandomCrop/Pad); "
                    "sparse conv supports zeros only",
    "weight_attr": "sparse-layer param attrs route through create_parameter",
    "name_prefix": "cosmetic parameter naming",
    "mode": "veneer knobs on engine/predictor stubs documented as such",
    "amp_configs": "implemented (auto_cast in train/eval batches)",
    "generator": "implemented (seeded split)",
    "inplace": "implemented (deepcopy when False)",
    "configs": "legacy save/load config dicts (SaveLoadConfig-era)",
    "verbose": "implemented where output exists; veneer elsewhere",
    "log_freq": "implemented (threaded to callbacks)",
    "steps": "evaluation bounded via num_samples; steps is its legacy twin",
    "num_samples": "implemented",
    "callbacks": "engine veneer (static Engine delegates to hapi Model)",
    "save_freq": "same engine veneer",
    "steps_per_iter": "same",
    "valid_freq": "same",
    "labels_spec": "auto-parallel spec inference reads shapes from data",
    "cluster": "auto-tuner cost model owns cluster topology",
    "process_group": "checkpoint IO is per-host file IO; no group comm",
    "master_endpoint": "rpc bootstrap uses the coordination service env",
    "graceful": "rpc shutdown drains synchronously either way",
    "rank_id": "gloo-era bootstrap; coordination service owns ranks",
    "rank_num": "same",
    "server_endpoint": "same",
    "worker_num": "same",
    "current_id": "same",
    "is_collective": "launch is always collective-mode here",
    "log_level": "launcher logging is per-rank files",
    "exclude_layer": "group-sharded wrapping covers all trainable layers",
    "segment_size": "no segment bucketing — one compiled update",
    "buffer_max_size": "same",
    "sync_buffers": "buffers live in the one logical model",
    "sync_comm": "same",
    "offload": "host offload is explicit via checkpoint/remat policies",
    "scale_fn": "implemented (CyclicLR custom scaling)",
    "scale_mode": "implemented",
    "three_phase": "implemented (OneCycleLR)",
    "epoch": "implemented (sets last_epoch)",
    "batch_axis": "implemented (vmapped per-sample jacobian/hessian)",
    "divisor_override": "implemented",
    "return_mask": "implemented",
    "ceil_mode": "implemented",
    "align_corners": "implemented",
    "align_mode": "implemented",
    "dilation": "implemented (BottleneckBlock) or raises (BasicBlock)",
    "dilate": "implemented (replace-stride-with-dilation)",
    "pretrained": "raises with pointer message (no network)",
    "arch": "used in the pretrained error message",
    "interpolation": "implemented (nearest/bilinear warps, resize modes)",
    "to_rgb": "implemented (BGR flip)",
    "encoding": "validated (PCM_16 only)",
    "save_dtype": "implemented (state-dict cast hook)",
    "initial_states": "implemented",
    "sequence_length": "implemented (masked scan) in nn.rnn; birnn "
                       "extended variants pending",
    "cache": "implemented (Cache/StaticCache protocol)",
    "include_self": "implemented (identity-element scatter)",
    "broadcast": "implemented (take/put_along_axis)",
}

# exact (file-suffix, function, param) waivers for cases the name rules
# shouldn't cover globally
SPECIFIC_WAIVERS = {
    ("incubate/nn/functional/__init__.py", "masked_multihead_attention"):
        "decode-path params wired in the generation rework (round 4 "
        "decode task); quantization shifts raise if passed",
    ("incubate/nn/functional/__init__.py", "fused_multi_transformer"):
        "distributed-era knobs on the fused veneer",
    ("incubate/nn/functional/__init__.py",
     "variable_length_memory_efficient_attention"):
        "pre-cache path pending the decode task",
    ("incubate/nn/functional/__init__.py", "blha_get_max_len"):
        "shape-only helper (reads lengths, batch implied)",
    ("incubate/nn/functional/__init__.py", "f"):
        "inner closure, not public API",
    ("vision/ops.py", "one"): "inner closure, not public API",
    ("nn/initializer.py", "__call__"):
        "block arg is static-graph-era; initializers act on the tensor",
    ("jit/api.py", "__get__"): "descriptor protocol signature",
    ("jit/api.py", "_run"): "internal",
    ("audio/datasets.py", "_fold_of"): "internal helper",
    ("optimizer/__init__.py", "_apply_one"):
        "per-op update hooks receive the full context; some rules read "
        "only a subset",
    ("hapi/summary.py", "hook"): "forward-hook signature (ins unused)",
    ("metric/__init__.py", "compute"): "base-class hook signature",
    ("metric/__init__.py", "update"): "base-class hook signature",
    ("profiler/__init__.py", "_default_scheduler"):
        "scheduler callback signature",
    ("profiler/__init__.py", "__init__"):
        "record_shapes/profile_memory/with_flops/targets: the jax xplane "
        "capture embeds shapes, memory and FLOPs natively — the knobs "
        "cannot disable what the backend always records",
    ("__init__.py", "disable_static"):
        "static-era placement arg; dygraph is the only mode",
    ("distributed/auto_parallel/parallelize.py", "apply"):
        "plan application binds layers to the GLOBAL mesh topology "
        "(fleet axes); the mesh arg is kept for reference API parity",
    ("distributed/auto_parallel/placement.py", "is_shard"):
        "polymorphic signature: non-Shard placements answer False for "
        "any dim (Shard overrides and reads dim)",
    ("distributed/extended.py", "__init__"): "PS/static-era config veneer "
        "(SURVEY §7 keep-API-stubs)",
    ("distributed/extended.py", "apply"): "same PS/static-era veneer",
    ("distributed/extended.py", "post_hook"): "hook protocol signature",
    ("distributed/extended.py", "pre_hook"): "hook protocol signature",
    ("distributed/extended.py", "to_distributed"):
        "device/node counts come from the launcher env in this design",
    ("distributed/extended.py", "to_static"):
        "static-era input_spec on the PS veneer",
    ("distributed/meta_parallel/sp_utils.py", "apply"):
        "sequence-parallel axis is fixed by the hybrid topology",
    ("distributed/meta_parallel/sp_utils.py",
     "register_sequence_parallel_allreduce_hooks"):
        "grads flow through the compiled collective path; no python hooks "
        "to attach (accepted for API parity)",
    ("distributed/passes/__init__.py", "apply"):
        "pass context carried for API parity; TPU passes act via jit/amp/"
        "sharding config, not program rewrite",
    ("distributed/utils/moe_utils.py", "global_gather"):
        "single-process identity; multi-process raises (EP all-to-all over "
        "the mesh is the real path, moe_layer.py)",
    ("distributed/utils/moe_utils.py", "global_scatter"): "same",
    ("inference/predictor.py", "enable_use_gpu"):
        "XLA owns the memory pool; the MB hint has no analog",
    ("jit/api.py", "__init__"):
        "build_strategy is CINN-era; input_spec shape specialization is "
        "call-site-driven (bucketed traces) — spec accepted for parity",
    ("jit/api.py", "ignore_module"):
        "no bytecode transform to exempt modules from",
    ("ops/extras.py", "create_tensor"):
        "persistable is a static-graph var property",
    ("sparse/__init__.py", "sparse_coo_tensor"):
        "one logical device; placement is runtime-owned",
    ("nn/layer/norm.py", "__init__"):
        "InstanceNorm momentum: the reference layer also accepts-ignores "
        "it (no running stats tracked)",
    ("vision/ops.py", "yolo_box"):
        "iou_aware_factor only applies when iou_aware=True, which raises",
}


def _used_names(node):
    used = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            used.add(sub.id)
        elif isinstance(sub, ast.Attribute):
            pass  # attribute bases appear as Name loads already
    return used


def _params(fn):
    a = fn.args
    out = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        out.append(a.vararg.arg)
    if a.kwarg:
        out.append(a.kwarg.arg)
    return out


def audit_file(path):
    rel = os.path.relpath(path, ROOT)
    with open(path) as f:
        try:
            tree = ast.parse(f.read())
        except SyntaxError as e:
            return [(rel, "<parse>", str(e), "error")]
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        body = ast.Module(body=node.body, type_ignores=[])
        used = _used_names(body)
        # a bare `raise` / NotImplementedError body is an honest stub
        is_stub = any(isinstance(s, ast.Raise) for s in node.body[:2])
        for p in _params(node):
            if p in CONVENTIONAL or p.startswith("_"):
                continue
            if p not in used:
                kind = "stub" if is_stub else "UNUSED"
                findings.append((rel, node.name, p, kind))
    return findings


def main(argv):
    show_all = "--all" in argv
    rows = []
    for dirpath, dirnames, filenames in os.walk(ROOT):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                rows.extend(audit_file(os.path.join(dirpath, fn)))

    unused = [r for r in rows if r[3] == "UNUSED"]
    waived, failing = [], []
    for r in unused:
        rel, fn, p, _ = r
        w = next((v for k, v in FILE_WAIVERS.items() if rel.startswith(k)),
                 None)
        if w is None:
            w = SPECIFIC_WAIVERS.get((rel, fn))
        if w is None:
            w = PARAM_WAIVERS.get(p)
        if w is None and p in ("kw", "kwargs", "args", "a", "k"):
            w = "catch-all compat kwargs"
        if w is None and fn == "__exit__":
            w = "context-manager protocol signature"
        (waived if w else failing).append((r, w))

    out = ["# Accepted-but-unused parameter audit",
           "",
           f"Generated by `tools/audit_unused_params.py` over `paddle_tpu/`.",
           f"Total function defs scanned: every .py under paddle_tpu.",
           f"UNUSED (non-stub, non-waived): **{len(failing)}**",
           f"Waived (documented compat surfaces): {len(waived)}",
           f"Honest stubs (body raises): {sum(1 for r in rows if r[3] == 'stub')}",
           ""]
    if failing:
        out.append("## FAILING — must work or raise")
        out.append("")
        out.append("| file | function | param |")
        out.append("|---|---|---|")
        for (rel, fn, p, _), _w in sorted(failing):
            out.append(f"| {rel} | {fn} | {p} |")
        out.append("")
    if show_all and waived:
        out.append("## Waived")
        out.append("")
        for (rel, fn, p, _), w in sorted(waived):
            out.append(f"- {rel}:{fn}({p}) — {w}")
        out.append("")
    report = "\n".join(out)
    dest = os.path.join(os.path.dirname(ROOT), "PARAM_AUDIT.md")
    with open(dest, "w") as f:
        f.write(report + "\n")
    print(report)
    return 1 if failing else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
