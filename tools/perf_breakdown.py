"""Decompose the LLaMA train step cost on the real chip (VERDICT r2 item 2:
'commit a per-step breakdown showing where time goes').

Times jitted sub-programs: matmul peak, fwd-only, fwd+bwd, lm_head/CE cost.
Prints one JSON line per probe.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def _sync(t):
    jax.device_get(jnp.ravel(t._data if hasattr(t, "_data") else t)[0])


def timeit(f, iters=8, warmup=3):
    for _ in range(warmup):
        _sync(f())
    t0 = time.perf_counter()
    for _ in range(iters):
        out = f()
    _sync(out)
    return (time.perf_counter() - t0) / iters


def probe_matmul_peak():
    """bf16 MXU peak achievable through the tunnel."""
    for n in (4096, 8192):
        a = jnp.ones((n, n), jnp.bfloat16)
        b = jnp.ones((n, n), jnp.bfloat16)
        f = jax.jit(lambda x, y: x @ y)
        dt = timeit(lambda: f(a, b))
        print(json.dumps({"probe": f"matmul_bf16_{n}",
                          "ms": round(dt * 1e3, 2),
                          "tflops": round(2 * n**3 / dt / 1e12, 1)}),
              flush=True)

    a = jnp.ones((8192, 8192), jnp.bfloat16)
    w = jnp.ones((8192, 8192), jnp.bfloat16)

    def chain(x, w):
        for _ in range(8):
            x = x @ w
        return x

    f = jax.jit(chain)
    dt = timeit(lambda: f(a, w))
    print(json.dumps({"probe": "matmul_chain8_bf16_8192",
                      "ms": round(dt * 1e3, 2),
                      "tflops": round(8 * 2 * 8192**3 / dt / 1e12, 1)}),
          flush=True)


def probe_llama_parts(batch=8, seq=1024):
    import paddle_tpu as paddle
    from paddle_tpu.text.models import LlamaConfig, LlamaForCausalLM

    paddle.seed(0)
    cfg = LlamaConfig(vocab_size=32000, hidden_size=1024,
                      intermediate_size=2816, num_hidden_layers=8,
                      num_attention_heads=16, max_position_embeddings=seq)
    model = LlamaForCausalLM(cfg)
    rs = np.random.RandomState(0)
    ids = paddle.to_tensor(rs.randint(0, 32000, (batch, seq)).astype("int64"))
    small = paddle.to_tensor(rs.randint(0, 32000, (1, 128)).astype("int64"))
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    toks = batch * seq
    fwd_flops = 2 * n_params * toks
    head_frac = (32000 * 1024) / n_params  # lm_head share of param matmuls

    def mk(fn):
        c = paddle.jit.to_static(fn, share_discovery=True)
        c(small)
        c(small)
        return c

    def fwd_ce(x):
        with paddle.amp.auto_cast(enable=True, dtype="bfloat16", level="O2"):
            from paddle_tpu.core.dispatch import no_grad

            with no_grad():
                return model(x, x)

    def fwd_no_head(x):
        with paddle.amp.auto_cast(enable=True, dtype="bfloat16", level="O2"):
            from paddle_tpu.core.dispatch import no_grad

            with no_grad():
                h = model.model(x)
                return (h.astype("float32") ** 2).mean()

    def fwd_bwd(x):
        with paddle.amp.auto_cast(enable=True, dtype="bfloat16", level="O2"):
            loss = model(x, x)
        loss.backward()
        for p in model.parameters():
            p.clear_gradient()
        return loss

    for name, fn, flops in (
            ("fwd_with_ce", fwd_ce, fwd_flops),
            ("fwd_no_head", fwd_no_head, fwd_flops * (1 - head_frac)),
            ("fwd_bwd_with_ce", fwd_bwd, 3 * fwd_flops)):
        c = mk(fn)
        dt = timeit(lambda: c(ids), iters=6, warmup=3)
        print(json.dumps({"probe": name, "ms": round(dt * 1e3, 1),
                          "tflops": round(flops / dt / 1e12, 1)}),
              flush=True)


def probe_residual_policy(batch=8, seq=1024):
    """Round-8 A/B: the full fwd+bwd step with the f32 vs bf16 residual
    stream (FLAGS_residual_dtype) — the non-attention bandwidth lever. The
    fused Pallas norm/rope/swiglu kernels engage on TPU in both rows; only
    the inter-kernel stream dtype changes."""
    import paddle_tpu as paddle
    from paddle_tpu.text.models import LlamaConfig, LlamaForCausalLM

    for policy in ("float32", "bfloat16"):
        paddle.set_flags({"FLAGS_residual_dtype": policy})
        try:
            paddle.seed(0)
            cfg = LlamaConfig(vocab_size=32000, hidden_size=1024,
                              intermediate_size=2816, num_hidden_layers=8,
                              num_attention_heads=16,
                              max_position_embeddings=seq)
            model = LlamaForCausalLM(cfg)
            model = paddle.amp.decorate(model, level="O2", dtype="bfloat16",
                                        master_weight=False)
            rs = np.random.RandomState(0)
            ids = paddle.to_tensor(
                rs.randint(0, 32000, (batch, seq)).astype("int64"))
            small = paddle.to_tensor(
                rs.randint(0, 32000, (1, 128)).astype("int64"))

            @paddle.jit.to_static(share_discovery=True)
            def fwd_bwd(x):
                with paddle.amp.auto_cast(enable=True, dtype="bfloat16",
                                          level="O2"):
                    loss = model(x, x)
                loss.backward()
                for p in model.parameters():
                    p.clear_gradient()
                return loss

            fwd_bwd(small)
            fwd_bwd(small)
            dt = timeit(lambda: fwd_bwd(ids), iters=6, warmup=3)
            n_params = sum(int(np.prod(p.shape))
                           for p in model.parameters())
            flops = 3 * 2 * n_params * batch * seq
            print(json.dumps({"probe": f"fwd_bwd_resid_{policy}",
                              "ms": round(dt * 1e3, 1),
                              "tokens_per_sec": round(batch * seq / dt, 1),
                              "tflops": round(flops / dt / 1e12, 1)}),
                  flush=True)
        finally:
            paddle.set_flags({"FLAGS_residual_dtype": "float32"})


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    if which in ("all", "matmul"):
        probe_matmul_peak()
    if which in ("all", "llama"):
        probe_llama_parts()
    if which in ("all", "resid"):
        probe_residual_policy()
