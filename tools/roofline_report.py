#!/usr/bin/env python
"""roofline_report — the measured-vs-roofline table for compiled programs.

Reads the obs cost ledger (paddle_tpu.obs.costs): per program, XLA
`cost_analysis()` flops / bytes accessed, the HBM footprint, the measured
compile wall, and — for programs that executed — mean execution wall,
achieved GB/s and roofline utilization (achieved / FLAGS_obs_peak_gbps).
This is the "~103 GB/s roofline" story from PERF.md as continuously
measured data instead of a per-round hand computation.  Rows also carry
`predicted_step_ms` / `collective_time_ms` — the static cost model's
estimate (analysis/costmodel.py: roofline max of compute at
FLAGS_obs_peak_tflops and HBM at FLAGS_obs_peak_gbps, plus the D10
collective volume billed at FLAGS_analysis_ici_gbps) — so predicted vs
measured sits in one table.

The ledger is per-process, so by default this tool drives the same tiny
serving smokes `tools/graft_lint.py` gates on (`--smoke`; implied by
`--write-baseline`) and reports on them.  Inside a live process, call
`paddle_tpu.obs.roofline_rows()` directly — bench rungs attach the same
rows to their BENCH_DETAILS entries.

`--write-baseline` regenerates `tools/cost_baseline.json`, the committed
analysis-D8 gate (`audit_cost_regressions`): a program whose
bytes-accessed grows more than FLAGS_obs_cost_regress_pct over the
baseline fails lint. Regenerate ONLY after an intentional cost change,
and commit the diff with the change that caused it.

Usage:
    python tools/roofline_report.py --smoke            # drive + table
    python tools/roofline_report.py --smoke --site serving.decode
    python tools/roofline_report.py --write-baseline   # regenerate D8 gate
    python tools/roofline_report.py --smoke --json
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

DEFAULT_BASELINE = os.path.join(REPO, "tools", "cost_baseline.json")


def _fmt_bytes(b):
    if b is None or b <= 0:
        return "-"
    for unit in ("B", "KB", "MB", "GB"):
        if b < 1024 or unit == "GB":
            return f"{b:.1f}{unit}" if unit != "B" else f"{int(b)}B"
        b /= 1024.0
    return f"{b:.1f}GB"


def render_table(rows) -> str:
    head = (f"{'program':<52} {'flops':>12} {'bytes':>10} {'hbm':>10} "
            f"{'pred_ms':>8} {'coll_ms':>8} "
            f"{'compile_s':>9} {'execs':>6} {'wall_ms':>8} {'GB/s':>8} "
            f"{'util':>6}")
    lines = [head, "-" * len(head)]
    for r in rows:
        if not r["analyzed"]:
            note = "(count-only: no XLA analysis at this site)"
            lines.append(f"{r['program']:<52} {note}")
            continue
        wall = (r["exec_wall_s"] / r["exec_count"] * 1e3
                if r["exec_count"] else None)
        gbps = r["achieved_gbps"]
        util = r["roofline_utilization"]
        pred = r.get("predicted_step_ms")
        coll = r.get("collective_time_ms")
        wall_s = f"{wall:.2f}" if wall is not None else "-"
        gbps_s = f"{gbps:.2f}" if gbps is not None else "-"
        util_s = f"{util:.1%}" if util is not None else "-"
        pred_s = f"{pred:.3f}" if pred is not None else "-"
        coll_s = f"{coll:.3f}" if coll is not None else "-"
        lines.append(
            f"{r['program']:<52} {r['flops']:>12.3g} "
            f"{_fmt_bytes(r['bytes_accessed']):>10} "
            f"{_fmt_bytes(r['peak_hbm_bytes']):>10} "
            f"{pred_s:>8} {coll_s:>8} "
            f"{r['compile_wall_s']:>9.3f} {r['exec_count']:>6} "
            f"{wall_s:>8} {gbps_s:>8} {util_s:>6}")
    return "\n".join(lines)


def run_smoke():
    """Drive the graft_lint serving smokes so the ledger holds the same
    deterministic tiny-engine programs the CI gate audits."""
    import graft_lint

    graft_lint.audit_serving()
    graft_lint.audit_obs()


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="drive the tiny lint serving smokes first (the "
                         "ledger is per-process and starts empty)")
    ap.add_argument("--site", default=None,
                    help="filter by site (serving / serving.decode / "
                         "generate / to_static / eager)")
    ap.add_argument("--json", dest="as_json", action="store_true")
    ap.add_argument("--write-baseline", nargs="?", const=DEFAULT_BASELINE,
                    default=None, metavar="PATH",
                    help=f"regenerate the D8 baseline (default "
                         f"{DEFAULT_BASELINE}) from the smoke's serving "
                         "programs; implies --smoke")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if args.smoke or args.write_baseline:
        run_smoke()
    from paddle_tpu import obs

    rows = obs.roofline_rows(args.site)
    if args.write_baseline:
        base = obs.write_baseline(args.write_baseline, site="serving")
        print(f"wrote {len(base['programs'])} program baseline(s) to "
              f"{args.write_baseline} (threshold "
              f"{base['threshold_pct']:g}%)", file=sys.stderr)
    if args.as_json:
        print(json.dumps({"peak_gbps": obs.peak_gbps(), "programs": rows},
                         indent=2))
    else:
        print(f"peak bandwidth: {obs.peak_gbps():g} GB/s "
              "(FLAGS_obs_peak_gbps; 0 = backend default)")
        print(render_table(rows) if rows else
              "cost ledger is empty — run with --smoke, or call from a "
              "process that compiled programs")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
