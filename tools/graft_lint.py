#!/usr/bin/env python
"""graft_lint — program auditor + AST lint CLI (paddle_tpu.analysis).

Runs the tracer-safety AST lint over paddle_tpu/ source and, per model,
compiles the LLaMA/GPT/BERT smoke configs (forward AND a 2-step AdamW
train step, the same configs tools/report_graph_breaks.py smokes) with
FLAGS_jit_debug_program=1 and audits the captured jaxprs:

  D1 dtype-stream (bf16 policy violations / silent promotions)
  D2 donation (train-step buffers not updated in place, with byte cost)
  D3 host-sync (graph-break flush sites, eager fallbacks, host callbacks)
  D4 fusion-miss (unfused norm/rotary/swiglu/dropout-add/decode-attention
     + gating reason)
  D5 VMEM budget (flash autotune entries + norm/decode configs vs the
     per-core limit)

The special model name `paged` audits the SERVING step program instead: a
tiny-LLaMA 2-slot continuous-batching engine is run through real
prefill/decode steps and its decode program's jaxpr goes through the
fusion-miss/callback/dtype detectors plus the D5 decode-config budget at
default flags.

The special model name `obs` (round 11) smokes the telemetry contract: a
tiny engine runs a warmup pass, declares warmup done, serves steady-state
requests, and the gate fails if required serving metrics are missing or
the compile watchdog saw a post-warmup retrace / recompile storm
(obs/watchdog.py audit_recompiles). It also drives one checkpoint
save/restore cycle and requires the REQUIRED_CKPT_METRICS rows.
Round 14 extends it with the flight-recorder/cost contract: the warmed
engine must dump a VALID Chrome-trace/Perfetto JSON (per-request spans
tiling the TTFT decomposition), every decode bucket it drove must have
an analyzed obs cost-ledger row (XLA bytes/flops + measured walls), and
analysis D8 (audit_cost_regressions) gates per-program bytes-accessed
against the committed tools/cost_baseline.json. Round 16 adds the
TRAINING contract: a short instrumented Model.fit must dump a valid
training trace (each step's data_wait+compute spans tile the recorded
step wall), land every REQUIRED_TRAIN_METRICS row (train_mfu, goodput,
data-wait), and pass analysis D12 (audit_train_steps: starvation
streaks / MFU collapse) at default flags.

The special model name `ckpt` (round 12) smokes crash consistency
end-to-end: a tiny model + AdamW trains, checkpoints twice, the NEWEST
checkpoint gets a bit flipped, and restore must fall back to the last
good one with a named reason and bit-exact state — plus the checkpoint
stall/failure audit (obs.audit_ckpt_stalls).

The special model name `spmd` (round 15) audits the SHARDED surface: the
tp x dp hybrid train step (the same shape __graft_entry__.dryrun_multichip
phase A proves) compiles on the 8-device virtual CPU mesh with
FLAGS_jit_debug_program=1 and runs through the full detector suite
INCLUDING the SPMD trio — D9 sharding coverage (every non-trivial mesh
axis must appear on a stream-size tensor's sharding), D10 collective
audit (jaxpr-level collectives attributed to axes with byte volume;
accidental all-gathers warn), D11 in-program device_put. The smoke then
SELF-TESTS the fire fixtures: a deliberately unsharded stream tensor, a
gratuitous all-gather, and an in-program device_put must each produce an
unsuppressed warning — a detector that stopped firing fails the gate
exactly like a detector that started firing falsely. To give the spmd
smoke its mesh, the CLI forces the same virtual 8-device CPU platform
tests/conftest.py uses, for every smoke. Round 18 adds the DECLARATIVE
half: the partitioner (distributed/partitioner) must shard the
UNMODIFIED tiny-LLaMA train step from one data+fsdp+tp MeshConfig with
clean D1-D11 + full D9 coverage, and an all-replicated rule table must
still fire D9 through the partitioner path (silently-dead self-test).

The special model name `conc` (round 17) smokes the CONCURRENCY
contract: a genuinely multi-threaded serving/ckpt/obs stress (engine
ticks + concurrent /metrics scrapes + overlapped async checkpoint
commits + a comm-watchdog scan) runs with core/lockdep recording on and
FLAGS_debug_thread_checks enabled; the D14 audit requires the recorded
lock-ORDER graph to be acyclic with zero blocking-calls-under-hot-lock,
D15 requires zero owner-thread contract violations, and the D13/D14/D15
fire fixtures then self-test (tests/lint_fixtures/fx_conc_*.py + a
deterministic two-lock cycle + a cross-thread contract breach) — a
silently-dead detector fails the gate. The D13 lock-discipline AST lint
itself (guarded-by / shared-state) rides EVERY run's AST pass.

The special model name `router` (round 20) smokes the MULTI-REPLICA
serving fabric: a real 2-replica tiny-LLaMA fleet behind
paddle_tpu.serving.Router with owner-thread contracts enforced — the
prefix_affine policy must concentrate a shared-prefix stream (≥1 router
affinity hit, ≥1 fleet prefix-cache hit), a drain/handoff rolling
restart mid-stream must complete every future exactly once (replacement
admitted only after warmup + readiness), zero compiles may land after
any replica's warmup barrier, D17 audit_fleet must come back clean, the
REQUIRED_FLEET_METRICS rows must exist in the router registry, and the
D17 affinity-defeat fire fixture (a drifting fingerprint scattering
byte-identical prompts) must still trip its warning.

The special model name `quant` (round 20) smokes the QUANTIZATION
byte-budget claims: int8/int4 weight-only paged engines plus an int4-KV
engine drive the same stream as a full-precision twin; D20
audit_quantized_bytes must verify the live decode-program pairs' ledger
boundary bytes against the 1.8x/3.4x shrink budgets, D20b
audit_silent_dequant + D1/D4 must be clean on the quantized decode
jaxprs, zero compiles may land after any engine's warmup barrier, and
the D20/D20b fire fixtures (a non-shrinking ledger pair, a weight-sized
int8->f32 convert) must trip — silence fails the gate.

The special model name `plan` (round 21) smokes the STATIC COST MODEL:
`autoplan.search` must rank ≥6 valid MeshConfigs for tiny-LLaMA on the
8-device virtual mesh from one abstract lowering (nothing executes),
D18 audit_plan must be clean on the search's own top-1, D19
audit_cost_model_calibration gates the predicted ordering against
MEASURED tok/s of the three partitioner_scaling configs, and the D18
(worst-candidate deploy + rigged HBM budget) and D19 (rigged-fabric
ranking flip) fire fixtures must trip — silence fails the gate.

Exit code: 0 when no unsuppressed warning/error finding survives the
baseline (notes never fail); 1 otherwise. CI runs
`graft_lint.py --models llama,gpt,bert,paged,obs,ckpt,spmd,conc,router,plan,quant --json`
via tools/check_scoreboard — round 17 splits that into PARALLEL
subprocess groups (check_scoreboard.LINT_GROUPS) so the gate wall stays
at the slowest group; each worker passes `--defer-stale` and the gate
aggregates baseline match counts across the union. Baseline entries
that matched ZERO findings are reported as `stale-suppression` (warning
on a full-coverage run, note on a partial one); `--prune-baseline`
rewrites the baseline without them.

Usage:
    python tools/graft_lint.py                      # AST lint + D5 only
    python tools/graft_lint.py --models llama,gpt,bert,paged,spmd
    python tools/graft_lint.py --json               # machine output
    python tools/graft_lint.py --baseline my.json   # suppression file
    python tools/graft_lint.py --no-ast             # jaxpr audits only
    python tools/graft_lint.py --models llama,gpt,bert,paged,obs,ckpt,spmd,conc \
        --prune-baseline                            # drop stale suppressions

Baseline format: see paddle_tpu/analysis/findings.py (default file
tools/lint_baseline.json; suppressed findings stay visible in --json).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_BASELINE = os.path.join(REPO, "tools", "lint_baseline.json")

#: the full CI smoke set (check_scoreboard.lint_gate's default): staleness
#: of baseline entries is only a gate FAILURE when a run covers all of it
#: — a partial run legitimately leaves model-specific suppressions
#: unmatched
CI_MODELS = ("llama", "gpt", "bert", "paged", "obs", "ckpt", "spmd",
             "conc", "router", "plan", "quant")

#: one tiny-LLaMA shared by the serving-side smokes (`paged`, `obs`): the
#: engines key their AOT executables on spec + param AVALS, so a shared
#: instance guarantees every engine in the run rides the round-14
#: executable cache instead of warming its own programs
_TINY_MODEL = None


def _tiny_llama():
    global _TINY_MODEL
    if _TINY_MODEL is None:
        import paddle_tpu as paddle
        from paddle_tpu.text.models import LlamaConfig, LlamaForCausalLM

        paddle.seed(0)
        cfg = LlamaConfig(vocab_size=128, hidden_size=64,
                          intermediate_size=128, num_hidden_layers=2,
                          num_attention_heads=4,
                          max_position_embeddings=64)
        _TINY_MODEL = LlamaForCausalLM(cfg)
        _TINY_MODEL.eval()
    return _TINY_MODEL


def audit_model(name: str) -> list:
    """Compile the named smoke config (forward + train step) and run every
    program-level detector. Imports stay inside so `--no-models` runs need
    no jax session."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import analysis
    from report_graph_breaks import SMOKES

    fwd_fn, args = SMOKES[name]()
    model = fwd_fn.__self__
    findings = []

    paddle.set_flags({"FLAGS_jit_debug_program": True})
    try:
        sfwd = paddle.jit.to_static(fwd_fn)
        for _ in range(3):
            sfwd(*args)
        findings += analysis.audit_compiled(sfwd, loc=f"{name}/forward")

        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters())

        @paddle.jit.to_static
        def train_step(*a):
            loss = fwd_fn(*a)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        for _ in range(4):
            loss = train_step(*args)
        assert np.isfinite(float(loss)), f"{name} train step diverged"
        findings += analysis.audit_compiled(train_step,
                                            loc=f"{name}/train_step")

        # D5 at this model's width (bf16 itemsize: the flagship stream)
        cfg = getattr(model, "config", None)
        hidden = getattr(cfg, "hidden_size", None)
        if hidden:
            findings += analysis.audit_norm_config(
                hidden, itemsize=2, loc=f"{name}/norm-config")
    finally:
        paddle.set_flags({"FLAGS_jit_debug_program": False})
    return findings


def audit_serving() -> list:
    """The `paged` smoke: drive a tiny-LLaMA 2-slot serving engine through
    real prefill + decode steps (mixed-length requests, so a slot frees
    and refills), then audit the decode step program's jaxpr and the
    decode kernel's launch-config/pool budget at default flags.

    Round 13 extends the smoke with a SHARED-PREFIX stream: after a
    warmup request computes a multi-block prompt (and a second request
    warms the cache-hit chunk program), the engine declares warmup done
    and serves another request sharing the same prefix — the gate then
    requires (a) at least one prefix-cache block hit (D7: an
    identical-prefix stream that never hits means the cache is
    defeated), and (b) ZERO compiles after the warmup barrier (the
    cache-hit suffix path must ride already-compiled chunk programs)."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import analysis, obs
    from paddle_tpu.core.flags import flag
    from paddle_tpu.inference.engine import ServingEngine

    paddle.seed(0)
    model = _tiny_llama()
    eng = ServingEngine(model, max_slots=2)
    rs = np.random.RandomState(0)
    for ln, nt in ((3, 2), (6, 5), (4, 3)):
        eng.add_request(rs.randint(0, 128, (ln,)), max_new_tokens=nt)
    out = eng.run()
    assert len(out) == 3 and all(len(v) for v in out.values()), \
        "paged smoke engine failed to drain"
    jx = eng.decode_program_jaxpr()
    findings = analysis.audit_fusion_misses(jx, loc="paged/decode_step")
    findings += analysis.audit_callbacks(jx, loc="paged/decode_step")
    findings += analysis.audit_dtype_stream(
        jx, policy=str(flag("FLAGS_residual_dtype")),
        loc="paged/decode_step")
    findings += analysis.audit_decode_config(
        eng.spec.head_dim, eng.block_size,
        group=max(1, eng.spec.num_heads // eng.spec.num_kv_heads),
        itemsize=2, pool_blocks=eng.allocator.num_blocks,
        slots=eng.max_slots, seq_pages=eng.pages,
        cached_blocks=eng.prefix_cache.cached_blocks,
        loc="paged/decode-config")

    # ---- shared-prefix stream (round 13): hit + zero-post-warmup gate
    obs.clear_events()
    eng2 = ServingEngine(model, max_slots=2)
    shared = rs.randint(0, 128, (2 * eng2.block_size + 1,))
    tail = rs.randint(0, 128, (3, 2))
    # request 1 computes + registers the prefix; request 2 warms the
    # cache-hit suffix chunk program at the buckets request 3 reuses
    eng2.add_request(np.concatenate([shared, tail[0]]), max_new_tokens=2)
    eng2.run()
    eng2.add_request(np.concatenate([shared, tail[1]]), max_new_tokens=2)
    eng2.run()
    eng2.finish_warmup()
    eng2.add_request(np.concatenate([shared, tail[2]]), max_new_tokens=2)
    out2 = eng2.run()
    assert len(out2) == 3, "shared-prefix smoke failed to drain"
    hits = int(eng2.prefix_cache.hits)
    if hits < 1:
        findings.append(analysis.Finding(
            "prefix-cache", "error", "paged/shared-prefix-smoke",
            "a 3-request stream sharing a 2-block prompt prefix produced "
            "ZERO prefix-cache hits at default flags — block reuse is "
            "not happening", data={"hits": hits}))
    else:
        findings.append(analysis.Finding(
            "prefix-cache", "note", "paged/shared-prefix-smoke",
            f"shared-prefix stream served {hits} block(s) from cache"))
    findings += analysis.audit_prefix_cache(
        eng2, loc="paged/shared-prefix-smoke")
    evs = [e for e in obs.compile_events() if e.site.startswith("serving")]
    findings += obs.audit_recompiles(evs, loc="paged/shared-prefix-smoke")

    # ---- speculative decode smoke (round 16): a 2-slot n-gram
    # speculating engine warms every program the steady stream rides
    # (spec-verify at buckets 1 and 2, plain decode for the mixed tick
    # and the empty-proposal fallback), declares warmup done, then
    # serves a repetitive-prompt request for ≥8 verify windows. Gates:
    # (a) ZERO post-warmup compiles on the verify family, (b) the
    # flight trace validates with verify-window spans covering the
    # steady run, (c) D4-family audits are clean on the verify
    # program's jaxpr, (d) the D16 greedy parity oracle vs a
    # non-speculative A/B engine on the same prompt.
    import tempfile

    from paddle_tpu.inference.speculative import AlwaysRejectProposer, \
        SpecConfig

    obs.clear_events()
    eng3 = ServingEngine(model, max_slots=2, spec_decode="ngram")
    base = np.tile(rs.randint(0, 128, (4,)), 5)     # repetitive stream
    eng3.add_request(base, max_new_tokens=6)        # spec bucket 1
    eng3.run()
    eng3.add_request(np.roll(base, 2), max_new_tokens=6)
    eng3.add_request(base, max_new_tokens=6, speculative=False)
    eng3.run()                                      # mixed spec/plain tick
    eng3.add_request(base, max_new_tokens=6)
    eng3.add_request(np.roll(base, 2), max_new_tokens=6)
    eng3.run()                                      # spec bucket 2
    eng3.finish_warmup()
    rid_s = eng3.add_request(base, max_new_tokens=24)
    out3 = eng3.run()
    eng_ab = ServingEngine(model, max_slots=2)
    rid_b = eng_ab.add_request(base, max_new_tokens=24)
    out_ab = eng_ab.run()
    parity = bool(np.array_equal(out3[rid_s], out_ab[rid_b]))
    findings += analysis.audit_spec_decode(
        eng3, parity=parity, loc="paged/spec-smoke")
    evs = [e for e in obs.compile_events() if e.site.startswith("serving")]
    findings += obs.audit_recompiles(evs, loc="paged/spec-smoke")

    fd, tpath = tempfile.mkstemp(prefix="graft_lint_spec_trace_",
                                 suffix=".json")
    os.close(fd)
    try:
        eng3.dump_trace(tpath)
        summary = obs.validate_trace(tpath)
        if summary["verify_spans"] < 8:
            findings.append(analysis.Finding(
                "spec-decode", "error", "paged/spec-smoke",
                "speculative smoke recorded fewer than 8 verify-window "
                "spans — the engine is not actually speculating tick "
                "over tick", data=dict(summary)))
    except (AssertionError, ValueError) as e:
        findings.append(analysis.Finding(
            "spec-decode", "error", "paged/spec-smoke",
            f"speculative trace dump failed validation: {e}"))
    finally:
        os.unlink(tpath)

    jxv = eng3.verify_program_jaxpr()
    findings += analysis.audit_fusion_misses(jxv, loc="paged/spec_verify")
    findings += analysis.audit_callbacks(jxv, loc="paged/spec_verify")
    findings += analysis.audit_dtype_stream(
        jxv, policy=str(flag("FLAGS_residual_dtype")),
        loc="paged/spec_verify")

    # ---- D16 fire-fixture self-test: a proposer that NEVER matches the
    # target must trip the acceptance-collapse warning on a warmed
    # engine. The warning is consumed here (it is the fixture working,
    # not a defect); a detector that stays silent is itself the gate
    # failure.
    eng4 = ServingEngine(
        model, max_slots=2,
        spec_decode=SpecConfig(proposer=AlwaysRejectProposer(4)))
    eng4.add_request(base, max_new_tokens=6)
    eng4.run()
    eng4.finish_warmup()
    eng4.add_request(np.roll(base, 1), max_new_tokens=6)
    eng4.run()
    fire = analysis.audit_spec_decode(eng4, loc="paged/spec-fire-fixture")
    if any(f.detector == "spec-decode" and f.severity == "warning"
           for f in fire):
        findings.append(analysis.Finding(
            "spec-decode", "note", "paged/spec-fire-fixture",
            "D16 fire fixture verified: the always-reject proposer "
            "tripped the acceptance-collapse warning",
            data={"accept_rate": eng4.spec_stats()["accept_rate"]}))
    else:
        findings.append(analysis.Finding(
            "spec-decode", "error", "paged/spec-fire-fixture",
            "D16 detector is SILENTLY DEAD: a warmed engine driven by "
            "an always-reject proposer produced no acceptance-collapse "
            "warning", data={"findings": [f.to_dict() for f in fire]}))
    return findings


#: metric names the obs smoke requires the serving registry to carry —
#: the instrumentation contract a refactor must not silently drop
REQUIRED_SERVING_METRICS = (
    "serving_ttft_seconds", "serving_queue_wait_seconds",
    "serving_prefill_seconds", "serving_decode_step_seconds",
    "serving_tpot_seconds", "serving_decode_tokens_total",
    "serving_prefill_tokens_total", "serving_requests_completed_total",
    "serving_requests_timeout_total",
    "serving_admission_rejects_total", "serving_admission_blocked_total",
    "serving_queue_depth", "serving_active_slots",
    "serving_block_pool_free_blocks", "serving_block_pool_used_blocks",
    # round 13: prefix cache + chunked prefill instrumentation
    "serving_prefix_blocks_hit_total", "serving_prefix_blocks_missed_total",
    "serving_prefill_chunks_total", "serving_prefix_cache_blocks",
    "serving_prefix_cache_referenced_blocks",
    "serving_prefix_cache_evictions_total",
    # round 14: flight recorder
    "serving_flight_anomalies_total", "serving_flight_dumps_total",
    "serving_flight_requests",
    # round 20: drain/handoff (router rolling restarts; zero on an
    # engine that never drained, so NOT in MUST_COUNT)
    "serving_drained_requests_total",
    # round 16: speculative decoding (NOT in MUST_COUNT — a non-spec
    # stream legitimately leaves them at zero)
    "serving_spec_windows_total", "serving_spec_proposed_tokens_total",
    "serving_spec_accepted_tokens_total", "serving_spec_accept_rate",
    "serving_spec_accepted_per_window")

#: process-default-registry rows the README "process-default registry"
#: catalog names (compile watchdog + cost attribution). The meta-test in
#: tests/test_flight.py pins README catalog rows to the REQUIRED_* sets;
#: post_warmup_compiles_total only materializes on an anomaly, so the
#: obs smoke's existence check uses the MUST_EXIST subset below.
REQUIRED_DEFAULT_METRICS = (
    "compiles_total", "compile_seconds", "post_warmup_compiles_total",
    "roofline_utilization")

MUST_EXIST_DEFAULT_METRICS = (
    "compiles_total", "compile_seconds", "roofline_utilization")

#: committed analysis-D8 baseline (per-program bytes-accessed from the
#: obs smoke's tiny serving engine)
COST_BASELINE = os.path.join(REPO, "tools", "cost_baseline.json")

#: checkpoint metric rows the obs smoke requires in the DEFAULT registry
#: after one save/restore cycle (the round-12 fault-tolerance contract)
REQUIRED_CKPT_METRICS = (
    "ckpt_save_seconds", "ckpt_restore_seconds", "ckpt_saves_total",
    "ckpt_restores_total", "ckpt_bytes_written_total", "ckpt_last_step")

#: training telemetry rows the obs smoke requires in the DEFAULT registry
#: after a short instrumented Model.fit (the round-16 training
#: flight-recorder / MFU / goodput contract)
REQUIRED_TRAIN_METRICS = (
    "train_step_seconds", "train_steps_total", "train_loss",
    "train_tokens_per_sec", "train_lazy_flushes_total",
    "train_data_wait_seconds", "train_mfu", "train_achieved_flops",
    "train_goodput_ratio", "train_goodput_seconds_total",
    "train_flight_steps", "train_flight_anomalies_total",
    "train_flight_dumps_total")

#: the subset that MUST have observed/counted after the smoke's drained
#: runs (rejects/blocked legitimately stay zero on a healthy stream)
MUST_COUNT_SERVING_METRICS = (
    "serving_ttft_seconds", "serving_queue_wait_seconds",
    "serving_prefill_seconds", "serving_decode_step_seconds",
    "serving_tpot_seconds", "serving_decode_tokens_total",
    "serving_prefill_tokens_total", "serving_requests_completed_total")

#: fleet telemetry rows the `router` smoke requires in the Router's
#: registry (round 20) — the multi-replica placement/failover contract;
#: tests/test_flight.py pins the README catalog rows to this set too
REQUIRED_FLEET_METRICS = (
    "router_requests_total", "router_prefix_affinity_hits_total",
    "router_session_affinity_hits_total", "router_rerouted_requests_total",
    "router_dead_replica_routes_total", "router_drains_total",
    "router_ready_replicas", "router_dead_replicas")


def audit_obs() -> list:
    """The `obs` smoke (round 11): drive a tiny-LLaMA 2-slot engine
    through a warmup pass, declare warmup done, run a steady-state
    request at the SAME buckets, then (a) assert the required serving
    metrics exist and counted, and (b) run the compile watchdog's
    recompile audit over the serving/generate event window — a
    post-warmup retrace or a storm fails the gate like a dtype
    regression."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import analysis, obs
    from paddle_tpu.inference.engine import ServingEngine

    paddle.seed(0)
    obs.clear_events()
    model = _tiny_llama()
    eng = ServingEngine(model, max_slots=2)
    rs = np.random.RandomState(0)
    for ln, nt in ((3, 3), (6, 4), (4, 3)):     # warm both slot buckets
        eng.add_request(rs.randint(0, 128, (ln,)), max_new_tokens=nt)
    eng.run()
    eng.finish_warmup()
    for ln, nt in ((5, 3), (3, 4)):             # steady state: same buckets
        eng.add_request(rs.randint(0, 128, (ln,)), max_new_tokens=nt)
    out = eng.run()
    assert out, "obs smoke engine failed to drain"

    findings = []
    snap = eng.metrics()
    missing = [m for m in REQUIRED_SERVING_METRICS if m not in snap]
    zero = [m for m in MUST_COUNT_SERVING_METRICS
            if m not in missing
            and not any(s.get("count") or s.get("value")
                        for s in snap[m]["samples"])]
    if missing or zero:
        findings.append(analysis.Finding(
            "obs-coverage", "error", "obs/serving-smoke",
            f"serving registry lost required metrics — missing: {missing}, "
            f"never-observed: {zero}",
            data={"missing": missing, "zero": zero}))
    else:
        findings.append(analysis.Finding(
            "obs-coverage", "note", "obs/serving-smoke",
            f"{len(REQUIRED_SERVING_METRICS)} required serving metrics "
            "present and counting"))
    evs = [e for e in obs.compile_events()
           if e.site.startswith("serving") or e.site == "generate"]
    findings += obs.audit_recompiles(evs, loc="obs/serving-smoke")

    # ---- flight recorder + cost attribution (round 14): the warmed run
    # must dump a VALID Perfetto trace (per-request spans tiling TTFT)
    # and every decode bucket it drove must have an ANALYZED cost-ledger
    # row (XLA bytes/flops) with measured execution walls; D8 then gates
    # those bytes against the committed baseline.
    import tempfile

    from paddle_tpu.obs import costs as obs_costs

    fd, tpath = tempfile.mkstemp(prefix="graft_lint_trace_",
                                 suffix=".json")
    os.close(fd)
    summary = None
    try:
        eng.dump_trace(tpath)
        summary = obs.validate_trace(tpath)
    except (AssertionError, ValueError) as e:
        findings.append(analysis.Finding(
            "obs-flight", "error", "obs/flight-smoke",
            f"serving trace dump failed validation: {e}"))
    finally:
        os.unlink(tpath)
    if summary is not None:
        done = len(eng.completed)
        if summary["tiled_requests"] < done or not summary["events"]:
            findings.append(analysis.Finding(
                "obs-flight", "error", "obs/flight-smoke",
                f"trace dump degraded: {summary['tiled_requests']} "
                f"TTFT-tiled request timelines for {done} completed "
                f"requests ({summary['events']} events)",
                data=summary))
        else:
            findings.append(analysis.Finding(
                "obs-flight", "note", "obs/flight-smoke",
                f"trace dump valid: {summary['events']} events, "
                f"{summary['tiled_requests']}/{done} requests TTFT-tiled",
                data=summary))
    driven = [e for e in obs_costs.ledger("serving.decode")
              if e.exec_count > 0]
    unanalyzed = [e.program for e in driven if not e.analyzed]
    if not driven or unanalyzed:
        findings.append(analysis.Finding(
            "obs-cost", "error", "obs/cost-smoke",
            "cost ledger lost decode coverage — "
            + (f"no measured serving.decode programs" if not driven else
               f"programs without XLA cost analysis: {unanalyzed}"),
            data={"driven": [e.program for e in driven],
                  "unanalyzed": unanalyzed}))
    else:
        findings.append(analysis.Finding(
            "obs-cost", "note", "obs/cost-smoke",
            f"{len(driven)} decode program(s) carry XLA costs + measured "
            f"walls (buckets {sorted(e.bucket for e in driven)})"))
    snap_def = obs.default_registry().to_dict()
    missing_def = [m for m in MUST_EXIST_DEFAULT_METRICS
                   if m not in snap_def]
    if missing_def:
        findings.append(analysis.Finding(
            "obs-coverage", "error", "obs/default-registry",
            f"default registry lost required metrics: {missing_def}",
            data={"missing": missing_def}))
    if not os.path.exists(COST_BASELINE):
        findings.append(analysis.Finding(
            "cost-regression", "error", "obs/cost-smoke",
            "tools/cost_baseline.json is missing — D8 cannot gate; "
            "regenerate with tools/roofline_report.py --write-baseline"))
    else:
        findings += analysis.audit_cost_regressions(
            COST_BASELINE, loc="obs/cost-smoke")

    # the ckpt row (round 12): one save/restore cycle must land every
    # REQUIRED_CKPT_METRICS entry in the default registry
    import shutil
    import tempfile

    from paddle_tpu import ckpt

    root = tempfile.mkdtemp(prefix="graft_lint_obs_ckpt_")
    try:
        ckpt.save_checkpoint(root, 1, {"w": np.ones(8, np.float32)})
        ckpt.restore_checkpoint(root)
    finally:
        shutil.rmtree(root, ignore_errors=True)
    snap = obs.default_registry().to_dict()
    missing_ckpt = [m for m in REQUIRED_CKPT_METRICS if m not in snap]
    if missing_ckpt:
        findings.append(analysis.Finding(
            "obs-coverage", "error", "obs/ckpt-smoke",
            f"default registry lost required checkpoint metrics after a "
            f"save/restore cycle — missing: {missing_ckpt}",
            data={"missing": missing_ckpt}))
    else:
        findings.append(analysis.Finding(
            "obs-coverage", "note", "obs/ckpt-smoke",
            f"{len(REQUIRED_CKPT_METRICS)} required ckpt metrics present"))
    findings += audit_train_smoke()
    return findings


def audit_train_smoke() -> list:
    """The training half of the `obs` smoke (round 16): run a short
    instrumented Model.fit (TelemetryCallback with its flight recorder +
    goodput ledger on the DEFAULT registry), then require (a) a VALID
    training trace dump — every step's data_wait+compute spans tile the
    recorded step wall, re-checked by obs.validate_trace, (b) the
    REQUIRED_TRAIN_METRICS rows (MFU, goodput, data-wait among them),
    and (c) a clean analysis D12 (audit_train_steps) at default flags —
    a starvation streak or MFU collapse in the smoke's in-memory loader
    would mean the detector itself is miscalibrated."""
    import tempfile

    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import analysis, obs

    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(8, 32), paddle.nn.ReLU(),
                               paddle.nn.Linear(32, 4))
    model = paddle.hapi.Model(net)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=net.parameters())
    model.prepare(opt, paddle.nn.MSELoss())
    rs = np.random.RandomState(0)
    data = [(rs.randn(8).astype("float32"), rs.randn(4).astype("float32"))
            for _ in range(16)]
    # eager steps have no compiled program to read flops from — declare
    # them (2 * params * 3 for fwd+bwd is the usual hand estimate; the
    # exact number only scales the MFU gauge, the smoke checks presence)
    n_params = sum(int(np.prod(p.shape)) for p in net.parameters())
    cb = paddle.hapi.TelemetryCallback(batch_tokens=8 * 4,
                                       step_flops=6.0 * n_params * 4)
    model.fit(data, batch_size=4, epochs=2, verbose=0, callbacks=[cb])

    findings = []
    steps_run = int(cb.ledger.steps)
    fd, tpath = tempfile.mkstemp(prefix="graft_lint_train_trace_",
                                 suffix=".json")
    os.close(fd)
    summary = None
    try:
        cb.flight.dump_trace(tpath)
        summary = obs.validate_trace(tpath)
    except (AssertionError, ValueError) as e:
        findings.append(analysis.Finding(
            "obs-train-flight", "error", "obs/train-smoke",
            f"training trace dump failed validation: {e}"))
    finally:
        os.unlink(tpath)
    if summary is not None:
        if summary["tiled_steps"] < steps_run or not summary["events"]:
            findings.append(analysis.Finding(
                "obs-train-flight", "error", "obs/train-smoke",
                f"training trace degraded: {summary['tiled_steps']} "
                f"wall-tiled step timelines for {steps_run} steps run "
                f"({summary['events']} events)", data=summary))
        else:
            findings.append(analysis.Finding(
                "obs-train-flight", "note", "obs/train-smoke",
                f"training trace valid: {summary['events']} events, "
                f"{summary['tiled_steps']}/{steps_run} steps tile their "
                "recorded walls", data=summary))
    snap = obs.default_registry().to_dict()
    missing = [m for m in REQUIRED_TRAIN_METRICS if m not in snap]
    zero = []
    for m in ("train_step_seconds", "train_steps_total", "train_mfu",
              "train_goodput_seconds_total", "train_data_wait_seconds"):
        if m not in missing and not any(
                s.get("count") or s.get("value")
                for s in snap[m]["samples"]):
            zero.append(m)
    if missing or zero:
        findings.append(analysis.Finding(
            "obs-coverage", "error", "obs/train-smoke",
            f"default registry lost required training metrics after an "
            f"instrumented fit — missing: {missing}, never-observed: "
            f"{zero}", data={"missing": missing, "zero": zero}))
    else:
        findings.append(analysis.Finding(
            "obs-coverage", "note", "obs/train-smoke",
            f"{len(REQUIRED_TRAIN_METRICS)} required training metrics "
            "present and counting"))
    findings += analysis.audit_train_steps(recorder=cb.flight,
                                           ledger=cb.ledger,
                                           loc="obs/train-smoke")
    return findings


def audit_ckpt() -> list:
    """The `ckpt` smoke (round 12): save → corrupt → restore-last-good on
    a tiny model, entirely through the public subsystem.  Proves in CI
    that (a) two committed checkpoints restore bit-exact, (b) a
    bit-flipped shard in the NEWEST one is caught by checksum
    verification and restore falls back to the previous good checkpoint
    with a named reason, and (c) the save window is stall/failure-free
    (obs.audit_ckpt_stalls)."""
    import shutil
    import sys as _sys
    import tempfile

    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import analysis, ckpt, obs

    _sys.path.insert(0, os.path.join(REPO, "tests"))
    import faultinject as fi

    paddle.seed(0)
    np.random.seed(0)
    obs.clear_events()
    model = paddle.nn.Sequential(paddle.nn.Linear(8, 16), paddle.nn.ReLU(),
                                 paddle.nn.Linear(16, 4))
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(4, 8).astype("float32"))
    findings = []
    root = tempfile.mkdtemp(prefix="graft_lint_ckpt_")
    try:
        for step in (1, 2):
            loss = (model(x) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            if step == 1:
                ckpt.save_checkpoint(
                    root, 1, ckpt.capture_train_state(model, opt, step=1))
                good = {k: v.numpy().copy()
                        for k, v in model.state_dict().items()}
        with fi.bit_flip_shard(0, byte_offset=3):
            ckpt.save_checkpoint(
                root, 2, ckpt.capture_train_state(model, opt, step=2))
        r = ckpt.restore_checkpoint(root)
        ok = (r.step == 1
              and r.fallbacks
              and r.fallbacks[0]["reason"] == "checksum_mismatch"
              and all(np.array_equal(r.tree["model"][k], good[k])
                      for k in good))
        if ok:
            findings.append(analysis.Finding(
                "ckpt-smoke", "note", "ckpt/save-corrupt-restore",
                "bit-flipped newest checkpoint detected "
                "(checksum_mismatch); restore fell back to the last good "
                "checkpoint bit-exact"))
        else:
            findings.append(analysis.Finding(
                "ckpt-smoke", "error", "ckpt/save-corrupt-restore",
                f"restore-last-good contract violated: step={r.step}, "
                f"fallbacks={r.fallbacks}",
                data={"step": r.step, "fallbacks": r.fallbacks}))
    finally:
        shutil.rmtree(root, ignore_errors=True)
    findings += obs.audit_ckpt_stalls(loc="ckpt/save-window")
    return findings


def audit_spmd() -> list:
    """The `spmd` smoke (round 15): compile the tp x dp hybrid train step
    (phase A of __graft_entry__.dryrun_multichip — fleet GSPMD sharding,
    tensor+sequence parallel tiny-LLaMA) on the 8-device virtual mesh and
    run the FULL detector suite over it, mesh-declared so D9 judges
    coverage even where the jaxpr alone couldn't recover the mesh. Then
    self-test the fire fixtures: each SPMD detector must still PRODUCE
    its warning on a deliberately broken program (unsharded stream /
    gratuitous all-gather / in-program device_put) — a silently-dead
    detector fails the gate like a falsely-firing one."""
    import jax
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import analysis
    from paddle_tpu.distributed import fleet
    from paddle_tpu.text.models import LlamaForCausalLM, llama_tiny_config

    if len(jax.devices()) < 8:
        return [analysis.Finding(
            "spmd-smoke", "error", "spmd/mesh",
            f"the spmd smoke needs >= 8 devices for the tp x dp mesh, got "
            f"{len(jax.devices())} — run through tools/graft_lint.py (it "
            "forces --xla_force_host_platform_device_count=8 before the "
            "backend initializes) or set XLA_FLAGS yourself")]
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 4, "mp_degree": 2}
    fleet.init(is_collective=True, strategy=strategy)
    mesh = fleet.get_hybrid_communicate_group().get_mesh()

    paddle.seed(0)
    cfg = llama_tiny_config(tensor_parallel=True, sequence_parallel=True)
    model = LlamaForCausalLM(cfg)
    model = fleet.distributed_model(model)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())

    paddle.set_flags({"FLAGS_jit_debug_program": True})
    try:
        @paddle.jit.to_static
        def train_step(ids, labels):
            loss = model(ids, labels)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        rs = np.random.RandomState(1)
        batch, seq = 8, 32
        loss = None
        for _ in range(4):
            ids = paddle.to_tensor(
                rs.randint(0, cfg.vocab_size, (batch, seq)).astype("int64"))
            labels = paddle.to_tensor(
                rs.randint(0, cfg.vocab_size, (batch, seq)).astype("int64"))
            loss = train_step(ids, labels)
        assert np.isfinite(float(loss)), "spmd train step diverged"

        findings = analysis.audit_compiled(train_step, mesh=mesh,
                                           loc="spmd/train_step")
        vol = analysis.jaxpr_collective_bytes(train_step.program_jaxpr())
        findings.append(analysis.Finding(
            "spmd-smoke", "note", "spmd/train_step",
            f"tp x dp train step compiled on mesh "
            f"{dict(mesh.shape)}; jaxpr-level collective volume "
            f"{vol['total']} B/device over {vol['sites']} site(s) "
            "(GSPMD-inserted collectives live in HLO below the jaxpr)",
            data=vol))
        findings += _audit_partitioner()
    finally:
        paddle.set_flags({"FLAGS_jit_debug_program": False})
    findings += _audit_spmd_fixtures(mesh)
    return findings


def _audit_partitioner() -> list:
    """Round-18 half of the spmd smoke: the DECLARATIVE partitioner
    compiles the UNMODIFIED tiny-LLaMA train step from one
    data+fsdp+tp MeshConfig (no mp_layers wiring), must audit clean
    D1-D11 at default flags with full D9 mesh coverage, and must keep
    its loss on the hand-wired path's trajectory. Then the fire fixture:
    an all-replicated rule table must STILL produce the D9 warning
    through the partitioner path — a silently-dead detector fails the
    gate (the round-15 rule)."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import analysis
    from paddle_tpu.distributed.partitioner import (MeshConfig,
                                                    REPLICATED_RULES,
                                                    partition)
    from paddle_tpu.text.models import LlamaForCausalLM, llama_tiny_config

    def build(mc):
        paddle.seed(0)
        model = LlamaForCausalLM(llama_tiny_config())
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters())

        def train_step(ids, labels):
            loss = model(ids, labels)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        return model, partition(train_step, mc, model=model)

    mc = MeshConfig(data=2, fsdp=2, tp=2)
    model, step = build(mc)
    rs = np.random.RandomState(1)
    cfg = model.config
    loss = None
    for _ in range(4):
        ids = paddle.to_tensor(
            rs.randint(0, cfg.vocab_size, (8, 32)).astype("int64"))
        labels = paddle.to_tensor(
            rs.randint(0, cfg.vocab_size, (8, 32)).astype("int64"))
        loss = step(ids, labels)
    assert np.isfinite(float(loss)), "partitioner train step diverged"

    findings = analysis.audit_compiled(step, loc="spmd/partitioner_step")
    cov = [f for f in findings if f.detector == "spmd-coverage"
           and "coverage ok" in f.message]
    if not cov:
        findings.append(analysis.Finding(
            "spmd-smoke", "error", "spmd/partitioner_step",
            f"the partitioner-driven {mc.describe()} step lost full D9 "
            "mesh-axis stream coverage — the declarative config no "
            "longer shards what it claims"))
    findings += step.plan.to_findings(loc="spmd/partitioner_plan")
    findings.append(analysis.Finding(
        "spmd-smoke", "note", "spmd/partitioner_step",
        f"declarative {mc.describe()} config sharded the unmodified "
        f"tiny-LLaMA train step: {step.plan.summary()}",
        data=step.plan.summary()))

    # fire fixture: REPLICATED_RULES through the same code path must
    # trip the D9 unsharded-stream warning
    paddle.set_flags({"FLAGS_partitioner_heuristics": False})
    try:
        _m, dead = build(MeshConfig(data=2, tp=2, rules=REPLICATED_RULES,
                                    batch_axes=(),
                                    stream_seq_axis="data"))
        for _ in range(4):
            ids = paddle.to_tensor(
                rs.randint(0, cfg.vocab_size, (8, 32)).astype("int64"))
            labels = paddle.to_tensor(
                rs.randint(0, cfg.vocab_size, (8, 32)).astype("int64"))
            dead(ids, labels)
        fired = [f for f in analysis.audit_compiled(
                     dead, loc="spmd/partitioner-fire")
                 if f.detector == "spmd-coverage"
                 and f.severity == "warning"]
    finally:
        paddle.set_flags({"FLAGS_partitioner_heuristics": True})
    if fired:
        findings.append(analysis.Finding(
            "spmd-smoke", "note", "spmd/fire-fixtures",
            "D9 spmd-coverage (all-replicated partitioner rules): fire "
            f"fixture produced {len(fired)} unsuppressed warning(s) — "
            "the detector gates the partitioner path",
            data={"warnings": len(fired)}))
    else:
        findings.append(analysis.Finding(
            "spmd-smoke", "error", "spmd/fire-fixtures",
            "D9 spmd-coverage (all-replicated partitioner rules): the "
            "fire fixture produced NO warning — the detector went "
            "silently dead for partitioner-driven programs"))
    return findings


def _audit_spmd_fixtures(mesh) -> list:
    """Fire-fixture self-test for D9/D10/D11 (see audit_spmd)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    from paddle_tpu import analysis

    # D9: a residual stream explicitly replicated along every mesh axis
    def unsharded(x):
        for _ in range(4):
            x = jax.lax.with_sharding_constraint(
                x + 1.0, NamedSharding(mesh, P(None, None, None)))
        return x

    jx9 = jax.make_jaxpr(unsharded)(jnp.ones((8, 32, 64), jnp.float32))
    d9 = [f for f in analysis.audit_sharding_coverage(jx9, mesh=mesh)
          if f.severity == "warning"]

    # D10: an all_gather whose output only feeds elementwise ops —
    # 128x256 f32 = 131072 B/device, above the default warning floor
    gather_axis = list(mesh.shape)[-1]

    def gratuitous(x):
        g = jax.lax.all_gather(x, gather_axis, axis=0, tiled=True)
        return g * 2.0 + 1.0

    fn = shard_map(gratuitous, mesh=mesh, in_specs=P(gather_axis),
                   out_specs=P(), check_rep=False)
    jx10 = jax.make_jaxpr(fn)(jnp.ones((128, 256), jnp.float32))
    d10 = [f for f in analysis.audit_collectives(jx10)
           if f.severity == "warning"]

    # D11: a device_put inside the program
    def putter(x):
        return jax.device_put(x * 2.0, NamedSharding(mesh, P())) + 1.0

    jx11 = jax.make_jaxpr(putter)(jnp.ones((8, 8), jnp.float32))
    d11 = [f for f in analysis.audit_transfers(jx11)
           if f.severity == "warning"]

    findings = []
    for det, fired in (("D9 spmd-coverage (unsharded stream)", d9),
                       ("D10 spmd-collective (gratuitous all-gather)", d10),
                       ("D11 spmd-transfer (in-program device_put)", d11)):
        if fired:
            findings.append(analysis.Finding(
                "spmd-smoke", "note", "spmd/fire-fixtures",
                f"{det}: fire fixture produced "
                f"{len(fired)} unsuppressed warning(s) — the detector "
                "gates", data={"warnings": len(fired)}))
        else:
            findings.append(analysis.Finding(
                "spmd-smoke", "error", "spmd/fire-fixtures",
                f"{det}: the fire fixture produced NO warning — the "
                "detector went silently dead and sharding regressions "
                "would pass lint"))
    return findings


def audit_conc() -> list:
    """The `conc` smoke (round 17): a genuinely multi-threaded
    serving/ckpt/obs stress with lockdep recording ON — serving ticks on
    the owner thread, a scraper thread hammering the shared /metrics +
    /healthz endpoint, overlapped async checkpoint commits on the saver
    thread, and a comm-watchdog scan loop — then the D14 audit requires
    the recorded lock-ORDER graph to be ACYCLIC with zero
    blocking-under-hot-lock events, and the D15 audit requires zero
    owner-thread contract violations (FLAGS_debug_thread_checks is on
    for the whole stress). Afterwards the fire fixtures self-test every
    detector: a silently-dead detector fails the gate exactly like a
    falsely-firing one (the spmd-smoke rule)."""
    import http.client
    import shutil
    import tempfile
    import threading

    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import analysis, ckpt, obs
    from paddle_tpu.core import lockdep
    from paddle_tpu.distributed.comm_watchdog import CommTaskManager
    from paddle_tpu.inference.engine import ServingEngine

    findings = []
    paddle.seed(0)
    model = _tiny_llama()
    lockdep.reset()
    lockdep.enable()
    paddle.set_flags({"FLAGS_debug_thread_checks": True})
    root = tempfile.mkdtemp(prefix="graft_lint_conc_")
    saver = srv = mgr = None
    try:
        eng = ServingEngine(model, max_slots=2)
        srv = obs.shared_server(0)
        srv.register_engine("conc0", eng.registry,
                            ready=lambda: eng.warmed)
        mgr = CommTaskManager(scan_interval=0.01,
                              default_timeout=60.0).start()
        saver = ckpt.AsyncCheckpointer(root)
        stop = threading.Event()
        scrape_errors: list = []
        scrapes = [0]

        def scrape():
            conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                              timeout=10)
            try:
                while not stop.is_set():
                    for path in ("/metrics", "/healthz"):
                        conn.request("GET", path)
                        conn.getresponse().read()
                        scrapes[0] += 1
            except Exception as e:       # surfaced as a gate error below
                scrape_errors.append(e)
            finally:
                conn.close()

        scraper = threading.Thread(target=scrape, name="conc-scraper",
                                   daemon=True)
        scraper.start()
        rs = np.random.RandomState(0)
        tree = {"w": rs.randn(64).astype("float32")}
        with mgr.watch("conc-smoke"):
            for i, (ln, nt) in enumerate(((3, 2), (6, 4), (4, 3), (5, 2))):
                eng.add_request(rs.randint(0, 128, (ln,)),
                                max_new_tokens=nt)
                while eng.has_work():
                    eng.step()
                saver.save(i + 1, tree)   # overlapped background commit
        saver.wait()
        stop.set()
        scraper.join(timeout=15)
        if scrape_errors:
            findings.append(analysis.Finding(
                "conc-smoke", "error", "conc/stress",
                f"/metrics scraper thread failed mid-stress: "
                f"{scrape_errors[0]!r}"))
        elif scrapes[0] < 2:
            findings.append(analysis.Finding(
                "conc-smoke", "error", "conc/stress",
                "the scraper thread never completed a scrape — the "
                "stress did not actually exercise concurrent reads"))
    finally:
        lockdep.disable()
        paddle.set_flags({"FLAGS_debug_thread_checks": False})
        if saver is not None:
            saver.close()
        if mgr is not None:
            mgr.shutdown()
        if srv is not None:
            srv.close()
        shutil.rmtree(root, ignore_errors=True)

    seen = lockdep.locks_seen()
    if len(seen) < 3:
        findings.append(analysis.Finding(
            "conc-smoke", "error", "conc/stress",
            f"lockdep instrumentation looks dead: only {sorted(seen)} "
            "tracked lock(s) recorded across a serving+scrape+ckpt+"
            "watchdog stress — the wrappers lost their recording hook"))
    else:
        findings.append(analysis.Finding(
            "conc-smoke", "note", "conc/stress",
            f"stress recorded {len(seen)} tracked locks, "
            f"{len(lockdep.lock_graph())} order edge(s), "
            f"{scrapes[0]} concurrent scrapes",
            data={"locks": sorted(seen)}))
    findings += analysis.audit_lock_order(loc="conc/stress")
    findings += analysis.audit_thread_contracts(loc="conc/stress")
    lockdep.reset()
    findings += _audit_conc_fixtures()
    return findings


def _audit_conc_fixtures() -> list:
    """Fire-fixture self-test for D13/D14/D15 (see audit_conc)."""
    import ast as ast_mod
    import threading

    import paddle_tpu as paddle
    from paddle_tpu import analysis
    from paddle_tpu.core import lockdep

    fx = os.path.join(REPO, "tests", "lint_fixtures")

    def _warns(findings):
        return [f for f in findings if f.severity == "warning"]

    p13 = os.path.join(fx, "fx_conc_guarded.py")
    src = open(p13).read()
    d13a = _warns(analysis.lint_guarded_by(
        ast_mod.parse(src), src, "fx_conc_guarded.py"))
    d13b = _warns(analysis.audit_shared_state(
        [os.path.join(fx, "fx_conc_shared.py")], fx))
    d15s = _warns(analysis.audit_contract_callsites(
        [os.path.join(fx, "fx_conc_contract.py")], fx))

    # D14: deterministic two-lock cycle + a blocking call under a hot
    # lock, on scratch lockdep state
    lockdep.reset()
    lockdep.enable()
    la = lockdep.make_lock("fx.A")
    lb = lockdep.make_lock("fx.B", hot=True)
    with la:
        with lb:
            pass
    with lb:
        with la:
            pass
        lockdep.note_blocking("fsync", "fx_conc")
    lockdep.disable()
    d14 = _warns(analysis.audit_lock_order(loc="conc/fire-fixtures"))
    d14_cycle = [f for f in d14 if f.detector == "conc-lock-order"]
    d14_block = [f for f in d14 if f.detector == "conc-blocking-under-lock"]
    lockdep.reset()

    # D15 runtime: a second thread driving a bound contract must BOTH
    # raise ConcurrencyContractError and record an auditable violation
    paddle.set_flags({"FLAGS_debug_thread_checks": True})
    try:
        contract = lockdep.ThreadContract("fx.Engine")
        contract.check("step")              # binds this (owner) thread
        raised: list = []

        def violate():
            try:
                contract.check("step")
            except lockdep.ConcurrencyContractError as e:
                raised.append(e)

        t = threading.Thread(target=violate, name="conc-violator")
        t.start()
        t.join()
        d15r = _warns(analysis.audit_thread_contracts(
            loc="conc/fire-fixtures")) if raised else []
    finally:
        paddle.set_flags({"FLAGS_debug_thread_checks": False})
        lockdep.reset()

    findings = []
    for det, fired in (
            ("D13 conc-guarded-by (unlocked mutations)", d13a),
            ("D13 conc-shared-state (thread-root global)", d13b),
            ("D14 conc-lock-order (two-lock cycle)", d14_cycle),
            ("D14 conc-blocking-under-lock (fsync under hot lock)",
             d14_block),
            ("D15 conc-thread-contract static (root drives engine)",
             d15s),
            ("D15 conc-thread-contract runtime (second thread)", d15r)):
        if fired:
            findings.append(analysis.Finding(
                "conc-smoke", "note", "conc/fire-fixtures",
                f"{det}: fire fixture produced {len(fired)} unsuppressed "
                "warning(s) — the detector gates",
                data={"warnings": len(fired)}))
        else:
            findings.append(analysis.Finding(
                "conc-smoke", "error", "conc/fire-fixtures",
                f"{det}: the fire fixture produced NO warning — the "
                "detector went silently dead and concurrency regressions "
                "would pass lint"))
    return findings


def audit_router() -> list:
    """The `router` smoke (round 20): a REAL 2-replica tiny-LLaMA fleet
    behind the multi-replica Router, with the engines' owner-thread
    contracts enforced (FLAGS_debug_thread_checks on for the whole
    smoke — each replica's driver thread is the only thing allowed to
    drive its engine, and a violation kills the replica, which fails the
    gate below).

    Sequence: both replicas warm through the router's warmup ladder
    (whole-prefill, cache-hit chunk and decode programs at the buckets
    the traffic uses, ending in ``finish_warmup()``) → a shared-prefix
    stream routed by ``prefix_affine`` must concentrate on one replica
    and record fleet prefix-cache hits + ≥1 router affinity hit → a
    drain/handoff ROLLING RESTART mid-stream (replacement admitted only
    after warmup + readiness) with every future completing exactly once
    → gates: ZERO compiles after any replica's warmup barrier (the
    shared AOT executable cache means the replacement warms off r0's
    programs), a clean D17 ``audit_fleet``, every REQUIRED_FLEET_METRICS
    row present in the router's registry, and the affinity-defeat fire
    fixture (a drifting fingerprint on a second fleet) must trip the D17
    warning — a silently-dead detector fails the gate like a falsely
    firing one."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import analysis, obs
    from paddle_tpu.inference.engine import ServingEngine
    from paddle_tpu.serving import Router

    findings = []
    paddle.seed(0)
    model = _tiny_llama()
    warm_rs = np.random.RandomState(1)

    def _mk():
        return ServingEngine(model, max_slots=2)

    probe = _mk()
    bs = probe.block_size
    probe.close()
    # warmup prompts share a 2-block prefix of their OWN (distinct from
    # the traffic prefix, so the stream starts cache-cold) but the SAME
    # shapes: request 1 warms the whole-prefill + decode programs,
    # request 2 the cache-hit suffix chunk ladder
    warm_shared = warm_rs.randint(0, 128, (2 * bs + 1,))
    warm_tails = warm_rs.randint(0, 128, (3, 2))

    def _warm(eng):
        # request 1 alone: whole-prefill + single-slot decode buckets
        eng.add_request(np.concatenate([warm_shared, warm_tails[0]]),
                        max_new_tokens=2)
        eng.run()
        # requests 2+3 TOGETHER: the cache-hit suffix chunk ladder and
        # the 2-slot decode bucket the concurrent traffic phase rides
        eng.add_request(np.concatenate([warm_shared, warm_tails[1]]),
                        max_new_tokens=8)
        eng.add_request(np.concatenate([warm_shared, warm_tails[2]]),
                        max_new_tokens=8)
        eng.run()

    paddle.set_flags({"FLAGS_debug_thread_checks": True})
    obs.clear_events()
    router = None
    try:
        router = Router([_mk(), _mk()], policy="prefix_affine",
                        warmup=_warm)
        if not router.wait_ready(300):
            findings.append(analysis.Finding(
                "fleet", "error", "router/fleet-smoke",
                "fleet never became ready: "
                + repr([(n, router.replica(n).state,
                         router.replica(n).error)
                        for n in router.replicas])))
            return findings
        rs = np.random.RandomState(0)
        shared = rs.randint(0, 128, (2 * bs + 1,))
        futs = []
        # phase 1: sequential shared-prefix stream — prefix_affine must
        # concentrate it (deterministic placement, deterministic hits)
        for i in range(6):
            fut = router.submit(
                np.concatenate([shared, rs.randint(0, 128, (2,))]),
                max_new_tokens=2)
            fut.result(120)
            futs.append(fut)
        # phase 2: rolling restart mid-stream — requests in flight on
        # the hot replica finish in place, nothing drops or duplicates
        hot = futs[-1].replica
        for _ in range(4):
            futs.append(router.submit(
                np.concatenate([shared, rs.randint(0, 128, (2,))]),
                max_new_tokens=8))
        new_name = router.drain(hot, replacement=_mk())
        for _ in range(4):
            futs.append(router.submit(
                np.concatenate([shared, rs.randint(0, 128, (2,))]),
                max_new_tokens=2))
        bad = []
        for fut in futs:
            try:
                fut.result(120)
            except Exception as e:      # noqa: BLE001 — gate evidence
                bad.append(repr(e))
            if fut.completions != 1:
                bad.append(f"completions={fut.completions}")
        stats = router.fleet_stats()
        if bad:
            findings.append(analysis.Finding(
                "fleet", "error", "router/fleet-smoke",
                f"rolling restart dropped or duplicated requests: {bad}",
                data={"bad": bad, "stats": stats}))
        else:
            findings.append(analysis.Finding(
                "fleet", "note", "router/fleet-smoke",
                f"14-request shared-prefix stream + drain/handoff of "
                f"{hot} (replacement {new_name}) completed every future "
                "exactly once"))
        if stats["affinity_hits"] < 1 or stats["fleet_prefix_hits"] < 1:
            findings.append(analysis.Finding(
                "fleet", "error", "router/fleet-smoke",
                "prefix_affine routed a shared-prefix stream with "
                f"{stats['affinity_hits']} affinity hit(s) and "
                f"{stats['fleet_prefix_hits']} fleet prefix-cache "
                "hit(s) — affinity placement is not concentrating "
                "shared traffic", data=dict(stats)))
        findings += analysis.audit_fleet(router, loc="router/fleet-smoke")
        snap = router.registry.to_dict()
        missing = [m for m in REQUIRED_FLEET_METRICS if m not in snap]
        if missing:
            findings.append(analysis.Finding(
                "fleet", "error", "router/fleet-smoke",
                f"router registry is missing required fleet metrics: "
                f"{missing}"))
        else:
            findings.append(analysis.Finding(
                "fleet", "note", "router/fleet-smoke",
                f"all {len(REQUIRED_FLEET_METRICS)} required fleet "
                "metrics present"))
        # zero post-warmup compiles per replica: traffic and the
        # replacement's warmup must ride programs the ladder compiled
        evs = [e for e in obs.compile_events()
               if e.site.startswith("serving")]
        findings += obs.audit_recompiles(evs, loc="router/fleet-smoke")
    finally:
        if router is not None:
            router.close()
        paddle.set_flags({"FLAGS_debug_thread_checks": False})

    # ---- D17 affinity-defeat fire fixture: a fleet whose router-side
    # fingerprint DRIFTS (unique hashes for byte-identical prompts —
    # the namespace-mismatch failure mode) must trip the defeat warning
    # through the real counter plumbing. Consumed here as the fixture
    # working; silence is the gate failure.
    fire_router = Router([_mk(), _mk()], policy="prefix_affine",
                         warmup=_warm)
    try:
        if not fire_router.wait_ready(300):
            findings.append(analysis.Finding(
                "fleet", "error", "router/fire-fixture",
                "fire-fixture fleet never became ready"))
            return findings
        drift = iter(range(10 ** 6))
        fire_router._fingerprint = lambda arr: (next(drift),)
        # same shape as the warmup prompts, so the fixture stream rides
        # already-compiled buckets
        prompt = np.random.RandomState(2).randint(
            0, 128, (2 * bs + 3,)).astype(np.int32)
        for _ in range(6):
            fire_router.submit(prompt, max_new_tokens=2).result(120)
        fire = analysis.audit_fleet(fire_router,
                                    loc="router/fire-fixture")
        if any(f.severity == "warning" and "DEFEATED" in f.message
               for f in fire):
            findings.append(analysis.Finding(
                "fleet", "note", "router/fire-fixture",
                "D17 fire fixture verified: a drifting fingerprint "
                "scattered byte-identical prompts and tripped the "
                "affinity-defeat warning"))
        else:
            findings.append(analysis.Finding(
                "fleet", "error", "router/fire-fixture",
                "D17 detector is SILENTLY DEAD: a drifting router "
                "fingerprint scattered repeated prompts with zero "
                "affinity hits and produced no defeat warning",
                data={"findings": [f.to_dict() for f in fire],
                      "stats": fire_router.fleet_stats()}))
    finally:
        fire_router.close()
    return findings


def audit_plan_smoke() -> list:
    """The `plan` smoke (round 21): the static cost model + auto-plan
    search gated end-to-end on the 8-device virtual mesh.

    Sequence: `autoplan.search` enumerates + ranks every valid
    MeshConfig for a tiny-LLaMA train step from ONE abstract lowering
    (nothing executes) — fewer than 6 valid candidates is a gate error
    → D18 ``audit_plan`` must be clean on the report's own top-1 →
    the three partitioner_scaling configs (data8 / data4×tp2 /
    data2×sep4) are ACTUALLY measured (3 warmup + 2 timed steps each)
    and D19 ``audit_cost_model_calibration`` gates the predicted
    ordering against measured tok/s at default tolerance → fire
    fixtures: D18 must warn when the WORST candidate is deployed and
    error on a rigged HBM budget, and D19 must fire on a rigged-fabric
    search (tp/sep on a free DCN, ICI throttled to nothing) that flips
    the predicted ranking against the same measurements — a silently
    dead detector fails the gate like a falsely firing one."""
    import time

    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import analysis
    from paddle_tpu.distributed.partitioner import (MeshConfig, autoplan,
                                                    partition)
    from paddle_tpu.text.models import LlamaForCausalLM, llama_tiny_config

    findings = []
    batch, seq = 8, 64
    paddle.seed(0)
    cfg = llama_tiny_config(max_position_embeddings=128)
    report = autoplan.search(LlamaForCausalLM(cfg), 8, batch=batch,
                             seq=seq)
    findings += report.findings
    if len(report.candidates) < 6:
        findings.append(analysis.Finding(
            "plan", "error", "plan/search",
            f"auto-plan search found only {len(report.candidates)} valid "
            "candidate(s) on the 8-device virtual mesh (>= 6 expected "
            "for tiny-LLaMA) — the enumerator or the rule-table guards "
            "regressed",
            data={"rejected": report.rejected}))
        return findings
    findings.append(analysis.Finding(
        "plan", "note", "plan/search",
        f"ranked {len(report.candidates)} valid candidate(s) "
        f"({len(report.rejected)} rejected) from one abstract lowering; "
        f"top-1 {report.chosen}"))
    findings += analysis.audit_plan(report, loc="plan/search")

    # ---- measure the three partitioner_scaling configs (the bench
    # rung's well-separated trio) so D19 compares prediction against
    # REAL steps, not against another model
    measured = {}
    for mc in (MeshConfig(data=8), MeshConfig(data=4, tp=2),
               MeshConfig(data=2, sep=4)):
        paddle.seed(0)
        model = LlamaForCausalLM(cfg)
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters())

        def step(ids, labels, model=model, opt=opt):
            loss = model(ids, labels)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        pstep = partition(step, mc, model=model)
        rs = np.random.RandomState(0)

        def batch_pair():
            return (paddle.to_tensor(rs.randint(
                        0, cfg.vocab_size,
                        (batch, seq)).astype("int64")),
                    paddle.to_tensor(rs.randint(
                        0, cfg.vocab_size,
                        (batch, seq)).astype("int64")))

        for _ in range(3):                 # eager/discovery/compile
            float(pstep(*batch_pair()))
        t0 = time.perf_counter()
        for _ in range(2):
            float(pstep(*batch_pair()))
        wall = time.perf_counter() - t0
        measured[mc.describe()] = 2 * batch * seq / wall
    findings += analysis.audit_cost_model_calibration(
        report, measured, loc="plan/calibration")

    # ---- D18 fire fixtures through the REAL report: deploying the
    # worst-ranked candidate must warn, a rigged HBM budget must error
    worst = report.candidates[-1].config
    fire = analysis.audit_plan(report, chosen=worst, regress_pct=5.0,
                               loc="plan/fire-d18")
    if any(f.severity == "warning" for f in fire):
        findings.append(analysis.Finding(
            "plan", "note", "plan/fire-d18",
            f"D18 fire fixture verified: deploying the worst candidate "
            f"({worst.describe()}) tripped the plan-regression warning"))
    else:
        findings.append(analysis.Finding(
            "plan", "error", "plan/fire-d18",
            "D18 detector is SILENTLY DEAD: the worst-ranked candidate "
            "deployed against a 5% regression budget produced no "
            "warning",
            data={"findings": [f.to_dict() for f in fire]}))
    fire = analysis.audit_plan(report, hbm_limit_mb=0.001,
                               loc="plan/fire-d18")
    if not any(f.severity == "error" for f in fire):
        findings.append(analysis.Finding(
            "plan", "error", "plan/fire-d18",
            "D18 detector is SILENTLY DEAD: a 0.001 MiB HBM budget "
            "produced no over-budget error",
            data={"findings": [f.to_dict() for f in fire]}))

    # ---- D19 fire fixture: rig the fabrics (tp/sep collectives on a
    # free DCN, ICI throttled to nothing) so the grad psum dominates
    # and the predicted ranking FLIPS among the measured trio — the
    # calibration detector must catch the misordering
    rig = {"FLAGS_analysis_ici_gbps": 1e-4,
           "FLAGS_analysis_dcn_gbps": 1e6,
           "FLAGS_analysis_dcn_alpha_us": 0.0}
    saved = paddle.get_flags(list(rig))
    paddle.set_flags(rig)
    try:
        paddle.seed(0)
        rigged = autoplan.search(
            LlamaForCausalLM(cfg), 8, batch=batch, seq=seq,
            candidates=[MeshConfig(data=8, dcn_axes=("tp", "sep")),
                        MeshConfig(data=4, tp=2, dcn_axes=("tp", "sep")),
                        MeshConfig(data=2, sep=4,
                                   dcn_axes=("tp", "sep"))])
    finally:
        paddle.set_flags(saved)
    fire = analysis.audit_cost_model_calibration(
        rigged, measured, tol_pct=0.0, loc="plan/fire-d19")
    if rigged.chosen == report.chosen:
        findings.append(analysis.Finding(
            "plan", "error", "plan/fire-d19",
            f"rigged fabrics did not flip the predicted ranking (top-1 "
            f"still {rigged.chosen}) — the alpha-beta model is not "
            "reading the axis->fabric mapping",
            data={"rigged": [c.describe for c in rigged.candidates]}))
    elif any(f.severity == "error" for f in fire):
        findings.append(analysis.Finding(
            "plan", "note", "plan/fire-d19",
            f"D19 fire fixture verified: rigged fabrics flipped the "
            f"predicted top-1 to {rigged.chosen} and the calibration "
            "detector caught the misordering against measured tok/s"))
    else:
        findings.append(analysis.Finding(
            "plan", "error", "plan/fire-d19",
            "D19 detector is SILENTLY DEAD: a rigged-fabric search "
            "misordered the measured configs and the calibration audit "
            "stayed clean",
            data={"rigged_top1": rigged.chosen,
                  "findings": [f.to_dict() for f in fire]}))
    return findings


def audit_quant() -> list:
    """The `quant` smoke (round 20): drive int8 and int4 weight-only
    paged engines plus an int4-KV engine against a full-precision twin
    ON THE SAME STREAM, then gate the quantization claims:

    - D20 audit_quantized_bytes over the REAL decode-program ledger
      rows: the int8 engine's measured weight traffic must shrink
      >= 1.8x, the int4 engine's >= 3.4x, vs the twin (weight bytes
      from engine.param_bytes — the packed stack, scales included);
    - D20b audit_silent_dequant + D1/D4 on the quantized decode
      program's jaxpr;
    - zero compiles after the warmup barrier on every quantized engine
      (a per-mode cache-key miss recompiling mid-serve is D6);
    - fire fixtures for both detectors — a rigged non-shrinking ledger
      pair and a weight-sized int8->f32 convert must each trip an
      error; silence is the gate failure."""
    import types

    import numpy as np

    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu import analysis, obs
    from paddle_tpu.core.flags import flag
    from paddle_tpu.inference.engine import ServingEngine
    from paddle_tpu.obs import costs as _costs

    paddle.seed(0)
    model = _tiny_llama()
    rs = np.random.RandomState(0)
    prompts = [rs.randint(0, 128, (ln,)) for ln in (5, 9)]
    findings = []

    obs.clear_events()

    def drive(wq, kv):
        eng = ServingEngine(model, max_slots=2, weight_quant=wq,
                            kv_cache_dtype=kv)
        for p in prompts:
            eng.add_request(p, max_new_tokens=4)
        eng.run()                        # warm this mode's programs
        eng = ServingEngine(model, max_slots=2, weight_quant=wq,
                            kv_cache_dtype=kv)
        eng.finish_warmup()
        for p in prompts:
            eng.add_request(p, max_new_tokens=4)
        out = eng.run()
        assert len(out) == len(prompts) and all(
            len(v) for v in out.values()), \
            f"quant smoke engine (w={wq}, kv={kv}) failed to drain"
        return eng

    eng_full = drive("none", "model")
    eng_i8 = drive("int8", "model")
    eng_i4 = drive("int4", "model")
    eng_kv = drive("int4", "int4")

    # ---- D20: the ledger arithmetic on the real decode programs. The
    # twin pair shares bucket + sampling + KV mode, so every non-weight
    # byte cancels and the difference isolates the weight stream. The
    # audit runs on PROGRAM-BOUNDARY bytes (args + outputs): that is the
    # HBM traffic a bandwidth-bound decode step must move, and it is
    # platform-stable — this smoke runs on the CPU XLA fallback, whose
    # per-instruction bytes_accessed re-buys the materialized dequant
    # intermediate the fused TPU kernel keeps in VMEM. The failure modes
    # D20 exists for (a cache keyed without the quant mode serving the
    # bf16 program; a packed tensor shipped next to its dequantized
    # copy) all land in the boundary bytes.
    def decode_row(wq, kv):
        rows = [e for e in _costs.ledger("serving.decode")
                if f"/kv{kv}/w{wq}" in e.program and e.analyzed]
        return max(rows, key=lambda e: e.bytes_accessed, default=None)

    full_row = decode_row("none", "model")
    decls, boundary = [], []
    for mode, eng in (("int8", eng_i8), ("int4", eng_i4)):
        row = decode_row(mode, "model")
        if row is None or full_row is None:
            findings.append(analysis.Finding(
                "quant-bytes", "error", "quant/ledger",
                f"decode program rows missing from the cost ledger "
                f"(mode {mode}: {row is not None}, twin: "
                f"{full_row is not None}) — the engines never recorded "
                "analyzed programs", data={"mode": mode}))
            continue
        decls.append({"program": row.program, "twin": full_row.program,
                      "mode": mode,
                      "weight_bytes_full": eng_full.param_bytes})
        boundary.append(row)
    if decls:
        boundary.append(full_row)
        entries = [types.SimpleNamespace(
            program=e.program, analyzed=e.analyzed,
            bytes_accessed=e.arg_bytes + e.out_bytes) for e in boundary]
        d20 = analysis.audit_quantized_bytes(decls, entries=entries,
                                             loc="quant/ledger")
    else:
        d20 = []
    findings += d20
    if decls and not d20:
        findings.append(analysis.Finding(
            "quant-bytes", "note", "quant/ledger",
            f"D20 verified on {len(decls)} live decode-program pair(s): "
            "int8/int4 weight traffic within budget vs the "
            "full-precision twin",
            data={"declarations": [d["program"] for d in decls]}))

    # ---- jaxpr-side audits on the quantized decode program: silent
    # f32 dequant, fusion misses, host callbacks, stream dtype
    for tag, eng in (("int4w", eng_i4), ("int4kv", eng_kv)):
        jx = eng.decode_program_jaxpr()
        findings += analysis.audit_silent_dequant(
            jx, loc=f"quant/decode_step[{tag}]")
        findings += analysis.audit_fusion_misses(
            jx, loc=f"quant/decode_step[{tag}]")
        findings += analysis.audit_callbacks(
            jx, loc=f"quant/decode_step[{tag}]")
        findings += analysis.audit_dtype_stream(
            jx, policy=str(flag("FLAGS_residual_dtype")),
            loc=f"quant/decode_step[{tag}]")

    # ---- D6: the measured drives above ran after finish_warmup() on
    # engines whose programs the warm drives compiled — any serving
    # compile after a warmup barrier is a per-mode cache-key bug
    evs = [e for e in obs.compile_events() if e.site.startswith("serving")]
    findings += obs.audit_recompiles(evs, loc="quant/post-warmup")

    # ---- D20 fire fixture: a declared-int4 program whose ledger bytes
    # never shrank must trip the budget error (and a declaration over a
    # ledger that never analyzed the program must also fail)
    wfull = 100e6
    rig = [types.SimpleNamespace(program="fix|decode/q", analyzed=True,
                                 bytes_accessed=120e6),
           types.SimpleNamespace(program="fix|decode/full", analyzed=True,
                                 bytes_accessed=121e6)]
    fire = analysis.audit_quantized_bytes(
        [{"program": "fix|decode/q", "twin": "fix|decode/full",
          "mode": "int4", "weight_bytes_full": wfull}],
        entries=rig, loc="quant/fire-d20")
    missing = analysis.audit_quantized_bytes(
        [{"program": "fix|nowhere", "twin": "fix|decode/full",
          "mode": "int8", "weight_bytes_full": wfull}],
        entries=rig, loc="quant/fire-d20")
    if any(f.severity == "error" for f in fire) and \
            any(f.severity == "error" for f in missing):
        findings.append(analysis.Finding(
            "quant-bytes", "note", "quant/fire-d20",
            "D20 fire fixtures verified: the non-shrinking ledger pair "
            "tripped the byte-budget error and the never-analyzed "
            "declaration tripped the dead-audit error"))
    else:
        findings.append(analysis.Finding(
            "quant-bytes", "error", "quant/fire-d20",
            "D20 detector is SILENTLY DEAD: a declared-int4 program "
            "moving full-width bytes (or a declaration over a ledger "
            "that never saw it) produced no error",
            data={"fire": [f.to_dict() for f in fire],
                  "missing": [f.to_dict() for f in missing]}))

    # ---- D20b fire fixture: a weight-sized int8 -> f32 convert inside
    # a program must trip the silent-dequant error
    def dequant_to_f32(q, s):
        return q.astype(jnp.float32) * s

    jx_fire = jax.make_jaxpr(dequant_to_f32)(
        jnp.zeros((1024, 1024), jnp.int8), jnp.float32(0.1))
    fire = analysis.audit_silent_dequant(jx_fire, loc="quant/fire-d20b")
    if any(f.severity == "error" for f in fire):
        findings.append(analysis.Finding(
            "quant-bytes", "note", "quant/fire-d20b",
            "D20b fire fixture verified: a 1M-element int8->f32 "
            "convert_element_type tripped the silent-dequant error"))
    else:
        findings.append(analysis.Finding(
            "quant-bytes", "error", "quant/fire-d20b",
            "D20b detector is SILENTLY DEAD: a weight-sized int8->f32 "
            "convert produced no silent-dequant error",
            data={"findings": [f.to_dict() for f in fire]}))
    return findings


#: the baseline entries (with their `_matched` counts) of the most
#: recent run() — the --json payload exposes them so a PARALLEL gate
#: (check_scoreboard.lint_gate round 17: one subprocess per smoke group)
#: can aggregate staleness across partial runs instead of losing it
LAST_BASELINE: list = []


def run(models=(), ast=True, baseline_path=DEFAULT_BASELINE,
        prune_baseline=False, defer_stale=False):
    global LAST_BASELINE

    from paddle_tpu import analysis

    findings = []
    if ast:
        # the static (no-trace) audits ride the AST pass: in the
        # parallel CI gate exactly ONE group runs them, so a tune-cache
        # warning is reported once, not once per worker
        findings += analysis.lint_tree(REPO)
        findings += analysis.audit_tune_cache()
    smokes = {"paged": audit_serving, "obs": audit_obs,
              "ckpt": audit_ckpt, "spmd": audit_spmd, "conc": audit_conc,
              "router": audit_router, "plan": audit_plan_smoke,
              "quant": audit_quant}
    for name in models:
        findings += smokes.get(name, lambda n=name: audit_model(n))()
    baseline = analysis.load_baseline(baseline_path)
    analysis.apply_baseline(findings, baseline)
    LAST_BASELINE = baseline
    if defer_stale:
        # the caller (the parallel CI gate) aggregates staleness over
        # the union of its partial runs via the --json baseline counts
        return findings

    # stale-suppression detection: an entry that suppressed nothing can
    # only mask a future real finding. On a FULL-coverage run (AST lint +
    # every CI smoke) that is a gate failure; on a partial run it is
    # informational (model-specific entries legitimately go unmatched).
    stale = analysis.stale_suppressions(baseline)
    full = ast and set(CI_MODELS) <= set(models)
    if stale and prune_baseline:
        if not full:
            findings.append(analysis.Finding(
                "stale-suppression", "error", baseline_path,
                "--prune-baseline requires a full-coverage run (--models "
                f"{','.join(CI_MODELS)} with the AST lint on): a partial "
                "run cannot tell a dead suppression from one whose smoke "
                "did not compile"))
        else:
            kept = [{k: v for k, v in e.items() if not k.startswith("_")}
                    for e in baseline if e.get("_matched")]
            with open(baseline_path, "w") as fh:
                json.dump({"suppressions": kept}, fh, indent=2)
                fh.write("\n")
            for e in stale:
                findings.append(analysis.Finding(
                    "stale-suppression", "note", baseline_path,
                    f"pruned stale suppression (matched zero findings): "
                    f"detector={e['detector']!r} match={e['match']!r}",
                    data={k: v for k, v in e.items()
                          if not k.startswith("_")}))
            stale = []
    for e in stale:
        findings.append(analysis.Finding(
            "stale-suppression", "warning" if full else "note",
            baseline_path,
            f"suppression matched zero findings this run: "
            f"detector={e['detector']!r} match={e['match']!r}"
            + (f" (reason: {e['reason']})" if e.get("reason") else "")
            + (" — remove it or rerun with --prune-baseline" if full else
               " — partial run; rerun with the full CI model set to "
               "confirm staleness"),
            data={k: v for k, v in e.items() if not k.startswith("_")}))
    return findings


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--models", default="",
                    help="comma-separated smoke configs to audit "
                         f"({','.join(CI_MODELS)})")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help=f"suppression file (default {DEFAULT_BASELINE})")
    ap.add_argument("--no-ast", action="store_true",
                    help="skip the static audits (AST lint + tune-cache "
                         "scan) — model/jaxpr audits only")
    ap.add_argument("--prune-baseline", action="store_true",
                    help="rewrite the baseline without entries that "
                         "matched zero findings (full-coverage runs only)")
    ap.add_argument("--defer-stale", action="store_true",
                    help="emit no stale-suppression findings; the --json "
                         "payload carries per-entry match counts so a "
                         "parallel caller can aggregate staleness over "
                         "the union of partial runs")
    args = ap.parse_args(argv)

    # every smoke runs on the same virtual 8-device CPU platform the test
    # suite uses (tests/conftest.py): the spmd smoke needs the mesh, the
    # others behave identically — must happen before the backend
    # initializes, i.e. before paddle_tpu imports
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    xla_flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in xla_flags:
        os.environ["XLA_FLAGS"] = (
            xla_flags + " --xla_force_host_platform_device_count=8").strip()
    if os.environ["JAX_PLATFORMS"] == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")

    models = [m for m in args.models.split(",") if m]
    from paddle_tpu import analysis

    findings = run(models=models, ast=not args.no_ast,
                   baseline_path=args.baseline,
                   prune_baseline=args.prune_baseline,
                   defer_stale=args.defer_stale)
    if args.as_json:
        payload = analysis.to_json(findings)
        payload["baseline"] = [
            {"detector": e.get("detector"), "match": e.get("match"),
             "matched": e.get("_matched", 0)} for e in LAST_BASELINE]
        payload["models"] = models
        payload["ast"] = not args.no_ast
        print(json.dumps(payload, indent=2))
    else:
        print(analysis.format_text(findings))
    return 1 if analysis.gate_failures(findings) else 0


if __name__ == "__main__":
    raise SystemExit(main())
