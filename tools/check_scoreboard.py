"""Scoreboard integrity gate (VERDICT r5 Weak #1 — third round of drift).

Every throughput/TFLOP claim in README.md + PERF.md + BASELINE.md must match
the committed official record (`BENCH_DETAILS.json`) within tolerance. Two
rules:

  1. CITATION-ANCHORED (all three docs): any line citing
     `BENCH_DETAILS.json <config>` opens a +/-2-line window; every
     throughput-unit number in the window must match a numeric field of the
     cited config(s) — or of any config when the citation names no key.
     This is exactly the check that would have caught round 5's "4,914
     img/s ... (`BENCH_DETAILS.json` lenet)" against the committed 2,086.

  2. README-WIDE: README.md is the current-state scoreboard, so every
     throughput-unit number anywhere in it must match SOME numeric field
     of the official record (historical tables live in BASELINE.md/PERF.md,
     not README).

Conventions understood: `19.9k` suffixes, `81-83k` ranges, `63.6 →` arrow
prefixes (the left side of an arrow is the prior round's number — only the
right side is a current claim), commas, `**bold**`/`~` decoration. Checked
units: tokens/s(ec), tok/s, img/s, images/sec, seq/s(ec), TFLOP/s. Times
(ms), bandwidth and memory figures are derived quantities and out of scope.

Run directly (exit 1 on drift) or via tests/test_scoreboard.py (quick tier).
"""
from __future__ import annotations

import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOCS = ("README.md", "PERF.md", "BASELINE.md")
RTOL = 0.05  # docs round aggressively ("19.9k" for 19,925)

_UNIT = (r"(?:tokens?/s(?:ec)?|tok/s|img/s|images?/sec|img/sec|"
         r"seq/s(?:ec)?|sequences/sec|TFLOP/s)")
_NUM = r"\d[\d,]*(?:\.\d+)?"
#: a number (or a-b range) with optional k suffix, immediately followed by a
#: checked unit; leading ~ / ** decoration tolerated
_CLAIM = re.compile(
    rf"[~*]*({_NUM})(?:\s*[-–]\s*({_NUM}))?(k?)[*]*\s*({_UNIT})\b")
#: "<number> →" / "<number> ->": the left side of an improvement arrow is
#: the PRIOR round's value, not a claim about the current record
_ARROW_LHS = re.compile(rf"{_NUM}k?\s*(?:→|->)")
#: official records: the single-chip bench ladder AND the multichip driver
#: capture (tok/s + scaling efficiency per config, __graft_entry__.py) —
#: a doc claim citing either is checked against that record's numbers
_RECORDS = ("BENCH_DETAILS", "MULTICHIP_DETAILS")
_CITE = re.compile(
    r"(BENCH_DETAILS|MULTICHIP_DETAILS)\.json[`'\"]*[\s,]*((?:[a-z0-9_]+)?)")


def _leaves(obj, out):
    if isinstance(obj, dict):
        for v in obj.values():
            _leaves(v, out)
    elif isinstance(obj, (list, tuple)):
        for v in obj:
            _leaves(v, out)
    elif isinstance(obj, (int, float)) and not isinstance(obj, bool):
        out.append(float(obj))


def _numbers_of(results, keys):
    vals = []
    for k in keys:
        _leaves(results.get(k, {}), vals)
    return [v for v in vals if v > 0]


def _claims(text):
    """(lo, hi, unit) claims in `text`, arrow left-hand sides removed."""
    text = _ARROW_LHS.sub("", text)
    out = []
    for m in _CLAIM.finditer(text):
        lo = float(m.group(1).replace(",", ""))
        hi = float(m.group(2).replace(",", "")) if m.group(2) else lo
        if m.group(3) == "k":
            lo, hi = lo * 1e3, hi * 1e3
        out.append((lo, hi, m.group(4)))
    return out


def _matches(lo, hi, values, rtol):
    return any(lo * (1 - rtol) <= v <= hi * (1 + rtol) for v in values)


def _load_records(repo, details_path=None):
    """{record_name: (results_dict, platform)} for every committed
    official record. BENCH_DETAILS is mandatory; MULTICHIP_DETAILS
    optional (absent until the first driver capture lands) and tolerated
    when corrupt — its writer can be killed mid-dump, and a truncated
    capture must degrade to 'no record', not crash the gate."""
    records = {}
    for name in _RECORDS:
        path = details_path if (details_path and name == "BENCH_DETAILS") \
            else os.path.join(repo, f"{name}.json")
        try:
            with open(path) as f:
                payload = json.load(f)
            records[name] = (payload.get("results", {}),
                             str(payload.get("platform", "")))
        except (OSError, ValueError):
            if name == "BENCH_DETAILS":
                raise
            records[name] = ({}, "")
    return records


def check(repo=REPO, details_path=None, rtol=RTOL):
    """Returns a list of failure strings (empty = scoreboard consistent)."""
    loaded = _load_records(repo, details_path)
    records = {k: res for k, (res, _plat) in loaded.items()}
    platforms = {k: plat for k, (_res, plat) in loaded.items()}
    all_values = []
    for name, res in records.items():
        # the README-wide pool accepts only REAL-hardware numbers: a
        # cpu-virtual-mesh multichip capture (host-core contention, its
        # own note says 'do not quote') must not green-light an uncited
        # README throughput claim. Citation-anchored checks still see it.
        if name == "MULTICHIP_DETAILS" and platforms.get(name) != "tpu":
            continue
        # same rule per-config (round 10): bench rungs captured off-chip
        # (interpret-mode kernels, host-CPU serving runs — their records
        # carry platform:"cpu") never green-light a README claim either
        keys = [k for k in res
                if not (isinstance(res[k], dict)
                        and res[k].get("platform") == "cpu")]
        all_values.extend(_numbers_of(res, keys))
    failures = []
    for doc in DOCS:
        path = os.path.join(repo, doc)
        if not os.path.exists(path):
            continue
        with open(path) as f:
            lines = f.read().splitlines()
        for i, line in enumerate(lines):
            cites = _CITE.findall(line)
            if not cites:
                continue
            values = []
            cited_names = []
            for rec, key in cites:
                res = records.get(rec, {})
                keys = [key] if key in res else list(res)
                values.extend(_numbers_of(res, keys))
                cited_names.append(f"{rec}.json"
                                   + (f" {key}" if key in res else ""))
            window = "\n".join(lines[max(0, i - 2):i + 3])
            for lo, hi, unit in _claims(window):
                if not _matches(lo, hi, values, rtol):
                    failures.append(
                        f"{doc}:{i + 1}: claim '{lo:g}"
                        + (f"-{hi:g}" if hi != lo else "")
                        + f" {unit}' near citation of {cited_names}"
                        " matches no committed value")
        if doc == "README.md":
            for i, line in enumerate(lines):
                for lo, hi, unit in _claims(line):
                    if not _matches(lo, hi, all_values, rtol):
                        failures.append(
                            f"{doc}:{i + 1}: claim '{lo:g}"
                            + (f"-{hi:g}" if hi != lo else "")
                            + f" {unit}' matches no value in the committed "
                            "official records (BENCH_DETAILS.json / "
                            "MULTICHIP_DETAILS.json)")
    return failures


#: the parallel-gate partition (round 17): each group runs as ONE
#: graft_lint subprocess so independent smokes overlap on separate
#: cores and the gate wall stays at max(group) instead of sum(groups)
#: despite the new `conc` smoke. Grouping rationale: the serving-side
#: smokes (`paged`,`obs`,`ckpt`) share one tiny-LLaMA + the AOT
#: executable cache, so they stay in one process; the AST lint rides the
#: first (cheapest-compile) group; `spmd` (the wall-dominating GSPMD
#: compile) and `conc` (the multi-threaded stress) get their own
#: workers. Staleness cannot be judged inside any single partial run, so
#: workers run --defer-stale and the gate aggregates each baseline
#: entry's match counts across the union (full coverage restored).
LINT_GROUPS = (("llama,gpt,bert", True), ("paged,obs,ckpt", False),
               ("spmd", False), ("conc", False), ("router", False),
               ("plan", False), ("quant", False))


def lint_gate(models="llama,gpt,bert,paged,obs,ckpt,spmd,conc,router,plan,"
                     "quant",
              timeout=900):
    """The graft_lint CI gate (round-9; round-10 adds the `paged` serving
    smoke — a tiny-LLaMA 2-slot continuous-batching engine whose decode
    step program is audited at default flags; round-11 adds the `obs`
    telemetry smoke — required serving metrics must exist and the compile
    watchdog must see zero post-warmup retraces; round-12 adds the `ckpt`
    crash-consistency smoke — save → bit-flip → restore must fall back to
    the last good checkpoint, and the required ckpt metric rows must
    exist; round-14 extends `obs` with the flight-recorder/cost gate —
    the warmed engine must dump a valid Perfetto trace whose request
    spans tile TTFT, every driven decode bucket must carry XLA costs,
    and analysis D8 gates per-program bytes-accessed against the
    committed tools/cost_baseline.json; round-15 adds the `spmd`
    sharding smoke — the tp x dp hybrid train step audits clean through
    D9 sharding-coverage / D10 collective / D11 transfer on the
    8-device virtual mesh, the D9-D11 fire fixtures must still produce
    warnings, and stale lint_baseline.json suppressions fail the
    full-coverage run): the AST lint plus the
    jaxpr program audits over the model smoke configs must come back
    clean (no unsuppressed warning/error past tools/lint_baseline.json).
    Round 17: the smoke groups run as PARALLEL subprocesses
    (``LINT_GROUPS``) so the gate wall stays at the slowest group
    despite the added `conc` smoke; each worker defers stale-suppression
    judgment (``--defer-stale``) and the gate aggregates every baseline
    entry's match count across the union of runs — full-coverage
    staleness detection survives the split. Round 21 adds the `plan`
    cost-model smoke (D18 auto-plan regression + D19 predicted-vs-
    measured calibration, with their fire fixtures) as its own
    worker. Returns failure strings
    (empty = clean); also prints the merged per-detector finding counts
    so drift between runs is visible in the gate log even when the gate
    passes."""
    import subprocess
    from concurrent.futures import ThreadPoolExecutor

    # D8 prerequisite: the committed baseline must exist BEFORE the
    # subprocess runs — a deleted/unparseable baseline is a named gate
    # failure here, not a confusing downstream lint error
    baseline = os.path.join(REPO, "tools", "cost_baseline.json")
    try:
        with open(baseline) as fh:
            json.load(fh)
    except (OSError, ValueError) as e:
        return [f"LINT: tools/cost_baseline.json missing/unparseable "
                f"({e}) — analysis D8 cannot gate; regenerate with "
                "tools/roofline_report.py --write-baseline"]

    requested = [m for m in models.split(",") if m]
    grouped: set = set()
    groups = []           # (models_csv, with_ast)
    for grp, with_ast in LINT_GROUPS:
        sel = [m for m in grp.split(",") if m in requested]
        grouped.update(sel)
        if sel or with_ast:
            groups.append((",".join(sel), with_ast))
    leftover = [m for m in requested if m not in grouped]
    if leftover:
        groups.append((",".join(leftover), False))

    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")

    def run_group(sel, with_ast):
        cmd = [sys.executable,
               os.path.join(REPO, "tools", "graft_lint.py"),
               "--models", sel, "--json", "--defer-stale"]
        if not with_ast:
            cmd.append("--no-ast")
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  env=env, timeout=timeout, cwd=REPO)
        except subprocess.TimeoutExpired:
            return None, (f"graft_lint group '{sel}' did not finish "
                          f"within {timeout}s — a smoke hung or the "
                          "machine is overloaded; run tools/graft_lint.py "
                          f"--models {sel} directly"), None
        try:
            return json.loads(proc.stdout), None, proc.returncode
        except ValueError:
            return None, (f"graft_lint group '{sel}' produced no JSON "
                          f"(rc={proc.returncode}): "
                          f"{proc.stderr[-800:] or proc.stdout[-800:]}"), \
                proc.returncode

    with ThreadPoolExecutor(max_workers=len(groups)) as ex:
        results = list(ex.map(lambda g: run_group(*g), groups))

    out = []
    by_det: dict = {}
    suppressed = 0
    matched: dict = {}          # (detector, match) -> total hits
    ast_ran = False
    for (sel, with_ast), (payload, err, rc) in zip(groups, results):
        if err:
            out.append(err)
            continue
        ast_ran = ast_ran or payload.get("ast", with_ast)
        for k, v in payload.get("by_detector", {}).items():
            by_det[k] = by_det.get(k, 0) + v
        suppressed += payload.get("suppressed", 0)
        for e in payload.get("baseline", []):
            key = (e.get("detector"), e.get("match"))
            matched[key] = matched.get(key, 0) + int(e.get("matched", 0))
        fails = [f for f in payload.get("findings", [])
                 if not f.get("suppressed")
                 and f.get("severity") in ("warning", "error")]
        out.extend(f"LINT: [{f['severity']}/{f['detector']}] {f['loc']}: "
                   f"{f['message']}" for f in fails)
        if rc not in (0, None) and not fails:
            # the safety net the sequential gate had: graft_lint's own
            # gating disagreed with this filter — never report clean on
            # a group that exited nonzero
            out.append(f"graft_lint group '{sel}' exited {rc} with no "
                       "findings this gate could extract — gating logic "
                       "drift between graft_lint and lint_gate")
    print("LINT per-detector findings: "
          + (", ".join(f"{k}={v}" for k, v in sorted(by_det.items()))
             or "none")
          + f" (suppressed={suppressed}, {len(groups)} parallel groups)")

    # aggregated staleness: only a FULL union (every CI smoke + the AST
    # lint, all groups parsed) may call an entry dead
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import graft_lint as _gl

    full = ast_ran and set(_gl.CI_MODELS) <= set(requested) \
        and not any(err for _p, err, _rc in results)
    if full:
        for (det, match), hits in sorted(matched.items()):
            if hits == 0:
                out.append(
                    f"LINT: [warning/stale-suppression] "
                    f"tools/lint_baseline.json: suppression matched zero "
                    f"findings across the full parallel gate: "
                    f"detector={det!r} match={match!r} — remove it or "
                    "run tools/graft_lint.py --models "
                    f"{','.join(_gl.CI_MODELS)} --prune-baseline")
    return out


def main(argv=None):
    failures = check()
    for fl in failures:
        print("SCOREBOARD DRIFT:", fl)
    if failures:
        print(f"{len(failures)} drifted claim(s); docs must quote "
              "BENCH_DETAILS.json (the committed official record)")
        return 1
    print("scoreboard consistent: every checked doc claim matches "
          "BENCH_DETAILS.json")
    lint_failures = lint_gate()
    for fl in lint_failures:
        print(fl)
    if lint_failures:
        print(f"{len(lint_failures)} lint gate failure(s); run "
              "tools/graft_lint.py --models llama,gpt,bert for details")
        return 1
    print("lint gate clean: graft_lint audit of the smoke configs has no "
          "unsuppressed warnings/errors")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
