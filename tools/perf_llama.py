"""Single-chip LLaMA perf experiments (VERDICT r2 item 2: find the missing
MFU). Runs one variant per invocation on the real TPU and prints one JSON
line. Variants sweep batch/seq/amp-mode/remat so the winning recipe can be
promoted into bench.py.

Usage: python tools/perf_llama.py <variant>
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _sync(x):
    import jax
    import jax.numpy as jnp

    arr = x._data if hasattr(x, "_data") else x
    jax.device_get(jnp.ravel(arr)[0])


def run(batch, seq, mode, layers=8, hidden=1024, inter=2816, heads=16,
        iters=6, warmup=4, recompute=False):
    import paddle_tpu as paddle
    from paddle_tpu.text.models import LlamaConfig, LlamaForCausalLM

    paddle.seed(0)
    cfg = LlamaConfig(vocab_size=32000, hidden_size=hidden,
                      intermediate_size=inter, num_hidden_layers=layers,
                      num_attention_heads=heads,
                      max_position_embeddings=seq, use_recompute=recompute)
    model = LlamaForCausalLM(cfg)
    if mode == "o2":
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters(),
                                     multi_precision=True)
        model, opt = paddle.amp.decorate(model, opt, level="O2",
                                         dtype="bfloat16")
    else:
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters())
    rs = np.random.RandomState(0)
    ids = paddle.to_tensor(rs.randint(0, 32000, (batch, seq)).astype("int64"))

    amp_on = mode in ("o1", "o2")
    level = "O2" if mode == "o2" else "O1"

    @paddle.jit.to_static(share_discovery=True)
    def train_step(x):
        with paddle.amp.auto_cast(enable=amp_on, dtype="bfloat16",
                                  level=level):
            loss = model(x, x)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    # prime eager warmup/discovery at TINY shapes (eager fp32 residuals at
    # full batch would exceed HBM); big shapes go straight to compile
    small = paddle.to_tensor(
        np.random.RandomState(1).randint(0, 32000, (1, 128)).astype("int64"))
    _sync(train_step(small))
    _sync(train_step(small))
    for _ in range(warmup):
        out = train_step(ids)
        _sync(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = train_step(ids)
    _sync(out)
    dt = (time.perf_counter() - t0) / iters
    toks = batch * seq / dt
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    # 6ND decoder flops + attention term 12*L*H*S^2... report plain 6ND for
    # comparability with BENCH_r02 plus the attention-inclusive number
    flops6nd = 6 * n_params * toks
    attn = 12 * layers * hidden * seq * (batch * seq / dt)
    return {"batch": batch, "seq": seq, "mode": mode, "recompute": recompute,
            "step_ms": round(dt * 1e3, 1), "tokens_per_sec": round(toks),
            "tflops_6nd": round(flops6nd / 1e12, 1),
            "tflops_with_attn": round((flops6nd + attn) / 1e12, 1),
            "n_params": n_params, "loss": float(out)}


VARIANTS = {
    "base": lambda: run(4, 512, "o1"),            # BENCH_r02 shape
    "b8s1024": lambda: run(8, 1024, "o1"),
    "b16s1024": lambda: run(16, 1024, "o1"),
    "b8s1024_o2": lambda: run(8, 1024, "o2"),
    "b16s1024_o2": lambda: run(16, 1024, "o2"),
    "b32s1024_o2": lambda: run(32, 1024, "o2"),
    "b8s2048_o2": lambda: run(8, 2048, "o2"),
    "b16s1024_o2_rc": lambda: run(16, 1024, "o2", recompute=True),
    "fp32": lambda: run(8, 1024, "fp32"),
}

if __name__ == "__main__":
    name = sys.argv[1]
    t0 = time.time()
    res = VARIANTS[name]()
    res["name"] = name
    res["wall_s"] = round(time.time() - t0, 1)
    print(json.dumps(res))
