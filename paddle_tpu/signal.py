"""paddle.signal — frame / overlap_add / stft / istft
(≙ python/paddle/signal.py:42,167,272,449; kernels: phi frame/overlap_add +
fft_r2c/c2c).

TPU-first: frame extraction is a strided gather expressed with static shapes
(one `jnp.take` over precomputed indices — XLA lowers it to a cheap gather);
overlap-add is a segment-sum scatter; stft = frame × window → batched FFT on
the last axis, which XLA fuses into a single program. All paths trace, jit,
and differentiate through the tape.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from .core.dispatch import op_call

__all__ = ['stft', 'istft']


def _check_pos_int(v, what):
    if not isinstance(v, int) or v <= 0:
        raise ValueError(f'Unexpected {what}: {v}. It should be an positive integer.')


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice into overlapping frames. axis=-1: [..., L] -> [..., frame_length,
    num_frames]; axis=0: [L, ...] -> [num_frames, frame_length, ...]."""
    if axis not in (0, -1):
        raise ValueError(f'Unexpected axis: {axis}. It should be 0 or -1.')
    _check_pos_int(frame_length, 'frame_length')
    _check_pos_int(hop_length, 'hop_length')
    L = x.shape[axis]
    if frame_length > L:
        raise ValueError(
            f'Attribute frame_length should be less equal than sequence length, '
            f'but got ({frame_length}) > ({L}).')
    n_frames = 1 + (L - frame_length) // hop_length
    # [n_frames, frame_length] static index grid
    idx = (np.arange(n_frames)[:, None] * hop_length +
           np.arange(frame_length)[None, :])

    def f(a):
        g = jnp.take(a, jnp.asarray(idx), axis=axis)
        if axis == -1:
            # take put [n_frames, frame_length] last; paddle wants
            # [..., frame_length, n_frames]
            return jnp.swapaxes(g, -1, -2)
        return g  # axis=0: [n_frames, frame_length, ...] already

    return op_call(f, x, name="frame")


def overlap_add(x, hop_length, axis=-1, name=None):
    """Reconstruct from frames by summing overlaps (inverse of `frame`).
    axis=-1: [..., frame_length, n_frames] -> [..., output_len]."""
    if axis not in (0, -1):
        raise ValueError(f'Unexpected axis: {axis}. It should be 0 or -1.')
    _check_pos_int(hop_length, 'hop_length')
    if axis == -1:
        frame_length, n_frames = x.shape[-2], x.shape[-1]
    else:
        n_frames, frame_length = x.shape[0], x.shape[1]
    out_len = (n_frames - 1) * hop_length + frame_length
    seg = (np.arange(n_frames)[:, None] * hop_length +
           np.arange(frame_length)[None, :]).ravel()

    def f(a):
        if axis == -1:
            fr = jnp.swapaxes(a, -1, -2)          # [..., n_frames, frame_length]
            flat = fr.reshape(a.shape[:-2] + (n_frames * frame_length,))
            z = jnp.zeros(a.shape[:-2] + (out_len,), dtype=a.dtype)
            return z.at[..., jnp.asarray(seg)].add(flat)
        flat = a.reshape((n_frames * frame_length,) + a.shape[2:])
        z = jnp.zeros((out_len,) + a.shape[2:], dtype=a.dtype)
        return z.at[jnp.asarray(seg)].add(flat)

    return op_call(f, x, name="overlap_add")


def stft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
         pad_mode="reflect", normalized=False, onesided=True, name=None):
    """Short-time Fourier transform; output [..., freq, num_frames]."""
    from .core.dtype import is_complex

    _check_pos_int(n_fft, 'n_fft')
    hop_length = hop_length if hop_length is not None else n_fft // 4
    win_length = win_length if win_length is not None else n_fft
    _check_pos_int(hop_length, 'hop_length')
    if not (0 < win_length <= n_fft):
        raise ValueError(f'Unexpected win_length: {win_length}.')
    complex_input = is_complex(x.dtype)
    if complex_input and onesided:
        raise ValueError('onesided should be False when input is a complex Tensor.')

    if window is not None:
        wshape = tuple(window.shape)
        if wshape != (win_length,):
            raise ValueError(
                f'Unexpected window shape: {wshape}, expected ({win_length},)')
        win = window  # stays a live Tensor: grads + trace capture flow
    else:
        win = jnp.ones((win_length,), dtype=jnp.float32)
    seq_len = x.shape[-1] + (2 * (n_fft // 2) if center else 0)
    if seq_len < n_fft:
        raise ValueError(
            f'Input too short: {x.shape[-1]} samples with n_fft={n_fft} '
            f'(center={center}) yields no complete frame.')

    def f(a, w):
        if win_length < n_fft:  # center-pad the window to n_fft
            lpad = (n_fft - win_length) // 2
            w = jnp.pad(w, (lpad, n_fft - win_length - lpad))
        if center:
            pad = n_fft // 2
            cfg = [(0, 0)] * (a.ndim - 1) + [(pad, pad)]
            a = jnp.pad(a, cfg, mode=pad_mode)
        L = a.shape[-1]
        n_frames = 1 + (L - n_fft) // hop_length
        idx = (jnp.arange(n_frames)[:, None] * hop_length +
               jnp.arange(n_fft)[None, :])
        fr = jnp.take(a, idx, axis=-1) * w          # [..., n_frames, n_fft]
        if complex_input:
            spec = jnp.fft.fft(fr, axis=-1)
        elif onesided:
            spec = jnp.fft.rfft(fr, axis=-1)
        else:
            spec = jnp.fft.fft(fr.astype(jnp.complex64), axis=-1)
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, dtype=spec.real.dtype))
        return jnp.swapaxes(spec, -1, -2)           # [..., freq, n_frames]

    return op_call(f, x, win, name="stft")


def istft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
          normalized=False, onesided=True, length=None, return_complex=False,
          name=None):
    """Inverse STFT (least-squares overlap-add); input [..., freq, frames]."""
    _check_pos_int(n_fft, 'n_fft')
    hop_length = hop_length if hop_length is not None else n_fft // 4
    win_length = win_length if win_length is not None else n_fft
    if return_complex and onesided:
        raise ValueError('onesided should be False when return_complex is True.')
    n_freq, n_frames = x.shape[-2], x.shape[-1]
    expected = n_fft // 2 + 1 if onesided else n_fft
    if n_freq != expected:
        raise ValueError(f'Unexpected freq dim: {n_freq}, expected {expected}.')

    if window is not None:
        wshape = tuple(window.shape)
        if wshape != (win_length,):
            raise ValueError(
                f'Unexpected window shape: {wshape}, expected ({win_length},)')
        win = window
    else:
        win = jnp.ones((win_length,), dtype=jnp.float32)

    out_len = (n_frames - 1) * hop_length + n_fft
    seg = (np.arange(n_frames)[:, None] * hop_length +
           np.arange(n_fft)[None, :]).ravel()

    def f(a, w):
        if win_length < n_fft:
            lpad = (n_fft - win_length) // 2
            w = jnp.pad(w, (lpad, n_fft - win_length - lpad))
        spec = jnp.swapaxes(a, -1, -2)              # [..., n_frames, freq]
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, dtype=spec.real.dtype))
        if onesided:
            fr = jnp.fft.irfft(spec, n=n_fft, axis=-1)
        else:
            fr = jnp.fft.ifft(spec, axis=-1)
            if not return_complex:
                fr = fr.real
        fr = fr * w                                  # windowed frames
        flat = fr.reshape(fr.shape[:-2] + (n_frames * n_fft,))
        num = jnp.zeros(fr.shape[:-2] + (out_len,), dtype=fr.dtype)
        num = num.at[..., jnp.asarray(seg)].add(flat)
        wsq = jnp.tile(w * w, n_frames)
        den = jnp.zeros((out_len,), dtype=w.dtype)
        den = den.at[jnp.asarray(seg)].add(wsq)
        out = num / jnp.where(den > 1e-11, den, 1.0)
        if center:
            out = out[..., n_fft // 2: out_len - n_fft // 2]
        if length is not None:
            if length > out.shape[-1]:  # zero-pad the tail (torch/reference)
                out = jnp.pad(
                    out, [(0, 0)] * (out.ndim - 1) +
                    [(0, length - out.shape[-1])])
            else:
                out = out[..., :length]
        return out

    return op_call(f, x, win, name="istft")
