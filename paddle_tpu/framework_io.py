"""paddle.save / paddle.load (≙ python/paddle/framework/io.py:773,1020).

Pickles nested containers with Tensors converted to numpy, like the reference.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from .core.tensor import Tensor


def _to_serializable(obj):
    if isinstance(obj, Tensor):
        return {"__paddle_tpu_tensor__": True, "data": obj.numpy(),
                "stop_gradient": obj.stop_gradient, "name": obj.name}
    if isinstance(obj, dict):
        return {k: _to_serializable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_to_serializable(v) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def _from_serializable(obj, return_numpy=False):
    if isinstance(obj, dict):
        if obj.get("__paddle_tpu_tensor__"):
            if return_numpy:
                return obj["data"]
            t = Tensor(obj["data"], stop_gradient=obj["stop_gradient"])
            t.name = obj["name"]
            return t
        return {k: _from_serializable(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_from_serializable(v, return_numpy) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def save(obj, path, protocol=4, **configs):
    from .ckpt.core import atomic_write_stream

    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    # route through the crash-consistent core (round 12): pickle STREAMS
    # into a temp file (no second in-memory copy of a multi-GB state
    # dict), then fsync + atomic replace — a crash mid-save can no
    # longer leave a torn pickle where a good file used to be
    payload = _to_serializable(obj)
    atomic_write_stream(path,
                        lambda f: pickle.dump(payload, f, protocol=protocol))


def load(path, **configs):
    return_numpy = configs.get("return_numpy", False)
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _from_serializable(obj, return_numpy)
