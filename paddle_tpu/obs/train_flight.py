"""Per-step training flight recorder — the artifact that explains a slow
step.

Round 11's ``TelemetryCallback`` says *what* (``train_step_seconds`` p95
grew); this module records *why*: every train step driven through an
instrumented ``Model.fit`` carries an ordered span timeline — the data
wait (loader blocked before the batch arrived), host→device transfer,
forward / backward / optimizer-commit phases, compiled-step dispatches
(``to_static`` programs, with their ledger flops), segmented-lazy flush
sites (graph-break host syncs, ``core/lazy.py``) and the blocking half of
checkpoint saves — next to a separate track of the **overlapped**
async-checkpoint IO commits (``ckpt/async_saver.py`` background thread).
``TrainFlightRecorder.dump(path)`` exports the ring as Chrome-trace /
Perfetto JSON, and anomaly triggers (data starvation past
``FLAGS_obs_data_wait_ms``, a step-wall spike past
``FLAGS_obs_step_spike_factor`` × the rolling median, a checkpoint stall
past ``FLAGS_ckpt_stall_seconds``) auto-dump the last N step timelines to
``FLAGS_obs_flight_dir`` so the trace of the bad minute exists even when
nobody was watching — the training twin of ``obs/flight.py``.

The tiling invariant is **asserted, not assumed** (same discipline as the
serving recorder): a step's ``data_wait`` span ends exactly where its
``compute`` span begins, the two tile the step window, and the compute
span's endpoints must reproduce the recorded step wall — the SAME
``perf_counter`` reads the ``train_step_seconds`` histogram observed —
bitwise. ``dump()`` raises on violation; every span's args carry exact
float seconds (``t0_s``/``t1_s``) so the dumped JSON round-trips the
proof (``obs.validate_trace`` re-parses + re-checks).

Bounding: finished steps are a ring (``FLAGS_obs_train_flight_steps``;
oldest finished evicted, the active step never), per-step span lists are
capped (a pathological 10k-flush step degrades to "first spans + a
counter", never host memory), and the IO track is a fixed deque. The
per-step cost is a handful of attribute writes plus one deque append —
measured against the round-11 2% bar in tests/test_train_flight.py.
"""
from __future__ import annotations

import bisect
import json
import os
import time
from collections import deque

from ..core.flags import flag

#: per-step program-span cap: flush/dispatch spans past it are counted
#: (``spans_dropped``) instead of stored
STEP_SPAN_CAP = 256

#: overlapped-IO track spans kept (async ckpt commits, epoch marks)
IO_SPAN_CAP = 1024

#: auto-dumps per recorder: a flapping spike must not fill the disk —
#: the anomaly counter keeps counting, the files stop
AUTODUMP_CAP = 16

#: rolling step-wall window for the spike trigger, and the minimum
#: population before the median is trusted
SPIKE_WINDOW = 64
SPIKE_MIN_STEPS = 8


class StepFlight:
    """One train step's timeline. Timestamps are ``time.perf_counter``
    seconds; the lifecycle boundaries (``fetch_s``/``begin_s``/``end_s``)
    are the very reads the TelemetryCallback histograms observe."""

    __slots__ = ("index", "epoch", "fetch_s", "begin_s", "end_s",
                 "wall_s", "data_wait_s", "loss", "flops", "flushes",
                 "spans", "spans_dropped", "marks", "programs")

    def __init__(self, index, epoch, fetch_s, begin_s):
        self.index = int(index)
        self.epoch = int(epoch)
        self.fetch_s = float(fetch_s)     # window start (prev step end)
        self.begin_s = float(begin_s)     # batch arrived, compute starts
        self.end_s = None
        self.wall_s = None                # recorded by the callback
        self.data_wait_s = begin_s - fetch_s
        self.loss = None
        self.flops = 0.0                  # ledger flops executed this step
        self.flushes = 0
        self.spans: list = []             # (name, t0, t1, args) programs
        self.spans_dropped = 0
        self.marks: list = []             # (name, t, args) instantaneous
        self.programs: list = []          # (program_id, flops) dispatched

    def add_span(self, name, t0, t1, args=None):
        if len(self.spans) >= STEP_SPAN_CAP:
            self.spans_dropped += 1
            return
        self.spans.append((name, float(t0), float(t1), args or {}))

    def add_mark(self, name, t, args=None):
        if len(self.marks) < STEP_SPAN_CAP:
            self.marks.append((name, float(t), args or {}))

    @property
    def finished(self) -> bool:
        return self.end_s is not None


class TrainFlightRecorder:
    """Bounded ring of :class:`StepFlight` timelines + an overlapped-IO
    track. One per ``TelemetryCallback`` (module-level ``current()``
    routes the hook sites in hapi/lazy/ckpt/jit here)."""

    def __init__(self, capacity: int | None = None, registry=None):
        if capacity is None:
            capacity = int(flag("FLAGS_obs_train_flight_steps"))
        self.capacity = max(1, int(capacity))
        self._steps: deque = deque()      # finished StepFlights
        self.active: StepFlight | None = None
        self._io: deque = deque(maxlen=IO_SPAN_CAP)
        # rolling wall window for the spike trigger: arrival order in the
        # deque, a parallel SORTED list maintained by bisect so the
        # per-step median is an index, not a 64-element re-sort (the
        # re-sort alone was most of the hook budget vs the 2% bar)
        self._walls: deque = deque()
        self._walls_sorted: list = []
        self.evicted = 0
        self.autodumps = 0
        self.autodump_paths: list[str] = []
        if registry is None:
            from . import default_registry

            registry = default_registry()
        self.registry = registry
        self._m_anomalies = registry.counter(
            "train_flight_anomalies_total", "training flight-recorder "
            "anomaly triggers (data_starvation, step_spike, ckpt_stall)",
            ("trigger",))
        self._m_dumps = registry.counter(
            "train_flight_dumps_total", "training flight-recorder "
            "postmortem trace files written to FLAGS_obs_flight_dir",
            ("trigger",))
        self._m_steps = registry.gauge(
            "train_flight_steps", "step timelines held in the training "
            "flight-recorder ring (active + finished)")

    # ----------------------------------------------------------- record
    def step_begin(self, index, epoch, fetch_s, begin_s) -> StepFlight:
        self.active = StepFlight(index, epoch, fetch_s, begin_s)
        self._m_steps.set(len(self._steps) + 1)
        return self.active

    def step_end(self, end_s, wall_s, loss=None, flushes=0):
        """Close the active step (``wall_s`` is the callback's own
        ``end - begin`` — the histogram sample — recorded separately so
        dump-time can ASSERT the recorder and the histogram agree) and
        run the anomaly triggers."""
        st = self.active
        if st is None:
            return None
        self.active = None
        st.end_s = float(end_s)
        st.wall_s = float(wall_s)
        st.loss = loss
        st.flushes = int(flushes)
        self._steps.append(st)
        while len(self._steps) > self.capacity:
            self._steps.popleft()
            self.evicted += 1
        self._m_steps.set(len(self._steps))
        # ---- anomaly triggers (dump AFTER the step joined the ring so
        # the postmortem contains the offending timeline)
        dw_ms = float(flag("FLAGS_obs_data_wait_ms"))
        if dw_ms > 0 and st.data_wait_s * 1e3 > dw_ms:
            self.anomaly("data_starvation")
        factor = float(flag("FLAGS_obs_step_spike_factor"))
        if factor > 0 and len(self._walls) >= SPIKE_MIN_STEPS:
            med = self._walls_sorted[len(self._walls_sorted) // 2]
            if med > 0 and st.wall_s > factor * med:
                self.anomaly("step_spike")
        if len(self._walls) >= SPIKE_WINDOW:
            old = self._walls.popleft()
            del self._walls_sorted[bisect.bisect_left(self._walls_sorted,
                                                      old)]
        self._walls.append(st.wall_s)
        bisect.insort(self._walls_sorted, st.wall_s)
        return st

    def program_span(self, name, t0, t1, **args):
        """One program-category span (lazy flush, h2d, fwd/bwd, optimizer
        commit, compiled dispatch, blocking ckpt copy). Attaches to the
        active step; between steps it lands on the IO track so a save at
        an epoch boundary is still visible."""
        st = self.active
        if st is not None:
            st.add_span(name, t0, t1, args)
        else:
            self._io.append((name, float(t0), float(t1), args))

    def program_dispatch(self, name, t0, t1, entry=None):
        """A compiled ``to_static`` program executed during this step:
        span + the ledger flops that make the MFU numerator."""
        args = {"program": name}
        st = self.active
        if entry is not None and getattr(entry, "analyzed", False):
            args["program"] = entry.program
            args["flops"] = entry.flops
            if st is not None:
                st.flops += entry.flops
                st.programs.append((entry.program, entry.flops))
        self.program_span(f"dispatch:{name}", t0, t1, **args)

    def io_span(self, name, t0, t1, **args):
        """Overlapped-IO track (async ckpt commits; background thread —
        a deque append is GIL-atomic like the metrics hot path)."""
        self._io.append((name, float(t0), float(t1), args))

    def mark(self, name, t=None, **args):
        t = time.perf_counter() if t is None else t
        st = self.active
        if st is not None:
            st.add_mark(name, t, args)
        else:
            self._io.append((name, float(t), None, args))

    def steps(self) -> list[StepFlight]:
        out = list(self._steps)
        if self.active is not None:
            out.append(self.active)
        return out

    # ----------------------------------------------------------- export
    def _check_tiling(self):
        """The invariant: ``data_wait`` + ``compute`` tile the step
        window and the compute endpoints reproduce the recorded wall —
        all derived from the same three ``perf_counter`` reads the
        ``train_step_seconds`` histogram observed."""
        for st in self._steps:
            if not (st.fetch_s <= st.begin_s <= st.end_s):
                raise AssertionError(
                    f"step {st.index}: non-monotonic lifecycle "
                    f"({st.fetch_s} -> {st.begin_s} -> {st.end_s})")
            if st.wall_s is not None and \
                    (st.end_s - st.begin_s) != st.wall_s:
                raise AssertionError(
                    f"step {st.index}: compute span does not tile the "
                    f"recorded step wall ({st.end_s - st.begin_s!r} != "
                    f"{st.wall_s!r}) — the callback's histogram "
                    "bookkeeping and the recorder's diverged")

    def to_chrome(self) -> dict:
        """Chrome-trace/Perfetto ``traceEvents`` JSON (object form): tid
        0 = the train loop (step + lifecycle + program spans), tid 1 =
        the overlapped-IO track. Complete events carry exact seconds in
        ``args``; ts/dur microseconds are viewer-resolution only."""
        self._check_tiling()
        steps = self.steps()
        times = [st.fetch_s for st in steps]
        times += [t0 for _, t0, _, _ in self._io]
        epoch0 = min(times) if times else 0.0

        def us(t):
            return (t - epoch0) * 1e6

        ev: list[dict] = [
            {"ph": "M", "pid": 1, "tid": 0, "name": "process_name",
             "args": {"name": "paddle_tpu training"}},
            {"ph": "M", "pid": 1, "tid": 0, "name": "thread_name",
             "args": {"name": "train loop"}},
            {"ph": "M", "pid": 1, "tid": 1, "name": "thread_name",
             "args": {"name": "ckpt io (overlapped)"}},
        ]
        for st in steps:
            # a mid-step dump (anomaly postmortem while this step is
            # still computing) has no end yet — the window stretches
            # over whatever spans it recorded so far
            end = st.end_s or st.begin_s
            for _, _, t1, _ in st.spans:
                end = max(end, t1)
            for _, t, _ in st.marks:
                end = max(end, t)
            ev.append({"ph": "X", "pid": 1, "tid": 0, "name": "step",
                       "ts": us(st.fetch_s),
                       "dur": (end - st.fetch_s) * 1e6, "cat": "step",
                       "args": {"step": st.index, "epoch": st.epoch,
                                "wall_s": st.wall_s,
                                "data_wait_s": st.data_wait_s,
                                "loss": st.loss, "flops": st.flops,
                                "flushes": st.flushes,
                                "spans_dropped": st.spans_dropped,
                                "t0_s": st.fetch_s, "t1_s": end}})
            ev.append({"ph": "X", "pid": 1, "tid": 0, "name": "data_wait",
                       "ts": us(st.fetch_s),
                       "dur": (st.begin_s - st.fetch_s) * 1e6,
                       "cat": "lifecycle",
                       "args": {"step": st.index, "t0_s": st.fetch_s,
                                "t1_s": st.begin_s}})
            if st.end_s is not None:
                ev.append({"ph": "X", "pid": 1, "tid": 0,
                           "name": "compute", "ts": us(st.begin_s),
                           "dur": (st.end_s - st.begin_s) * 1e6,
                           "cat": "lifecycle",
                           "args": {"step": st.index,
                                    "wall_s": st.wall_s,
                                    "t0_s": st.begin_s,
                                    "t1_s": st.end_s}})
            for name, t0, t1, args in st.spans:
                ev.append({"ph": "X", "pid": 1, "tid": 0, "name": name,
                           "ts": us(t0), "dur": (t1 - t0) * 1e6,
                           "cat": "program",
                           "args": dict(args, step=st.index, t0_s=t0,
                                        t1_s=t1)})
            for name, t, args in st.marks:
                ev.append({"ph": "i", "pid": 1, "tid": 0, "name": name,
                           "ts": us(t), "s": "t",
                           "args": dict(args, step=st.index, t_s=t)})
        for name, t0, t1, args in self._io:
            if t1 is None:
                ev.append({"ph": "i", "pid": 1, "tid": 1, "name": name,
                           "ts": us(t0), "s": "t",
                           "args": dict(args, t_s=t0)})
            else:
                ev.append({"ph": "X", "pid": 1, "tid": 1, "name": name,
                           "ts": us(t0), "dur": (t1 - t0) * 1e6,
                           "cat": "io",
                           "args": dict(args, t0_s=t0, t1_s=t1)})
        return {"traceEvents": ev, "displayTimeUnit": "ms",
                "otherData": {"source": "paddle_tpu.obs.train_flight",
                              "steps": len(steps),
                              "evicted": self.evicted,
                              "epoch_s": epoch0}}

    def dump(self, path: str) -> str:
        obj = self.to_chrome()
        with open(path, "w") as fh:
            json.dump(obj, fh)
        return path

    #: name parity with ServingEngine.dump_trace — same artifact shape,
    #: same validator entry point (obs.validate_trace)
    dump_trace = dump

    # ---------------------------------------------------------- anomaly
    def anomaly(self, trigger: str) -> str | None:
        """One anomaly: count it and (when FLAGS_obs_flight_dir is set)
        write the last-N-steps postmortem, capped at AUTODUMP_CAP files
        per recorder. Never raises — a broken postmortem path must not
        take the train loop down."""
        self._m_anomalies.labels(trigger).inc()
        d = str(flag("FLAGS_obs_flight_dir") or "")
        if not d or self.autodumps >= AUTODUMP_CAP:
            return None
        try:
            os.makedirs(d, exist_ok=True)
            path = os.path.join(
                d, f"train_{trigger}_{os.getpid()}_{self.autodumps}.json")
            self.dump(path)
        except Exception:
            return None
        self.autodumps += 1
        self.autodump_paths.append(path)
        self._m_dumps.labels(trigger).inc()
        return path


# ----------------------------------------------------- module-level hook
#: the recorder the hook sites (hapi train_batch, core/lazy flushes,
#: ckpt savers, jit dispatch) report to; set by TelemetryCallback for the
#: duration of a fit. A plain module global: the train loop is
#: single-threaded, background IO threads only append to their own track.
_CURRENT: TrainFlightRecorder | None = None


def current() -> TrainFlightRecorder | None:
    return _CURRENT


def set_current(rec: TrainFlightRecorder | None):
    """Install ``rec`` as the active recorder; returns the previous one
    (nested fits restore it on exit)."""
    global _CURRENT

    prev = _CURRENT
    _CURRENT = rec
    return prev


# ------------------------------------------------------------ validation
def validate_train_trace(obj_or_path) -> dict:
    """Structural validation of a dumped TRAINING trace — the re-parse
    half of the round trip (``obs.validate_trace`` routes training dumps
    here via ``otherData.source``). Verifies: JSON loads, traceEvents
    exists, non-negative durations, and per step: the lifecycle spans
    NEST inside the step window, ``data_wait`` starts the window and ends
    exactly where ``compute`` begins, ``compute`` ends the step window,
    and the compute endpoints reproduce the recorded ``wall_s`` bitwise.
    Raises ValueError on violation; returns a summary dict."""
    if isinstance(obj_or_path, (str, os.PathLike)):
        with open(obj_or_path) as fh:
            obj = json.load(fh)
    else:
        obj = obj_or_path
    evs = obj.get("traceEvents")
    if not isinstance(evs, list) or not evs:
        raise ValueError("trace has no traceEvents array")
    by_step: dict = {}
    io_spans = 0
    for e in evs:
        if e.get("ph") != "X":
            continue
        if e.get("dur", 0) < 0:
            raise ValueError(f"negative-duration span: {e}")
        if e.get("tid") == 1:
            io_spans += 1
            continue
        idx = (e.get("args") or {}).get("step")
        if idx is not None:
            by_step.setdefault(idx, {}).setdefault(
                e["name"], []).append(e)
    steps = 0
    tiled = 0
    for idx, spans in sorted(by_step.items()):
        if "step" not in spans:
            raise ValueError(
                f"step {idx}: sub-spans without a step window span")
        steps += 1
        win = spans["step"][0]["args"]
        lo, hi = win["t0_s"], win["t1_s"]
        for name, group in spans.items():
            for s in group:
                a = s["args"]
                if not (lo <= a["t0_s"] and a["t1_s"] <= hi):
                    raise ValueError(
                        f"span {name!r} escapes its step window on step "
                        f"{idx}: [{a['t0_s']}, {a['t1_s']}] outside "
                        f"[{lo}, {hi}]")
        if "data_wait" in spans and "compute" in spans:
            dw = spans["data_wait"][0]["args"]
            cp = spans["compute"][0]["args"]
            if dw["t0_s"] != lo:
                raise ValueError(
                    f"step {idx}: data_wait does not start the step "
                    f"window ({dw['t0_s']!r} != {lo!r})")
            if dw["t1_s"] != cp["t0_s"]:
                raise ValueError(
                    f"step {idx}: data_wait does not end where compute "
                    f"begins ({dw['t1_s']!r} != {cp['t0_s']!r})")
            wall = cp.get("wall_s")
            if wall is not None and (cp["t1_s"] - cp["t0_s"]) != wall:
                raise ValueError(
                    f"step {idx}: compute span does not tile the "
                    f"recorded step wall "
                    f"({cp['t1_s'] - cp['t0_s']!r} != {wall!r})")
            tiled += 1
    return {"events": len(evs), "steps": steps, "tiled_steps": tiled,
            "io_spans": io_spans}
