"""Optional stdlib /metrics + /healthz endpoint for the serving path.

``serve_metrics(port, registry)`` starts a daemon-thread
``http.server`` exposing:

  * ``/metrics``  — Prometheus text exposition of the base registry
    PLUS every registered engine's registry with an ``engine="<name>"``
    label stamped on its samples (round 16: one scrape target covers N
    ``ServingEngine`` instances in one process — pre-round-16 only the
    first engine to bind the port was exported).
  * ``/healthz``  — readiness, not just liveness: with engines
    registered it returns 200 ``ready`` only once EVERY registered
    engine's readiness probe passes (a ``ServingEngine`` flips ready at
    ``finish_warmup()`` — the health signal a multi-replica router
    consumes), 503 ``warming`` before that; with none registered it
    stays the plain 200 ``ok`` liveness check.
  * ``/healthz?engine=NAME``  — per-replica readiness (round 20): the
    named engine's probe alone, so a router can admit replica B while
    replica A is still warming. 404 ``unknown engine`` when NAME is not
    registered. The bare-path aggregate contract is unchanged.

No dependencies beyond the stdlib (the container bakes no prometheus
client), one thread, read-only — good enough for a scrape target, not a
general web server. Engines attach automatically when
``FLAGS_obs_http_port`` > 0: the first engine creates the shared server
(``shared_server(port)``), later engines register into it instead of
failing the bind.
"""
from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..core import lockdep


class MetricsServer:
    def __init__(self, port: int, registry, host: str = "127.0.0.1"):
        self.registry = registry
        self._lock = lockdep.make_lock("obs.MetricsServer._lock", hot=True)
        # name -> (registry, ready_fn) — mutated under _lock, read by
        # the handler thread (dict snapshot per request)
        self._engines: dict = {}      # guarded-by: _lock
        self._closed = False          # guarded-by: _lock
        srv = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib API name)
                path = self.path.split("?")[0]
                if path == "/metrics":
                    body = srv.render().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                elif path == "/healthz":
                    from urllib.parse import parse_qs, urlparse

                    q = parse_qs(urlparse(self.path).query)
                    name = q.get("engine", [None])[0]
                    ready, body = srv.health(engine=name)
                    body = body.encode()
                    if ready:
                        code = 200
                    else:
                        code = 404 if body.startswith(b"unknown") else 503
                    self.send_response(code)
                    self.send_header("Content-Type", "text/plain")
                else:
                    body = b"not found\n"
                    self.send_response(404)
                    self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # silence per-request stderr spam
                return None

        self._httpd = ThreadingHTTPServer((host, int(port)), Handler)
        self.port = self._httpd.server_address[1]  # resolved (port=0 OK)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name=f"obs-metrics-:{self.port}",
                                        daemon=True)
        self._thread.start()

    # ------------------------------------------------------ multi-engine
    def register_engine(self, name: str, registry, ready=None):
        """Attach one engine's registry (exported with
        ``engine="<name>"`` labels) and its readiness probe (a callable;
        ``ServingEngine`` passes ``lambda: self.warmed``)."""
        with self._lock:
            self._engines[str(name)] = (registry, ready)
        return self

    def unregister_engine(self, name: str):
        with self._lock:
            self._engines.pop(str(name), None)

    def engines(self) -> list[str]:
        with self._lock:
            return sorted(self._engines)

    def render(self) -> str:
        """The /metrics body: base registry samples bare, engine
        registries with an ``engine`` label — merged PER METRIC NAME so
        each name gets exactly one HELP/TYPE group (the text format
        rejects duplicates, which a naive per-registry concatenation
        produced when two engines shared a metric name)."""
        with self._lock:
            engines = dict(self._engines)
        sources = []
        if self.registry is not None:
            sources.append((self.registry, ()))
        for name in sorted(engines):
            sources.append((engines[name][0], (("engine", name),)))
        from .metrics import _escape_help

        # group by FULL (namespaced) metric name: one HELP/TYPE each
        names: dict = {}          # full name -> (bare name, first reg)
        for reg, _ in sources:
            ns = reg.namespace
            for n in reg.names():
                names.setdefault(f"{ns}_{n}" if ns else n, (n, reg))
        lines = []
        for full in sorted(names):
            n, first = names[full]
            m = first.get(n)
            lines.append(f"# HELP {full} {_escape_help(m.doc or n)}")
            lines.append(f"# TYPE {full} {m.kind}")
            for reg, extra in sources:
                if (f"{reg.namespace}_{n}" if reg.namespace else n) == full:
                    lines.extend(reg._render_samples(n, extra))
        return "\n".join(lines) + ("\n" if lines else "")

    def health(self, engine: str | None = None) -> tuple[bool, str]:
        """Aggregate readiness, or — with ``engine`` (round 20, the
        ``/healthz?engine=NAME`` probe) — the named engine's alone: a
        router admits a warmed replica while its peers still warm."""
        with self._lock:
            engines = dict(self._engines)
        if engine is not None:
            if engine not in engines:
                return False, f"unknown engine: {engine}\n"
            _, ready = engines[engine]
            if ready is not None and not ready():
                return False, f"warming: {engine}\n"
            return True, "ready\n"
        if not engines:
            return True, "ok\n"
        warming = sorted(name for name, (_, ready) in engines.items()
                         if ready is not None and not ready())
        if warming:
            return False, "warming: " + ",".join(warming) + "\n"
        return True, "ready\n"

    def close(self):
        """Idempotent under concurrent callers (round-17 satellite): the
        first caller through the flag tears the server down, every later
        or concurrent close() is a no-op — two engines shutting down at
        once must not double-close the socket or race the registry
        teardown."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._httpd.shutdown()
        self._httpd.server_close()
        with self._lock:
            self._engines.clear()
        with _SERVERS_LOCK:
            for p in [p for p, s in _SERVERS.items() if s is self]:
                del _SERVERS[p]


def serve_metrics(port: int, registry=None, host: str = "127.0.0.1"
                  ) -> MetricsServer:
    """Start the endpoint; returns the server (``.port`` is the bound
    port — pass 0 to let the OS pick, handy in tests)."""
    if registry is None:
        from . import default_registry

        registry = default_registry()
    return MetricsServer(port, registry, host=host)


#: per-port shared servers (the FLAGS_obs_http_port path): engines in
#: one process scrape through ONE endpoint instead of fighting the bind
_SERVERS_LOCK = lockdep.make_lock("obs.http._SERVERS_LOCK", hot=True)
_SERVERS: dict = {}           # guarded-by: _SERVERS_LOCK


def shared_server(port: int, host: str = "127.0.0.1") -> MetricsServer:
    """Get-or-create the process-shared server for ``port`` (base body =
    the process-default registry; engines register on top). Port 0 means
    "any free port" and always creates a fresh server — only resolved
    ports are shared."""
    with _SERVERS_LOCK:
        srv = _SERVERS.get(int(port)) if int(port) != 0 else None
        if srv is None:
            from . import default_registry

            srv = MetricsServer(port, default_registry(), host=host)
            _SERVERS[srv.port] = srv
        return srv
