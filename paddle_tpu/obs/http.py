"""Optional stdlib /metrics endpoint for the serving path.

``serve_metrics(port, registry)`` starts a daemon-thread
``http.server`` exposing:

  * ``/metrics``  — Prometheus text exposition of the registry
  * ``/healthz``  — 200 "ok" (load-balancer liveness)

No dependencies beyond the stdlib (the container bakes no prometheus
client), one thread, read-only — good enough for a scrape target, not a
general web server. The ServingEngine starts one automatically when
``FLAGS_obs_http_port`` > 0.
"""
from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class MetricsServer:
    def __init__(self, port: int, registry, host: str = "127.0.0.1"):
        reg = registry

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib API name)
                if self.path.split("?")[0] == "/metrics":
                    body = reg.render_prometheus().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                elif self.path.split("?")[0] == "/healthz":
                    body = b"ok\n"
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                else:
                    body = b"not found\n"
                    self.send_response(404)
                    self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # silence per-request stderr spam
                return None

        self._httpd = ThreadingHTTPServer((host, int(port)), Handler)
        self.port = self._httpd.server_address[1]  # resolved (port=0 OK)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name=f"obs-metrics-:{self.port}",
                                        daemon=True)
        self._thread.start()

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()


def serve_metrics(port: int, registry=None, host: str = "127.0.0.1"
                  ) -> MetricsServer:
    """Start the endpoint; returns the server (``.port`` is the bound
    port — pass 0 to let the OS pick, handy in tests)."""
    if registry is None:
        from . import default_registry

        registry = default_registry()
    return MetricsServer(port, registry, host=host)
