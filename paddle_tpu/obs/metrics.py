"""Metrics registry — the runtime telemetry substrate (ROADMAP items 2/5
report through this: p95-TTFT-under-SLO, cache-hit stats, compile counts).

Reference parity: the role paddle.profiler + VisualDL scalar logging play
in the reference stack, rebuilt as a serving-grade registry: Prometheus
data model (Counter / Gauge / Histogram with labels), two exporters
(JSONL event log via ``FLAGS_obs_log_path``; Prometheus text exposition
via ``render_prometheus()`` + an optional stdlib-http ``/metrics``
endpoint in obs/http.py), and a hot path cheap enough to live inside the
serving engine's per-tick loop.

Hot-path design (the 2%-overhead acceptance bar, PERF.md round 11):

* NO locks on observe/inc — a sample is one dict lookup (pre-resolved by
  ``labels()`` at setup time into a child handle) plus 2-4 Python
  attribute updates. Under the GIL a lost increment race is the worst
  case, and metric writers tolerate last-write-wins the way every
  statsd-style client does; correctness-critical counting (tokens
  emitted, requests completed) happens in the scheduler's own state, the
  registry only mirrors it.
* Histograms keep BOTH forms: fixed cumulative buckets (Prometheus ``le``
  semantics, O(#buckets) per observe via one bisect) and an exact-sample
  ring (capped) so small populations (per-request TTFTs) quote exact
  quantiles while unbounded ones (per-step decode wall) degrade to bucket
  interpolation instead of growing without bound.
* Label cardinality is CAPPED per metric (default 64 label sets): past
  the cap new label sets collapse into one reserved ``__overflow__``
  child and ``dropped_label_sets`` counts them — a runaway label (e.g.
  request id as a label, the classic cardinality bomb) degrades the
  metric, never host memory.
"""
from __future__ import annotations

import bisect
import json
import math
import threading
import time

from ..core import lockdep

#: reserved child absorbing label sets past the cardinality cap
OVERFLOW = "__overflow__"

#: default per-metric label-set cap (the cardinality bomb guard)
DEFAULT_LABEL_CAP = 64

#: default fixed bucket ladder: latency-flavored seconds, 100us..~2min —
#: wide enough for TTFT (ms..s) and compile walls (s..min) alike
DEFAULT_BUCKETS = (
    1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2, 5e-2,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)

#: exact-sample ring size for histogram quantiles (beyond: interpolation)
DEFAULT_EXACT_CAP = 4096


def _label_key(labelnames, labelvalues):
    return tuple(str(v) for v in labelvalues)


class _Metric:
    """Shared parent bookkeeping: named children per label set, capped."""

    kind = "untyped"

    def __init__(self, name: str, doc: str, labelnames=(),
                 label_cap: int = DEFAULT_LABEL_CAP):
        self.name = name
        self.doc = doc
        self.labelnames = tuple(labelnames)
        self.label_cap = int(label_cap)
        # setup-time only (labels() at instrument-site creation); the
        # observe/inc hot path never takes it
        self._setup_lock = lockdep.make_lock("obs.Metric._setup_lock",
                                             hot=True)
        self.dropped_label_sets = 0      # guarded-by: _setup_lock
        # mutations guarded; the labels() fast path reads lock-free (a
        # memoized child handle — last-write-wins is the documented
        # statsd-style contract)
        self._children: dict[tuple, _Metric] = {}  # guarded-by: _setup_lock

    def labels(self, *labelvalues, **labelkv):
        """Resolve (and memoize) the child for one label set. Call this at
        instrumentation-SETUP time and keep the handle — the per-sample
        path is then just child.inc()/observe()."""
        if labelkv:
            if labelvalues:
                raise ValueError("pass labels positionally or by name, "
                                 "not both")
            try:
                labelvalues = tuple(labelkv[n] for n in self.labelnames)
            except KeyError as e:
                raise ValueError(
                    f"metric {self.name} has labels {self.labelnames}, "
                    f"got {sorted(labelkv)}") from e
        if len(labelvalues) != len(self.labelnames):
            raise ValueError(
                f"metric {self.name} expects {len(self.labelnames)} label "
                f"value(s) {self.labelnames}, got {labelvalues!r}")
        key = _label_key(self.labelnames, labelvalues)
        child = self._children.get(key)
        if child is not None:
            return child
        with self._setup_lock:
            child = self._children.get(key)
            if child is not None:
                return child
            if len(self._children) >= self.label_cap:
                self.dropped_label_sets += 1
                key = (OVERFLOW,) * len(self.labelnames)
                child = self._children.get(key)
                if child is not None:
                    return child
            child = self._make_child()
            self._children[key] = child
            return child

    def _make_child(self):
        raise NotImplementedError

    # -- introspection
    def samples(self):
        """[(labelvalues_tuple, child)] — parent included when unlabeled."""
        if not self.labelnames:
            return [((), self)]
        return sorted(self._children.items())


class Counter(_Metric):
    """Monotonically increasing count. ``inc()`` is the whole hot path."""

    kind = "counter"

    def __init__(self, name, doc, labelnames=(), label_cap=DEFAULT_LABEL_CAP):
        super().__init__(name, doc, labelnames, label_cap)
        self.value = 0.0

    def _make_child(self):
        return Counter(self.name, self.doc)

    def inc(self, n=1.0):
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        self.value += n


class Gauge(_Metric):
    """Point-in-time value (queue depth, pool occupancy)."""

    kind = "gauge"

    def __init__(self, name, doc, labelnames=(), label_cap=DEFAULT_LABEL_CAP):
        super().__init__(name, doc, labelnames, label_cap)
        self.value = 0.0

    def _make_child(self):
        return Gauge(self.name, self.doc)

    def set(self, v):
        self.value = float(v)

    def inc(self, n=1.0):
        self.value += n

    def dec(self, n=1.0):
        self.value -= n


class Histogram(_Metric):
    """Fixed cumulative buckets + exact-sample ring.

    ``quantile(q)`` is exact while the population fits ``exact_cap``
    (TTFT-per-request scale), linear-interpolated from the bucket counts
    past it (per-step scale) — both modes are covered against each other
    in tests/test_obs.py."""

    kind = "histogram"

    def __init__(self, name, doc, labelnames=(), buckets=DEFAULT_BUCKETS,
                 exact_cap=DEFAULT_EXACT_CAP, label_cap=DEFAULT_LABEL_CAP):
        super().__init__(name, doc, labelnames, label_cap)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self.exact_cap = int(exact_cap)
        self.bucket_counts = [0] * (len(self.buckets) + 1)  # +inf tail
        self.count = 0
        self.sum = 0.0
        self._exact: list[float] = []
        self._exact_i = 0  # ring cursor once the cap is hit

    def _make_child(self):
        return Histogram(self.name, self.doc, buckets=self.buckets,
                         exact_cap=self.exact_cap)

    def observe(self, v):
        v = float(v)
        self.count += 1
        self.sum += v
        self.bucket_counts[bisect.bisect_left(self.buckets, v)] += 1
        if len(self._exact) < self.exact_cap:
            self._exact.append(v)
        else:
            self._exact[self._exact_i] = v
            self._exact_i = (self._exact_i + 1) % self.exact_cap

    @property
    def exact(self) -> bool:
        """True while quantiles come from the full sample population."""
        return self.count <= self.exact_cap

    def quantile(self, q: float) -> float:
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile {q} outside [0, 1]")
        if self.count == 0:
            return float("nan")
        if self.exact:
            s = sorted(self._exact)
            return s[min(len(s) - 1, int(math.ceil(q * len(s))) - 1)] \
                if q > 0 else s[0]
        # bucket interpolation over cumulative counts (Prometheus
        # histogram_quantile semantics: linear within the hit bucket)
        target = q * self.count
        cum = 0
        lo = 0.0
        for i, c in enumerate(self.bucket_counts):
            if c == 0:
                lo = self.buckets[i] if i < len(self.buckets) else lo
                continue
            if cum + c >= target:
                hi = self.buckets[i] if i < len(self.buckets) \
                    else self.buckets[-1]
                frac = (target - cum) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            cum += c
            lo = self.buckets[i] if i < len(self.buckets) else lo
        return self.buckets[-1]

    def mean(self) -> float:
        return self.sum / self.count if self.count else float("nan")


class Registry:
    """One namespace of metrics. The framework default lives in
    obs/__init__ (``default_registry()``); the serving engine builds its
    own per instance so concurrent engines/tests never share counters."""

    def __init__(self, namespace: str = "paddle_tpu"):
        self.namespace = namespace
        self._lock = lockdep.make_lock("obs.Registry._lock", hot=True)
        self._metrics: dict[str, _Metric] = {}   # guarded-by: _lock

    def _get_or_make(self, cls, name, doc, labelnames, **kw):
        m = self._metrics.get(name)
        if m is None:
            with self._lock:
                m = self._metrics.get(name)
                if m is None:
                    m = cls(name, doc, labelnames, **kw)
                    self._metrics[name] = m
                    return m
        if not isinstance(m, cls) or m.labelnames != tuple(labelnames):
            raise ValueError(
                f"metric {name!r} already registered as {m.kind} with "
                f"labels {m.labelnames}")
        if "buckets" in kw and m.buckets != tuple(sorted(
                float(b) for b in kw["buckets"])):
            raise ValueError(
                f"histogram {name!r} already registered with buckets "
                f"{m.buckets}; a second ladder would silently skew its "
                "interpolated quantiles")
        return m

    def counter(self, name, doc="", labelnames=(), **kw) -> Counter:
        return self._get_or_make(Counter, name, doc, labelnames, **kw)

    def gauge(self, name, doc="", labelnames=(), **kw) -> Gauge:
        return self._get_or_make(Gauge, name, doc, labelnames, **kw)

    def histogram(self, name, doc="", labelnames=(), buckets=DEFAULT_BUCKETS,
                  **kw) -> Histogram:
        return self._get_or_make(Histogram, name, doc, labelnames,
                                 buckets=buckets, **kw)

    def get(self, name) -> _Metric | None:
        return self._metrics.get(name)

    def names(self) -> list[str]:
        return sorted(self._metrics)

    def unregister(self, name):
        # D13 fix (round 17): these mutated the map bare — racing a
        # concurrent _get_or_make's double-checked insert could publish
        # a metric into a dict mid-clear (lost unregister, or a reader's
        # iteration seeing a half-applied reset)
        with self._lock:
            self._metrics.pop(name, None)

    def clear(self):
        with self._lock:
            self._metrics.clear()

    # ------------------------------------------------------------ export
    def to_dict(self) -> dict:
        """Snapshot for --metrics-json consumers / ServingPredictor
        .metrics(): plain JSON-able values, histograms summarized."""
        out = {}
        for name, m in sorted(self._metrics.items()):
            rows = []
            for labelvalues, child in m.samples():
                labels = dict(zip(m.labelnames, labelvalues))
                if m.kind == "histogram":
                    row = {"count": child.count, "sum": child.sum,
                           "mean": (child.mean() if child.count else None),
                           "p50": (child.quantile(0.5) if child.count
                                   else None),
                           "p95": (child.quantile(0.95) if child.count
                                   else None),
                           "p99": (child.quantile(0.99) if child.count
                                   else None),
                           "exact": child.exact}
                else:
                    row = {"value": child.value}
                if labels:
                    row["labels"] = labels
                rows.append(row)
            out[name] = {"kind": m.kind, "doc": m.doc,
                         "dropped_label_sets": m.dropped_label_sets,
                         "samples": rows}
        return out

    def _render_samples(self, name: str, extra_labels=()) -> list:
        """Sample lines (no HELP/TYPE) for one metric, with
        ``extra_labels`` pairs injected — the multi-registry /metrics
        endpoint merges same-named metrics across engine registries this
        way (the text format forbids a second HELP/TYPE group for one
        metric name, so the merge has to happen at the sample level)."""
        m = self._metrics.get(name)
        if m is None:
            return []
        ns = self.namespace
        full = f"{ns}_{name}" if ns else name
        extra = tuple(extra_labels)
        lines = []
        for labelvalues, child in m.samples():
            base = extra + tuple(zip(m.labelnames, labelvalues))
            lab = _fmt_labels(base)
            if m.kind == "histogram":
                cum = 0
                for b, c in zip(child.buckets, child.bucket_counts):
                    cum += c
                    lines.append(
                        f"{full}_bucket{_fmt_labels(base, ('le', _fmt_float(b)))} {cum}")
                lines.append(
                    f"{full}_bucket{_fmt_labels(base, ('le', '+Inf'))} {child.count}")
                lines.append(f"{full}_sum{lab} {_fmt_float(child.sum)}")
                lines.append(f"{full}_count{lab} {child.count}")
            else:
                lines.append(f"{full}{lab} {_fmt_float(child.value)}")
        return lines

    def render_prometheus(self, extra_labels=()) -> str:
        """Prometheus text exposition format 0.0.4 (the /metrics body).
        ``extra_labels`` — ((name, value), ...) — is injected into every
        sample line."""
        ns = self.namespace
        lines = []
        for name, m in sorted(self._metrics.items()):
            full = f"{ns}_{name}" if ns else name
            lines.append(f"# HELP {full} {_escape_help(m.doc or name)}")
            lines.append(f"# TYPE {full} {m.kind}")
            lines.extend(self._render_samples(name, extra_labels))
        return "\n".join(lines) + ("\n" if lines else "")


def _fmt_float(v: float) -> str:
    f = float(v)
    if math.isnan(f):
        return "NaN"           # text-format spec spells the specials
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _fmt_labels(pairs, extra=None):
    parts = [f'{n}="{_escape(v)}"' for n, v in pairs]
    if extra is not None:
        parts.append(f'{extra[0]}="{extra[1]}"')
    return "{" + ",".join(parts) + "}" if parts else ""


def _escape(v: str) -> str:
    """Label-value escaping per the Prometheus text format 0.0.4: inside
    double quotes, backslash, double-quote and line feed must escape (in
    this order — escaping the backslash LAST would re-escape the
    escapes). Pinned fire/no-fire in tests/test_obs.py."""
    return str(v).replace("\\", r"\\").replace('"', r'\"').replace("\n",
                                                                   r"\n")


def _escape_help(v: str) -> str:
    """HELP-line escaping: backslash and line feed only (quotes are legal
    there). A metric doc containing a newline used to tear the
    exposition into an unparseable line — the scrape-side failure mode
    the round-14 satellite pins."""
    return str(v).replace("\\", r"\\").replace("\n", r"\n")


# ----------------------------------------------------------- JSONL export
class _JsonlSink:
    """Append-only JSONL event log at FLAGS_obs_log_path. The file handle
    opens lazily on first event and re-opens when the flag changes (tests
    point it at tmp paths); line-buffered so a crashed process leaves
    whole lines.

    Size-capped rotation (round-14 satellite — the log used to grow
    without bound under a long-lived serving loop): past
    ``FLAGS_obs_log_max_mb`` the file rolls to ``<path>.1`` (older rolls
    shift to ``.2`` .. ``.N``, ``FLAGS_obs_log_backups``; the oldest is
    deleted). Rotation happens BETWEEN records under the sink lock, so a
    rollover can never tear a JSON line — every line in every file of
    the set parses (pinned in tests/test_obs.py)."""

    def __init__(self):
        self._lock = lockdep.make_lock("obs.JsonlSink._lock", hot=True)
        self._fh = None       # guarded-by: _lock
        self._path = None     # guarded-by: _lock
        self._bytes = 0       # guarded-by: _lock

    def _open(self, path):  # requires-lock: _lock
        import os

        self._fh = open(path, "a", buffering=1)
        self._path = path
        try:
            self._bytes = os.path.getsize(path)
        except OSError:
            self._bytes = 0

    def _handle(self):  # requires-lock: _lock
        from ..core.flags import flag

        path = str(flag("FLAGS_obs_log_path") or "")
        if not path:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
                self._path = None
            return None
        if path != self._path:
            if self._fh is not None:
                self._fh.close()
            self._open(path)
        return self._fh

    def _rotate(self):  # requires-lock: _lock
        import os

        from ..core.flags import flag

        backups = max(1, int(flag("FLAGS_obs_log_backups")))
        self._fh.close()
        oldest = f"{self._path}.{backups}"
        if os.path.exists(oldest):
            os.remove(oldest)
        for i in range(backups - 1, 0, -1):
            src = f"{self._path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{self._path}.{i + 1}")
        os.replace(self._path, f"{self._path}.1")
        self._open(self._path)

    def emit(self, kind: str, payload: dict):
        from ..core.flags import flag

        with self._lock:
            fh = self._handle()
            if fh is None:
                return False
            rec = {"t": time.time(), "kind": kind}
            rec.update(payload)
            line = json.dumps(rec) + "\n"
            cap = int(flag("FLAGS_obs_log_max_mb")) * 1024 * 1024
            if cap > 0 and self._bytes and self._bytes + len(line) > cap:
                self._rotate()
                fh = self._fh
            fh.write(line)
            self._bytes += len(line)
            return True


_sink = _JsonlSink()


def log_event(kind: str, **payload) -> bool:
    """One structured event onto the JSONL log (no-op with the flag
    unset). Compile events, admission decisions and logger records all
    funnel through here so one tail -f shows the runtime's story."""
    return _sink.emit(kind, payload)


def dump_registry(registry: Registry, path: str | None = None) -> bool:
    """Write a full registry snapshot as one JSONL `metrics` event (to
    `path` when given, else the flag sink)."""
    if path is not None:
        with open(path, "a", buffering=1) as fh:
            rec = {"t": time.time(), "kind": "metrics",
                   "metrics": registry.to_dict()}
            fh.write(json.dumps(rec) + "\n")
        return True
    return log_event("metrics", metrics=registry.to_dict())
