"""Compile watchdog — every compile/retrace in the process becomes an
event, and pathological compile behavior becomes a lint Finding.

The round-10 serving push shipped (and satellite-fixed) the classic
failure this module exists to catch: ``_GenSpec`` keyed one compiled
program per exact ``max_new_tokens``, so a stream of varied request
lengths silently compiled O(#distinct lengths) programs — found by
accident. Now every compile site reports here:

  * ``core/dispatch.py``    eager executable-cache misses  (site "eager")
  * ``jit/api.py``          to_static specializations      (site "to_static")
  * ``text/generation.py``  static-engine programs         (site "generate")
  * ``inference/engine.py`` serving prefill/decode buckets (site
                            "serving.prefill" / "serving.decode")

Each event carries the program key, its bucket, wall time, donation
summary and jaxpr size (eqn count, when the site has a cheap jaxpr), and
increments ``compiles_total{site=...}`` / ``compile_seconds`` in the
default registry plus the JSONL log. ``audit_recompiles()`` turns the
event history into ``analysis.Finding``s:

  * RECOMPILE STORM — one (site, group) accumulated more than
    ``FLAGS_obs_compile_storm_threshold`` distinct program keys, or any
    single key compiled more than once (an executable cache losing
    entries mid-run). A generation-length ladder that buckets compiles
    O(log L) keys and stays under the threshold; exact-length keying
    blows past it — the fire/no-fire pair in tests/test_obs.py proves
    both directions.
  * POST-WARMUP COMPILE — any compile recorded after a ServingEngine
    declared warmup complete (``finish_warmup()``): a steady-state
    serving tick must never trace.

Both are warnings, so they fail ``tools/graft_lint.py`` (the ``obs``
smoke) exactly like dtype regressions do.
"""
from __future__ import annotations

import time
from collections import deque

from ..core.flags import flag

#: bounded event history (a process compiling >4096 programs has worse
#: problems than a truncated audit window). Appends/counter bumps rely
#: on the GIL like the metrics hot path — no lock.
_EVENT_CAP = 4096
# thread-safe: GIL-atomic bounded-deque appends; readers take list()
# snapshots and clear_events is a test/bench barrier run with no recorder
_events: deque = deque(maxlen=_EVENT_CAP)

#: compiles tagged warm=True by their site (the serving engine tags any
#: compile after its finish_warmup() barrier) — steady-state retraces.
# thread-safe: GIL-atomic int bump mirroring post_warmup_compiles_total;
# the per-event warm flag drives the audit, a lost bump is a lost metric
_post_warmup_total = 0


class CompileEvent:
    """One compile/retrace, as recorded at the site."""

    __slots__ = ("site", "group", "key", "bucket", "wall_s", "jaxpr_eqns",
                 "donated", "warm", "cost", "t")

    def __init__(self, site, group, key, bucket=None, wall_s=0.0,
                 jaxpr_eqns=None, donated=None, warm=False, cost=None):
        self.site = str(site)
        self.group = str(group)      # program FAMILY (fn/model), storms
        self.key = str(key)          # exact specialization key
        self.bucket = bucket
        self.wall_s = float(wall_s)
        self.jaxpr_eqns = jaxpr_eqns
        self.donated = donated
        self.warm = bool(warm)
        # round 14: XLA cost_analysis summary captured at AOT sites
        # (obs/costs.py extract_cost dict: flops / bytes_accessed / HBM
        # footprint) — the compile event carries WHAT was compiled, the
        # cost ledger carries how it performs over time
        self.cost = cost
        self.t = time.time()

    def to_dict(self) -> dict:
        return {"site": self.site, "group": self.group, "key": self.key,
                "bucket": self.bucket, "wall_s": round(self.wall_s, 4),
                "jaxpr_eqns": self.jaxpr_eqns, "donated": self.donated,
                "warm": self.warm, "cost": self.cost, "t": self.t}


def record_compile(site: str, group: str, key: str, bucket=None,
                   wall_s: float = 0.0, jaxpr_eqns=None, donated=None,
                   warm: bool = False, cost=None) -> CompileEvent:
    """Record one compile. Cheap (an append + two counter bumps) and only
    reached on cache MISSES, so the steady-state hot paths never pay it."""
    from . import default_registry, metrics

    ev = CompileEvent(site, group, key, bucket=bucket, wall_s=wall_s,
                      jaxpr_eqns=jaxpr_eqns, donated=donated, warm=warm,
                      cost=cost)
    # D14 blocking-under-lock probe: record_compile runs in the same
    # frame as the compile it reports, so any hot (scrape-path) lock
    # held here was held across the compile wall
    from ..core import lockdep

    lockdep.note_blocking("compile", site)
    _events.append(ev)
    reg = default_registry()
    reg.counter("compiles_total", "compiled programs (any site)",
                ("site",)).labels(site).inc()
    reg.counter("compile_seconds", "wall seconds spent compiling/tracing",
                ("site",)).labels(site).inc(max(ev.wall_s, 0.0))
    if warm:
        global _post_warmup_total
        _post_warmup_total += 1
        reg.counter("post_warmup_compiles_total",
                    "compiles recorded after a serving warmup barrier",
                    ("site",)).labels(site).inc()
    metrics.log_event("compile", **ev.to_dict())
    # training goodput (round 16): a compile wall paid while a fit is
    # instrumented is non-productive training time (no-op otherwise)
    from .goodput import note_compile

    note_compile(ev.wall_s)
    return ev


def compile_events(site: str | None = None) -> list[CompileEvent]:
    evs = list(_events)
    if site is not None:
        evs = [e for e in evs if e.site == site]
    return evs


def compile_counts() -> dict:
    """{site: count} over the current event window — what bench rungs and
    --metrics-json attach to their rows."""
    out: dict[str, int] = {}
    for e in _events:
        out[e.site] = out.get(e.site, 0) + 1
    return out


def post_warmup_compiles() -> int:
    return _post_warmup_total


def clear_events():
    """Reset the window (tests; bench rungs call it so each row's counts
    are the rung's own)."""
    global _post_warmup_total
    _events.clear()
    _post_warmup_total = 0
    _ckpt_events.clear()


# ------------------------------------------------------- ckpt watchdog
#: checkpoint save events (round 12) — same bounded-window design as the
#: compile events; ckpt/core.py reports every save outcome here, including
#: from the AsyncCheckpointer commit thread.
# thread-safe: GIL-atomic bounded-deque appends; audits read a snapshot
_ckpt_events: deque = deque(maxlen=_EVENT_CAP)


def record_ckpt_save(step: int, wall_s: float, nbytes: int, result: str,
                     attempts: int = 1) -> dict:
    """One checkpoint-save outcome (ok / retry_ok / error).  Counters
    live in the default registry (``ckpt_saves_total{result}`` etc.,
    recorded by ckpt/core); this window feeds ``audit_ckpt_stalls``."""
    from . import metrics

    ev = {"step": int(step), "wall_s": float(wall_s),
          "bytes": int(nbytes), "result": str(result),
          "attempts": int(attempts), "t": time.time()}
    _ckpt_events.append(ev)
    metrics.log_event("ckpt_save", **ev)
    # ckpt-stall postmortem (round 16): a save blowing its wall budget
    # (or failing outright) while a training flight recorder is active
    # auto-dumps the last N step timelines — the trace of the stall
    if wall_s > float(flag("FLAGS_ckpt_stall_seconds")) \
            or result == "error":
        from .train_flight import current as _tf_current

        rec = _tf_current()
        if rec is not None:
            rec.anomaly("ckpt_stall")
    return ev


def ckpt_save_events() -> list:
    return list(_ckpt_events)


def audit_ckpt_stalls(events=None, threshold: float | None = None,
                      loc: str = "obs/ckpt") -> list:
    """Checkpoint-save health Findings over the event window: a save
    exceeding ``FLAGS_ckpt_stall_seconds`` wall (the checkpoint path is
    blocking training far longer than budgeted) or a save that exhausted
    its retries is a warning; a healthy window is a note.  Gated by the
    graft_lint ``ckpt`` smoke exactly like recompile storms."""
    from ..analysis import Finding

    if events is None:
        events = ckpt_save_events()
    if threshold is None:
        threshold = float(flag("FLAGS_ckpt_stall_seconds"))
    findings: list = []
    stalls = [e for e in events if e["wall_s"] > threshold]
    failures = [e for e in events if e["result"] == "error"]
    if stalls:
        worst = max(e["wall_s"] for e in stalls)
        findings.append(Finding(
            "ckpt-stall", "warning", loc,
            f"{len(stalls)} checkpoint save(s) exceeded "
            f"FLAGS_ckpt_stall_seconds={threshold:g} (worst {worst:.1f}s) "
            "— saves are blocking training; shrink the state, raise "
            "max_in_flight, or fix the filesystem",
            data={"threshold": threshold, "stalls": stalls[:8]}))
    if failures:
        findings.append(Finding(
            "ckpt-stall", "warning", loc,
            f"{len(failures)} checkpoint save(s) FAILED after retries — "
            "a preemption now loses everything since the last good "
            "checkpoint",
            data={"failures": failures[:8]}))
    if not stalls and not failures:
        findings.append(Finding(
            "ckpt-stall", "note", loc,
            f"{len(events)} checkpoint save(s), none stalled past "
            f"{threshold:g}s, none failed",
            data={"count": len(events), "threshold": threshold}))
    return findings


def jaxpr_size(jaxpr) -> int:
    """Eqn count of a ClosedJaxpr incl. sub-jaxprs — the 'program size'
    a compile event records when the site has a jaxpr in hand."""
    from ..analysis import iter_eqns

    return sum(1 for _ in iter_eqns(jaxpr))


# ---------------------------------------------------------------- audit
def audit_recompiles(events=None, threshold: int | None = None,
                     loc: str = "obs/watchdog") -> list:
    """Recompile-storm + post-warmup-compile Findings over the event
    window. Notes for healthy sites (visible in --json), warnings for the
    two failure shapes — the graft_lint ``obs`` smoke gates on these."""
    from ..analysis import Finding

    if events is None:
        events = compile_events()
    if threshold is None:
        threshold = int(flag("FLAGS_obs_compile_storm_threshold"))
    findings: list = []

    groups: dict[tuple, list] = {}
    for e in events:
        groups.setdefault((e.site, e.group), []).append(e)
    for (site, group), evs in sorted(groups.items()):
        keys: dict[str, int] = {}
        for e in evs:
            keys[e.key] = keys.get(e.key, 0) + 1
        distinct = len(keys)
        repeats = {k: n for k, n in keys.items() if n > 1}
        # the eager cache specializes per (statics, diff-mask) BY DESIGN —
        # distinct-key growth there is normal; only a re-BUILD of the
        # same key (eviction thrash) is pathological
        if distinct > threshold and site != "eager":
            findings.append(Finding(
                "recompile-storm", "warning", f"{loc}:{site}/{group}",
                f"{distinct} distinct programs compiled for one family "
                f"(threshold {threshold}) — lengths/shapes are not "
                f"bucketing (the round-10 exact-max_new_tokens failure "
                f"shape); keys: "
                f"{sorted(keys)[:6]}{'...' if distinct > 6 else ''}",
                data={"site": site, "group": group, "distinct": distinct,
                      "threshold": threshold,
                      "total_compiles": len(evs)}))
        elif repeats:
            worst = max(repeats.values())
            findings.append(Finding(
                "recompile-storm", "warning", f"{loc}:{site}/{group}",
                f"same program key compiled {worst}x (cache thrash: the "
                f"executable cache is losing entries mid-run); "
                f"{len(repeats)} key(s) affected",
                data={"site": site, "group": group, "repeats": repeats,
                      "total_compiles": len(evs)}))
        else:
            findings.append(Finding(
                "recompile-storm", "note", f"{loc}:{site}/{group}",
                f"{distinct} program(s), no retraces",
                data={"site": site, "group": group, "distinct": distinct}))

    warm = [e for e in events if e.warm]
    if warm:
        sites = sorted({f"{e.site}/{e.group}" for e in warm})
        findings.append(Finding(
            "post-warmup-compile", "warning", loc,
            f"{len(warm)} compile(s) recorded AFTER serving warmup "
            f"completed — steady-state ticks are retracing ({sites}); "
            f"every serving bucket must compile during warmup",
            data={"count": len(warm),
                  "events": [e.to_dict() for e in warm[:8]]}))
    return findings
