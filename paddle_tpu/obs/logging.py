"""Structured runtime logger — FLAGS_log_level finally drives something.

Reference parity: glog's VLOG(n) + LOG(WARNING) as used throughout the
reference C++ (the registry defines FLAGS_log_level but, before this
module, nothing consumed it — messages came from scattered ``print`` and
``warnings.warn`` calls). Design:

  * module-scoped loggers: ``log = obs.get_logger(__name__)`` — every
    record carries the module tag, so grep/JSONL filtering works;
  * VLOG semantics: ``log.vlog(2, ...)`` prints only when
    ``FLAGS_log_level >= 2``; ``info`` is vlog(1); ``warning``/``error``
    always print (to stderr, like glog);
  * RATE LIMITING per (logger, message key): a repeating message (the
    serving engine's admission-blocked path can fire every tick) prints
    at most once per window (default 5s) and reports how many repeats
    were suppressed when it next prints — so a hot loop can log
    unconditionally and the terminal stays readable;
  * every record that passes the level check also lands on the JSONL
    event log (FLAGS_obs_log_path) unrated — the file is for machines;
  * ``warning(..., also_warn=True)`` additionally raises a Python
    ``warnings.warn`` so call sites migrating from warnings keep their
    contract with ``warnings.catch_warnings`` consumers (the dy2static
    fallback tests assert on those).
"""
from __future__ import annotations

import sys
import time
import warnings as _warnings

from ..core import lockdep
from ..core.flags import flag
from . import metrics as _metrics

#: default suppression window for repeated messages (seconds)
RATE_WINDOW_S = 5.0

_lock = lockdep.make_lock("obs.logging._lock", hot=True)
_loggers: dict[str, "ObsLogger"] = {}   # guarded-by: _lock


def get_logger(module: str) -> "ObsLogger":
    lg = _loggers.get(module)
    if lg is None:
        with _lock:
            lg = _loggers.get(module)
            if lg is None:
                lg = ObsLogger(module)
                _loggers[module] = lg
    return lg


class ObsLogger:
    __slots__ = ("module", "_last", "_suppressed", "suppressed_total")

    def __init__(self, module: str):
        self.module = module.removeprefix("paddle_tpu.")
        self._last: dict[str, float] = {}    # message key -> last print t
        self._suppressed: dict[str, int] = {}
        self.suppressed_total = 0

    # ------------------------------------------------------------- core
    def _emit(self, severity: str, msg: str, key: str | None,
              rate_s: float, fields: dict):
        now = time.perf_counter()
        k = key if key is not None else msg[:80]
        last = self._last.get(k)
        if last is not None and rate_s > 0 and now - last < rate_s:
            self._suppressed[k] = self._suppressed.get(k, 0) + 1
            self.suppressed_total += 1
            # the JSONL sink still sees every record (machines don't
            # need rate limiting; the flag gates the file entirely)
            _metrics.log_event("log", severity=severity,
                               module=self.module, msg=msg,
                               suppressed=True, **fields)
            return False
        self._last[k] = now
        n_sup = self._suppressed.pop(k, 0)
        tail = f" [{n_sup} similar suppressed]" if n_sup else ""
        extra = "".join(f" {k2}={v!r}" for k2, v in fields.items())
        print(f"[paddle_tpu:{self.module}] {severity.upper()}: "
              f"{msg}{extra}{tail}", file=sys.stderr)
        _metrics.log_event("log", severity=severity, module=self.module,
                           msg=msg, **fields)
        return True

    # -------------------------------------------------------------- API
    def vlog(self, level: int, msg: str, key: str | None = None,
             rate_s: float = RATE_WINDOW_S, **fields) -> bool:
        """Print when FLAGS_log_level >= level; returns whether it
        printed (False: below level or rate-limited)."""
        if int(flag("FLAGS_log_level")) < level:
            return False
        return self._emit(f"v{level}", msg, key, rate_s, fields)

    def info(self, msg: str, **kw) -> bool:
        return self.vlog(1, msg, **kw)

    def warning(self, msg: str, key: str | None = None,
                rate_s: float = RATE_WINDOW_S, also_warn: bool = False,
                stacklevel: int = 2, **fields) -> bool:
        """Always eligible (no level gate). ``also_warn=True`` keeps the
        Python-warnings contract for migrated call sites — the structured
        record is the log of record, the warning is the compat surface."""
        out = self._emit("warning", msg, key, rate_s, fields)
        if also_warn:
            _warnings.warn(msg, stacklevel=stacklevel + 1)
        return out

    def error(self, msg: str, key: str | None = None, rate_s: float = 0.0,
              **fields) -> bool:
        """Errors never rate-limit by default."""
        return self._emit("error", msg, key, rate_s, fields)

    def reset(self):
        self._last.clear()
        self._suppressed.clear()
        self.suppressed_total = 0
