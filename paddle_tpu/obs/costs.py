"""Compiled-program cost attribution — the ledger that turns "~103 GB/s
roofline" from a hand-computed PERF.md footnote into continuously
measured data.

Every AOT compile site (the serving engine's bucket programs, the static
generation engine, `to_static` under FLAGS_jit_debug_program) hands its
compiled executable here; XLA's own `cost_analysis()` /
`memory_analysis()` give flops, bytes accessed and the HBM footprint
(argument/output/temp bytes) **for free** — the analysis rides the
executable object, no extra trace or compile is paid. The eager dispatch
cache registers its entries too (count + key only: per-op executables
lower lazily inside jax.jit, forcing an analysis there would cost one
extra compile per op — by design the ledger's cost rows are
program-scale, not op-scale).

Combining the static bytes with measured wall time per execution yields
the roofline story per program:

    achieved GB/s = bytes_accessed * executions / exec_wall
    roofline_utilization{program} = achieved / peak     (obs gauge)

`tools/roofline_report.py` prints the table; bench serving/decode rungs
attach the same numbers to their rows; and **analysis D8**
(`audit_cost_regressions`) compares each program's bytes-accessed
against a committed baseline (`tools/cost_baseline.json`) — a program
whose memory traffic quietly grew past FLAGS_obs_cost_regress_pct fails
`tools/graft_lint.py` exactly like a dtype regression, which is how a
"minor refactor" that un-fuses a decode step gets caught before a
capture run does.

Thread-safety follows obs/watchdog.py: appends and counter bumps rely on
the GIL; compile sites are cold paths, `observe_wall` is a dict lookup
plus a few float ops per program invocation (ticks, not tokens).
"""
from __future__ import annotations

import json
import time

from ..core.flags import flag

#: per-backend peak-HBM-bandwidth defaults (GB/s) when FLAGS_obs_peak_gbps
#: is 0: the axon-tunnel TPU measured ~103 GB/s effective (PERF.md round
#: 4 roofline); off-chip hosts get a nominal DDR-class figure — their
#: utilization numbers are smoke-test plumbing, not quotable
PEAK_GBPS_DEFAULTS = {"tpu": 103.0}
PEAK_GBPS_FALLBACK = 25.0

#: roofline gauges get a wider label cap than the default 64: a serving
#: ladder (prefill x chunk x decode buckets) legitimately exceeds it
_GAUGE_LABEL_CAP = 256


def peak_gbps() -> float:
    v = float(flag("FLAGS_obs_peak_gbps"))
    if v > 0:
        return v
    from .trace import _backend

    return PEAK_GBPS_DEFAULTS.get(_backend(), PEAK_GBPS_FALLBACK)


def extract_cost(compiled) -> dict | None:
    """flops / bytes-accessed / HBM-footprint dict from a jax AOT
    ``Compiled`` object, or None when the backend exposes neither
    analysis. cost_analysis() returns a list of per-partition dicts on
    this jax; single-device programs have exactly one."""
    out: dict = {}
    try:
        ca = compiled.cost_analysis()
    except Exception:
        ca = None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if isinstance(ca, dict):
        out["flops"] = float(ca.get("flops", 0.0))
        out["bytes_accessed"] = float(ca.get("bytes accessed", 0.0))
    try:
        ma = compiled.memory_analysis()
    except Exception:
        ma = None
    if ma is not None:
        arg = int(getattr(ma, "argument_size_in_bytes", 0) or 0)
        outb = int(getattr(ma, "output_size_in_bytes", 0) or 0)
        tmp = int(getattr(ma, "temp_size_in_bytes", 0) or 0)
        ali = int(getattr(ma, "alias_size_in_bytes", 0) or 0)
        out["arg_bytes"] = arg
        out["out_bytes"] = outb
        out["temp_bytes"] = tmp
        # donated (aliased) outputs reuse argument HBM — don't count twice
        out["peak_hbm_bytes"] = arg + max(outb - ali, 0) + tmp
    return out or None


class ProgramCost:
    """One compiled program's ledger row: static XLA costs + measured
    execution walls."""

    __slots__ = ("program", "site", "group", "key", "bucket", "flops",
                 "bytes_accessed", "arg_bytes", "out_bytes", "temp_bytes",
                 "peak_hbm_bytes", "collective_bytes", "compile_wall_s",
                 "analyzed", "exec_count", "exec_wall_s", "last_util", "t",
                 "_gauge")

    def __init__(self, program, site, group, key, bucket=None,
                 compile_wall_s=0.0, cost=None, collective_bytes=0):
        self.program = program      # stable id: "site|key"
        self.site = site
        self.group = group
        self.key = key
        self.bucket = bucket
        self.compile_wall_s = float(compile_wall_s)
        cost = cost or {}
        self.analyzed = bool(cost)
        self.flops = float(cost.get("flops", 0.0))
        self.bytes_accessed = float(cost.get("bytes_accessed", 0.0))
        self.arg_bytes = int(cost.get("arg_bytes", 0))
        self.out_bytes = int(cost.get("out_bytes", 0))
        self.temp_bytes = int(cost.get("temp_bytes", 0))
        self.peak_hbm_bytes = int(cost.get("peak_hbm_bytes", 0))
        # per-device collective byte volume of the program's jaxpr-level
        # collectives (analysis D10, jaxpr_collective_bytes) — the SPMD
        # twin of bytes_accessed: HBM traffic vs fabric traffic
        self.collective_bytes = int(collective_bytes or 0)
        self.exec_count = 0
        self.exec_wall_s = 0.0
        self.last_util = None
        self.t = time.time()
        self._gauge = None          # resolved roofline gauge handle

    # ------------------------------------------------------ measurement
    def observe(self, wall_s: float):
        """One measured execution of this program. Updates the rolling
        achieved-bandwidth numbers and the roofline_utilization{program}
        gauge in the default registry."""
        self.exec_count += 1
        self.exec_wall_s += float(wall_s)
        if not self.analyzed or wall_s <= 0.0:
            return None
        util = self.bytes_accessed / (wall_s * peak_gbps() * 1e9)
        self.last_util = util
        if self._gauge is None:
            from . import default_registry

            self._gauge = default_registry().gauge(
                "roofline_utilization",
                "achieved HBM bandwidth of one compiled program over the "
                "device roofline (bytes_accessed from XLA cost_analysis / "
                "measured wall / FLAGS_obs_peak_gbps)",
                ("program",), label_cap=_GAUGE_LABEL_CAP).labels(
                    self.program)
        self._gauge.set(util)
        return util

    def achieved_gbps(self) -> float | None:
        """Mean achieved bandwidth over every measured execution."""
        if not (self.analyzed and self.exec_count and self.exec_wall_s > 0):
            return None
        return self.bytes_accessed * self.exec_count / self.exec_wall_s / 1e9

    def utilization(self) -> float | None:
        g = self.achieved_gbps()
        return None if g is None else g / peak_gbps()

    def predicted(self) -> tuple[float | None, float | None]:
        """(predicted_step_ms, collective_time_ms) for this program from
        the static cost model (analysis/costmodel.py): roofline
        max(compute, HBM) at the obs peaks plus the program's D10
        collective volume billed at the ICI line rate. None when XLA
        never analyzed the executable."""
        if not self.analyzed:
            return None, None
        from .goodput import peak_tflops

        coll_ms = 0.0
        if self.collective_bytes:
            coll_ms = self.collective_bytes \
                / (float(flag("FLAGS_analysis_ici_gbps")) * 1e9) * 1e3
        compute_ms = self.flops / (peak_tflops() * 1e12) * 1e3
        hbm_ms = self.bytes_accessed / (peak_gbps() * 1e9) * 1e3
        return max(compute_ms, hbm_ms) + coll_ms, coll_ms

    def to_dict(self) -> dict:
        g = self.achieved_gbps()
        pred_ms, coll_ms = self.predicted()
        return {"program": self.program, "site": self.site,
                "group": self.group, "key": self.key, "bucket": self.bucket,
                "analyzed": self.analyzed, "flops": self.flops,
                "bytes_accessed": self.bytes_accessed,
                "arg_bytes": self.arg_bytes, "out_bytes": self.out_bytes,
                "temp_bytes": self.temp_bytes,
                "peak_hbm_bytes": self.peak_hbm_bytes,
                "collective_bytes": self.collective_bytes,
                "predicted_step_ms": (None if pred_ms is None
                                      else round(pred_ms, 4)),
                "collective_time_ms": (None if coll_ms is None
                                       else round(coll_ms, 4)),
                "compile_wall_s": round(self.compile_wall_s, 4),
                "exec_count": self.exec_count,
                "exec_wall_s": round(self.exec_wall_s, 6),
                "achieved_gbps": None if g is None else round(g, 3),
                "roofline_utilization": (None if g is None
                                         else round(g / peak_gbps(), 4))}


#: program id -> ProgramCost; process-global like the compile-event
#: window (executables themselves are shared across engine instances)
_ledger: dict[str, ProgramCost] = {}

#: the eager dispatch cache registers count-only rows (its per-op
#: executables lower lazily; forcing an analysis would cost one compile
#: per op) — cap them so a shape-churning eager workload can't grow the
#: ledger without bound. Dropped registrations are counted.
_EAGER_LEDGER_CAP = 2048
eager_rows_dropped = 0
_site_counts: dict[str, int] = {}


def record_program(site: str, group: str, key: str, compiled=None,
                   wall_s: float = 0.0, bucket=None,
                   collective_bytes=0) -> ProgramCost:
    """Register one compiled program in the ledger (idempotent per
    program id — a cleared event mirror re-recording an already-compiled
    executable keeps the original analysis). Returns the entry; the
    caller attaches ``entry.observe(wall)`` per execution.
    `collective_bytes` carries the program's jaxpr-level collective
    volume (analysis.jaxpr_collective_bytes) next to bytes-accessed."""
    pid = f"{site}|{key}"
    entry = _ledger.get(pid)
    if entry is not None:
        if collective_bytes and not entry.collective_bytes:
            entry.collective_bytes = int(collective_bytes)
        return entry
    if site == "eager" and compiled is None \
            and _site_counts.get("eager", 0) >= _EAGER_LEDGER_CAP:
        global eager_rows_dropped

        eager_rows_dropped += 1
        return ProgramCost(pid, site, group, key, bucket=bucket,
                           compile_wall_s=wall_s, cost=None)
    cost = None
    if compiled is not None and flag("FLAGS_obs_cost_capture"):
        cost = extract_cost(compiled)
    entry = ProgramCost(pid, site, group, key, bucket=bucket,
                        compile_wall_s=wall_s, cost=cost,
                        collective_bytes=collective_bytes)
    _ledger[pid] = entry
    _site_counts[site] = _site_counts.get(site, 0) + 1
    from . import metrics

    metrics.log_event("program_cost", **entry.to_dict())
    return entry


def reregister(entry: "ProgramCost") -> "ProgramCost":
    """Re-insert a live ProgramCost whose row was dropped by
    ``clear_ledger()``. Compiled executables outlive the ledger (the
    serving engine's module-level AOT cache), so a cache-HIT program
    must surface its original analysis in the fresh ledger instead of
    silently vanishing from roofline/bench/D8 views."""
    if entry.program not in _ledger:
        _ledger[entry.program] = entry
        _site_counts[entry.site] = _site_counts.get(entry.site, 0) + 1
    return entry


def get_program(site: str, key: str) -> ProgramCost | None:
    return _ledger.get(f"{site}|{key}")


def ledger(site: str | None = None) -> list[ProgramCost]:
    """Ledger rows, optionally filtered by site prefix (``"serving"``
    matches serving.prefill / serving.decode / serving.chunk_prefill)."""
    rows = list(_ledger.values())
    if site is not None:
        rows = [e for e in rows if e.site == site
                or e.site.startswith(site + ".")]
    return sorted(rows, key=lambda e: e.program)


def clear_ledger():
    global eager_rows_dropped

    _ledger.clear()
    _site_counts.clear()
    _baselined_this_run.clear()
    eager_rows_dropped = 0


def reset_exec_stats():
    """Zero the measured-execution accumulators (bench rungs call this
    next to obs.clear_events() so each row's utilization is its own);
    the static analyses stay — they belong to the executable."""
    for e in _ledger.values():
        e.exec_count = 0
        e.exec_wall_s = 0.0
        e.last_util = None


def roofline_rows(site: str | None = None, measured_only: bool = False
                  ) -> list[dict]:
    rows = [e.to_dict() for e in ledger(site)]
    if measured_only:
        rows = [r for r in rows if r["roofline_utilization"] is not None]
    return rows


# -------------------------------------------------------------- baseline
#: programs committed by write_baseline() IN THIS PROCESS — D8 skips its
#: "new unbaselined program" note for them, so `roofline_report
#: --write-baseline` followed by an audit in the same run doesn't nag
#: about rows it just wrote to disk itself
_baselined_this_run: set = set()


def write_baseline(path: str, site: str = "serving",
                   threshold_pct: float | None = None) -> dict:
    """Commit the current ledger's analyzed programs as the D8 baseline.
    Only static quantities are recorded (bytes accessed, flops, HBM
    footprint) — walls are machine-dependent and have no business in a
    committed gate."""
    if threshold_pct is None:
        threshold_pct = float(flag("FLAGS_obs_cost_regress_pct"))
    progs = {e.program: {"bytes_accessed": e.bytes_accessed,
                         "flops": e.flops,
                         "peak_hbm_bytes": e.peak_hbm_bytes}
             for e in ledger(site) if e.analyzed}
    _baselined_this_run.update(progs)
    base = {"_comment": "analysis D8 baseline: per-program XLA "
                        "bytes-accessed/flops from the graft_lint obs "
                        "smoke (tiny-LLaMA serving engine). Regenerate "
                        "with tools/roofline_report.py --write-baseline "
                        "after an INTENTIONAL cost change.",
            "threshold_pct": float(threshold_pct), "programs": progs}
    with open(path, "w") as fh:
        json.dump(base, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return base


def load_baseline(path_or_dict) -> dict:
    if isinstance(path_or_dict, dict):
        return path_or_dict
    with open(path_or_dict) as fh:
        return json.load(fh)


def audit_cost_regressions(baseline, entries=None,
                           threshold_pct: float | None = None,
                           loc: str = "obs/costs") -> list:
    """D8 — compiled-program cost regressions vs a committed baseline.

    A program present in the baseline whose CURRENT bytes-accessed grew
    more than ``threshold_pct`` (baseline's own value, else
    FLAGS_obs_cost_regress_pct) is a **warning** — the memory-traffic
    budget regressed, which on a bandwidth-bound device is the perf
    budget. Programs the baseline knows but this run never compiled are
    notes (partial runs are normal); new unbaselined programs are one
    note (additions are fine until someone commits them). Shrunk
    programs are explicitly called out as notes too — an improvement
    worth re-baselining."""
    from ..analysis import Finding

    base = load_baseline(baseline)
    if threshold_pct is None:
        threshold_pct = float(base.get("threshold_pct",
                                       flag("FLAGS_obs_cost_regress_pct")))
    if entries is None:
        entries = ledger()
    cur = {e.program: e for e in entries}
    findings: list = []
    grown, shrunk, missing, checked = [], [], [], 0
    for pid, b in sorted(base.get("programs", {}).items()):
        e = cur.get(pid)
        if e is None or not e.analyzed:
            missing.append(pid)
            continue
        checked += 1
        b_bytes = float(b.get("bytes_accessed", 0.0))
        if b_bytes <= 0:
            continue
        growth = (e.bytes_accessed - b_bytes) / b_bytes
        if growth * 100.0 > threshold_pct:
            grown.append((pid, b_bytes, e.bytes_accessed, growth))
        elif growth < -0.05:
            shrunk.append((pid, b_bytes, e.bytes_accessed, growth))
    for pid, b_bytes, now, growth in grown:
        findings.append(Finding(
            "cost-regression", "warning", f"{loc}:{pid}",
            f"bytes accessed grew {growth:+.0%} over the committed "
            f"baseline ({b_bytes:.0f} -> {now:.0f} B, threshold "
            f"{threshold_pct:g}%) — this program's HBM traffic budget "
            "regressed; if intentional, regenerate "
            "tools/cost_baseline.json (tools/roofline_report.py "
            "--write-baseline)",
            data={"program": pid, "baseline_bytes": b_bytes,
                  "bytes": now, "growth_pct": round(growth * 100, 1),
                  "threshold_pct": threshold_pct}))
    for pid, b_bytes, now, growth in shrunk:
        findings.append(Finding(
            "cost-regression", "note", f"{loc}:{pid}",
            f"bytes accessed SHRANK {growth:+.0%} vs baseline "
            f"({b_bytes:.0f} -> {now:.0f} B) — re-baseline to lock the "
            "improvement in",
            data={"program": pid, "baseline_bytes": b_bytes,
                  "bytes": now}))
    if missing:
        findings.append(Finding(
            "cost-regression", "note", loc,
            f"{len(missing)} baselined program(s) not compiled this run "
            f"(partial smoke): {missing[:4]}"
            f"{'...' if len(missing) > 4 else ''}",
            data={"missing": missing}))
    new = sorted(pid for pid, e in cur.items()
                 if e.analyzed and pid not in base.get("programs", {})
                 and pid not in _baselined_this_run)
    if new:
        findings.append(Finding(
            "cost-regression", "note", loc,
            f"{len(new)} analyzed program(s) not in the baseline "
            f"(unbaselined additions): {new[:4]}"
            f"{'...' if len(new) > 4 else ''}",
            data={"new": new}))
    if not grown:
        findings.append(Finding(
            "cost-regression", "note", loc,
            f"{checked} baselined program(s) within the "
            f"{threshold_pct:g}% bytes-accessed budget",
            data={"checked": checked,
                  "threshold_pct": threshold_pct}))
    return findings
