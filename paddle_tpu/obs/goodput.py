"""Training MFU + goodput ledger — productive step seconds over total
wall, net of compile / checkpoint / data-wait / post-resume-replay
overheads (the ML-goodput accounting shape), plus model-FLOPs utilization
from the cost ledger's XLA flops.

Two joined stories:

* **MFU** — ``obs/costs.py`` already captures XLA ``cost_analysis()``
  flops for every AOT-compiled program (``to_static`` train steps under
  ``FLAGS_jit_debug_program``); the train flight recorder accumulates
  the flops each step actually dispatched, and dividing by the measured
  step wall and the device peak (``FLAGS_obs_peak_tflops``) gives
  ``train_mfu{program}`` per compiled program plus an aggregate
  ``train_mfu{program="step"}`` and ``train_achieved_flops``. Eager
  training (no compiled step program) declares its per-step flops the
  same way token accounting is declared
  (``TelemetryCallback(step_flops=...)``).

* **Goodput** — cumulative wall-second accounting into
  ``train_goodput_seconds_total{category}``: ``productive`` (step
  compute), ``data_wait`` (loader stalls), ``compile`` (watchdog compile
  walls recorded while training), ``ckpt`` (the BLOCKING portion of
  checkpoint saves — the overlapped async commit costs nothing here),
  and ``replay`` (the round-12 resume fast-forward: batches re-consumed
  without compute count against goodput, NOT against MFU).
  ``train_goodput_ratio`` = productive seconds / total wall since
  ``start()``.

The module-level ``activate()``/``deactivate()`` pair scopes the hook
sites (watchdog compile events, ``Model.fit``'s replay loop, checkpoint
callbacks) to the ledger of the fit that is actually running, so a
serving engine compiling in the same process never pollutes training
goodput.

**Analysis D12** (``audit_train_steps``) turns the joined recorder +
ledger story into lint Findings: a data-starvation STREAK (consecutive
steps blocked on input past ``FLAGS_obs_data_wait_ms``) and an MFU
COLLAPSE (recent median a fraction of the run median) are warnings the
``graft_lint`` obs smoke gates on, exactly like recompile storms.
"""
from __future__ import annotations

import statistics
import time
from collections import deque

from ..core.flags import flag

#: per-backend peak-compute defaults (bf16 TFLOP/s) when
#: FLAGS_obs_peak_tflops is 0 — the off-chip figure makes the smoke-test
#: plumbing produce finite gauges, not quotable numbers (same contract
#: as obs/costs.py PEAK_GBPS_FALLBACK)
PEAK_TFLOPS_DEFAULTS = {"tpu": 275.0}
PEAK_TFLOPS_FALLBACK = 0.5

#: goodput categories (the label set of train_goodput_seconds_total)
CATEGORIES = ("productive", "data_wait", "compile", "ckpt", "replay")

#: per-step MFU history kept for D12's collapse detector
MFU_HISTORY = 256

#: train_mfu gets the same widened label cap as roofline_utilization —
#: a step dispatching several compiled programs is legitimate
_GAUGE_LABEL_CAP = 256


#: (flag_value, resolved) memo — observe_step runs per train step; the
#: backend never changes mid-process and the flag rarely does
_peak_memo: tuple = (None, None)


def peak_tflops() -> float:
    global _peak_memo

    v = float(flag("FLAGS_obs_peak_tflops"))
    if _peak_memo[0] == v:
        return _peak_memo[1]
    if v > 0:
        out = v
    else:
        from .trace import _backend

        out = PEAK_TFLOPS_DEFAULTS.get(_backend(), PEAK_TFLOPS_FALLBACK)
    _peak_memo = (v, out)
    return out


class GoodputLedger:
    """Cumulative MFU/goodput accounting over one registry. Persists
    across sequential fits (``start()``/``stop()`` accumulate elapsed
    wall per session); ``reset()`` zeroes the host-side state (registry
    counters are monotonic by contract and stay)."""

    def __init__(self, registry=None):
        if registry is None:
            from . import default_registry

            registry = default_registry()
        self.registry = registry
        self._m_secs = registry.counter(
            "train_goodput_seconds_total", "cumulative training wall "
            "seconds by goodput category (productive step compute vs "
            "data_wait / compile / blocking-ckpt / resume-replay "
            "overheads)", ("category",))
        self._sec_handles = {c: self._m_secs.labels(c) for c in CATEGORIES}
        self._m_ratio = registry.gauge(
            "train_goodput_ratio", "productive step seconds over total "
            "training wall since the ledger started (ML goodput)")
        self._m_mfu = registry.gauge(
            "train_mfu", "model-FLOPs utilization: flops executed per "
            "measured step wall over FLAGS_obs_peak_tflops; one child "
            "per compiled program plus the aggregate program=\"step\"",
            ("program",), label_cap=_GAUGE_LABEL_CAP)
        self._m_aflops = registry.gauge(
            "train_achieved_flops", "achieved FLOP/s of the last train "
            "step (ledger flops / measured wall)")
        self._m_dwait = registry.histogram(
            "train_data_wait_seconds", "per-step loader stall: previous "
            "step end -> batch available (the data_wait flight span)")
        self.seconds = {c: 0.0 for c in CATEGORIES}
        self.steps = 0
        self.mfu_history: deque = deque(maxlen=MFU_HISTORY)
        self._t_start = None          # active session anchor
        self._elapsed_closed = 0.0    # wall from closed sessions
        self._window_skip = 0.0       # replay wall the next data_wait
        #                               measurement must not re-count

    # ---------------------------------------------------------- session
    @property
    def active(self) -> bool:
        return self._t_start is not None

    def start(self):
        if self._t_start is None:
            self._t_start = time.perf_counter()
        return self

    def stop(self):
        if self._t_start is not None:
            self._elapsed_closed += time.perf_counter() - self._t_start
            self._t_start = None
        return self

    def elapsed(self) -> float:
        live = (time.perf_counter() - self._t_start) \
            if self._t_start is not None else 0.0
        return self._elapsed_closed + live

    def reset(self):
        self.seconds = {c: 0.0 for c in CATEGORIES}
        self.steps = 0
        self.mfu_history.clear()
        self._t_start = None
        self._elapsed_closed = 0.0
        self._window_skip = 0.0

    # ------------------------------------------------------- accounting
    def _add(self, category: str, wall_s: float):
        wall_s = max(float(wall_s), 0.0)
        self.seconds[category] += wall_s
        self._sec_handles[category].inc(wall_s)

    def observe_step(self, wall_s, data_wait_s=0.0, flops=0.0,
                     programs=()):
        """One completed train step: ``wall_s`` productive seconds,
        ``data_wait_s`` loader stall, ``flops`` the step's total FLOP
        count (ledger-accumulated or declared), ``programs`` the
        (program_id, flops) pairs dispatched — each gets its own
        ``train_mfu{program}`` child. Returns the aggregate MFU (or
        None without a flops source)."""
        self.steps += 1
        self._add("productive", wall_s)
        self._add("data_wait", data_wait_s)
        self._m_dwait.observe(max(float(data_wait_s), 0.0))
        # denominator: real elapsed wall, floored by the categorized
        # seconds so synthetic accounting (tests, offline replays of a
        # recorded run) can never quote a ratio above 1
        total = max(self.elapsed(), sum(self.seconds.values()))
        if total > 0:
            self._m_ratio.set(self.seconds["productive"] / total)
        if not flops or wall_s <= 0:
            return None
        peak = peak_tflops() * 1e12
        aflops = float(flops) / float(wall_s)
        self._m_aflops.set(aflops)
        mfu = aflops / peak
        self._m_mfu.labels("step").set(mfu)
        # sum per program FIRST: one compiled program dispatched N times
        # in a step (grad-accumulation microbatches) contributes N x its
        # flops, matching the aggregate instead of the last dispatch
        per_prog: dict = {}
        for pid, p_flops in programs:
            per_prog[pid] = per_prog.get(pid, 0.0) + float(p_flops)
        for pid, p_flops in per_prog.items():
            self._m_mfu.labels(pid).set(p_flops / float(wall_s) / peak)
        self.mfu_history.append(mfu)
        return mfu

    def note_compile(self, wall_s: float):
        self._add("compile", wall_s)

    def note_ckpt(self, wall_s: float):
        """The BLOCKING portion of a checkpoint save (host copy /
        synchronous commit) — overlapped background IO is free."""
        self._add("ckpt", wall_s)

    def note_replay(self, wall_s: float):
        """Resume fast-forward (round 12): re-consumed batches count
        against goodput, not MFU — and the wall is remembered so the
        next step's data_wait measurement can net it out instead of
        double-counting it as a loader stall."""
        self._add("replay", wall_s)
        self._window_skip += max(float(wall_s), 0.0)

    def take_window_skip(self) -> float:
        s, self._window_skip = self._window_skip, 0.0
        return s

    def to_dict(self) -> dict:
        el = self.elapsed()
        total = max(el, sum(self.seconds.values()))
        return {"steps": self.steps, "elapsed_s": round(el, 6),
                "seconds": {c: round(v, 6)
                            for c, v in self.seconds.items()},
                "goodput_ratio": (self.seconds["productive"] / total
                                  if total > 0 else None),
                "mfu_last": (self.mfu_history[-1]
                             if self.mfu_history else None),
                "mfu_median": (statistics.median(self.mfu_history)
                               if self.mfu_history else None),
                "peak_tflops": peak_tflops()}


# ------------------------------------------------------ module-level hook
#: the ledger of the fit currently running — the watchdog / fit-replay /
#: ckpt hook sites only report while one is active, so serving compiles
#: in the same process never count against training goodput
_ACTIVE: GoodputLedger | None = None


def activate(ledger: GoodputLedger) -> GoodputLedger | None:
    """Install ``ledger`` as the hook target; returns the previous one
    (nested fits restore it)."""
    global _ACTIVE

    prev = _ACTIVE
    _ACTIVE = ledger
    return prev


def deactivate(ledger: GoodputLedger | None = None):
    global _ACTIVE

    if ledger is None or _ACTIVE is ledger:
        _ACTIVE = None


def active_ledger() -> GoodputLedger | None:
    return _ACTIVE


def note_compile(wall_s: float):
    if _ACTIVE is not None and _ACTIVE.active:
        _ACTIVE.note_compile(wall_s)


def note_ckpt(wall_s: float):
    if _ACTIVE is not None and _ACTIVE.active:
        _ACTIVE.note_ckpt(wall_s)


def note_replay(wall_s: float):
    if _ACTIVE is not None and _ACTIVE.active:
        _ACTIVE.note_replay(wall_s)


# ------------------------------------------------------------------- D12
def audit_train_steps(recorder=None, ledger=None, data_wait_ms=None,
                      streak: int = 3, collapse_ratio: float = 0.5,
                      min_mfu_steps: int = 16,
                      loc: str = "obs/train") -> list:
    """D12 — training-step health Findings over the flight recorder's
    step ring and the goodput ledger's MFU history.

    * **data-starvation streak**: ``streak`` or more CONSECUTIVE steps
      whose data_wait exceeded ``FLAGS_obs_data_wait_ms`` — the input
      pipeline, not compute, is the bottleneck (warning). Isolated
      stalls (epoch boundaries, first batch) stay notes.
    * **MFU collapse**: with at least ``min_mfu_steps`` MFU samples,
      the median of the most recent quarter fell below
      ``collapse_ratio`` x the run median — throughput regressed
      mid-run (a retrace, a growing host sync, a dying input pipeline)
      even though steps still complete (warning).

    Healthy windows produce notes, so --json shows the audit ran."""
    from ..analysis import Finding
    from . import train_flight

    if recorder is None:
        recorder = train_flight.current()
    if ledger is None:
        ledger = _ACTIVE
    if data_wait_ms is None:
        data_wait_ms = float(flag("FLAGS_obs_data_wait_ms"))
    findings: list = []

    steps = [st for st in (recorder.steps() if recorder else [])
             if st.finished]
    worst_streak, run, worst_end = 0, 0, None
    if data_wait_ms > 0:
        for st in steps:
            if st.data_wait_s * 1e3 > data_wait_ms:
                run += 1
                if run > worst_streak:
                    worst_streak, worst_end = run, st.index
            else:
                run = 0
    if worst_streak >= streak:
        findings.append(Finding(
            "train-starvation", "warning", loc,
            f"{worst_streak} consecutive step(s) blocked on input past "
            f"FLAGS_obs_data_wait_ms={data_wait_ms:g} (ending at step "
            f"{worst_end}) — the loader, not compute, bounds this run; "
            "raise num_workers / prefetch or fix the input pipeline",
            data={"streak": worst_streak, "threshold_ms": data_wait_ms,
                  "end_step": worst_end}))
    else:
        findings.append(Finding(
            "train-starvation", "note", loc,
            f"{len(steps)} recorded step(s), longest data-wait streak "
            f"{worst_streak} (< {streak}) at "
            f"threshold {data_wait_ms:g}ms",
            data={"steps": len(steps), "streak": worst_streak}))

    hist = list(ledger.mfu_history) if ledger is not None else []
    if len(hist) >= min_mfu_steps:
        overall = statistics.median(hist)
        recent = statistics.median(hist[-max(len(hist) // 4, 4):])
        if overall > 0 and recent < collapse_ratio * overall:
            findings.append(Finding(
                "train-mfu-collapse", "warning", loc,
                f"MFU collapsed mid-run: recent median "
                f"{recent:.4f} < {collapse_ratio:g} x run median "
                f"{overall:.4f} — throughput regressed while steps "
                "still complete (retrace storm, growing host sync, or "
                "a dying input pipeline); dump the flight ring",
                data={"recent": recent, "overall": overall,
                      "collapse_ratio": collapse_ratio}))
        else:
            findings.append(Finding(
                "train-mfu-collapse", "note", loc,
                f"MFU steady over {len(hist)} step(s): recent median "
                f"{recent:.4f} vs run median {overall:.4f}",
                data={"recent": recent, "overall": overall}))
    else:
        findings.append(Finding(
            "train-mfu-collapse", "note", loc,
            f"{len(hist)} MFU sample(s) (< {min_mfu_steps}) — collapse "
            "detection needs a longer window or a flops source "
            "(compiled step program or TelemetryCallback(step_flops=))",
            data={"samples": len(hist)}))
    return findings
