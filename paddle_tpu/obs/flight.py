"""Per-request flight recorder — the artifact that explains a TTFT p95.

Round 11's histograms say *what* (`serving_ttft_seconds` p95 breached);
this module records *why*: every request riding a ``ServingEngine``
carries an ordered span timeline — enqueue, admission (including how
many scheduler ticks it sat blocked on the block pool), prefix-cache
hit / copy-on-write, every prefill chunk program, the decode phase, and
its finish or timeout reason — held in a bounded ring alongside an
engine-level track of decode ticks. ``ServingEngine.dump_trace(path)``
exports the ring as **Chrome-trace JSON** (the ``traceEvents`` array
format Perfetto / ``chrome://tracing`` load directly), and anomaly
triggers (request timeout, TTFT SLO breach, post-warmup compile)
auto-dump a postmortem to ``FLAGS_obs_flight_dir`` so the trace of the
bad minute exists even when nobody was watching.

The TTFT invariant is **asserted, not assumed**: a request's
``queue_wait`` span ends exactly where its ``prefill`` span begins, and
the two must tile the engine's recorded TTFT bitwise (they are derived
from the same three timestamps the histograms observe —
``arrival/admitted/first_token``). ``dump()`` raises on violation, and
every span's args carry the exact float seconds (``t0_s``/``t1_s``) so
the dumped JSON round-trips the invariant losslessly (the microsecond
``ts``/``dur`` fields are for the viewer, not the proof).

Bounding: finished flights are a ring (``FLAGS_obs_flight_requests``;
the oldest finished flight is evicted, active requests never are),
per-flight span lists are capped (a pathological 10k-chunk prompt
degrades to "first chunks + a counter", never host memory), and the
engine tick track is a fixed deque. Per-token cost on the hot path is
two attribute writes; spans are only appended per *program invocation*
(ticks and chunks, not tokens).
"""
from __future__ import annotations

import json
import os
import time
from collections import OrderedDict, deque

from ..core.flags import flag

#: engine-track spans kept (decode ticks, chunk phases): one per
#: scheduler tick, so this window covers the last ~4k ticks
TICK_SPAN_CAP = 4096

#: per-flight program-span cap: chunks/prefill programs past it are
#: counted (``spans_dropped``) instead of stored
REQUEST_SPAN_CAP = 512

#: auto-dumps per recorder: a flapping SLO must not fill the disk —
#: the dumps counter keeps counting, the files stop
AUTODUMP_CAP = 16


class RequestFlight:
    """One request's timeline. Timestamps are ``time.perf_counter``
    seconds, the same clock (and for the lifecycle marks, the same
    *reads*) the engine's histograms observe."""

    __slots__ = ("rid", "prompt_len", "max_new_tokens", "arrival_s",
                 "admitted_s", "first_token_s", "last_token_s",
                 "finish_s", "reason", "cached_blocks", "cow",
                 "blocked_ticks", "tokens", "chunks", "spans",
                 "spans_dropped", "marks", "ttft_s")

    def __init__(self, rid, prompt_len, max_new_tokens, arrival_s):
        self.rid = int(rid)
        self.prompt_len = int(prompt_len)
        self.max_new_tokens = int(max_new_tokens)
        self.arrival_s = float(arrival_s)
        self.admitted_s = None
        self.first_token_s = None
        self.last_token_s = None
        self.finish_s = None
        self.reason = None
        self.cached_blocks = 0
        self.cow = False
        self.blocked_ticks = 0
        self.tokens = 0
        self.chunks = 0
        self.spans: list = []        # (name, t0, t1, args) program spans
        self.spans_dropped = 0
        self.marks: list = []        # (name, t, args) instantaneous
        self.ttft_s = None           # engine-recorded, for the assertion

    def add_span(self, name, t0, t1, args=None):
        if len(self.spans) >= REQUEST_SPAN_CAP:
            self.spans_dropped += 1
            return
        self.spans.append((name, float(t0), float(t1), args or {}))

    def add_mark(self, name, t, args=None):
        if len(self.marks) < REQUEST_SPAN_CAP:
            self.marks.append((name, float(t), args or {}))

    @property
    def finished(self) -> bool:
        return self.finish_s is not None


class FlightRecorder:
    def __init__(self, capacity: int | None = None):
        if capacity is None:
            capacity = int(flag("FLAGS_obs_flight_requests"))
        self.capacity = max(1, int(capacity))
        self._flights: "OrderedDict[int, RequestFlight]" = OrderedDict()
        self._finished: deque = deque()   # rids in finish order
        self._ticks: deque = deque(maxlen=TICK_SPAN_CAP)
        self.evicted = 0
        self.autodumps = 0
        self.autodump_paths: list[str] = []

    # ----------------------------------------------------------- record
    def begin(self, rid, prompt_len, max_new_tokens, arrival_s
              ) -> RequestFlight:
        fl = RequestFlight(rid, prompt_len, max_new_tokens, arrival_s)
        self._flights[rid] = fl
        return fl

    def get(self, rid) -> RequestFlight | None:
        return self._flights.get(rid)

    def tick_span(self, name, t0, t1, **args):
        """One engine-track span (decode tick / chunk phase)."""
        self._ticks.append((name, float(t0), float(t1), args))

    def tick_mark(self, name, t, **args):
        self._ticks.append((name, float(t), None, args))

    def finish(self, rid, t, reason):
        fl = self._flights.get(rid)
        if fl is None:
            return
        fl.finish_s = float(t)
        fl.reason = reason
        self._finished.append(rid)
        while len(self._finished) > self.capacity:
            old = self._finished.popleft()
            if self._flights.pop(old, None) is not None:
                self.evicted += 1

    # ----------------------------------------------------------- export
    def flights(self) -> list[RequestFlight]:
        return list(self._flights.values())

    def _check_tiling(self):
        """The TTFT invariant: queue_wait and prefill spans are derived
        from the SAME timestamps the histograms observed, are contiguous
        by construction, and must sum to the recorded TTFT bitwise."""
        for fl in self._flights.values():
            if fl.first_token_s is None:
                continue
            if fl.admitted_s is None:
                raise AssertionError(
                    f"flight {fl.rid}: first token without an admission "
                    "timestamp — the queue_wait span cannot tile TTFT")
            if not (fl.arrival_s <= fl.admitted_s <= fl.first_token_s):
                raise AssertionError(
                    f"flight {fl.rid}: non-monotonic lifecycle "
                    f"({fl.arrival_s} -> {fl.admitted_s} -> "
                    f"{fl.first_token_s})")
            if fl.ttft_s is not None and \
                    (fl.first_token_s - fl.arrival_s) != fl.ttft_s:
                raise AssertionError(
                    f"flight {fl.rid}: span endpoints do not tile the "
                    f"recorded TTFT ({fl.first_token_s - fl.arrival_s!r} "
                    f"!= {fl.ttft_s!r}) — the engine's timestamp "
                    "bookkeeping and the recorder's diverged")

    def to_chrome(self) -> dict:
        """Chrome-trace/Perfetto ``traceEvents`` JSON (object form).
        One process, tid 0 = the engine scheduler track, tid rid+1 per
        request; complete (``ph:"X"``) events carry exact seconds in
        ``args`` — ts/dur microseconds are viewer-resolution only."""
        self._check_tiling()
        times = [fl.arrival_s for fl in self._flights.values()]
        times += [t0 for _, t0, _, _ in self._ticks]
        epoch = min(times) if times else 0.0

        def us(t):
            return (t - epoch) * 1e6

        ev: list[dict] = [
            {"ph": "M", "pid": 1, "tid": 0, "name": "process_name",
             "args": {"name": "paddle_tpu serving"}},
            {"ph": "M", "pid": 1, "tid": 0, "name": "thread_name",
             "args": {"name": "engine"}},
        ]
        for name, t0, t1, args in self._ticks:
            if t1 is None:
                ev.append({"ph": "i", "pid": 1, "tid": 0, "name": name,
                           "ts": us(t0), "s": "t",
                           "args": dict(args, t_s=t0)})
            else:
                ev.append({"ph": "X", "pid": 1, "tid": 0, "name": name,
                           "ts": us(t0), "dur": (t1 - t0) * 1e6,
                           "cat": "engine",
                           "args": dict(args, t0_s=t0, t1_s=t1)})
        for fl in self._flights.values():
            tid = fl.rid + 1
            ev.append({"ph": "M", "pid": 1, "tid": tid,
                       "name": "thread_name",
                       "args": {"name": f"request {fl.rid}"}})
            end = fl.finish_s or fl.last_token_s or fl.first_token_s \
                or fl.admitted_s or fl.arrival_s
            # a mid-flight dump (anomaly postmortem while this request
            # is still prefilling) has lifecycle timestamps that stop at
            # admission while chunk spans/marks run past it — the window
            # must cover them or validate_trace rejects the postmortem
            for _, _, t1, _ in fl.spans:
                end = max(end, t1)
            for _, t, _ in fl.marks:
                end = max(end, t)
            ev.append({"ph": "X", "pid": 1, "tid": tid, "name": "request",
                       "ts": us(fl.arrival_s),
                       "dur": (end - fl.arrival_s) * 1e6, "cat": "request",
                       "args": {"rid": fl.rid, "prompt_len": fl.prompt_len,
                                "max_new_tokens": fl.max_new_tokens,
                                "tokens": fl.tokens,
                                "reason": fl.reason,
                                "cached_blocks": fl.cached_blocks,
                                "cow": fl.cow,
                                "blocked_ticks": fl.blocked_ticks,
                                "spans_dropped": fl.spans_dropped,
                                "t0_s": fl.arrival_s, "t1_s": end}})
            if fl.admitted_s is not None:
                ev.append({"ph": "X", "pid": 1, "tid": tid,
                           "name": "queue_wait", "ts": us(fl.arrival_s),
                           "dur": (fl.admitted_s - fl.arrival_s) * 1e6,
                           "cat": "lifecycle",
                           "args": {"blocked_ticks": fl.blocked_ticks,
                                    "t0_s": fl.arrival_s,
                                    "t1_s": fl.admitted_s}})
            if fl.first_token_s is not None:
                ev.append({"ph": "X", "pid": 1, "tid": tid,
                           "name": "prefill", "ts": us(fl.admitted_s),
                           "dur": (fl.first_token_s - fl.admitted_s) * 1e6,
                           "cat": "lifecycle",
                           "args": {"cached_blocks": fl.cached_blocks,
                                    "cow": fl.cow, "chunks": fl.chunks,
                                    "ttft_s": fl.ttft_s,
                                    "t0_s": fl.admitted_s,
                                    "t1_s": fl.first_token_s}})
            if fl.first_token_s is not None and fl.last_token_s is not None \
                    and fl.last_token_s > fl.first_token_s:
                ev.append({"ph": "X", "pid": 1, "tid": tid,
                           "name": "decode", "ts": us(fl.first_token_s),
                           "dur": (fl.last_token_s - fl.first_token_s)
                           * 1e6, "cat": "lifecycle",
                           "args": {"tokens": fl.tokens,
                                    "t0_s": fl.first_token_s,
                                    "t1_s": fl.last_token_s}})
            for name, t0, t1, args in fl.spans:
                ev.append({"ph": "X", "pid": 1, "tid": tid, "name": name,
                           "ts": us(t0), "dur": (t1 - t0) * 1e6,
                           "cat": "program",
                           "args": dict(args, t0_s=t0, t1_s=t1)})
            for name, t, args in fl.marks:
                ev.append({"ph": "i", "pid": 1, "tid": tid, "name": name,
                           "ts": us(t), "s": "t",
                           "args": dict(args, t_s=t)})
        return {"traceEvents": ev, "displayTimeUnit": "ms",
                "otherData": {"source": "paddle_tpu.obs.flight",
                              "flights": len(self._flights),
                              "evicted": self.evicted,
                              "epoch_s": epoch}}

    def dump(self, path: str) -> str:
        obj = self.to_chrome()
        with open(path, "w") as fh:
            json.dump(obj, fh)
        return path

    # ---------------------------------------------------------- anomaly
    def anomaly_dump(self, trigger: str) -> str | None:
        """Postmortem auto-dump: write the current ring to
        FLAGS_obs_flight_dir (created on demand), capped at
        AUTODUMP_CAP files per recorder. Returns the path, or None when
        disabled/capped. Never raises — a broken postmortem path must
        not take the serving loop down."""
        d = str(flag("FLAGS_obs_flight_dir") or "")
        if not d or self.autodumps >= AUTODUMP_CAP:
            return None
        try:
            os.makedirs(d, exist_ok=True)
            path = os.path.join(
                d, f"flight_{trigger}_{os.getpid()}_{self.autodumps}.json")
            self.dump(path)
        except Exception:
            return None
        self.autodumps += 1
        self.autodump_paths.append(path)
        return path


# ------------------------------------------------------------ validation
def validate_trace(obj_or_path) -> dict:
    """Structural validation of a dumped trace — the re-parse half of the
    Perfetto round-trip (the lint ``obs`` smoke and the tests both call
    this instead of hand-rolling checks). Verifies: JSON loads, the
    traceEvents array exists, every complete event has non-negative
    ``dur``, per-request lifecycle spans NEST (queue_wait and prefill
    inside the request span, programs inside the request span) and TILE
    (queue_wait ends exactly where prefill begins, and their exact-
    seconds args reproduce ``ttft_s`` bitwise). Raises ValueError on any
    violation; returns a summary dict."""
    if isinstance(obj_or_path, (str, os.PathLike)):
        with open(obj_or_path) as fh:
            obj = json.load(fh)
    else:
        obj = obj_or_path
    source = (obj.get("otherData") or {}).get("source", "")
    if source.endswith("train_flight"):
        # training dumps carry step timelines, not request timelines —
        # same entry point, train-specific invariants (round 16)
        from .train_flight import validate_train_trace

        return validate_train_trace(obj)
    evs = obj.get("traceEvents")
    if not isinstance(evs, list) or not evs:
        raise ValueError("trace has no traceEvents array")
    by_tid: dict = {}
    for e in evs:
        if e.get("ph") == "X":
            if e.get("dur", 0) < 0:
                raise ValueError(f"negative-duration span: {e}")
            by_tid.setdefault(e["tid"], {}).setdefault(
                e["name"], []).append(e)
    requests = 0
    tiled = 0
    for tid, spans in by_tid.items():
        if "request" not in spans:
            continue
        requests += 1
        req = spans["request"][0]["args"]
        lo, hi = req["t0_s"], req["t1_s"]
        for name, group in spans.items():
            for s in group:
                a = s["args"]
                if not (lo <= a["t0_s"] and a["t1_s"] <= hi):
                    raise ValueError(
                        f"span {name!r} escapes its request window on "
                        f"tid {tid}: [{a['t0_s']}, {a['t1_s']}] outside "
                        f"[{lo}, {hi}]")
        if "queue_wait" in spans and "prefill" in spans:
            q = spans["queue_wait"][0]["args"]
            p = spans["prefill"][0]["args"]
            if q["t1_s"] != p["t0_s"]:
                raise ValueError(
                    f"tid {tid}: queue_wait does not end where prefill "
                    f"begins ({q['t1_s']!r} != {p['t0_s']!r})")
            ttft = p.get("ttft_s")
            if ttft is not None and (p["t1_s"] - q["t0_s"]) != ttft:
                raise ValueError(
                    f"tid {tid}: spans do not tile TTFT "
                    f"({p['t1_s'] - q['t0_s']!r} != {ttft!r})")
            tiled += 1
    return {"events": len(evs), "requests": requests,
            "tiled_requests": tiled,
            "engine_spans": len(by_tid.get(0, {}).get("decode_tick", [])),
            "verify_spans": len(by_tid.get(0, {}).get("verify_window", []))}
