"""Trace spans — named scopes over the device profiler, with a host
fallback.

On TPU a span wraps ``jax.profiler.TraceAnnotation`` (named scopes in the
xplane capture; ``step_span`` uses ``StepTraceAnnotation`` so XProf groups
per-step work), and ``capture_trace(dir)`` is the on-demand profile
capture — wrap any suspect window and read the xplane in
TensorBoard/XProf. Off-TPU (the CPU build hosts, CI) the same API records
wall-clock spans into a bounded host buffer with nesting tracked by a
thread-local stack, so span-shaped assertions (tests) and span timings
(the JSONL log) work everywhere the code runs.

Distinct from paddle_tpu.profiler: that module is the reference-parity
``paddle.profiler`` surface (scheduler states, summary tables, chrome
trace). ``obs.span`` is the always-available internal instrumentation
primitive the runtime itself uses — no scheduler, no global recording
toggle, ~1us per span off-TPU.
"""
from __future__ import annotations

import contextlib
import threading
import time
from collections import deque

_tls = threading.local()

#: host-side span record buffer (off-TPU fallback + tests); bounded so an
#: instrumented serving loop can run forever
_SPAN_BUF_CAP = 8192
# thread-safe: GIL-atomic bounded-deque appends; readers snapshot
_span_buf: deque = deque(maxlen=_SPAN_BUF_CAP)

# thread-safe: idempotent memo — concurrent first calls write the same
# backend string, last-write-wins
_backend_memo: str | None = None


def _backend() -> str:
    """jax.default_backend(), memoized — span() must not pay a backend
    query per call."""
    global _backend_memo
    if _backend_memo is None:
        try:
            import jax

            _backend_memo = jax.default_backend()
        except Exception:
            _backend_memo = "none"
    return _backend_memo


def _stack() -> list:
    s = getattr(_tls, "span_stack", None)
    if s is None:
        s = _tls.span_stack = []
    return s


@contextlib.contextmanager
def span(name: str, histogram=None):
    """Named scope: ``with obs.span("prefill"): ...``.

    On TPU, emits a ``TraceAnnotation`` so the scope shows up in xplane
    captures. Everywhere, records a wall-clock span (qualified with its
    nesting path, e.g. ``step/prefill``) into the host buffer; when
    `histogram` (an obs.metrics.Histogram handle) is given, the duration
    is observed into it — that is how the engine's span timings reach the
    registry without a second clock read."""
    stack = _stack()
    qual = "/".join([*(s for s in stack), name]) if stack else name
    stack.append(name)
    ann = None
    if _backend() == "tpu":
        import jax.profiler

        ann = jax.profiler.TraceAnnotation(name)
        ann.__enter__()
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        if ann is not None:
            ann.__exit__(None, None, None)
        stack.pop()
        _span_buf.append({"name": name, "path": qual, "seconds": dt,
                          "depth": len(stack)})
        if histogram is not None:
            histogram.observe(dt)


@contextlib.contextmanager
def step_span(step: int, name: str = "train_step"):
    """Per-step scope: ``StepTraceAnnotation`` on TPU (XProf step
    grouping), a plain span elsewhere."""
    if _backend() == "tpu":
        import jax.profiler

        with jax.profiler.StepTraceAnnotation(name, step_num=int(step)):
            yield
        return
    with span(f"{name}[{int(step)}]"):
        yield


def span_events(clear: bool = False) -> list[dict]:
    """Snapshot of the host span buffer (newest last)."""
    out = list(_span_buf)
    if clear:
        _span_buf.clear()
    return out


def clear_spans():
    _span_buf.clear()


@contextlib.contextmanager
def capture_trace(log_dir: str):
    """On-demand device profile capture around a suspect window:

        with obs.capture_trace("/tmp/xplane"):
            engine.step()

    Wraps ``jax.profiler.start_trace/stop_trace`` (works on CPU too — the
    xplane then holds host events only). Refuses to nest with an already
    running capture (paddle_tpu.profiler's device tracing included):
    jax allows one active trace per process."""
    import os

    import jax.profiler

    os.makedirs(log_dir, exist_ok=True)
    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()
