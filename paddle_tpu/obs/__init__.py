"""paddle_tpu.obs — runtime telemetry: metrics, spans, compile watchdog,
structured logging.

The observability substrate the ROADMAP's serving/partitioner items
report through (the role paddle.profiler + VisualDL play in the
reference stack, rebuilt serving-grade):

  * **metrics**  — Counter/Gauge/Histogram registry with labels
    (cardinality-capped), JSONL event log (``FLAGS_obs_log_path``) and
    Prometheus text exposition (``render_prometheus()`` +
    ``serve_metrics(port)`` stdlib endpoint). The serving engine owns a
    per-instance registry; the framework default (compile metrics) is
    ``default_registry()``.
  * **trace**    — ``span("name")`` over ``jax.profiler.TraceAnnotation``
    on TPU / wall-clock off-TPU; ``capture_trace(dir)`` on-demand xplane
    capture.
  * **watchdog** — every compile/retrace (eager cache, to_static, the
    generation engine, serving buckets) becomes an event +
    ``compiles_total``/``compile_seconds``; ``audit_recompiles()`` turns
    storms and post-warmup compiles into ``analysis.Finding``s that fail
    ``tools/graft_lint.py`` (the ``obs`` smoke).
  * **logging**  — module-scoped VLOG driven by ``FLAGS_log_level`` with
    per-message rate limiting; the dy2static fallback + engine admission
    messages route through it.
  * **train_flight / goodput** (round 16) — the training twins of the
    request recorder + cost ledger: per-step span timelines (data wait,
    h2d, fwd/bwd/opt, lazy flushes, compiled dispatches, ckpt IO) with
    a dump-time wall-tiling assertion and anomaly postmortems
    (data starvation / step spike / ckpt stall), plus MFU
    (``train_mfu{program}``) and ML-goodput accounting
    (``train_goodput_seconds_total{category}``); ``audit_train_steps``
    (analysis D12) gates starvation streaks and MFU collapse in lint.

Overhead: metrics are OFF by default everywhere except the serving
engine (whose per-tick cost is a handful of attribute updates — measured
within 2% tok/s of uninstrumented steady-state decode, PERF.md round 11);
``FLAGS_obs_metrics=1`` opts the train loop in.
"""
from __future__ import annotations

from .costs import (ProgramCost, audit_cost_regressions, clear_ledger,
                    extract_cost, ledger, peak_gbps, record_program,
                    reset_exec_stats, roofline_rows, write_baseline)
from .flight import FlightRecorder, RequestFlight, validate_trace
from .goodput import (GoodputLedger, audit_train_steps, peak_tflops)
from .http import MetricsServer, serve_metrics, shared_server
from .logging import ObsLogger, get_logger
from .metrics import (DEFAULT_BUCKETS, OVERFLOW, Counter, Gauge, Histogram,
                      Registry, dump_registry, log_event)
from .trace import (capture_trace, clear_spans, span, span_events,
                    step_span)
from .train_flight import (StepFlight, TrainFlightRecorder,
                           validate_train_trace)
from .watchdog import (CompileEvent, audit_ckpt_stalls, audit_recompiles,
                       ckpt_save_events, clear_events, compile_counts,
                       compile_events, jaxpr_size, post_warmup_compiles,
                       record_ckpt_save, record_compile)

#: process-default registry: compile watchdog counters, train-callback
#: metrics, anything not tied to one engine instance
_default = Registry()


def default_registry() -> Registry:
    return _default


def render_prometheus() -> str:
    """Prometheus text exposition of the default registry."""
    return _default.render_prometheus()


def metrics_enabled() -> bool:
    """Global opt-in for instrumentation OUTSIDE the serving engine
    (FLAGS_obs_metrics). The engine instruments unconditionally (its
    registry is the serving product); the watchdog records compiles
    unconditionally (compiles are rare events, not a hot path)."""
    from ..core.flags import flag

    return bool(flag("FLAGS_obs_metrics"))


__all__ = [
    "Counter", "Gauge", "Histogram", "Registry", "DEFAULT_BUCKETS",
    "OVERFLOW", "default_registry", "render_prometheus", "metrics_enabled",
    "dump_registry", "log_event",
    "span", "step_span", "span_events", "clear_spans", "capture_trace",
    "CompileEvent", "record_compile", "compile_events", "compile_counts",
    "post_warmup_compiles", "clear_events", "audit_recompiles",
    "jaxpr_size",
    "record_ckpt_save", "ckpt_save_events", "audit_ckpt_stalls",
    "get_logger", "ObsLogger",
    "serve_metrics", "MetricsServer", "shared_server",
    "FlightRecorder", "RequestFlight", "validate_trace",
    "TrainFlightRecorder", "StepFlight", "validate_train_trace",
    "GoodputLedger", "audit_train_steps", "peak_tflops",
    "ProgramCost", "record_program", "ledger", "clear_ledger",
    "reset_exec_stats", "roofline_rows", "extract_cost", "peak_gbps",
    "write_baseline", "audit_cost_regressions",
]
