"""paddle.onnx (≙ python/paddle/onnx — paddle2onnx shim).

ONNX export is explicitly deferred in the TPU-native design (SURVEY §7
"what we do NOT rebuild"): the deployment artifact is serialized StableHLO
(paddle.jit.save → paddle.inference), which XLA-backed runtimes consume
directly. export() raises with that guidance.
"""
from __future__ import annotations


def export(layer, path, input_spec=None, opset_version=9, **configs):
    raise NotImplementedError(
        "paddle.onnx.export: ONNX is not the TPU deployment path — use "
        "paddle.jit.save(layer, path, input_spec=...) to produce serialized "
        "StableHLO and serve it with paddle.inference.create_predictor "
        "(SURVEY §7 defers ONNX by design)")


__all__ = ["export"]
