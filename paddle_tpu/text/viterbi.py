"""ViterbiDecoder (≙ python/paddle/text/viterbi_decode.py → phi
viterbi_decode_kernel): CRF max-sum decoding as one lax.scan over time —
a single fused XLA loop, batched.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import op_call
from ..nn.layer_base import Layer

__all__ = ['ViterbiDecoder', 'viterbi_decode']


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """potentials [B,T,N], transitions [N,N] (or [N+2,N+2] with BOS/EOS
    rows when include_bos_eos_tag), lengths [B] → (scores [B], paths [B,T]).
    Positions past each length repeat the last valid tag (reference
    semantics: outputs are only meaningful up to `lengths`)."""

    def f(emit, trans, lens):
        b, t, n = emit.shape
        if include_bos_eos_tag:
            # reference convention (phi viterbi_decode kernel splits the
            # transition ROWS): row n-1 = start tag, row n-2 = stop tag
            start = trans[n - 1, :][None, :]     # BOS → tag
            stop = trans[n - 2, :][None, :]      # tag → EOS
        else:
            start = jnp.zeros((1, n), emit.dtype)
            stop = jnp.zeros((1, n), emit.dtype)

        alpha0 = emit[:, 0] + start              # [B, N]

        def step(carry, xs):
            alpha, tstep = carry, xs
            emit_t, idx = tstep
            # scores[b, i, j] = alpha[b, i] + trans[i, j]
            scores = alpha[:, :, None] + trans[None, :, :]
            best_prev = jnp.argmax(scores, axis=1)           # [B, N]
            best_score = jnp.max(scores, axis=1) + emit_t    # [B, N]
            # past the sequence end: carry alpha forward unchanged
            valid = (idx < lens)[:, None]
            new_alpha = jnp.where(valid, best_score, alpha)
            bp = jnp.where(valid, best_prev,
                           jnp.broadcast_to(jnp.arange(n)[None, :], (b, n)))
            return new_alpha, bp

        idxs = jnp.arange(1, t)
        alpha, backptrs = jax.lax.scan(
            step, alpha0, (jnp.swapaxes(emit[:, 1:], 0, 1), idxs))
        final = alpha + stop
        scores = jnp.max(final, axis=-1)
        last_tag = jnp.argmax(final, axis=-1)                # [B]

        if t == 1:
            return scores, last_tag[:, None].astype(jnp.int64)

        def back(carry, bp):
            # carry = tag at time s; bp[b, j] = best tag at s-1 given j at s
            prev = jnp.take_along_axis(bp, carry[:, None], axis=1)[:, 0]
            return prev, carry

        first_tag, tags_rev = jax.lax.scan(back, last_tag,
                                           jnp.flip(backptrs, 0))
        # tags_rev rows: tag_{t-1}, ..., tag_1 → flip to tag_1..tag_{t-1}
        tags = jnp.flip(jnp.swapaxes(tags_rev, 0, 1), 1)
        path = jnp.concatenate([first_tag[:, None], tags], axis=1)
        return scores, path.astype(jnp.int64)

    return op_call(f, potentials, transition_params, lengths,
                   name="viterbi_decode", n_diff=2)


class ViterbiDecoder(Layer):
    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
