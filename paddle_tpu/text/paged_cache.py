"""Block-paged KV cache: the allocator + the pure cache-update rules.

Reference parity: the block-table KV management behind
block_multihead_attention (fusion/gpu/block_multi_head_attention_kernel.cu)
— PagedAttention's (Kwon et al.) block-granular allocation, so a serving
engine's HBM footprint tracks the TOKENS ACTUALLY HELD rather than
max_len * max_batch.

Pieces:
  * `BlockAllocator` — host-side free list over a fixed block pool.
    Block 0 is the reserved TRASH block: every in-program write whose
    destination must be masked out (padded prefill positions, padded
    decode slots) is routed there instead of carrying a scatter mask —
    copy-free release is then trivial (free the ids; nothing is zeroed,
    stale contents are never attended to because the length mask bounds
    every read and appends overwrite before reads reach them).
  * `PagedKVCache` — the device arrays: `[L, num_blocks, H_kv,
    block_size, D]` per k/v (layer axis outermost so the per-step
    program's `lax.scan` over stacked layer weights threads the matching
    cache slice), plus per-(layer, block) f32 scales when the storage
    dtype is int8.
  * pure jnp functions used INSIDE the compiled step programs: decode
    append (scatter one token per slot through the block table) and
    prefill scatter (page-granular), each with an int8 variant that
    requantizes the touched block against its per-block scale.

Static shapes everywhere: block tables are padded [slots, pages] arrays,
the trash block absorbs masked writes, and the allocator is the only
dynamic piece — it lives on the host and never enters a trace.

Round 13 adds PREFIX CACHING on top of the same block pool (vLLM's
block-hash reuse): `PrefixCache` keys FULL blocks by a rolling content
hash over their token ids (chained, so a block's identity includes its
whole prefix) and refcounts every block a live request's table holds.
A request whose prompt shares a cached prefix points its table rows at
the cached blocks (zero prefill for those pages); `release` returns
hash-mapped blocks to an LRU of refcount-0 cached blocks instead of the
free list, and allocation under pressure evicts from that LRU — never
from a block something still references.
"""
from __future__ import annotations

import hashlib
from collections import OrderedDict

import jax.numpy as jnp
import numpy as np

from ..core.lockdep import ThreadContract
from ..ops.quantized import INT4_QMAX, int4_pack, int4_unpack

#: block id 0 is never allocated — masked writes land there (see module doc)
TRASH_BLOCK = 0


class BlockAllocator:
    """Free-list allocator over `num_blocks` cache blocks (block 0
    reserved as trash). Allocation is all-or-nothing: a request either
    gets its full block budget up front (admission control) or stays
    queued — no mid-flight OOM/preemption.

    THREAD CONTRACT (D15): single-owner, lock-free by design — the
    ServingEngine shares its contract object with the pool so one owner
    thread covers the whole serving object graph
    (``FLAGS_debug_thread_checks`` asserts it)."""

    #: D15 static marker: methods the single-owner contract guards
    _thread_contract = ("alloc", "free")

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (block 0 is the trash block)")
        self.num_blocks = int(num_blocks)
        self.contract = ThreadContract("BlockAllocator")
        self._free = list(range(self.num_blocks - 1, 0, -1))  # pop() -> 1..

    @property
    def available(self) -> int:
        return len(self._free)

    def alloc(self, n: int):
        """n block ids, or None when the pool can't cover them."""
        self.contract.check("alloc")
        if n < 0:
            raise ValueError(f"negative block count {n}")
        if n > len(self._free):
            return None
        return [self._free.pop() for _ in range(n)]

    def free(self, ids) -> None:
        self.contract.check("free")
        for b in ids:
            b = int(b)
            if not 0 < b < self.num_blocks:
                raise ValueError(f"freeing invalid block id {b}")
            if b in self._free:
                raise ValueError(f"double free of block {b}")
            self._free.append(b)


def blocks_for(tokens: int, block_size: int) -> int:
    """Blocks needed to hold `tokens` cache entries."""
    return -(-int(tokens) // int(block_size))


# ------------------------------------------------------- prefix caching

def hash_blocks(tokens, block_size: int, namespace: int = 0) -> list:
    """Chained content hashes for every FULL block of `tokens`: block i's
    hash covers its own token ids AND (through the chain) every token
    before it, so equal hashes mean equal whole prefixes — the property
    that makes hash->block reuse sound. `namespace` seeds the chain: KV
    content depends on the model weights / layer config / cache dtype,
    so two engines over different models must never collide (a namespace
    mismatch shows up as 0% hits on an identical-prompt stream — the D7
    cache-defeated finding). Hashes are sha256 digests, not Python
    `hash()`: a 64-bit builtin-hash collision between two different
    prefixes would silently serve one request's KV content to another
    (token ids are caller-controlled, so the weak hash is also
    adversarially reachable — the vLLM CVE-2025-25183 shape)."""
    bs = int(block_size)
    toks = np.asarray(tokens).reshape(-1).astype(np.int64)
    h = hashlib.sha256(
        b"paddle_tpu.prefix_cache:%d" % int(namespace)).digest()
    out = []
    for i in range(len(toks) // bs):
        h = hashlib.sha256(h + toks[i * bs:(i + 1) * bs].tobytes()).digest()
        out.append(h)
    return out


class PrefixCache:
    """Hash->block map + per-block refcounts + LRU over a BlockAllocator.

    Block lifecycle: `allocate` hands out private blocks at refcount 1
    (evicting refcount-0 cached blocks when the free list runs dry);
    `register` publishes a computed full block under its content hash;
    `lookup` serves a new request's shared prefix by bumping refcounts;
    `release` (the finish path) decrefs — a hash-mapped block at
    refcount 0 parks in the LRU (its KV stays warm for the next request)
    while an unmapped block goes straight back to the free list. Only
    refcount-0 blocks are ever evicted.

    THREAD CONTRACT (D15): single-owner like the engine that drives it —
    the hash map / refcounts / LRU mutate lock-free by design; the
    engine shares its ThreadContract here so one owner thread covers the
    whole serving object graph."""

    #: D15 static marker: methods the single-owner contract guards
    _thread_contract = ("allocate", "lookup", "register", "release",
                        "cancel_lookup")

    def __init__(self, allocator: BlockAllocator, max_cached_blocks: int = 0):
        self.allocator = allocator
        self.contract = ThreadContract("PrefixCache")
        #: cap on refcount-0 cached blocks (0 = bounded only by the pool)
        self.max_cached_blocks = int(max_cached_blocks)
        self._map: dict = {}          # hash -> block id (full blocks only)
        self._block_hash: dict = {}   # block id -> hash (inverse)
        self._ref: dict = {}          # block id -> refcount (live blocks)
        self._lru: OrderedDict = OrderedDict()  # refcount-0 cached blocks
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    # ------------------------------------------------------------ queries
    @property
    def cached_blocks(self) -> int:
        """Blocks currently addressable by content hash."""
        return len(self._map)

    @property
    def referenced_blocks(self) -> int:
        """Hash-mapped blocks some live request still references. Mapped
        refcount-0 blocks are exactly the LRU members (release parks
        them there, ref() removes them, eviction drops both sides), so
        this is O(1) — it runs in the pool gauges on every admission and
        finish."""
        return len(self._map) - len(self._lru)

    @property
    def evictable(self) -> int:
        return len(self._lru)

    @property
    def available(self) -> int:
        """Blocks an admission could obtain: free list + evictable LRU."""
        return self.allocator.available + len(self._lru)

    def refcount(self, block_id: int) -> int:
        return self._ref.get(int(block_id), 0)

    # ------------------------------------------------------------- alloc
    def allocate(self, n: int):
        """All-or-nothing like BlockAllocator.alloc, but refcount-0 cached
        blocks count as capacity: when the free list can't cover, LRU
        blocks are evicted (hash entries dropped) to make room. Returns
        private block ids at refcount 1, or None."""
        self.contract.check("allocate")
        n = int(n)
        if n < 0:
            raise ValueError(f"negative block count {n}")
        if n > self.available:
            return None
        while self.allocator.available < n:
            self._evict_one()
        ids = self.allocator.alloc(n)
        for b in ids:
            self._ref[b] = 1
        return ids

    def _evict_one(self):
        blk, _ = self._lru.popitem(last=False)      # least recently used
        h = self._block_hash.pop(blk)
        del self._map[h]
        self._ref.pop(blk, None)
        self.allocator.free([blk])
        self.evictions += 1

    # ------------------------------------------------------------ lookup
    def lookup(self, hashes) -> list:
        """Longest cached prefix of `hashes`: consecutive from block 0.
        Found blocks get a refcount bump (and leave the LRU — a
        referenced block is never eviction-eligible). Counts hits for the
        found run and misses for the remainder."""
        self.contract.check("lookup")
        found = []
        for h in hashes:
            blk = self._map.get(h)
            if blk is None:
                break
            self.ref(blk)
            found.append(blk)
        self.hits += len(found)
        self.misses += len(hashes) - len(found)
        return found

    def ref(self, block_id: int) -> None:
        blk = int(block_id)
        self._ref[blk] = self._ref.get(blk, 0) + 1
        self._lru.pop(blk, None)

    def cancel_lookup(self, found, n_hashes: int) -> None:
        """Undo a lookup whose admission could not proceed (pool full):
        releases the refs it took and rolls the hit/miss counters back so
        blocked retries don't inflate the hit rate."""
        self.hits -= len(found)
        self.misses -= int(n_hashes) - len(found)
        self.release(found)

    # ---------------------------------------------------------- register
    def register(self, hashes, block_ids) -> None:
        """Publish computed full blocks under their content hashes (zip of
        parallel lists). A hash already mapped to a DIFFERENT block keeps
        the existing mapping (two concurrent misses computed the same
        content; the newer copy stays private and free-lists on release).
        Idempotent for already-registered pairs."""
        self.contract.check("register")
        for h, blk in zip(hashes, block_ids):
            blk = int(blk)
            if h in self._map:
                continue
            old_h = self._block_hash.get(blk)
            if old_h is not None and old_h != h:
                # the block's content moved on (it was extended past the
                # originally registered run) — rekey it
                del self._map[old_h]
            self._map[h] = blk
            self._block_hash[blk] = h

    # ------------------------------------------------------------ release
    def release(self, block_ids) -> None:
        """Decref each block; at refcount 0 a hash-mapped block parks in
        the LRU (release-to-cache) and an unmapped block free-lists. THE
        round-13 sharing contract: finish/timeout paths must come through
        here — an unconditional allocator.free() on a shared block would
        corrupt every other request pointing at it."""
        self.contract.check("release")
        for blk in block_ids:
            blk = int(blk)
            refs = self._ref.get(blk, 0)
            if refs <= 0:
                raise ValueError(f"release of unreferenced block {blk}")
            if refs > 1:
                self._ref[blk] = refs - 1
                continue
            del self._ref[blk]
            if blk in self._block_hash:
                self._lru[blk] = None
                self._lru.move_to_end(blk)
                self._trim()
            else:
                self.allocator.free([blk])

    def _trim(self):
        if self.max_cached_blocks <= 0:
            return
        while len(self._lru) > self.max_cached_blocks:
            self._evict_one()


class PagedKVCache:
    """The pooled cache arrays for every layer of one model.

    dtype: the storage mode ("int8" adds per-(layer, block) f32 scale
    arrays; "int4" additionally packs two tokens per byte along the
    block_size axis, halving the cache's HBM footprint again; anything
    else stores k/v directly). Arrays start zeroed —
    freshly (re)allocated blocks may hold stale data from a finished
    request, which is fine: reads are bounded by per-sequence lengths and
    appends overwrite before the length mask ever exposes a slot.

    THREAD CONTRACT (D15): single-owner like the engine — the ``k``/``v``
    array handles are replaced functionally by the owner thread's step
    programs through :meth:`swap` (the one sanctioned python-side
    mutation point, contract-checked); the driving engine shares its
    ThreadContract here."""

    #: D15 static marker: methods the single-owner contract guards
    _thread_contract = ("swap",)

    def __init__(self, num_layers: int, num_blocks: int, num_kv_heads: int,
                 block_size: int, head_dim: int, dtype):
        self.contract = ThreadContract("PagedKVCache")
        if int(block_size) % 8:
            raise ValueError(
                f"kv block_size {block_size} must be a multiple of 8 "
                "(sublane alignment of the (block_size, head_dim) tile)")
        self.num_layers = int(num_layers)
        self.num_blocks = int(num_blocks)
        self.num_kv_heads = int(num_kv_heads)
        self.block_size = int(block_size)
        self.head_dim = int(head_dim)
        #: "model" | "int8" | "int4" — int4 stores int8 ARRAYS too (two
        #: tokens per byte along the block_size axis), so mode, not the
        #: array dtype, is what callers key programs/namespaces on
        self.mode = str(dtype) if str(dtype) in ("int8", "int4") else "model"
        self.quantized = self.mode != "model"
        self.dtype = jnp.int8 if self.quantized else dtype
        tok = self.block_size
        if self.mode == "int4":
            # split-half packed along the token axis: byte t holds token t
            # (low nibble) and token bs/2 + t (high nibble); block_size is
            # a multiple of 8, so the halves are exact
            tok = self.block_size // 2
        self.stored_block_size = tok
        shape = (self.num_layers, self.num_blocks, self.num_kv_heads,
                 tok, self.head_dim)
        self.k = jnp.zeros(shape, self.dtype)
        self.v = jnp.zeros(shape, self.dtype)
        if self.quantized:
            self.k_scale = jnp.full((self.num_layers, self.num_blocks),
                                    1e-8, jnp.float32)
            self.v_scale = jnp.full((self.num_layers, self.num_blocks),
                                    1e-8, jnp.float32)
        else:
            self.k_scale = self.v_scale = None

    def swap(self, k, v, k_scale=None, v_scale=None):
        """Install the updated cache buffers a step program returned —
        the only sanctioned python-side mutation of the pool handles
        (donated inputs mean the OLD handles are dead the moment the
        program ran, so a second thread racing this swap would publish
        a deleted buffer)."""
        self.contract.check("swap")
        self.k, self.v = k, v
        self.k_scale, self.v_scale = k_scale, v_scale

    @property
    def hbm_bytes(self) -> int:
        per = int(np.prod(self.k.shape)) * self.k.dtype.itemsize
        scales = 0 if self.k_scale is None else 2 * int(
            np.prod(self.k_scale.shape)) * 4
        return 2 * per + scales


# ---------------------------------------------------- in-program updates
# All functions below are pure jnp and run inside the compiled step
# programs; `cache`/`scale` arguments are ONE layer's slice
# ([num_blocks, H_kv, block_size, D] / [num_blocks]).

def append_token(cache, kv, block_ids, offsets):
    """Scatter one token per slot: kv [B, H_kv, D] written at
    (block_ids[b], :, offsets[b]). Padded slots route block_ids to the
    trash block; duplicate trash destinations are harmless."""
    return cache.at[block_ids, :, offsets].set(kv.astype(cache.dtype))


def append_token_int8(cache, scale, kv, block_ids, offsets):
    """Int8 append with per-block requantization: the touched block is
    dequantized against its current scale, the new token inserted, a new
    scale taken over the VALID prefix (slots <= offset — stale tail
    entries never pollute it), and the whole block requantized. Returns
    (cache, scale)."""
    b = kv.shape[0]
    bs = cache.shape[2]
    old = cache[block_ids].astype(jnp.float32)          # [B, Hkv, bs, D]
    x = old * scale[block_ids][:, None, None, None]
    x = x.at[jnp.arange(b), :, offsets].set(kv.astype(jnp.float32))
    valid = (jnp.arange(bs)[None, :] <= offsets[:, None])  # [B, bs]
    amax = jnp.max(jnp.abs(x) * valid[:, None, :, None], axis=(1, 2, 3))
    new_scale = jnp.maximum(amax / 127.0, 1e-8)          # [B]
    q8 = jnp.clip(jnp.round(x / new_scale[:, None, None, None]),
                  -127, 127).astype(jnp.int8)
    return (cache.at[block_ids].set(q8),
            scale.at[block_ids].set(new_scale))


def _prefill_pages(ks, true_len, table_row, block_size):
    """Shared prefill-scatter prep: ks [L, S, H_kv, D] (S a multiple of
    block_size) -> per-page tiles [L, P_b, H_kv, bs, D] plus destination
    block ids [P_b] (invalid pages -> trash) and a per-token validity
    mask [P_b, bs]."""
    l, s, hkv, d = ks.shape
    bs = int(block_size)
    p_b = s // bs
    tiles = jnp.swapaxes(ks.reshape(l, p_b, bs, hkv, d), 2, 3)
    page_valid = (jnp.arange(p_b) * bs) < true_len
    dest = jnp.where(page_valid, table_row[:p_b], TRASH_BLOCK)
    tok_valid = (jnp.arange(p_b)[:, None] * bs
                 + jnp.arange(bs)[None, :]) < true_len   # [P_b, bs]
    return tiles, dest.astype(jnp.int32), tok_valid


def scatter_prefill(cache, ks, true_len, table_row, block_size):
    """Write a whole prompt's K (or V) into its pages in one scatter.
    ks [L, S, H_kv, D]; positions >= true_len land in the trash block."""
    tiles, dest, _ = _prefill_pages(ks, true_len, table_row, block_size)
    return cache.at[:, dest].set(tiles.astype(cache.dtype))


def scatter_prefill_int8(cache, scale, ks, true_len, table_row,
                         block_size):
    """Int8 prefill scatter: one scale per (layer, page) over the page's
    valid tokens, whole-page requantized write. Returns (cache, scale)."""
    tiles, dest, tok_valid = _prefill_pages(ks, true_len, table_row,
                                            block_size)
    tf = tiles.astype(jnp.float32)                 # [L, P_b, Hkv, bs, D]
    amax = jnp.max(jnp.abs(tf) * tok_valid[None, :, None, :, None],
                   axis=(2, 3, 4))                 # [L, P_b]
    new_scale = jnp.maximum(amax / 127.0, 1e-8)
    q8 = jnp.clip(jnp.round(tf / new_scale[:, :, None, None, None]),
                  -127, 127).astype(jnp.int8)
    return (cache.at[:, dest].set(q8),
            scale.at[:, dest].set(new_scale))


# ------------------------------------------------ chunked-prefill updates
# One LAYER's cache slice, like the decode appends above — these run
# inside the chunk-prefill program's layer scan. Unlike scatter_prefill
# the chunk's first position is NOT page-aligned (a prefix-cache hit can
# start a suffix mid-block after copy-on-write), so the scatter is
# token-granular: position p lands at (table_row[p // bs], p % bs).

def scatter_chunk(cache, ks, start, true_end, table_row, block_size):
    """Write one chunk's K (or V) through the block table. ks [C, H_kv, D]
    holds positions [start, start + C); positions >= true_end route to
    the trash block. cache is one layer's [num_blocks, H_kv, bs, D].

    Speculative verify windows (round 16) reuse this scatter with
    chunk = K+1 candidate tokens. Rollback of rejected candidates is
    NOT an erase: the host simply does not advance the slot's kv_len
    past the accepted prefix, so the stale-data contract above makes
    the rejected K/V unreachable (length masks bound every read), and
    the next window idempotently overwrites the same positions."""
    c = ks.shape[0]
    pos = start + jnp.arange(c)
    ok = pos < true_end
    page = jnp.clip(pos // block_size, 0, table_row.shape[0] - 1)
    blk = jnp.where(ok, table_row[page], TRASH_BLOCK)
    off = (pos % block_size).astype(jnp.int32)
    # dims 0 and 2 take advanced indices with a slice between, so the
    # update value keeps ks's own [C, H_kv, D] layout
    return cache.at[blk, :, off].set(ks.astype(cache.dtype))


def scatter_chunk_int8(cache, scale, ks, start, true_end, table_row,
                       block_size):
    """Int8 chunk scatter: every page the chunk touches is dequantized
    against its current scale (pre-existing content — earlier chunks, a
    copy-on-write prefix — survives), the chunk tokens inserted, and the
    page requantized over its valid prefix (positions < true_end).
    Returns (cache, scale)."""
    c = ks.shape[0]
    bs = int(block_size)
    # a chunk starting mid-block spans up to ceil(c/bs)+1 pages (worst
    # case: start offset bs-1) — c//bs+1 under-counts whenever c % bs
    # and the spilled tokens would silently route to the drop index
    p_t = -(-c // bs) + 1                      # pages a C-chunk can span
    page0 = start // bs
    pages = page0 + jnp.arange(p_t)
    page_ok = (pages * bs < true_end) & (pages < table_row.shape[0])
    dest = jnp.where(page_ok,
                     table_row[jnp.clip(pages, 0, table_row.shape[0] - 1)],
                     TRASH_BLOCK).astype(jnp.int32)
    old = cache[dest].astype(jnp.float32) \
        * scale[dest][:, None, None, None]     # [P_t, Hkv, bs, D]
    pos = start + jnp.arange(c)
    ok = pos < true_end
    tok_page = jnp.where(ok, pos // bs - page0, p_t)   # OOB -> dropped
    off = (pos % bs).astype(jnp.int32)
    old = old.at[tok_page, :, off].set(ks.astype(jnp.float32),
                                       mode="drop")
    valid = (pages[:, None] * bs + jnp.arange(bs)[None, :]) < true_end
    amax = jnp.max(jnp.abs(old) * valid[:, None, :, None], axis=(1, 2, 3))
    new_scale = jnp.maximum(amax / 127.0, 1e-8)        # [P_t]
    q8 = jnp.clip(jnp.round(old / new_scale[:, None, None, None]),
                  -127, 127).astype(jnp.int8)
    return (cache.at[dest].set(q8), scale.at[dest].set(new_scale))


# -------------------------------------------------------- int4-KV updates
# Same contracts as the int8 variants above, with the block's tokens stored
# two-per-byte along the block_size axis (split-half: byte t holds token t
# in the low nibble, token bs/2 + t in the high nibble — ops/quantized's
# axis-generic rule). Every update dequantizes the touched block (unpack +
# scale), edits at FULL block_size resolution, requantizes over the valid
# prefix against the -7..7 range, and repacks — so a block's scale always
# covers exactly its valid tokens, like int8.

def _unpack_block(packed, bs):
    """[..., bs/2, D] packed int8 -> [..., bs, D] int4 values (int8)."""
    return int4_unpack(packed, bs, axis=-2)


def _requant_pack_int4(x, new_scale, lead_dims):
    """Quantize a dequantized block tensor x [..., bs, D] against
    per-block scales (broadcast over `lead_dims` leading axes) and repack
    to [..., bs/2, D] int8."""
    s = new_scale.reshape(new_scale.shape + (1,) * (x.ndim - lead_dims))
    q = jnp.clip(jnp.round(x / s), -INT4_QMAX, INT4_QMAX).astype(jnp.int8)
    return int4_pack(q, axis=-2)


def append_token_int4(cache, scale, kv, block_ids, offsets):
    """Int4 decode append: dequantize (unpack + scale) the touched block,
    insert the new token, rescale over the valid prefix, requantize and
    REPACK. cache [N, Hkv, bs/2, D] int8-packed; returns (cache, scale)."""
    b = kv.shape[0]
    bs = cache.shape[2] * 2
    old = _unpack_block(cache[block_ids], bs).astype(jnp.float32)
    x = old * scale[block_ids][:, None, None, None]     # [B, Hkv, bs, D]
    x = x.at[jnp.arange(b), :, offsets].set(kv.astype(jnp.float32))
    valid = (jnp.arange(bs)[None, :] <= offsets[:, None])  # [B, bs]
    amax = jnp.max(jnp.abs(x) * valid[:, None, :, None], axis=(1, 2, 3))
    new_scale = jnp.maximum(amax / INT4_QMAX, 1e-8)      # [B]
    packed = _requant_pack_int4(x, new_scale, 1)
    return (cache.at[block_ids].set(packed),
            scale.at[block_ids].set(new_scale))


def scatter_prefill_int4(cache, scale, ks, true_len, table_row,
                         block_size):
    """Int4 prefill scatter: one scale per (layer, page) over the page's
    valid tokens, whole-page requantized + packed write. Returns
    (cache, scale)."""
    tiles, dest, tok_valid = _prefill_pages(ks, true_len, table_row,
                                            block_size)
    tf = tiles.astype(jnp.float32)                 # [L, P_b, Hkv, bs, D]
    amax = jnp.max(jnp.abs(tf) * tok_valid[None, :, None, :, None],
                   axis=(2, 3, 4))                 # [L, P_b]
    new_scale = jnp.maximum(amax / INT4_QMAX, 1e-8)
    packed = _requant_pack_int4(tf, new_scale, 2)
    return (cache.at[:, dest].set(packed),
            scale.at[:, dest].set(new_scale))


def scatter_chunk_int4(cache, scale, ks, start, true_end, table_row,
                       block_size):
    """Int4 chunk scatter: every page the chunk touches is dequantized
    (unpack + scale — pre-existing content survives), the chunk tokens
    inserted at full resolution, and the page requantized over its valid
    prefix and repacked. Same page window as int8: a chunk starting
    mid-block spans up to ceil(c/bs)+1 pages. Returns (cache, scale)."""
    c = ks.shape[0]
    bs = int(block_size)
    p_t = -(-c // bs) + 1                      # pages a C-chunk can span
    page0 = start // bs
    pages = page0 + jnp.arange(p_t)
    page_ok = (pages * bs < true_end) & (pages < table_row.shape[0])
    dest = jnp.where(page_ok,
                     table_row[jnp.clip(pages, 0, table_row.shape[0] - 1)],
                     TRASH_BLOCK).astype(jnp.int32)
    old = _unpack_block(cache[dest], bs).astype(jnp.float32) \
        * scale[dest][:, None, None, None]     # [P_t, Hkv, bs, D]
    pos = start + jnp.arange(c)
    ok = pos < true_end
    tok_page = jnp.where(ok, pos // bs - page0, p_t)   # OOB -> dropped
    off = (pos % bs).astype(jnp.int32)
    old = old.at[tok_page, :, off].set(ks.astype(jnp.float32),
                                       mode="drop")
    valid = (pages[:, None] * bs + jnp.arange(bs)[None, :]) < true_end
    amax = jnp.max(jnp.abs(old) * valid[:, None, :, None], axis=(1, 2, 3))
    new_scale = jnp.maximum(amax / INT4_QMAX, 1e-8)    # [P_t]
    packed = _requant_pack_int4(old, new_scale, 1)
    return (cache.at[dest].set(packed), scale.at[dest].set(new_scale))


def gather_context(cache, scale, table_row, ctx_pages, int4=False):
    """One layer's context K (or V) for chunk attention: the first
    `ctx_pages` table entries gathered to [ctx_pages * bs, H_kv, D]
    (dequantized when `scale` is given; `int4=True` additionally unpacks
    the token axis first). Unwritten/trash pages surface garbage that the
    caller's `kv_pos <= q_pos` mask never attends."""
    tiles = cache[table_row[:ctx_pages]]       # [P, Hkv, bs(/2), D]
    if int4:
        tiles = _unpack_block(tiles, tiles.shape[2] * 2)
    if scale is not None:
        tiles = tiles.astype(jnp.float32) \
            * scale[table_row[:ctx_pages]][:, None, None, None]
    p, hkv, bs, d = tiles.shape
    return jnp.swapaxes(tiles, 1, 2).reshape(p * bs, hkv, d)
