"""Block-paged KV cache: the allocator + the pure cache-update rules.

Reference parity: the block-table KV management behind
block_multihead_attention (fusion/gpu/block_multi_head_attention_kernel.cu)
— PagedAttention's (Kwon et al.) block-granular allocation, so a serving
engine's HBM footprint tracks the TOKENS ACTUALLY HELD rather than
max_len * max_batch.

Pieces:
  * `BlockAllocator` — host-side free list over a fixed block pool.
    Block 0 is the reserved TRASH block: every in-program write whose
    destination must be masked out (padded prefill positions, padded
    decode slots) is routed there instead of carrying a scatter mask —
    copy-free release is then trivial (free the ids; nothing is zeroed,
    stale contents are never attended to because the length mask bounds
    every read and appends overwrite before reads reach them).
  * `PagedKVCache` — the device arrays: `[L, num_blocks, H_kv,
    block_size, D]` per k/v (layer axis outermost so the per-step
    program's `lax.scan` over stacked layer weights threads the matching
    cache slice), plus per-(layer, block) f32 scales when the storage
    dtype is int8.
  * pure jnp functions used INSIDE the compiled step programs: decode
    append (scatter one token per slot through the block table) and
    prefill scatter (page-granular), each with an int8 variant that
    requantizes the touched block against its per-block scale.

Static shapes everywhere: block tables are padded [slots, pages] arrays,
the trash block absorbs masked writes, and the allocator is the only
dynamic piece — it lives on the host and never enters a trace.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

#: block id 0 is never allocated — masked writes land there (see module doc)
TRASH_BLOCK = 0


class BlockAllocator:
    """Free-list allocator over `num_blocks` cache blocks (block 0
    reserved as trash). Allocation is all-or-nothing: a request either
    gets its full block budget up front (admission control) or stays
    queued — no mid-flight OOM/preemption."""

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (block 0 is the trash block)")
        self.num_blocks = int(num_blocks)
        self._free = list(range(self.num_blocks - 1, 0, -1))  # pop() -> 1..

    @property
    def available(self) -> int:
        return len(self._free)

    def alloc(self, n: int):
        """n block ids, or None when the pool can't cover them."""
        if n < 0:
            raise ValueError(f"negative block count {n}")
        if n > len(self._free):
            return None
        return [self._free.pop() for _ in range(n)]

    def free(self, ids) -> None:
        for b in ids:
            b = int(b)
            if not 0 < b < self.num_blocks:
                raise ValueError(f"freeing invalid block id {b}")
            if b in self._free:
                raise ValueError(f"double free of block {b}")
            self._free.append(b)


def blocks_for(tokens: int, block_size: int) -> int:
    """Blocks needed to hold `tokens` cache entries."""
    return -(-int(tokens) // int(block_size))


class PagedKVCache:
    """The pooled cache arrays for every layer of one model.

    dtype: the storage dtype ("int8" adds per-(layer, block) f32 scale
    arrays; anything else stores k/v directly). Arrays start zeroed —
    freshly (re)allocated blocks may hold stale data from a finished
    request, which is fine: reads are bounded by per-sequence lengths and
    appends overwrite before the length mask ever exposes a slot."""

    def __init__(self, num_layers: int, num_blocks: int, num_kv_heads: int,
                 block_size: int, head_dim: int, dtype):
        if int(block_size) % 8:
            raise ValueError(
                f"kv block_size {block_size} must be a multiple of 8 "
                "(sublane alignment of the (block_size, head_dim) tile)")
        self.num_layers = int(num_layers)
        self.num_blocks = int(num_blocks)
        self.num_kv_heads = int(num_kv_heads)
        self.block_size = int(block_size)
        self.head_dim = int(head_dim)
        self.quantized = str(dtype) == "int8"
        self.dtype = jnp.int8 if self.quantized else dtype
        shape = (self.num_layers, self.num_blocks, self.num_kv_heads,
                 self.block_size, self.head_dim)
        self.k = jnp.zeros(shape, self.dtype)
        self.v = jnp.zeros(shape, self.dtype)
        if self.quantized:
            self.k_scale = jnp.full((self.num_layers, self.num_blocks),
                                    1e-8, jnp.float32)
            self.v_scale = jnp.full((self.num_layers, self.num_blocks),
                                    1e-8, jnp.float32)
        else:
            self.k_scale = self.v_scale = None

    @property
    def hbm_bytes(self) -> int:
        per = int(np.prod(self.k.shape)) * self.k.dtype.itemsize
        scales = 0 if self.k_scale is None else 2 * int(
            np.prod(self.k_scale.shape)) * 4
        return 2 * per + scales


# ---------------------------------------------------- in-program updates
# All functions below are pure jnp and run inside the compiled step
# programs; `cache`/`scale` arguments are ONE layer's slice
# ([num_blocks, H_kv, block_size, D] / [num_blocks]).

def append_token(cache, kv, block_ids, offsets):
    """Scatter one token per slot: kv [B, H_kv, D] written at
    (block_ids[b], :, offsets[b]). Padded slots route block_ids to the
    trash block; duplicate trash destinations are harmless."""
    return cache.at[block_ids, :, offsets].set(kv.astype(cache.dtype))


def append_token_int8(cache, scale, kv, block_ids, offsets):
    """Int8 append with per-block requantization: the touched block is
    dequantized against its current scale, the new token inserted, a new
    scale taken over the VALID prefix (slots <= offset — stale tail
    entries never pollute it), and the whole block requantized. Returns
    (cache, scale)."""
    b = kv.shape[0]
    bs = cache.shape[2]
    old = cache[block_ids].astype(jnp.float32)          # [B, Hkv, bs, D]
    x = old * scale[block_ids][:, None, None, None]
    x = x.at[jnp.arange(b), :, offsets].set(kv.astype(jnp.float32))
    valid = (jnp.arange(bs)[None, :] <= offsets[:, None])  # [B, bs]
    amax = jnp.max(jnp.abs(x) * valid[:, None, :, None], axis=(1, 2, 3))
    new_scale = jnp.maximum(amax / 127.0, 1e-8)          # [B]
    q8 = jnp.clip(jnp.round(x / new_scale[:, None, None, None]),
                  -127, 127).astype(jnp.int8)
    return (cache.at[block_ids].set(q8),
            scale.at[block_ids].set(new_scale))


def _prefill_pages(ks, true_len, table_row, block_size):
    """Shared prefill-scatter prep: ks [L, S, H_kv, D] (S a multiple of
    block_size) -> per-page tiles [L, P_b, H_kv, bs, D] plus destination
    block ids [P_b] (invalid pages -> trash) and a per-token validity
    mask [P_b, bs]."""
    l, s, hkv, d = ks.shape
    bs = int(block_size)
    p_b = s // bs
    tiles = jnp.swapaxes(ks.reshape(l, p_b, bs, hkv, d), 2, 3)
    page_valid = (jnp.arange(p_b) * bs) < true_len
    dest = jnp.where(page_valid, table_row[:p_b], TRASH_BLOCK)
    tok_valid = (jnp.arange(p_b)[:, None] * bs
                 + jnp.arange(bs)[None, :]) < true_len   # [P_b, bs]
    return tiles, dest.astype(jnp.int32), tok_valid


def scatter_prefill(cache, ks, true_len, table_row, block_size):
    """Write a whole prompt's K (or V) into its pages in one scatter.
    ks [L, S, H_kv, D]; positions >= true_len land in the trash block."""
    tiles, dest, _ = _prefill_pages(ks, true_len, table_row, block_size)
    return cache.at[:, dest].set(tiles.astype(cache.dtype))


def scatter_prefill_int8(cache, scale, ks, true_len, table_row,
                         block_size):
    """Int8 prefill scatter: one scale per (layer, page) over the page's
    valid tokens, whole-page requantized write. Returns (cache, scale)."""
    tiles, dest, tok_valid = _prefill_pages(ks, true_len, table_row,
                                            block_size)
    tf = tiles.astype(jnp.float32)                 # [L, P_b, Hkv, bs, D]
    amax = jnp.max(jnp.abs(tf) * tok_valid[None, :, None, :, None],
                   axis=(2, 3, 4))                 # [L, P_b]
    new_scale = jnp.maximum(amax / 127.0, 1e-8)
    q8 = jnp.clip(jnp.round(tf / new_scale[:, :, None, None, None]),
                  -127, 127).astype(jnp.int8)
    return (cache.at[:, dest].set(q8),
            scale.at[:, dest].set(new_scale))
