"""GPT decoder (≙ BASELINE.json config-4: GPT-3-medium, DP + sharding-2).

Pre-norm GPT-2/3 style: learned positions, LayerNorm, GELU MLP. Shares the
TP-aware layer selection with the LLaMA flagship.
"""
from __future__ import annotations

from dataclasses import dataclass

from ... import nn
from ...nn import functional as F
from .llama import _tp_layers


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 1024          # GPT-3 medium
    num_hidden_layers: int = 24
    num_attention_heads: int = 16
    intermediate_size: int | None = None
    max_position_embeddings: int = 2048
    layer_norm_eps: float = 1e-5
    tensor_parallel: bool = False
    sequence_parallel: bool = False

    def __post_init__(self):
        if self.intermediate_size is None:
            self.intermediate_size = 4 * self.hidden_size


class GPTAttention(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.num_heads = config.num_attention_heads
        self.head_dim = config.hidden_size // config.num_attention_heads
        col, row, _ = _tp_layers(config)
        h = config.hidden_size
        self.qkv_proj = col(h, 3 * h)
        self.out_proj = row(h, h)
        # declarative-partitioner logical axes (distributed/partitioner);
        # the fused qkv out-dim is 3*heads*head_dim — still head-granular
        self.qkv_proj.shard_annotate(weight=("embed", "heads"))
        self.out_proj.shard_annotate(weight=("heads", "embed"))
        if getattr(self.qkv_proj, "bias", None) is not None:
            self.qkv_proj.shard_annotate(bias=("heads",))
        if getattr(self.out_proj, "bias", None) is not None:
            self.out_proj.shard_annotate(bias=("norm",))

    def forward(self, x):
        b, s, h = x.shape
        qkv = self.qkv_proj(x).reshape([b, s, 3, self.num_heads, self.head_dim])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        o = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        return self.out_proj(o.reshape([b, s, h]))


class GPTBlock(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        col, row, _ = _tp_layers(config)
        self.ln_1 = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        self.attn = GPTAttention(config)
        self.ln_2 = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        self.fc_in = col(config.hidden_size, config.intermediate_size)
        self.fc_out = row(config.intermediate_size, config.hidden_size)
        self.fc_in.shard_annotate(weight=("embed", "mlp"))
        self.fc_out.shard_annotate(weight=("mlp", "embed"))
        if getattr(self.fc_in, "bias", None) is not None:
            self.fc_in.shard_annotate(bias=("mlp",))
        if getattr(self.fc_out, "bias", None) is not None:
            self.fc_out.shard_annotate(bias=("norm",))

    def forward(self, x):
        a = self.attn(self.ln_1(x))
        # post-attention residual add fused into the LN kernel (one HBM
        # pass on TPU; identical math off it) — see llama.LlamaDecoderLayer
        y, x = self.ln_2.forward_fused_add(a, x)
        x = x + self.fc_out(F.gelu(self.fc_in(y)))
        return x


class GPTForCausalLM(nn.Layer):
    _gen_arch = "gpt"  # generation-engine layout (text/generation.py)
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        _, _, emb = _tp_layers(config)
        self.wte = emb(config.vocab_size, config.hidden_size)
        self.wpe = nn.Embedding(config.max_position_embeddings, config.hidden_size)
        self.blocks = nn.LayerList([GPTBlock(config)
                                    for _ in range(config.num_hidden_layers)])
        self.ln_f = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        if config.tensor_parallel:
            from ...distributed.meta_parallel.mp_layers import ColumnParallelLinear

            self.lm_head = ColumnParallelLinear(
                config.hidden_size, config.vocab_size, has_bias=False,
                gather_output=True)
        else:
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size,
                                     bias_attr=False)
        self.wte.shard_annotate(weight=("vocab", "embed"))
        self.wpe.shard_annotate(weight=("pos", "embed"))
        self.lm_head.shard_annotate(weight=("embed", "vocab"))

    def forward(self, input_ids, labels=None):
        import paddle_tpu as paddle

        from ._policy import _cast_residual

        s = input_ids.shape[1]
        pos = paddle.arange(s, dtype="int64").unsqueeze(0)
        x = _cast_residual(self.wte(input_ids) + self.wpe(pos))
        for blk in self.blocks:
            x = blk(x)
        hidden = self.ln_f(x)
        if labels is not None and not self.config.tensor_parallel and \
                self.config.vocab_size >= 4096:
            # fused lm_head+CE — 50304 has no usable multiple-of-128 vocab
            # divisor, so this takes the TOKEN-chunked path (round 6):
            # full-vocab GEMM per token slice, [tokens, 50304] logits never
            # materialized (the plain path below spends ~412 MB of f32
            # logits traffic per direction at b4 s1024)
            from ...incubate.nn.functional import fused_linear_cross_entropy

            return fused_linear_cross_entropy(
                hidden, self.lm_head.weight, labels, chunk_size=8192)
        logits = self.lm_head(hidden)
        if labels is not None:
            return F.cross_entropy(logits.reshape([-1, self.config.vocab_size]),
                                   labels.reshape([-1]), reduction="mean")
        return logits

    def generate(self, input_ids, max_new_tokens=32, max_length=None,
                 do_sample=False, temperature=1.0, top_k=0, top_p=1.0,
                 eos_token_id=None, seed=None, engine="static",
                 prefix_cache=None, spec_decode=None, weight_quant="none"):
        """KV-cached decoding (see text/generation.py; gpt arch: LayerNorm
        + learned positions + fused-qkv pre-LN blocks). engine="static":
        one compiled XLA program; engine="paged": the continuous-batching
        paged-KV serving engine (inference/engine.py; `prefix_cache`
        overrides FLAGS_prefix_cache there). weight_quant="int8"/"int4"
        serves weight-only-quantized matmuls (round 20: int4 is true
        packed storage)."""
        from ..generation import generate as _generate

        return _generate(self, input_ids, max_new_tokens=max_new_tokens,
                         max_length=max_length, do_sample=do_sample,
                         temperature=temperature, top_k=top_k, top_p=top_p,
                         eos_token_id=eos_token_id, seed=seed,
                         engine=engine, prefix_cache=prefix_cache,
                         spec_decode=spec_decode,
                         weight_quant=weight_quant)
