from .llama import (
    LlamaConfig,
    LlamaForCausalLM,
    LlamaModel,
    llama_7b_config,
    llama_tiny_config,
)
from .bert import BertConfig, BertForSequenceClassification, BertModel
from .gpt import GPTConfig, GPTForCausalLM

__all__ = [n for n in dir() if not n.startswith("_")]
