"""Model-agnostic dtype policies shared by the text models (round 8).

FLAGS_residual_dtype=bfloat16 keeps the LLaMA/GPT/BERT residual stream
(and the rope tables that would otherwise poison the stream back to f32)
in bf16 between kernels; f32 lives only inside the norm kernels'
accumulation. ONE definition here so the three models can never drift.
"""
from __future__ import annotations


def _residual_dtype():
    """'bfloat16' when the bf16 residual-stream policy is on, else None
    (f32 stream, the default)."""
    from ...core.flags import flag

    v = str(flag("FLAGS_residual_dtype")).lower()
    return "bfloat16" if v in ("bf16", "bfloat16") else None


def _cast_residual(x):
    rd = _residual_dtype()
    if rd is not None and str(x.dtype) != rd:
        return x.astype(rd)
    return x
