"""LLaMA-family decoder — the flagship model of the framework.

Reference parity: the BASELINE.json config-5 workload (PaddleNLP LLaMA-7B
hybrid tp×pp×dp pretrain). The reference ecosystem implements the model with
fleet mpu layers + fused CUDA kernels (fusion inventory at
/root/reference/paddle/phi/kernels/fusion/); here the same architecture is
built TPU-first:

  - attention runs through F.scaled_dot_product_attention, whose fast path is
    the Pallas flash kernel (paddle_tpu/ops/pallas_attention.py) on TPU;
  - tensor parallelism = Column/Row/VocabParallelLinear layers storing FULL
    logical weights with NamedSharding over the `mp` mesh axis (GSPMD inserts
    the collectives Megatron codes by hand);
  - sequence parallelism = sharding annotations on the sequence dim
    (meta_parallel/sp_utils.py);
  - pipeline = `pipeline_descs()` emits LayerDesc chunks for PipelineLayer.

All matmuls are [B*S, H]-shaped and bf16-friendly for the MXU.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field

import numpy as np

from ... import nn
from ...amp import fp8
from ...core.tensor import Tensor
from ...nn import functional as F


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int | None = None
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    # parallelism switches (≙ PaddleNLP config knobs)
    tensor_parallel: bool = False
    sequence_parallel: bool = False
    use_recompute: bool = False  # ≙ recompute per block
    # "full": rematerialize the whole decoder block (max memory savings,
    # recomputes flash attention in backward). "mlp": keep attention
    # activations resident and rematerialize only the MLP — saves one flash
    # forward per layer in the backward at ~60 MB/layer extra residency.
    # "flash_resident": full-block remat under a jax.checkpoint policy that
    # keeps ONLY the flash-attention outputs + softmax stats resident
    # (~B·S·H bf16 per layer) while the qkv/o/MLP GEMM and pointwise chains
    # rematerialize — near-"full" memory at "mlp"-like backward cost; the
    # round-6 memory lever that unlocks flagship batch 4
    # (≙ PaddleNLP recompute_granularity full/full_attn/core_attn ladder)
    recompute_granularity: str = "full"

    def __post_init__(self):
        if self.num_key_value_heads is None:
            self.num_key_value_heads = self.num_attention_heads

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads


def llama_7b_config(**kw) -> LlamaConfig:
    return LlamaConfig(**kw)


def llama_tiny_config(**kw) -> LlamaConfig:
    base = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                num_hidden_layers=2, num_attention_heads=4,
                max_position_embeddings=128)
    base.update(kw)
    return LlamaConfig(**base)


from ._policy import _cast_residual, _residual_dtype  # noqa: E402

_ROPE_CACHE: dict = {}


def _rope_tables(seq_len: int, head_dim: int, theta: float, dtype="float32"):
    """Shared across layers: every LlamaAttention uses the SAME [1,S,1,D]
    cos/sin Tensors (one HBM copy, not num_layers copies)."""
    key = (seq_len, head_dim, theta, dtype)
    if key in _ROPE_CACHE:
        return _ROPE_CACHE[key]
    inv = 1.0 / (theta ** (np.arange(0, head_dim, 2, dtype=np.float64) / head_dim))
    t = np.arange(seq_len, dtype=np.float64)
    freqs = np.outer(t, inv)  # [S, D/2]
    emb = np.concatenate([freqs, freqs], axis=-1)  # [S, D]
    cos = Tensor(np.cos(emb)[None, :, None, :].astype(dtype), stop_gradient=True)
    sin = Tensor(np.sin(emb)[None, :, None, :].astype(dtype), stop_gradient=True)
    _ROPE_CACHE[key] = (cos, sin)
    return cos, sin


def _tp_layers(config: LlamaConfig):
    """Pick dense vs tensor-parallel linear/embedding classes."""
    if config.tensor_parallel:
        from ...distributed.meta_parallel.mp_layers import (
            ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding)

        col = lambda i, o: ColumnParallelLinear(i, o, has_bias=False,
                                                gather_output=False)
        row = lambda i, o: RowParallelLinear(i, o, has_bias=False,
                                             input_is_parallel=True)
        emb = lambda v, h: VocabParallelEmbedding(v, h)
        return col, row, emb
    col = lambda i, o: nn.Linear(i, o, bias_attr=False)
    row = lambda i, o: nn.Linear(i, o, bias_attr=False)
    emb = lambda v, h: nn.Embedding(v, h)
    return col, row, emb


class LlamaAttention(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.num_heads = config.num_attention_heads
        self.num_kv_heads = config.num_key_value_heads
        self.head_dim = config.head_dim
        col, row, _ = _tp_layers(config)
        h = config.hidden_size
        self.q_proj = col(h, self.num_heads * self.head_dim)
        self.k_proj = col(h, self.num_kv_heads * self.head_dim)
        self.v_proj = col(h, self.num_kv_heads * self.head_dim)
        self.o_proj = row(self.num_heads * self.head_dim, h)
        # declarative-partitioner logical axes (distributed/partitioner):
        # the rule table maps heads/kv -> tp and embed -> fsdp at
        # partition time; the hand-wired tensor_parallel path ignores it
        self.q_proj.shard_annotate(weight=("embed", "heads"))
        self.k_proj.shard_annotate(weight=("embed", "kv"))
        self.v_proj.shard_annotate(weight=("embed", "kv"))
        self.o_proj.shard_annotate(weight=("heads", "embed"))
        # rope tables are shared non-trainable buffers (one copy per process)
        self.cos, self.sin = _rope_tables(
            config.max_position_embeddings, self.head_dim, config.rope_theta)

    def forward(self, x, attn_mask=None):
        b, s, _ = x.shape
        # FLAGS_amp_fp8: the four attention GEMMs run e4m3-fwd/e5m2-bwd with
        # delayed per-site scaling (amp/fp8.py); rope/softmax/norms keep
        # their existing bf16/f32 policy
        if fp8.enabled():
            mm = fp8.linear
        else:
            mm = lambda lyr, t: lyr(t)
        q = mm(self.q_proj, x).reshape([b, s, self.num_heads, self.head_dim])
        k = mm(self.k_proj, x).reshape([b, s, self.num_kv_heads, self.head_dim])
        v = mm(self.v_proj, x).reshape([b, s, self.num_kv_heads, self.head_dim])
        cos = self.cos[:, :s]
        sin = self.sin[:, :s]
        rd = _residual_dtype()
        if rd is not None:
            # f32 rope tables would promote q/k (and everything downstream
            # of attention) back to f32 — the single biggest source of f32
            # elementwise traffic in the bf16 block (PERF.md round 8)
            cos = cos.astype(rd)
            sin = sin.astype(rd)
        q, k = F.rotary_position_embedding(q, k, cos, sin)
        out = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask,
                                             is_causal=True)
        out = out.reshape([b, s, self.num_heads * self.head_dim])
        return mm(self.o_proj, out)


class LlamaMLP(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        col, row, _ = _tp_layers(config)
        self.gate_proj = col(config.hidden_size, config.intermediate_size)
        self.up_proj = col(config.hidden_size, config.intermediate_size)
        self.down_proj = row(config.intermediate_size, config.hidden_size)
        self.gate_proj.shard_annotate(weight=("embed", "mlp"))
        self.up_proj.shard_annotate(weight=("embed", "mlp"))
        self.down_proj.shard_annotate(weight=("mlp", "embed"))

    def forward(self, x):
        if fp8.enabled():
            h = F.swiglu(fp8.linear(self.gate_proj, x),
                         fp8.linear(self.up_proj, x))
            return fp8.linear(self.down_proj, h)
        return self.down_proj(F.swiglu(self.gate_proj(x), self.up_proj(x)))


class LlamaDecoderLayer(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.self_attn = LlamaAttention(config)
        self.mlp = LlamaMLP(config)
        self.input_layernorm = nn.RMSNorm(config.hidden_size,
                                          epsilon=config.rms_norm_eps)
        self.post_attention_layernorm = nn.RMSNorm(config.hidden_size,
                                                   epsilon=config.rms_norm_eps)
        self.input_layernorm.shard_annotate(weight=("norm",))
        self.post_attention_layernorm.shard_annotate(weight=("norm",))

    def forward(self, x, attn_mask=None):
        if self.config.use_recompute and \
                self.config.recompute_granularity == "mlp":
            from ...distributed.fleet.utils import recompute

            x = x + self.self_attn(self.input_layernorm(x), attn_mask)
            x = x + recompute(self._mlp_branch, x)
            return x
        a = self.self_attn(self.input_layernorm(x), attn_mask)
        # residual add fused INTO the norm kernel: y = norm(x + a) and the
        # summed stream come out of ONE HBM pass (ops/pallas_norm.py);
        # exact same math as the x = x + a; norm(x) chain off-TPU
        y, x = self.post_attention_layernorm.forward_fused_add(a, x)
        x = x + self.mlp(y)
        return x

    def _mlp_branch(self, x):
        return self.mlp(self.post_attention_layernorm(x))


class LlamaModel(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        _, _, emb = _tp_layers(config)
        self.embed_tokens = emb(config.vocab_size, config.hidden_size)
        self.layers = nn.LayerList(
            [LlamaDecoderLayer(config) for _ in range(config.num_hidden_layers)])
        self.norm = nn.RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)
        self.embed_tokens.shard_annotate(weight=("vocab", "embed"))
        self.norm.shard_annotate(weight=("norm",))

    def forward(self, input_ids, attn_mask=None):
        x = _cast_residual(self.embed_tokens(input_ids))
        if self.config.sequence_parallel:
            # Megatron-SP: activations sequence-sharded between blocks
            # (meta_parallel/sp_utils.py ≙ sequence_parallel_utils.py:429,564)
            from ...distributed.meta_parallel.sp_utils import ScatterOp

            x = ScatterOp.apply(x, axis=1)
        gran = self.config.recompute_granularity if self.config.use_recompute \
            else None
        for layer in self.layers:
            if gran == "full":
                from ...distributed.fleet.utils import recompute

                x = recompute(layer, x, attn_mask)
            elif gran == "flash_resident":
                from ...distributed.fleet.utils import recompute

                x = recompute(layer, x, attn_mask, policy="flash_resident")
            else:
                x = layer(x, attn_mask)
        x = self.norm(x)
        if self.config.sequence_parallel:
            from ...distributed.meta_parallel.sp_utils import GatherOp

            x = GatherOp.apply(x, axis=1)
        return x


class LlamaForCausalLM(nn.Layer):
    _gen_arch = "llama"  # generation-engine layout (text/generation.py)
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.llama = self.model = LlamaModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None  # logits = hidden @ embed.weight^T
        elif config.tensor_parallel:
            from ...distributed.meta_parallel.mp_layers import ColumnParallelLinear

            self.lm_head = ColumnParallelLinear(
                config.hidden_size, config.vocab_size, has_bias=False,
                gather_output=True)
        else:
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size,
                                     bias_attr=False)
        if self.lm_head is not None:
            self.lm_head.shard_annotate(weight=("embed", "vocab"))

    def forward(self, input_ids, labels=None, attn_mask=None):
        import paddle_tpu as paddle

        hidden = self.model(input_ids, attn_mask)
        if labels is not None and self.lm_head is not None and \
                not self.config.tensor_parallel and \
                self.config.vocab_size >= 4096:
            # fused lm_head+CE: the [tokens, vocab] logits tensor is never
            # materialized (incubate/nn/functional/fused_loss.py) — the
            # memory-bound tail of the train step. The chunk axis follows
            # FLAGS_flce_chunk_axis: "auto" picks the vocab-chunked path
            # (32000 -> 6400) here and the token(sequence)-chunked path for
            # vocabs with no good divisor (GPT's 50304); the token chunk
            # size is the FLAGS_flce_token_chunk sweep knob
            # (tools/sweep_ce_chunk.py).
            from ...incubate.nn.functional import fused_linear_cross_entropy

            return fused_linear_cross_entropy(
                hidden, self.lm_head.weight, labels, chunk_size=8192)
        if self.lm_head is None:
            logits = paddle.matmul(hidden, self.model.embed_tokens.weight,
                                   transpose_y=True)
        else:
            logits = self.lm_head(hidden)
        if labels is not None:
            loss = F.cross_entropy(
                logits.reshape([-1, self.config.vocab_size]),
                labels.reshape([-1]), reduction="mean")
            return loss
        return logits

    def generate(self, input_ids, max_new_tokens=32, max_length=None,
                 do_sample=False, temperature=1.0, top_k=0, top_p=1.0,
                 eos_token_id=None, seed=None, weight_quant="none",
                 engine="static", prefix_cache=None, spec_decode=None):
        """KV-cached autoregressive decoding — the role of the reference's
        fused decode-attention family + PaddleNLP generate. engine="static"
        (default): ONE compiled XLA program (prefill + lax.scan decode
        loop, ≙ masked_multihead_attention's role; text/generation.py).
        engine="paged": the continuous-batching serving engine over the
        block-paged KV cache (≙ block_multihead_attention's role;
        inference/engine.py) — same greedy tokens, built for request
        streams; `prefix_cache` overrides FLAGS_prefix_cache there."""
        from ..generation import generate as _generate

        return _generate(self, input_ids, max_new_tokens=max_new_tokens,
                         max_length=max_length, do_sample=do_sample,
                         temperature=temperature, top_k=top_k, top_p=top_p,
                         eos_token_id=eos_token_id, seed=seed,
                         weight_quant=weight_quant, engine=engine,
                         prefix_cache=prefix_cache, spec_decode=spec_decode)


class _PipeEmbed(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        _, _, emb = _tp_layers(config)
        self.embed_tokens = emb(config.vocab_size, config.hidden_size)

    def forward(self, ids):
        return self.embed_tokens(ids)

    @property
    def weight(self):
        # SharedLayerDesc(shared_weight_attr="weight") resolves here
        return self.embed_tokens.weight


class _PipeHead(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.norm = nn.RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)
        if config.tensor_parallel:
            from ...distributed.meta_parallel.mp_layers import ColumnParallelLinear

            self.lm_head = ColumnParallelLinear(
                config.hidden_size, config.vocab_size, has_bias=False,
                gather_output=True)
        else:
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size,
                                     bias_attr=False)

    def forward(self, x):
        return self.lm_head(self.norm(x))


class _PipeNormOnly(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.norm = nn.RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)

    def forward(self, x):
        return self.norm(x)


def pipeline_descs(config: LlamaConfig):
    """LayerDesc list for PipelineLayer (≙ PaddleNLP LlamaForCausalLMPipe).

    With tie_word_embeddings the embedding appears in the first AND last
    stage as ONE SharedLayerDesc key — pp_layers builds a single instance,
    so tying and grad accumulation are automatic."""
    from ...distributed.meta_parallel.pp_layers import LayerDesc, SharedLayerDesc

    body = [LayerDesc(LlamaDecoderLayer, config)
            for _ in range(config.num_hidden_layers)]
    if config.tie_word_embeddings:
        import paddle_tpu as paddle

        def lm_head(x, w):
            return paddle.matmul(x, w, transpose_y=True)

        return ([SharedLayerDesc("embed", _PipeEmbed, config,
                                 shared_weight_attr="weight")]
                + body
                + [LayerDesc(_PipeNormOnly, config),
                   SharedLayerDesc("embed", _PipeEmbed, config,
                                   forward_func=lm_head,
                                   shared_weight_attr="weight")])
    return [LayerDesc(_PipeEmbed, config)] + body + [LayerDesc(_PipeHead, config)]
