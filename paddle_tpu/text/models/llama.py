"""LLaMA-family decoder — the flagship model of the framework.

Reference parity: the BASELINE.json config-5 workload (PaddleNLP LLaMA-7B
hybrid tp×pp×dp pretrain). The reference ecosystem implements the model with
fleet mpu layers + fused CUDA kernels (fusion inventory at
/root/reference/paddle/phi/kernels/fusion/); here the same architecture is
built TPU-first:

  - attention runs through F.scaled_dot_product_attention, whose fast path is
    the Pallas flash kernel (paddle_tpu/ops/pallas_attention.py) on TPU;
  - tensor parallelism = Column/Row/VocabParallelLinear layers storing FULL
    logical weights with NamedSharding over the `mp` mesh axis (GSPMD inserts
    the collectives Megatron codes by hand);
  - sequence parallelism = sharding annotations on the sequence dim
    (meta_parallel/sp_utils.py);
  - pipeline = `pipeline_descs()` emits LayerDesc chunks for PipelineLayer.

All matmuls are [B*S, H]-shaped and bf16-friendly for the MXU.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field

import numpy as np

from ... import nn
from ...core.tensor import Tensor
from ...nn import functional as F


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int | None = None
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    # parallelism switches (≙ PaddleNLP config knobs)
    tensor_parallel: bool = False
    sequence_parallel: bool = False
    use_recompute: bool = False  # ≙ recompute_granularity: jax.checkpoint per block

    def __post_init__(self):
        if self.num_key_value_heads is None:
            self.num_key_value_heads = self.num_attention_heads

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads


def llama_7b_config(**kw) -> LlamaConfig:
    return LlamaConfig(**kw)


def llama_tiny_config(**kw) -> LlamaConfig:
    base = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                num_hidden_layers=2, num_attention_heads=4,
                max_position_embeddings=128)
    base.update(kw)
    return LlamaConfig(**base)


def _rope_tables(seq_len: int, head_dim: int, theta: float, dtype="float32"):
    inv = 1.0 / (theta ** (np.arange(0, head_dim, 2, dtype=np.float64) / head_dim))
    t = np.arange(seq_len, dtype=np.float64)
    freqs = np.outer(t, inv)  # [S, D/2]
    emb = np.concatenate([freqs, freqs], axis=-1)  # [S, D]
    cos = np.cos(emb)[None, :, None, :].astype(dtype)  # [1, S, 1, D]
    sin = np.sin(emb)[None, :, None, :].astype(dtype)
    return cos, sin


def _tp_layers(config: LlamaConfig):
    """Pick dense vs tensor-parallel linear/embedding classes."""
    if config.tensor_parallel:
        from ...distributed.meta_parallel.mp_layers import (
            ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding)

        col = lambda i, o: ColumnParallelLinear(i, o, has_bias=False,
                                                gather_output=False)
        row = lambda i, o: RowParallelLinear(i, o, has_bias=False,
                                             input_is_parallel=True)
        emb = lambda v, h: VocabParallelEmbedding(v, h)
        return col, row, emb
    col = lambda i, o: nn.Linear(i, o, bias_attr=False)
    row = lambda i, o: nn.Linear(i, o, bias_attr=False)
    emb = lambda v, h: nn.Embedding(v, h)
    return col, row, emb


class LlamaAttention(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.num_heads = config.num_attention_heads
        self.num_kv_heads = config.num_key_value_heads
        self.head_dim = config.head_dim
        col, row, _ = _tp_layers(config)
        h = config.hidden_size
        self.q_proj = col(h, self.num_heads * self.head_dim)
        self.k_proj = col(h, self.num_kv_heads * self.head_dim)
        self.v_proj = col(h, self.num_kv_heads * self.head_dim)
        self.o_proj = row(self.num_heads * self.head_dim, h)
        cos, sin = _rope_tables(config.max_position_embeddings, self.head_dim,
                                config.rope_theta)
        # rope tables are non-trainable buffers
        self.cos = Tensor(cos, stop_gradient=True)
        self.sin = Tensor(sin, stop_gradient=True)

    def forward(self, x, attn_mask=None):
        b, s, _ = x.shape
        q = self.q_proj(x).reshape([b, s, self.num_heads, self.head_dim])
        k = self.k_proj(x).reshape([b, s, self.num_kv_heads, self.head_dim])
        v = self.v_proj(x).reshape([b, s, self.num_kv_heads, self.head_dim])
        cos = self.cos[:, :s]
        sin = self.sin[:, :s]
        q, k = F.rotary_position_embedding(q, k, cos, sin)
        out = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask,
                                             is_causal=True)
        out = out.reshape([b, s, self.num_heads * self.head_dim])
        return self.o_proj(out)


class LlamaMLP(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        col, row, _ = _tp_layers(config)
        self.gate_proj = col(config.hidden_size, config.intermediate_size)
        self.up_proj = col(config.hidden_size, config.intermediate_size)
        self.down_proj = row(config.intermediate_size, config.hidden_size)

    def forward(self, x):
        return self.down_proj(F.swiglu(self.gate_proj(x), self.up_proj(x)))


class LlamaDecoderLayer(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.self_attn = LlamaAttention(config)
        self.mlp = LlamaMLP(config)
        self.input_layernorm = nn.RMSNorm(config.hidden_size,
                                          epsilon=config.rms_norm_eps)
        self.post_attention_layernorm = nn.RMSNorm(config.hidden_size,
                                                   epsilon=config.rms_norm_eps)

    def forward(self, x, attn_mask=None):
        x = x + self.self_attn(self.input_layernorm(x), attn_mask)
        x = x + self.mlp(self.post_attention_layernorm(x))
        return x


class LlamaModel(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        _, _, emb = _tp_layers(config)
        self.embed_tokens = emb(config.vocab_size, config.hidden_size)
        self.layers = nn.LayerList(
            [LlamaDecoderLayer(config) for _ in range(config.num_hidden_layers)])
        self.norm = nn.RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)

    def forward(self, input_ids, attn_mask=None):
        x = self.embed_tokens(input_ids)
        for layer in self.layers:
            x = layer(x, attn_mask)
        return self.norm(x)


class LlamaForCausalLM(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.llama = self.model = LlamaModel(config)
        if config.tensor_parallel:
            from ...distributed.meta_parallel.mp_layers import ColumnParallelLinear

            self.lm_head = ColumnParallelLinear(
                config.hidden_size, config.vocab_size, has_bias=False,
                gather_output=True)
        else:
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size,
                                     bias_attr=False)

    def forward(self, input_ids, labels=None, attn_mask=None):
        hidden = self.model(input_ids, attn_mask)
        logits = self.lm_head(hidden)
        if labels is not None:
            loss = F.cross_entropy(
                logits.reshape([-1, self.config.vocab_size]),
                labels.reshape([-1]), reduction="mean")
            return loss
        return logits


def pipeline_descs(config: LlamaConfig):
    """LayerDesc list for PipelineLayer (≙ PaddleNLP LlamaForCausalLMPipe)."""
    from ...distributed.meta_parallel.pp_layers import LayerDesc, SharedLayerDesc

    _, _, emb_cls = _tp_layers(config)

    class _Embed(nn.Layer):
        def __init__(self):
            super().__init__()
            _, _, emb = _tp_layers(config)
            self.embed_tokens = emb(config.vocab_size, config.hidden_size)

        def forward(self, ids):
            return self.embed_tokens(ids)

    class _Head(nn.Layer):
        def __init__(self):
            super().__init__()
            self.norm = nn.RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size,
                                     bias_attr=False)

        def forward(self, x):
            return self.lm_head(self.norm(x))

    descs = [LayerDesc(_Embed)]
    descs += [LayerDesc(LlamaDecoderLayer, config)
              for _ in range(config.num_hidden_layers)]
    descs += [LayerDesc(_Head)]
    return descs
