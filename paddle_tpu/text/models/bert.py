"""BERT-base encoder (≙ BASELINE.json config-3: ERNIE-3.0 / BERT fine-tune).

Reference ecosystem implements this in PaddleNLP over paddle.nn
(nn/layer/transformer.py); here it is composed from the same nn surface with
F.scaled_dot_product_attention as the attention core.
"""
from __future__ import annotations

from dataclasses import dataclass

from ... import nn
from ...nn import functional as F


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout_prob: float = 0.1
    layer_norm_eps: float = 1e-12


class BertEmbeddings(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.word_embeddings = nn.Embedding(config.vocab_size, config.hidden_size)
        self.position_embeddings = nn.Embedding(config.max_position_embeddings,
                                                config.hidden_size)
        self.token_type_embeddings = nn.Embedding(config.type_vocab_size,
                                                  config.hidden_size)
        self.word_embeddings.shard_annotate(weight=("vocab", "embed"))
        self.position_embeddings.shard_annotate(weight=("pos", "embed"))
        self.token_type_embeddings.shard_annotate(weight=("type", "embed"))
        self.layer_norm = nn.LayerNorm(config.hidden_size,
                                       epsilon=config.layer_norm_eps)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None):
        import paddle_tpu as paddle

        s = input_ids.shape[1]
        pos = paddle.arange(s, dtype="int64").unsqueeze(0)
        x = self.word_embeddings(input_ids) + self.position_embeddings(pos)
        if token_type_ids is not None:
            x = x + self.token_type_embeddings(token_type_ids)
        from ._policy import _cast_residual

        x = _cast_residual(x)
        return self.dropout(self.layer_norm(x))


class BertSelfAttention(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.num_heads = config.num_attention_heads
        self.head_dim = config.hidden_size // config.num_attention_heads
        h = config.hidden_size
        self.query = nn.Linear(h, h)
        self.key = nn.Linear(h, h)
        self.value = nn.Linear(h, h)
        self.out = nn.Linear(h, h)
        # declarative-partitioner logical axes (distributed/partitioner)
        for lin in (self.query, self.key, self.value):
            lin.shard_annotate(weight=("embed", "heads"), bias=("heads",))
        self.out.shard_annotate(weight=("heads", "embed"), bias=("norm",))

    def forward(self, x, attn_mask=None):
        b, s, h = x.shape
        q = self.query(x).reshape([b, s, self.num_heads, self.head_dim])
        k = self.key(x).reshape([b, s, self.num_heads, self.head_dim])
        v = self.value(x).reshape([b, s, self.num_heads, self.head_dim])
        o = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask)
        return self.out(o.reshape([b, s, h]))


class BertLayer(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.attention = BertSelfAttention(config)
        self.attn_norm = nn.LayerNorm(config.hidden_size,
                                      epsilon=config.layer_norm_eps)
        self.intermediate = nn.Linear(config.hidden_size, config.intermediate_size)
        self.output = nn.Linear(config.intermediate_size, config.hidden_size)
        self.intermediate.shard_annotate(weight=("embed", "mlp"),
                                         bias=("mlp",))
        self.output.shard_annotate(weight=("mlp", "embed"), bias=("norm",))
        self.out_norm = nn.LayerNorm(config.hidden_size,
                                     epsilon=config.layer_norm_eps)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)

    def forward(self, x, attn_mask=None):
        # post-LN BERT: dropout + residual add run as ONE fused op (Pallas
        # dropout_add kernel on TPU), the norm kernel takes the second pass
        h = F.fused_dropout_add(self.attention(x, attn_mask), x,
                                p=self.dropout.p, training=self.training)
        x = self.attn_norm(h)
        y = self.output(F.gelu(self.intermediate(x)))
        return self.out_norm(F.fused_dropout_add(y, x, p=self.dropout.p,
                                                 training=self.training))


class BertModel(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        self.encoder = nn.LayerList([BertLayer(config)
                                     for _ in range(config.num_hidden_layers)])
        self.pooler = nn.Linear(config.hidden_size, config.hidden_size)

    def forward(self, input_ids, token_type_ids=None, attn_mask=None):
        x = self.embeddings(input_ids, token_type_ids)
        for layer in self.encoder:
            x = layer(x, attn_mask)
        pooled = F.tanh(self.pooler(x[:, 0]))
        return x, pooled


class BertForSequenceClassification(nn.Layer):
    def __init__(self, config: BertConfig, num_classes=2):
        super().__init__()
        self.bert = BertModel(config)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)
        self.classifier = nn.Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, labels=None, attn_mask=None):
        _, pooled = self.bert(input_ids, token_type_ids, attn_mask)
        logits = self.classifier(self.dropout(pooled))
        if labels is not None:
            return F.cross_entropy(logits, labels, reduction="mean")
        return logits
