"""Autoregressive decoding engine — KV cache + single-program generation.

Reference parity: the decode-attention family the reference ships as fused
CUDA kernels — masked_multihead_attention
(/root/reference/paddle/phi/kernels/fusion/gpu/masked_multihead_attention_kernel.cu),
block_multihead_attention (fusion/gpu/block_multi_head_attention_kernel.cu) —
plus the PaddleNLP `generate()` loop those kernels serve.

TPU-native design (NOT a kernel translation):
  - The ENTIRE generation — prefill + every decode step — is ONE compiled
    XLA program: `lax.scan` over decode steps, `lax.scan` over the stacked
    layer weights inside each step. Over the axon tunnel one invocation
    costs ~13-17 ms, so a per-token Python loop would be latency-bound at
    ~70 tok/s; the fused program pays the overhead once per SEQUENCE.
  - KV cache is a static-shaped buffer [L, B, T, H_kv, D] updated with
    `lax.dynamic_update_slice` — static shapes keep XLA happy; the valid
    region is tracked by a scalar position (the masked_multihead_attention
    role: seq-1 query attending to the cache under a length mask).
  - Prefill rides the Pallas flash kernel (ops/pallas_attention.py) on TPU.
  - Prompt lengths bucket via jit.default_buckets so a serving stream
    compiles O(log S) programs, keyed by (bucket, B, sampling config).
"""
from __future__ import annotations

import functools
import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class _GenSpec:
    """Static configuration that keys the compiled generate program."""
    num_layers: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    rope_theta: float
    rms_eps: float
    max_new_tokens: int
    do_sample: bool
    top_k: int
    top_p: float
    temperature: float
    eos_token_id: int
    tie_embeddings: bool
    arch: str = "llama"  # "llama" (RMSNorm+RoPE+SwiGLU) | "gpt" (LN+wpe+GELU)
    # "none" | "int8" | "int4": weight-only per-output-channel quantization
    # on the layer matmuls + lm_head (≙ weight_only_linear's serving role) —
    # decode is HBM-bandwidth-bound, so shrinking weight bytes is the win;
    # activations stay bf16. int8 stores [K, N] int8 (XLA fuses the
    # int8->bf16 convert into the matmul tiles); int4 stores TRUE packed
    # [ceil(K/2), N] nibbles (ops/quantized.py) so the packed bytes are the
    # only HBM weight traffic — the Pallas fused dequant-matmul unpacks in
    # VMEM on TPU, the XLA take-bits composition everywhere else
    weight_quant: str = "none"


def _rms_norm(x, w, eps):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x.astype(jnp.float32) * jax.lax.rsqrt(var + eps)).astype(x.dtype) \
        * w


def _rope(x, cos, sin):
    # x [..., D]; cos/sin broadcastable [..., D]
    x1, x2 = jnp.split(x, 2, axis=-1)
    rotated = jnp.concatenate([-x2, x1], axis=-1)
    return x * cos + rotated * sin


def _rope_tables_np(max_len, head_dim, theta, dtype):
    inv = 1.0 / (theta ** (np.arange(0, head_dim, 2, dtype=np.float64)
                           / head_dim))
    t = np.arange(max_len, dtype=np.float64)
    freqs = np.outer(t, inv)
    emb = np.concatenate([freqs, freqs], axis=-1)  # [T, D]
    return (np.cos(emb).astype(dtype), np.sin(emb).astype(dtype))


def _repeat_kv(x, rep, axis):
    return x if rep == 1 else jnp.repeat(x, rep, axis=axis)


def _mm(x, w):
    """x @ w where w is either a dense array or a weight-only pair
    (int8 [K,N] or packed int4 [ceil(K/2),N], scale f32 [N]) — the pair
    shape disambiguates, see ops/quantized.quant_matmul (the single shared
    dequant-matmul behind generation, weight_only_linear and the paged
    engine)."""
    if isinstance(w, tuple):
        from ..ops.quantized import quant_matmul

        return quant_matmul(x, w[0], w[1])
    return x @ w


def _quantize_w(w):
    """Per-output-channel symmetric int8 for a [K, N] weight — delegates to
    the public weight_quantize rule so serving and the quant API can never
    drift numerically."""
    from ..incubate.nn.functional import weight_quantize_raw

    return weight_quantize_raw(w)


def _quantize_w4(w):
    """TRUE packed int4 (two nibbles per byte) with per-output-channel
    scales — the same rule weight_quantize(algo="weight_only_int4") applies
    (ops/quantized.quantize_int4 handles stacked [L, K, N] weights
    directly: every axis rule is relative to the trailing two dims)."""
    from ..ops.quantized import quantize_int4

    return quantize_int4(w)


def _sample_token(logits, key, spec: _GenSpec):
    """Greedy or (temperature, top-k, top-p) sampling. logits [B, V]."""
    if not spec.do_sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    lg = logits.astype(jnp.float32) / max(spec.temperature, 1e-6)
    if spec.top_k > 0:
        kth = jax.lax.top_k(lg, spec.top_k)[0][..., -1:]
        lg = jnp.where(lg < kth, -jnp.inf, lg)
    if spec.top_p < 1.0:
        srt = jnp.sort(lg, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(srt, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix with cumsum(prev) < p (nucleus incl.
        # the boundary token, matching ops/extras.top_p_sampling)
        keep = cum - probs < spec.top_p
        cutoff = jnp.min(jnp.where(keep, srt, jnp.inf), axis=-1,
                         keepdims=True)
        lg = jnp.where(lg < cutoff, -jnp.inf, lg)
    return jax.random.categorical(key, lg, axis=-1).astype(jnp.int32)


def _layer_forward_prefill(x, lw, spec: _GenSpec, cos, sin):
    """One decoder block over the full prompt. x [B, S, H]."""
    from ..ops.pallas_attention import flash_attention_raw

    b, s, h = x.shape
    hn = _rms_norm(x, lw["input_ln"], spec.rms_eps)
    flat = hn.reshape(b * s, h)
    q = _mm(flat, lw["q"]).reshape(b, s, spec.num_heads, spec.head_dim)
    k = _mm(flat, lw["k"]).reshape(b, s, spec.num_kv_heads, spec.head_dim)
    v = _mm(flat, lw["v"]).reshape(b, s, spec.num_kv_heads, spec.head_dim)
    c = cos[None, :s, None, :]
    sn = sin[None, :s, None, :]
    q = _rope(q, c, sn)
    k = _rope(k, c, sn)
    rep = spec.num_heads // spec.num_kv_heads
    if jax.default_backend() == "tpu" and s >= 128:
        out = flash_attention_raw(
            jnp.swapaxes(q, 1, 2), jnp.swapaxes(_repeat_kv(k, rep, 2), 1, 2),
            jnp.swapaxes(_repeat_kv(v, rep, 2), 1, 2), causal=True)
        out = jnp.swapaxes(out, 1, 2)
    else:
        kr = _repeat_kv(k, rep, 2)
        vr = _repeat_kv(v, rep, 2)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, kr) \
            / math.sqrt(spec.head_dim)
        mask = jnp.tril(jnp.ones((s, s), bool))
        scores = jnp.where(mask[None, None], scores,
                           jnp.finfo(scores.dtype).min)
        probs = jax.nn.softmax(scores.astype(jnp.float32),
                               axis=-1).astype(q.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs, vr)
    attn = _mm(out.reshape(b * s, spec.num_heads * spec.head_dim), lw["o"])
    x = x + attn.reshape(b, s, h)
    hn = _rms_norm(x, lw["post_ln"], spec.rms_eps).reshape(b * s, h)
    mlp = _mm(jax.nn.silu(_mm(hn, lw["gate"])) * _mm(hn, lw["up"]),
              lw["down"])
    return x + mlp.reshape(b, s, h), (k, v)


def _layer_forward_decode(x, lw, kc, vc, pos, spec: _GenSpec, cos, sin):
    """One decoder block for a seq-1 query against the cache.
    x [B, H]; kc/vc [B, T, H_kv, D]; pos scalar (current write index)."""
    b, h = x.shape
    hn = _rms_norm(x, lw["input_ln"], spec.rms_eps)
    q = _mm(hn, lw["q"]).reshape(b, spec.num_heads, spec.head_dim)
    k = _mm(hn, lw["k"]).reshape(b, spec.num_kv_heads, spec.head_dim)
    v = _mm(hn, lw["v"]).reshape(b, spec.num_kv_heads, spec.head_dim)
    c = jax.lax.dynamic_slice(cos, (pos, jnp.int32(0)), (1, spec.head_dim))
    sn = jax.lax.dynamic_slice(sin, (pos, jnp.int32(0)), (1, spec.head_dim))
    q = _rope(q, c[None], sn[None])
    k = _rope(k, c[None], sn[None])
    z = jnp.int32(0)
    kc = jax.lax.dynamic_update_slice(kc, k[:, None], (z, pos, z, z))
    vc = jax.lax.dynamic_update_slice(vc, v[:, None], (z, pos, z, z))
    rep = spec.num_heads // spec.num_kv_heads
    kr = _repeat_kv(kc, rep, 2)                       # [B, T, Hq, D]
    vr = _repeat_kv(vc, rep, 2)
    scores = jnp.einsum("bhd,bthd->bht", q, kr) / math.sqrt(spec.head_dim)
    valid = jnp.arange(kc.shape[1]) <= pos            # length mask
    scores = jnp.where(valid[None, None, :], scores,
                       jnp.finfo(scores.dtype).min)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    out = jnp.einsum("bht,bthd->bhd", probs, vr)
    attn = _mm(out.reshape(b, spec.num_heads * spec.head_dim), lw["o"])
    x = x + attn
    hn = _rms_norm(x, lw["post_ln"], spec.rms_eps)
    mlp = _mm(jax.nn.silu(_mm(hn, lw["gate"])) * _mm(hn, lw["up"]),
              lw["down"])
    return x + mlp, kc, vc


def _layer_norm(x, w, b, eps):
    xf = x.astype(jnp.float32)
    m = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - m), axis=-1, keepdims=True)
    return ((xf - m) * jax.lax.rsqrt(var + eps)).astype(x.dtype) * w + b


def _gpt_layer_prefill(x, lw, spec: _GenSpec):
    """Pre-LN GPT block over the full prompt. x [B, S, H]."""
    from ..ops.pallas_attention import flash_attention_raw

    b, s, h = x.shape
    hn = _layer_norm(x, lw["ln1_w"], lw["ln1_b"], spec.rms_eps)
    qkv = _mm(hn.reshape(b * s, h), lw["qkv"]).reshape(
        b, s, 3, spec.num_heads, spec.head_dim)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    if jax.default_backend() == "tpu" and s >= 128:
        out = flash_attention_raw(
            jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
            jnp.swapaxes(v, 1, 2), causal=True)
        out = jnp.swapaxes(out, 1, 2)
    else:
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) \
            / math.sqrt(spec.head_dim)
        mask = jnp.tril(jnp.ones((s, s), bool))
        scores = jnp.where(mask[None, None], scores,
                           jnp.finfo(scores.dtype).min)
        probs = jax.nn.softmax(scores.astype(jnp.float32),
                               axis=-1).astype(q.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    x = x + _mm(out.reshape(b * s, h), lw["o"]).reshape(b, s, h)
    hn = _layer_norm(x, lw["ln2_w"], lw["ln2_b"], spec.rms_eps)
    mlp = _mm(jax.nn.gelu(_mm(hn.reshape(b * s, h), lw["fc_in"]),
                          approximate=False), lw["fc_out"])
    return x + mlp.reshape(b, s, h), (k, v)


def _gpt_layer_decode(x, lw, kc, vc, pos, spec: _GenSpec):
    """Pre-LN GPT block for a seq-1 query. x [B, H]."""
    b, h = x.shape
    hn = _layer_norm(x, lw["ln1_w"], lw["ln1_b"], spec.rms_eps)
    qkv = _mm(hn, lw["qkv"]).reshape(b, 3, spec.num_heads, spec.head_dim)
    q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
    z = jnp.int32(0)
    kc = jax.lax.dynamic_update_slice(kc, k[:, None], (z, pos, z, z))
    vc = jax.lax.dynamic_update_slice(vc, v[:, None], (z, pos, z, z))
    scores = jnp.einsum("bhd,bthd->bht", q, kc) / math.sqrt(spec.head_dim)
    valid = jnp.arange(kc.shape[1]) <= pos
    scores = jnp.where(valid[None, None, :], scores,
                       jnp.finfo(scores.dtype).min)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    out = jnp.einsum("bht,bthd->bhd", probs, vc)
    x = x + _mm(out.reshape(b, h), lw["o"])
    hn = _layer_norm(x, lw["ln2_w"], lw["ln2_b"], spec.rms_eps)
    return x + _mm(jax.nn.gelu(_mm(hn, lw["fc_in"]),
                               approximate=False), lw["fc_out"]), kc, vc


def _logits(x, params, spec: _GenSpec):
    """x [B, H] -> [B, V]."""
    if spec.arch == "gpt":
        x = _layer_norm(x, params["final_ln"], params["final_ln_b"],
                        spec.rms_eps)
    else:
        x = _rms_norm(x, params["final_ln"], spec.rms_eps)
    if spec.tie_embeddings:
        return x.astype(jnp.float32) @ params["embed"].T.astype(jnp.float32)
    head = params["lm_head"]
    if isinstance(head, tuple):
        # f32 activations keep the historical logits numerics: for int8
        # this is exactly (x_f32 @ w8_f32) * ws_f32; int4 unpacks first
        return _mm(x.astype(jnp.float32), head)
    return x.astype(jnp.float32) @ head.astype(jnp.float32)


#: host-side mirror of the generation program keys — a NEW key here
#: records a compile event for the obs watchdog. Kept separate from the
#: executable cache below so tests can clear the event mirror without
#: forcing a real recompile (the obs watchdog fire/no-fire pairs do).
_seen_gen_programs: set = set()

#: round 14: the generation engine owns its executables via the AOT path
#: (_generate_program.lower().compile()) — the compiled object carries
#: XLA cost_analysis()/memory_analysis() into the obs cost ledger for
#: free, and the compile wall is measured exactly instead of smeared
#: into the first generate() call. prog_key -> (compiled, ProgramCost)
_gen_executables: dict = {}


@functools.partial(jax.jit, static_argnums=(2,), donate_argnums=())
def _generate_program(params, ids, spec: _GenSpec, rng_key, true_len):
    """The fused prefill+decode program. ids [B, S_bucket] int32, right-
    padded to the prompt bucket; `true_len` (traced scalar) is the real
    prompt length, so the program is keyed by (bucket, B, spec) — a serving
    stream compiles O(log S) programs, not one per distinct prompt length.
    Padded prefill positions produce garbage K/V at cache slots
    [true_len, S_bucket); decode writes start at true_len and the
    `arange <= pos` mask never reaches an unwritten slot, so the garbage is
    progressively overwritten and never attended to.
    Returns tokens [B, max_new_tokens] int32."""
    b, s = ids.shape
    total = s + spec.max_new_tokens
    dtype = params["embed"].dtype
    gpt = spec.arch == "gpt"
    if gpt:
        x = params["embed"][ids] + params["wpe"][None, :s]

        def pre(xc, lw):
            return _gpt_layer_prefill(xc, lw, spec)
    else:
        cos, sin = params["rope_cos"], params["rope_sin"]
        x = params["embed"][ids]                      # [B, S, H]

        def pre(xc, lw):
            return _layer_forward_prefill(xc, lw, spec, cos, sin)

    x, (ks, vs) = jax.lax.scan(pre, x, params["layers"])
    # static-shaped cache for the whole generation
    pad = total - s
    kcache = jnp.pad(ks, ((0, 0), (0, 0), (0, pad), (0, 0), (0, 0)))
    vcache = jnp.pad(vs, ((0, 0), (0, 0), (0, pad), (0, 0), (0, 0)))

    # the last REAL prompt position, not the last padded one
    x_last = jax.lax.dynamic_slice_in_dim(x, true_len - 1, 1, axis=1)[:, 0]
    logits0 = _logits(x_last, params, spec)
    key0, sub = jax.random.split(rng_key)
    tok0 = _sample_token(logits0, sub, spec)
    finished0 = tok0 == spec.eos_token_id

    def step(carry, _):
        tok, kc, vc, pos, key, finished = carry
        xt = params["embed"][tok].astype(dtype)       # [B, H]
        if gpt:
            xt = xt + params["wpe"][pos]

        def layer(xc, per_layer):
            lw, kcl, vcl = per_layer
            if gpt:
                xo, kcl, vcl = _gpt_layer_decode(xc, lw, kcl, vcl, pos,
                                                 spec)
            else:
                xo, kcl, vcl = _layer_forward_decode(xc, lw, kcl, vcl, pos,
                                                     spec, cos, sin)
            return xo, (kcl, vcl)

        xt, (kc, vc) = jax.lax.scan(layer, xt, (params["layers"], kc, vc))
        lg = _logits(xt, params, spec)
        key, sub2 = jax.random.split(key)
        nxt = _sample_token(lg, sub2, spec)
        nxt = jnp.where(finished, spec.eos_token_id, nxt)
        finished = finished | (nxt == spec.eos_token_id)
        return (nxt, kc, vc, pos + 1, key, finished), tok

    # scan max_new_tokens-1 steps and append the final carried token: the
    # last sampled token needs no forward pass of its own (a full-length
    # scan would run one dead per-layer forward whose sample is discarded)
    (last_tok, _, _, _, _, _), toks = jax.lax.scan(
        step, (tok0, kcache, vcache, true_len.astype(jnp.int32), key0,
               finished0),
        None, length=spec.max_new_tokens - 1)
    toks = jnp.swapaxes(toks, 0, 1)                   # [B, new-1]
    return jnp.concatenate([toks, last_tok[:, None]], axis=1)


_STACK_CACHE: dict = {}
_STACK_CACHE_MAX = 2  # stacked weights are a full model-size copy; bound it


def _cached_extract(model, extract_fn, tag=""):
    """Stack-cache wrapper: key = per-buffer monotonic version
    (Tensor._buf_version — bumped by every construction and every
    buffer-swap mutation, never reused). id() is deliberately NOT part of
    the key: CPython reuses freed addresses, so a training step followed by
    allocation could produce the same id set and silently serve stale
    stacked weights."""
    sd = {k: v for k, v in model.state_dict().items()}
    key = (tag,) + tuple((k, sd[k]._buf_version) for k in sorted(sd))
    hit = _STACK_CACHE.get((id(model), tag))
    if hit is not None and hit[0] == key:
        return hit[1]
    params = extract_fn(sd)
    _STACK_CACHE[(id(model), tag)] = (key, params)
    while len(_STACK_CACHE) > _STACK_CACHE_MAX:
        _STACK_CACHE.pop(next(iter(_STACK_CACHE)))
    return params


def _stacked_params(model, weight_quant="none"):
    """Extract + stack per-layer weights [L, ...] for lax.scan (cached,
    see _cached_extract). weight_quant="int8"/"int4" stores the seven
    layer matmul weights and lm_head as weight-only pairs (see _mm; int4
    is true packed-nibble storage)."""
    cfg = model.config
    return _cached_extract(
        model, lambda sd: _extract_llama(cfg, sd, weight_quant),
        tag=weight_quant)


def _extract_llama(cfg, sd, weight_quant="none"):
    def w(name):
        return sd[name]._data

    prefix = "model." if any(k.startswith("model.") for k in sd) else "llama."
    layers = {"q": [], "k": [], "v": [], "o": [], "gate": [], "up": [],
              "down": [], "input_ln": [], "post_ln": []}
    for i in range(cfg.num_hidden_layers):
        base = f"{prefix}layers.{i}."
        layers["q"].append(w(base + "self_attn.q_proj.weight"))
        layers["k"].append(w(base + "self_attn.k_proj.weight"))
        layers["v"].append(w(base + "self_attn.v_proj.weight"))
        layers["o"].append(w(base + "self_attn.o_proj.weight"))
        layers["gate"].append(w(base + "mlp.gate_proj.weight"))
        layers["up"].append(w(base + "mlp.up_proj.weight"))
        layers["down"].append(w(base + "mlp.down_proj.weight"))
        layers["input_ln"].append(w(base + "input_layernorm.weight"))
        layers["post_ln"].append(w(base + "post_attention_layernorm.weight"))
    quant = weight_quant in ("int8", "int4")
    qfn = _quantize_w4 if weight_quant == "int4" else _quantize_w

    def stack(k, vals):
        stacked = jnp.stack(vals)
        if quant and k not in ("input_ln", "post_ln"):
            if weight_quant == "int4":
                # quantize_int4's axis rules are trailing-dim-relative, so
                # the stacked [L, K, N] tensor quantizes in one call
                return qfn(stacked)
            # vmap the per-channel quantizer over the layer axis
            return jax.vmap(qfn)(stacked)
        return stacked

    params = {
        "embed": w(prefix + "embed_tokens.weight"),
        "final_ln": w(prefix + "norm.weight"),
        "layers": {k: stack(k, v) for k, v in layers.items()},
    }
    if not cfg.tie_word_embeddings:
        head = w("lm_head.weight")
        params["lm_head"] = qfn(head) if quant else head
    cos, sin = _rope_tables_np(cfg.max_position_embeddings, cfg.head_dim,
                               cfg.rope_theta,
                               np.dtype(params["embed"].dtype).name
                               if params["embed"].dtype != jnp.bfloat16
                               else "float32")
    params["rope_cos"] = jnp.asarray(cos, params["embed"].dtype)
    params["rope_sin"] = jnp.asarray(sin, params["embed"].dtype)
    return params


def _stacked_params_gpt(model, weight_quant="none"):
    """GPT-family extraction: LN weights/biases, fused qkv, learned wpe.
    weight_quant="int8"/"int4" stores qkv/o/fc_in/fc_out + lm_head as
    weight-only pairs (see _mm)."""
    cfg = model.config
    return _cached_extract(
        model, lambda sd: _extract_gpt(cfg, sd, weight_quant),
        tag=weight_quant)


def _extract_gpt(cfg, sd, weight_quant="none"):
    def w(name):
        return sd[name]._data

    layers = {"ln1_w": [], "ln1_b": [], "qkv": [], "o": [], "ln2_w": [],
              "ln2_b": [], "fc_in": [], "fc_out": []}
    for i in range(cfg.num_hidden_layers):
        base = f"blocks.{i}."
        layers["ln1_w"].append(w(base + "ln_1.weight"))
        layers["ln1_b"].append(w(base + "ln_1.bias"))
        layers["qkv"].append(w(base + "attn.qkv_proj.weight"))
        layers["o"].append(w(base + "attn.out_proj.weight"))
        layers["ln2_w"].append(w(base + "ln_2.weight"))
        layers["ln2_b"].append(w(base + "ln_2.bias"))
        layers["fc_in"].append(w(base + "fc_in.weight"))
        layers["fc_out"].append(w(base + "fc_out.weight"))
    quant = weight_quant in ("int8", "int4")
    qfn = _quantize_w4 if weight_quant == "int4" else _quantize_w
    qkeys = ("qkv", "o", "fc_in", "fc_out")

    def stack(k, vals):
        stacked = jnp.stack(vals)
        if quant and k in qkeys:
            return qfn(stacked) if weight_quant == "int4" \
                else jax.vmap(qfn)(stacked)
        return stacked

    head = w("lm_head.weight")
    params = {
        "embed": w("wte.weight"),
        "wpe": w("wpe.weight"),
        "final_ln": w("ln_f.weight"),
        "final_ln_b": w("ln_f.bias"),
        "lm_head": qfn(head) if quant else head,
        "layers": {k: stack(k, v) for k, v in layers.items()},
    }
    return params


def generate(model, input_ids, max_new_tokens=32, max_length=None,
             do_sample=False, temperature=1.0, top_k=0, top_p=1.0,
             eos_token_id=None, seed=None, weight_quant="none",
             engine="static", prefix_cache=None, spec_decode=None):
    """Autoregressive generation with a static KV cache, greedy or sampled.

    Returns a Tensor [B, prompt_len + n_generated] (prompt included, like
    the reference ecosystem's generate with full-sequence output).

    engine="static" (default): the whole loop is one compiled XLA program
    keyed by (batch, prompt bucket, generation-length bucket, sampling
    config). engine="paged": the continuous-batching serving engine
    (inference/engine.py) over the block-paged KV cache — same greedy
    tokens, the serving route for streams of requests. `prefix_cache`
    overrides FLAGS_prefix_cache for the paged engine (shared prompt
    prefixes across the batch/stream reuse KV blocks; greedy tokens are
    identical either way). `spec_decode` turns on speculative decoding
    (inference/speculative.py): for engine="paged" it is forwarded to
    the ServingEngine (string or SpecConfig); for engine="static" only
    the greedy n-gram proposer is wired ("ngram" | SpecConfig) — tokens
    stay identical to the non-speculative run either way.
    """
    from ..core.tensor import Tensor

    cfg = model.config
    ids = np.asarray(input_ids._data if hasattr(input_ids, "_data")
                     else input_ids).astype(np.int32)
    if ids.ndim == 1:
        ids = ids[None]
    if max_length is not None:
        max_new_tokens = int(max_length) - ids.shape[1]
    if max_new_tokens <= 0:
        raise ValueError("max_new_tokens must be positive")
    total = ids.shape[1] + int(max_new_tokens)
    if total > int(cfg.max_position_embeddings):
        # positional tables (wpe / rope) end here; indexing past them would
        # silently clamp to the last row under jit
        raise ValueError(
            f"prompt ({ids.shape[1]}) + max_new_tokens ({max_new_tokens}) "
            f"= {total} exceeds max_position_embeddings "
            f"({cfg.max_position_embeddings})")
    if engine not in ("static", "paged"):
        raise ValueError(f"engine must be 'static' or 'paged', got "
                         f"{engine!r}")
    # models declare their engine arch; default is the llama layout
    arch = getattr(model, "_gen_arch", "llama")
    from ..core.flags import flag

    if weight_quant in (None, "none"):
        # the serving-wide default: per-call weight_quant= overrides
        weight_quant = str(flag("FLAGS_weight_only_dtype"))
    if weight_quant not in ("none", "int8", "int4"):
        raise ValueError(f"weight_quant must be 'none', 'int8' or 'int4', "
                         f"got {weight_quant!r}")
    mnt = int(max_new_tokens)
    if engine == "paged":
        # the paged engine addresses context through whole KV blocks, so
        # its usable length is max_position_embeddings rounded DOWN to the
        # block size — surface the gap here, at the API boundary, instead
        # of deep inside the engine's admission check

        kv_bs = int(flag("FLAGS_kv_block_size"))
        usable = (int(cfg.max_position_embeddings) // kv_bs) * kv_bs
        if total > usable:
            raise ValueError(
                f"prompt ({ids.shape[1]}) + max_new_tokens "
                f"({max_new_tokens}) = {total} exceeds the paged engine's "
                f"usable context ({usable} = max_position_embeddings "
                f"rounded down to whole {kv_bs}-token kv blocks); use "
                "engine='static' or a smaller generation budget")
        from ..inference.engine import generate_paged

        toks = generate_paged(model, ids.astype(np.int64), mnt,
                              do_sample=bool(do_sample),
                              temperature=float(temperature),
                              top_k=int(top_k), top_p=float(top_p),
                              eos_token_id=eos_token_id,
                              seed=None if seed is None else int(seed),
                              prefix_cache=prefix_cache,
                              spec_decode=spec_decode,
                              weight_quant=str(weight_quant))
        return _assemble_output(ids, toks, eos_token_id, Tensor)
    if prefix_cache is not None:
        raise ValueError("prefix_cache applies to engine='paged' only "
                         "(the static engine holds no block pool)")
    if spec_decode not in (None, "off"):
        if do_sample:
            raise NotImplementedError(
                "static-engine speculative decoding is greedy-only; "
                "rejection sampling rides engine='paged'")
        if weight_quant != "none":
            raise NotImplementedError(
                "static-engine speculative decoding runs unquantized "
                "weights")
        # deferred import: inference.speculative imports from this module
        from ..inference.speculative import (SpecConfig,
                                             generate_static_spec)

        sc = spec_decode if isinstance(spec_decode, SpecConfig) \
            else SpecConfig(method=str(spec_decode))
        if sc.method != "ngram" or sc.proposer is not None:
            raise NotImplementedError(
                "the static engine wires the n-gram proposer only; "
                "draft-model speculation rides engine='paged'")
        toks = generate_static_spec(model, ids, mnt,
                                    eos_token_id=eos_token_id, k=sc.k,
                                    max_ngram=sc.max_ngram)
        return _assemble_output(ids, toks, eos_token_id, Tensor)
    from ..jit.api import default_buckets

    s_true = ids.shape[1]
    # bucket the GENERATION length too: _GenSpec used to key a fresh
    # program per exact max_new_tokens — a serving stream of varied
    # lengths now compiles O(log L) programs, trading ≤2x dead decode
    # steps (the tail is trimmed below; eos masking is unchanged)
    mnt_bucket = min(default_buckets(mnt),
                     int(cfg.max_position_embeddings) - s_true)
    mnt_bucket = max(mnt_bucket, mnt)
    if arch == "gpt":
        nh = cfg.num_attention_heads
        spec = _GenSpec(
            num_layers=cfg.num_hidden_layers, num_heads=nh, num_kv_heads=nh,
            head_dim=cfg.hidden_size // nh, rope_theta=0.0,
            rms_eps=cfg.layer_norm_eps,
            max_new_tokens=mnt_bucket, do_sample=bool(do_sample),
            top_k=int(top_k), top_p=float(top_p),
            temperature=float(temperature),
            eos_token_id=int(eos_token_id if eos_token_id is not None
                             else -1),
            tie_embeddings=False, arch="gpt",
            weight_quant=str(weight_quant))
        params = _stacked_params_gpt(model, weight_quant=str(weight_quant))
    else:
        spec = _GenSpec(
            num_layers=cfg.num_hidden_layers,
            num_heads=cfg.num_attention_heads,
            num_kv_heads=cfg.num_key_value_heads, head_dim=cfg.head_dim,
            rope_theta=cfg.rope_theta, rms_eps=cfg.rms_norm_eps,
            max_new_tokens=mnt_bucket, do_sample=bool(do_sample),
            top_k=int(top_k), top_p=float(top_p),
            temperature=float(temperature),
            eos_token_id=int(eos_token_id if eos_token_id is not None
                             else -1),
            tie_embeddings=bool(cfg.tie_word_embeddings),
            weight_quant=str(weight_quant))
        params = _stacked_params(model, weight_quant=str(weight_quant))
    if seed is not None:
        key = jax.random.PRNGKey(int(seed))
    else:
        from ..core.rng import next_key

        key = next_key()
    # pad the prompt up to its bucket so the compiled program is keyed by
    # (bucket, B, spec): O(log S) compilations per serving stream. The
    # bucket is clamped so the padded total still fits the position tables.
    bucket = min(default_buckets(s_true),
                 int(cfg.max_position_embeddings) - mnt_bucket)
    bucket = max(bucket, s_true)
    ids_padded = np.pad(ids, ((0, 0), (0, bucket - s_true))) \
        if bucket > s_true else ids
    # compile watchdog + AOT executable cache: the generation program is
    # keyed by (spec, shapes, param avals) — the host key now addresses
    # the REAL compiled executable, not a mirror of jax.jit's cache.
    # This is the site whose round-10 failure (a program per exact
    # max_new_tokens) motivated the watchdog: exact-length keying shows
    # up as a recompile-storm finding instead of an accidental
    # discovery, and since round 14 every program also lands in the obs
    # cost ledger (flops / bytes accessed from the compiled object).
    import time as _time

    params_fp = tuple((tuple(p.shape), str(p.dtype))
                      for p in jax.tree_util.tree_leaves(params))
    prog_key = (spec, ids_padded.shape, str(params["embed"].dtype),
                params_fp)
    import hashlib

    key_str = (f"b{ids_padded.shape[0]}/s{bucket}/g{spec.max_new_tokens}/"
               f"sample{int(spec.do_sample)}/p"
               + hashlib.sha1(repr(params_fp).encode()).hexdigest()[:8])
    exe_cost = _gen_executables.get(prog_key)
    compile_wall = 0.0
    if exe_cost is None:
        from ..obs import costs as _costs

        _t0 = _time.perf_counter()
        exe = _generate_program.lower(
            params, jnp.asarray(ids_padded), spec, key,
            jnp.int32(s_true)).compile()
        compile_wall = _time.perf_counter() - _t0
        entry = _costs.record_program(
            "generate", f"generate/{arch}", key_str, compiled=exe,
            wall_s=compile_wall, bucket=bucket)
        exe_cost = (exe, entry)
        _gen_executables[prog_key] = exe_cost
    exe, entry = exe_cost
    if prog_key not in _seen_gen_programs:
        _seen_gen_programs.add(prog_key)
        from ..obs.watchdog import record_compile

        record_compile(
            "generate", f"generate/{arch}", key_str,
            bucket=(bucket, spec.max_new_tokens), wall_s=compile_wall,
            cost=({"flops": entry.flops,
                   "bytes_accessed": entry.bytes_accessed,
                   "peak_hbm_bytes": entry.peak_hbm_bytes}
                  if entry.analyzed else None))
    _t_run = _time.perf_counter()
    toks = exe(params, jnp.asarray(ids_padded), key, jnp.int32(s_true))
    # drop the bucketed tail: tokens [mnt, mnt_bucket) are dead steps the
    # length bucketing trades for program reuse
    toks = np.asarray(jax.device_get(toks))[:, :mnt]
    entry.observe(_time.perf_counter() - _t_run)
    return _assemble_output(ids, toks, eos_token_id, Tensor)


def _assemble_output(ids, toks, eos_token_id, Tensor):
    """Shared static/paged postprocessing: trim columns past the point
    where every row finished, prepend the prompt."""
    if eos_token_id is not None:
        # trim columns past the point where every row finished
        done = (toks == int(eos_token_id))
        all_done = done.all(axis=0)
        keep = len(all_done)
        first = np.argmax(all_done) if all_done.any() else None
        if first is not None and all_done[first]:
            keep = first + 1
        toks = toks[:, :keep]
    full = np.concatenate([ids, toks], axis=1)
    return Tensor(jnp.asarray(full.astype(np.int64)), _internal=True,
                  stop_gradient=True)
