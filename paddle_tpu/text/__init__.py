"""paddle_tpu.text — NLP model zoo (≙ PaddleNLP models the BASELINE.json
config ladder names: BERT/ERNIE fine-tune, GPT-3-medium, LLaMA-7B)."""
from . import models
from . import datasets
