"""paddle_tpu.text (≙ python/paddle/text): NLP datasets + ViterbiDecoder,
plus the model zoo the BASELINE.json config ladder names (BERT/ERNIE
fine-tune, GPT-3-medium, LLaMA-7B)."""
from . import models
from . import datasets
from .datasets import (
    Conll05st, Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16,
)
from .viterbi import ViterbiDecoder, viterbi_decode

__all__ = [
    "Conll05st", "Imdb", "Imikolov", "Movielens", "UCIHousing", "WMT14",
    "WMT16", "ViterbiDecoder", "viterbi_decode", "models", "datasets",
]
