from .datasets import Conll05st, Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16

__all__ = ["Imdb", "UCIHousing", "Conll05st", "Imikolov", "Movielens",
           "WMT14", "WMT16"]
