"""paddle.text.datasets (≙ python/paddle/text/datasets/*).

Local-file readers only (zero-egress environment): Imdb reads the standard
aclImdb tarball/directory, UCIHousing the housing.data table. The
download-era corpora without a stable local format raise with instructions.
"""
from __future__ import annotations

import os
import re
import tarfile

import numpy as np

from ...io.dataset import Dataset


def _no_download(name: str, hint: str):
    raise RuntimeError(
        f"{name}: downloads are unavailable in this environment; place "
        f"{hint} locally and pass data_file=...")


class UCIHousing(Dataset):
    """Boston housing regression table (13 features + target per row)."""

    def __init__(self, data_file=None, mode="train", download=False):
        if download:
            _no_download("UCIHousing", "housing.data")
        if data_file is None:
            _no_download("UCIHousing", "housing.data")
        raw = np.loadtxt(data_file).astype("float32")
        feats, target = raw[:, :-1], raw[:, -1:]
        # reference normalizes by feature max/min over the train split
        lo, hi = feats.min(0), feats.max(0)
        feats = (feats - lo) / np.maximum(hi - lo, 1e-8)
        n_train = int(len(raw) * 0.8)
        if mode == "train":
            self.x, self.y = feats[:n_train], target[:n_train]
        else:
            self.x, self.y = feats[n_train:], target[n_train:]

    def __len__(self):
        return len(self.x)

    def __getitem__(self, idx):
        return self.x[idx], self.y[idx]


class Imdb(Dataset):
    """IMDB sentiment corpus from the standard aclImdb_v1.tar.gz (or the
    extracted directory). Builds the vocabulary from the train split."""

    def __init__(self, data_file=None, mode="train", cutoff=150, download=False):
        if download:
            _no_download("Imdb", "aclImdb_v1.tar.gz (or the extracted dir)")
        if data_file is None:
            _no_download("Imdb", "aclImdb_v1.tar.gz (or the extracted dir)")
        self.mode = mode
        docs = {"pos": [], "neg": []}
        pat = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
        if os.path.isdir(data_file):
            for label in ("pos", "neg"):
                d = os.path.join(data_file, "aclImdb", mode, label)
                if not os.path.isdir(d):
                    d = os.path.join(data_file, mode, label)
                for fname in sorted(os.listdir(d)):
                    with open(os.path.join(d, fname), "rb") as f:
                        docs[label].append(f.read().decode("utf-8", "ignore"))
        else:
            with tarfile.open(data_file) as tf:
                for m in tf.getmembers():
                    match = pat.match(m.name)
                    if match:
                        docs[match.group(1)].append(
                            tf.extractfile(m).read().decode("utf-8", "ignore"))
        self.word_idx = self._build_vocab(docs, cutoff)
        unk = self.word_idx["<unk>"]
        self.docs, self.labels = [], []
        for label, texts in (("pos", docs["pos"]), ("neg", docs["neg"])):
            for t in texts:
                toks = self._tokenize(t)
                self.docs.append(np.array(
                    [self.word_idx.get(w, unk) for w in toks], "int64"))
                self.labels.append(0 if label == "pos" else 1)

    @staticmethod
    def _tokenize(text):
        return re.sub(r"[^a-z0-9\s]", "", text.lower()).split()

    def _build_vocab(self, docs, cutoff):
        from collections import Counter

        counts = Counter()
        for texts in docs.values():
            for t in texts:
                counts.update(self._tokenize(t))
        vocab = [w for w, c in counts.most_common() if c > cutoff or len(counts) < 200]
        word_idx = {w: i for i, w in enumerate(sorted(vocab))}
        word_idx["<unk>"] = len(word_idx)
        return word_idx

    def __len__(self):
        return len(self.docs)

    def __getitem__(self, idx):
        return self.docs[idx], int(self.labels[idx])


def _stub(name, hint):
    class _Stub(Dataset):
        def __init__(self, *a, **k):
            _no_download(name, hint)

    _Stub.__name__ = name
    return _Stub


Conll05st = _stub("Conll05st", "the conll05st corpus files")
Imikolov = _stub("Imikolov", "simple-examples.tgz")
Movielens = _stub("Movielens", "ml-1m.zip")
WMT14 = _stub("WMT14", "the wmt14 corpus files")
WMT16 = _stub("WMT16", "the wmt16 corpus files")
