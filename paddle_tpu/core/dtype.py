"""Dtype surface mirroring paddle's dtype API on top of numpy/jax dtypes.

Reference parity: paddle exposes paddle.float32 etc. as DataType enum values
(/root/reference/python/paddle/framework/dtype.py). Here dtypes ARE numpy dtypes
(what jax consumes natively) so no conversion layer is needed on the hot path.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import ml_dtypes

bool_ = np.dtype(np.bool_)
uint8 = np.dtype(np.uint8)
int8 = np.dtype(np.int8)
int16 = np.dtype(np.int16)
int32 = np.dtype(np.int32)
int64 = np.dtype(np.int64)
float16 = np.dtype(np.float16)
bfloat16 = np.dtype(ml_dtypes.bfloat16)
float32 = np.dtype(np.float32)
float64 = np.dtype(np.float64)
complex64 = np.dtype(np.complex64)
complex128 = np.dtype(np.complex128)
float8_e4m3fn = np.dtype(ml_dtypes.float8_e4m3fn)
float8_e5m2 = np.dtype(ml_dtypes.float8_e5m2)

_STR2DTYPE = {
    "bool": bool_,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "bfloat16": bfloat16,
    "float32": float32,
    "float64": float64,
    "complex64": complex64,
    "complex128": complex128,
    "float8_e4m3fn": float8_e4m3fn,
    "float8_e5m2": float8_e5m2,
    "fp16": float16,
    "bf16": bfloat16,
    "fp32": float32,
    "fp64": float64,
}

_FLOATING = {float16, bfloat16, float32, float64, float8_e4m3fn, float8_e5m2}
_COMPLEX = {complex64, complex128}
_INTEGER = {uint8, int8, int16, int32, int64}


def convert_dtype(dtype) -> np.dtype:
    """Normalize str / np.dtype / jnp scalar type / paddle-style name to np.dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        try:
            return _STR2DTYPE[dtype]
        except KeyError:
            return np.dtype(dtype)
    return np.dtype(dtype)


def dtype_name(dtype) -> str:
    return np.dtype(dtype).name


def is_floating_point(dtype) -> bool:
    return convert_dtype(dtype) in _FLOATING


def is_complex(dtype) -> bool:
    return convert_dtype(dtype) in _COMPLEX


def is_integer(dtype) -> bool:
    d = convert_dtype(dtype)
    return d in _INTEGER or d == bool_


# paddle.get_default_dtype / set_default_dtype
_default_dtype = float32


def set_default_dtype(d):
    global _default_dtype
    _default_dtype = convert_dtype(d)


def get_default_dtype():
    return _default_dtype


def promote_types(a, b):
    return jnp.promote_types(a, b)
