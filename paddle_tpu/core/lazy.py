"""Segmented lazy execution — graph breaks without giving up compilation.

Reference parity: the SOT bytecode JIT executes the *compilable prefix* of a
function as a graph and resumes Python past a break
(/root/reference/python/paddle/jit/sot/opcode_translator/executor/
opcode_executor.py:320,1865). A bytecode simulator is the CUDA-era answer;
the TPU-native answer is LazyTensor-style staging:

  * ops funnel through `op_call` as usual, but under an active LazyContext
    they are RECORDED, not executed — outputs are Tensors holding `LazyData`
    placeholders (shape/dtype known via jax.eval_shape, no device work);
  * the moment Python needs a concrete value (float(loss), .numpy(), bool,
    any raw-jnp use of a staged buffer) the pending segment FLUSHES: the
    recorded ops replay inside ONE jitted XLA program, every placeholder is
    filled, and Python simply continues — a graph break costs one segment
    boundary, not compilation;
  * per-op vjp closures come out of the same compiled segment (jax.vjp
    Partials are returnable pytrees), so autograd sees ordinary GradNodes.

Python re-runs every call (side effects preserved — print/log still fire);
device work runs as large compiled segments. Segment executables are cached
by op-sequence signature (op keys + exact dataflow wiring), so steady-state
calls execute compiled code only.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any

import jax
import numpy as np

_tls = threading.local()


def current_lazy():
    return getattr(_tls, "lazy_ctx", None)


@contextlib.contextmanager
def lazy_context(ctx):
    old = current_lazy()
    _tls.lazy_ctx = ctx
    try:
        yield ctx
    finally:
        _tls.lazy_ctx = old


class LazyData:
    """Placeholder for a staged op output. Knows its shape/dtype; any other
    access materializes (flushes the owning segment) and delegates."""

    __slots__ = ("seg", "src", "aval", "real", "__weakref__")

    def __init__(self, seg, src, aval):
        self.seg = seg
        self.src = src          # (op_index, out_index) within the segment
        self.aval = aval
        self.real = None

    # -- cheap metadata (no flush)
    @property
    def shape(self):
        return self.aval.shape if self.real is None else self.real.shape

    @property
    def dtype(self):
        return self.aval.dtype if self.real is None else self.real.dtype

    @property
    def ndim(self):
        return len(self.aval.shape)

    @property
    def size(self):
        return int(np.prod(self.aval.shape)) if self.aval.shape else 1

    # -- materialization
    def get(self):
        if self.real is None:
            self.seg.flush()
            if self.real is None:
                raise RuntimeError(
                    "lazy segment flush failed earlier (see the original "
                    "exception); this staged value was lost — re-run the "
                    "computation")
        return self.real

    def astype(self, dt):
        return self.get().astype(dt)

    def __jax_array__(self):
        return self.get()

    def __array__(self, dtype=None):
        a = np.asarray(self.get())
        return a.astype(dtype) if dtype is not None else a

    def __getattr__(self, name):  # only fires for attrs not defined above
        return getattr(self.get(), name)

    def __repr__(self):
        state = "pending" if self.real is None else "flushed"
        return f"LazyData({tuple(self.aval.shape)}, {self.aval.dtype}, {state})"


def _fwd_dunder(name):
    def f(self, *a, **k):
        return getattr(self.get(), name)(*a, **k)

    f.__name__ = name
    return f


for _n in ("__add__", "__radd__", "__sub__", "__rsub__", "__mul__",
           "__rmul__", "__truediv__", "__rtruediv__", "__floordiv__",
           "__rfloordiv__", "__mod__", "__rmod__", "__pow__", "__rpow__",
           "__matmul__", "__rmatmul__", "__neg__", "__pos__", "__abs__",
           "__getitem__", "__len__", "__iter__", "__float__", "__int__",
           "__bool__", "__index__", "__eq__", "__ne__", "__lt__", "__le__",
           "__gt__", "__ge__", "__and__", "__or__", "__xor__", "__invert__"):
    setattr(LazyData, _n, _fwd_dunder(_n))


class _VjpBox:
    """GradNode.vjp_fn for a staged op: resolves to the real vjp Partial
    (produced inside the compiled segment) on first backward use."""

    __slots__ = ("seg", "vjp")

    def __init__(self, seg):
        self.seg = seg
        self.vjp = None

    def __call__(self, cot):
        from .dispatch import _apply_vjp

        if self.vjp is None:
            self.seg.flush(reason="backward")
            if self.vjp is None:
                raise RuntimeError(
                    "lazy segment flush failed earlier (see the original "
                    "exception); this op's vjp was lost — re-run the "
                    "forward computation")
        if isinstance(cot, (tuple, list)):
            cot = type(cot)(c.get() if isinstance(c, LazyData) else c
                            for c in cot)
        elif isinstance(cot, LazyData):
            cot = cot.get()
        return _apply_vjp(self.vjp, cot)


class _OpRecord:
    __slots__ = ("fn", "bindings", "diff_dyn", "out_lazy", "single_out",
                 "vjp_box", "key")

    def __init__(self, fn, bindings, diff_dyn, out_lazy, single_out,
                 vjp_box, key):
        self.fn = fn                  # statics folded; takes dynamic args
        self.bindings = bindings      # ("L", (op_i, out_i)) | ("E", ext_i)
        self.diff_dyn = diff_dyn      # diff positions among DYNAMIC args
        self.out_lazy = out_lazy      # list[LazyData]
        self.single_out = single_out
        self.vjp_box = vjp_box
        self.key = key


#: segment executable cache: op-sequence signature -> jitted replay
_seg_cache: dict = {}
_seg_hits = 0
_seg_misses = 0
#: process-wide flush count (every Segment.flush with staged ops): the
#: graph-break rate the obs train callback reports per step — a step
#: whose flush count grows is paying host syncs (analysis D3 territory)
_flushes_total = 0


class FlushScope:
    """One attribution scope for segment flushes (round 16). Flushes
    credit the INNERMOST active scope only, so a nested ``Model.fit``
    (its ``TelemetryCallback`` pushes its own scope) never double-counts
    into the outer fit's per-step delta, and a callback reattached to a
    second fit re-baselines by pushing a fresh scope instead of diffing
    the process-global total (which still carries the prior fit's
    flushes)."""

    __slots__ = ("count",)

    def __init__(self):
        self.count = 0


#: innermost-active-scope stack; empty = flushes only hit the global
_flush_scopes: list[FlushScope] = []


def push_flush_scope() -> FlushScope:
    s = FlushScope()
    _flush_scopes.append(s)
    return s


def pop_flush_scope(scope: FlushScope):
    """Pop ``scope`` (and anything pushed above it that a non-local exit
    failed to pop — exception-robust like a context manager)."""
    if scope in _flush_scopes:
        while _flush_scopes:
            if _flush_scopes.pop() is scope:
                break


def _count_flush():
    global _flushes_total
    _flushes_total += 1
    if _flush_scopes:
        _flush_scopes[-1].count += 1


def seg_cache_info():
    return {"entries": len(_seg_cache), "hits": _seg_hits,
            "misses": _seg_misses}


def flush_info() -> dict:
    """Segment-flush telemetry for obs consumers. NOTE: ``flushes`` is
    the PROCESS total; per-fit deltas must come from a
    :class:`FlushScope` (push/pop around the fit) — the round-16 fix for
    sequential/nested fits re-reporting each other's flushes."""
    return {"flushes": _flushes_total, **seg_cache_info()}


def seg_cache_clear():
    global _seg_hits, _seg_misses
    _seg_cache.clear()
    _seg_hits = _seg_misses = 0


import os as _os

_PKG_DIR = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))


def _user_site():
    """file:line of the nearest stack frame OUTSIDE paddle_tpu — the user
    code whose concretization forced this flush (a graph-break site for
    tools/report_graph_breaks.py). Frames in generated dy2static code keep
    their synthetic '<dy2static ...>' filename, which is still useful."""
    import sys

    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        if fn.startswith("<dy2static"):
            # generated code: report the ORIGINAL source file (the embedded
            # "<dy2static /path/file.py:firstline>" tag)
            orig = fn[len("<dy2static "):].rstrip(">")
            return (f"{_os.path.basename(orig)} (in converted "
                    f"'{f.f_code.co_name}')", f.f_code.co_name)
        if not fn.startswith(_PKG_DIR):
            return f"{_os.path.basename(fn)}:{f.f_lineno}", f.f_code.co_name
        f = f.f_back
    return "<unknown>", "<unknown>"


class Segment:
    """One replayable run of staged ops → a single jitted XLA program."""

    __slots__ = ("ops", "ext", "ext_ids", "flushed", "ctx", "__weakref__")

    def __init__(self, ctx):
        self.ops: list[_OpRecord] = []
        self.ext: list[Any] = []           # concrete external inputs
        self.ext_ids: dict[int, int] = {}
        self.flushed = False
        self.ctx = ctx

    def bind_ext(self, arr) -> int:
        i = self.ext_ids.get(id(arr))
        if i is None:
            i = len(self.ext)
            self.ext.append(arr)
            self.ext_ids[id(arr)] = i
        return i

    # ------------------------------------------------------------ flush
    def flush(self, reason="concretization"):
        global _seg_hits, _seg_misses
        if self.flushed:
            return
        self.flushed = True  # first, so re-entrant get() can't recurse
        if self.ctx is not None and self.ctx.open_seg is self:
            self.ctx.open_seg = None
        if not self.ops:
            return
        _count_flush()
        if self.ctx is not None:
            self.ctx.segments_flushed += 1
            from .flags import flag as _flag

            # the end-of-call flush_all is the normal drain, not a graph
            # break — only mid-call concretizations are break sites
            if _flag("FLAGS_lazy_break_sites") and not self.ctx.closing:
                loc, fn_name = _user_site()
                self.ctx.break_sites.append(
                    {"loc": loc, "in": fn_name, "kind": reason,
                     "ops_in_segment": len(self.ops)})
        need_vjp = tuple(rec.vjp_box is not None for rec in self.ops)
        sig = (tuple(rec.key for rec in self.ops), need_vjp,
               tuple((tuple(a.shape), str(a.dtype)) for a in self.ext))
        from .flags import flag

        exe = _seg_cache.get(sig)
        if exe is None:
            _seg_misses += 1
            limit = max(int(flag("FLAGS_eager_cache_size")), 1)
            while len(_seg_cache) >= limit and _seg_cache:
                _seg_cache.pop(next(iter(_seg_cache)))
            exe = _build_replay(
                tuple((rec.fn, tuple(rec.bindings), tuple(rec.diff_dyn),
                       rec.single_out) for rec in self.ops), need_vjp)
            _seg_cache[sig] = exe
        else:
            _seg_hits += 1
        # flush-site span for the training flight recorder (round 16):
        # a graph-break host sync shows up ON the step timeline with its
        # replay wall — the recorder check is one module attr read, so
        # uninstrumented flushes pay nothing measurable
        from ..obs.train_flight import current as _tf_current

        _rec = _tf_current()
        _n_ops = len(self.ops)
        if _rec is not None:
            import time as _time

            _t0 = _time.perf_counter()
        try:
            outs, vjps = exe(self.ext)
        finally:
            ops, self.ops = self.ops, []
            self.ext = []
            self.ext_ids = {}
        if _rec is not None:
            _rec.program_span("lazy_flush", _t0, _time.perf_counter(),
                              reason=reason, ops=_n_ops)
        oi = vi = 0
        for rec, has_vjp in zip(ops, need_vjp):
            for ld in rec.out_lazy:
                ld.real = outs[oi]
                oi += 1
            if has_vjp:
                rec.vjp_box.vjp = vjps[vi]
                vi += 1


def _build_replay(opspecs, need_vjp):
    """Compile-once replay over the recorded op graph. Captures only plain
    (fn, bindings, diff_dyn, single_out) tuples — NOT the _OpRecord objects,
    whose out_lazy/vjp_box fields are later filled with device buffers (a
    cached closure over records would pin one whole run's outputs and vjp
    residuals in HBM for the cache lifetime). Bindings address producers by
    (op_index, out_index), so the wiring is positional and the executable is
    reusable for any segment with the same signature."""

    def replay(ext):
        env: dict[tuple, Any] = {}
        outs, vjps = [], []
        for idx, ((fn, bindings, diff_dyn, single_out), has_vjp) in \
                enumerate(zip(opspecs, need_vjp)):
            vals = [env[b] if tag == "L" else ext[b] for tag, b in bindings]
            if has_vjp:
                def primal(*dv, _vals=vals, _fn=fn, _di=diff_dyn):
                    vs = list(_vals)
                    for j, v in zip(_di, dv):
                        vs[j] = v
                    return _fn(*vs)

                out, vjp = jax.vjp(primal, *[vals[i] for i in diff_dyn])
                vjps.append(vjp)
            else:
                out = fn(*vals)
            flat = [out] if single_out else list(out)
            for oi, o in enumerate(flat):
                env[(idx, oi)] = o
            outs.extend(flat)
        return outs, vjps

    return jax.jit(replay)


class LazyContext:
    """Active across one segmented to_static call."""

    __slots__ = ("open_seg", "segments_flushed", "created", "break_sites",
                 "closing")

    def __init__(self):
        self.open_seg: Segment | None = None
        self.segments_flushed = 0
        # graph-break bookkeeping: the user site that forced each flush
        self.break_sites: list = []
        self.closing = False
        # weakrefs of every Tensor holding staged LazyData — after the final
        # flush the caller swaps in the concrete buffers so no LazyData
        # leaks out of the segmented call (a leaked one would defeat the
        # compiled-eager cache's _is_dynamic check on later eager use)
        self.created: list = []

    def seg(self) -> Segment:
        if self.open_seg is None or self.open_seg.flushed:
            self.open_seg = Segment(self)
        return self.open_seg

    def flush_all(self):
        self.closing = True
        try:
            if self.open_seg is not None and not self.open_seg.flushed:
                self.open_seg.flush()
        finally:
            self.closing = False

    # -------------------------------------------------------------- stage
    def stage(self, fn, fn_key, name, datas, diff_idx, target):
        """Try to record the op. Returns (out_lazy, vjp_box, avals, single)
        or None — caller materializes lazy inputs and runs eagerly."""
        from . import dtype as dtypes
        from .dispatch import _UNCACHABLE, _freeze, _is_dynamic

        if fn_key is _UNCACHABLE:
            return None
        # under an active jax trace (e.g. a nested to_static compiling while
        # the outer function runs segmented) tracers must NOT be staged as
        # segment externals — let the op execute inside the enclosing trace
        if any(isinstance(d, jax.core.Tracer) for d in datas):
            return None
        seg = self.seg()
        op_idx = len(seg.ops)
        bindings = []          # dynamic bindings, in dynamic-arg order
        dyn_avals = []
        key_parts = []
        statics = []           # (position-in-fn-args, value)
        orig_to_dyn = {}
        n_dyn = 0
        for i, d in enumerate(datas):
            if isinstance(d, LazyData):
                if d.real is not None:
                    d = d.real
                elif d.seg is not seg:
                    # cross-segment input: close the old one
                    d.seg.flush(reason="cross-segment-input")
                    d = d.real
                else:
                    if dtypes.is_complex(np.dtype(d.aval.dtype)):
                        return None  # complex grads: eager bridge path
                    bindings.append(("L", d.src))
                    dyn_avals.append(jax.ShapeDtypeStruct(d.aval.shape,
                                                          d.aval.dtype))
                    key_parts.append(("L",) + d.src)
                    orig_to_dyn[i] = n_dyn
                    n_dyn += 1
                    continue
            if _is_dynamic(d):
                if dtypes.is_complex(np.dtype(d.dtype)):
                    return None
                ei = seg.bind_ext(d)
                bindings.append(("E", ei))
                dyn_avals.append(jax.ShapeDtypeStruct(d.shape, d.dtype))
                key_parts.append(("E", ei, tuple(d.shape), str(d.dtype)))
                orig_to_dyn[i] = n_dyn
                n_dyn += 1
            else:
                fr = _freeze(d)
                if fr is _UNCACHABLE:
                    return None
                statics.append((i, d))
                key_parts.append(("S", fr))

        if any(i not in orig_to_dyn for i in diff_idx):
            return None  # differentiating a static operand: eager path

        static_map = dict(statics)
        n_args = len(datas)

        def bound_fn(*dyn_vals, _fn=fn, _smap=static_map, _n=n_args):
            vals = []
            it = iter(dyn_vals)
            for i in range(_n):
                vals.append(_smap[i] if i in _smap else next(it))
            return _fn(*vals)

        try:
            out_aval = jax.eval_shape(bound_fn, *dyn_avals)
        except Exception:
            return None
        single = not isinstance(out_aval, (tuple, list))
        flat_avals = [out_aval] if single else list(out_aval)
        if not all(hasattr(a, "shape") for a in flat_avals):
            return None
        if any(dtypes.is_complex(np.dtype(a.dtype)) for a in flat_avals):
            return None

        out_lazy = [LazyData(seg, (op_idx, oi), a)
                    for oi, a in enumerate(flat_avals)]
        opkey = (fn_key, name, target, tuple(key_parts), tuple(diff_idx),
                 single, len(flat_avals))
        vjp_box = _VjpBox(seg) if diff_idx else None
        rec = _OpRecord(bound_fn, bindings,
                        [orig_to_dyn[i] for i in diff_idx], out_lazy,
                        single, vjp_box, opkey)
        seg.ops.append(rec)
        return out_lazy, vjp_box, flat_avals, single
