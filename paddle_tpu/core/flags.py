"""Global flag registry.

Reference parity: paddle's gflags-compatible registry (paddle/common/flags.h:38,
flags.cc: 187 PHI_DEFINE_EXPORTED_* definitions) exposed through
paddle.set_flags/get_flags and FLAGS_* env vars. Same surface here; flags also
seed from the environment at import.
"""
from __future__ import annotations

import os
from typing import Any

_REGISTRY: dict[str, dict[str, Any]] = {}


def define_flag(name: str, default, doc: str = ""):
    if not name.startswith("FLAGS_"):
        name = "FLAGS_" + name
    env = os.environ.get(name)
    value = default
    if env is not None:
        value = _parse(env, default)
    _REGISTRY[name] = {"value": value, "default": default, "doc": doc}
    return value


def _parse(text: str, default):
    if isinstance(default, bool):
        return text.lower() in ("1", "true", "yes", "on")
    if isinstance(default, int):
        return int(text)
    if isinstance(default, float):
        return float(text)
    return text


#: bumped on every flag write — hot paths snapshot flag values keyed by
#: this generation instead of paying registry lookups per op (dispatch.py)
generation = 0


def set_flags(flags: dict):
    global generation
    for k, v in flags.items():
        if not k.startswith("FLAGS_"):
            k = "FLAGS_" + k
        if k not in _REGISTRY:
            _REGISTRY[k] = {"value": v, "default": v, "doc": "(ad-hoc)"}
        else:
            _REGISTRY[k]["value"] = v
    # bump AFTER the writes: snapshot readers keyed on the generation must
    # never observe the new generation with old registry values
    generation += 1


def get_flags(flags) -> dict:
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for k in flags:
        key = k if k.startswith("FLAGS_") else "FLAGS_" + k
        out[k] = _REGISTRY[key]["value"]
    return out


def flag(name: str):
    key = name if name.startswith("FLAGS_") else "FLAGS_" + name
    return _REGISTRY[key]["value"]


# Core flags (subset of reference's 187; grows as subsystems land).
define_flag("FLAGS_check_nan_inf", False, "scan every op output for nan/inf")
define_flag("FLAGS_use_compiled_eager", True, "jit-compile per-op eager dispatch")
define_flag("FLAGS_eager_cache_size", 4096, "per-op executable cache entries")
define_flag("FLAGS_eager_defer_vjp", True,
            "eager grad ops run a lean fwd-only executable; the vjp is "
            "re-derived inside one jitted backward call (trades ~1 extra "
            "fwd of the op's FLOPs in backward for ~2x cheaper per-op "
            "dispatch — see core/dispatch._build_entry). Operand "
            "retention: the deferred closure pins only the forward "
            "operands the vjp recompute provably reads (per-signature "
            "jaxpr liveness mask, computed on the first backward; until "
            "then one closure pins all operands — see _bwd_used_mask)")
define_flag("FLAGS_to_static_donate", True, "donate captured buffers in to_static")
define_flag("FLAGS_to_static_segmented", True,
            "on graph break, run segmented lazy execution (compiled XLA "
            "segments bridged eagerly) instead of whole-function eager")
define_flag("FLAGS_enable_double_grad", True,
            "record per-node re-derivation ctx for grad(create_graph=True); "
            "disable to shed the extra operand retention")
define_flag("FLAGS_log_level", 0, "VLOG-style verbosity")
define_flag("FLAGS_benchmark", False,
            "benchmark mode: block until each op's outputs are ready "
            "(per-op device sync, ≙ reference benchmark flag)")
define_flag("FLAGS_check_nan_inf_level", 0,
            "0: raise on nan/inf when FLAGS_check_nan_inf; >=1: warn only")
define_flag("FLAGS_cudnn_deterministic", False, "parity shim; XLA is deterministic")
define_flag("FLAGS_embedding_deterministic", False, "parity shim")
define_flag("FLAGS_allocator_strategy", "xla", "parity shim; XLA owns allocation")

# Reference flag-name parity (flags.cc defines 187 PHI_DEFINE_EXPORTED_*;
# the commonly consumed ones are registered here so set_flags/get_flags and
# FLAGS_* env seeding work for ported code — shims note where XLA makes the
# knob moot).
define_flag("FLAGS_eager_delete_tensor_gb", 0.0, "shim; XLA GC owns buffers")
define_flag("FLAGS_fraction_of_gpu_memory_to_use", 0.92,
            "maps to XLA_PYTHON_CLIENT_MEM_FRACTION at init")
define_flag("FLAGS_gpu_memory_limit_mb", 0, "per-chip HBM cap shim")
define_flag("FLAGS_initial_cpu_memory_in_mb", 500, "host allocator shim")
define_flag("FLAGS_use_pinned_memory", True, "host staging shim")
define_flag("FLAGS_conv_workspace_size_limit", 512, "shim; XLA autotunes")
define_flag("FLAGS_cudnn_exhaustive_search", False, "shim; XLA autotunes")
define_flag("FLAGS_sync_nccl_allreduce", False,
            "shim; ICI collectives are compiler-scheduled")
define_flag("FLAGS_max_inplace_grad_add", 0, "grad accumulation fusion shim")
define_flag("FLAGS_apply_pass_to_program", False, "shim; XLA pass pipeline")
define_flag("FLAGS_new_executor_serial_run", False, "shim; XLA owns scheduling")
define_flag("FLAGS_use_stream_safe_cuda_allocator", True, "shim")
define_flag("FLAGS_call_stack_level", 1, "error stack verbosity (1|2|3)")
define_flag("FLAGS_enable_pir_api", True, "shim; jaxpr/StableHLO ARE the IR")
define_flag("FLAGS_use_cinn", True, "shim; XLA IS the tensor compiler")
define_flag("FLAGS_cinn_subgraph_graphviz_dir", "", "shim")
define_flag("FLAGS_low_precision_op_list", 0, "amp op-stats collection level")
define_flag("FLAGS_enable_auto_parallel_align_mode", False,
            "bitwise-align debugging shim")
define_flag("FLAGS_flash_attn_version", 2, "pallas flash kernel version")
define_flag("FLAGS_set_to_1d", False, "0-D tensor compat shim")
define_flag("FLAGS_tensor_operants_mode", "eager", "parity shim")
define_flag("FLAGS_jit_engine_type", "xla", "executor engine selector shim")
define_flag("FLAGS_allreduce_record_one_event", False, "comm stream shim")
define_flag("FLAGS_distributed_heartbeat_timeout", 600,
            "comm watchdog default timeout (seconds)")
define_flag("FLAGS_rpc_retry_times", 3, "rpc retry shim")
define_flag("FLAGS_dataloader_use_shared_memory", True,
            "native shm ring transport for DataLoader workers")
define_flag("FLAGS_enable_to_static", True,
            "global to_static toggle (jit.enable_to_static)")
define_flag("FLAGS_jit_code_level", 100, "SOT code-dump verbosity shim")
define_flag("FLAGS_jit_verbosity", 0, "dy2static logging verbosity shim")
define_flag("FLAGS_jit_log_to_stdout", False,
            "mirror dy2static logs to stdout (set_verbosity also_to_stdout)")
define_flag("FLAGS_flash_autotune", True,
            "runtime autotune of Pallas flash attention block sizes per "
            "shape family (≙ phi autotune/auto_tune_base.h)")
define_flag("FLAGS_flash_tune_bwd_split", True,
            "autotune backward (dq/dkv) flash block sizes separately from "
            "the forward's instead of reusing the forward winner")
define_flag("FLAGS_flce_chunk_axis", "auto",
            "fused_linear_cross_entropy chunk axis: vocab | tokens | auto "
            "(auto = vocab when a multiple-of-128 divisor exists, else "
            "tokens — tools/sweep_ce_chunk.py measures the ladder)")
define_flag("FLAGS_flce_token_chunk", 1024,
            "token-chunk size for the sequence-chunked fused CE path "
            "(tokens per [chunk, H] @ [H, V] GEMM; <= 0 disables)")
define_flag("FLAGS_dy2static", True,
            "to_static capture-time AST rewrite of tensor-predicate "
            "if/while/for into lax.cond/while_loop/scan "
            "(jit/dy2static); off = pre-dy2static behavior (any "
            "data-dependent control flow is a graph break)")
define_flag("FLAGS_dy2static_speculate", True,
            "during to_static discovery, abstractly trace the UNTAKEN "
            "branch of converted ifs so tensors it reads are recorded as "
            "captures instead of being baked as constants at trace time")
define_flag("FLAGS_jit_debug_program", False,
            "retain each to_static specialization's traceable closure so "
            "CompiledFunction.program_text() can print its jaxpr (pins "
            "the compile-call args; tests/tools only)")
define_flag("FLAGS_lazy_break_sites", True,
            "record the user file:line that forces each segmented-lazy "
            "flush (graph-break sites, tools/report_graph_breaks.py)")
define_flag("FLAGS_pallas_fused_ops", True,
            "route rms/layer norm (+fused residual add), rotary, SwiGLU "
            "and dropout+add through the Pallas fused kernels on TPU above "
            "the size threshold (ops/pallas_norm.py); off = the XLA "
            "compositions everywhere")
define_flag("FLAGS_analysis_vmem_limit_mb", 16,
            "per-core VMEM budget (MiB) the static analyzer checks Pallas "
            "launch configs against (analysis/vmem.py D5: flash autotune "
            "entries + norm block configs fail lint, not runtime)")
define_flag("FLAGS_analysis_fusion_min_elems", 4096,
            "fusion-miss detector (analysis D4) reporting floor: "
            "norm/rotary/swiglu/dropout-add compositions smaller than "
            "this many elements are not worth a finding")
define_flag("FLAGS_analysis_collective_min_bytes", 65536,
            "SPMD collective audit (analysis D10) warning floor: an "
            "all_gather whose output is consumed only by elementwise/"
            "slice ops fires the accidental-all-gather warning only at "
            "or above this per-device byte volume (smaller gathers stay "
            "attribution notes)")
define_flag("FLAGS_analysis_ici_gbps", 90.0,
            "per-link ICI bandwidth (GB/s) the static cost model "
            "(analysis/costmodel.py) charges collectives on intra-slice "
            "mesh axes against in its alpha-beta model")
define_flag("FLAGS_analysis_dcn_gbps", 12.5,
            "per-host DCN bandwidth (GB/s) for collectives on mesh axes "
            "a MeshConfig maps to the data-center network "
            "(MeshConfig.dcn_axes — the hybrid-mesh fabric split)")
define_flag("FLAGS_analysis_ici_alpha_us", 1.0,
            "per-hop ICI latency (microseconds) — the alpha term of the "
            "static cost model's alpha-beta collective estimate")
define_flag("FLAGS_analysis_dcn_alpha_us", 25.0,
            "per-hop DCN latency (microseconds) — the alpha term for "
            "collectives on dcn-mapped mesh axes")
define_flag("FLAGS_analysis_plan_regress_pct", 20.0,
            "D18 audit_plan threshold: the chosen MeshConfig predicted "
            "at least this percent slower than the best valid candidate "
            "in the same PlanReport is a lint warning")
define_flag("FLAGS_analysis_hbm_limit_mb", 0.0,
            "per-device HBM budget (MiB) for the static liveness pass: "
            "a candidate plan whose predicted peak exceeds it is "
            "rejected in autoplan.search and is a D18 error for the "
            "chosen config (0 = no budget check)")
define_flag("FLAGS_analysis_calibration_tol_pct", 10.0,
            "D19 audit_cost_model_calibration tie tolerance: a "
            "predicted-order pair only counts as a misprediction when "
            "the measured tok/s of the predicted-slower config beats "
            "the predicted-faster one by more than this percent "
            "(virtual-mesh walls are noisy; near-ties are not signal)")
define_flag("FLAGS_pallas_decode", True,
            "route paged decode attention through the Pallas flash-decode "
            "kernel (ops/pallas_decode.py) on TPU above the size "
            "threshold; off = the XLA gather+softmax composition "
            "everywhere")
define_flag("FLAGS_kv_block_size", 16,
            "tokens per KV-cache block in the paged serving engine "
            "(text/paged_cache.py); must be a multiple of 8 so a "
            "(block_size, head_dim) cache tile is sublane-aligned")
define_flag("FLAGS_kv_cache_dtype", "model",
            "paged KV cache storage dtype: model (match the model's "
            "compute dtype) | int8 (per-block-scale quantized cache — "
            "decode reads halve; blocks requantize on append)")
define_flag("FLAGS_serving_slots", 8,
            "slot count of the continuous-batching serving engine "
            "(inference/engine.py): the fixed request-slot array the "
            "per-step program runs over; requests join freed slots "
            "mid-flight")
define_flag("FLAGS_prefix_cache", True,
            "content-hash full KV blocks in the paged serving engine and "
            "serve shared prompt prefixes from cached blocks (zero "
            "prefill for those pages); finish releases blocks to an LRU "
            "of refcount-0 cached blocks instead of the free list, "
            "copy-on-write guards partially-overwritten shared blocks")
define_flag("FLAGS_chunked_prefill_tokens", 256,
            "split prompt prefill into chunks of at most this many "
            "tokens, one chunk per scheduler tick interleaved with "
            "decode — bounds the head-of-line TTFT/TPOT cost of a long "
            "prompt on in-flight decodes; 0 = monolithic prefill "
            "(cache-hit suffixes still ride one chunk program)")
define_flag("FLAGS_prefix_cache_max_blocks", 0,
            "cap on refcount-0 cached prefix blocks held in the LRU "
            "(0 = bounded only by pool pressure); eviction never touches "
            "a block a live request references")
define_flag("FLAGS_residual_dtype", "float32",
            "dtype of the transformer residual stream in text/models "
            "(float32 | bfloat16): bfloat16 keeps every inter-kernel "
            "activation crossing HBM in bf16 — f32 survives only inside "
            "the norm kernels' accumulation — halving the elementwise "
            "traffic on this bandwidth-capped device; loss drift is "
            "bounded by tests/test_pallas_norm.py")
define_flag("FLAGS_obs_metrics", False,
            "opt-in for obs registry instrumentation OUTSIDE the serving "
            "engine (hapi TelemetryCallback auto-attach in fit()); the "
            "serving engine always records into its own registry and the "
            "compile watchdog always records compile events — both are "
            "off the steady-state hot path")
define_flag("FLAGS_obs_log_path", "",
            "JSONL event log path (obs/metrics.py): compile events, "
            "logger records and registry snapshots append here as one "
            "structured line each; empty = disabled")
define_flag("FLAGS_obs_compile_storm_threshold", 8,
            "compile watchdog (obs/watchdog.py): more than this many "
            "DISTINCT program keys for one (site, family) is a "
            "recompile-storm warning in audit_recompiles — bucketing "
            "keeps real ladders O(log L), exact-length keying blows "
            "past it")
define_flag("FLAGS_ckpt_save_retries", 3,
            "checkpoint saves retry transient IO errors this many times "
            "with exponential backoff before surfacing "
            "CheckpointSaveError (ckpt/core.py); applies to sync and "
            "async saves alike")
define_flag("FLAGS_ckpt_retry_backoff_s", 0.05,
            "base of the checkpoint-save retry backoff: attempt k sleeps "
            "base * 2^k seconds")
define_flag("FLAGS_ckpt_async", True,
            "CheckpointCallback commits checkpoints on the background "
            "thread (the device->host copy stays synchronous, so the "
            "next step's donation can't race the bytes being written); "
            "off = every periodic save blocks the train loop")
define_flag("FLAGS_ckpt_max_in_flight", 2,
            "bound on queued async checkpoint saves; AsyncCheckpointer."
            "save() blocks (backpressure) when this many are already in "
            "flight instead of accumulating unbounded host copies")
define_flag("FLAGS_ckpt_keep_last_n", 0,
            "checkpoint retention: keep only the newest N committed "
            "checkpoints under a root (0 = keep all); the dir the "
            "`latest` pointer names is never deleted, deletion is "
            "strictly oldest-first and only touches fully-committed "
            "dirs (ckpt/core.py gc_checkpoints)")
define_flag("FLAGS_ckpt_stall_seconds", 300.0,
            "checkpoint-stall watchdog: a save whose wall time exceeds "
            "this becomes an obs.audit_ckpt_stalls warning finding "
            "(gated by the graft_lint ckpt smoke)")
define_flag("FLAGS_obs_http_port", 0,
            "when > 0 the ServingEngine exposes its metrics registry at "
            "http://127.0.0.1:<port>/metrics (Prometheus text "
            "exposition, stdlib http.server daemon thread); 0 = off")
define_flag("FLAGS_obs_log_max_mb", 64,
            "size cap in MB for the JSONL event log at FLAGS_obs_log_path "
            "(obs/metrics.py): past the cap the file rotates to "
            "<path>.1 .. <path>.N between records — a line is never torn "
            "mid-write; 0 = unbounded (the pre-round-14 behavior)")
define_flag("FLAGS_obs_log_backups", 3,
            "rolled JSONL event-log files kept after rotation "
            "(<path>.1 newest .. <path>.N oldest); the oldest is deleted "
            "when a rotation would exceed N")
define_flag("FLAGS_obs_flight_requests", 256,
            "per-engine flight-recorder ring capacity (obs/flight.py): "
            "finished request timelines kept for dump_trace(); the "
            "oldest finished flight is evicted past the cap — active "
            "requests are never evicted")
define_flag("FLAGS_obs_flight_dir", "",
            "anomaly auto-dump directory for the flight recorder: on a "
            "request timeout, a TTFT SLO breach "
            "(FLAGS_obs_slo_ttft_ms) or a post-warmup compile the "
            "engine writes a Chrome-trace JSON postmortem here "
            "(flight_<trigger>_<n>.json, capped per engine); empty = "
            "record but never auto-dump")
define_flag("FLAGS_obs_slo_ttft_ms", 0.0,
            "TTFT SLO in ms for the flight recorder's anomaly trigger: "
            "a request whose first token lands later than this "
            "auto-dumps the flight ring (FLAGS_obs_flight_dir) and "
            "counts serving_flight_dumps_total{trigger=slo_breach}; "
            "0 = no SLO trigger")
define_flag("FLAGS_obs_cost_capture", True,
            "capture XLA cost_analysis()/memory_analysis() (flops, bytes "
            "accessed, HBM footprint) into the compile event and the "
            "per-program cost ledger (obs/costs.py) at the AOT compile "
            "sites (serving buckets, generation engine; to_static under "
            "FLAGS_jit_debug_program) — compiled executables carry the "
            "analysis for free, no extra compile is paid")
define_flag("FLAGS_obs_peak_gbps", 0.0,
            "peak HBM bandwidth (GB/s) the roofline_utilization gauges "
            "divide achieved bytes/s by; 0 = per-backend default (103 "
            "on this axon-tunnel TPU — the measured round-4 roofline — "
            "else a nominal host number, do not quote off-chip)")
define_flag("FLAGS_obs_cost_regress_pct", 25.0,
            "analysis D8 (audit_cost_regressions) threshold: a compiled "
            "program whose bytes-accessed grew more than this percent "
            "over tools/cost_baseline.json fails lint like a dtype "
            "regression")
define_flag("FLAGS_obs_train_flight_steps", 64,
            "training flight-recorder ring capacity "
            "(obs/train_flight.py): finished per-step span timelines "
            "kept for dump_trace(); the oldest finished step is evicted "
            "past the cap — the active step never is")
define_flag("FLAGS_obs_data_wait_ms", 100.0,
            "data-starvation threshold for the training flight recorder "
            "and analysis D12: a step whose data_wait span (loader "
            "blocked before the batch arrived) exceeds this many ms "
            "counts a data_starvation anomaly and auto-dumps the step "
            "ring (FLAGS_obs_flight_dir); 0 = trigger off")
define_flag("FLAGS_obs_step_spike_factor", 3.0,
            "step-time-spike anomaly trigger: a train step whose wall "
            "exceeds this factor times the rolling median of recent "
            "steps (min population 8) auto-dumps the step ring; "
            "0 = trigger off")
define_flag("FLAGS_obs_peak_tflops", 0.0,
            "peak device compute (TFLOP/s, bf16) the train_mfu gauges "
            "divide achieved FLOP/s by; 0 = per-backend default "
            "(obs/goodput.py PEAK_TFLOPS_DEFAULTS — a nominal host "
            "number off-chip, do not quote)")
define_flag("FLAGS_partitioner_heuristics", True,
            "declarative partitioner (distributed/partitioner): "
            "rule-match UNANNOTATED parameters by shape/name heuristics "
            "(2D up/down projections, embedding-shaped tables) instead "
            "of leaving them replicated; every guess is a named note in "
            "the PartitionPlan surfaced by the graft_lint spmd smoke")
define_flag("FLAGS_partitioner_sep_impl", "ring",
            "attention exchange for sep-axis (context-parallel) "
            "partitioner configs: ring (lax.ppermute K/V rotation, any "
            "head count) | ulysses (all-to-all seq<->head transpose, "
            "needs heads % sep == 0 — falls back to ring otherwise)")
define_flag("FLAGS_partitioner_fsdp_min_size", 1024,
            "parameters with fewer elements than this stay replicated "
            "instead of ZeRO-3 fsdp-sharded (tiny tensors pay the "
            "per-use all-gather latency without meaningful HBM savings)")
define_flag("FLAGS_spec_decode", "off",
            "speculative decoding on the paged serving engine "
            "(inference/speculative.py): off | ngram (model-free "
            "prompt-lookup proposer — the tail of prompt+generation is "
            "matched against earlier history and the continuation "
            "proposed) | draft (a small draft model proposes; pass it "
            "via SpecConfig(draft_model=...)). Proposed tokens are "
            "verified K+1 at a time in ONE batched paged-attention "
            "pass; greedy outputs stay token-identical to the "
            "non-speculative engine")
define_flag("FLAGS_spec_k", 4,
            "speculation depth: tokens proposed per verify window "
            "(each window scores K+1 candidate positions in one pass "
            "and emits 1..K+1 tokens depending on acceptance)")
define_flag("FLAGS_spec_min_accept", 0.1,
            "D16 audit_spec_decode acceptance floor: a WARMED engine "
            "whose overall speculative acceptance rate falls below "
            "this fraction is burning verify FLOPs for no goodput — "
            "lint warning (graft_lint `paged` smoke fire-fixture "
            "self-tests the detector)")
define_flag("FLAGS_router_policy", "prefix_affine",
            "placement policy of the multi-replica serving router "
            "(serving/router.py): prefix_affine (route by prompt "
            "fingerprint to the replica whose prefix cache already "
            "holds the blocks, falling back to least_loaded) | "
            "least_loaded (queue depth + free-block budget from "
            "stats()) | round_robin")
define_flag("FLAGS_router_fingerprint_blocks", 1024,
            "per-replica bound on the router's prefix fingerprint "
            "index: block hashes remembered per replica for "
            "prefix_affine placement (LRU beyond the cap; 0 disables "
            "fingerprint tracking and prefix_affine degrades to "
            "least_loaded)")
define_flag("FLAGS_router_sessions_max", 4096,
            "session-affinity map bound: session IDs the router pins "
            "to their replica (LRU beyond the cap — an evicted session "
            "re-pins via the placement policy on its next turn)")
define_flag("FLAGS_router_drain_ms", 10000.0,
            "default drain deadline for router.drain(): in-flight "
            "requests on the draining replica get at most this many "
            "ms to finish before the round-12 per-request deadline "
            "path timeout-finishes them (0 = wait forever)")
define_flag("FLAGS_router_skew_pct", 0.9,
            "D17 audit_fleet placement-skew threshold: one replica "
            "taking more than this fraction of routed requests while "
            "another ready replica got none is a lint warning "
            "(graft_lint `router` smoke self-tests the detector)")
define_flag("FLAGS_weight_only_dtype", "none",
            "default weight-only quantization of the serving engines' "
            "decode matmuls + lm_head (text/generation.py, "
            "inference/engine.py): none | int8 (per-channel scales, the "
            "round-5 1.67× bandwidth win) | int4 (true 2-nibbles-per-byte "
            "packed storage, ops/quantized.py — packed bytes are the only "
            "HBM weight traffic); per-call weight_quant= overrides")
define_flag("FLAGS_pallas_quant_matmul", True,
            "route int4 weight-only matmuls through the Pallas fused "
            "dequant-matmul kernel (ops/quantized.py: unpack + scale in "
            "VMEM) on TPU above the size threshold; off = the XLA "
            "take-bits composition everywhere (the parity oracle)")
define_flag("FLAGS_amp_fp8", False,
            "fp8 GEMM training leg of the amp policy (amp/fp8.py): the "
            "decoder-block projections (qkv/o/gate/up/down) run "
            "e4m3-forward / e5m2-gradient matmuls with delayed scaling — "
            "per-tensor amax history rings threaded as state through "
            "to_static, never host round-trips; loss parity vs bf16 is "
            "bounded by tests/test_quantized.py")
define_flag("FLAGS_fp8_amax_history", 16,
            "length of the per-tensor amax history ring delayed fp8 "
            "scaling maxes over (amp/fp8.py Fp8State)")
define_flag("FLAGS_debug_thread_checks", False,
            "owner-thread contract assertions on the deliberately "
            "single-threaded serving objects (ServingEngine, "
            "PagedKVCache's block pool, PrefixCache): a call from a "
            "thread other than the first user raises "
            "ConcurrencyContractError and records a D15 lint violation "
            "(core/lockdep.py ThreadContract). Debug mode — the "
            "graft_lint `conc` smoke and the thread-stress tests enable "
            "it; production leaves the checks compiled out to one flag "
            "lookup per engine call")


# the full reference flag surface (compat entries; must come after the
# real-behavior definitions above so those win)
from . import flags_compat as _flags_compat  # noqa: E402,F401
