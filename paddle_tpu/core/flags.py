"""Global flag registry.

Reference parity: paddle's gflags-compatible registry (paddle/common/flags.h:38,
flags.cc: 187 PHI_DEFINE_EXPORTED_* definitions) exposed through
paddle.set_flags/get_flags and FLAGS_* env vars. Same surface here; flags also
seed from the environment at import.
"""
from __future__ import annotations

import os
from typing import Any

_REGISTRY: dict[str, dict[str, Any]] = {}


def define_flag(name: str, default, doc: str = ""):
    if not name.startswith("FLAGS_"):
        name = "FLAGS_" + name
    env = os.environ.get(name)
    value = default
    if env is not None:
        value = _parse(env, default)
    _REGISTRY[name] = {"value": value, "default": default, "doc": doc}
    return value


def _parse(text: str, default):
    if isinstance(default, bool):
        return text.lower() in ("1", "true", "yes", "on")
    if isinstance(default, int):
        return int(text)
    if isinstance(default, float):
        return float(text)
    return text


def set_flags(flags: dict):
    for k, v in flags.items():
        if not k.startswith("FLAGS_"):
            k = "FLAGS_" + k
        if k not in _REGISTRY:
            _REGISTRY[k] = {"value": v, "default": v, "doc": "(ad-hoc)"}
        else:
            _REGISTRY[k]["value"] = v


def get_flags(flags) -> dict:
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for k in flags:
        key = k if k.startswith("FLAGS_") else "FLAGS_" + k
        out[k] = _REGISTRY[key]["value"]
    return out


def flag(name: str):
    key = name if name.startswith("FLAGS_") else "FLAGS_" + name
    return _REGISTRY[key]["value"]


# Core flags (subset of reference's 187; grows as subsystems land).
define_flag("FLAGS_check_nan_inf", False, "scan every op output for nan/inf")
define_flag("FLAGS_use_compiled_eager", True, "jit-compile per-op eager dispatch")
define_flag("FLAGS_eager_cache_size", 4096, "per-op executable cache entries")
define_flag("FLAGS_to_static_donate", True, "donate captured buffers in to_static")
define_flag("FLAGS_enable_double_grad", True,
            "record per-node re-derivation ctx for grad(create_graph=True); "
            "disable to shed the extra operand retention")
define_flag("FLAGS_log_level", 0, "VLOG-style verbosity")
define_flag("FLAGS_cudnn_deterministic", False, "parity shim; XLA is deterministic")
define_flag("FLAGS_embedding_deterministic", False, "parity shim")
define_flag("FLAGS_allocator_strategy", "xla", "parity shim; XLA owns allocation")
