"""Device/place abstraction over jax.devices().

Reference parity: paddle Places (phi/common/place.h) + DeviceManager
(paddle/phi/backends/device_manager.h:134). TPU-first: a "place" names a jax
device; default compute device is jax's default backend (TPU when present).
"""
from __future__ import annotations

import functools

import jax


class Place:
    """A device place. Wraps one jax.Device."""

    def __init__(self, device: "jax.Device | None" = None):
        self._device = device

    @property
    def jax_device(self):
        if self._device is None:
            self._device = jax.devices()[0]
        return self._device

    def is_cpu_place(self):
        return self.jax_device.platform == "cpu"

    def is_tpu_place(self):
        return self.jax_device.platform in ("tpu", "axon")

    def is_gpu_place(self):  # parity shim; never true on this stack
        return self.jax_device.platform == "gpu"

    def __eq__(self, other):
        return isinstance(other, Place) and self.jax_device == other.jax_device

    def __hash__(self):
        return hash(self.jax_device)

    def __repr__(self):
        d = self.jax_device
        return f"Place({d.platform}:{d.id})"


class CPUPlace(Place):
    def __init__(self, idx: int = 0):
        devs = [d for d in jax.devices("cpu")] if _has_platform("cpu") else []
        super().__init__(devs[idx] if devs else None)


class TPUPlace(Place):
    def __init__(self, idx: int = 0):
        devs = _accelerators()
        super().__init__(devs[idx] if idx < len(devs) else None)


# Paddle calls its accelerator place CUDAPlace; alias for API parity.
CUDAPlace = TPUPlace
XPUPlace = TPUPlace
CustomPlace = TPUPlace


@functools.lru_cache(maxsize=None)
def _has_platform(platform: str) -> bool:
    try:
        return len(jax.devices(platform)) > 0
    except RuntimeError:
        return False


def _accelerators():
    for p in ("tpu", "axon", "gpu"):
        if _has_platform(p):
            return jax.devices(p)
    return jax.devices()


_current_device: Place | None = None


def get_device() -> str:
    d = (_current_device or Place()).jax_device
    plat = "tpu" if d.platform in ("tpu", "axon") else d.platform
    return f"{plat}:{d.id}"


def set_device(device: str) -> Place:
    global _current_device
    if isinstance(device, Place):
        _current_device = device
        return _current_device
    name = device.split(":")[0]
    idx = int(device.split(":")[1]) if ":" in device else 0
    if name in ("cpu",):
        _current_device = CPUPlace(idx)
    else:
        _current_device = TPUPlace(idx)
    return _current_device


def current_place() -> Place:
    return _current_device or Place()


def _validate_place(device) -> None:
    """Accept a Place or a device string like 'cpu'/'gpu:0'/'tpu:0'; reject
    anything unparseable (used by Layer.to / Tensor.to device args)."""
    if isinstance(device, Place):
        return
    if not isinstance(device, str):
        raise ValueError(f"unsupported device spec {device!r}")
    name = device.split(":")[0]
    if name not in ("cpu", "gpu", "tpu", "xpu", "npu", "custom_device", "axon"):
        raise ValueError(f"unsupported device {device!r}")


def device_count() -> int:
    return len(_accelerators())


def is_compiled_with_cuda() -> bool:  # parity shim
    return False


def is_compiled_with_tpu() -> bool:
    return _has_platform("tpu") or _has_platform("axon")


class CUDAPinnedPlace(Place):
    """≙ paddle CUDAPinnedPlace (page-locked host staging memory). Host↔TPU
    transfers here always stage through pinned-equivalent buffers managed by
    the XLA runtime, so this place is informational (host-device backed)."""

    def __init__(self):
        super().__init__(None)

    @property
    def jax_device(self):
        import jax as _jax

        if self._device is None:
            try:
                self._device = _jax.devices("cpu")[0]
            except RuntimeError:
                self._device = _jax.devices()[0]
        return self._device

    def __repr__(self):
        return "Place(cuda_pinned)"
