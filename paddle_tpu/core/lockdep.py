"""Runtime lock-order + thread-contract instrumentation ("lockdep").

The reference's thread-heavy C++ runtime leans on TSAN and years of soak;
this reproduction's concurrency surface is Python — AsyncCheckpointer
commit threads, the shared /metrics HTTP server, comm/compile watchdogs,
RPC serve loops, the per-instance to_static RLock — so it gets the
kernel-lockdep treatment instead:

  * :class:`TrackedLock` (``make_lock``/``make_rlock``) — a NAMED wrapper
    over ``threading.Lock``/``RLock``. While ``enable()`` is on, every
    acquire records the acquiring thread's current HELD-SET and each
    (held → acquired) pair becomes an edge in a process-global
    lock-ORDER graph. A cycle in that graph is a latent deadlock even if
    no actual run ever interleaved badly — the whole point of auditing
    the order instead of waiting for the hang. Locks are named per
    class/site (kernel lockdep's "lock classes"), so two Registry
    instances share one graph node and cross-instance inversions are
    visible.
  * :func:`note_blocking` — instrumented blocking sites (fsync in
    ckpt/core, compile recording in obs/watchdog) report here; holding a
    ``hot=True`` lock (metrics registry / metric setup / JSONL sink /
    /metrics endpoint / logging — locks on scrape and instrumentation
    paths) across one is a violation: a slow fsync under the sink lock
    stalls every logger in the process.
  * :class:`ThreadContract` — the declared owner-thread contract of the
    deliberately single-threaded serving objects (ServingEngine,
    PagedKVCache's block pool, PrefixCache). The contract binds to the
    first thread that exercises it; under ``FLAGS_debug_thread_checks``
    a call from any other thread raises
    :class:`ConcurrencyContractError` AND records the violation for the
    lint audit. ``rebind()`` is the explicit handoff for legitimate
    ownership transfer (a router draining a replica).

``paddle_tpu.analysis.concurrency`` turns this state into Findings (D14
``conc-lock-order`` / ``conc-blocking-under-lock``, D15
``conc-thread-contract``); the graft_lint ``conc`` smoke drives a
multi-threaded serving+scrape+ckpt+watchdog stress with recording on and
gates on an acyclic graph with zero violations.

Overhead when disabled (the default): one module-bool check per
acquire/release and one flag lookup per contract check — nothing on the
per-op hot paths (the metrics observe/inc path takes no lock at all, by
design; see obs/metrics.py).
"""
from __future__ import annotations

import os
import threading
import traceback

from .flags import flag

#: recording switch — enable()/disable(); kept a plain module bool so the
#: disabled acquire path costs one attribute load
_enabled = False

#: caps on recorded state (a runaway graph must degrade, not grow)
_CAP_EDGES = 4096
_CAP_EVENTS = 1024

#: lockdep's own bookkeeping lock — a RAW threading.Lock on purpose: the
#: instrumentation must never observe itself
_meta = threading.Lock()

_edges: dict = {}                # guarded-by: _meta — (held, acquired) -> info
_locks_seen: dict = {}           # guarded-by: _meta — name -> acquire count
_blocking: list = []             # guarded-by: _meta — blocking-under-hot-lock
_contract_violations: list = []  # guarded-by: _meta — ThreadContract breaches

_tls = threading.local()   # per-thread held-set: [[name, hot, depth, id]]


def _held() -> list:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def _stack_summary(skip: int = 2, depth: int = 5) -> str:
    frames = traceback.extract_stack()[:-skip][-depth:]
    return " > ".join(f"{os.path.basename(f.filename)}:{f.lineno}"
                      for f in frames)


class TrackedLock:
    """Named lock wrapper feeding the order graph. Drop-in for the
    ``with lock:`` / ``acquire``/``release`` surface the framework uses."""

    __slots__ = ("name", "hot", "_lock")

    def __init__(self, name: str, hot: bool = False, reentrant: bool = False):
        self.name = str(name)
        self.hot = bool(hot)
        self._lock = threading.RLock() if reentrant else threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._lock.acquire(blocking, timeout)
        if ok and _enabled:
            held = _held()
            for entry in held:
                if entry[3] == id(self):
                    entry[2] += 1   # reentrant re-acquire (RLock): no edge
                    return ok
            with _meta:
                _locks_seen[self.name] = _locks_seen.get(self.name, 0) + 1
                for hname, _hot, _n, _hid in held:
                    # NOTE: a DIFFERENT instance of the same lock class
                    # deliberately records the (name, name) self-edge —
                    # kernel-lockdep semantics: same-class nesting is a
                    # latent inversion unless an explicit order exists,
                    # and suppressing it would hide A->B/B->A deadlocks
                    # between two instances of one class
                    key = (hname, self.name)
                    e = _edges.get(key)
                    if e is not None:
                        e["count"] += 1
                    elif len(_edges) < _CAP_EDGES:
                        _edges[key] = {
                            "count": 1,
                            "thread": threading.current_thread().name,
                            "stack": _stack_summary(skip=3)}
            held.append([self.name, self.hot, 1, id(self)])
        return ok

    def release(self):
        # the held-set pop is UNCONDITIONAL (entries are only ever
        # pushed while enabled): gating it on _enabled left a permanent
        # phantom entry when recording was disabled between a thread's
        # acquire and release — every later enable() then fabricated
        # "stale-lock -> X" order edges from that thread
        held = getattr(_tls, "held", None)
        if held:
            for i in range(len(held) - 1, -1, -1):
                if held[i][3] == id(self):
                    held[i][2] -= 1
                    if held[i][2] == 0:
                        del held[i]
                    break
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._lock.locked() if hasattr(self._lock, "locked") else None

    def __repr__(self):
        return f"TrackedLock({self.name!r}, hot={self.hot})"


def make_lock(name: str, hot: bool = False) -> TrackedLock:
    """A tracked ``threading.Lock``. ``hot=True`` marks locks on the
    scrape/instrumentation paths: blocking work (fsync/compile/HTTP)
    under a hot lock is a D14 violation."""
    return TrackedLock(name, hot=hot)


def make_rlock(name: str, hot: bool = False) -> TrackedLock:
    """A tracked ``threading.RLock`` (reentrant re-acquires record no
    edge)."""
    return TrackedLock(name, hot=hot, reentrant=True)


def note_blocking(kind: str, detail: str = "", allow: tuple = ()):
    """An instrumented blocking site (fsync, compile, outbound HTTP).
    Records a violation when the calling thread holds any hot tracked
    lock not named in ``allow`` (a sink's own lock legitimately guards
    its own IO)."""
    if not _enabled:
        return
    held = getattr(_tls, "held", None)
    if not held:
        return
    hot = [name for name, is_hot, _n, _hid in held
           if is_hot and name not in allow]
    if not hot:
        return
    with _meta:
        if len(_blocking) < _CAP_EVENTS:
            _blocking.append({
                "kind": str(kind), "detail": str(detail)[:200],
                "locks": hot,
                "thread": threading.current_thread().name,
                "stack": _stack_summary(skip=3)})


# ------------------------------------------------------ thread contracts

class ConcurrencyContractError(AssertionError):
    """A declared single-owner object was driven from a second thread."""


class ThreadContract:
    """Owner-thread contract: binds to the first checking thread; any
    other thread fails the check (under FLAGS_debug_thread_checks)."""

    __slots__ = ("name", "_owner", "_owner_name")

    def __init__(self, name: str):
        self.name = str(name)
        self._owner = None
        self._owner_name = ""

    def check(self, op: str = ""):
        if not flag("FLAGS_debug_thread_checks"):
            return
        t = threading.get_ident()
        if self._owner is None:
            # bind under the meta lock: two threads racing the FIRST
            # check is exactly the cross-thread misuse this detector
            # exists for — an unsynchronized check-then-set would let
            # both pass and the loser silently steal ownership
            with _meta:
                if self._owner is None:
                    self._owner = t
                    self._owner_name = threading.current_thread().name
                    return
        if t != self._owner:
            rec = {"contract": self.name, "op": str(op),
                   "owner": self._owner_name,
                   "caller": threading.current_thread().name,
                   "stack": _stack_summary(skip=3)}
            with _meta:
                if len(_contract_violations) < _CAP_EVENTS:
                    _contract_violations.append(rec)
            raise ConcurrencyContractError(
                f"{self.name}.{op or 'call'}: owner-thread contract "
                f"violated — bound to thread {self._owner_name!r}, called "
                f"from {rec['caller']!r}. This object is deliberately "
                "single-threaded (README: Serving / thread contract); a "
                "router or driver must serialize access, or rebind() "
                "after an explicit ownership handoff.")

    def rebind(self):
        """Explicit ownership handoff: the next check() rebinds."""
        self._owner = None
        self._owner_name = ""

    @property
    def owner_thread(self) -> str:
        return self._owner_name


# ------------------------------------------------------- state / queries

def enable():
    global _enabled
    _enabled = True


def disable():
    global _enabled
    _enabled = False


def enabled() -> bool:
    return _enabled


def reset():
    """Drop the recorded graph/violations (fixture isolation). Held-set
    entries of threads currently inside a tracked lock are per-thread
    and survive — call with helper threads joined."""
    with _meta:
        _edges.clear()
        _locks_seen.clear()
        del _blocking[:]
        del _contract_violations[:]


def lock_graph() -> dict:
    """{(held_name, acquired_name): {count, thread, stack}} snapshot."""
    with _meta:
        return {k: dict(v) for k, v in _edges.items()}


def locks_seen() -> dict:
    with _meta:
        return dict(_locks_seen)


def blocking_violations() -> list:
    with _meta:
        return [dict(v) for v in _blocking]


def contract_violations() -> list:
    with _meta:
        return [dict(v) for v in _contract_violations]


def find_cycles(edges: dict | None = None) -> list:
    """Simple cycles in the lock-order graph, each as a node path
    ``[a, b, ..., a]``; one representative per distinct node set."""
    if edges is None:
        edges = lock_graph()
    adj: dict = {}
    for a, b in edges:
        adj.setdefault(a, []).append(b)
    cycles: list = []
    seen_sets: set = set()
    color: dict = {}
    path: list = []

    def visit(u):
        color[u] = 1
        path.append(u)
        for v in sorted(adj.get(u, ())):
            c = color.get(v)
            if c == 1:
                cyc = path[path.index(v):] + [v]
                key = frozenset(cyc)
                if key not in seen_sets:
                    seen_sets.add(key)
                    cycles.append(cyc)
            elif c is None:
                visit(v)
        path.pop()
        color[u] = 2

    for n in sorted(adj):
        if color.get(n) is None:
            visit(n)
    return cycles
