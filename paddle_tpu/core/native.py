"""Native (C++) runtime component loader.

The hot runtime pieces that are C++ in the reference stay C++ here
(SURVEY §2.1): csrc/*.cpp are compiled with g++ on first use into cached
shared objects and bound via ctypes (pybind11 isn't vendored in this
image). Every native component has a pure-Python fallback — load() returns
None when the toolchain is unavailable and callers degrade gracefully.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess

from . import lockdep

_CSRC = os.path.join(os.path.dirname(os.path.dirname(__file__)), "csrc")
_BUILD = os.path.join(_CSRC, "_build")
# build-under-lock is deliberate (serializes concurrent g++ builds onto
# the atomic-rename cache), so this lock is NOT marked hot
_lock = lockdep.make_lock("core.native._lock")
_cache: dict[str, object] = {}    # guarded-by: _lock


def _compile(name: str) -> str | None:
    src = os.path.join(_CSRC, f"{name}.cpp")
    if not os.path.exists(src):
        return None
    with open(src, "rb") as f:
        tag = hashlib.sha1(f.read()).hexdigest()[:12]
    so = os.path.join(_BUILD, f"{name}-{tag}.so")
    if os.path.exists(so):
        return so
    os.makedirs(_BUILD, exist_ok=True)
    tmp = so + f".tmp{os.getpid()}"
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", src, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, so)  # atomic: concurrent builders race safely
        return so
    except (subprocess.SubprocessError, OSError):
        return None


def load(name: str):
    """ctypes.CDLL for csrc/<name>.cpp, or None (no toolchain / bad build)."""
    with _lock:
        if name in _cache:
            lib = _cache[name]
            return lib if lib is not None else None
        so = _compile(name)
        lib = None
        if so is not None:
            try:
                lib = ctypes.CDLL(so)
            except OSError:
                lib = None
        _cache[name] = lib
        return lib


def ring_lib():
    lib = load("ring_queue")
    if lib is not None and not getattr(lib, "_typed", False):
        u64, i64, i32 = ctypes.c_uint64, ctypes.c_longlong, ctypes.c_int
        p = ctypes.c_void_p
        lib.ring_header_bytes.restype = u64
        lib.ring_init.argtypes = [p, u64]
        lib.ring_push.argtypes = [p, ctypes.c_char_p, u64]
        lib.ring_push.restype = i32
        lib.ring_next_size.argtypes = [p]
        lib.ring_next_size.restype = i64
        lib.ring_pop.argtypes = [p, ctypes.c_char_p, u64]
        lib.ring_pop.restype = i64
        lib._typed = True
    return lib


def tracer_lib():
    lib = load("host_tracer")
    if lib is not None and not getattr(lib, "_typed", False):
        u64, u32 = ctypes.c_uint64, ctypes.c_uint32
        lib.tracer_intern.argtypes = [ctypes.c_char_p]
        lib.tracer_intern.restype = u32
        lib.tracer_name.argtypes = [u32]
        lib.tracer_name.restype = ctypes.c_char_p
        lib.tracer_record.argtypes = [u32, u64, u64, u32]
        lib.tracer_count.restype = u64
        lib.tracer_drain.argtypes = [ctypes.POINTER(u32), ctypes.POINTER(u32),
                                     ctypes.POINTER(u64), ctypes.POINTER(u64),
                                     u64]
        lib.tracer_drain.restype = u64
        lib._typed = True
    return lib
