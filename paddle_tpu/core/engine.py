"""Reverse-mode autograd engine: reverse-topological walk over GradNodes.

Reference parity: egr::RunBackward (paddle/fluid/eager/backward.cc:105) —
in-degree counted over the reachable subgraph, queue-driven, with
GradTensorHolder-style accumulation and per-tensor hooks. Cotangents for
non-differentiable (integer) op outputs use jax's float0 convention.
"""
from __future__ import annotations

from collections import defaultdict, deque

import jax
import jax.numpy as jnp
import numpy as np

from . import dtype as dtypes
from .dispatch import GradNode, no_grad
from .tensor import Tensor


def _zero_cotangent(shape, dtype):
    if dtypes.is_floating_point(dtype) or dtypes.is_complex(dtype):
        return jnp.zeros(shape, dtype)
    return np.zeros(shape, jax.dtypes.float0)


def _accum(slot, value):
    return value if slot is None else slot + value


# ------------------------------------------------- zero-bubble dW/dX split
# Reference parity: the zero-bubble pipeline pass splits matmul_grad into a
# dX job (critical path: feeds the previous stage's backward) and a dW job
# (fills the bubble later) —
# /root/reference/python/paddle/distributed/passes/pipeline_scheduler_pass/
# pipeline_zero_bubble.py:62,151. Here the split is a VJP-rule override: for
# weight-bearing ops the engine computes the activation grad immediately and
# defers a thunk computing the weight grads. Compiled once via jax.jit.

_dx_linear = jax.jit(lambda g, w: g @ w.T)
_dw_linear = jax.jit(
    lambda x, g: jnp.einsum("ni,no->io", x.reshape(-1, x.shape[-1]),
                            g.reshape(-1, g.shape[-1])))
_db_linear = jax.jit(lambda g: g.reshape(-1, g.shape[-1]).sum(0))


def _split_linear(node, cot):
    """Split rule for F.linear(x, w[, b]) where w/b are graph leaves.

    Returns (in_grads aligned with node.inputs, deferred thunks) or None
    when the node isn't splittable (x not differentiated, or the weight is
    itself a non-leaf — e.g. tied/derived weights need the fused vjp)."""
    # positions must be exactly (x=0, w=1[, b=2]): a frozen weight with a
    # trainable bias (diff_idx [0, 2]) would misalign inputs[1] onto the bias
    if node.diff_idx not in ([0, 1], [0, 1, 2]):
        return None
    weights = node.inputs[1:]
    if not weights or any(t._node is not None for t in weights):
        return None
    x = node.inputs[0]
    xd = x._data
    in_grads = [_dx_linear(cot, weights[0]._data)]
    thunks = []
    w = weights[0]
    thunks.append((w, lambda _x=xd, _g=cot: _dw_linear(_x, _g)))
    in_grads.append(None)
    if len(weights) > 1:  # bias
        b = weights[1]
        thunks.append((b, lambda _g=cot: _db_linear(_g)))
        in_grads.append(None)
    return in_grads, thunks


#: op name -> split rule. matmul/einsum variants can register here too; the
#: transformer hot path (every Linear) is what zero-bubble needs.
SPLIT_VJP_RULES = {"linear": _split_linear}


def flush_deferred(deferred: list) -> int:
    """Run deferred dW thunks, accumulating into parameter .grad (the
    bubble-filling phase of the zero-bubble schedule). Returns #thunks."""
    n = 0
    with no_grad():
        for t, thunk in deferred:
            g = thunk()
            for hook in t._hooks:
                out = hook(Tensor(g, _internal=True))
                if out is not None:
                    g = out._data if isinstance(out, Tensor) else out
            if not t.stop_gradient:
                _write_grad_raw(t, g)
            n += 1
    deferred.clear()
    return n


def _write_grad_raw(t, g_raw):
    """Accumulate a raw array into t.grad IN PLACE (buffer swap on the
    existing grad Tensor) so an active to_static trace records the write as
    a program output — a step that ends with live grads (gradient merge's
    accumulate program) must emit them, not leak tracers."""
    if t._grad is None:
        t._grad = Tensor(_accum(None, g_raw), _internal=True)
        from .dispatch import current_trace

        tr = current_trace()
        if tr is not None:
            tr.on_mutate(t._grad)
    else:
        t._grad._assign_raw(_accum(t._grad._data, g_raw))


def _write_grad(t, g, accum_tensor, create_graph=False):
    """Tensor-level variant: create_graph keeps the Tensor-add path (the
    accumulation itself must be on the tape)."""
    if create_graph:
        t._grad = accum_tensor(t._grad, g)
    else:
        _write_grad_raw(t, g._data if isinstance(g, Tensor) else g)


def _regrad(node, cots):
    """Re-derive a node's input grads THROUGH op_call so the backward
    computation itself lands on the tape (create_graph=True). The node's
    saved (fn, datas) ctx is re-traced with jax.vjp; cotangents enter as
    differentiable operands, so grad-of-grad chains through both the
    primals and the upstream cotangents."""
    from .dispatch import op_call

    fn, datas = node.ctx
    from .dispatch import _PackedSaved

    datas = [d.get() if isinstance(d, _PackedSaved) else d for d in datas]
    diff_idx = node.diff_idx or []
    k = len(diff_idx)
    # float cotangents ride as op args (differentiable); float0 stay closed over
    float_pos = [i for i, c in enumerate(cots) if isinstance(c, Tensor)]
    closed = [c._data if isinstance(c, Tensor) else c for c in cots]
    single = node.single_out

    def grad_fn(*vals):
        dvals = vals[:k]
        cot_vals = list(closed)
        for j, p in enumerate(float_pos):
            cot_vals[p] = vals[k + j]
        full = list(datas)
        for i, v in zip(diff_idx, dvals):
            full[i] = v

        def primal(*ds):
            vs = list(full)
            for i, dv in zip(diff_idx, ds):
                vs[i] = dv
            return fn(*vs)

        _out, vjp = jax.vjp(primal, *dvals)
        # Paddle↔JAX complex grad convention bridge (see dispatch._complexify_vjp)
        conj = lambda v: jnp.conj(v) if jnp.iscomplexobj(v) else v
        if single:
            cot_in = conj(cot_vals[0])
        else:
            cot_in = tuple(conj(c) for c in cot_vals)
        return tuple(conj(g) for g in vjp(cot_in))

    args = list(node.inputs) + [cots[p] for p in float_pos]
    out = op_call(grad_fn, *args, name=node.name + "_grad")
    return list(out) if isinstance(out, tuple) else [out]


def run_backward(root: Tensor, grad_tensor=None, retain_graph: bool = False,
                 deferred: list | None = None, create_graph: bool = False,
                 restrict_to: set | None = None):
    """deferred: when a list is passed, weight grads of splittable ops are
    NOT computed now — (param, thunk) pairs are appended for a later
    flush_deferred() call (zero-bubble dX phase).

    create_graph: backward ops are recorded on the tape (via _regrad), so
    the returned/accumulated grads support another backward (double grad,
    ≙ eager/backward.cc grad-of-grad). Implies retain_graph.

    restrict_to: ids of the only tensors allowed to receive .grad —
    paddle.grad() semantics (other leaves stay untouched)."""
    if root.stop_gradient:
        raise RuntimeError(
            "Tensor.backward() on a tensor with stop_gradient=True — nothing to do"
        )
    if grad_tensor is None:
        if root.size != 1:
            raise RuntimeError(
                f"grad must be provided for non-scalar backward root (shape {root.shape})"
            )
        seed = jnp.ones(root._data.shape, root._data.dtype)
    else:
        seed = grad_tensor._data if isinstance(grad_tensor, Tensor) else jnp.asarray(grad_tensor)

    if root._node is None:
        if restrict_to is None or id(root) in restrict_to:
            _write_grad_raw(root, seed)
        return

    # -- collect reachable graph + consumer counts
    root_node = root._node
    nodes: set[int] = set()
    consumers: dict[int, int] = defaultdict(int)  # id(node) -> #edges from reachable consumers
    stack = [root_node]
    node_by_id: dict[int, GradNode] = {}
    while stack:
        n = stack.pop()
        if id(n) in nodes:
            continue
        nodes.add(id(n))
        node_by_id[id(n)] = n
        for t in n.inputs:
            pn = t._node
            if pn is not None:
                consumers[id(pn)] += 1
                if id(pn) not in nodes:
                    stack.append(pn)

    pending: dict[int, list] = {
        nid: [None] * len(node_by_id[nid].out_avals) for nid in nodes
    }
    pending[id(root_node)][root._out_idx] = _accum(
        pending[id(root_node)][root._out_idx], seed
    )
    remaining = dict(consumers)

    queue = deque()
    if remaining.get(id(root_node), 0) == 0:
        queue.append(root_node)

    retain_graph = retain_graph or create_graph
    import contextlib

    def as_tensor(g):
        return g if isinstance(g, Tensor) else Tensor(g, _internal=True)

    def accum_tensor(slot, g) -> Tensor:
        """slot: Tensor | raw array | None. One accumulation rule for both
        .grad writes and pending cotangent slots, in both grad modes."""
        if create_graph:
            g = as_tensor(g)
            return g if slot is None else as_tensor(slot) + g
        gd = g._data if isinstance(g, Tensor) else g
        sd = slot._data if isinstance(slot, Tensor) else slot
        return Tensor(_accum(sd, gd), _internal=True)

    grad_mode = contextlib.nullcontext() if create_graph else no_grad()
    with grad_mode:
        while queue:
            node = queue.popleft()
            outs = pending.pop(id(node))
            cots = [
                g if g is not None else _zero_cotangent(shape, dt)
                for g, (shape, dt) in zip(outs, node.out_avals)
            ]
            if node.vjp_fn is None:
                raise RuntimeError(
                    "Trying to backward through the graph a second time; "
                    "call backward(retain_graph=True) the first time."
                )
            in_grads = None
            if create_graph:
                if node.ctx is None:
                    # no re-derivation context — silently treating the
                    # cotangents as constants would drop Hessian terms,
                    # so refuse loudly
                    from .flags import flag as _flag

                    if not _flag("FLAGS_enable_double_grad"):
                        raise NotImplementedError(
                            "create_graph=True needs per-node re-derivation "
                            "ctx, but FLAGS_enable_double_grad is disabled — "
                            "re-enable it (paddle.set_flags) and rebuild the "
                            "graph")
                    raise NotImplementedError(
                        f"create_graph=True through '{node.name}' "
                        "(a hand-built GradNode) is not supported; use "
                        "paddle_tpu.incubate.autograd (jax transform "
                        "composition) for higher-order grads of this op")
                cots_t = [c if isinstance(c, Tensor) or
                          (hasattr(c, "dtype") and c.dtype == jax.dtypes.float0)
                          else as_tensor(c) for c in cots]
                in_grads = _regrad(node, cots_t)
            else:
                raw = [c._data if isinstance(c, Tensor) else c for c in cots]
                cot = raw[0] if node.single_out else tuple(raw)
                if deferred is not None and node.name in SPLIT_VJP_RULES:
                    split = SPLIT_VJP_RULES[node.name](node, cot)
                    if split is not None:
                        in_grads, thunks = split
                        deferred.extend(thunks)
                if in_grads is None:
                    in_grads = node.vjp_fn(cot)
            if not retain_graph:
                node.vjp_fn = None
                node.ctx = None  # release the pinned input buffers too
            for t, g in zip(node.inputs, in_grads):
                if g is None or (hasattr(g, "dtype") and g.dtype == jax.dtypes.float0):
                    continue
                for hook in t._hooks:
                    out = hook(as_tensor(g))
                    if out is not None:
                        g = out if create_graph and isinstance(out, Tensor) else (
                            out._data if isinstance(out, Tensor) else out)
                pn = t._node
                allowed = restrict_to is None or id(t) in restrict_to
                if pn is None:
                    if not t.stop_gradient and allowed:
                        _write_grad(t, g, accum_tensor, create_graph)
                else:
                    if t._retain_grads and allowed:
                        _write_grad(t, g, accum_tensor, create_graph)
                    if id(pn) in pending:
                        pending[id(pn)][t._out_idx] = accum_tensor(
                            pending[id(pn)][t._out_idx], g)
                        remaining[id(pn)] -= 1
                        if remaining[id(pn)] == 0:
                            queue.append(pn)


def grad(outputs, inputs, grad_outputs=None, retain_graph=False, create_graph=False,
         allow_unused=False):
    """paddle.grad — functional gradient of outputs w.r.t. inputs.

    create_graph=True records the backward pass on the tape (via _regrad),
    so the returned grads can be backward()ed again — gradient penalties,
    Hessian-vector products, etc. (≙ eager/backward.cc double grad).
    """
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)

    saved = [(t._grad, t._retain_grads) for t in inputs]
    for t in inputs:
        t._grad = None
        t._retain_grads = True
    try:
        only = {id(t) for t in inputs}
        for i, (o, go) in enumerate(zip(outputs, grad_outputs)):
            last = i == len(outputs) - 1
            run_backward(o, go, retain_graph=retain_graph if last else True,
                         create_graph=create_graph, restrict_to=only)
        result = []
        for t in inputs:
            if t._grad is None and not allow_unused:
                raise RuntimeError(f"input {t.name} unused in graph (allow_unused=False)")
            result.append(t._grad)
    finally:
        for t, (g, r) in zip(inputs, saved):
            t._grad, t._retain_grads = g, r
    return result
