"""Reference flag surface (compat layer).

Reference parity: every PHI_DEFINE_EXPORTED_* flag from
/root/reference/paddle/common/flags.cc (185 definitions) is registered here
so `paddle.set_flags` / `paddle.get_flags` / `FLAGS_*` env vars accept the
full reference surface. Flags whose subsystem is replaced wholesale on TPU
(CUDA libraries, CINN, PIR, the allocator, PS/GPU-graph) are accepted and
carried with a doc explaining the TPU-native analog; flags with real
TPU-side behavior live in flags.py (check_nan_inf, benchmark, caches,
to_static switches) and win over the entries below.
"""
from .flags import _REGISTRY, define_flag


def _define(name, default, doc):
    if name not in _REGISTRY:  # real-behavior definitions in flags.py win
        define_flag(name, default, doc)


_define("FLAGS_inner_op_parallelism", 0,
            "accepted for API compatibility (see doc for the TPU-native analog)")
_define("FLAGS_paddle_num_threads", 1,
            "accepted for API compatibility (see doc for the TPU-native analog)")
_define("FLAGS_enable_opt_get_features", False,
            "accepted for API compatibility (see doc for the TPU-native analog)")
_define("FLAGS_enable_cublas_tensor_op_math", False,
            "accepted, no effect on TPU: CUDA/vendor-library subsystem is replaced by XLA")
_define("FLAGS_gemm_use_half_precision_compute_type", False,
            "accepted for API compatibility (see doc for the TPU-native analog)")
_define("FLAGS_selected_gpus", '',
            "accepted for API compatibility (see doc for the TPU-native analog)")
_define("FLAGS_cublaslt_exhaustive_search_times", 0,
            "accepted, no effect on TPU: CUDA/vendor-library subsystem is replaced by XLA")
_define("FLAGS_enable_api_kernel_fallback", True,
            "accepted for API compatibility (see doc for the TPU-native analog)")
_define("FLAGS_cudnn_exhaustive_search_times", -1,
            "accepted, no effect on TPU: CUDA/vendor-library subsystem is replaced by XLA")
_define("FLAGS_batch_norm_use_miopen", False,
            "accepted, no effect on TPU: CUDA/vendor-library subsystem is replaced by XLA")
_define("FLAGS_cudnn_batchnorm_spatial_persistent", False,
            "accepted, no effect on TPU: CUDA/vendor-library subsystem is replaced by XLA")
_define("FLAGS_communicator_max_merge_var_num", 20,
            "accepted, no effect: PS/GPU-graph stack is out of north-star scope (SURVEY §7)")
_define("FLAGS_communicator_is_sgd_optimizer", True,
            "accepted, no effect: PS/GPU-graph stack is out of north-star scope (SURVEY §7)")
_define("FLAGS_communicator_send_queue_size", 20,
            "accepted, no effect: PS/GPU-graph stack is out of north-star scope (SURVEY §7)")
_define("FLAGS_dist_threadpool_size", 0,
            "accepted, no effect: PS/GPU-graph stack is out of north-star scope (SURVEY §7)")
_define("FLAGS_fast_eager_deletion_mode", True,
            "accepted for API compatibility (see doc for the TPU-native analog)")
_define("FLAGS_memory_fraction_of_eager_deletion", 1.0,
            "accepted, no effect: device memory is managed by the XLA allocator (use XLA_PYTHON_CLIENT_MEM_FRACTION / _PREALLOCATE env vars)")
_define("FLAGS_fraction_of_cpu_memory_to_use", 1,
            "accepted, no effect: device memory is managed by the XLA allocator (use XLA_PYTHON_CLIENT_MEM_FRACTION / _PREALLOCATE env vars)")
_define("FLAGS_fraction_of_cuda_pinned_memory_to_use", 0.5,
            "accepted, no effect on TPU: CUDA/vendor-library subsystem is replaced by XLA")
_define("FLAGS_initial_gpu_memory_in_mb", 0,
            "accepted, no effect: device memory is managed by the XLA allocator (use XLA_PYTHON_CLIENT_MEM_FRACTION / _PREALLOCATE env vars)")
_define("FLAGS_reallocate_gpu_memory_in_mb", 0,
            "accepted, no effect: device memory is managed by the XLA allocator (use XLA_PYTHON_CLIENT_MEM_FRACTION / _PREALLOCATE env vars)")
_define("FLAGS_auto_growth_chunk_size_in_mb", 0,
            "accepted, no effect: device memory is managed by the XLA allocator (use XLA_PYTHON_CLIENT_MEM_FRACTION / _PREALLOCATE env vars)")
_define("FLAGS_local_exe_sub_scope_limit", 256.0,
            "accepted, no effect: the executor is the XLA runtime (SURVEY §7 L8)")
_define("FLAGS_reader_queue_speed_test_mode", False,
            "accepted for API compatibility (see doc for the TPU-native analog)")
_define("FLAGS_use_mkldnn", False,
            "accepted, no effect on TPU: CUDA/vendor-library subsystem is replaced by XLA")
_define("FLAGS_sort_sum_gradient", False,
            "accepted for API compatibility (see doc for the TPU-native analog)")
_define("FLAGS_tracer_onednn_ops_on", '',
            "accepted, no effect on TPU: CUDA/vendor-library subsystem is replaced by XLA")
_define("FLAGS_static_runtime_data_save_path", './',
            "accepted, no effect: the executor is the XLA runtime (SURVEY §7 L8)")
_define("FLAGS_tracer_onednn_ops_off", '',
            "accepted, no effect on TPU: CUDA/vendor-library subsystem is replaced by XLA")
_define("FLAGS_check_kernel_launch", False,
            "accepted for API compatibility (see doc for the TPU-native analog)")
_define("FLAGS_conv2d_disable_cudnn", False,
            "accepted, no effect on TPU: CUDA/vendor-library subsystem is replaced by XLA")
_define("FLAGS_use_fast_math", False,
            "accepted for API compatibility (see doc for the TPU-native analog)")
_define("FLAGS_get_host_by_name_time", 120,
            "accepted for API compatibility (see doc for the TPU-native analog)")
_define("FLAGS_save_static_runtime_data", False,
            "accepted, no effect: the executor is the XLA runtime (SURVEY §7 L8)")
_define("FLAGS_graph_load_in_parallel", False,
            "accepted, no effect: PS/GPU-graph stack is out of north-star scope (SURVEY §7)")
_define("FLAGS_enable_neighbor_list_use_uva", False,
            "accepted for API compatibility (see doc for the TPU-native analog)")
_define("FLAGS_graph_neighbor_size_percent", 1.0,
            "accepted, no effect: PS/GPU-graph stack is out of north-star scope (SURVEY §7)")
_define("FLAGS_graph_metapath_split_opt", False,
            "accepted, no effect: PS/GPU-graph stack is out of north-star scope (SURVEY §7)")
_define("FLAGS_graph_get_neighbor_id", False,
            "accepted, no effect: PS/GPU-graph stack is out of north-star scope (SURVEY §7)")
_define("FLAGS_enable_exit_when_partial_worker", False,
            "accepted for API compatibility (see doc for the TPU-native analog)")
_define("FLAGS_enable_adjust_op_order", 0,
            "accepted for API compatibility (see doc for the TPU-native analog)")
_define("FLAGS_gpugraph_storage_mode", 1,
            "accepted, no effect: PS/GPU-graph stack is out of north-star scope (SURVEY §7)")
_define("FLAGS_run_kp_kernel", False,
            "accepted for API compatibility (see doc for the TPU-native analog)")
_define("FLAGS_allow_cinn_ops", '',
            "accepted, no effect: CINN's role (fusion/scheduling) is owned by XLA")
_define("FLAGS_deny_cinn_ops", '',
            "accepted, no effect: CINN's role (fusion/scheduling) is owned by XLA")
_define("FLAGS_enable_cinn_compile_cache", True,
            "accepted, no effect: CINN's role (fusion/scheduling) is owned by XLA")
_define("FLAGS_cinn_compile_thread_num", -1,
            "accepted, no effect: CINN's role (fusion/scheduling) is owned by XLA")
_define("FLAGS_enable_interpretercore_launch_cinn", True,
            "accepted, no effect: CINN's role (fusion/scheduling) is owned by XLA")
_define("FLAGS_enable_cinn_auto_tune", False,
            "accepted, no effect: CINN's role (fusion/scheduling) is owned by XLA")
_define("FLAGS_cinn_specify_input_dynamic_dim", False,
            "accepted, no effect on TPU: CUDA/vendor-library subsystem is replaced by XLA")
_define("FLAGS_cinn_input_dynamic_dim_spec_file", '',
            "accepted, no effect on TPU: CUDA/vendor-library subsystem is replaced by XLA")
_define("FLAGS_new_executor_use_cuda_graph", False,
            "accepted, no effect on TPU: CUDA/vendor-library subsystem is replaced by XLA")
_define("FLAGS_use_cuda_malloc_async_allocator", False,
            "accepted, no effect on TPU: CUDA/vendor-library subsystem is replaced by XLA")
_define("FLAGS_cuda_malloc_async_pool_memory_throttle_ratio", 0.8,
            "accepted, no effect on TPU: CUDA/vendor-library subsystem is replaced by XLA")
_define("FLAGS_auto_free_cudagraph_allocations_on_launch", True,
            "accepted, no effect on TPU: CUDA/vendor-library subsystem is replaced by XLA")
_define("FLAGS_executor_log_deps_every_microseconds", 0,
            "accepted, no effect: the executor is the XLA runtime (SURVEY §7 L8)")
_define("FLAGS_gpugraph_enable_hbm_table_collision_stat", False,
            "accepted, no effect: PS/GPU-graph stack is out of north-star scope (SURVEY §7)")
_define("FLAGS_cache_inference_while_scope", False,
            "accepted, no effect: the executor is the XLA runtime (SURVEY §7 L8)")
_define("FLAGS_gpugraph_hbm_table_load_factor", 0.75,
            "accepted, no effect: PS/GPU-graph stack is out of north-star scope (SURVEY §7)")
_define("FLAGS_gpugraph_enable_gpu_direct_access", False,
            "accepted, no effect: PS/GPU-graph stack is out of north-star scope (SURVEY §7)")
_define("FLAGS_gpugraph_enable_segment_merge_grads", False,
            "accepted, no effect: PS/GPU-graph stack is out of north-star scope (SURVEY §7)")
_define("FLAGS_gpugraph_merge_grads_segment_size", 128,
            "accepted, no effect: PS/GPU-graph stack is out of north-star scope (SURVEY §7)")
_define("FLAGS_gpugraph_slot_feasign_max_num", 5,
            "accepted, no effect: PS/GPU-graph stack is out of north-star scope (SURVEY §7)")
_define("FLAGS_gpugraph_dedup_pull_push_mode", 0,
            "accepted, no effect: PS/GPU-graph stack is out of north-star scope (SURVEY §7)")
_define("FLAGS_gpugraph_load_node_list_into_hbm", True,
            "accepted, no effect: PS/GPU-graph stack is out of north-star scope (SURVEY §7)")
_define("FLAGS_gpugraph_sparse_table_storage_mode", 0,
            "accepted, no effect: PS/GPU-graph stack is out of north-star scope (SURVEY §7)")
_define("FLAGS_enable_auto_detect_gpu_topo", True,
            "accepted for API compatibility (see doc for the TPU-native analog)")
_define("FLAGS_enable_auto_rdma_trans", True,
            "accepted for API compatibility (see doc for the TPU-native analog)")
_define("FLAGS_enable_tracker_all2all", False,
            "accepted, no effect: PS/GPU-graph stack is out of north-star scope (SURVEY §7)")
_define("FLAGS_enable_all2all_use_fp16", False,
            "accepted, no effect: PS/GPU-graph stack is out of north-star scope (SURVEY §7)")
_define("FLAGS_enable_sparse_inner_gather", False,
            "accepted, no effect: PS/GPU-graph stack is out of north-star scope (SURVEY §7)")
_define("FLAGS_gpugraph_debug_gpu_memory", False,
            "accepted, no effect: PS/GPU-graph stack is out of north-star scope (SURVEY §7)")
_define("FLAGS_graph_embedding_split_infer_mode", True,
            "accepted, no effect: PS/GPU-graph stack is out of north-star scope (SURVEY §7)")
_define("FLAGS_enable_graph_multi_node_sampling", False,
            "accepted, no effect: PS/GPU-graph stack is out of north-star scope (SURVEY §7)")
_define("FLAGS_query_dest_rank_by_multi_node", False,
            "accepted for API compatibility (see doc for the TPU-native analog)")
_define("FLAGS_multi_node_sample_use_gpu_table", True,
            "accepted for API compatibility (see doc for the TPU-native analog)")
_define("FLAGS_nccl_blocking_wait", False,
            "accepted, no effect on TPU: CUDA/vendor-library subsystem is replaced by XLA")
_define("FLAGS_benchmark_nccl", False,
            "accepted, no effect on TPU: CUDA/vendor-library subsystem is replaced by XLA")
_define("FLAGS_eager_communication_connection", False,
            "accepted for API compatibility (see doc for the TPU-native analog)")
_define("FLAGS_tcp_max_syn_backlog", 2048,
            "accepted for API compatibility (see doc for the TPU-native analog)")
_define("FLAGS_use_autotune", False,
            "accepted for API compatibility (see doc for the TPU-native analog)")
_define("FLAGS_disable_dyshape_in_train", False,
            "accepted for API compatibility (see doc for the TPU-native analog)")
_define("FLAGS_enable_cinn_accuracy_check", False,
            "accepted, no effect: CINN's role (fusion/scheduling) is owned by XLA")
_define("FLAGS_enable_fuse_parallel_matmul_pass", True,
            "accepted, no effect: CINN's role (fusion/scheduling) is owned by XLA")
_define("FLAGS_enable_fusion_fallback", False,
            "accepted, no effect: CINN's role (fusion/scheduling) is owned by XLA")
_define("FLAGS_enable_fusion_result_check", False,
            "accepted, no effect: CINN's role (fusion/scheduling) is owned by XLA")
_define("FLAGS_enable_transpose_iters_in_fusion", True,
            "accepted, no effect: CINN's role (fusion/scheduling) is owned by XLA")
_define("FLAGS_enable_reuse_iters_in_fusion", True,
            "accepted, no effect: CINN's role (fusion/scheduling) is owned by XLA")
_define("FLAGS_enable_append_iters_in_fusion", True,
            "accepted, no effect: CINN's role (fusion/scheduling) is owned by XLA")
_define("FLAGS_search_cache_max_number", 1000000,
            "accepted for API compatibility (see doc for the TPU-native analog)")
_define("FLAGS_einsum_opt", False,
            "accepted for API compatibility (see doc for the TPU-native analog)")
_define("FLAGS_enable_auto_layout_pass", False,
            "accepted, no effect: CINN's role (fusion/scheduling) is owned by XLA")
_define("FLAGS_npu_storage_format", False,
            "accepted, no effect on TPU: CUDA/vendor-library subsystem is replaced by XLA")
_define("FLAGS_enable_cudnn_frontend", False,
            "accepted, no effect on TPU: CUDA/vendor-library subsystem is replaced by XLA")
_define("FLAGS_cudnn_cache_saturation_count", 1,
            "accepted, no effect on TPU: CUDA/vendor-library subsystem is replaced by XLA")
_define("FLAGS_trt_ibuilder_cache", False,
            "accepted, no effect on TPU: CUDA/vendor-library subsystem is replaced by XLA")
_define("FLAGS_use_shm_cache", False,
            "accepted for API compatibility (see doc for the TPU-native analog)")
_define("FLAGS_dataloader_use_file_descriptor", False,
            "accepted for API compatibility (see doc for the TPU-native analog)")
_define("FLAGS_enable_pir_in_executor", False,
            "accepted, no effect: PIR/ProgramDesc is replaced by jaxpr/StableHLO")
_define("FLAGS_enable_pir_with_pt_in_dy2st", True,
            "accepted, no effect: PIR/ProgramDesc is replaced by jaxpr/StableHLO")
_define("FLAGS_logging_pir_py_code_dir", '',
            "accepted, no effect: PIR/ProgramDesc is replaced by jaxpr/StableHLO")
_define("FLAGS_logging_pir_py_code_int_tensor_element_limit", 2048,
            "accepted, no effect: PIR/ProgramDesc is replaced by jaxpr/StableHLO")
_define("FLAGS_logging_trunc_pir_py_code", True,
            "accepted, no effect: PIR/ProgramDesc is replaced by jaxpr/StableHLO")
_define("FLAGS_logging_pir_py_code_dump_symbolic_dims", False,
            "accepted, no effect: PIR/ProgramDesc is replaced by jaxpr/StableHLO")
_define("FLAGS_pir_interpreter_record_stream_for_gc_cache", False,
            "accepted, no effect: PIR/ProgramDesc is replaced by jaxpr/StableHLO")
_define("FLAGS_enable_pir_in_executor_trace_run", False,
            "accepted, no effect: PIR/ProgramDesc is replaced by jaxpr/StableHLO")
_define("FLAGS_pir_apply_inplace_pass", True,
            "accepted, no effect: PIR/ProgramDesc is replaced by jaxpr/StableHLO")
_define("FLAGS_ir_inplace_kernel_blacklist", '',
            "accepted, no effect: PIR/ProgramDesc is replaced by jaxpr/StableHLO")
_define("FLAGS_enable_record_memory", False,
            "accepted, no effect: device memory is managed by the XLA allocator (use XLA_PYTHON_CLIENT_MEM_FRACTION / _PREALLOCATE env vars)")
_define("FLAGS_eager_delete_scope", True,
            "accepted, no effect: device memory is managed by the XLA allocator (use XLA_PYTHON_CLIENT_MEM_FRACTION / _PREALLOCATE env vars)")
_define("FLAGS_host_trace_level", 1,
            "accepted for API compatibility (see doc for the TPU-native analog)")
_define("FLAGS_multiple_of_cupti_buffer_size", 1,
            "accepted, no effect on TPU: CUDA/vendor-library subsystem is replaced by XLA")
_define("FLAGS_print_ir", False,
            "accepted, no effect: PIR/ProgramDesc is replaced by jaxpr/StableHLO")
_define("FLAGS_prim_skip_dynamic", True,
            "accepted, no effect: PIR/ProgramDesc is replaced by jaxpr/StableHLO")
_define("FLAGS_prim_enable_dynamic", False,
            "accepted, no effect: PIR/ProgramDesc is replaced by jaxpr/StableHLO")
_define("FLAGS_prim_check_ops", False,
            "accepted, no effect: PIR/ProgramDesc is replaced by jaxpr/StableHLO")
_define("FLAGS_prim_forward_blacklist", '',
            "accepted, no effect: PIR/ProgramDesc is replaced by jaxpr/StableHLO")
_define("FLAGS_disable_logging_op_attr_list", '',
            "accepted for API compatibility (see doc for the TPU-native analog)")
_define("FLAGS_dynamic_static_unified_comm", True,
            "accepted for API compatibility (see doc for the TPU-native analog)")
_define("FLAGS_enable_async_trace", False,
            "accepted for API compatibility (see doc for the TPU-native analog)")
_define("FLAGS_async_trace_count", 5,
            "accepted for API compatibility (see doc for the TPU-native analog)")
_define("FLAGS_use_auto_growth_pinned_allocator", False,
            "accepted, no effect: device memory is managed by the XLA allocator (use XLA_PYTHON_CLIENT_MEM_FRACTION / _PREALLOCATE env vars)")
_define("FLAGS_sync_after_alloc", False,
            "accepted, no effect: device memory is managed by the XLA allocator (use XLA_PYTHON_CLIENT_MEM_FRACTION / _PREALLOCATE env vars)")
_define("FLAGS_alloc_fill_value", -1,
            "accepted, no effect: device memory is managed by the XLA allocator (use XLA_PYTHON_CLIENT_MEM_FRACTION / _PREALLOCATE env vars)")
_define("FLAGS_pir_apply_shape_optimization_pass", False,
            "accepted, no effect: PIR/ProgramDesc is replaced by jaxpr/StableHLO")
_define("FLAGS_pir_broadcast_tree_limit", 32,
            "accepted, no effect: PIR/ProgramDesc is replaced by jaxpr/StableHLO")
_define("FLAGS_nvidia_package_dir", '',
            "accepted, no effect on TPU: CUDA/vendor-library subsystem is replaced by XLA")
_define("FLAGS_cudnn_dir", '',
            "accepted, no effect on TPU: CUDA/vendor-library subsystem is replaced by XLA")
_define("FLAGS_cublas_dir", '',
            "accepted, no effect on TPU: CUDA/vendor-library subsystem is replaced by XLA")
_define("FLAGS_nccl_dir", '',
            "accepted, no effect on TPU: CUDA/vendor-library subsystem is replaced by XLA")
_define("FLAGS_cupti_dir", '',
            "accepted, no effect on TPU: CUDA/vendor-library subsystem is replaced by XLA")
_define("FLAGS_mklml_dir", '',
            "accepted, no effect on TPU: CUDA/vendor-library subsystem is replaced by XLA")
_define("FLAGS_lapack_dir", '',
            "accepted, no effect on TPU: CUDA/vendor-library subsystem is replaced by XLA")
_define("FLAGS_check_infer_symbolic", False,
            "accepted for API compatibility (see doc for the TPU-native analog)")
_define("FLAGS_manually_trans_conv_filter", False,
            "accepted for API compatibility (see doc for the TPU-native analog)")
_define("FLAGS_enable_cse_in_dy2st", True,
            "accepted, no effect: PIR/ProgramDesc is replaced by jaxpr/StableHLO")
_define("FLAGS_cse_max_count", -1,
            "accepted, no effect: PIR/ProgramDesc is replaced by jaxpr/StableHLO")
_define("FLAGS_enable_blaslt_global_search", False,
            "accepted for API compatibility (see doc for the TPU-native analog)")
_define("FLAGS_cublaslt_device_best_config", '',
            "accepted, no effect on TPU: CUDA/vendor-library subsystem is replaced by XLA")
_define("FLAGS_use_xqa_optim", False,
            "accepted, no effect on TPU: CUDA/vendor-library subsystem is replaced by XLA")
_define("FLAGS_cuda_core_int8_gemm", False,
            "accepted, no effect on TPU: CUDA/vendor-library subsystem is replaced by XLA")
_define("FLAGS_mkl_dir", '',
            "accepted, no effect on TPU: CUDA/vendor-library subsystem is replaced by XLA")
_define("FLAGS_op_dir", '',
            "accepted for API compatibility (see doc for the TPU-native analog)")
_define("FLAGS_cusparselt_dir", '',
            "accepted, no effect on TPU: CUDA/vendor-library subsystem is replaced by XLA")
_define("FLAGS_curand_dir", '',
            "accepted, no effect on TPU: CUDA/vendor-library subsystem is replaced by XLA")
_define("FLAGS_cusolver_dir", '',
            "accepted, no effect on TPU: CUDA/vendor-library subsystem is replaced by XLA")
_define("FLAGS_cusparse_dir", '',
            "accepted, no effect on TPU: CUDA/vendor-library subsystem is replaced by XLA")
_define("FLAGS_win_cuda_bin_dir", '',
            "accepted, no effect on TPU: CUDA/vendor-library subsystem is replaced by XLA")
_define("FLAGS_enable_collect_shape", False,
            "accepted for API compatibility (see doc for the TPU-native analog)")
_define("FLAGS_accuracy_check_atol_fp32", 1e-6,
            "tolerance consumed by paddle.amp.debugging accuracy comparison")
_define("FLAGS_accuracy_check_rtol_fp32", 1e-6,
            "tolerance consumed by paddle.amp.debugging accuracy comparison")
_define("FLAGS_accuracy_check_atol_fp16", 1e-3,
            "tolerance consumed by paddle.amp.debugging accuracy comparison")
_define("FLAGS_accuracy_check_rtol_fp16", 1e-3,
            "tolerance consumed by paddle.amp.debugging accuracy comparison")
_define("FLAGS_accuracy_check_atol_bf16", 1e-3,
            "tolerance consumed by paddle.amp.debugging accuracy comparison")
_define("FLAGS_accuracy_check_rtol_bf16", 1e-3,
            "tolerance consumed by paddle.amp.debugging accuracy comparison")
_define("FLAGS_pinned_memory_as_cpu_backend", False,
            "accepted, no effect: device memory is managed by the XLA allocator (use XLA_PYTHON_CLIENT_MEM_FRACTION / _PREALLOCATE env vars)")
_define("FLAGS_trt_min_group_size", 3,
            "accepted, no effect on TPU: CUDA/vendor-library subsystem is replaced by XLA")
_define("FLAGS_fused_multi_transformer_op_use_mbfmha", False,
            "accepted for API compatibility (see doc for the TPU-native analog)")
_define("FLAGS_multi_block_attention_min_partition_size", 1024,
            "accepted for API compatibility (see doc for the TPU-native analog)")
_define("FLAGS_save_cf_stack_op", False,
            "accepted, no effect: PIR/ProgramDesc is replaced by jaxpr/StableHLO")
