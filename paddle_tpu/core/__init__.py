from . import dtype, device, flags
from .tensor import Tensor, Parameter, to_tensor
from .dispatch import no_grad, enable_grad, set_grad_enabled, op_call, grad_enabled
from .engine import run_backward, grad
from .rng import seed, get_rng_state, set_rng_state
