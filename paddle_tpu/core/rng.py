"""Global RNG state with trace-aware key threading.

Reference parity: paddle.seed + per-device generators
(python/paddle/framework/random.py). TPU-first: state is a counter-free jax
PRNG key held in a Tensor so that `to_static` capture machinery threads it
through compiled programs automatically (each traced step consumes and
rewrites the key — no stale-randomness, no recompilation).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .dispatch import current_trace
from .tensor import Tensor

_key_tensor: Tensor | None = None


def seed(value: int):
    global _key_tensor
    _key_tensor = Tensor(jax.random.PRNGKey(value), _internal=True)
    return _key_tensor


def _state() -> Tensor:
    global _key_tensor
    if _key_tensor is None:
        seed(0)
    return _key_tensor


def next_key():
    """Split the global key; returns a raw jax key for immediate consumption."""
    kt = _state()
    tr = current_trace()
    if tr is not None:
        tr.on_read(kt)
        tr.on_mutate(kt)
    new, sub = jax.random.split(kt._data)
    kt._data = new
    return sub


def get_rng_state():
    return [_state().numpy()]


def set_rng_state(state):
    global _key_tensor
    _key_tensor = Tensor(jnp.asarray(state[0]), _internal=True)
