"""Eager Tensor: paddle.Tensor semantics over immutable jax.Array buffers.

Reference parity: the eager Tensor (paddle/phi/api/include/tensor.h:82 +
pybind eager_method.cc). Mutability (add_, set_value, optimizer updates) is
buffer-swap: ._data is replaced, never written through — old autograd
residuals keep referencing the old immutable buffers, so in-place updates
under no_grad are always safe. ``stop_gradient`` defaults True like paddle;
Parameters default False.

Most operator methods are attached by paddle_tpu.ops at import time (the
analog of generated pybind tensor methods).
"""
from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from . import dtype as dtypes
from .device import Place, current_place
from .dispatch import current_trace, no_grad


class Tensor:
    __slots__ = (
        "_data_buf",
        "stop_gradient",
        "_grad",
        "_node",
        "_out_idx",
        "name",
        "persistable",
        "_retain_grads",
        "_hooks",
        "_dist_attr",
        "_buf_version",
        "_seq",
        "__weakref__",
        "__dict__",
    )

    _iid = 0
    # globally-monotonic buffer-state counter: every construction AND every
    # buffer swap draws a fresh value, so no two buffer states ever share a
    # version — unlike id(), which CPython reuses after free (caches keying
    # on id() alone could silently serve stale weights). The bump lives in
    # the `_data` property setter so EVERY buffer swap in the codebase
    # (to_static _finish, checkpoint load, optimizer lr writes, ...) bumps
    # it — not just the _assign_raw funnel.
    _next_buf_version = 0

    @property
    def _data(self):
        return self._data_buf

    @_data.setter
    def _data(self, value):
        self._data_buf = value
        Tensor._next_buf_version += 1
        self._buf_version = Tensor._next_buf_version

    def __init__(self, data, dtype=None, place=None, stop_gradient=True, _internal=False):
        if _internal:
            self._data = data
        else:
            self._data = _to_jax(data, dtype, place)
        self.stop_gradient = stop_gradient
        self._grad = None
        self._node = None
        self._out_idx = 0
        Tensor._iid += 1
        # creation-order stamp: dy2static uses it to tell tensors that
        # existed BEFORE a converted branch ran (external reads to thread
        # as op operands) from intermediates the branch itself created
        self._seq = Tensor._iid
        self.name = f"tensor_{Tensor._iid}"
        self.persistable = False
        self._retain_grads = False
        self._hooks = []
        self._dist_attr = None

    # ------------------------------------------------------------ properties
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    dim = ndim

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def dtype(self):
        return np.dtype(self._data.dtype)

    @property
    def place(self):
        devs = getattr(self._data, "devices", None)
        if devs is not None and not isinstance(self._data, jax.core.Tracer):
            try:
                ds = self._data.devices()
            except RuntimeError:  # buffer donated/deleted by a jitted step
                ds = None
            if ds:
                return Place(next(iter(ds)))
        return current_place()

    @property
    def grad(self):
        return self._grad

    @grad.setter
    def grad(self, value):
        self._grad = value

    @property
    def is_leaf(self):
        return self._node is None

    # ------------------------------------------------- dist tensor surface
    @property
    def placements(self):
        return list(self._dist_attr.placements) if self._dist_attr is not None else None

    @property
    def process_mesh(self):
        return self._dist_attr.process_mesh if self._dist_attr is not None else None

    def is_dist(self):
        return self._dist_attr is not None

    def retain_grads(self):
        self._retain_grads = True

    def register_hook(self, hook):
        self._hooks.append(hook)

        class _Handle:
            def remove(_self):
                try:
                    self._hooks.remove(hook)
                except ValueError:
                    pass

        return _Handle()

    # ------------------------------------------------------------ conversion
    def numpy(self):
        return np.asarray(self._data)

    def item(self, *args):
        if args:
            return self.numpy().item(*args)
        return self.numpy().item()

    def tolist(self):
        return self.numpy().tolist()

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    def detach(self) -> "Tensor":
        t = Tensor(self._data, _internal=True, stop_gradient=True)
        return t

    def clone(self) -> "Tensor":
        from ..ops import assign

        return assign(self)

    def numel(self):
        return self.size

    def element_size(self):
        return self.dtype.itemsize

    # ------------------------------------------------------------ autograd
    def backward(self, grad_tensor=None, retain_graph=False):
        from .engine import run_backward

        run_backward(self, grad_tensor, retain_graph)

    def clear_grad(self):
        self._grad = None

    def clear_gradient(self, set_to_zero=False):
        if set_to_zero and self._grad is not None:
            # IN-PLACE zero (buffer swap on the existing grad Tensor): under
            # to_static the write registers as a program output, so compiled
            # programs actually reset the accumulation buffer (gradient
            # merge's apply program depends on this; `= None` is a python-
            # level effect no compiled program can replay)
            self._grad._assign_raw(jnp.zeros_like(self._grad._data))
        else:
            self._grad = None

    # ------------------------------------------------------------ mutation
    def _assign_raw(self, value):
        """Swap the underlying buffer, notifying any active trace (mutation ⇒
        compiled-program output)."""
        tr = current_trace()
        if tr is not None:
            tr.on_read(self)
            tr.on_mutate(self)
        self._data = value

    def set_value(self, value):
        if isinstance(value, Tensor):
            value = value._data
        else:
            value = _to_jax(value, self.dtype, None)
        if tuple(value.shape) != tuple(self._data.shape):
            value = jnp.broadcast_to(value, self._data.shape)
        if value.dtype != self._data.dtype:
            value = value.astype(self._data.dtype)
        self._assign_raw(value)
        return self

    def copy_(self, other, blocking=True):
        return self.set_value(other)

    def _in_place(self, fn, *others):
        """Shared driver for add_/scale_/zero_ etc. (buffer swap)."""
        datas = [o._data if isinstance(o, Tensor) else o for o in others]
        self._assign_raw(fn(self._data, *datas))
        return self

    def zero_(self):
        return self._in_place(lambda x: jnp.zeros_like(x))

    def fill_(self, value):
        return self._in_place(lambda x: jnp.full_like(x, value))

    # ------------------------------------------------------------ misc parity
    def to(self, *args, **kwargs):
        from ..ops import _tensor_to

        return _tensor_to(self, *args, **kwargs)

    def cuda(self, *a, **k):  # parity shim: accelerator == TPU
        return self.to("tpu")

    def cpu(self):
        return self.to("cpu")

    def pin_memory(self):
        return self

    def contiguous(self):
        return self

    def is_contiguous(self):
        return True

    @property
    def T(self):
        from ..ops import transpose

        perm = list(range(self.ndim))[::-1]
        return transpose(self, perm)

    @property
    def mT(self):
        from ..ops import transpose

        perm = list(range(self.ndim))
        perm[-2], perm[-1] = perm[-1], perm[-2]
        return transpose(self, perm)

    def astype(self, dtype):
        from ..ops import cast

        return cast(self, dtype)

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __bool__(self):
        return bool(self._data)

    def __int__(self):
        return int(self._data)

    def __float__(self):
        return float(self._data)

    def __index__(self):
        return int(self._data)

    def __hash__(self):
        return id(self)

    def __repr__(self):
        prefix = "Parameter" if isinstance(self, Parameter) else "Tensor"
        if isinstance(self._data, jax.core.Tracer):
            return f"{prefix}(shape={self.shape}, dtype={self.dtype.name}, <traced>)"
        return (
            f"{prefix}(shape={self.shape}, dtype={self.dtype.name}, "
            f"stop_gradient={self.stop_gradient},\n{np.asarray(self._data)})"
        )

    # dict-style state for pickling via numpy
    def __getstate__(self):
        return {
            "data": self.numpy(),
            "stop_gradient": self.stop_gradient,
            "name": self.name,
        }

    def __setstate__(self, state):
        Tensor.__init__(self, state["data"], stop_gradient=state["stop_gradient"])
        self.name = state["name"]


class Parameter(Tensor):
    """Trainable tensor (≙ paddle EagerParamBase). stop_gradient=False."""

    def __init__(self, data, dtype=None, trainable=True, _internal=False):
        super().__init__(data, dtype=dtype, stop_gradient=not trainable, _internal=_internal)
        self.persistable = True

    @property
    def trainable(self):
        return not self.stop_gradient

    @trainable.setter
    def trainable(self, v):
        self.stop_gradient = not v


def _to_jax(data, dtype=None, place=None):
    dtype = dtypes.convert_dtype(dtype)
    if isinstance(data, Tensor):
        arr = data._data
        return arr.astype(dtype) if dtype is not None and arr.dtype != dtype else arr
    if isinstance(data, (jax.Array, jax.core.Tracer)):
        return data.astype(dtype) if dtype is not None and data.dtype != dtype else data
    arr = np.asarray(data)
    if dtype is None:
        # paddle default: python floats -> default dtype, ints -> int64
        if arr.dtype == np.float64:
            dtype = dtypes.get_default_dtype()
    dev = place.jax_device if isinstance(place, Place) else None
    out = jnp.asarray(arr, dtype=dtype)
    if dev is not None:
        out = jax.device_put(out, dev)
    return out


def to_tensor(data, dtype=None, place=None, stop_gradient=True) -> Tensor:
    """paddle.to_tensor."""
    return Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)
