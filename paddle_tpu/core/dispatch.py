"""Op dispatch: the single funnel every eager op goes through.

Reference parity: this is the TPU-native analog of the generated
`<op>_ad_func` + KernelFactory dispatch chain
(/root/reference/paddle/fluid/eager/auto_code_generator/generator/eager_gen.py,
/root/reference/paddle/phi/core/kernel_factory.h:326). Instead of a kernel
registry keyed by (name, backend, layout, dtype), every op is a pure jax
function; XLA is the kernel zoo. Autograd recording happens here: when any
floating input requires grad, the forward runs through jax.vjp and the
returned vjp closure (holding residuals on-device) becomes the GradNode —
the analog of TensorWrapper-saved inputs
(/root/reference/paddle/fluid/eager/tensor_wrapper.h:39).

The same funnel implements `to_static` capture: an active TraceContext is
notified of every concrete-valued Tensor read (a "capture", i.e. a free
variable of the traced program: parameters, optimizer state, RNG key) and
every in-place mutation (a program output to write back).
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Callable, Sequence

import jax
import numpy as np

from . import dtype as dtypes
from .flags import flag

_tls = threading.local()


# ---------------------------------------------------------------- grad mode
def grad_enabled() -> bool:
    return getattr(_tls, "grad_enabled", True)


@contextlib.contextmanager
def set_grad_enabled(mode: bool):
    old = grad_enabled()
    _tls.grad_enabled = mode
    try:
        yield
    finally:
        _tls.grad_enabled = old


class no_grad(contextlib.ContextDecorator):
    """paddle.no_grad: usable as context manager and decorator."""

    def __enter__(self):
        self._old = grad_enabled()
        _tls.grad_enabled = False
        return self

    def __exit__(self, *exc):
        _tls.grad_enabled = self._old
        return False


class enable_grad(contextlib.ContextDecorator):
    def __enter__(self):
        self._old = grad_enabled()
        _tls.grad_enabled = True
        return self

    def __exit__(self, *exc):
        _tls.grad_enabled = self._old
        return False


# ---------------------------------------------------------------- tracing
class TraceContext:
    """Active while paddle_tpu.jit.to_static discovers/retraces a program.

    phase == "discover": eager run; concrete Tensors read by ops are recorded
    as program inputs, in-place writes as program outputs.
    phase == "trace": running under jax.jit; captured Tensors carry tracers in
    ._data (bound by the jit wrapper), so ops Just Work.
    """

    def __init__(self, phase: str):
        self.phase = phase
        self.captures: dict[int, Any] = {}  # id(tensor) -> tensor (ordered)
        self.mutated: dict[int, Any] = {}

    def on_read(self, tensor):
        if self.phase == "discover" and not isinstance(tensor._data, jax.core.Tracer):
            self.captures.setdefault(id(tensor), tensor)

    def on_mutate(self, tensor):
        self.mutated.setdefault(id(tensor), tensor)


def current_trace() -> TraceContext | None:
    return getattr(_tls, "trace_ctx", None)


@contextlib.contextmanager
def trace_context(ctx: TraceContext):
    old = current_trace()
    _tls.trace_ctx = ctx
    try:
        yield ctx
    finally:
        _tls.trace_ctx = old


# ---------------------------------------------------------------- autograd tape
class GradNode:
    """One recorded op on the tape (≙ GradNodeBase, grad_node_info.h:197)."""

    __slots__ = ("vjp_fn", "inputs", "out_avals", "single_out", "name", "__weakref__")

    def __init__(self, vjp_fn, inputs, out_avals, single_out, name):
        self.vjp_fn = vjp_fn
        self.inputs = inputs  # list[Tensor] — differentiable inputs, positional
        self.out_avals = out_avals  # list[(shape, dtype)]
        self.single_out = single_out
        self.name = name


_amp_dtype_for = None


def _is_tensor(x) -> bool:
    from .tensor import Tensor

    return isinstance(x, Tensor)


def _check_nan_inf(name, arrs):
    import jax.numpy as jnp

    for a in arrs:
        if dtypes.is_floating_point(a.dtype) and not isinstance(a, jax.core.Tracer):
            if bool(jnp.any(~jnp.isfinite(a))):
                raise FloatingPointError(f"Operator '{name}' output contains NaN/Inf")


def op_call(fn: Callable, *args, name: str | None = None, n_diff: int | None = None):
    """Run pure jax function `fn` over mixed Tensor/raw args, recording autograd.

    Args after position `n_diff` (when given) are never differentiated —
    use for index/shape/flag operands. Returns Tensor or tuple[Tensor].
    """
    from .tensor import Tensor

    name = name or getattr(fn, "__name__", "op")
    trace = current_trace()

    datas = []
    for a in args:
        if _is_tensor(a):
            if trace is not None:
                trace.on_read(a)
            datas.append(a._data)
        else:
            datas.append(a)

    # AMP O1/O2 input casting (paddle: amp_auto_cast.h logic inlined in ad_funcs)
    global _amp_dtype_for
    if _amp_dtype_for is None:
        from ..amp import amp_dtype_for as _adf

        _amp_dtype_for = _adf
    target = _amp_dtype_for(name)
    if target is not None:
        # cast inside the differentiated fn so vjp returns grads in the
        # original param dtype (cast is part of the recorded graph)
        inner_fn = fn

        def fn(*vals):  # noqa: F811
            vals = [
                v.astype(target)
                if hasattr(v, "dtype") and dtypes.is_floating_point(v.dtype)
                and v.dtype != target else v
                for v in vals
            ]
            return inner_fn(*vals)

    limit = len(args) if n_diff is None else n_diff
    diff_idx = []
    if grad_enabled():
        for i, a in enumerate(args[:limit]):
            if _is_tensor(a) and not a.stop_gradient and dtypes.is_floating_point(a.dtype):
                diff_idx.append(i)

    if not diff_idx:
        out = fn(*datas)
        return _wrap_outputs(out, None, name)

    if len(diff_idx) == len(datas):
        primal_fn = fn
        diff_vals = datas
    else:
        def primal_fn(*dvals):
            vals = list(datas)
            for i, v in zip(diff_idx, dvals):
                vals[i] = v
            return fn(*vals)

        diff_vals = [datas[i] for i in diff_idx]

    out, vjp_fn = jax.vjp(primal_fn, *diff_vals)

    single = not isinstance(out, (tuple, list))
    outs = [out] if single else list(out)
    avals = [(o.shape, o.dtype) for o in outs]
    node = GradNode(vjp_fn, [args[i] for i in diff_idx], avals, single, name)
    return _wrap_outputs(out, node, name)


def _wrap_outputs(out, node, name):
    from .tensor import Tensor

    if flag("FLAGS_check_nan_inf"):
        flat = [out] if not isinstance(out, (tuple, list)) else list(out)
        _check_nan_inf(name, [o for o in flat if hasattr(o, "dtype")])

    def mk(o, idx):
        t = Tensor(o, stop_gradient=node is None, _internal=True)
        if node is not None:
            t._node = node
            t._out_idx = idx
        return t

    if not isinstance(out, (tuple, list)):
        return mk(out, 0)
    return tuple(mk(o, i) for i, o in enumerate(out))
