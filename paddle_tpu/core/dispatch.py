"""Op dispatch: the single funnel every eager op goes through.

Reference parity: this is the TPU-native analog of the generated
`<op>_ad_func` + KernelFactory dispatch chain
(/root/reference/paddle/fluid/eager/auto_code_generator/generator/eager_gen.py,
/root/reference/paddle/phi/core/kernel_factory.h:326). Instead of a kernel
registry keyed by (name, backend, layout, dtype), every op is a pure jax
function; XLA is the kernel zoo. Autograd recording happens here: when any
floating input requires grad, the forward runs through jax.vjp and the
returned vjp closure (holding residuals on-device) becomes the GradNode —
the analog of TensorWrapper-saved inputs
(/root/reference/paddle/fluid/eager/tensor_wrapper.h:39).

The same funnel implements `to_static` capture: an active TraceContext is
notified of every concrete-valued Tensor read (a "capture", i.e. a free
variable of the traced program: parameters, optimizer state, RNG key) and
every in-place mutation (a program output to write back).
"""
from __future__ import annotations

import contextlib
import threading
import weakref
from typing import Any, Callable, Sequence

import jax
import numpy as np

from . import dtype as dtypes
from . import flags as _flags_mod
from .flags import flag
from .lazy import LazyData as _LazyData
from .lazy import current_lazy as _current_lazy


class _HotFlags:
    """Per-generation snapshot of the flags the dispatch hot loop reads
    4-5 times per op; refreshed whenever set_flags bumps the generation."""

    __slots__ = ("gen", "use_cache", "defer_vjp", "benchmark",
                 "check_nan_inf", "double_grad")

    def __init__(self):
        # slots pre-populated so a concurrent reader that races a refresh
        # never sees unset attributes; gen starts stale so the first
        # _hot_flags() call refreshes
        self.gen = -1
        self.use_cache = self.defer_vjp = self.double_grad = True
        self.benchmark = self.check_nan_inf = False

    def refresh(self):
        # read the generation FIRST and publish it LAST: if set_flags runs
        # mid-refresh, gen stays stale and the next reader re-refreshes
        gen = _flags_mod.generation
        self.use_cache = flag("FLAGS_use_compiled_eager")
        self.defer_vjp = flag("FLAGS_eager_defer_vjp")
        self.benchmark = flag("FLAGS_benchmark")
        self.check_nan_inf = flag("FLAGS_check_nan_inf")
        self.double_grad = flag("FLAGS_enable_double_grad")
        self.gen = gen
        return self


_HOT_FLAGS = _HotFlags()


def _hot_flags():
    hf = _HOT_FLAGS
    if hf.gen != _flags_mod.generation:
        hf.refresh()
    return hf

_tls = threading.local()


# ---------------------------------------------------------------- grad mode
def grad_enabled() -> bool:
    return getattr(_tls, "grad_enabled", True)


@contextlib.contextmanager
def set_grad_enabled(mode: bool):
    old = grad_enabled()
    _tls.grad_enabled = mode
    try:
        yield
    finally:
        _tls.grad_enabled = old


class no_grad(contextlib.ContextDecorator):
    """paddle.no_grad: usable as context manager and decorator."""

    def __enter__(self):
        self._old = grad_enabled()
        _tls.grad_enabled = False
        return self

    def __exit__(self, *exc):
        _tls.grad_enabled = self._old
        return False


class enable_grad(contextlib.ContextDecorator):
    def __enter__(self):
        self._old = grad_enabled()
        _tls.grad_enabled = True
        return self

    def __exit__(self, *exc):
        _tls.grad_enabled = self._old
        return False


# ---------------------------------------------------------------- tracing
class TraceContext:
    """Active while paddle_tpu.jit.to_static discovers/retraces a program.

    phase == "discover": eager run; concrete Tensors read by ops are recorded
    as program inputs, in-place writes as program outputs.
    phase == "trace": running under jax.jit; captured Tensors carry tracers in
    ._data (bound by the jit wrapper), so ops Just Work.
    """

    def __init__(self, phase: str, borrowed: bool = False):
        self.phase = phase
        self.captures: dict[int, Any] = {}  # id(tensor) -> tensor (ordered)
        self.mutated: dict[int, Any] = {}
        # borrowed=True: this trace reuses a discovery from a DIFFERENT
        # input signature (to_static share_discovery); concrete tensor reads
        # here mean the borrowed capture set missed a tensor — it would be
        # silently baked in as a constant, so record for a warning
        self.borrowed = borrowed
        self.folded: dict[int, Any] = {}

    def on_read(self, tensor):
        if isinstance(tensor._data, jax.core.Tracer):
            return
        if self.phase == "discover":
            self.captures.setdefault(id(tensor), tensor)
        elif self.borrowed:
            self.folded.setdefault(id(tensor), tensor)

    def on_mutate(self, tensor):
        self.mutated.setdefault(id(tensor), tensor)


def current_trace() -> TraceContext | None:
    return getattr(_tls, "trace_ctx", None)


@contextlib.contextmanager
def trace_context(ctx: TraceContext):
    old = current_trace()
    _tls.trace_ctx = ctx
    try:
        yield ctx
    finally:
        _tls.trace_ctx = old


# ---------------------------------------------------------------- autograd tape
class GradNode:
    """One recorded op on the tape (≙ GradNodeBase, grad_node_info.h:197)."""

    __slots__ = ("vjp_fn", "inputs", "out_avals", "single_out", "name",
                 "diff_idx", "ctx", "__weakref__")

    def __init__(self, vjp_fn, inputs, out_avals, single_out, name,
                 diff_idx=None, ctx=None):
        self.vjp_fn = vjp_fn
        self.inputs = inputs  # list[Tensor] — differentiable inputs, positional
        self.out_avals = out_avals  # list[(shape, dtype)]
        self.single_out = single_out
        self.name = name
        # original arg positions of `inputs` (zero-bubble dW/dX split rules
        # need to know which operand is the activation vs the weight)
        self.diff_idx = diff_idx
        # (fn, datas): enough to RE-derive this op's vjp as a fresh traced
        # computation — how create_graph=True records backward ops onto the
        # tape (≙ the reference generating grad-of-grad GradNodes,
        # eager/backward.cc double-grad path)
        self.ctx = ctx


_amp_dtype_for = None


def _complexify_vjp(vjp_fn, single_out):
    """Convention bridge: JAX's complex cotangents/grads are the conjugate of
    Paddle's (reference AbsGradFunctor<complex>, funcs/complex_functors.h:158,
    computes dout·x/|x|, i.e. the non-holomorphic ∂L/∂conj(z) convention).
    The tape carries Paddle-convention grads, so conj on the way into
    jax.vjp and conj complex grads on the way out. Only installed when a
    complex dtype is involved — the real-dtype hot path is untouched."""
    import jax.numpy as jnp

    def wrapped(cot):
        if single_out:
            c = jnp.conj(cot) if jnp.iscomplexobj(cot) else cot
        else:
            c = tuple(jnp.conj(x) if jnp.iscomplexobj(x) else x for x in cot)
        grads = vjp_fn(c)
        return tuple(
            jnp.conj(g) if hasattr(g, "dtype") and jnp.iscomplexobj(g) else g
            for g in grads)

    return wrapped


_COMPLEX_DTYPE_MEMO: dict = {}


def _is_complex_dtype(dt) -> bool:
    r = _COMPLEX_DTYPE_MEMO.get(dt)
    if r is None:
        r = np.issubdtype(np.dtype(dt), np.complexfloating)
        _COMPLEX_DTYPE_MEMO[dt] = r
    return r


def _needs_complex_bridge(avals, datas, diff_idx):
    for _, dt in avals:
        if _is_complex_dtype(dt):
            return True
    for i in diff_idx:
        d = datas[i]
        dt = getattr(d, "dtype", None)
        if dt is not None and _is_complex_dtype(dt):
            return True
    return False


#: raw jax/numpy dtypes with meaningful VJPs (floats + complex — fft ops
#: have complex VJPs); frozen set of the dtype OBJECTS jax actually attaches
#: to arrays, so the hot diff-scan avoids np.dtype construction
_DIFF_DTYPES = frozenset(
    np.dtype(n) for n in ("float16", "bfloat16", "float32", "float64",
                          "float8_e4m3fn", "float8_e5m2",
                          "complex64", "complex128"))

_TENSOR_CLS = None


def _is_tensor(x) -> bool:
    # the Tensor class is bound lazily ONCE: an in-function import costs a
    # sys.modules lookup per call, and this predicate runs for every operand
    # of every eager op (the SURVEY §7-1 hot loop)
    global _TENSOR_CLS
    if _TENSOR_CLS is None:
        from .tensor import Tensor as _TENSOR_CLS  # noqa: F811
    return isinstance(x, _TENSOR_CLS)


# ------------------------------------------------- eager executable cache
# TPU-native analog of KernelFactory::SelectKernelOrThrowError
# (/root/reference/paddle/phi/core/kernel_factory.h:326) + the generated C++
# ad_funcs: instead of a registry of precompiled kernels, each (op, static
# operands, diff-mask, amp-target) gets a jitted executable pair — forward
# returns (out, vjp Partial), and vjp application itself runs through one
# shared jitted trampoline so backward is compiled too. jax.jit's internal
# C++ dispatch handles shape/dtype keying within an entry, so re-tracing
# happens only on genuinely new signatures.
_SIMPLE_TYPES = (int, float, bool, str, bytes, complex, type(None))
_UNCACHABLE = object()  # sentinel: this key can never be compiled

_eager_cache: dict = {}
_eager_hits = 0
_eager_misses = 0
_vjp_apply_jit = None

#: "fn inspects concrete values under tracing" — shared by the eager cache
#: (permanently uncachable key) and to_static (SOT-style graph break).
GRAPH_BREAK_ERRORS = (
    jax.errors.TracerArrayConversionError,
    jax.errors.TracerBoolConversionError,
    jax.errors.TracerIntegerConversionError,
    jax.errors.ConcretizationTypeError,
)


def _freeze(v):
    """Hashable cache-key fragment for a static operand, or _UNCACHABLE."""
    if isinstance(v, _SIMPLE_TYPES):
        return (type(v).__name__, v)
    if isinstance(v, (tuple, list)):
        parts = tuple(_freeze(x) for x in v)
        if any(p is _UNCACHABLE for p in parts):
            return _UNCACHABLE
        return (type(v).__name__, parts)
    if isinstance(v, np.dtype) or (isinstance(v, type) and issubclass(v, np.generic)):
        return ("dtype", np.dtype(v).name)
    if callable(v):
        return _fn_key(v)
    return _UNCACHABLE


def _fn_key(fn):
    """Identity key for the op function. Keyed by code object (stable across
    per-call re-creation of nested defs — ops like rope build a fresh inner
    fn each call) plus frozen defaults/closure cells. Unhashable cells ⇒
    uncachable."""
    import functools

    if isinstance(fn, functools.partial):
        base = _fn_key(fn.func)
        args = tuple(_freeze(a) for a in fn.args)
        kws = tuple(sorted((k, _freeze(v)) for k, v in fn.keywords.items()))
        if base is _UNCACHABLE or any(
            p is _UNCACHABLE for p in args
        ) or any(v is _UNCACHABLE for _, v in kws):
            return _UNCACHABLE
        return ("partial", base, args, kws)

    code = getattr(fn, "__code__", None)
    if code is None:  # builtins / C-level callables: stable module objects
        try:
            hash(fn)
        except TypeError:
            return _UNCACHABLE
        return fn

    defaults = getattr(fn, "__defaults__", None) or ()
    frozen_defaults = tuple(_freeze(d) for d in defaults)
    if any(d is _UNCACHABLE for d in frozen_defaults):
        return _UNCACHABLE

    vals = []
    for c in fn.__closure__ or ():
        try:
            frozen = _freeze(c.cell_contents)
        except ValueError:  # empty cell
            return _UNCACHABLE
        if frozen is _UNCACHABLE:
            return _UNCACHABLE
        vals.append(frozen)
    return (code, frozen_defaults, tuple(vals))


def _is_dynamic(v) -> bool:
    return isinstance(v, (jax.Array, np.ndarray))


def _bwd_used_mask(bwd_raw, dyn, cot):
    """Which positions of `dyn` the deferred-vjp recompute actually reads.

    Reverse liveness over the (untraced) bwd jaxpr: start from the output
    vars, walk equations backwards, mark an equation's inputs live when any
    of its outputs are. Equations with sub-jaxprs are treated atomically
    (all inputs live) — conservative, never drops a needed operand. E.g.
    add: nothing read (mask all-False); mul: both read. Returns None when
    the jaxpr can't be built (unusual cotangents) — caller keeps all."""
    try:
        closed = jax.make_jaxpr(bwd_raw)(tuple(dyn), cot)
    except Exception:
        return None
    jaxpr = closed.jaxpr
    live = {v for v in jaxpr.outvars if isinstance(v, jax.core.Var)}
    for eqn in reversed(jaxpr.eqns):
        if any(ov in live for ov in eqn.outvars):
            for iv in eqn.invars:
                if isinstance(iv, jax.core.Var):
                    live.add(iv)
    return tuple(v in live for v in jaxpr.invars[:len(dyn)])


def _dyn_sig(dyn):
    return tuple((tuple(d.shape), str(d.dtype)) for d in dyn)


def _has_float0(cot) -> bool:
    leaves = cot if isinstance(cot, (tuple, list)) else (cot,)
    return any(getattr(c, "dtype", None) == jax.dtypes.float0 for c in leaves)


def _apply_vjp(vjp_fn, cot):
    global _vjp_apply_jit
    if _has_float0(cot):  # float0 cotangents can't cross a jit boundary
        return vjp_fn(cot)
    if _vjp_apply_jit is None:
        _vjp_apply_jit = jax.jit(lambda f, c: f(c))
    return _vjp_apply_jit(vjp_fn, cot)


def _build_entry(fn, datas, diff_idx, dyn_pos):
    """Compile-once closure over the static operands (they're in the key)."""
    raw = [None if i in dyn_pos else d for i, d in enumerate(datas)]

    def _vals(dyn):
        vals = list(raw)
        for p, v in zip(dyn_pos, dyn):
            vals[p] = v
        return vals

    if not diff_idx:
        def call(*dyn):
            return fn(*_vals(dyn))

        return ("nograd", jax.jit(call))

    def _primal_over(vals):
        def primal(*ds):
            vs = list(vals)
            for i, dv in zip(diff_idx, ds):
                vs[i] = dv
            return fn(*vs)

        return primal

    def fwd(*dyn):
        vals = _vals(dyn)
        return jax.vjp(_primal_over(vals), *[vals[i] for i in diff_idx])

    # deferred-vjp pair (FLAGS_eager_defer_vjp, default on): forward runs
    # the lean fwd-only executable — a jit call returning a vjp closure
    # costs ~2x a plain call in pytree packaging (measured on host CPU:
    # 103 vs 55 us) and eager dispatch overhead is the metric here.
    # Backward re-derives the vjp INSIDE one jitted call (fwd recompute +
    # cotangent application fused by XLA). Trade: ~1 extra forward of this
    # op's FLOPs in backward — negligible for the dispatch-bound regime
    # eager mode serves; compute-bound training runs under to_static where
    # none of this path exists.
    def fwd_only(*dyn):
        return fn(*_vals(dyn))

    def bwd(dyn, cot):
        vals = _vals(dyn)
        _, vjp = jax.vjp(_primal_over(vals), *[vals[i] for i in diff_idx])
        return vjp(cot)

    # trailing dict: per-shape-signature mask of which dyn operands the vjp
    # recompute actually reads (ADVICE r5: don't pin every forward operand
    # until backward); filled lazily by _bwd_used_mask on first backward
    return ("grad", jax.jit(fwd), jax.jit(fwd_only), jax.jit(bwd), bwd, {})


def _cached_dispatch(fn, fn_id, name, datas, diff_idx, target,
                     dyn_pos=None, has_tracer=None):
    """Returns (out, vjp_or_None) via the executable cache, or None to fall
    back to the uncached path (unhashable statics / trace failure).
    dyn_pos/has_tracer may be precomputed by the caller's operand scan
    (one pass instead of three over the hot loop's operands)."""
    global _eager_hits, _eager_misses
    if has_tracer is None:
        has_tracer = any(isinstance(d, jax.core.Tracer) for d in datas)
    if has_tracer:
        return None
    if dyn_pos is None:
        dyn_pos = tuple(i for i, d in enumerate(datas) if _is_dynamic(d))
    if len(dyn_pos) == len(datas):  # common case: every operand dynamic
        statics = ()
    else:
        dyn_set = set(dyn_pos)
        statics = tuple(
            _freeze(d) for i, d in enumerate(datas) if i not in dyn_set
        )
    if fn_id is _UNCACHABLE or any(s is _UNCACHABLE for s in statics):
        return None
    key = (fn_id, name, target, dyn_pos, tuple(diff_idx), statics)
    entry = _eager_cache.get(key)
    if entry is _UNCACHABLE:
        return None
    if entry is None:
        limit = flag("FLAGS_eager_cache_size")
        if limit <= 0:  # size 0 ⇒ cache disabled
            return None
        _eager_misses += 1
        while len(_eager_cache) >= limit and _eager_cache:
            _eager_cache.pop(next(iter(_eager_cache)))
        entry = _build_entry(fn, datas, diff_idx, dyn_pos)
        _eager_cache[key] = entry
        # compile watchdog: a miss means a new executable entry — record
        # it (obs/watchdog.py). Only this cold path pays the event; wall
        # time is ~0 here because jax.jit traces lazily on first call.
        # The key is digested: a re-BUILD of the same digest after
        # eviction is the cache-thrash signal audit_recompiles flags.
        digest = f"{name}#{hash(key) & 0xffffffff:08x}"
        _record_compile()("eager", name, digest)
        # cost ledger (obs/costs.py): count-only rows — per-op eager
        # executables lower lazily inside jax.jit, so no XLA analysis
        # is reachable without paying one extra compile per op; the
        # ledger still shows WHERE the eager program population lives
        _record_cost_program()("eager", name, digest)
    else:
        _eager_hits += 1
    kind, jitted, *defer = entry
    dyn = [datas[p] for p in dyn_pos]
    try:
        if kind == "nograd":
            return jitted(*dyn), None
        if defer and _hot_flags().defer_vjp:
            fwd_only, bwd, bwd_raw, masks = defer
            out = fwd_only(*dyn)
            # pin only the operands the vjp recompute reads (known after the
            # first backward of this signature); unused positions are
            # rebuilt as zeros at backward time — values can't matter, the
            # bwd program provably never reads them
            sig = _dyn_sig(dyn)
            mask = masks.get(sig)
            if mask is None:
                kept = tuple(dyn)
                avals = None
            else:
                kept = tuple(d if m else None for d, m in zip(dyn, mask))
                avals = tuple(None if m else (d.shape, d.dtype)
                              for d, m in zip(dyn, mask))

            def deferred(cot, _b=bwd, _k=kept, _a=avals, _raw=bwd_raw,
                         _ms=masks, _sig=sig):
                import jax.numpy as jnp

                if _a is None:
                    d = _k
                    if not _has_float0(cot) and _sig not in _ms:
                        m = _bwd_used_mask(_raw, d, cot)
                        if m is not None:
                            _ms[_sig] = m
                else:
                    d = tuple(k if k is not None else jnp.zeros(*a)
                              for k, a in zip(_k, _a))
                if _has_float0(cot):  # float0 can't cross a jit boundary
                    with jax.disable_jit():
                        return _b(d, cot)
                return _b(d, cot)

            return out, deferred
        out, vjp_fn = jitted(*dyn)
        return out, (lambda cot, _v=vjp_fn: _apply_vjp(_v, cot))
    except GRAPH_BREAK_ERRORS:
        # fn inspects concrete values — shape-independent, permanently
        # uncachable for this key
        _eager_cache[key] = _UNCACHABLE
        return None
    except TypeError:
        # usually a per-shape user error (e.g. mismatched contracting dims):
        # fall back for THIS call only — the uncached path raises the same
        # error to the user; valid calls keep using the cached entry
        return None


_RECORD_COMPILE = None
_RECORD_COST = None


def _record_compile():
    # bound lazily like _TENSOR_CLS: obs lives above core in the package
    # graph and this only runs on the rare miss path
    global _RECORD_COMPILE
    if _RECORD_COMPILE is None:
        from ..obs.watchdog import record_compile as _RECORD_COMPILE  # noqa: F811
    return _RECORD_COMPILE


def _record_cost_program():
    global _RECORD_COST
    if _RECORD_COST is None:
        from ..obs.costs import record_program as _RECORD_COST  # noqa: F811
    return _RECORD_COST


def eager_cache_info() -> dict:
    return {
        "entries": len(_eager_cache),
        "hits": _eager_hits,
        "misses": _eager_misses,
    }


def eager_cache_clear():
    global _eager_hits, _eager_misses
    _eager_cache.clear()
    _eager_hits = _eager_misses = 0


def _check_nan_inf(name, arrs):
    import jax.numpy as jnp

    def hit(msg):
        # FLAGS_check_nan_inf_level >= 1: report, don't abort (reference
        # nan_inf_utils level semantics)
        if flag("FLAGS_check_nan_inf_level") >= 1:
            import warnings

            warnings.warn(msg)
        else:
            raise FloatingPointError(msg)

    for a in arrs:
        if isinstance(a, jax.core.Tracer) or isinstance(a, _LazyData):
            continue
        if dtypes.is_floating_point(a.dtype):
            if bool(jnp.any(~jnp.isfinite(a))):
                hit(f"Operator '{name}' output contains NaN/Inf")
        elif dtypes.is_complex(a.dtype):
            if bool(jnp.any(~jnp.isfinite(a.real) | ~jnp.isfinite(a.imag))):
                hit(f"Operator '{name}' output contains NaN/Inf")


#: (pack, unpack) installed by autograd.saved_tensors_hooks; applied to the
#: ctx-pinned operand buffers (the framework-visible saved tensors — the
#: XLA-managed vjp residuals live in device memory outside hook scope)
saved_tensor_hooks = None


def _make_ctx(fn, datas, diff_idx):
    """Re-derivation ctx for create_graph. Differentiable operands are
    stored as None — _regrad rebuilds them from node.inputs, so the ctx
    pins only the non-diff operands (and most of those are already alive
    in the vjp residuals)."""
    if not _hot_flags().double_grad:
        return None
    diff = set(diff_idx)
    kept = [None if i in diff else d for i, d in enumerate(datas)]
    if saved_tensor_hooks is not None:
        pack, unpack = saved_tensor_hooks
        kept = [None if d is None else _PackedSaved(pack(d), unpack)
                for d in kept]
    return (fn, kept)


class _PackedSaved:
    """A ctx slot transformed by saved_tensors_hooks; unpacked lazily on
    first re-derivation use."""

    __slots__ = ("payload", "unpack")

    def __init__(self, payload, unpack):
        self.payload = payload
        self.unpack = unpack

    def get(self):
        return self.unpack(self.payload)


#: set by paddle_tpu.profiler while recording: callable(name) -> RecordEvent
_profiler_hook = None

#: set by amp.debugging while collecting op-dtype stats: fn(name, outputs)
_op_stat_fn = None


def op_call(fn: Callable, *args, name: str | None = None, n_diff: int | None = None):
    """Run pure jax function `fn` over mixed Tensor/raw args, recording autograd.

    Args after position `n_diff` (when given) are never differentiated —
    use for index/shape/flag operands. Returns Tensor or tuple[Tensor].
    """
    hook = _profiler_hook
    if hook is not None:
        ev = hook(name or getattr(fn, "__name__", "op"))
        ev.begin()
        try:
            return _op_call_impl(fn, *args, name=name, n_diff=n_diff)
        finally:
            ev.end()
    return _op_call_impl(fn, *args, name=name, n_diff=n_diff)


def _op_call_impl(fn: Callable, *args, name: str | None = None, n_diff: int | None = None):
    name = name or getattr(fn, "__name__", "op")
    trace = current_trace()

    # ONE pass over the operands collects buffers, dynamic positions and
    # tracer-ness (the eager hot loop previously re-scanned three times)
    datas = []
    dyn_pos_l = []
    has_tracer = False
    for i, a in enumerate(args):
        if _is_tensor(a):
            if trace is not None:
                trace.on_read(a)
            d = a._data_buf
        else:
            d = a
        datas.append(d)
        if isinstance(d, (jax.Array, np.ndarray)):
            dyn_pos_l.append(i)
            if isinstance(d, jax.core.Tracer):
                has_tracer = True
    dyn_pos = tuple(dyn_pos_l)

    # AMP O1/O2 input casting (paddle: amp_auto_cast.h logic inlined in ad_funcs)
    global _amp_dtype_for
    if _amp_dtype_for is None:
        from ..amp import amp_dtype_for as _adf

        _amp_dtype_for = _adf
    orig_fn = fn
    target = _amp_dtype_for(name)
    if target is not None:
        # cast inside the differentiated fn so vjp returns grads in the
        # original param dtype (cast is part of the recorded graph)
        inner_fn = fn

        def fn(*vals):  # noqa: F811
            vals = [
                v.astype(target)
                if hasattr(v, "dtype") and dtypes.is_floating_point(v.dtype)
                and v.dtype != target else v
                for v in vals
            ]
            return inner_fn(*vals)

    limit = len(args) if n_diff is None else n_diff
    diff_idx = []
    if grad_enabled():
        for i, a in enumerate(args[:limit]):
            # raw-dtype membership check: the Tensor.dtype property builds
            # a fresh np.dtype per access — measurable in this hot loop
            if _is_tensor(a) and not a.stop_gradient \
                    and getattr(a._data, "dtype", None) in _DIFF_DTYPES:
                diff_idx.append(i)

    # segmented lazy staging (to_static graph-break mode): record the op
    # into the open segment instead of executing; see core/lazy.py
    lazy = _current_lazy()
    if lazy is not None:
        staged = lazy.stage(fn, _fn_key(orig_fn), name, datas, diff_idx,
                            target)
        if staged is not None:
            out_lazy, vjp_box, avals, single = staged
            node = None
            if vjp_box is not None:
                node = GradNode(
                    vjp_box, [args[i] for i in diff_idx],
                    [(tuple(a.shape), a.dtype) for a in avals], single, name,
                    diff_idx=list(diff_idx),
                    ctx=_make_ctx(fn, datas, diff_idx))
            out = out_lazy[0] if single else tuple(out_lazy)
            wrapped = _wrap_outputs(out, node, name)
            for t in ([wrapped] if single else list(wrapped)):
                lazy.created.append(weakref.ref(t))
            return wrapped
        # un-stageable op: materialize lazy inputs, fall through to eager
        datas = [d.get() if isinstance(d, _LazyData) else d for d in datas]
        # materialization changes which operands are dynamic: recompute
        dyn_pos = has_tracer = None

    use_cache = _hot_flags().use_cache

    if not diff_idx:
        if use_cache:
            cached = _cached_dispatch(fn, _fn_key(orig_fn), name, datas, [],
                                      target, dyn_pos, has_tracer)
            if cached is not None:
                return _wrap_outputs(cached[0], None, name)
        out = fn(*datas)
        return _wrap_outputs(out, None, name)

    if use_cache:
        cached = _cached_dispatch(fn, _fn_key(orig_fn), name, datas,
                                  diff_idx, target, dyn_pos, has_tracer)
        if cached is not None:
            out, vjp_fn = cached
            single = not isinstance(out, (tuple, list))
            outs = [out] if single else list(out)
            avals = [(o.shape, o.dtype) for o in outs]
            if _needs_complex_bridge(avals, datas, diff_idx):
                vjp_fn = _complexify_vjp(vjp_fn, single)
            node = GradNode(vjp_fn, [args[i] for i in diff_idx], avals, single, name,
                            diff_idx=list(diff_idx), ctx=_make_ctx(fn, datas, diff_idx))
            return _wrap_outputs(out, node, name)

    if len(diff_idx) == len(datas):
        primal_fn = fn
        diff_vals = datas
    else:
        def primal_fn(*dvals):
            vals = list(datas)
            for i, v in zip(diff_idx, dvals):
                vals[i] = v
            return fn(*vals)

        diff_vals = [datas[i] for i in diff_idx]

    out, vjp_fn = jax.vjp(primal_fn, *diff_vals)

    single = not isinstance(out, (tuple, list))
    outs = [out] if single else list(out)
    avals = [(o.shape, o.dtype) for o in outs]
    if _needs_complex_bridge(avals, datas, diff_idx):
        vjp_fn = _complexify_vjp(vjp_fn, single)
    node = GradNode(vjp_fn, [args[i] for i in diff_idx], avals, single, name,
                    diff_idx=list(diff_idx), ctx=_make_ctx(fn, datas, diff_idx))
    return _wrap_outputs(out, node, name)


def _wrap_outputs(out, node, name):
    global _TENSOR_CLS
    if _TENSOR_CLS is None:
        from .tensor import Tensor as _TENSOR_CLS  # noqa: F811
    Tensor = _TENSOR_CLS

    hf = _hot_flags()
    if hf.benchmark:
        # benchmark mode: per-op completion barrier (≙ reference benchmark
        # flag forcing synchronous kernel launches). NOTE: a scalar fetch,
        # not block_until_ready — on the axon tunnel the latter returns
        # before device execution completes (bench.py _sync measurement)
        import jax.numpy as _jnp

        flat = [out] if not isinstance(out, (tuple, list)) else list(out)
        for o in flat:
            if isinstance(o, jax.Array) and not isinstance(o, jax.core.Tracer):
                jax.device_get(_jnp.ravel(o)[0]) if o.size else None
    if hf.check_nan_inf:
        flat = [out] if not isinstance(out, (tuple, list)) else list(out)
        _check_nan_inf(name, [o for o in flat if hasattr(o, "dtype")])
    if _op_stat_fn is not None:
        flat = [out] if not isinstance(out, (tuple, list)) else list(out)
        _op_stat_fn(name, [o for o in flat if hasattr(o, "dtype")])

    if not isinstance(out, (tuple, list)):  # single output: the hot shape
        t = Tensor(out, stop_gradient=node is None, _internal=True)
        if node is not None:
            t._node = node
        return t

    def mk(o, idx):
        t = Tensor(o, stop_gradient=node is None, _internal=True)
        if node is not None:
            t._node = node
            t._out_idx = idx
        return t

    return tuple(mk(o, i) for i, o in enumerate(out))
