"""paddle_tpu — a TPU-native deep-learning framework with PaddlePaddle's
capability surface, built from scratch on JAX/XLA/Pallas/pjit.

Blueprint: /root/repo/SURVEY.md (structural analysis of the reference at
/root/reference). The engine is XLA: ops are jax compositions + Pallas
kernels, autograd is a define-by-run tape over jax.vjp closures, to_static
compiles whole train steps with jax.jit, and distributed training is
jax.sharding meshes + XLA collectives over ICI/DCN.
"""
from __future__ import annotations

__version__ = "0.1.0"

import os as _os

import jax as _jax

# Full dtype surface (int64 labels, float64 CPU math — paddle defaults int64
# for integer tensors). Framework default float dtype stays float32; creation
# ops always pass explicit dtypes, so x64 never leaks into TPU programs.
_jax.config.update("jax_enable_x64", True)

# Honor JAX_PLATFORMS even when a site hook imported jax before us (env is
# read once at jax import; re-apply so `JAX_PLATFORMS=cpu python app.py`
# behaves as documented regardless of interpreter-startup hooks).
if _os.environ.get("JAX_PLATFORMS"):
    try:
        _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])
    except Exception:
        pass

from .core import dtype as _dtype_mod
from .core.dtype import (
    bool_ as bool,  # noqa: A001
    uint8, int8, int16, int32, int64,
    float16, bfloat16, float32, float64,
    complex64, complex128, float8_e4m3fn, float8_e5m2,
    set_default_dtype, get_default_dtype,
)
from .core.device import (
    CPUPlace, CUDAPlace, TPUPlace, XPUPlace, CustomPlace, Place,
    get_device, set_device, device_count, is_compiled_with_cuda,
    is_compiled_with_tpu,
)
from .core.flags import set_flags, get_flags
from .core.tensor import Tensor, to_tensor
from .core.dispatch import no_grad, enable_grad, set_grad_enabled
from .core.rng import seed, get_rng_state, set_rng_state
from .core.engine import grad

from .ops import *  # noqa: F401,F403 — the ~300 tensor ops at top level
from .ops import _tensor_to  # noqa: F401

from . import autograd
from . import nn
from . import optimizer
from . import io
from . import amp
from . import jit
from . import metric
from . import vision
from . import distributed
# NOTE: `from .ops import *` above leaked the ops.linalg SUBMODULE as the
# `linalg` attribute, which makes `from . import linalg` short-circuit
# (the import system skips the submodule load when the attr exists) —
# force-load the real top-level namespace module instead.
import importlib as _importlib

linalg = _importlib.import_module(".linalg", __name__)
from . import incubate
from . import profiler
from . import hapi
from .hapi import Model
from . import distribution
from . import quantization
from . import sparse
from . import static
from . import device
from . import text
from . import inference
from . import serving
from . import ckpt
from . import audio
from . import onnx
from . import utils
from . import fft
from . import signal
from . import geometric
from . import obs
from . import version
from . import sysconfig
from . import hub
from . import regularizer
from . import callbacks
from . import reader
from . import framework
from . import base
from . import tensor
from . import dataset
from . import tensorrt
from . import cost_model
from . import decomposition
from .batch import batch
from .framework_io import save, load

# paddle.framework parity namespace bits
from .core.tensor import Parameter  # noqa

import numpy as _np


def disable_static(place=None):  # dygraph is the only mode; parity shim
    return None


def enable_static():
    raise NotImplementedError(
        "paddle_tpu is dygraph-first; use paddle_tpu.jit.to_static for compiled graphs"
    )


def in_dynamic_mode():
    return True


def is_grad_enabled():
    from .core.dispatch import grad_enabled

    return grad_enabled()


def summary(net, input_size=None, dtypes=None, input=None):
    from .hapi.summary import summary as _s

    return _s(net, input_size, dtypes, input)


def flops(net, input_size, custom_ops=None, print_detail=False):
    from .hapi.summary import flops as _f

    return _f(net, input_size, custom_ops, print_detail)

# ---------------------------------------------------- top-level export closure
# (≙ reference python/paddle/__init__.py long tail)
import math as _math

e = _math.e
pi = _math.pi
inf = float("inf")
nan = float("nan")
newaxis = None  # paddle.newaxis ≙ np.newaxis

from .nn import ParamAttr  # noqa: E402
from .distributed.meta_parallel import DataParallel  # noqa: E402
from .core.device import CUDAPinnedPlace  # noqa: E402
dtype = _np.dtype  # paddle.dtype: dtype objects ARE numpy dtypes here
pstring = "pstring"  # string-tensor dtype tag (no string tensors yet)


class LazyGuard:
    """≙ paddle.LazyGuard (lazy parameter materialization). Parameters here
    are created eagerly but cheaply (no device sync until first use), so the
    guard is a transparent context kept for API parity."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """≙ paddle.set_printoptions → numpy print options (Tensor repr prints
    via numpy)."""
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    _np.set_printoptions(**kw)


def to_dlpack(x):
    """≙ paddle.utils.dlpack.to_dlpack: returns an object implementing the
    DLPack protocol (the jax.Array itself — zero copy; modern DLPack passes
    protocol objects, not raw capsules)."""
    return x._data


def from_dlpack(ext):
    """Accepts any object with __dlpack__ (torch/numpy/jax arrays, or the
    product of to_dlpack)."""
    import jax.numpy as _jnp

    arr = _jnp.from_dlpack(ext)
    return Tensor(arr, _internal=True, stop_gradient=True)


def get_cuda_rng_state():
    """CUDA alias of the device RNG state (the TPU key chain)."""
    return get_rng_state()


def set_cuda_rng_state(state):
    return set_rng_state(state)


def disable_signal_handler():
    """≙ paddle.disable_signal_handler: the XLA runtime installs no python
    signal handlers — nothing to disable."""
    return None


def tolist(x):
    return x.tolist()  # Tensor.tolist is defined in core/tensor.py


def _cuda_lib_version_stub(_name):
    def version():
        return 0  # no CUDA libraries in the TPU-native build

    version.__name__ = _name
    version.__doc__ = f"{_name} version probe — CUDA-free build returns 0."
    return version


cublas = _cuda_lib_version_stub("cublas")
cudnn = _cuda_lib_version_stub("cudnn")
cufft = _cuda_lib_version_stub("cufft")
curand = _cuda_lib_version_stub("curand")
cusolver = _cuda_lib_version_stub("cusolver")
cusparse = _cuda_lib_version_stub("cusparse")
cuda_runtime = _cuda_lib_version_stub("cuda_runtime")
cuda_nvrtc = _cuda_lib_version_stub("cuda_nvrtc")
nvjitlink = _cuda_lib_version_stub("nvjitlink")

