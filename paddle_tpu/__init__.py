"""paddle_tpu — a TPU-native deep-learning framework with PaddlePaddle's
capability surface, built from scratch on JAX/XLA/Pallas/pjit.

Blueprint: /root/repo/SURVEY.md (structural analysis of the reference at
/root/reference). The engine is XLA: ops are jax compositions + Pallas
kernels, autograd is a define-by-run tape over jax.vjp closures, to_static
compiles whole train steps with jax.jit, and distributed training is
jax.sharding meshes + XLA collectives over ICI/DCN.
"""
from __future__ import annotations

__version__ = "0.1.0"

import os as _os

import jax as _jax

# Full dtype surface (int64 labels, float64 CPU math — paddle defaults int64
# for integer tensors). Framework default float dtype stays float32; creation
# ops always pass explicit dtypes, so x64 never leaks into TPU programs.
_jax.config.update("jax_enable_x64", True)

# Honor JAX_PLATFORMS even when a site hook imported jax before us (env is
# read once at jax import; re-apply so `JAX_PLATFORMS=cpu python app.py`
# behaves as documented regardless of interpreter-startup hooks).
if _os.environ.get("JAX_PLATFORMS"):
    try:
        _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])
    except Exception:
        pass

from .core import dtype as _dtype_mod
from .core.dtype import (
    bool_ as bool,  # noqa: A001
    uint8, int8, int16, int32, int64,
    float16, bfloat16, float32, float64,
    complex64, complex128, float8_e4m3fn, float8_e5m2,
    set_default_dtype, get_default_dtype,
)
from .core.device import (
    CPUPlace, CUDAPlace, TPUPlace, XPUPlace, CustomPlace, Place,
    get_device, set_device, device_count, is_compiled_with_cuda,
    is_compiled_with_tpu,
)
from .core.flags import set_flags, get_flags
from .core.tensor import Tensor, to_tensor
from .core.dispatch import no_grad, enable_grad, set_grad_enabled
from .core.rng import seed, get_rng_state, set_rng_state
from .core.engine import grad

from .ops import *  # noqa: F401,F403 — the ~300 tensor ops at top level
from .ops import _tensor_to  # noqa: F401

from . import autograd
from . import nn
from . import optimizer
from . import io
from . import amp
from . import jit
from . import metric
from . import vision
from . import distributed
# NOTE: `from .ops import *` above leaked the ops.linalg SUBMODULE as the
# `linalg` attribute, which makes `from . import linalg` short-circuit
# (the import system skips the submodule load when the attr exists) —
# force-load the real top-level namespace module instead.
import importlib as _importlib

linalg = _importlib.import_module(".linalg", __name__)
from . import incubate
from . import profiler
from . import hapi
from .hapi import Model
from . import distribution
from . import quantization
from . import sparse
from . import static
from . import device
from . import text
from . import inference
from . import audio
from . import onnx
from . import utils
from . import fft
from . import signal
from . import geometric
from . import version
from . import sysconfig
from . import hub
from . import regularizer
from . import callbacks
from . import reader
from . import framework
from . import base
from . import tensor
from . import dataset
from . import tensorrt
from . import cost_model
from . import decomposition
from .batch import batch
from .framework_io import save, load

# paddle.framework parity namespace bits
from .core.tensor import Parameter  # noqa

import numpy as _np


def disable_static(place=None):  # dygraph is the only mode; parity shim
    return None


def enable_static():
    raise NotImplementedError(
        "paddle_tpu is dygraph-first; use paddle_tpu.jit.to_static for compiled graphs"
    )


def in_dynamic_mode():
    return True


def is_grad_enabled():
    from .core.dispatch import grad_enabled

    return grad_enabled()


def summary(net, input_size=None, dtypes=None, input=None):
    from .hapi.summary import summary as _s

    return _s(net, input_size, dtypes, input)


def flops(net, input_size, custom_ops=None, print_detail=False):
    from .hapi.summary import flops as _f

    return _f(net, input_size, custom_ops, print_detail)
