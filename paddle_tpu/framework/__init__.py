"""paddle.framework parity (≙ python/paddle/framework/__init__.py): the
grab-bag namespace user code reaches into for dtype defaults, grad guards,
places, and random state."""
from __future__ import annotations

from ..core.dtype import (  # noqa: F401
    get_default_dtype, set_default_dtype,
)
from ..core.device import (  # noqa: F401
    CPUPlace, CUDAPlace, TPUPlace, XPUPlace, CustomPlace, Place,
)
from ..core.dispatch import no_grad, set_grad_enabled  # noqa: F401
from ..core.rng import seed, get_rng_state, set_rng_state  # noqa: F401
from ..core.tensor import Parameter  # noqa: F401
from ..framework_io import save, load  # noqa: F401


def in_dynamic_mode():
    return True


def in_pir_mode():
    """The IR here is jaxpr/StableHLO under jit; no separate PIR mode."""
    return False


def use_pir_api():
    return False


def is_grad_enabled():
    from ..core.dispatch import grad_enabled

    return grad_enabled()


from ..nn import ParamAttr  # noqa: F401,E402 — one definition, shared
