"""paddle.reader parity — legacy reader decorators
(≙ python/paddle/reader/decorator.py): composable generator transforms kept
for capability parity; paddle.io.DataLoader is the modern path.
"""
from __future__ import annotations

import itertools
import random as _random
from queue import Queue
from threading import Thread

__all__ = ['cache', 'map_readers', 'buffered', 'compose', 'chain',
           'shuffle', 'firstn', 'xmap_readers', 'multiprocess_reader']


def cache(reader):
    """Materialize the wrapped reader once; replay from memory after."""
    all_data = tuple(reader())

    def cached_reader():
        yield from all_data

    return cached_reader


def map_readers(func, *readers):
    """Zip readers and map func over the per-reader samples."""

    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)

    return reader


def shuffle(reader, buf_size):
    """Shuffle within a sliding buffer of buf_size samples."""

    def shuffled_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf

    return shuffled_reader


def chain(*readers):
    """Concatenate readers back-to-back."""

    def chained_reader():
        for r in readers:
            yield from r()

    return chained_reader


class ComposeNotAligned(ValueError):
    pass


def compose(*readers, **kwargs):
    """Zip readers into tuples of their outputs; check_alignment raises
    ComposeNotAligned when one reader runs short."""
    check_alignment = kwargs.pop('check_alignment', True)

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in itertools.zip_longest(*rs):
                yield sum((make_tuple(x) for x in outputs), ())
        else:
            for outputs in itertools.zip_longest(*rs):
                if any(o is None for o in outputs):
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned")
                yield sum((make_tuple(x) for x in outputs), ())

    return reader


def buffered(reader, size):
    """Prefetch up to `size` samples in a background thread."""

    class _End:
        pass

    def read_worker(r, q):
        for d in r:
            q.put(d)
        q.put(_End())

    def data_reader():
        r = reader()
        q = Queue(maxsize=size)
        t = Thread(target=read_worker, args=(r, q))
        t.daemon = True
        t.start()
        e = q.get()
        while not isinstance(e, _End):
            yield e
            e = q.get()

    return data_reader


def firstn(reader, n):
    """Keep only the first n samples."""

    def firstn_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item

    return firstn_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Map `mapper` over samples with a pool of worker threads."""
    end_token = object()

    def read_worker(r, in_q):
        for i, d in enumerate(r()):
            in_q.put((i, d) if order else d)
        in_q.put(end_token)

    def map_worker(in_q, out_q):
        sample = in_q.get()
        while sample is not end_token:
            if order:
                i, d = sample
                out_q.put((i, mapper(d)))
            else:
                out_q.put(mapper(sample))
            sample = in_q.get()
        in_q.put(end_token)  # let siblings see the end
        out_q.put(end_token)

    def xreader():
        in_q, out_q = Queue(buffer_size), Queue(buffer_size)
        t = Thread(target=read_worker, args=(reader, in_q))
        t.daemon = True
        t.start()
        workers = []
        for _ in range(process_num):
            w = Thread(target=map_worker, args=(in_q, out_q))
            w.daemon = True
            w.start()
            workers.append(w)
        finished = 0
        if order:
            buf, want = {}, 0
            while finished < process_num:
                s = out_q.get()
                if s is end_token:
                    finished += 1
                    continue
                i, d = s
                buf[i] = d
                while want in buf:
                    yield buf.pop(want)
                    want += 1
            while want in buf:
                yield buf.pop(want)
                want += 1
        else:
            while finished < process_num:
                s = out_q.get()
                if s is end_token:
                    finished += 1
                else:
                    yield s

    return xreader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Interleave multiple readers; thread-based here (the heavy multiprocess
    IO path lives in paddle.io.DataLoader's worker pool)."""

    def reader():
        q = Queue(queue_size)
        end_token = object()

        def worker(r):
            for d in r():
                q.put(d)
            q.put(end_token)

        ts = []
        for r in readers:
            t = Thread(target=worker, args=(r,))
            t.daemon = True
            t.start()
            ts.append(t)
        finished = 0
        while finished < len(readers):
            s = q.get()
            if s is end_token:
                finished += 1
            else:
                yield s

    return reader
