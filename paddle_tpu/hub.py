"""paddle.hub parity (≙ python/paddle/hub.py): load models from a hubconf.py
entrypoint file. The `local` source is fully supported; `github`/`gitee`
need network access and raise (this build runs with zero egress — vendor the
repo and use source='local')."""
from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ['list', 'help', 'load']

_HUBCONF = 'hubconf.py'


def _load_entry_module(repo_dir):
    path = os.path.join(repo_dir, _HUBCONF)
    if not os.path.isfile(path):
        raise FileNotFoundError(f"no {_HUBCONF} found in {repo_dir}")
    spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.path.insert(0, repo_dir)
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.path.remove(repo_dir)
    return mod


def _check_source(source):
    if source not in ('local', 'github', 'gitee'):
        raise ValueError(
            f"Unknown source: {source}. Should be 'github', 'gitee' or 'local'.")
    if source in ('github', 'gitee'):
        raise RuntimeError(
            f"source='{source}' needs network access, unavailable in this "
            "build — clone the repo and pass source='local'.")


def list(repo_dir, source='github', force_reload=False):  # noqa: A001
    """List callable entrypoints exported by the repo's hubconf.py."""
    _check_source(source)
    mod = _load_entry_module(repo_dir)
    return [n for n, f in vars(mod).items()
            if callable(f) and not n.startswith('_')]


def help(repo_dir, model, source='github', force_reload=False):  # noqa: A001
    """Return the docstring of one entrypoint."""
    _check_source(source)
    mod = _load_entry_module(repo_dir)
    if not hasattr(mod, model):
        raise RuntimeError(f"Cannot find model '{model}' in {repo_dir}")
    return getattr(mod, model).__doc__


def load(repo_dir, model, source='github', force_reload=False, **kwargs):
    """Instantiate an entrypoint: load(repo, 'resnet50', pretrained=False)."""
    _check_source(source)
    mod = _load_entry_module(repo_dir)
    if not hasattr(mod, model):
        raise RuntimeError(f"Cannot find model '{model}' in {repo_dir}")
    return getattr(mod, model)(**kwargs)
