"""Fused multi-tensor Adam/AdamW update.

Reference parity: paddle's fused adam paths — the multi-tensor CUDA kernel
(`/root/reference/paddle/phi/kernels/fused_adam_kernel.h`, one kernel launch
updating many params) and the python chunking helper
(`/root/reference/python/paddle/optimizer/fusion_utils.py`). TPU-native
design: ONE jitted XLA program takes the whole (params, grads, moments)
pytree, applies optional global-norm clipping and the Adam/AdamW update to
every leaf, and returns the new state with input buffers DONATED — eager
mode pays a single dispatch per step instead of ~4·P small ones, and the
params/moments update in place in HBM like the reference's in-place kernels.

Engaged by `Adam/AdamW(..., use_multi_tensor=True)` in eager mode; under
`to_static` tracing the per-param path is kept (the whole step compiles into
the train-step program anyway, where XLA does the same fusion).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import dtype as dtypes
from ..nn.clip import ClipGradByGlobalNorm


def _is_tracer(x) -> bool:
    return isinstance(x, jax.core.Tracer)


#: test seam: called between the donating jitted update and the commit of
#: its results (tests/test_flagship_perf.py raises KeyboardInterrupt here
#: to prove the commit still lands)
_interrupt_test_hook = None


def _guarded_update(exe, args, commit):
    """Run a DONATING jitted update and commit its results to the framework
    tensors in a finally block. The inputs (params/moments) are donated —
    dead the moment `exe` dispatches — so a KeyboardInterrupt landing
    between the call returning and the last `_assign_raw` must not leave
    optimizer state pointing at deleted buffers (ADVICE round 5): once
    results exist, the commit runs even if the interrupt arrives first."""
    out = None
    try:
        out = exe(*args)
        if _interrupt_test_hook is not None:
            _interrupt_test_hook()
    finally:
        if out is not None:
            commit(out)


def _build_executor(n, b1, b2, eps, decoupled, amsgrad, clip_norm, has_master):
    """Compile-once fused update. Positional buffer lists are donated:
    bases (fp32 master or param), low-precision params (master mode),
    moment1, moment2, [moment2_max]."""

    def update(bases, lo_params, ms, vs, vmaxs, grads, wds, lrfs, step_t, lr):
        if clip_norm is not None:
            sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in grads)
            gnorm = jnp.sqrt(sq)
            scale = jnp.minimum(clip_norm / jnp.maximum(gnorm, 1e-12), 1.0)
            grads = [(g * scale.astype(jnp.float32)).astype(g.dtype)
                     for g in grads]
        new_bases, new_lo, new_ms, new_vs, new_vmaxs = [], [], [], [], []
        t = step_t
        low = (jnp.float16, jnp.bfloat16)
        for i in range(n):
            base = bases[i]
            # match the per-param path: low-precision params without a
            # master still compute (and keep moments) in fp32
            comp_dt = jnp.float32 if base.dtype in low else base.dtype
            bc = base.astype(comp_dt)
            gd = grads[i].astype(comp_dt)
            lr_i = lr * lrfs[i]
            if not decoupled:
                gd = gd + wds[i] * bc
            new_m = b1 * ms[i].astype(comp_dt) + (1 - b1) * gd
            new_v = b2 * vs[i].astype(comp_dt) + (1 - b2) * jnp.square(gd)
            mhat = new_m / (1 - b1 ** t)
            if amsgrad:
                new_vmax = jnp.maximum(vmaxs[i].astype(comp_dt), new_v)
                vhat = new_vmax / (1 - b2 ** t)
                new_vmaxs.append(new_vmax.astype(vmaxs[i].dtype))
            else:
                vhat = new_v / (1 - b2 ** t)
            step = lr_i * mhat / (jnp.sqrt(vhat) + eps)
            newb = bc
            if decoupled:
                newb = newb * (1.0 - lr_i * wds[i])
            newb = newb - step
            new_bases.append(newb.astype(base.dtype))
            if has_master:
                new_lo.append(newb.astype(lo_params[i].dtype))
            # store moments back in their accumulator dtype (per-param path
            # parity: compute fp32, storage follows the declared state dtype)
            new_ms.append(new_m.astype(ms[i].dtype))
            new_vs.append(new_v.astype(vs[i].dtype))
        return new_bases, new_lo, new_ms, new_vs, new_vmaxs

    return jax.jit(update, donate_argnums=(0, 1, 2, 3, 4))


def fused_adam_step(opt, pgs, lr_data) -> bool:
    """One fused update over every (param, grad) pair. Returns False when
    this step can't take the fused path (tracing, exotic clip, L1 decay,
    per-param hooks) — caller falls back to the per-param loop."""
    from . import _wd_coeff  # late: circular import

    clip = opt._grad_clip
    clip_norm = None
    if clip is not None:
        if isinstance(clip, ClipGradByGlobalNorm):
            clip_norm = float(clip.clip_norm)
        else:
            return False

    params, grads, groups = [], [], []
    for p, g, grp in pgs:
        if g is None:
            continue
        params.append(p)
        grads.append(g)
        groups.append(grp)
    if not params:
        return True
    if any(_is_tracer(p._data) or _is_tracer(g._data)
           for p, g in zip(params, grads)):
        return False

    wds, lrfs = [], []
    for p, grp in zip(params, groups):
        wd = grp.get("weight_decay", opt._weight_decay)
        if getattr(wd, "_kind", "l2") == "l1":
            return False  # L1 penalty: keep the per-param path
        c = _wd_coeff(wd)
        decay_fun = getattr(opt, "_apply_decay_param_fun", None)
        if decay_fun is not None and not decay_fun(p.name):
            c = 0.0
        lf = grp.get("learning_rate", 1.0)
        lr_ratio = getattr(opt, "_lr_ratio", None)
        if lr_ratio is not None:
            lf = lf * lr_ratio(p)
        wds.append(float(c))
        lrfs.append(float(lf))

    # materialize accumulators/masters (first step) BEFORE keying
    masters = [opt._master(p) for p in params]
    has_master = any(m is not None for m in masters)
    if has_master and not all(m is not None for m in masters):
        return False  # mixed master/non-master set: rare; per-param path
    ms = [opt._acc("moment1", p) for p in params]
    vs = [opt._acc("moment2", p) for p in params]
    vmaxs = [opt._acc("moment2_max", p) for p in params] if opt._amsgrad else []

    key = (tuple((tuple(p.shape), p.dtype.name) for p in params),
           tuple(wds), tuple(lrfs),
           opt._beta1, opt._beta2, opt._epsilon, opt._decoupled_wd,
           opt._amsgrad, clip_norm, has_master)
    cached = getattr(opt, "_fused_exec", None)
    if cached is None or cached[0] != key:
        exe = _build_executor(len(params), opt._beta1, opt._beta2,
                              opt._epsilon, opt._decoupled_wd, opt._amsgrad,
                              clip_norm, has_master)
        opt._fused_exec = cached = (key, exe)
    exe = cached[1]

    bases = [(m._data if m is not None else p._data)
             for p, m in zip(params, masters)]
    lo = [p._data for p in params] if has_master else []

    def commit(out):
        new_bases, new_lo, new_ms, new_vs, new_vmaxs = out
        for i, p in enumerate(params):
            if has_master:
                masters[i]._assign_raw(new_bases[i])
                p._assign_raw(new_lo[i])
            else:
                p._assign_raw(new_bases[i])
            ms[i]._assign_raw(new_ms[i])
            vs[i]._assign_raw(new_vs[i])
            if opt._amsgrad:
                vmaxs[i]._assign_raw(new_vmaxs[i])

    _guarded_update(
        exe, (bases, lo, [m._data for m in ms], [v._data for v in vs],
              [vm._data for vm in vmaxs], [g._data for g in grads],
              wds, lrfs, opt._step_t._data, lr_data), commit)
    return True


def _build_momentum_executor(n, mu, nesterov, clip_norm, has_master):
    """Compile-once fused Momentum update (≙ phi merged_momentum kernel):
    bases / low-precision params / velocities are donated."""

    def update(bases, lo_params, vels, grads, wds, lrfs, lr):
        if clip_norm is not None:
            sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in grads)
            gnorm = jnp.sqrt(sq)
            scale = jnp.minimum(clip_norm / jnp.maximum(gnorm, 1e-12), 1.0)
            grads = [(g * scale.astype(jnp.float32)).astype(g.dtype)
                     for g in grads]
        new_bases, new_lo, new_vels = [], [], []
        low = (jnp.float16, jnp.bfloat16)
        for i in range(n):
            base = bases[i]
            comp_dt = jnp.float32 if base.dtype in low else base.dtype
            bc = base.astype(comp_dt)
            gd = grads[i].astype(comp_dt) + wds[i] * bc
            new_v = mu * vels[i].astype(comp_dt) + gd
            upd = gd + mu * new_v if nesterov else new_v
            newb = bc - lr * lrfs[i] * upd
            new_bases.append(newb.astype(base.dtype))
            if has_master:
                new_lo.append(newb.astype(lo_params[i].dtype))
            new_vels.append(new_v.astype(vels[i].dtype))
        return new_bases, new_lo, new_vels

    return jax.jit(update, donate_argnums=(0, 1, 2))


def fused_momentum_step(opt, pgs, lr_data) -> bool:
    """One fused update over every (param, grad) pair for Momentum/SGD-with-
    momentum. Returns False when the fused path doesn't apply (tracing,
    exotic clip, L1 decay) — caller falls back to the per-param loop."""
    from . import _wd_coeff  # late: circular import

    clip = opt._grad_clip
    clip_norm = None
    if clip is not None:
        if isinstance(clip, ClipGradByGlobalNorm):
            clip_norm = float(clip.clip_norm)
        else:
            return False

    params, grads, groups = [], [], []
    for p, g, grp in pgs:
        if g is None:
            continue
        params.append(p)
        grads.append(g)
        groups.append(grp)
    if not params:
        return True
    if any(_is_tracer(p._data) or _is_tracer(g._data)
           for p, g in zip(params, grads)):
        return False

    wds, lrfs = [], []
    for p, grp in zip(params, groups):
        wd = grp.get("weight_decay", opt._weight_decay)
        if getattr(wd, "_kind", "l2") == "l1":
            return False
        wds.append(float(_wd_coeff(wd)))
        lrfs.append(float(grp.get("learning_rate", 1.0)))

    masters = [opt._master(p) for p in params]
    has_master = any(m is not None for m in masters)
    if has_master and not all(m is not None for m in masters):
        return False
    vels = [opt._acc("velocity", p) for p in params]

    key = (tuple((tuple(p.shape), p.dtype.name) for p in params),
           tuple(wds), tuple(lrfs), opt._momentum, opt._nesterov,
           clip_norm, has_master)
    cached = getattr(opt, "_fused_exec", None)
    if cached is None or cached[0] != key:
        exe = _build_momentum_executor(len(params), opt._momentum,
                                       opt._nesterov, clip_norm, has_master)
        opt._fused_exec = cached = (key, exe)
    exe = cached[1]

    bases = [(m._data if m is not None else p._data)
             for p, m in zip(params, masters)]
    lo = [p._data for p in params] if has_master else []

    def commit(out):
        new_bases, new_lo, new_vels = out
        for i, p in enumerate(params):
            if has_master:
                masters[i]._assign_raw(new_bases[i])
                p._assign_raw(new_lo[i])
            else:
                p._assign_raw(new_bases[i])
            vels[i]._assign_raw(new_vels[i])

    _guarded_update(
        exe, (bases, lo, [v._data for v in vels], [g._data for g in grads],
              wds, lrfs, lr_data), commit)
    return True
