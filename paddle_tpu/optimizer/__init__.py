"""Optimizers (≙ python/paddle/optimizer). Updates are single fused jnp
expressions per parameter executed under no_grad; in to_static the whole
optimizer step traces into the compiled program (the analog of paddle's fused
multi-tensor adam paths — XLA fuses across parameters after donation)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtypes
from ..core.dispatch import no_grad
from ..core.tensor import Parameter, Tensor
from . import lr
from .lr import LRScheduler


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=False):
        self._lr = learning_rate
        if parameters is None:
            raise ValueError("parameters must be provided (dygraph mode)")
        plist = list(parameters)
        # parameter groups (paddle: list of dicts with 'params')
        if plist and isinstance(plist[0], dict):
            self._param_groups = plist
            self._parameters = [p for g in plist for p in g["params"]]
        else:
            self._param_groups = [{"params": plist}]
            self._parameters = plist
        self._weight_decay = weight_decay
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        self._accumulators: dict[str, dict[int, Tensor]] = {}
        self._pending_state: dict[str, Tensor] = {}  # set_state_dict before first step
        self._master_weights: dict[int, Tensor] = {}
        self._step_count = 0
        # trace-threaded step counter: python ints would be baked as constants
        # into to_static programs (Adam bias correction must advance per step)
        self._step_t = Tensor(jnp.zeros((), jnp.float32), _internal=True)
        self._lr_t = Tensor(jnp.asarray(
            learning_rate() if isinstance(learning_rate, LRScheduler) else learning_rate,
            jnp.float32), _internal=True)
        if isinstance(learning_rate, LRScheduler):
            import weakref

            learning_rate._bound.append(weakref.ref(self))
        self._aux_tensors: list[Tensor] = []

    # ------------------------------------------------------------ lr
    def get_lr(self):
        if isinstance(self._lr, LRScheduler):
            return self._lr()
        return self._lr

    def set_lr(self, value):
        if isinstance(self._lr, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._lr = value

    @property
    def _learning_rate(self):
        return self._lr

    # ------------------------------------------------------------ state
    def _acc(self, kind, p, init=None, dtype=None):
        store = self._accumulators.setdefault(kind, {})
        key = id(p)
        if key not in store:
            dt = dtype or (dtypes.float32 if self._multi_precision and
                           p.dtype in (dtypes.float16, dtypes.bfloat16) else p._data.dtype)
            pend = self._pending_state.pop(f"{p.name}_{kind}", None)
            if pend is not None:
                data = jnp.asarray(pend.numpy() if isinstance(pend, Tensor) else pend, dt)
            elif init is None:
                data = jnp.zeros(tuple(p.shape), dt)
            else:
                data = init() if callable(init) else init
            t = Tensor(data, _internal=True)
            store[key] = t
            self._aux_tensors.append(t)
        return store[key]

    def _master(self, p):
        if not self._multi_precision or p.dtype not in (dtypes.float16, dtypes.bfloat16):
            return None
        key = id(p)
        if key not in self._master_weights:
            pend = self._pending_state.pop(f"{p.name}_master", None)
            if pend is not None:
                data = jnp.asarray(
                    pend.numpy() if isinstance(pend, Tensor) else pend, jnp.float32)
            else:
                data = p._data.astype(jnp.float32)
            t = Tensor(data, _internal=True)
            self._master_weights[key] = t
            self._aux_tensors.append(t)
        return self._master_weights[key]

    def _structured_maps(self, structured_names):
        """(id(param) -> structured key, structured key -> raw name) for
        the params this optimizer owns. `structured_names` is
        {id(param): model-state-dict key}."""
        fwd, inv = {}, {}
        for p in self._parameters:
            sk = structured_names.get(id(p))
            if sk is not None:
                fwd[id(p)] = sk
                inv[sk] = p.name
        return fwd, inv

    def state_dict(self, structured_names=None):
        """Accumulator/master entries key as ``{param_name}_{kind}``.
        Raw tensor names come from a process-global counter, so they do
        NOT reproduce in a fresh process — pass `structured_names`
        ({id(param): model-state-dict key}) to key entries as
        ``{structured_key}@{kind}`` instead, which is what makes a
        checkpointed optimizer state restorable after a crash
        (ckpt/train_state.py does this automatically)."""
        fwd = {}
        if structured_names:
            fwd, inv = self._structured_maps(structured_names)

        def key_of(p, kind):
            sk = fwd.get(id(p))
            return f"{sk}@{kind}" if sk is not None else f"{p.name}_{kind}"

        out = {}
        # restored-but-not-yet-materialized entries pass through; with
        # structured naming requested, re-translate raw-named ones so a
        # save-before-first-step round-trips across processes too
        for k, v in self._pending_state.items():
            out[self._raw_to_structured(k, fwd) if fwd else k] = v
        for kind, store in self._accumulators.items():
            for p in self._parameters:
                if id(p) in store:
                    out[key_of(p, kind)] = store[id(p)]
        for p in self._parameters:
            if id(p) in self._master_weights:
                out[key_of(p, "master")] = self._master_weights[id(p)]
        # the device-side counter is the truth: compiled train steps advance
        # _step_t inside the XLA program without running this Python method
        dev_step = int(np.asarray(self._step_t._data))
        out["step"] = max(self._step_count, dev_step)
        if isinstance(self._lr, LRScheduler):
            out["LR_Scheduler"] = self._lr.state_dict()
        return out

    def _raw_to_structured(self, key, fwd):
        # longest raw name first: names come from a global counter, so
        # one name + "_" can prefix another ("w" vs "w_1"); the longest
        # match is the actual owner ("w_1_moment1" must never resolve to
        # param "w" with kind "1_moment1")
        for p in sorted(self._parameters, key=lambda q: -len(q.name)):
            sk = fwd.get(id(p))
            if sk is not None and key.startswith(p.name + "_"):
                return f"{sk}@{key[len(p.name) + 1:]}"
        return key

    def set_state_dict(self, state, structured_names=None):
        if structured_names:
            _, inv = self._structured_maps(structured_names)
            translated = {}
            for k, v in state.items():
                if "@" in k:
                    sk, kind = k.rsplit("@", 1)
                    raw = inv.get(sk)
                    if raw is not None:
                        translated[f"{raw}_{kind}"] = v
                        continue
                translated[k] = v
            state = translated
        consumed = set()
        for kind, store in self._accumulators.items():
            for p in self._parameters:
                k = f"{p.name}_{kind}"
                if k in state and id(p) in store:
                    v = state[k]
                    store[id(p)].set_value(v.numpy() if isinstance(v, Tensor) else v)
                    consumed.add(k)
        for p in self._parameters:
            k = f"{p.name}_master"
            if k in state and id(p) in self._master_weights:
                v = state[k]
                self._master_weights[id(p)].set_value(
                    v.numpy() if isinstance(v, Tensor) else v)
                consumed.add(k)
        # not-yet-created accumulators: stash and materialize on first _acc
        for k, v in state.items():
            if k in consumed or k in ("step", "LR_Scheduler"):
                continue
            self._pending_state[k] = v
        self._step_count = int(state.get("step", self._step_count))
        self._step_t._assign_raw(jnp.asarray(float(self._step_count), jnp.float32))
        if isinstance(self._lr, LRScheduler) and "LR_Scheduler" in state:
            self._lr.set_state_dict(state["LR_Scheduler"])

    set_dict = set_state_dict

    # ------------------------------------------------------------ step
    def _collect_params_grads(self):
        pg = []
        for group in self._param_groups:
            for p in group["params"]:
                if p.stop_gradient:
                    continue
                pg.append((p, p.grad, group))
        return pg

    def _lr_value(self):
        """jnp scalar LR, trace-aware: outside a trace (or in discovery) the
        tensor is refreshed from the scheduler, and the read is registered so
        compiled programs take LR as an input — never a baked constant."""
        from ..core.dispatch import current_trace

        tr = current_trace()
        if tr is None or tr.phase == "discover":
            self._lr_t._data = jnp.asarray(self.get_lr(), jnp.float32)
            if tr is not None:
                tr.on_read(self._lr_t)
        return self._lr_t._data

    def step(self):
        with no_grad():
            pgs = self._collect_params_grads()
            if self._grad_clip is not None:
                clipped = self._grad_clip([(p, g) for p, g, _ in pgs])
                pgs = [(p, g2, grp) for (p, _, grp), (_, g2) in zip(pgs, clipped)]
            self._step_count += 1
            self._step_t._assign_raw(self._step_t._data + 1.0)
            lr_data = self._lr_value()
            for p, g, group in pgs:
                if g is None:
                    continue
                lr_val = group.get("learning_rate", 1.0) * lr_data \
                    if "learning_rate" in group else lr_data
                wd = group.get("weight_decay", self._weight_decay)
                self._apply_one(p, g, lr_val, wd)

    @no_grad()
    def _apply_one(self, p, g, lr_val, wd):
        raise NotImplementedError

    def clear_grad(self, set_to_zero=False):
        for p in self._parameters:
            p.clear_gradient(set_to_zero)

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        if parameters is not None:
            # reference semantics: only the listed parameters are updated
            keep = {id(p) for p in parameters}
            saved = self._parameters
            self._parameters = [p for p in saved if id(p) in keep]
            try:
                self.step()
            finally:
                self._parameters = saved
        else:
            self.step()
        return None, None

    def _decay_l2(self, data, wd):
        if wd is None:
            return data * 0.0
        w = wd if isinstance(wd, float) else getattr(wd, "_coeff", 0.0)
        return data * w


def _wd_coeff(wd):
    if wd is None:
        return 0.0
    if isinstance(wd, (int, float)):
        return float(wd)
    return getattr(wd, "_coeff", 0.0)


def _wd_grad(wd, base):
    """Penalty gradient for coupled weight decay: L2 (float or
    regularizer.L2Decay) adds coeff*param; regularizer.L1Decay adds
    coeff*sign(param) (reference python/paddle/regularizer.py semantics)."""
    c = _wd_coeff(wd)
    if c == 0.0:
        return 0.0
    if getattr(wd, "_kind", "l2") == "l1":
        return c * jnp.sign(base)
    return c * base


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name,
                         multi_precision)

    def _apply_one(self, p, g, lr_val, wd):
        gd = g._data.astype(jnp.float32) if self._multi_precision else g._data
        master = self._master(p)
        base = master._data if master is not None else p._data
        gd = gd + _wd_grad(wd, base)
        new = base - lr_val * gd
        if master is not None:
            master._assign_raw(new)
            p._assign_raw(new.astype(p._data.dtype))
        else:
            p._assign_raw(new.astype(p._data.dtype))


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, use_multi_tensor=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name,
                         multi_precision)
        self._momentum = momentum
        self._nesterov = use_nesterov
        self._use_multi_tensor = use_multi_tensor

    def step(self):
        """use_multi_tensor=True (eager): ONE jitted fused update over the
        whole param pytree with donated buffers (≙ phi merged_momentum_)
        instead of a python loop of per-param updates."""
        if not getattr(self, "_use_multi_tensor", False):
            return super().step()
        from .fused import fused_momentum_step

        with no_grad():
            pgs = self._collect_params_grads()
            self._step_count += 1
            lr_data = self._lr_value()
            if fused_momentum_step(self, pgs, lr_data):
                return
            self._step_count -= 1
        return super().step()

    def _apply_one(self, p, g, lr_val, wd):
        v = self._acc("velocity", p)
        master = self._master(p)
        base = master._data if master is not None else p._data
        gd = g._data.astype(base.dtype) + _wd_grad(wd, base)
        vel = self._momentum * v._data + gd
        v._assign_raw(vel)
        if self._nesterov:
            upd = gd + self._momentum * vel
        else:
            upd = vel
        new = base - lr_val * upd
        if master is not None:
            master._assign_raw(new)
        p._assign_raw(new.astype(p._data.dtype))


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _apply_one(self, p, g, lr_val, wd):
        acc = self._acc("moment", p, init=lambda: jnp.full(
            tuple(p.shape), self._init_acc, p._data.dtype))
        gd = g._data + _wd_grad(wd, p._data)
        new_acc = acc._data + jnp.square(gd)
        acc._assign_raw(new_acc)
        p._assign_raw(p._data - lr_val * gd / (jnp.sqrt(new_acc) + self._epsilon))


class DecayedAdagrad(Optimizer):
    """Adagrad with an exponentially decayed accumulator (≙ phi
    decayed_adagrad kernel, /root/reference/paddle/phi/kernels/
    decayed_adagrad_kernel.h): acc = decay·acc + (1-decay)·g²."""

    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._decay = decay
        self._epsilon = epsilon

    def _apply_one(self, p, g, lr_val, wd):
        acc = self._acc("moment", p)
        gd = g._data + _wd_grad(wd, p._data)
        new_acc = self._decay * acc._data + (1 - self._decay) * jnp.square(gd)
        acc._assign_raw(new_acc)
        p._assign_raw(p._data - lr_val * gd / (jnp.sqrt(new_acc) + self._epsilon))


class Ftrl(Optimizer):
    """FTRL-proximal (McMahan 2013) (≙ phi ftrl kernel,
    /root/reference/paddle/phi/kernels/ftrl_kernel.h): per-coordinate
    adaptive step with L1/L2 proximal regularization."""

    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._l1 = l1
        self._l2 = l2
        self._lr_power = lr_power

    def _apply_one(self, p, g, lr_val, wd):
        sq = self._acc("squared", p)     # n: sum of g²
        lin = self._acc("linear", p)     # z
        gd = g._data + _wd_grad(wd, p._data)
        new_sq = sq._data + jnp.square(gd)
        lp = self._lr_power
        sigma = (jnp.power(new_sq, -lp) - jnp.power(sq._data, -lp)) / lr_val
        new_lin = lin._data + gd - sigma * p._data
        sq._assign_raw(new_sq)
        lin._assign_raw(new_lin)
        quad = jnp.power(new_sq, -lp) / lr_val + 2.0 * self._l2
        pre = jnp.clip(new_lin, -self._l1, self._l1) - new_lin
        p._assign_raw(jnp.where(jnp.abs(new_lin) > self._l1,
                                pre / quad, jnp.zeros_like(p._data)))


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _apply_one(self, p, g, lr_val, wd):
        ms = self._acc("mean_square", p)
        mom = self._acc("momentum", p)
        gd = g._data + _wd_grad(wd, p._data)
        new_ms = self._rho * ms._data + (1 - self._rho) * jnp.square(gd)
        ms._assign_raw(new_ms)
        denom = new_ms
        if self._centered:
            mg = self._acc("mean_grad", p)
            new_mg = self._rho * mg._data + (1 - self._rho) * gd
            mg._assign_raw(new_mg)
            denom = new_ms - jnp.square(new_mg)
        upd = self._momentum * mom._data + lr_val * gd / jnp.sqrt(denom + self._epsilon)
        mom._assign_raw(upd)
        p._assign_raw(p._data - upd)


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = epsilon
        self._rho = rho

    def _apply_one(self, p, g, lr_val, wd):
        avg_sq = self._acc("avg_squared_grad", p)
        avg_upd = self._acc("avg_squared_update", p)
        gd = g._data + _wd_grad(wd, p._data)
        new_sq = self._rho * avg_sq._data + (1 - self._rho) * jnp.square(gd)
        upd = jnp.sqrt(avg_upd._data + self._epsilon) / jnp.sqrt(new_sq + self._epsilon) * gd
        new_upd = self._rho * avg_upd._data + (1 - self._rho) * jnp.square(upd)
        avg_sq._assign_raw(new_sq)
        avg_upd._assign_raw(new_upd)
        p._assign_raw(p._data - lr_val * upd)


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, use_multi_tensor=False, amsgrad=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name,
                         multi_precision)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._amsgrad = amsgrad
        self._decoupled_wd = False
        self._use_multi_tensor = use_multi_tensor

    def step(self):
        """use_multi_tensor=True (eager): ONE jitted fused update over the
        whole param pytree with donated buffers (≙ phi fused_adam_kernel.h)
        instead of a python loop of per-param updates."""
        if not getattr(self, "_use_multi_tensor", False):
            return super().step()
        from .fused import fused_adam_step

        with no_grad():
            pgs = self._collect_params_grads()
            self._step_count += 1
            self._step_t._assign_raw(self._step_t._data + 1.0)
            lr_data = self._lr_value()
            if fused_adam_step(self, pgs, lr_data):
                return
            # unsupported case: roll the counter back, take the base path
            self._step_count -= 1
            self._step_t._assign_raw(self._step_t._data - 1.0)
        return super().step()

    def _apply_one(self, p, g, lr_val, wd):
        m = self._acc("moment1", p)
        v = self._acc("moment2", p)
        master = self._master(p)
        base = master._data if master is not None else p._data
        comp_dt = base.dtype if master is not None else (
            jnp.float32 if p.dtype in (dtypes.float16, dtypes.bfloat16) else base.dtype)
        gd = g._data.astype(comp_dt)
        if not self._decoupled_wd:
            gd = gd + _wd_grad(wd, base.astype(comp_dt))
        t = self._step_t._data
        b1, b2 = self._beta1, self._beta2
        new_m = b1 * m._data.astype(comp_dt) + (1 - b1) * gd
        new_v = b2 * v._data.astype(comp_dt) + (1 - b2) * jnp.square(gd)
        # moments STAY in their accumulator dtype (p.dtype unless
        # multi_precision) — compute is fp32, storage follows paddle
        # semantics so a bf16-decorated model keeps bf16 optimizer state
        # (how a ~1B model + AdamW fits one v5e chip)
        m._assign_raw(new_m.astype(m._data.dtype))
        v._assign_raw(new_v.astype(v._data.dtype))
        mhat = new_m / (1 - b1 ** t)
        if self._amsgrad:
            vmax = self._acc("moment2_max", p)
            new_vmax = jnp.maximum(vmax._data.astype(comp_dt), new_v)
            vmax._assign_raw(new_vmax.astype(vmax._data.dtype))
            vhat = new_vmax / (1 - b2 ** t)
        else:
            vhat = new_v / (1 - b2 ** t)
        step = lr_val * mhat / (jnp.sqrt(vhat) + self._epsilon)
        newb = base.astype(comp_dt)
        if self._decoupled_wd:
            # decoupled decay honors the regularizer kind: L2 (default) is
            # the multiplicative AdamW shrink, L1Decay subtracts
            # lr·coeff·sign(param)
            if getattr(wd, "_kind", "l2") == "l1":
                newb = newb - lr_val * _wd_coeff(wd) * jnp.sign(newb)
            else:
                newb = newb * (1.0 - lr_val * _wd_coeff(wd))
        new = newb - step
        if master is not None:
            master._assign_raw(new)
        p._assign_raw(new.astype(p._data.dtype))


class AdamW(Adam):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, lr_ratio=None,
                 apply_decay_param_fun=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, amsgrad=False, use_multi_tensor=False,
                 name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, multi_precision,
                         use_multi_tensor=use_multi_tensor, amsgrad=amsgrad)
        self._decoupled_wd = True
        self._apply_decay_param_fun = apply_decay_param_fun
        self._lr_ratio = lr_ratio

    def _apply_one(self, p, g, lr_val, wd):
        if self._apply_decay_param_fun is not None and not self._apply_decay_param_fun(p.name):
            wd = 0.0
        if self._lr_ratio is not None:
            lr_val = lr_val * self._lr_ratio(p)
        super()._apply_one(p, g, lr_val, wd)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _apply_one(self, p, g, lr_val, wd):
        m = self._acc("moment", p)
        u = self._acc("inf_norm", p)
        gd = g._data + _wd_grad(wd, p._data)
        new_m = self._beta1 * m._data + (1 - self._beta1) * gd
        new_u = jnp.maximum(self._beta2 * u._data, jnp.abs(gd))
        m._assign_raw(new_m)
        u._assign_raw(new_u)
        t = self._step_t._data
        p._assign_raw(p._data - lr_val / (1 - self._beta1 ** t) * new_m /
                      (new_u + self._epsilon))


class NAdam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 momentum_decay=0.004, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._momentum_decay = momentum_decay

    def _apply_one(self, p, g, lr_val, wd):
        m = self._acc("moment1", p)
        v = self._acc("moment2", p)
        # cumulative mu product accumulator (scalar per param)
        mu_prod = self._acc("mu_product", p,
                            init=lambda: jnp.ones((), jnp.float32), dtype=jnp.float32)
        gd = g._data + _wd_grad(wd, p._data)
        t = self._step_t._data
        b1, b2 = self._beta1, self._beta2
        mu_t = b1 * (1 - 0.5 * 0.96 ** (t * self._momentum_decay))
        mu_t1 = b1 * (1 - 0.5 * 0.96 ** ((t + 1) * self._momentum_decay))
        new_mu_prod = mu_prod._data * mu_t
        mu_prod._assign_raw(new_mu_prod)
        new_m = b1 * m._data + (1 - b1) * gd
        new_v = b2 * v._data + (1 - b2) * jnp.square(gd)
        m._assign_raw(new_m)
        v._assign_raw(new_v)
        mhat = (mu_t1 * new_m / (1 - new_mu_prod * mu_t1)
                + (1 - mu_t) * gd / (1 - new_mu_prod))
        vhat = new_v / (1 - b2 ** t)
        p._assign_raw(p._data - lr_val * mhat / (jnp.sqrt(vhat) + self._epsilon))


class RAdam(Adam):
    pass


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, lamb_weight_decay, grad_clip, name,
                         multi_precision)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _apply_one(self, p, g, lr_val, wd):
        m = self._acc("moment1", p)
        v = self._acc("moment2", p)
        gd = g._data.astype(jnp.float32)
        t = self._step_t._data
        b1, b2 = self._beta1, self._beta2
        new_m = b1 * m._data + (1 - b1) * gd
        new_v = b2 * v._data + (1 - b2) * jnp.square(gd)
        m._assign_raw(new_m)
        v._assign_raw(new_v)
        mhat = new_m / (1 - b1 ** t)
        vhat = new_v / (1 - b2 ** t)
        r = mhat / (jnp.sqrt(vhat) + self._epsilon)
        wd_c = 0.0 if (self._exclude_fn is not None and self._exclude_fn(p)) else self._wd
        base = p._data.astype(jnp.float32)
        upd = r + wd_c * base
        wnorm = jnp.sqrt(jnp.sum(jnp.square(base)))
        unorm = jnp.sqrt(jnp.sum(jnp.square(upd)))
        trust = jnp.where((wnorm > 0) & (unorm > 0), wnorm / unorm, 1.0)
        p._assign_raw((base - lr_val * trust * upd).astype(p._data.dtype))


class ASGD(Optimizer):
    """Averaged SGD (≙ optimizer/asgd.py → phi asgd_kernel): keeps a running
    average of the last `batch_num` gradients; the update uses the average."""

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        if batch_num <= 0:
            raise ValueError("batch_num must be positive")
        self._n = int(batch_num)

    def _apply_one(self, p, g, lr_val, wd):
        gd = g._data + _wd_grad(wd, p._data)
        d = self._acc("d", p)                       # running mean of grads
        step = self._acc("step", p, init=lambda: jnp.zeros((), jnp.float32))
        if self._n > 1:
            ys = self._acc("ys", p,
                           init=lambda: jnp.zeros((self._n,) + tuple(p.shape),
                                                  p._data.dtype))
            slot = (step._data.astype(jnp.int32)) % self._n
            old = ys._data[slot]
            new_d = d._data + (gd - old) / self._n
            ys._assign_raw(ys._data.at[slot].set(gd))
        else:
            new_d = gd
        d._assign_raw(new_d)
        step._assign_raw(step._data + 1)
        p._assign_raw((p._data - lr_val * new_d).astype(p._data.dtype))


class Rprop(Optimizer):
    """Resilient backprop (≙ optimizer/rprop.py → phi rprop_kernel):
    sign-based per-element step sizes, grown on sign agreement and shrunk
    on sign flips (flipped entries skip the update that round)."""

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50.0),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name,
                         multi_precision)
        self._lr_min, self._lr_max = learning_rate_range
        self._eta_neg, self._eta_pos = etas
        self._init_step = learning_rate

    def _apply_one(self, p, g, lr_val, wd):
        gd = g._data
        prev = self._acc("prev_grad", p)
        steps = self._acc("steps", p,
                          init=lambda: jnp.full(tuple(p.shape),
                                                self._init_step, jnp.float32))
        sign = jnp.sign(gd * prev._data)
        new_steps = jnp.where(
            sign > 0, jnp.minimum(steps._data * self._eta_pos, self._lr_max),
            jnp.where(sign < 0,
                      jnp.maximum(steps._data * self._eta_neg, self._lr_min),
                      steps._data))
        eff_grad = jnp.where(sign < 0, 0.0, gd)
        p._assign_raw((p._data - jnp.sign(eff_grad) * new_steps
                       ).astype(p._data.dtype))
        steps._assign_raw(new_steps)
        prev._assign_raw(eff_grad)


class LBFGS(Optimizer):
    """Limited-memory BFGS (≙ optimizer/lbfgs.py): two-loop recursion over a
    host-side (s, y) history; the closure re-runs eagerly, so each inner
    evaluation is itself a cached XLA program. line_search_fn='strong_wolfe'
    is approximated with Armijo backtracking (documented deviation)."""

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9, history_size=100,
                 line_search_fn=None, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._max_iter = max_iter
        self._max_eval = max_eval or max_iter * 5 // 4
        self._tol_grad = tolerance_grad
        self._tol_change = tolerance_change
        self._history = int(history_size)
        self._line_search = line_search_fn
        self._s, self._y = [], []

    def _gather(self):
        flat = jnp.concatenate([jnp.ravel(p._data.astype(jnp.float32))
                                for p in self._parameters])
        return flat

    def _gather_grad(self):
        return jnp.concatenate([
            jnp.ravel((p.grad._data if p.grad is not None
                       else jnp.zeros(tuple(p.shape))).astype(jnp.float32))
            for p in self._parameters])

    def _scatter(self, flat):
        i = 0
        for p in self._parameters:
            n = int(np.prod(p.shape)) if p.shape else 1
            p._assign_raw(flat[i:i + n].reshape(tuple(p.shape))
                          .astype(p._data.dtype))
            i += n

    def _direction(self, grad):
        # standard two-loop recursion
        q = grad
        alphas = []
        for s, y in zip(reversed(self._s), reversed(self._y)):
            rho = 1.0 / (jnp.dot(y, s) + 1e-10)
            a = rho * jnp.dot(s, q)
            q = q - a * y
            alphas.append((a, rho, s, y))
        if self._s:
            s, y = self._s[-1], self._y[-1]
            q = q * (jnp.dot(s, y) / (jnp.dot(y, y) + 1e-10))
        for a, rho, s, y in reversed(alphas):
            b = rho * jnp.dot(y, q)
            q = q + (a - b) * s
        return -q

    @no_grad()
    def step(self, closure=None):
        if closure is None:
            raise ValueError("LBFGS.step requires a closure that recomputes "
                             "the loss (reference lbfgs.py contract)")

        def eval_closure():
            from ..core.dispatch import enable_grad

            self.clear_grad()
            with enable_grad():
                loss = closure()
                # paddle contract: the closure just returns the loss; the
                # optimizer drives the backward pass
                loss.backward()
            return float(np.asarray(loss._data))

        loss = eval_closure()
        evals = 1
        for _ in range(self._max_iter):
            flat = self._gather()
            grad = self._gather_grad()
            if float(jnp.max(jnp.abs(grad))) <= self._tol_grad:
                break
            d = self._direction(grad)
            lr0 = float(self._lr_value())
            t = lr0
            if self._line_search is not None:
                gtd = float(jnp.dot(grad, d))
                ok = False
                for _bt in range(10):  # Armijo backtracking
                    self._scatter(flat + t * d)
                    new_loss = eval_closure()
                    evals += 1
                    if new_loss <= loss + 1e-4 * t * gtd:
                        ok = True
                        break
                    t *= 0.5
                if not ok:
                    self._scatter(flat)
                    eval_closure()
                    break
            else:
                self._scatter(flat + t * d)
                new_loss = eval_closure()
                evals += 1
            new_grad = self._gather_grad()
            s = t * d
            y = new_grad - grad
            if float(jnp.dot(s, y)) > 1e-10:
                self._s.append(s)
                self._y.append(y)
                if len(self._s) > self._history:
                    self._s.pop(0)
                    self._y.pop(0)
            if abs(new_loss - loss) < self._tol_change:
                loss = new_loss
                break
            loss = new_loss
            if evals >= self._max_eval:
                break
        return loss
