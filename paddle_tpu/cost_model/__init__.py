"""paddle.cost_model parity (≙ python/paddle/cost_model/cost_model.py +
static_op_benchmark.json): per-op time/memory estimates for planners
(auto-tuner, auto-parallel static Engine).

TPU-first: instead of shipping a stale benchmark JSON, ops are measured
live on the current backend (compile once, time the cached executable) and
memoized for the process — the numbers planners consume reflect the chip
they will actually run on.
"""
from __future__ import annotations

import time

__all__ = ['CostModel']


class CostModel:
    def __init__(self):
        self._cache: dict = {}

    def get_static_op_time(self, op_name, forward=True, dtype="float32",
                           shape=(64, 64)):
        """Measure one op's steady-state latency on the live backend.
        Returns {"op_time_ms": float} like the reference's JSON entries."""
        key = (op_name, bool(forward), str(dtype), tuple(shape))
        if key in self._cache:
            return self._cache[key]

        import numpy as np

        import paddle_tpu as paddle

        rs = np.random.RandomState(0)
        x = paddle.to_tensor(rs.rand(*shape).astype(dtype) + 0.5)
        fn = getattr(paddle, op_name, None)
        if fn is None:
            raise ValueError(f"unknown op for cost model: {op_name}")
        try:
            import inspect

            nargs = 2 if len(
                [p for p in inspect.signature(fn).parameters.values()
                 if p.default is p.empty]) >= 2 else 1
        except (TypeError, ValueError):
            nargs = 1
        args = (x, x) if nargs == 2 else (x,)

        if forward:
            def run():
                return fn(*args)
        else:
            xg = paddle.to_tensor(rs.rand(*shape).astype(dtype) + 0.5)
            xg.stop_gradient = False
            gargs = (xg, x) if nargs == 2 else (xg,)

            def run():
                out = fn(*gargs)
                out.sum().backward()
                return xg.grad

        for _ in range(3):  # warm-up: compile + cache
            out = run()
        import jax

        jax.block_until_ready(out._data)
        t0 = time.perf_counter()
        iters = 10
        for _ in range(iters):
            out = run()
        jax.block_until_ready(out._data)
        res = {"op_time_ms": (time.perf_counter() - t0) / iters * 1e3}
        self._cache[key] = res
        return res

    # reference API names kept for drop-in use
    def profile_measure(self, *args, **kwargs):
        raise NotImplementedError(
            "whole-program profiling lives in paddle.profiler (xplane); "
            "per-op estimates via get_static_op_time")
