"""paddle.audio.datasets parity (≙ python/paddle/audio/datasets/{tess,esc50}.py):
folder-layout readers over locally provided archives (zero-egress build —
no download), emitting raw waveforms or features via paddle.audio.features.
"""
from __future__ import annotations

import os

import numpy as np

from ..io import Dataset

__all__ = ['TESS', 'ESC50']


class _AudioFolderDataset(Dataset):
    """Walk a directory of WAV files, label from filename via _label_of;
    train/dev partitioning via _fold_of (reference datasets split by fold)."""

    def __init__(self, data_dir, sample_rate, mode, n_folds, split,
                 feat_type='raw', **feat_kwargs):
        if data_dir is None or not os.path.isdir(data_dir):
            raise ValueError(
                f"{type(self).__name__}: data_dir with the extracted WAV "
                "files is required (downloads unavailable in this build)")
        if mode not in ('train', 'dev'):
            raise ValueError(f"mode should be 'train' or 'dev', got {mode!r}")
        all_files = []
        for root, _dirs, files in os.walk(data_dir):
            for fn in sorted(files):
                if fn.lower().endswith('.wav'):
                    all_files.append(os.path.join(root, fn))
        if not all_files:
            raise ValueError(f"no .wav files under {data_dir}")
        self.files = []
        for i, path in enumerate(all_files):
            fold = self._fold_of(path, i, n_folds)
            in_dev = fold == split
            if (mode == 'dev') == in_dev:
                self.files.append(path)
        self.sample_rate = sample_rate
        self.feat_type = feat_type
        self.feat_kwargs = feat_kwargs
        self._extractor = None

    def _fold_of(self, path, index, n_folds):
        """Default fold assignment: stable round-robin by sorted position
        (1-based, like the reference's fold column)."""
        return index % n_folds + 1

    def _feature(self, wave):
        if self.feat_type == 'raw':
            return wave
        if self._extractor is None:
            from . import features as F

            cls = {'spectrogram': F.Spectrogram,
                   'melspectrogram': F.MelSpectrogram,
                   'logmelspectrogram': F.LogMelSpectrogram,
                   'mfcc': F.MFCC}.get(self.feat_type)
            if cls is None:
                raise ValueError(f"unknown feat_type {self.feat_type!r}")
            self._extractor = cls(**self.feat_kwargs)
        return self._extractor(wave)

    def __len__(self):
        return len(self.files)

    def __getitem__(self, idx):
        from .backends import load

        wave, _sr = load(self.files[idx])
        mono = wave[0] if wave.shape[0] >= 1 else wave
        return np.asarray(self._feature(mono)._data), self._label_of(
            self.files[idx])


class TESS(_AudioFolderDataset):
    """Toronto emotional speech set: label = emotion token in the filename
    (OAF_back_angry.wav → angry)."""

    EMOTIONS = ['angry', 'disgust', 'fear', 'happy', 'neutral', 'ps', 'sad']

    def __init__(self, data_dir=None, mode='train', n_folds=5, split=1,
                 feat_type='raw', **kwargs):
        super().__init__(data_dir, 24414, mode, n_folds, split, feat_type,
                         **kwargs)

    def _label_of(self, path):
        token = os.path.basename(path).rsplit('.', 1)[0].split('_')[-1].lower()
        if token not in self.EMOTIONS:
            raise ValueError(f"unrecognized TESS emotion in {path}")
        return self.EMOTIONS.index(token)


class ESC50(_AudioFolderDataset):
    """ESC-50 environmental sounds: label = target field of the filename
    (1-100032-A-0.wav → class 0)."""

    def __init__(self, data_dir=None, mode='train', split=1, feat_type='raw',
                 **kwargs):
        super().__init__(data_dir, 44100, mode, 5, split, feat_type, **kwargs)

    def _fold_of(self, path, index, n_folds):
        """ESC-50 filenames carry their fold: {fold}-{id}-{take}-{target}.wav."""
        stem = os.path.basename(path).rsplit('.', 1)[0]
        try:
            return int(stem.split('-')[0])
        except ValueError:
            return index % n_folds + 1

    def _label_of(self, path):
        stem = os.path.basename(path).rsplit('.', 1)[0]
        return int(stem.split('-')[-1])
