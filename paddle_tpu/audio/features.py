"""paddle.audio.features layers (≙ python/paddle/audio/features/layers.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.dispatch import op_call
from ..nn.layer_base import Layer
from . import functional as AF


class Spectrogram(Layer):
    def __init__(self, n_fft: int = 512, hop_length: int | None = None,
                 win_length: int | None = None, window: str = "hann",
                 power: float = 2.0, center: bool = True, pad_mode: str = "reflect",
                 dtype: str = "float32"):
        super().__init__()
        self.kw = dict(n_fft=n_fft, hop_length=hop_length,
                       win_length=win_length, window=window, power=power,
                       center=center, pad_mode=pad_mode)
        self._out_dtype = dtype

    def forward(self, x):
        return AF.spectrogram(x, **self.kw).astype(self._out_dtype)


class MelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: int | None = None, win_length: int | None = None,
                 window: str = "hann", power: float = 2.0, center: bool = True,
                 n_mels: int = 64, f_min: float = 50.0, f_max: float | None = None,
                 htk: bool = False, norm: str = "slaney", dtype: str = "float32"):
        super().__init__()
        self.spec = Spectrogram(n_fft, hop_length, win_length, window, power,
                                center)
        self.fbank = AF.compute_fbank_matrix(sr, n_fft, n_mels, f_min, f_max,
                                             htk, norm)
        self._out_dtype = dtype

    def forward(self, x):
        s = self.spec(x)  # [..., bins, frames] (reference orientation)
        fb = self.fbank

        def fn(sv, fbv):
            return fbv @ sv  # [..., n_mels, frames]

        return op_call(fn, s, fb, name="mel_spectrogram").astype(self._out_dtype)


class LogMelSpectrogram(MelSpectrogram):
    def __init__(self, *args, ref_value: float = 1.0, amin: float = 1e-10,
                 top_db: float | None = None, **kw):
        super().__init__(*args, **kw)
        self.amin = amin
        self.ref_value = ref_value
        self.top_db = top_db

    def forward(self, x):
        mel = super().forward(x)
        amin, ref, top_db = self.amin, self.ref_value, self.top_db

        def fn(m):
            db = 10.0 * jnp.log10(jnp.maximum(m, amin) / ref)
            if top_db is not None:
                db = jnp.maximum(db, db.max() - top_db)
            return db

        return op_call(fn, mel, name="log_mel")


class MFCC(Layer):
    def __init__(self, sr: int = 22050, n_mfcc: int = 40, n_mels: int = 64,
                 **mel_kw):
        super().__init__()
        self.logmel = LogMelSpectrogram(sr=sr, n_mels=n_mels, **mel_kw)
        # type-II DCT basis
        k = np.arange(n_mels)
        dct = np.cos(np.pi / n_mels * (k + 0.5)[None, :] * np.arange(n_mfcc)[:, None])
        dct *= np.sqrt(2.0 / n_mels)
        dct[0] *= np.sqrt(0.5)
        self._dct = jnp.asarray(dct.T, jnp.float32)  # [n_mels, n_mfcc]

    def forward(self, x):
        lm = self.logmel(x)  # [..., n_mels, frames]
        dct = self._dct

        def fn(m):
            # [..., n_mfcc, frames] (reference orientation)
            return jnp.swapaxes(jnp.swapaxes(m, -1, -2) @ dct, -1, -2)

        return op_call(fn, lm, name="mfcc")
