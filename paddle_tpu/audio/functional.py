"""paddle.audio.functional subset (≙ python/paddle/audio/functional).

STFT/mel machinery as jnp compositions through the dispatch funnel — the
MXU-friendly formulation (framing via gather + matmul with the DFT/mel
bases) rather than a CUDA FFT binding.
"""
from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from ..core.dispatch import op_call
from ..core.tensor import Tensor


def get_window(window: str, win_length: int, fftbins: bool = True):
    """hann/hamming/blackman/rectangular window as a Tensor."""
    n = win_length
    k = np.arange(n)
    denom = n if fftbins else n - 1
    if window in ("hann", "hanning"):
        w = 0.5 - 0.5 * np.cos(2 * np.pi * k / denom)
    elif window == "hamming":
        w = 0.54 - 0.46 * np.cos(2 * np.pi * k / denom)
    elif window == "blackman":
        w = (0.42 - 0.5 * np.cos(2 * np.pi * k / denom)
             + 0.08 * np.cos(4 * np.pi * k / denom))
    elif window in ("rect", "rectangular", "ones", "boxcar"):
        w = np.ones(n)
    else:
        raise ValueError(f"unknown window '{window}'")
    return Tensor(jnp.asarray(w, jnp.float32), _internal=True)


def hz_to_mel(freq, htk: bool = False):
    f = np.asarray(freq, np.float64)
    if htk:
        return 2595.0 * np.log10(1.0 + f / 700.0)
    f_min, f_sp = 0.0, 200.0 / 3
    mels = (f - f_min) / f_sp
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return np.where(f >= min_log_hz,
                    min_log_mel + np.log(np.maximum(f, 1e-10) / min_log_hz) / logstep,
                    mels)


def mel_to_hz(mel, htk: bool = False):
    m = np.asarray(mel, np.float64)
    if htk:
        return 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    f_min, f_sp = 0.0, 200.0 / 3
    freqs = f_min + f_sp * m
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return np.where(m >= min_log_mel,
                    min_log_hz * np.exp(logstep * (m - min_log_mel)), freqs)


def compute_fbank_matrix(sr: int, n_fft: int, n_mels: int = 64, f_min: float = 0.0,
                         f_max: float | None = None, htk: bool = False,
                         norm: str = "slaney"):
    """[n_mels, n_fft//2 + 1] triangular mel filter bank."""
    f_max = f_max or sr / 2.0
    n_bins = n_fft // 2 + 1
    fft_freqs = np.linspace(0, sr / 2, n_bins)
    mel_pts = np.linspace(hz_to_mel(f_min, htk), hz_to_mel(f_max, htk),
                          n_mels + 2)
    hz_pts = mel_to_hz(mel_pts, htk)
    fb = np.zeros((n_mels, n_bins))
    for m in range(n_mels):
        lo, ctr, hi = hz_pts[m], hz_pts[m + 1], hz_pts[m + 2]
        up = (fft_freqs - lo) / max(ctr - lo, 1e-10)
        down = (hi - fft_freqs) / max(hi - ctr, 1e-10)
        fb[m] = np.maximum(0, np.minimum(up, down))
    if norm == "slaney":
        enorm = 2.0 / (hz_pts[2:] - hz_pts[:-2])
        fb *= enorm[:, None]
    return Tensor(jnp.asarray(fb, jnp.float32), _internal=True)


def _frame(xv, frame_length, hop_length):
    n = xv.shape[-1]
    n_frames = 1 + (n - frame_length) // hop_length
    idx = (np.arange(frame_length)[None, :]
           + hop_length * np.arange(n_frames)[:, None])
    return xv[..., idx]  # [..., n_frames, frame_length]


def stft(x: Tensor, n_fft: int = 512, hop_length: int | None = None,
         win_length: int | None = None, window: str = "hann",
         center: bool = True, pad_mode: str = "reflect"):
    """Magnitude-capable complex STFT as framed matmul with the DFT basis.
    Returns (real, imag) Tensors [..., n_frames, n_fft//2 + 1]."""
    win_length = win_length or n_fft
    hop_length = hop_length or win_length // 4
    w = get_window(window, win_length)._data
    if win_length < n_fft:  # center-pad the window
        pad = (n_fft - win_length) // 2
        w = jnp.pad(w, (pad, n_fft - win_length - pad))
    k = np.arange(n_fft // 2 + 1)[:, None] * np.arange(n_fft)[None, :]
    ang = -2.0 * np.pi * k / n_fft
    cos_b = jnp.asarray(np.cos(ang).T, jnp.float32)  # [n_fft, bins]
    sin_b = jnp.asarray(np.sin(ang).T, jnp.float32)

    def fn(xv):
        if center:
            pad = n_fft // 2
            mode = "reflect" if pad_mode == "reflect" else "constant"
            xv = jnp.pad(xv, [(0, 0)] * (xv.ndim - 1) + [(pad, pad)], mode=mode)
        frames = _frame(xv, n_fft, hop_length) * w
        return frames @ cos_b, frames @ sin_b

    return op_call(fn, x, name="stft")


def spectrogram(x: Tensor, n_fft: int = 512, hop_length: int | None = None,
                win_length: int | None = None, window: str = "hann",
                power: float = 2.0, center: bool = True,
                pad_mode: str = "reflect"):
    re, im = stft(x, n_fft, hop_length, win_length, window, center, pad_mode)

    def fn(r, i):
        mag = r * r + i * i
        out = mag if power == 2.0 else jnp.power(jnp.sqrt(mag), power)
        # reference orientation: [..., n_fft//2+1, num_frames]
        return jnp.swapaxes(out, -1, -2)

    return op_call(fn, re, im, name="spectrogram")
