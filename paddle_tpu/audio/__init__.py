"""paddle.audio (≙ python/paddle/audio) — feature extraction subset.

Functional features implemented over jnp (differentiable); dataset
downloads are unavailable in this environment (datasets raise with
instructions, like paddle.vision.datasets).
"""
from . import functional
from .features import LogMelSpectrogram, MFCC, MelSpectrogram, Spectrogram

__all__ = ["functional", "Spectrogram", "MelSpectrogram", "LogMelSpectrogram",
           "MFCC"]
