"""paddle.audio (≙ python/paddle/audio) — features, WAV backends, datasets.

Feature extractors are jnp compositions (differentiable, jit-able); the
backend is a zero-dependency stdlib `wave` reader/writer; datasets read
locally provided archives (downloads unavailable in this environment).
"""
from . import functional
from . import backends
from . import datasets
from .backends import load, save, info
from .features import LogMelSpectrogram, MFCC, MelSpectrogram, Spectrogram

__all__ = ["functional", "backends", "datasets", "load", "save", "info",
           "Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]
