"""paddle.audio.backends parity (≙ python/paddle/audio/backends/ —
wave_backend.py): WAV load/save/info without external audio libs (stdlib
`wave` + numpy). The reference's optional paddleaudio backend is a plugin;
here the wave backend is the only one (zero-dependency build)."""
from __future__ import annotations

import wave as _wave

import numpy as np

__all__ = ['load', 'save', 'info', 'list_available_backends', 'get_current_backend',
           'set_backend']

_BACKEND = "wave_backend"


class AudioInfo:
    """≙ backends/backend.AudioInfo."""

    def __init__(self, sample_rate, num_samples, num_channels, bits_per_sample,
                 encoding="PCM_S"):
        self.sample_rate = sample_rate
        self.num_samples = num_samples
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample
        self.encoding = encoding

    def __repr__(self):
        return (f"AudioInfo(sample_rate={self.sample_rate}, "
                f"num_samples={self.num_samples}, "
                f"num_channels={self.num_channels}, "
                f"bits_per_sample={self.bits_per_sample})")


def list_available_backends():
    return [_BACKEND]


def get_current_backend():
    return _BACKEND


def set_backend(backend_name):
    if backend_name != _BACKEND:
        raise NotImplementedError(
            f"only '{_BACKEND}' is available in this build (no external "
            f"audio libraries); got {backend_name!r}")


_WIDTH_DTYPE = {1: np.uint8, 2: np.int16, 4: np.int32}


def info(filepath):
    """Read WAV header metadata."""
    with _wave.open(str(filepath), 'rb') as f:
        return AudioInfo(f.getframerate(), f.getnframes(), f.getnchannels(),
                         f.getsampwidth() * 8)


def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True):
    """Load WAV → (Tensor [channels, time] float32 in [-1,1] when normalize,
    sample_rate) (≙ wave_backend.load)."""
    from ..core.tensor import Tensor

    with _wave.open(str(filepath), 'rb') as f:
        sr, nch, width = f.getframerate(), f.getnchannels(), f.getsampwidth()
        f.setpos(frame_offset)
        n = f.getnframes() - frame_offset if num_frames < 0 else num_frames
        raw = f.readframes(n)
    dt = _WIDTH_DTYPE.get(width)
    if dt is None:
        raise ValueError(f"unsupported WAV sample width: {width} bytes")
    data = np.frombuffer(raw, dtype=dt).reshape(-1, nch)
    if width == 1:  # 8-bit WAV is unsigned
        data = data.astype(np.float32) - 128.0
        scale = 128.0
    else:
        data = data.astype(np.float32)
        scale = float(2 ** (8 * width - 1))
    if normalize:
        data = data / scale
    out = data.T if channels_first else data
    return Tensor(out.copy(), _internal=True, stop_gradient=True), sr


def save(filepath, src, sample_rate, channels_first=True, encoding="PCM_16",
         bits_per_sample=16):
    """Save a float waveform Tensor/array to 16-bit PCM WAV."""
    data = np.asarray(src._data if hasattr(src, "_data") else src)
    if data.ndim == 1:
        # 1-D mono has no channel axis: normalize to [1, time] and treat as
        # channels-first regardless of the flag
        data = data[None, :]
        channels_first = True
    if channels_first:
        data = data.T  # → [time, channels]
    if bits_per_sample != 16 or encoding != "PCM_16":
        raise NotImplementedError("wave backend writes 16-bit PCM only")
    pcm = np.clip(data, -1.0, 1.0)
    pcm = (pcm * 32767.0).astype(np.int16)
    with _wave.open(str(filepath), 'wb') as f:
        f.setnchannels(pcm.shape[1])
        f.setsampwidth(2)
        f.setframerate(int(sample_rate))
        f.writeframes(pcm.tobytes())
