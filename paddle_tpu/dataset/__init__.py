"""paddle.dataset parity (≙ python/paddle/dataset/): legacy reader-factory
datasets. Each submodule exposes train()/test() reader creators compatible
with paddle.batch / paddle.reader decorators, backed by the vision dataset
readers (local files only — zero-egress build)."""
from . import mnist  # noqa: F401
from . import cifar  # noqa: F401
from . import uci_housing  # noqa: F401

__all__ = ['mnist', 'cifar', 'uci_housing']
