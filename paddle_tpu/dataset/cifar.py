"""paddle.dataset.cifar parity (≙ python/paddle/dataset/cifar.py): reader
creators over a local cifar python tarball/dir."""
from __future__ import annotations

__all__ = ['train10', 'test10', 'train100', 'test100']


def _reader(data_path, mode, n_classes):
    from ..vision.datasets import Cifar10, Cifar100

    cls = Cifar10 if n_classes == 10 else Cifar100
    ds = cls(data_file=data_path, mode=mode)

    def reader():
        for i in range(len(ds)):
            img, label = ds[i]
            yield img.reshape(-1).astype("float32") / 255.0, label

    return reader


def train10(data_path=None):
    return _reader(data_path, "train", 10)


def test10(data_path=None):
    return _reader(data_path, "test", 10)


def train100(data_path=None):
    return _reader(data_path, "train", 100)


def test100(data_path=None):
    return _reader(data_path, "test", 100)
