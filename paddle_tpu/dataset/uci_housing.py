"""paddle.dataset.uci_housing parity (≙ python/paddle/dataset/uci_housing.py):
reader creators over a local housing.data file (13 features + target,
whitespace-separated UCI format), feature-normalized like the reference."""
from __future__ import annotations

import numpy as np

__all__ = ['train', 'test']

_TRAIN_RATIO = 0.8


def _load(path):
    data = np.loadtxt(path)
    if data.ndim != 2 or data.shape[1] != 14:
        raise ValueError(
            f"uci_housing: expected Nx14 whitespace table, got {data.shape}")
    feats = data[:, :-1]
    mx, mn, avg = feats.max(0), feats.min(0), feats.mean(0)
    feats = (feats - avg) / (mx - mn)
    data = np.concatenate([feats, data[:, -1:]], axis=1).astype("float32")
    split = int(len(data) * _TRAIN_RATIO)
    return data[:split], data[split:]


def train(data_path=None):
    if data_path is None:
        raise ValueError("uci_housing.train: data_path to housing.data is "
                         "required (no-network environment)")
    tr, _ = _load(data_path)

    def reader():
        for row in tr:
            yield row[:-1], row[-1:]

    return reader


def test(data_path=None):
    if data_path is None:
        raise ValueError("uci_housing.test: data_path to housing.data is "
                         "required (no-network environment)")
    _, te = _load(data_path)

    def reader():
        for row in te:
            yield row[:-1], row[-1:]

    return reader
