"""paddle.dataset.mnist parity (≙ python/paddle/dataset/mnist.py): reader
creators over local IDX files."""
from __future__ import annotations

__all__ = ['train', 'test']


def _reader(image_path, label_path):
    from ..vision.datasets import MNIST

    ds = MNIST(image_path=image_path, label_path=label_path)

    def reader():
        for i in range(len(ds)):
            img, label = ds[i]
            yield img.reshape(-1).astype("float32") / 255.0 * 2.0 - 1.0, label

    return reader


def train(image_path=None, label_path=None):
    """Reader creator for the training split: yields (784-float vector in
    [-1,1], int label). Local IDX file paths are required."""
    return _reader(image_path, label_path)


def test(image_path=None, label_path=None):
    """Reader creator for the test split."""
    return _reader(image_path, label_path)
