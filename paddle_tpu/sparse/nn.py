"""paddle.sparse.nn — activations/layers over sparse tensors (subset)."""
from __future__ import annotations

from ..nn.layer_base import Layer


class ReLU(Layer):
    def forward(self, x):
        from . import relu

        return relu(x)


class Softmax(Layer):
    """Row-wise softmax over CSR/COO values (≙ sparse.nn.Softmax)."""

    def __init__(self, axis=-1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        from . import to_dense, to_sparse_coo
        import paddle_tpu.nn.functional as F

        dense = to_dense(x)
        # -inf at structural zeros so they stay zero probability
        import jax.numpy as jnp

        from ..core.dispatch import op_call

        mask = op_call(lambda d: (d != 0).astype(d.dtype), dense, name="nonzero_mask")
        out = F.softmax(
            op_call(lambda d, m: jnp.where(m > 0, d, -jnp.inf), dense, mask,
                    name="mask_fill"), axis=self.axis)
        out = op_call(lambda o, m: jnp.where(m > 0, o, 0.0), out, mask,
                      name="mask_zero")
        return to_sparse_coo(out)
