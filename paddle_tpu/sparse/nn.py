"""paddle.sparse.nn — activations/layers over sparse tensors (subset)."""
from __future__ import annotations

from ..nn.layer_base import Layer


class ReLU(Layer):
    def forward(self, x):
        from . import relu

        return relu(x)


class Softmax(Layer):
    """Row-wise softmax over CSR/COO values (≙ sparse.nn.Softmax)."""

    def __init__(self, axis=-1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        from . import to_dense, to_sparse_coo
        import paddle_tpu.nn.functional as F

        dense = to_dense(x)
        # -inf at structural zeros so they stay zero probability
        import jax.numpy as jnp

        from ..core.dispatch import op_call

        mask = op_call(lambda d: (d != 0).astype(d.dtype), dense, name="nonzero_mask")
        out = F.softmax(
            op_call(lambda d, m: jnp.where(m > 0, d, -jnp.inf), dense, mask,
                    name="mask_fill"), axis=self.axis)
        out = op_call(lambda o, m: jnp.where(m > 0, o, 0.0), out, mask,
                      name="mask_zero")
        return to_sparse_coo(out)


# ---------------------------------------------------------------- functional
class _Functional:
    """paddle.sparse.nn.functional — conv/pool entry points (module-like)."""


def _install_functional():
    import types

    from . import conv as _conv

    functional = types.ModuleType("paddle_tpu.sparse.nn.functional")
    for name in ("conv2d", "conv3d", "subm_conv2d", "subm_conv3d",
                 "max_pool3d", "avg_pool3d"):
        setattr(functional, name, getattr(_conv, name))

    def relu(x, name=None):  # late: sparse/__init__ may still be loading
        from . import relu as _relu

        return _relu(x, name)

    functional.relu = relu
    import sys

    sys.modules["paddle_tpu.sparse.nn.functional"] = functional
    return functional


functional = _install_functional()


class _SparseConvBase(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, subm=False, dims=3,
                 bias_attr=None, data_format=None):
        super().__init__()
        from .conv import _tuplize

        self._dims = dims
        self._subm = subm
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        k = _tuplize(kernel_size, dims)
        import numpy as np

        from ..core.tensor import Parameter

        fan_in = in_channels * int(np.prod(k))
        bound = 1.0 / np.sqrt(fan_in)
        rs = np.random
        self.weight = Parameter(
            (rs.uniform(-bound, bound,
                        k + (in_channels, out_channels))).astype("float32"))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = Parameter(
                rs.uniform(-bound, bound, (out_channels,)).astype("float32"))

    def forward(self, x):
        from .conv import _conv_impl

        name = ("sparse_subm_conv" if self._subm else "sparse_conv") + \
            f"{self._dims}d"
        return _conv_impl(x, self.weight, self.bias, self._stride,
                          self._padding, self._dilation, self._subm,
                          self._dims, name)


class Conv3D(_SparseConvBase):
    """≙ paddle.sparse.nn.Conv3D (phi sparse conv3d, NDHWC)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, subm=False, dims=3,
                         bias_attr=bias_attr)


class SubmConv3D(_SparseConvBase):
    """≙ paddle.sparse.nn.SubmConv3D — output sites == input sites."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 key=None, weight_attr=None, bias_attr=None,
                 data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, subm=True, dims=3,
                         bias_attr=bias_attr)


class Conv2D(_SparseConvBase):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, subm=False, dims=2,
                         bias_attr=bias_attr)


class SubmConv2D(_SparseConvBase):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 key=None, weight_attr=None, bias_attr=None,
                 data_format="NHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, subm=True, dims=2,
                         bias_attr=bias_attr)


class MaxPool3D(Layer):
    """≙ paddle.sparse.nn.MaxPool3D over active sites."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NDHWC", name=None):
        super().__init__()
        self._k = kernel_size
        self._s = stride
        self._p = padding

    def forward(self, x):
        from .conv import max_pool3d

        return max_pool3d(x, self._k, self._s, self._p)
