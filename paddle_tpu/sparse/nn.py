"""paddle.sparse.nn — activations/layers over sparse tensors (subset)."""
from __future__ import annotations

from ..nn.layer_base import Layer


class ReLU(Layer):
    def forward(self, x):
        from . import relu

        return relu(x)


class Softmax(Layer):
    """Row-wise softmax over CSR/COO values (≙ sparse.nn.Softmax)."""

    def __init__(self, axis=-1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        from . import to_dense, to_sparse_coo
        import paddle_tpu.nn.functional as F

        dense = to_dense(x)
        # -inf at structural zeros so they stay zero probability
        import jax.numpy as jnp

        from ..core.dispatch import op_call

        mask = op_call(lambda d: (d != 0).astype(d.dtype), dense, name="nonzero_mask")
        out = F.softmax(
            op_call(lambda d, m: jnp.where(m > 0, d, -jnp.inf), dense, mask,
                    name="mask_fill"), axis=self.axis)
        out = op_call(lambda o, m: jnp.where(m > 0, o, 0.0), out, mask,
                      name="mask_zero")
        return to_sparse_coo(out)


# ---------------------------------------------------------------- functional
class _Functional:
    """paddle.sparse.nn.functional — conv/pool entry points (module-like)."""


def _install_functional():
    import types

    from . import conv as _conv

    functional = types.ModuleType("paddle_tpu.sparse.nn.functional")
    for name in ("conv2d", "conv3d", "subm_conv2d", "subm_conv3d",
                 "max_pool3d", "avg_pool3d"):
        setattr(functional, name, getattr(_conv, name))

    def relu(x, name=None):  # late: sparse/__init__ may still be loading
        from . import relu as _relu

        return _relu(x, name)

    functional.relu = relu
    import sys

    sys.modules["paddle_tpu.sparse.nn.functional"] = functional
    return functional


functional = _install_functional()


class _SparseConvBase(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, subm=False, dims=3,
                 bias_attr=None, data_format=None):
        super().__init__()
        from .conv import _tuplize

        self._dims = dims
        self._subm = subm
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        k = _tuplize(kernel_size, dims)
        import numpy as np

        from ..core.tensor import Parameter

        fan_in = in_channels * int(np.prod(k))
        bound = 1.0 / np.sqrt(fan_in)
        rs = np.random
        self.weight = Parameter(
            (rs.uniform(-bound, bound,
                        k + (in_channels, out_channels))).astype("float32"))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = Parameter(
                rs.uniform(-bound, bound, (out_channels,)).astype("float32"))

    def forward(self, x):
        from .conv import _conv_impl

        name = ("sparse_subm_conv" if self._subm else "sparse_conv") + \
            f"{self._dims}d"
        return _conv_impl(x, self.weight, self.bias, self._stride,
                          self._padding, self._dilation, self._subm,
                          self._dims, name)


class Conv3D(_SparseConvBase):
    """≙ paddle.sparse.nn.Conv3D (phi sparse conv3d, NDHWC)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, subm=False, dims=3,
                         bias_attr=bias_attr)


class SubmConv3D(_SparseConvBase):
    """≙ paddle.sparse.nn.SubmConv3D — output sites == input sites."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 key=None, weight_attr=None, bias_attr=None,
                 data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, subm=True, dims=3,
                         bias_attr=bias_attr)


class Conv2D(_SparseConvBase):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, subm=False, dims=2,
                         bias_attr=bias_attr)


class SubmConv2D(_SparseConvBase):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 key=None, weight_attr=None, bias_attr=None,
                 data_format="NHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, subm=True, dims=2,
                         bias_attr=bias_attr)


class MaxPool3D(Layer):
    """≙ paddle.sparse.nn.MaxPool3D over active sites."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NDHWC", name=None):
        super().__init__()
        self._k = kernel_size
        self._s = stride
        self._p = padding

    def forward(self, x):
        from .conv import max_pool3d

        return max_pool3d(x, self._k, self._s, self._p)


class BatchNorm(Layer):
    """Batch normalization over the VALUES of a channel-last SparseCooTensor
    (≙ /root/reference/python/paddle/sparse/nn/layer/norm.py:35, which
    reuses BatchNorm1D on the nnz-values view). Statistics are computed per
    channel over the nonzero entries only; indices pass through unchanged."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        if data_format not in ("NDHWC", "NHWC"):
            raise ValueError(
                "sparse BatchNorm only supports channel-last layouts "
                f"(NDHWC/NHWC), got {data_format}")
        from ..nn import BatchNorm1D

        self._bn = BatchNorm1D(num_features, momentum=momentum,
                               epsilon=epsilon, weight_attr=weight_attr,
                               bias_attr=bias_attr,
                               use_global_stats=use_global_stats)

    def forward(self, x):
        from . import _build, _check_sparse

        _check_sparse(x)
        vals = x._spvals
        if vals.ndim >= 2:
            # hybrid layout: values already [nnz, C] — the reference's
            # exact values-view BN
            out_vals = self._bn(vals)
        else:
            # all-sparse COO: group values by their channel coordinate
            # (last index dim) and normalize per channel over that
            # channel's nonzeros — the values-view semantics generalized
            out_vals = self._bn_by_channel(vals, x._spidx)
        out = _build(out_vals, x._spidx, x._spshape)
        if getattr(x, "_csr", None) is not None:
            out._csr = x._csr
        return out

    def _bn_by_channel(self, vals, spidx):
        import jax.numpy as jnp
        import numpy as np

        from paddle_tpu.core.dispatch import no_grad, op_call
        from paddle_tpu.core.tensor import Tensor

        bn = self._bn
        c = bn._num_features
        ch = np.asarray(spidx[:, -1]).astype(np.int64)
        ch_t = Tensor(jnp.asarray(ch), _internal=True, stop_gradient=True)
        training = self.training and not bn._use_global_stats
        eps = bn._epsilon

        import jax

        if training:
            def f(v, chv, w, b):
                cnt = jnp.maximum(
                    jax.ops.segment_sum(jnp.ones_like(v), chv, c), 1.0)
                m = jax.ops.segment_sum(v, chv, c) / cnt
                var = jax.ops.segment_sum(jnp.square(v), chv, c) / cnt \
                    - jnp.square(m)
                out = (v - m[chv]) * jax.lax.rsqrt(var[chv] + eps)
                return out * w[chv] + b[chv], m, var

            out, m, var = op_call(f, vals, ch_t, bn.weight, bn.bias,
                                  name="sparse_batch_norm")
            with no_grad():
                mom = bn._momentum
                bn._mean._assign_raw(bn._mean._data * mom
                                     + m._data * (1 - mom))
                bn._variance._assign_raw(bn._variance._data * mom
                                         + var._data * (1 - mom))
            return out

        def f(v, chv, rm, rv, w, b):
            out = (v - rm[chv]) * jax.lax.rsqrt(rv[chv] + eps)
            return out * w[chv] + b[chv]

        return op_call(f, vals, ch_t, bn._mean, bn._variance, bn.weight,
                       bn.bias, name="sparse_batch_norm_eval")


class SyncBatchNorm(BatchNorm):
    """≙ sparse.nn.SyncBatchNorm: under the single-controller mesh design
    batch statistics are computed over the global (replicated or sharded)
    values view, so the dense SyncBatchNorm semantics carry over."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, BatchNorm) and not isinstance(layer,
                                                           SyncBatchNorm):
            new = SyncBatchNorm(layer._bn._num_features)
            new._bn = layer._bn
            return new
        for name, sub in getattr(layer, "_sub_layers", {}).items():
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


def _sparse_attention(query, key, value, sparse_mask, key_padding_mask=None,
                      attn_mask=None, name=None):
    """softmax(QK^T/sqrt(d) masked to sparse_mask's CSR pattern) V
    (≙ sparse/nn/functional/transformer.py attention). q/k/v dense
    [B, H, S, D]; sparse_mask CSR with dense shape [B*H, S, S] (or one
    shared [S, S] pattern)."""
    import numpy as np

    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.nn.functional.extended import sparse_attention as _sa
    import jax.numpy as jnp

    b, h, s, _ = (int(v) for v in query.shape)
    csr = getattr(sparse_mask, "_csr", None)
    if csr is None:
        raise TypeError("sparse_mask must be a SparseCsrTensor")
    crows, cols = csr
    crows = np.asarray(crows)
    cols = np.asarray(cols)
    if crows.ndim == 1 and crows.shape[0] == s + 1:
        offs = np.broadcast_to(crows, (b, h, s + 1))
        colm = np.broadcast_to(cols, (b, h, cols.shape[0]))
    else:
        offs = crows.reshape(b, h, s + 1)
        colm = cols.reshape(b, h, -1)
    return _sa(query, key, value,
               Tensor(jnp.asarray(offs), _internal=True, stop_gradient=True),
               Tensor(jnp.asarray(colm), _internal=True, stop_gradient=True),
               key_padding_mask, attn_mask)


functional.attention = _sparse_attention
