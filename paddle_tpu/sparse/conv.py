"""Sparse convolution / pooling on COO site lists.

Reference parity: paddle.sparse.nn.functional conv3d/subm_conv3d/conv2d +
max_pool3d over SparseCooTensor (phi sparse kernels,
/root/reference/paddle/phi/kernels/sparse/conv_kernel.h,
gpu/conv_kernel.cu; layout NDHWC, weight [*k, C_in, C_out]).

TPU-native design: the reference builds a "rulebook" (offset -> (in site,
out site) pairs) on GPU; here the rulebook is built host-side from the
concrete COO indices (numpy dict over coordinates), then the compute is ONE
jitted program with static shapes: for each kernel offset (static unroll,
<=27 for 3^3) gather the matching input rows, mask invalid, matmul with
that offset's weight slice, accumulate. Grads flow through values and
weight via the ordinary tape; XLA fuses the per-offset chain.
"""
from __future__ import annotations

import itertools

import jax.numpy as jnp
import numpy as np

from ..core.dispatch import op_call
from ..core.tensor import Tensor


def _tuplize(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


def _rulebook(coords, shape, ksize, stride, padding, dilation, subm):
    """Host-side site matching. coords: [nnz, 1+dims] (batch + spatial).
    Returns (out_coords [n_out, 1+dims], src [n_off, n_out] input row or -1,
    out_spatial_shape)."""
    dims = len(ksize)
    spatial = [int(s) for s in shape[1:1 + dims]]
    out_sp = [(spatial[i] + 2 * padding[i]
               - dilation[i] * (ksize[i] - 1) - 1) // stride[i] + 1
              for i in range(dims)]
    offsets = list(itertools.product(*[range(k) for k in ksize]))
    site = {tuple(c): i for i, c in enumerate(map(tuple, coords))}

    if subm:
        out_coords = coords
    else:
        outs = set()
        for c in coords:
            b, pos = int(c[0]), c[1:]
            for off in offsets:
                num = [pos[i] + padding[i] - dilation[i] * off[i]
                       for i in range(dims)]
                if all(n % stride[i] == 0 and
                       0 <= n // stride[i] < out_sp[i]
                       for i, n in enumerate(num)):
                    outs.add((b,) + tuple(n // stride[i]
                                          for i, n in enumerate(num)))
        out_coords = np.array(sorted(outs), dtype=np.int64).reshape(
            len(outs), 1 + dims)

    n_out = len(out_coords)
    src = np.full((len(offsets), n_out), -1, dtype=np.int64)
    for oi, o in enumerate(out_coords):
        b, pos = int(o[0]), o[1:]
        for ki, off in enumerate(offsets):
            inp = tuple(pos[i] * stride[i] - padding[i] + dilation[i] * off[i]
                        for i in range(dims))
            if all(0 <= inp[i] < spatial[i] for i in range(dims)):
                j = site.get((b,) + inp)
                if j is not None:
                    src[ki, oi] = j
    return out_coords, src, out_sp


def _conv_impl(x, weight, bias, stride, padding, dilation, subm, dims,
               name):
    from . import _build

    vals = x._spvals                       # [nnz, C_in] Tensor
    coords = np.asarray(x._spidx)
    shape = x._spshape                     # (N, *spatial, C_in)
    wshape = list(weight.shape)            # [*k, C_in, C_out]
    ksize = tuple(int(k) for k in wshape[:dims])
    cin, cout = int(wshape[dims]), int(wshape[dims + 1])
    stride = _tuplize(stride, dims)
    padding = _tuplize(padding, dims)
    dilation = _tuplize(dilation, dims)
    if subm and (any(s != 1 for s in stride) or
                 any(k % 2 == 0 for k in ksize)):
        raise ValueError("submanifold conv needs stride 1 and odd kernels")

    out_coords, src, out_sp = _rulebook(coords, shape, ksize, stride,
                                        padding, dilation, subm)
    n_off = src.shape[0]
    nnz = max(int(vals.shape[0]), 1)

    def fn(v, w, *rest):
        # NOTE: only ints/bools may be closed over — an ndarray in the
        # closure would make the op key uncachable (dispatch._fn_key)
        srcs = rest[-1]
        b = rest[0] if bias is not None else None
        wf = w.reshape((n_off, cin, cout))
        out = jnp.zeros((srcs.shape[1], cout), v.dtype)
        for k in range(n_off):     # static unroll over kernel offsets
            idx = srcs[k]
            g = v[jnp.clip(idx, 0, nnz - 1)]
            g = jnp.where((idx >= 0)[:, None], g, 0)
            out = out + g.astype(v.dtype) @ wf[k].astype(v.dtype)
        if b is not None:
            out = out + b
        return out

    args = [vals, weight] + ([bias] if bias is not None else []) + \
        [src.astype(np.int32)]
    out_vals = op_call(fn, *args, name=name, n_diff=3 if bias is not None
                       else 2)
    out_shape = (shape[0],) + tuple(out_sp) + (cout,)
    return _build(out_vals, out_coords, out_shape)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NDHWC", name=None):
    """Sparse conv3d (≙ sparse conv3d, phi sparse/conv_kernel.h). Output
    sites = all positions reached by any input site."""
    if groups != 1:
        raise NotImplementedError("sparse conv groups > 1")
    return _conv_impl(x, weight, bias, stride, padding, dilation, False, 3,
                      "sparse_conv3d")


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    """Submanifold conv3d: output sites == input sites (point clouds keep
    their sparsity pattern; ≙ sparse subm_conv3d)."""
    if groups != 1:
        raise NotImplementedError("sparse conv groups > 1")
    return _conv_impl(x, weight, bias, stride, padding, dilation, True, 3,
                      "sparse_subm_conv3d")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NHWC", name=None):
    if groups != 1:
        raise NotImplementedError("sparse conv groups > 1")
    return _conv_impl(x, weight, bias, stride, padding, dilation, False, 2,
                      "sparse_conv2d")


def subm_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NHWC", key=None, name=None):
    if groups != 1:
        raise NotImplementedError("sparse conv groups > 1")
    return _conv_impl(x, weight, bias, stride, padding, dilation, True, 2,
                      "sparse_subm_conv2d")


def _pool_impl(x, ksize, stride, padding, dims, mode, name):
    from . import _build

    vals = x._spvals
    coords = np.asarray(x._spidx)
    shape = x._spshape
    ksize = _tuplize(ksize, dims)
    stride = _tuplize(stride if stride is not None else ksize, dims)
    padding = _tuplize(padding, dims)
    out_coords, src, out_sp = _rulebook(coords, shape, ksize, stride,
                                        padding, (1,) * dims, False)
    nnz = max(int(vals.shape[0]), 1)
    n_off = src.shape[0]

    def fn(v, srcs):
        neg = jnp.asarray(-np.inf, v.dtype) if mode == "max" else 0.0
        acc = jnp.full((srcs.shape[1], v.shape[-1]), neg, v.dtype) \
            if mode == "max" else jnp.zeros((srcs.shape[1], v.shape[-1]),
                                            v.dtype)
        cnt = jnp.zeros((srcs.shape[1], 1), v.dtype)
        for k in range(n_off):
            idx = srcs[k]
            g = v[jnp.clip(idx, 0, nnz - 1)]
            valid = (idx >= 0)[:, None]
            if mode == "max":
                acc = jnp.maximum(acc, jnp.where(valid, g, neg))
            else:
                acc = acc + jnp.where(valid, g, 0)
                cnt = cnt + valid.astype(v.dtype)
        if mode == "max":
            return acc
        return acc / jnp.maximum(cnt, 1)

    out_vals = op_call(fn, vals, src.astype(np.int32), name=name, n_diff=1)
    out_shape = (shape[0],) + tuple(out_sp) + (int(vals.shape[-1]),)
    return _build(out_vals, out_coords, out_shape)


def max_pool3d(x, kernel_size, stride=None, padding=0,
               data_format="NDHWC", name=None):
    """Sparse max pooling (≙ sparse max_pool3d, phi sparse/pool_kernel.h);
    max over the ACTIVE sites in each window."""
    return _pool_impl(x, kernel_size, stride, padding, 3, "max",
                      "sparse_max_pool3d")


def avg_pool3d(x, kernel_size, stride=None, padding=0,
               data_format="NDHWC", name=None):
    """Average over the active sites in each window (paddle sparse
    semantics: divisor = active count, not window volume)."""
    return _pool_impl(x, kernel_size, stride, padding, 3, "avg",
                      "sparse_avg_pool3d")
