"""paddle.sparse — COO/CSR sparse tensors.

Reference parity: python/paddle/sparse (SparseCooTensor/SparseCsrTensor
API over paddle/phi/kernels/sparse/, ~21k LoC of CUDA). TPU-native: the
storage/compute engine is jax.experimental.sparse (BCOO) — XLA lowers
sparse ops to gather/scatter/segment-sum; dense bridging via todense().

Autograd: a sparse Tensor carries its VALUES as a real framework Tensor
(`._spvals`), and every sparse op dispatches on it through op_call — so
gradients flow back to the values the user built the tensor from, exactly
like the reference's differentiable sparse kernels.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..core.dispatch import op_call
from ..core.tensor import Tensor
from . import nn  # noqa: F401  (re-export subpackage)

__all__ = [
    "sparse_coo_tensor", "sparse_csr_tensor", "is_sparse", "is_sparse_coo",
    "is_sparse_csr", "matmul", "masked_matmul", "add", "multiply", "subtract",
    "relu", "abs", "sin", "tanh", "sqrt", "pow", "neg", "cast", "transpose",
    "nn",
]


def _data_of(x):
    return x._data if isinstance(x, Tensor) else x


def _build(values: Tensor, indices, shape) -> Tensor:
    """Assemble a sparse Tensor around a values Tensor (graph-preserving)."""
    t = Tensor(jnp.zeros((), values._data.dtype), _internal=True,
               stop_gradient=values.stop_gradient)
    t._spvals = values
    t._spidx = jnp.asarray(indices)  # [nnz, ndim]
    t._spshape = tuple(int(s) for s in shape)
    return t


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    """Build a COO sparse Tensor: indices [ndim, nnz], values [nnz, ...]."""
    idx = np.asarray(_data_of(indices))
    if isinstance(values, Tensor):
        vt = values
    else:
        vt = Tensor(jnp.asarray(values), _internal=True,
                    stop_gradient=stop_gradient)
    if dtype is not None:
        from ..core import dtype as dtypes

        vt = vt.astype(dtypes.convert_dtype(dtype))
    if shape is None:
        shape = tuple(int(i) + 1 for i in idx.max(axis=1))
    vt.stop_gradient = stop_gradient and vt.stop_gradient
    return _build(vt, idx.T, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      stop_gradient=True):
    """CSR: stored as COO internally (XLA has one sparse lowering path);
    crows/cols layout is preserved for round-tripping."""
    crows = np.asarray(_data_of(crows)).astype(np.int32)
    cols = np.asarray(_data_of(cols)).astype(np.int32)
    rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
    t = sparse_coo_tensor(np.stack([rows, cols]), values, shape, dtype,
                          stop_gradient=stop_gradient)
    t._csr = (crows, cols)
    return t


def is_sparse(x) -> bool:
    return getattr(x, "_spvals", None) is not None


def is_sparse_coo(x) -> bool:
    return is_sparse(x) and getattr(x, "_csr", None) is None


def is_sparse_csr(x) -> bool:
    return is_sparse(x) and getattr(x, "_csr", None) is not None


def _check_sparse(x):
    if not is_sparse(x):
        raise TypeError("expected a sparse Tensor")
    return x


def _bcoo(x) -> jsparse.BCOO:
    _check_sparse(x)
    return jsparse.BCOO((x._spvals._data, x._spidx), shape=x._spshape)


# --------------------------------------------------------------- conversions
def to_dense(x) -> Tensor:
    _check_sparse(x)
    shape = x._spshape

    def fn(vals, idx):
        return jsparse.BCOO((vals, idx), shape=shape).todense()

    # idx rides as an operand (closure arrays would defeat the eager cache)
    return op_call(fn, x._spvals, x._spidx, name="coo_to_dense", n_diff=1)


def to_sparse_coo(x, sparse_dim=None) -> Tensor:
    """Dense -> COO. The value gather is dispatched, so gradients flow back
    into the dense source."""
    arr = _data_of(x)
    snapshot = np.asarray(jax.device_get(arr))
    idx = np.argwhere(snapshot != 0)
    gather = tuple(jnp.asarray(idx[:, d]) for d in range(idx.shape[1]))
    xt = x if isinstance(x, Tensor) else Tensor(jnp.asarray(arr), _internal=True)
    vals = op_call(lambda d: d[gather], xt, name="coo_gather_values")
    return _build(vals, idx, snapshot.shape)


# --------------------------------------------------------------- compute
def matmul(x, y, name=None) -> Tensor:
    """sparse @ dense -> dense (the training hot path)."""
    _check_sparse(x)
    idx, shape = x._spidx, x._spshape
    yt = y if isinstance(y, Tensor) else Tensor(jnp.asarray(y), _internal=True)

    def fn(vals, dense, idxv):
        return jsparse.BCOO((vals, idxv), shape=shape) @ dense

    return op_call(fn, x._spvals, yt, idx, name="sparse_matmul", n_diff=2)


def masked_matmul(x, y, mask, name=None) -> Tensor:
    """dense @ dense, output only at mask's nonzero positions (SDDMM)."""
    _check_sparse(mask)
    idx, shape = mask._spidx, mask._spshape
    xt = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x), _internal=True)
    yt = y if isinstance(y, Tensor) else Tensor(jnp.asarray(y), _internal=True)
    def fn(a, b, rows, cols):
        return (a[rows] * b[:, cols].T).sum(-1)

    vals = op_call(fn, xt, yt, jnp.asarray(idx[:, 0]), jnp.asarray(idx[:, 1]),
                   name="masked_matmul", n_diff=2)
    return _build(vals, idx, shape)


def _ewise(x, y, jnp_fn, name):
    """Elementwise over (possibly different) patterns via dense align; the
    whole chain is dispatched so both inputs receive gradients."""
    da, db = to_dense(x), to_dense(y)
    dense = op_call(jnp_fn, da, db, name=name)
    return to_sparse_coo(dense)


def add(x, y, name=None):
    return _ewise(x, y, jnp.add, "sparse_add")


def subtract(x, y, name=None):
    return _ewise(x, y, jnp.subtract, "sparse_subtract")


def multiply(x, y, name=None):
    return _ewise(x, y, jnp.multiply, "sparse_multiply")


def _unary(x, jnp_fn, name):
    _check_sparse(x)
    vals = op_call(jnp_fn, x._spvals, name=name)
    return _build(vals, x._spidx, x._spshape)


def relu(x, name=None):
    return _unary(x, lambda v: jnp.maximum(v, 0), "sparse_relu")


def abs(x, name=None):
    return _unary(x, jnp.abs, "sparse_abs")


def sin(x, name=None):
    return _unary(x, jnp.sin, "sparse_sin")


def tanh(x, name=None):
    return _unary(x, jnp.tanh, "sparse_tanh")


def sqrt(x, name=None):
    return _unary(x, jnp.sqrt, "sparse_sqrt")


def pow(x, factor, name=None):
    return _unary(x, lambda v: jnp.power(v, factor), "sparse_pow")


def neg(x, name=None):
    return _unary(x, jnp.negative, "sparse_neg")


def cast(x, index_dtype=None, value_dtype=None, name=None):
    from ..core import dtype as dtypes

    if value_dtype is None:
        return x
    return _unary(x, lambda v: v.astype(dtypes.convert_dtype(value_dtype)),
                  "sparse_cast")


def transpose(x, perm, name=None):
    _check_sparse(x)
    idx = np.asarray(x._spidx)[:, list(perm)]
    shape = tuple(x._spshape[p] for p in perm)
    return _build(x._spvals, idx, shape)


# Tensor methods (paddle exposes these on Tensor directly)
def _install_tensor_methods():
    Tensor.to_dense = lambda self: to_dense(self) if is_sparse(self) else self
    Tensor.to_sparse_coo = lambda self, sparse_dim=None: to_sparse_coo(self, sparse_dim)
    Tensor.is_sparse = lambda self: is_sparse(self)
    Tensor.is_sparse_coo = lambda self: is_sparse_coo(self)
    Tensor.is_sparse_csr = lambda self: is_sparse_csr(self)

    def _values(self):
        return _check_sparse(self)._spvals

    def _indices(self):
        return Tensor(_check_sparse(self)._spidx.T, _internal=True)

    Tensor.values = _values
    Tensor.indices = _indices
    Tensor.nnz = lambda self: int(_check_sparse(self)._spidx.shape[0])


_install_tensor_methods()
