"""paddle.sparse — COO/CSR sparse tensors.

Reference parity: python/paddle/sparse (SparseCooTensor/SparseCsrTensor
API over paddle/phi/kernels/sparse/, ~21k LoC of CUDA). TPU-native: the
storage/compute engine is jax.experimental.sparse (BCOO) — XLA lowers
sparse ops to gather/scatter/segment-sum; dense bridging via todense().

Autograd: a sparse Tensor carries its VALUES as a real framework Tensor
(`._spvals`), and every sparse op dispatches on it through op_call — so
gradients flow back to the values the user built the tensor from, exactly
like the reference's differentiable sparse kernels.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..core.dispatch import op_call
from ..core.tensor import Tensor
from . import nn  # noqa: F401  (re-export subpackage)

__all__ = [
    "sparse_coo_tensor", "sparse_csr_tensor", "is_sparse", "is_sparse_coo",
    "is_sparse_csr", "matmul", "masked_matmul", "add", "multiply", "subtract",
    "relu", "abs", "sin", "tanh", "sqrt", "pow", "neg", "cast", "transpose",
    "nn",
]

# sparse conv/pool entry points also surface at paddle.sparse level


def _data_of(x):
    return x._data if isinstance(x, Tensor) else x


def _build(values: Tensor, indices, shape) -> Tensor:
    """Assemble a sparse Tensor around a values Tensor (graph-preserving)."""
    t = Tensor(jnp.zeros((), values._data.dtype), _internal=True,
               stop_gradient=values.stop_gradient)
    t._spvals = values
    t._spidx = jnp.asarray(indices)  # [nnz, ndim]
    t._spshape = tuple(int(s) for s in shape)
    return t


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    """Build a COO sparse Tensor: indices [ndim, nnz], values [nnz, ...]."""
    idx = np.asarray(_data_of(indices))
    if isinstance(values, Tensor):
        vt = values
    else:
        vt = Tensor(jnp.asarray(values), _internal=True,
                    stop_gradient=stop_gradient)
    if dtype is not None:
        from ..core import dtype as dtypes

        vt = vt.astype(dtypes.convert_dtype(dtype))
    if shape is None:
        shape = tuple(int(i) + 1 for i in idx.max(axis=1))
    vt.stop_gradient = stop_gradient and vt.stop_gradient
    return _build(vt, idx.T, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      stop_gradient=True):
    """CSR: stored as COO internally (XLA has one sparse lowering path);
    crows/cols layout is preserved for round-tripping."""
    crows = np.asarray(_data_of(crows)).astype(np.int32)
    cols = np.asarray(_data_of(cols)).astype(np.int32)
    rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
    t = sparse_coo_tensor(np.stack([rows, cols]), values, shape, dtype,
                          stop_gradient=stop_gradient)
    t._csr = (crows, cols)
    return t


def is_sparse(x) -> bool:
    return getattr(x, "_spvals", None) is not None


def is_sparse_coo(x) -> bool:
    return is_sparse(x) and getattr(x, "_csr", None) is None


def is_sparse_csr(x) -> bool:
    return is_sparse(x) and getattr(x, "_csr", None) is not None


def _check_sparse(x):
    if not is_sparse(x):
        raise TypeError("expected a sparse Tensor")
    return x


def _bcoo(x) -> jsparse.BCOO:
    _check_sparse(x)
    return jsparse.BCOO((x._spvals._data, x._spidx), shape=x._spshape)


# --------------------------------------------------------------- conversions
def to_dense(x) -> Tensor:
    _check_sparse(x)
    shape = x._spshape

    def fn(vals, idx):
        return jsparse.BCOO((vals, idx), shape=shape).todense()

    # idx rides as an operand (closure arrays would defeat the eager cache)
    return op_call(fn, x._spvals, x._spidx, name="coo_to_dense", n_diff=1)


def to_sparse_coo(x, sparse_dim=None) -> Tensor:
    """Dense -> COO. The value gather is dispatched, so gradients flow back
    into the dense source."""
    arr = _data_of(x)
    if sparse_dim is not None and int(sparse_dim) != len(arr.shape):
        raise NotImplementedError(
            "to_sparse_coo: hybrid tensors (sparse_dim < ndim, dense value "
            "blocks) are not supported; all dims are sparse")
    snapshot = np.asarray(jax.device_get(arr))
    idx = np.argwhere(snapshot != 0)
    gather = tuple(jnp.asarray(idx[:, d]) for d in range(idx.shape[1]))
    xt = x if isinstance(x, Tensor) else Tensor(jnp.asarray(arr), _internal=True)
    vals = op_call(lambda d: d[gather], xt, name="coo_gather_values")
    return _build(vals, idx, snapshot.shape)


# --------------------------------------------------------------- compute
def matmul(x, y, name=None) -> Tensor:
    """sparse @ dense -> dense (the training hot path)."""
    _check_sparse(x)
    idx, shape = x._spidx, x._spshape
    yt = y if isinstance(y, Tensor) else Tensor(jnp.asarray(y), _internal=True)

    def fn(vals, dense, idxv):
        return jsparse.BCOO((vals, idxv), shape=shape) @ dense

    return op_call(fn, x._spvals, yt, idx, name="sparse_matmul", n_diff=2)


def masked_matmul(x, y, mask, name=None) -> Tensor:
    """dense @ dense, output only at mask's nonzero positions (SDDMM)."""
    _check_sparse(mask)
    idx, shape = mask._spidx, mask._spshape
    xt = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x), _internal=True)
    yt = y if isinstance(y, Tensor) else Tensor(jnp.asarray(y), _internal=True)
    def fn(a, b, rows, cols):
        return (a[rows] * b[:, cols].T).sum(-1)

    vals = op_call(fn, xt, yt, jnp.asarray(idx[:, 0]), jnp.asarray(idx[:, 1]),
                   name="masked_matmul", n_diff=2)
    return _build(vals, idx, shape)


def _ewise(x, y, jnp_fn, name):
    """Elementwise over (possibly different) patterns via dense align; the
    whole chain is dispatched so both inputs receive gradients."""
    da, db = to_dense(x), to_dense(y)
    dense = op_call(jnp_fn, da, db, name=name)
    return to_sparse_coo(dense)


def add(x, y, name=None):
    return _ewise(x, y, jnp.add, "sparse_add")


def subtract(x, y, name=None):
    return _ewise(x, y, jnp.subtract, "sparse_subtract")


def multiply(x, y, name=None):
    return _ewise(x, y, jnp.multiply, "sparse_multiply")


def _unary(x, jnp_fn, name):
    _check_sparse(x)
    vals = op_call(jnp_fn, x._spvals, name=name)
    return _build(vals, x._spidx, x._spshape)


def relu(x, name=None):
    return _unary(x, lambda v: jnp.maximum(v, 0), "sparse_relu")


def abs(x, name=None):
    return _unary(x, jnp.abs, "sparse_abs")


def sin(x, name=None):
    return _unary(x, jnp.sin, "sparse_sin")


def tanh(x, name=None):
    return _unary(x, jnp.tanh, "sparse_tanh")


def sqrt(x, name=None):
    return _unary(x, jnp.sqrt, "sparse_sqrt")


def pow(x, factor, name=None):
    return _unary(x, lambda v: jnp.power(v, factor), "sparse_pow")


def neg(x, name=None):
    return _unary(x, jnp.negative, "sparse_neg")


def cast(x, index_dtype=None, value_dtype=None, name=None):
    from ..core import dtype as dtypes

    out = x
    if value_dtype is not None:
        out = _unary(out, lambda v: v.astype(dtypes.convert_dtype(value_dtype)),
                     "sparse_cast")
    if index_dtype is not None and getattr(out, "_spidx", None) is not None:
        if out is x:
            # cast must be pure: never mutate the input's indices
            out = _build(x._spvals, x._spidx, x._spshape)
        out._spidx = out._spidx.astype(dtypes.convert_dtype(index_dtype))
    return out


def transpose(x, perm, name=None):
    _check_sparse(x)
    idx = np.asarray(x._spidx)[:, list(perm)]
    shape = tuple(x._spshape[p] for p in perm)
    return _build(x._spvals, idx, shape)


# Tensor methods (paddle exposes these on Tensor directly)
def _install_tensor_methods():
    Tensor.to_dense = lambda self: to_dense(self) if is_sparse(self) else self
    Tensor.to_sparse_coo = lambda self, sparse_dim=None: to_sparse_coo(self, sparse_dim)
    Tensor.is_sparse = lambda self: is_sparse(self)
    Tensor.is_sparse_coo = lambda self: is_sparse_coo(self)
    Tensor.is_sparse_csr = lambda self: is_sparse_csr(self)

    def _values(self):
        return _check_sparse(self)._spvals

    def _indices(self):
        return Tensor(_check_sparse(self)._spidx.T, _internal=True)

    Tensor.values = _values
    Tensor.indices = _indices
    Tensor.nnz = lambda self: int(_check_sparse(self)._spidx.shape[0])


_install_tensor_methods()


# --------------------------------------------------------- surface completion
# (≙ python/paddle/sparse/{unary,binary,multiary}.py remaining exports)

def asin(x, name=None):
    return _unary(x, jnp.arcsin, "sparse_asin")


def asinh(x, name=None):
    return _unary(x, jnp.arcsinh, "sparse_asinh")


def atan(x, name=None):
    return _unary(x, jnp.arctan, "sparse_atan")


def atanh(x, name=None):
    return _unary(x, jnp.arctanh, "sparse_atanh")


def sinh(x, name=None):
    return _unary(x, jnp.sinh, "sparse_sinh")


def tan(x, name=None):
    return _unary(x, jnp.tan, "sparse_tan")


def square(x, name=None):
    return _unary(x, jnp.square, "sparse_square")


def log1p(x, name=None):
    return _unary(x, jnp.log1p, "sparse_log1p")


def expm1(x, name=None):
    return _unary(x, jnp.expm1, "sparse_expm1")


def deg2rad(x, name=None):
    return _unary(x, jnp.deg2rad, "sparse_deg2rad")


def rad2deg(x, name=None):
    return _unary(x, jnp.rad2deg, "sparse_rad2deg")


def isnan(x, name=None):
    _check_sparse(x)
    vals = op_call(jnp.isnan, x._spvals, name="sparse_isnan")
    return _build(vals, x._spidx, x._spshape)


def divide(x, y, name=None):
    return _ewise(x, y, jnp.divide, "sparse_divide")


def mv(x, vec, name=None):
    """Sparse matrix × dense vector (≙ sparse/binary.py mv). Differentiable
    w.r.t. both the sparse values and the vector (indices ride last,
    excluded via n_diff)."""
    _check_sparse(x)

    def f(vals, v, idx):
        rows = idx[:, 0]
        cols = idx[:, 1]
        contrib = vals * v[cols]
        return jnp.zeros((x._spshape[0],), vals.dtype).at[rows].add(contrib)

    return op_call(f, x._spvals, vec,
                   Tensor(x._spidx, _internal=True, stop_gradient=True),
                   name="sparse_mv", n_diff=2)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta·input + alpha·(x @ y) with sparse x (≙ sparse/multiary.py)."""
    prod = matmul(x, y)
    from ..ops.math import add as dense_add, scale

    pd = prod if not is_sparse(prod) else to_dense(prod)
    ind = input if not is_sparse(input) else to_dense(input)
    return dense_add(scale(ind, beta), scale(pd, alpha))


def sum(x, axis=None, dtype=None, keepdim=False, name=None):  # noqa: A001
    """Sparse reduce-sum; returns a dense tensor (value-equivalent to the
    reference). axis=None never densifies — summing the stored values is
    the whole reduction (zeros contribute nothing)."""
    from ..ops.reduction import sum as dense_sum

    if axis is None:
        _check_sparse(x)
        total = dense_sum(x._spvals, dtype=dtype)
        if keepdim:
            from ..ops.manipulation import reshape as dense_reshape

            return dense_reshape(total, [1] * len(x._spshape))
        return total
    return dense_sum(to_dense(x), axis=axis, dtype=dtype, keepdim=keepdim)


def reshape(x, shape, name=None):
    _check_sparse(x)
    dense = to_dense(x)
    from ..ops.manipulation import reshape as dense_reshape

    return to_sparse_coo(dense_reshape(dense, shape))


def slice(x, axes, starts, ends, name=None):  # noqa: A001
    _check_sparse(x)
    dense = to_dense(x)
    import builtins

    def f(a):
        sl = [builtins.slice(None)] * a.ndim
        for ax, st, en in zip(axes, starts, ends):
            sl[ax % a.ndim] = builtins.slice(st, en)
        return a[tuple(sl)]

    out = op_call(f, dense, name="sparse_slice")
    return to_sparse_coo(out)


def coalesce(x, name=None):
    """Merge duplicate indices (≙ sparse/creation.py coalesce)."""
    _check_sparse(x)
    idx = np.asarray(x._spidx)
    uniq, inv = np.unique(idx, axis=0, return_inverse=True)

    def f(vals):
        return jnp.zeros((uniq.shape[0],) + vals.shape[1:],
                         vals.dtype).at[jnp.asarray(inv)].add(vals)

    vals = op_call(f, x._spvals, name="sparse_coalesce")
    return _build(vals, uniq, x._spshape)


def is_same_shape(x, y, name=None):
    sx = tuple(x._spshape) if is_sparse(x) else tuple(x.shape)
    sy = tuple(y._spshape) if is_sparse(y) else tuple(y.shape)
    return sx == sy


def mask_as(x, mask, name=None):
    """Keep x's entries at mask's sparsity pattern (≙ sparse/unary.py
    mask_as)."""
    _check_sparse(mask)
    dense = x if not is_sparse(x) else to_dense(x)
    idx = Tensor(mask._spidx, _internal=True, stop_gradient=True)

    def f(a, ind):
        return a[tuple(ind[:, d] for d in range(ind.shape[1]))]

    vals = op_call(f, dense, idx, name="sparse_mask_as", n_diff=1)
    return _build(vals, mask._spidx, mask._spshape)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Randomized PCA over the densified matrix (≙ sparse pca_lowrank)."""
    from ..ops.extras import svd_lowrank
    from ..ops.reduction import mean as dense_mean

    dense = to_dense(x) if is_sparse(x) else x
    qq = q or min(6, *dense.shape[-2:])
    if center:
        from ..ops.math import subtract as dense_sub

        m = dense_mean(dense, axis=-2, keepdim=True)
        dense = dense_sub(dense, m)
    return svd_lowrank(dense, q=qq, niter=niter)


__all__ += [
    "asin", "asinh", "atan", "atanh", "sinh", "tan", "square", "log1p",
    "expm1", "deg2rad", "rad2deg", "isnan", "divide", "mv", "addmm", "sum",
    "reshape", "slice", "coalesce", "is_same_shape", "mask_as", "pca_lowrank",
]


from .conv import (  # noqa: E402
    avg_pool3d, conv2d, conv3d, max_pool3d, subm_conv2d, subm_conv3d)

__all__ += ["conv2d", "conv3d", "subm_conv2d", "subm_conv3d",
            "max_pool3d", "avg_pool3d"]
