"""Shared-memory batch transport for the DataLoader worker path.

Reference parity: paddle's shared-memory queue under
_DataLoaderIterMultiProcess (io/dataloader/dataloader_iter.py:368, C++
shared-mem LoDTensor transport). Each worker owns one native SPSC ring
(csrc/ring_queue.cpp) inside a multiprocessing.SharedMemory segment; numpy
payloads travel as pickle-protocol-5 out-of-band buffers, so array bytes
are ONE memcpy into the ring and one out — no pipe writes, no per-array
pickle copies. Frames that can't fit fall back to the mp.Queue path.
"""
from __future__ import annotations

import ctypes
import pickle
import struct
import time
from multiprocessing import shared_memory

from ..core import native


def available() -> bool:
    return native.ring_lib() is not None


def _encode(obj) -> bytes:
    buffers: list = []
    head = pickle.dumps(obj, protocol=5, buffer_callback=buffers.append)
    parts = [struct.pack("<II", len(head), len(buffers)), head]
    for b in buffers:
        raw = b.raw()
        parts.append(struct.pack("<Q", raw.nbytes))
        parts.append(raw)
    return b"".join(parts)


def _decode(frame: memoryview):
    n_head, n_buf = struct.unpack_from("<II", frame, 0)
    off = 8
    head = bytes(frame[off:off + n_head])
    off += n_head
    bufs = []
    for _ in range(n_buf):
        (n,) = struct.unpack_from("<Q", frame, off)
        off += 8
        # bytearray: reconstructed arrays stay WRITABLE, matching the
        # mp.Queue fallback path (bytes would make them read-only)
        bufs.append(bytearray(frame[off:off + n]))
        off += n
    return pickle.loads(head, buffers=bufs)


class ShmRing:
    """One SPSC ring in a SharedMemory segment (producer=worker)."""

    def __init__(self, size: int = 64 << 20, name: str | None = None,
                 create: bool = True):
        self._lib = native.ring_lib()
        if self._lib is None:
            raise RuntimeError("native ring_queue unavailable")
        self.shm = shared_memory.SharedMemory(create=create, size=size,
                                              name=name)
        self._cbuf = (ctypes.c_char * self.shm.size).from_buffer(self.shm.buf)
        self._ptr = ctypes.addressof(self._cbuf)
        if create:
            self._lib.ring_init(self._ptr, self.shm.size)
        self.capacity = self.shm.size - int(self._lib.ring_header_bytes())

    @property
    def name(self):
        return self.shm.name

    def push(self, payload: bytes, timeout: float = 120.0) -> bool:
        """Blocking push; False only when the frame can NEVER fit."""
        deadline = time.monotonic() + timeout
        while True:
            rc = self._lib.ring_push(self._ptr, payload, len(payload))
            if rc == 0:
                return True
            if rc == -2:
                return False  # oversize: caller uses the fallback queue
            if time.monotonic() > deadline:
                raise TimeoutError("shm ring full for too long")
            time.sleep(0.0005)

    def try_pop(self):
        """One frame as a decoded object, or None when empty."""
        size = self._lib.ring_next_size(self._ptr)
        if size < 0:
            return None
        buf = ctypes.create_string_buffer(int(size))
        got = self._lib.ring_pop(self._ptr, buf, int(size))
        if got < 0:
            return None
        return _decode(memoryview(buf)[:int(got)])

    def close(self, unlink: bool = False):
        # the exported pointer must be dropped before the mmap can close
        del self._cbuf
        self._ptr = None
        try:
            self.shm.close()
            if unlink:
                self.shm.unlink()
        except (FileNotFoundError, OSError):
            pass


class ShmDataChannel:
    """Parent-side multiplexer over per-worker rings + an mp.Queue fallback
    for oversize frames; same (seq, data, err) contract as the queue path."""

    def __init__(self, num_workers: int, fallback_queue, ring_bytes: int = 64 << 20):
        self.rings = [ShmRing(ring_bytes) for _ in range(num_workers)]
        self.fallback = fallback_queue

    def worker_names(self):
        return [r.name for r in self.rings]

    def get(self, timeout: float = 120.0):
        deadline = time.monotonic() + timeout
        delay = 0.0005
        while True:
            for ring in self.rings:
                item = ring.try_pop()
                if item is not None:
                    return item
            try:
                return self.fallback.get_nowait()
            except Exception:
                pass
            if time.monotonic() > deadline:
                raise TimeoutError("no batch from workers within timeout")
            time.sleep(delay)
            # back off toward 20ms when idle so a slow dataset doesn't cost
            # the fork-shared workers a busy-polling core
            delay = min(delay * 1.5, 0.02)

    def close(self):
        for r in self.rings:
            r.close(unlink=True)


class ShmWorkerSender:
    """Worker-side producer handle (attaches to the parent's segment)."""

    def __init__(self, ring_name: str, fallback_queue, timeout: float = 120.0):
        self.ring = ShmRing(name=ring_name, create=False, size=1)  # attach
        self.fallback = fallback_queue
        self.timeout = timeout

    def put(self, item):
        payload = _encode(item)
        try:
            fits = self.ring.push(payload, timeout=self.timeout)
        except TimeoutError:
            fits = False  # ring wedged: the mp.Queue still reaches the parent
        if not fits:
            self.fallback.put(item)  # oversize frame or stuck ring

    def close(self):
        self.ring.close()
