"""paddle.io: Dataset / DataLoader / samplers (≙ python/paddle/io).

Datasets and samplers are host-side Python; the DataLoader moves batches to
the chips. Multiprocess workers (≙ io/dataloader/dataloader_iter.py:368
_DataLoaderIterMultiProcess + shared-memory queue) use fork + queues with
numpy payloads; transfer to HBM is the collate step's device_put, prefetched
one batch ahead.
"""
from .dataset import (
    Dataset,
    IterableDataset,
    TensorDataset,
    ComposeDataset,
    ChainDataset,
    ConcatDataset,
    Subset,
    random_split,
)
from .sampler import (
    Sampler,
    SequenceSampler,
    RandomSampler,
    SubsetRandomSampler,
    WeightedRandomSampler,
    BatchSampler,
    DistributedBatchSampler,
)
from .dataloader import DataLoader, WorkerInfo, default_collate_fn, get_worker_info

__all__ = [
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
    "ChainDataset", "ConcatDataset", "Subset", "random_split",
    "Sampler", "SequenceSampler", "RandomSampler", "WeightedRandomSampler",
    "BatchSampler", "DistributedBatchSampler",
    "DataLoader", "WorkerInfo", "default_collate_fn", "get_worker_info",
]
