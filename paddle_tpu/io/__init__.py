"""paddle.io: Dataset / DataLoader / samplers (≙ python/paddle/io).

Single-process loader with async host→device prefetch (device_put pipelining —
the TPU analog of paddle's pinned-memory + GPU prefetch path). Multiprocess
workers (io/reader.py:262 _DataLoaderIterMultiProcess) use a
multiprocessing.Pool-based prefetcher; a C++ shared-memory ring is planned.
"""
from __future__ import annotations

import bisect
import itertools
import math

import numpy as np

from ..core.rng import next_key
from ..core.tensor import Tensor


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise RuntimeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = datasets

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (list, tuple)) else [item])
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = datasets

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumulative_sizes = list(itertools.accumulate(len(d) for d in self.datasets))

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        i = bisect.bisect_right(self.cumulative_sizes, idx)
        off = idx - (self.cumulative_sizes[i - 1] if i > 0 else 0)
        return self.datasets[i][off]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = indices

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    if all(isinstance(l, float) for l in lengths):
        n = len(dataset)
        lengths = [int(math.floor(n * l)) for l in lengths]
        lengths[-1] += n - sum(lengths)
    perm = np.random.permutation(sum(lengths))
    out = []
    off = 0
    for l in lengths:
        out.append(Subset(dataset, perm[off:off + l].tolist()))
        off += l
    return out


# ---------------------------------------------------------------- samplers
class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None, generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        return iter(np.random.choice(len(self.weights), self.num_samples,
                                     replace=self.replacement, p=p).tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1,
                 drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards the index space across data-parallel ranks
    (≙ python/paddle/io/dataloader/batch_sampler.py DistributedBatchSampler)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from ..distributed import get_rank, get_world_size

        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        indices = list(range(len(self.dataset)))
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            rng.shuffle(indices)
        indices += indices[: self.total_size - len(indices)]
        indices = indices[self.local_rank::self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size


# ---------------------------------------------------------------- collate
def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, Tensor):
        from ..ops import stack

        return stack(batch)
    if isinstance(sample, (int, np.integer)):
        return Tensor(np.asarray(batch, np.int64))
    if isinstance(sample, float):
        return Tensor(np.asarray(batch, np.float32))
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return tuple(default_collate_fn(list(s)) for s in transposed)
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    return batch


class _SingleProcessIter:
    def __init__(self, loader):
        self.loader = loader
        ds = loader.dataset
        if isinstance(ds, IterableDataset):
            self._it = iter(ds)
            self._mode = "iterable"
        else:
            self._batches = iter(loader.batch_sampler)
            self._mode = "map"
        self._prefetched = []

    def __iter__(self):
        return self

    def _fetch(self):
        if self._mode == "iterable":
            batch = list(itertools.islice(self._it, self.loader.batch_size))
            if not batch:
                raise StopIteration
        else:
            idxs = next(self._batches)
            batch = [self.loader.dataset[i] for i in idxs]
        fn = self.loader.collate_fn or default_collate_fn
        return fn(batch)

    def __next__(self):
        return self._fetch()


class DataLoader:
    """≙ paddle.io.DataLoader (io/reader.py:262). num_workers>0 uses a thread
    prefetcher (jax host compute releases the GIL during device transfers);
    process workers + shm queue arrive with the C++ runtime component."""

    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = getattr(batch_sampler, "batch_size", batch_size)
        elif not isinstance(dataset, IterableDataset):
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size, drop_last=drop_last)
        else:
            self.batch_sampler = None

    def __iter__(self):
        if self.num_workers > 0:
            return _ThreadPrefetchIter(self)
        return _SingleProcessIter(self)

    def __len__(self):
        if self.batch_sampler is not None:
            return len(self.batch_sampler)
        raise TypeError("IterableDataset DataLoader has no length")


class _ThreadPrefetchIter(_SingleProcessIter):
    def __init__(self, loader):
        super().__init__(loader)
        import queue
        import threading

        self._q = queue.Queue(maxsize=max(2, loader.prefetch_factor * loader.num_workers))
        self._done = object()
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    def _worker(self):
        try:
            while True:
                try:
                    self._q.put(self._fetch())
                except StopIteration:
                    self._q.put(self._done)
                    return
        except Exception as e:  # propagate to consumer
            self._q.put(e)

    def __next__(self):
        item = self._q.get()
        if item is self._done:
            raise StopIteration
        if isinstance(item, Exception):
            raise item
        return item


def get_worker_info():
    return None
