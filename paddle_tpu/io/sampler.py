"""Samplers (≙ python/paddle/io/sampler.py, batch_sampler.py).

DistributedBatchSampler keeps the reference's rank/num_replicas contract
(per-rank slice of the epoch), driven by PADDLE_TRAINER_* envs in
multi-process mode. In single-controller TPU runs the global batch is
sharded over the dp mesh axis instead, so rank defaults to 0/1 replica and
the sampler degrades to a plain BatchSampler.
"""
from __future__ import annotations

import math

import numpy as np


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None, generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator

    @property
    def num_samples(self):
        return self._num_samples if self._num_samples is not None else len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            yield from np.random.randint(0, n, self.num_samples).tolist()
        else:
            yield from np.random.permutation(n)[: self.num_samples].tolist()

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        super().__init__(None)
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(
            len(self.weights), self.num_samples, replace=self.replacement, p=p)
        yield from idx.tolist()

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1,
                 drop_last=False):
        super().__init__(dataset)
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)
        self.batch_size = int(batch_size)
        self.drop_last = drop_last
        self.shuffle = shuffle

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        return n // self.batch_size if self.drop_last else math.ceil(n / self.batch_size)


class DistributedBatchSampler(BatchSampler):
    """≙ io/dataloader/batch_sampler.py DistributedBatchSampler."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None:
            from ..distributed import get_world_size

            num_replicas = max(get_world_size(), 1)
        if rank is None:
            from ..distributed import get_rank

            rank = get_rank()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / num_replicas))
        self.total_size = self.num_samples * num_replicas

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            rng.shuffle(indices)
        indices = np.concatenate([indices, indices[: self.total_size - n]])
        local = indices[self.local_rank::self.nranks].tolist()
        batch = []
        for idx in local:
            batch.append(int(idx))
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return math.ceil(self.num_samples / self.batch_size)




class SubsetRandomSampler(Sampler):
    """≙ io/sampler.py SubsetRandomSampler: random order over a fixed index
    subset."""

    def __init__(self, indices):
        if len(indices) == 0:
            raise ValueError("indices must not be empty")
        self.indices = list(indices)

    def __iter__(self):
        import numpy as _np

        from ..core.rng import next_key

        seed_words = _np.asarray(next_key()).astype(_np.uint32).ravel()
        order = _np.random.default_rng(seed_words.tolist()).permutation(
            len(self.indices))
        return iter(self.indices[i] for i in order)

    def __len__(self):
        return len(self.indices)
