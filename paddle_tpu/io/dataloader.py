"""DataLoader — batches from a Dataset onto the chips.

Reference parity: paddle.io.DataLoader (io/reader.py:262) with
_DataLoaderIterMultiProcess (io/dataloader/dataloader_iter.py:368): worker
subprocesses + shared-memory queue + a GPU-transfer thread. TPU-native
layout: workers produce HOST numpy batches (multiprocessing when
num_workers>0); transfer is an async `jax.device_put` started one batch
AHEAD (prefetch) so host→HBM DMA for batch k+1 overlaps step k's compute —
the role of paddle's pin-memory + cuda stream thread.
"""
from __future__ import annotations

import itertools
import multiprocessing as mp
import queue as queue_mod
import threading

import numpy as np

from ..core.tensor import Tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler

_worker_info = None


class WorkerInfo:
    def __init__(self, id, num_workers, dataset, seed=0):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed


def default_collate_fn(batch):
    """list of samples -> batched Tensor(s), mirroring paddle's collate."""
    sample = batch[0]
    if isinstance(sample, (Tensor,)):
        import jax.numpy as jnp

        return Tensor(jnp.stack([s._data for s in batch]), _internal=True)
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, np.integer)):
        return Tensor(np.asarray(batch, dtype=np.int64))
    if isinstance(sample, (float, np.floating)):
        return Tensor(np.asarray(batch, dtype=np.float32))
    if isinstance(sample, (list, tuple)):
        return [default_collate_fn([s[i] for s in batch]) for i in range(len(sample))]
    if isinstance(sample, dict):
        return {k: default_collate_fn([s[k] for s in batch]) for k in sample}
    raise TypeError(f"cannot collate {type(sample)}")


def _numpy_collate(batch):
    """Worker-side collate: keep numpy (pickles across processes cheaply)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    if isinstance(sample, (list, tuple)):
        return [_numpy_collate([s[i] for s in batch]) for i in range(len(sample))]
    if isinstance(sample, dict):
        return {k: _numpy_collate([s[k] for s in batch]) for k in sample}
    if isinstance(sample, Tensor):
        return np.stack([s.numpy() for s in batch])
    return batch


def _to_tensors(obj):
    if isinstance(obj, np.ndarray):
        return Tensor(obj)
    if isinstance(obj, list):
        return [_to_tensors(o) for o in obj]
    if isinstance(obj, dict):
        return {k: _to_tensors(v) for k, v in obj.items()}
    return obj


def get_worker_info():
    return _worker_info


def _worker_loop(dataset, index_queue, data_queue, collate_fn, worker_id, num_workers,
                 worker_init_fn=None, ring_name=None, timeout=120.0):
    global _worker_info
    _worker_info = WorkerInfo(worker_id, num_workers, dataset)
    sink = data_queue
    if ring_name is not None:
        try:  # native shared-memory transport (csrc/ring_queue.cpp)
            from .shm_channel import ShmWorkerSender

            sink = ShmWorkerSender(ring_name, data_queue, timeout=timeout)
        except Exception:
            sink = data_queue
    if worker_init_fn is not None:
        try:
            worker_init_fn(worker_id)
        except Exception as e:
            sink.put((-1, None, e))
            return
    while True:
        item = index_queue.get()
        if item is None:
            break
        seq, indices = item
        try:
            samples = [dataset[i] for i in indices]
            sink.put((seq, collate_fn(samples), None))
        except Exception as e:  # surface worker errors on the main process
            sink.put((seq, None, e))


class DataLoader:
    def __init__(self, dataset: Dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=120, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.num_workers = max(int(num_workers), 0)
        self.use_shared_memory = use_shared_memory
        self.collate_fn = collate_fn
        self.timeout = timeout or 120
        self.prefetch_factor = max(int(prefetch_factor), 1)
        self.worker_init_fn = worker_init_fn
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size, drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("DataLoader over IterableDataset has no len()")
        return len(self.batch_sampler)

    # ------------------------------------------------------------ iteration
    def __iter__(self):
        if self._iterable_mode:
            return self._iter_iterable()
        if self.num_workers == 0:
            return self._iter_sync()
        return self._iter_workers()

    def _collate(self, samples):
        fn = self.collate_fn or default_collate_fn
        return fn(samples)

    def _iter_sync(self):
        for indices in self.batch_sampler:
            yield self._collate([self.dataset[i] for i in indices])

    def _iter_iterable(self):
        it = iter(self.dataset)
        while True:
            samples = list(itertools.islice(it, self.batch_size))
            if not samples:
                return
            if len(samples) < self.batch_size and self.drop_last:
                return
            yield self._collate(samples)

    def _iter_workers(self):
        """Round-robin index distribution to worker processes, in-order
        results with a bounded reorder buffer (≙ _DataLoaderIterMultiProcess)."""
        # fork is cheapest (no re-import, dataset shared CoW) but unavailable
        # on some platforms; fall back to spawn there
        method = "fork" if "fork" in mp.get_all_start_methods() else "spawn"
        ctx = mp.get_context(method)
        index_queues = [ctx.Queue() for _ in range(self.num_workers)]
        data_queue = ctx.Queue()
        collate = self.collate_fn or _numpy_collate
        channel = None
        ring_names = [None] * self.num_workers
        if self.use_shared_memory:
            try:  # native shm rings; silently fall back to the queue path
                from .shm_channel import ShmDataChannel, available

                if available():
                    channel = ShmDataChannel(self.num_workers, data_queue)
                    ring_names = channel.worker_names()
            except Exception:
                channel = None
        source = channel if channel is not None else data_queue
        workers = [
            ctx.Process(
                target=_worker_loop,
                args=(self.dataset, index_queues[w], data_queue, collate,
                      w, self.num_workers, self.worker_init_fn,
                      ring_names[w], self.timeout),
                daemon=True,
            )
            for w in range(self.num_workers)
        ]
        for w in workers:
            w.start()
        try:
            batches = list(self.batch_sampler)
            inflight = 0
            next_send = 0
            next_yield = 0
            reorder: dict[int, object] = {}
            budget = self.num_workers * self.prefetch_factor
            while next_send < len(batches) and inflight < budget:
                index_queues[next_send % self.num_workers].put(
                    (next_send, batches[next_send]))
                next_send += 1
                inflight += 1
            while next_yield < len(batches):
                while next_yield not in reorder:
                    seq, data, err = source.get(timeout=self.timeout)
                    if err is not None:
                        raise err
                    reorder[seq] = data
                    inflight -= 1
                    if next_send < len(batches):
                        index_queues[next_send % self.num_workers].put(
                            (next_send, batches[next_send]))
                        next_send += 1
                        inflight += 1
                data = reorder.pop(next_yield)
                next_yield += 1
                if self.collate_fn is None:
                    data = _to_tensors(data)
                yield data
        finally:
            for q in index_queues:
                q.put(None)
            for w in workers:
                w.join(timeout=5)
                if w.is_alive():
                    w.terminate()
            if channel is not None:
                channel.close()
