"""Datasets (≙ python/paddle/io/dataset.py et al.).

Map-style and iterable datasets plus the combinators paddle ships
(TensorDataset, ComposeDataset, ChainDataset, Subset, ConcatDataset,
random_split). Pure host-side Python — device transfer happens in the
DataLoader's collate/prefetch stage.
"""
from __future__ import annotations

import bisect

import numpy as np


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        lens = {len(t) for t in tensors}
        if len(lens) != 1:
            raise ValueError("all tensors must have the same first dimension")
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


class ComposeDataset(Dataset):
    """Zip several same-length datasets; sample = flattened fields."""

    def __init__(self, datasets):
        self.datasets = list(datasets)
        lens = {len(d) for d in self.datasets}
        if len(lens) != 1:
            raise ValueError("datasets must have equal lengths")

    def __len__(self):
        return len(self.datasets[0])

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            out.extend(sample if isinstance(sample, (tuple, list)) else [sample])
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumulative_sizes = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cumulative_sizes[-1] if self.cumulative_sizes else 0

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds = bisect.bisect_right(self.cumulative_sizes, idx)
        prev = self.cumulative_sizes[ds - 1] if ds > 0 else 0
        return self.datasets[ds][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    lengths = list(lengths)
    if all(isinstance(l, float) for l in lengths) and abs(sum(lengths) - 1.0) < 1e-6:
        n = len(dataset)
        sizes = [int(np.floor(n * f)) for f in lengths]
        for i in range(n - sum(sizes)):
            sizes[i % len(sizes)] += 1
        lengths = sizes
    if sum(lengths) != len(dataset):
        raise ValueError("sum of lengths must equal dataset size")
    if generator is not None:
        # generator: anything with a .seed attribute or an int-like seed,
        # giving a reproducible split (reference random_split generator arg)
        seed = getattr(generator, "seed", generator)
        seed = seed() if callable(seed) else seed
        perm = np.random.RandomState(int(seed)).permutation(len(dataset))
    else:
        perm = np.random.permutation(len(dataset))
    out, ofs = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[ofs:ofs + l].tolist()))
        ofs += l
    return out
