"""Extended single-source op table entries (round 4, VERDICT r3 Missing #3):
migrates the rest of the public op surface into ops/op_table.py's registry so
the auto-generated sweep grad-checks everything differentiable
(≙ /root/reference/test/legacy_test/op_test.py:418 discipline — the reference
grad-checks EVERY op).

Split from op_table.py only for file size; `ensure_populated` pulls both.
"""
from __future__ import annotations

import math as _math

import numpy as np

from .op_table import OpSpec, register

_POS = (0.2, 2.0)
_UNIT = (-0.95, 0.95)
_SAFE = (-2.0, 2.0)


def _np_softmax(x, axis=-1):
    e = np.exp(x - x.max(axis, keepdims=True))
    return e / e.sum(axis, keepdims=True)


def populate_ext():
    import paddle_tpu as pd

    from .. import nn
    from . import extras as ex
    from . import linalg as la
    from . import manipulation as mp
    from . import math as m
    from . import reduction as r

    F = nn.functional

    # ---- special functions (vs scipy-free numpy refs where stable)
    register(OpSpec("gammaln", ex.gammaln, 1, True, domain=_POS,
                    ref=np.vectorize(_math.lgamma), tags=("special",)))
    register(OpSpec("gammainc", ex.gammainc, 2, True,
                    domains=(_POS, _POS), tags=("special",)))
    register(OpSpec("gammaincc", ex.gammaincc, 2, True,
                    domains=(_POS, _POS), tags=("special",)))
    register(OpSpec("multigammaln", lambda x: ex.multigammaln(x, 2), 1, True,
                    domain=(1.5, 3.0), tags=("special",)))
    register(OpSpec("polygamma", lambda x: ex.polygamma(x, 1), 1, True,
                    domain=_POS, tags=("special",)))
    register(OpSpec("i0e", ex.i0e, 1, True, tags=("special",)))
    register(OpSpec("i1", ex.i1, 1, True, tags=("special",)))
    register(OpSpec("i1e", ex.i1e, 1, True, tags=("special",)))
    register(OpSpec("sinc", ex.sinc, 1, True, ref=np.sinc,
                    tags=("special",)))
    register(OpSpec("sgn", ex.sgn, 1, False, ref=np.sign, tags=("special",)))
    register(OpSpec("logit", m.logit, 1, True, domain=(0.05, 0.95),
                    ref=lambda x: np.log(x / (1 - x)), tags=("special",)))
    register(OpSpec("expit_via_sigmoid", m.sigmoid, 1, True,
                    ref=lambda x: 1 / (1 + np.exp(-x)), tags=("special",)))
    register(OpSpec("square_grad", m.square, 1, True, ref=np.square,
                    tags=("special",)))
    register(OpSpec("stanh", m.stanh, 1, True,
                    ref=lambda x: 1.7159 * np.tanh(0.67 * x),
                    rtol=1e-4, tags=("special",)))
    register(OpSpec("softplus_beta",
                    lambda x: F.softplus(x, beta=2.0), 1, True,
                    ref=lambda x: np.log1p(np.exp(2 * x)) / 2.0,
                    tags=("special",)))

    # ---- comparison / predicate tails
    register(OpSpec("allclose", lambda a, b: pd.allclose(a, b), 2, False,
                    ref=lambda a, b: np.allclose(a, b), bf16=False,
                    tags=("logical",)))
    register(OpSpec("isclose", lambda a, b: pd.isclose(a, b), 2, False,
                    ref=np.isclose, bf16=False, tags=("logical",)))
    register(OpSpec("isneginf", ex.isneginf, 1, False, ref=np.isneginf,
                    bf16=False, tags=("logical",)))
    register(OpSpec("isposinf", ex.isposinf, 1, False, ref=np.isposinf,
                    bf16=False, tags=("logical",)))
    register(OpSpec("isreal", ex.isreal, 1, False, ref=np.isreal,
                    bf16=False, tags=("logical",)))
    register(OpSpec("is_empty", ex.is_empty, 1, False, bf16=False,
                    tags=("logical",)))
    register(OpSpec("isin_op", lambda a, b: ex.isin(a, b), 2, False,
                    ref=np.isin, bf16=False, int_inputs=(0, 1),
                    tags=("logical",)))

    # ---- math tails
    register(OpSpec("remainder", m.remainder if hasattr(m, "remainder")
                    else m.mod, 2, False,
                    domains=(_SAFE, _POS), ref=np.mod, tags=("binary",)))
    register(OpSpec("fmod", pd.fmod if hasattr(pd, "fmod") else
                    (lambda a, b: a - b * (a / b).trunc()), 2, False,
                    domains=(_SAFE, _POS), ref=np.fmod, tags=("binary",)))
    register(OpSpec("inner", pd.inner, 2, True, shapes=((3, 4), (2, 4)),
                    ref=np.inner, tags=("linalg",)))
    register(OpSpec("logaddexp2_via_log2", m.log2, 1, True, domain=_POS,
                    ref=np.log2, tags=("unary",)))
    register(OpSpec("rsqrt_grad", m.rsqrt, 1, True, domain=_POS,
                    ref=lambda x: 1 / np.sqrt(x), tags=("unary",)))
    register(OpSpec("trapezoid", ex.trapezoid, 1, True, shape=(3, 5),
                    ref=lambda y: np.trapezoid(y, axis=-1),
                    tags=("reduction",)))
    register(OpSpec("cumulative_trapezoid", ex.cumulative_trapezoid, 1,
                    True, shape=(3, 5), tags=("reduction",)))
    register(OpSpec("diff_op", ex.diff, 1, True, shape=(3, 5),
                    ref=lambda x: np.diff(x, axis=-1),
                    tags=("manipulation",)))
    register(OpSpec("frac_op", m.frac, 1, False,
                    ref=lambda x: x - np.trunc(x), tags=("unary",)))
    register(OpSpec("nan_to_num", lambda x: pd.nan_to_num(x), 1, True,
                    ref=np.nan_to_num, tags=("unary",)))
    register(OpSpec("lerp_op", lambda a, b: m.lerp(a, b, 0.3), 2, True,
                    ref=lambda a, b: a + 0.3 * (b - a), tags=("binary",)))
    register(OpSpec("angle", pd.angle, 1, True, domain=_POS,
                    ref=lambda x: np.angle(x), tags=("unary",)))
    register(OpSpec("conj", pd.conj, 1, True, ref=np.conj, tags=("unary",)))
    register(OpSpec("real", pd.real, 1, True, ref=np.real, tags=("unary",)))
    register(OpSpec("scale_op",
                    lambda x: m.scale(x, scale=2.0, bias=1.0), 1, True,
                    ref=lambda x: 2 * x + 1, tags=("unary",)))
    register(OpSpec("clip_grad", lambda x: m.clip(x, -1.0, 1.0), 1, True,
                    ref=lambda x: np.clip(x, -1, 1), tags=("unary",)))
    register(OpSpec("logcumsumexp", lambda x: m.logcumsumexp(x, axis=0), 1,
                    True, shape=(4, 3),
                    ref=lambda x: np.log(np.cumsum(np.exp(x), 0)),
                    rtol=1e-4, tags=("reduction",)))
    register(OpSpec("logdet_via_slogdet",
                    lambda x: la.slogdet(x)[1], 1, True, shape=(3, 3),
                    domain=(0.5, 1.5),
                    bf16=False,
                    tags=("linalg",)))

    # ---- reductions tails
    register(OpSpec("count_nonzero", lambda x: pd.count_nonzero(x), 1,
                    False, ref=np.count_nonzero, bf16=False,
                    tags=("reduction",)))
    register(OpSpec("nanmedian", r.nanmedian, 1, False, ref=np.nanmedian,
                    tags=("reduction",)))
    register(OpSpec("quantile", lambda x: r.quantile(x, 0.5), 1, True,
                    ref=lambda x: np.quantile(x, 0.5), tags=("reduction",)))
    register(OpSpec("nanquantile", lambda x: r.nanquantile(x, 0.5), 1,
                    False, ref=lambda x: np.nanquantile(x, 0.5),
                    tags=("reduction",)))
    register(OpSpec("cummax", lambda x: pd.cummax(x, axis=0)[0], 1, True,
                    shape=(4, 3), ref=lambda x: np.maximum.accumulate(x, 0),
                    tags=("reduction",)))
    register(OpSpec("cummin", lambda x: pd.cummin(x, axis=0)[0], 1, True,
                    shape=(4, 3), ref=lambda x: np.minimum.accumulate(x, 0),
                    tags=("reduction",)))
    register(OpSpec("mode", lambda x: pd.mode(x)[0], 1, False, shape=(3, 5),
                    int_inputs=(0,), bf16=False, tags=("reduction",)))
    register(OpSpec("median_min",
                    lambda x: r.median(x, axis=-1, mode="min")[0], 1, False,
                    shape=(3, 5), tags=("reduction",)))
    register(OpSpec("reduce_as", ex.reduce_as, 2, True,
                    shapes=((4, 3), (1, 3)),
                    ref=lambda x, t: x.sum(0, keepdims=True),
                    no_grad_inputs=(1,), tags=("reduction",)))
    register(OpSpec("l2_normalize_axis",
                    lambda x: F.normalize(x, axis=0), 1, True,
                    ref=lambda x: x / np.linalg.norm(x, axis=0,
                                                     keepdims=True),
                    tags=("reduction",)))
    register(OpSpec("norm_p1", lambda x: la.norm(x, p=1), 1, True,
                    ref=lambda x: np.abs(x).sum(), tags=("reduction",)))
    register(OpSpec("norm_inf",
                    lambda x: la.norm(x, p=float("inf")), 1, True,
                    ref=lambda x: np.abs(x).max(), tags=("reduction",)))
    register(OpSpec("dist", lambda a, b: pd.dist(a, b, p=2), 2, True,
                    ref=lambda a, b: np.linalg.norm((a - b).ravel()),
                    tags=("reduction",)))

    # ---- manipulation tails
    register(OpSpec("unstack_op", lambda x: ex.unstack(x, 0)[0], 1, True,
                    shape=(3, 4), ref=lambda x: x[0], tags=("manipulation",)))
    register(OpSpec("unflatten_op", lambda x: ex.unflatten(x, 0, [2, 2]), 1,
                    True, shape=(4, 3),
                    ref=lambda x: x.reshape(2, 2, 3), tags=("manipulation",)))
    register(OpSpec("unbind", lambda x: pd.unbind(x, 0)[1], 1, True,
                    shape=(3, 4), ref=lambda x: x[1], tags=("manipulation",)))
    register(OpSpec("rot90", lambda x: pd.rot90(x), 1, True, shape=(3, 4),
                    ref=lambda x: np.rot90(x), tags=("manipulation",)))
    register(OpSpec("moveaxis", lambda x: pd.moveaxis(x, 0, 1), 1, True,
                    ref=lambda x: np.moveaxis(x, 0, 1),
                    tags=("manipulation",)))
    register(OpSpec("swapaxes", lambda x: pd.swapaxes(x, 0, 1), 1, True,
                    ref=lambda x: np.swapaxes(x, 0, 1),
                    tags=("manipulation",)))
    register(OpSpec("expand_as", lambda x, y: pd.expand_as(x, y), 2, True,
                    shapes=((1, 3), (4, 3)),
                    ref=lambda x, y: np.broadcast_to(x, y.shape),
                    no_grad_inputs=(1,), tags=("manipulation",)))
    register(OpSpec("as_strided",
                    lambda x: pd.as_strided(x, [2, 2], [1, 1]), 1, True,
                    shape=(6,), tags=("manipulation",)))
    register(OpSpec("view_op", lambda x: pd.view(x, [3, 2]), 1, True,
                    shape=(2, 3), ref=lambda x: x.reshape(3, 2),
                    tags=("manipulation",)))
    register(OpSpec("atleast_2d", lambda x: pd.atleast_2d(x), 1, True,
                    shape=(4,), ref=np.atleast_2d, tags=("manipulation",)))
    register(OpSpec("atleast_3d", lambda x: pd.atleast_3d(x), 1, True,
                    shape=(4,), ref=np.atleast_3d, tags=("manipulation",)))
    register(OpSpec("hstack", lambda a, b: pd.hstack([a, b]), 2, True,
                    ref=lambda a, b: np.hstack([a, b]),
                    tags=("manipulation",)))
    register(OpSpec("vstack", lambda a, b: pd.vstack([a, b]), 2, True,
                    ref=lambda a, b: np.vstack([a, b]),
                    tags=("manipulation",)))
    register(OpSpec("dstack", lambda a, b: pd.dstack([a, b]), 2, True,
                    ref=lambda a, b: np.dstack([a, b]),
                    tags=("manipulation",)))
    register(OpSpec("column_stack", lambda a, b: pd.column_stack([a, b]), 2,
                    True, ref=lambda a, b: np.column_stack([a, b]),
                    tags=("manipulation",)))
    register(OpSpec("row_stack", lambda a, b: pd.row_stack([a, b]), 2, True,
                    ref=lambda a, b: np.vstack([a, b]),
                    tags=("manipulation",)))
    register(OpSpec("hsplit", lambda x: pd.hsplit(x, 2)[0], 1, True,
                    shape=(3, 4), ref=lambda x: np.hsplit(x, 2)[0],
                    tags=("manipulation",)))
    register(OpSpec("vsplit", lambda x: pd.vsplit(x, 2)[0], 1, True,
                    shape=(4, 3), ref=lambda x: np.vsplit(x, 2)[0],
                    tags=("manipulation",)))
    register(OpSpec("tensor_split",
                    lambda x: pd.tensor_split(x, 2, axis=0)[0], 1, True,
                    shape=(4, 3),
                    ref=lambda x: np.array_split(x, 2, axis=0)[0],
                    tags=("manipulation",)))
    register(OpSpec("crop", lambda x: pd.crop(x, shape=[2, 2],
                                              offsets=[1, 1]), 1, True,
                    shape=(4, 4), ref=lambda x: x[1:3, 1:3],
                    tags=("manipulation",)))
    register(OpSpec("slice_op",
                    lambda x: pd.slice(x, [0], [1], [3]), 1, True,
                    shape=(4, 3), ref=lambda x: x[1:3],
                    tags=("manipulation",)))
    register(OpSpec("strided_slice",
                    lambda x: pd.strided_slice(x, [0], [0], [4], [2]), 1,
                    True, shape=(4, 3), ref=lambda x: x[0:4:2],
                    tags=("manipulation",)))
    register(OpSpec("index_put",
                    lambda x, i, v: pd.index_put(x, [i], v), 3, True,
                    shapes=((4, 3), (2,), (2, 3)), int_inputs=(1,),
                    int_high=4, tags=("manipulation",)))
    register(OpSpec("index_fill",
                    lambda x, i: pd.index_fill(x, i, 0, 0.5), 2, True,
                    shapes=((4, 3), (2,)), int_inputs=(1,), int_high=4,
                    tags=("manipulation",)))
    register(OpSpec("index_add",
                    lambda x, i, v: pd.index_add(x, i, 0, v), 3, True,
                    shapes=((4, 3), (2,), (2, 3)), int_inputs=(1,),
                    int_high=4, tags=("manipulation",)))
    register(OpSpec("put_along_axis",
                    lambda x, i, v: mp.put_along_axis(x, i, v, 1), 3, True,
                    shapes=((3, 4), (3, 2), (3, 2)), int_inputs=(1,),
                    int_high=4, tags=("manipulation",)))
    register(OpSpec("scatter_op", lambda x, i, u: mp.scatter(x, i, u), 3,
                    True, shapes=((4, 3), (2,), (2, 3)), int_inputs=(1,),
                    int_high=4, tags=("manipulation",)))
    register(OpSpec("scatter_nd_add",
                    lambda x, i, u: pd.scatter_nd_add(x, i, u), 3, True,
                    shapes=((4, 3), (2, 1), (2, 3)), int_inputs=(1,),
                    int_high=4, tags=("manipulation",)))
    register(OpSpec("gather_nd", lambda x, i: mp.gather_nd(x, i), 2, True,
                    shapes=((4, 3), (2, 2)), int_inputs=(1,), int_high=3,
                    tags=("manipulation",)))
    register(OpSpec("masked_select", lambda x, m2: pd.masked_select(
        x, m2 > 2), 2, False, int_inputs=(1,), bf16=False,
        ref=lambda x, m2: x[m2 > 2], tags=("manipulation",)))
    register(OpSpec("masked_scatter",
                    lambda x, m2, v: pd.masked_scatter(x, m2 > 2, v), 3,
                    False, int_inputs=(1,), bf16=False,
                    tags=("manipulation",)))
    register(OpSpec("select_scatter",
                    lambda x, v: pd.select_scatter(x, v, 0, 1), 2, True,
                    shapes=((3, 4), (4,)), tags=("manipulation",)))
    register(OpSpec("diagonal_scatter",
                    lambda x, v: pd.diagonal_scatter(x, v), 2, True,
                    shapes=((3, 3), (3,)), tags=("manipulation",)))
    register(OpSpec("fill_diagonal_tensor",
                    lambda x, v: ex.fill_diagonal_tensor(x, v), 2, True,
                    shapes=((3, 3), (3,)), no_grad_inputs=(1,), tags=("manipulation",)))
    register(OpSpec("roll_axis", lambda x: mp.roll(x, 1, axis=1), 1, True,
                    ref=lambda x: np.roll(x, 1, axis=1),
                    tags=("manipulation",)))
    register(OpSpec("rot90_k2", lambda x: pd.rot90(x, k=2), 1, True,
                    shape=(3, 4), ref=lambda x: np.rot90(x, 2),
                    tags=("manipulation",)))
    register(OpSpec("flatten_range",
                    lambda x: mp.flatten(x, start_axis=1, stop_axis=2), 1,
                    True, shape=(2, 3, 4),
                    ref=lambda x: x.reshape(2, 12), tags=("manipulation",)))
    register(OpSpec("repeat_tensor",
                    lambda x: pd.repeat_interleave(x, 3, axis=1), 1, True,
                    ref=lambda x: np.repeat(x, 3, axis=1),
                    tags=("manipulation",)))
    register(OpSpec("unique_vals", lambda x: mp.unique(x), 1, False,
                    int_inputs=(0,), bf16=False, ref=np.unique,
                    tags=("manipulation",)))
    register(OpSpec("unique_consecutive_vals",
                    lambda x: mp.unique_consecutive(x), 1, False,
                    int_inputs=(0,), bf16=False, tags=("manipulation",)))
    register(OpSpec("bucketize", lambda s, v: pd.bucketize(v, s), 2, False,
                    shapes=((4,), (3,)), domains=((0.0, 1.0), (0.0, 1.0)),
                    bf16=False, tags=("search",)))
    register(OpSpec("vander", lambda x: pd.vander(x, 3), 1, True,
                    shape=(4,), ref=lambda x: np.vander(x, 3),
                    tags=("creation",)))
    register(OpSpec("renorm", lambda x: pd.renorm(x, 2.0, 0, 1.0), 1, True,
                    shape=(3, 4), tags=("manipulation",)))
    register(OpSpec("flip_multi", lambda x: mp.flip(x, [0, 1]), 1, True,
                    ref=lambda x: np.flip(x, (0, 1)),
                    tags=("manipulation",)))
    register(OpSpec("shard_index_like_cast",
                    lambda x: x.astype("int32").astype("float32"), 1, False,
                    tags=("manipulation",)))

    # ---- linalg decompositions / solvers (forward parity; most n_diff via
    # tape where JAX defines gradients)
    spd = lambda x: x @ np.swapaxes(x, -1, -2) + 3 * np.eye(x.shape[-1],
                                                            dtype=x.dtype)

    register(OpSpec("inverse", la.inverse, 1, True, shape=(3, 3),
                    domain=(0.5, 1.5),
                    bf16=False,
                    tags=("linalg",)))
    register(OpSpec("det",
                    la.det, 1, True, shape=(3, 3), domain=(0.5, 1.5),
                    ref=np.linalg.det, rtol=1e-4, bf16=False,
                    tags=("linalg",)))
    register(OpSpec("slogdet", lambda x: la.slogdet(x)[1], 1, True,
                    shape=(3, 3), domain=(0.5, 1.5), bf16=False,
                    tags=("linalg",)))
    register(OpSpec("cholesky",
                    lambda x: la.cholesky(pd.to_tensor(np.eye(3, dtype="float32") * 2.0) + x @ x.t() * 0.1),
                    1, True, shape=(3, 3), bf16=False,
                    tags=("linalg",)))
    register(OpSpec("qr_q", lambda x: la.qr(x)[0], 1, True, shape=(3, 3),
                    bf16=False,
                    tags=("linalg",)))
    register(OpSpec("svdvals", lambda x: la.svd(x)[1], 1, True,
                    shape=(3, 3),
                    ref=lambda x: np.linalg.svd(x, compute_uv=False),
                    rtol=1e-4, bf16=False,
                    tags=("linalg",)))
    register(OpSpec("eigvalsh_op", lambda x: la.eigvalsh(x), 1, True,
                    shape=(3, 3), bf16=False,
                    tags=("linalg",)))
    register(OpSpec("matrix_power", lambda x: la.matrix_power(x, 2), 1,
                    True, shape=(3, 3), ref=lambda x: x @ x,
                    bf16=False,
                    tags=("linalg",)))
    register(OpSpec("pinv", la.pinv, 1, True, shape=(3, 4),
                    ref=np.linalg.pinv, rtol=1e-3, atol=1e-4,
                    bf16=False,
                    tags=("linalg",)))
    register(OpSpec("solve", la.solve, 2, True, shapes=((3, 3), (3, 2)),
                    domains=((0.5, 1.5), _SAFE),
                    bf16=False,
                    tags=("linalg",)))
    register(OpSpec("triangular_solve",
                    lambda a, b: la.triangular_solve(a, b, upper=False), 2,
                    True, shapes=((3, 3), (3, 2)),
                    domains=((0.8, 1.5), _SAFE), bf16=False,
                    tags=("linalg",)))
    register(OpSpec("matrix_rank_op", lambda x: la.matrix_rank(x), 1,
                    False, shape=(3, 3), ref=np.linalg.matrix_rank,
                    bf16=False, tags=("linalg",)))
    register(OpSpec("cond_2", lambda x: la.cond(x), 1, False,
                    shape=(3, 3), domain=(0.5, 1.5),
                    ref=lambda x: np.linalg.cond(x), rtol=1e-3,
                    bf16=False,
                    tags=("linalg",)))
    register(OpSpec("cov_op", lambda x: la.cov(x), 1, True, shape=(3, 6),
                    ref=lambda x: np.cov(x), rtol=1e-4, tags=("linalg",)))
    register(OpSpec("corrcoef_op", lambda x: la.corrcoef(x), 1, True,
                    shape=(3, 6), ref=np.corrcoef, rtol=1e-4,
                    tags=("linalg",)))
    register(OpSpec("householder_product",
                    lambda a, tau: la.householder_product(a, tau), 2, True,
                    shapes=((4, 3), (3,)), bf16=False,
                    tags=("linalg",)))
    register(OpSpec("tensordot_op",
                    lambda a, b: pd.tensordot(a, b, axes=1), 2, True,
                    shapes=((3, 4), (4, 2)),
                    ref=lambda a, b: np.tensordot(a, b, 1),
                    tags=("linalg",)))
    register(OpSpec("multi_dot",
                    lambda a, b, c: la.multi_dot([a, b, c]), 3, True,
                    shapes=((2, 3), (3, 4), (4, 2)),
                    ref=lambda a, b, c: a @ b @ c, tags=("linalg",)))
    register(OpSpec("lu_op", lambda x: la.lu(x)[0], 1, False,
                    shape=(3, 3), domain=(0.5, 1.5), bf16=False,
                    tags=("linalg",)))
    register(OpSpec("ormqr",
                    lambda a, tau, o: ex.ormqr(a, tau, o), 3, False,
                    shapes=((3, 3), (3,), (3, 2)), bf16=False,
                    tags=("linalg",)))
    register(OpSpec("cdist", lambda a, b: pd.cdist(a, b), 2, True,
                    shapes=((3, 4), (2, 4)), rtol=1e-4, tags=("linalg",)))
    register(OpSpec("bincount", lambda x: pd.bincount(x), 1, False,
                    shape=(6,), int_inputs=(0,), bf16=False,
                    ref=np.bincount, tags=("reduction",)))
    register(OpSpec("histogram",
                    lambda x: pd.histogram(x, bins=4, min=-2, max=2), 1,
                    False, bf16=False,
                    ref=lambda x: np.histogram(x, 4, (-2, 2))[0],
                    tags=("reduction",)))
    register(OpSpec("histogram_bin_edges",
                    lambda x: ex.histogram_bin_edges(x, 4, -2, 2), 1, False,
                    bf16=False,
                    ref=lambda x: np.histogram_bin_edges(x, 4, (-2, 2)),
                    tags=("reduction",)))

    # ---- nn.functional: convs / norms / embeddings (fwd + grad through
    # dispatched path; refs where a clean numpy form exists)
    register(OpSpec("linear_op",
                    lambda x, w, b: F.linear(x, w, b), 3, True,
                    shapes=((2, 4), (4, 3), (3,)),
                    ref=lambda x, w, b: x @ w + b, tags=("nn",)))
    register(OpSpec("conv2d_op",
                    lambda x, w: F.conv2d(x, w), 2, True,
                    shapes=((1, 2, 5, 5), (3, 2, 3, 3)), rtol=1e-4,
                    tags=("nn",)))
    register(OpSpec("conv1d_op", lambda x, w: F.conv1d(x, w), 2, True,
                    shapes=((1, 2, 6), (3, 2, 3)), rtol=1e-4, tags=("nn",)))
    register(OpSpec("conv3d_op", lambda x, w: F.conv3d(x, w), 2, True,
                    shapes=((1, 1, 4, 4, 4), (2, 1, 2, 2, 2)), rtol=1e-4,
                    tags=("nn",)))
    register(OpSpec("conv2d_transpose_op",
                    lambda x, w: F.conv2d_transpose(x, w), 2, True,
                    shapes=((1, 3, 4, 4), (3, 2, 3, 3)), rtol=1e-4,
                    tags=("nn",)))
    register(OpSpec("layer_norm_op",
                    lambda x, w, b: F.layer_norm(x, [4], w, b), 3, True,
                    shapes=((3, 4), (4,), (4,)), rtol=1e-4, tags=("nn",)))
    register(OpSpec("group_norm_op",
                    lambda x: F.group_norm(x, 2), 1, True,
                    shape=(2, 4, 3, 3), rtol=1e-4, tags=("nn",)))
    register(OpSpec("instance_norm_op", lambda x: F.instance_norm(x), 1,
                    True, shape=(2, 3, 4, 4), rtol=1e-4, tags=("nn",)))
    register(OpSpec("rms_norm_op", lambda x, w: F.rms_norm(x, w), 2, True,
                    shapes=((3, 4), (4,)), rtol=1e-4, tags=("nn",)))
    register(OpSpec("embedding_op",
                    lambda i, w: F.embedding(i, w), 2, True,
                    shapes=((5,), (6, 4)), int_inputs=(0,), int_high=6,
                    ref=lambda i, w: w[i], tags=("nn",)))
    register(OpSpec("one_hot_op", lambda i: F.one_hot(i, 6), 1, False,
                    shape=(4,), int_inputs=(0,), int_high=6, bf16=False,
                    ref=lambda i: np.eye(6)[i], tags=("nn",)))
    register(OpSpec("max_pool2d_op",
                    lambda x: F.max_pool2d(x, 2), 1, True,
                    shape=(1, 2, 4, 4), tags=("nn",)))
    register(OpSpec("avg_pool2d_op", lambda x: F.avg_pool2d(x, 2), 1, True,
                    shape=(1, 2, 4, 4), tags=("nn",)))
    register(OpSpec("adaptive_avg_pool2d_op",
                    lambda x: F.adaptive_avg_pool2d(x, 2), 1, True,
                    shape=(1, 2, 6, 6), tags=("nn",)))
    register(OpSpec("max_pool2d_mask",
                    lambda x: F.max_pool2d(x, 2, return_mask=True)[0], 1,
                    True, shape=(1, 2, 4, 4), tags=("nn",)))
    register(OpSpec("max_unpool2d_op",
                    lambda x: F.max_unpool2d(*F.max_pool2d(
                        x, 2, return_mask=True), 2), 1, True,
                    shape=(1, 2, 4, 4), tags=("nn",)))
    register(OpSpec("unfold_op", lambda x: F.unfold(x, 2), 1, True,
                    shape=(1, 2, 4, 4), tags=("nn",)))
    register(OpSpec("fold_op",
                    lambda x: F.fold(x, [4, 4], [2, 2]), 1, True,
                    shape=(1, 8, 9), tags=("nn",)))
    register(OpSpec("pixel_shuffle_op",
                    lambda x: F.pixel_shuffle(x, 2), 1, True,
                    shape=(1, 4, 3, 3), tags=("nn",)))
    register(OpSpec("pixel_unshuffle_op",
                    lambda x: F.pixel_unshuffle(x, 2), 1, True,
                    shape=(1, 1, 4, 4), tags=("nn",)))
    register(OpSpec("channel_shuffle_op",
                    lambda x: F.channel_shuffle(x, 2), 1, True,
                    shape=(1, 4, 3, 3), tags=("nn",)))
    register(OpSpec("interpolate_op",
                    lambda x: F.interpolate(x, scale_factor=2,
                                            mode="bilinear"), 1, True,
                    shape=(1, 2, 3, 3), tags=("nn",)))
    register(OpSpec("grid_sample_op",
                    lambda x, g: F.grid_sample(x, g), 2, True,
                    shapes=((1, 2, 4, 4), (1, 3, 3, 2)),
                    domains=(_SAFE, _UNIT), rtol=1e-4, tags=("nn",)))
    register(OpSpec("affine_grid_op",
                    lambda t: F.affine_grid(t, [1, 1, 3, 3]), 1, True,
                    shape=(1, 2, 3), tags=("nn",)))
    register(OpSpec("glu_op", F.glu, 1, True, shape=(3, 4), tags=("nn",)))
    register(OpSpec("swiglu_op", lambda x: F.swiglu(x), 1, True,
                    shape=(3, 4), tags=("nn",)))
    register(OpSpec("prelu_op",
                    lambda x, w: F.prelu(x, w), 2, True,
                    shapes=((2, 3), (1,)), tags=("nn",)))
    register(OpSpec("temporal_shift_op",
                    lambda x: F.temporal_shift(x, 2), 1, True,
                    shape=(4, 4, 3, 3), tags=("nn",)))
    register(OpSpec("pad_reflect",
                    lambda x: F.pad(x, [1, 1, 1, 1], mode="reflect"), 1,
                    True, shape=(1, 2, 3, 3), tags=("nn",)))
    register(OpSpec("zeropad2d_op", lambda x: F.zeropad2d(x, [1, 1, 1, 1]),
                    1, True, shape=(1, 2, 3, 3), tags=("nn",)))
    register(OpSpec("dropout_eval",
                    lambda x: F.dropout(x, 0.5, training=False), 1, True,
                    ref=lambda x: x, tags=("nn",)))
    register(OpSpec("affine_channel_op",
                    lambda x, s, b: ex.affine_channel(x, s, b), 3, True,
                    shapes=((1, 2, 3, 3), (2,), (2,)), tags=("nn",)))
    register(OpSpec("bilinear_op",
                    lambda a, b, w: F.bilinear(a, b, w), 3, True,
                    shapes=((3, 2), (3, 4), (5, 2, 4)), rtol=1e-4,
                    tags=("nn",)))

    # ---- losses
    register(OpSpec("bce", lambda p, t: F.binary_cross_entropy(
        m.sigmoid(p), m.sigmoid(t)), 2, True, no_grad_inputs=(1,), tags=("loss",)))
    register(OpSpec("bce_logits",
                    lambda p, t: F.binary_cross_entropy_with_logits(
                        p, m.sigmoid(t)), 2, True, no_grad_inputs=(1,), tags=("loss",)))
    register(OpSpec("nll", lambda lp, i: F.nll_loss(
        F.log_softmax(lp), i), 2, True, shapes=((4, 5), (4,)),
        int_inputs=(1,), int_high=5, tags=("loss",)))
    register(OpSpec("cross_entropy_op", lambda lg, i: F.cross_entropy(
        lg, i), 2, True, shapes=((4, 5), (4,)), int_inputs=(1,),
        int_high=5, tags=("loss",)))
    register(OpSpec("margin_ranking",
                    lambda a, b, y: F.margin_ranking_loss(
                        a, b, m.sign(y)), 3, True, no_grad_inputs=(2,), tags=("loss",)))
    register(OpSpec("soft_margin", lambda x, y: F.soft_margin_loss(
        x, m.sign(y)), 2, True, no_grad_inputs=(1,), tags=("loss",)))
    register(OpSpec("triplet_margin",
                    lambda a, p, n2: F.triplet_margin_loss(a, p, n2), 3,
                    True, shapes=((3, 4), (3, 4), (3, 4)), tags=("loss",)))
    register(OpSpec("hinge_loss_op", lambda x, y: ex.hinge_loss(
        x, (m.sign(y) + 1) / 2), 2, True, no_grad_inputs=(1,), tags=("loss",)))
    register(OpSpec("poisson_nll", lambda x, y: F.poisson_nll_loss(
        x, m.abs(y)), 2, True, no_grad_inputs=(1,), tags=("loss",)))
    register(OpSpec("gaussian_nll",
                    lambda x, y, v: F.gaussian_nll_loss(x, y, m.abs(v) + 0.1),
                    3, True, tags=("loss",)))
    register(OpSpec("multi_label_soft_margin",
                    lambda x, y: F.multi_label_soft_margin_loss(
                        x, (m.sign(y) + 1) / 2), 2, True, no_grad_inputs=(1,), tags=("loss",)))
    register(OpSpec("square_error_cost",
                    F.square_error_cost, 2, True,
                    ref=lambda a, b: (a - b) ** 2, tags=("loss",)))
    register(OpSpec("log_loss",
                    lambda p, t: F.log_loss(m.sigmoid(p), m.sigmoid(t)), 2,
                    True, tags=("loss",)))
    register(OpSpec("dice_loss",
                    lambda p, i: F.dice_loss(F.softmax(p), i), 2, True,
                    shapes=((3, 5), (3, 1)), int_inputs=(1,), int_high=5,
                    tags=("loss",)))
    register(OpSpec("npair",
                    lambda a, p: F.npair_loss(a, p, pd.to_tensor(
                        np.arange(3).astype("int64"))), 2, True,
                    shapes=((3, 4), (3, 4)), rtol=1e-4, tags=("loss",)))
    register(OpSpec("label_smooth_op",
                    lambda lab: F.label_smooth(lab), 1, True,
                    shape=(3, 5), domain=(0.0, 1.0),
                    ref=lambda lab: 0.9 * lab + 0.1 / 5, tags=("loss",)))

    # ---- search / sampling tails
    register(OpSpec("nonzero", lambda x: pd.nonzero(x > 0)[0] if isinstance(
        pd.nonzero(x > 0), (list, tuple)) else pd.nonzero(x > 0), 1, False,
        bf16=False, tags=("search",)))
    register(OpSpec("index_sample",
                    lambda x, i: pd.index_sample(x, i), 2, True,
                    shapes=((3, 4), (3, 2)), int_inputs=(1,), int_high=4,
                    ref=lambda x, i: np.take_along_axis(x, i, 1),
                    tags=("search",)))
    register(OpSpec("take", lambda x, i: pd.take(x, i), 2, True,
                    shapes=((3, 4), (3,)), int_inputs=(1,), int_high=10,
                    ref=lambda x, i: x.ravel()[i], tags=("search",)))
    register(OpSpec("gather_tree", lambda i, p: F.gather_tree(i, p), 2,
                    False, shapes=((3, 2, 4), (3, 2, 4)),
                    int_inputs=(0, 1), int_high=4, bf16=False,
                    tags=("search",)))
    register(OpSpec("viterbi_decode",
                    lambda pot, trans: __import__(
                        "paddle_tpu.text.viterbi", fromlist=["viterbi_decode"]
                    ).viterbi_decode(pot, trans, pd.to_tensor(
                        np.array([3, 3], "int64")))[0], 2, False,
                    shapes=((2, 3, 4), (4, 4)), bf16=False,
                    tags=("search",)))
    register(OpSpec("searchsorted_right",
                    lambda s, v: pd.searchsorted(mp.sort(s), v, right=True),
                    2, False, shapes=((5,), (3,)),
                    domains=((0.0, 1.0), (0.0, 1.0)), bf16=False,
                    ref=lambda s, v: np.searchsorted(np.sort(s), v,
                                                     side="right"),
                    tags=("search",)))

    # ---- fft / signal (forward parity vs numpy)
    register(OpSpec("fft_abs", lambda x: pd.fft.fft(x).abs(), 1, True,
                    shape=(8,), ref=lambda x: np.abs(np.fft.fft(x)),
                    rtol=1e-4, bf16=False, tags=("fft",)))
    register(OpSpec("rfft_abs", lambda x: pd.fft.rfft(x).abs(), 1, True,
                    shape=(8,), ref=lambda x: np.abs(np.fft.rfft(x)),
                    rtol=1e-4, bf16=False, tags=("fft",)))
    register(OpSpec("fft2_abs", lambda x: pd.fft.fft2(x).abs(), 1, True,
                    shape=(4, 4), ref=lambda x: np.abs(np.fft.fft2(x)),
                    rtol=1e-4, bf16=False, tags=("fft",)))
    register(OpSpec("fftshift", lambda x: pd.fft.fftshift(x), 1, True,
                    shape=(6,), ref=np.fft.fftshift, bf16=False,
                    tags=("fft",)))

    # ---- edit distance / sequence (forward-only, host-side)
    register(OpSpec("edit_distance_op",
                    lambda h, r2: ex.edit_distance(h, r2)[0], 2, False,
                    shapes=((2, 5), (2, 4)), int_inputs=(0, 1), int_high=4,
                    bf16=False, tags=("sequence",)))

    # ---- keepdim / axis variants (distinct compiled shapes)
    register(OpSpec("sum_axis_keepdim",
                    lambda x: r.sum(x, axis=1, keepdim=True), 1, True,
                    shape=(3, 4), ref=lambda x: x.sum(1, keepdims=True),
                    tags=("reduction",)))
    register(OpSpec("mean_axis", lambda x: r.mean(x, axis=0), 1, True,
                    shape=(3, 4), ref=lambda x: x.mean(0),
                    tags=("reduction",)))
    register(OpSpec("max_axis", lambda x: r.max(x, axis=1), 1, True,
                    shape=(3, 4), ref=lambda x: x.max(1),
                    tags=("reduction",)))
    register(OpSpec("softmax_axis0", lambda x: F.softmax(x, axis=0), 1,
                    True, ref=lambda x: _np_softmax(x, 0),
                    tags=("activation",)))
    register(OpSpec("cumsum_rev_axis", lambda x: pd.cumsum(x, 1), 1, True,
                    shape=(3, 4), ref=lambda x: np.cumsum(x, 1),
                    tags=("manipulation",)))
    register(OpSpec("squeeze_all", lambda x: mp.squeeze(x), 1, True,
                    shape=(1, 3, 1), ref=np.squeeze, tags=("manipulation",)))
    register(OpSpec("amax_axis", lambda x: r.amax(x, axis=1), 1, True,
                    shape=(3, 4), ref=lambda x: x.max(1),
                    tags=("reduction",)))
    register(OpSpec("prod_axis", lambda x: r.prod(x, axis=1), 1, True,
                    shape=(3, 4), domain=_POS, ref=lambda x: x.prod(1),
                    rtol=1e-4, tags=("reduction",)))
    register(OpSpec("matmul_tn",
                    lambda a, b: la.matmul(a, b, transpose_x=True), 2, True,
                    shapes=((3, 2), (3, 4)), ref=lambda a, b: a.T @ b,
                    tags=("linalg",)))
    register(OpSpec("matmul_nt",
                    lambda a, b: la.matmul(a, b, transpose_y=True), 2, True,
                    shapes=((2, 3), (4, 3)), ref=lambda a, b: a @ b.T,
                    tags=("linalg",)))

    # ---- geometric segment ops
    register(OpSpec("segment_sum",
                    lambda x: pd.geometric.segment_sum(
                        x, pd.to_tensor(np.array([0, 0, 1], "int64"))), 1,
                    True, shape=(3, 4), tags=("geometric",)))
    register(OpSpec("segment_mean",
                    lambda x: pd.geometric.segment_mean(
                        x, pd.to_tensor(np.array([0, 0, 1], "int64"))), 1,
                    True, shape=(3, 4), tags=("geometric",)))
    register(OpSpec("segment_max",
                    lambda x: pd.geometric.segment_max(
                        x, pd.to_tensor(np.array([0, 0, 1], "int64"))), 1,
                    True, shape=(3, 4), tags=("geometric",)))
