"""Reduction & search ops (≙ python/paddle/tensor/math.py reductions,
stat.py, search.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import dtype as dtypes
from ..core.dispatch import op_call
from ..core.tensor import Tensor
from ._helpers import norm_axis


def _red(jfn, opname, int_promote=False):
    def op(x, axis=None, keepdim=False, name=None, dtype=None):
        ax = norm_axis(axis)

        def f(a):
            if int_promote and dtypes.is_integer(a.dtype) and dtype is None:
                a = a.astype(jnp.int64)
            out = jfn(a, axis=ax, keepdims=keepdim)
            if dtype is not None:
                out = out.astype(dtypes.convert_dtype(dtype))
            return out

        return op_call(f, x, name=opname)

    op.__name__ = opname
    return op


sum = _red(jnp.sum, "sum", int_promote=True)
mean = _red(jnp.mean, "mean")
prod = _red(jnp.prod, "prod", int_promote=True)
amax = _red(jnp.max, "amax")
amin = _red(jnp.min, "amin")
nansum = _red(jnp.nansum, "nansum")
nanmean = _red(jnp.nanmean, "nanmean")
logsumexp = _red(jax.scipy.special.logsumexp, "logsumexp")


def max(x, axis=None, keepdim=False, name=None):
    ax = norm_axis(axis)
    return op_call(lambda a: jnp.max(a, axis=ax, keepdims=keepdim), x, name="max")


def min(x, axis=None, keepdim=False, name=None):
    ax = norm_axis(axis)
    return op_call(lambda a: jnp.min(a, axis=ax, keepdims=keepdim), x, name="min")


def all(x, axis=None, keepdim=False, name=None):
    ax = norm_axis(axis)
    return op_call(lambda a: jnp.all(a, axis=ax, keepdims=keepdim), x, name="all", n_diff=0)


def any(x, axis=None, keepdim=False, name=None):
    ax = norm_axis(axis)
    return op_call(lambda a: jnp.any(a, axis=ax, keepdims=keepdim), x, name="any", n_diff=0)


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    ax = norm_axis(axis)
    return op_call(lambda a: jnp.argmax(a, axis=ax, keepdims=keepdim).astype(
        dtypes.convert_dtype(dtype)), x, name="argmax", n_diff=0)


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    ax = norm_axis(axis)
    return op_call(lambda a: jnp.argmin(a, axis=ax, keepdims=keepdim).astype(
        dtypes.convert_dtype(dtype)), x, name="argmin", n_diff=0)


def count_nonzero(x, axis=None, keepdim=False, name=None):
    ax = norm_axis(axis)
    return op_call(lambda a: jnp.count_nonzero(a, axis=ax, keepdims=keepdim).astype(jnp.int64),
                   x, name="count_nonzero", n_diff=0)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = norm_axis(axis)
    return op_call(lambda a: jnp.std(a, axis=ax, ddof=1 if unbiased else 0, keepdims=keepdim),
                   x, name="std")


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = norm_axis(axis)
    return op_call(lambda a: jnp.var(a, axis=ax, ddof=1 if unbiased else 0, keepdims=keepdim),
                   x, name="var")


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    ax = norm_axis(axis)
    if mode == "avg":
        return op_call(lambda a: jnp.median(a, axis=ax, keepdims=keepdim),
                       x, name="median")
    if mode != "min":
        raise ValueError(f"median mode must be 'avg' or 'min', got {mode!r}")

    # mode='min': even-length inputs take the LOWER middle element; with an
    # integer axis the reference also returns its index
    def f(a):
        if ax is None:
            flat = a.reshape(-1)
            val = jnp.sort(flat)[(flat.shape[0] - 1) // 2]
            return val.reshape((1,) * a.ndim) if keepdim else val
        mid = (a.shape[ax] - 1) // 2
        order = jnp.argsort(a, axis=ax)
        ind = jnp.take(order, mid, axis=ax)
        val = jnp.take_along_axis(a, jnp.expand_dims(ind, ax), axis=ax)
        if not keepdim:
            val = jnp.squeeze(val, axis=ax)
        else:
            ind = jnp.expand_dims(ind, ax)
        return val, ind.astype(jnp.int64)

    return op_call(f, x, name="median_min", n_diff=0)


def nanmedian(x, axis=None, keepdim=False, name=None):
    ax = norm_axis(axis)
    return op_call(lambda a: jnp.nanmedian(a, axis=ax, keepdims=keepdim), x, name="nanmedian")


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    ax = norm_axis(axis)
    qv = q._data if isinstance(q, Tensor) else jnp.asarray(q)
    return op_call(lambda a: jnp.quantile(a, qv, axis=ax, keepdims=keepdim,
                                          method=interpolation), x, name="quantile")


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    ax = norm_axis(axis)
    return op_call(lambda a: jnp.nanquantile(a, jnp.asarray(q), axis=ax, keepdims=keepdim),
                   x, name="nanquantile")


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def f(a):
        ax = axis % a.ndim
        srt = jnp.sort(a, axis=ax)
        idx = jnp.argsort(a, axis=ax)
        val = jnp.take(srt, k - 1, axis=ax)
        ind = jnp.take(idx, k - 1, axis=ax)
        if keepdim:
            val = jnp.expand_dims(val, ax)
            ind = jnp.expand_dims(ind, ax)
        return val, ind.astype(jnp.int64)

    return op_call(f, x, name="kthvalue")


def mode(x, axis=-1, keepdim=False, name=None):
    def f(a):
        ax = axis % a.ndim
        av = jnp.moveaxis(a, ax, -1)
        cnt = jnp.sum(av[..., :, None] == av[..., None, :], axis=-1)
        best = jnp.argmax(cnt, axis=-1)
        val = jnp.take_along_axis(av, best[..., None], axis=-1)[..., 0]
        idx = jnp.argmax(av == val[..., None], axis=-1)
        if keepdim:
            val = jnp.expand_dims(val, ax)
            idx = jnp.expand_dims(idx, ax)
        return val, idx.astype(jnp.int64)

    return op_call(f, x, name="mode")


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    if isinstance(k, Tensor):
        k = int(k.item())

    def f(a):
        ax = axis % a.ndim
        am = jnp.moveaxis(a, ax, -1)
        if largest:
            v, i = jax.lax.top_k(am, k)
        else:
            v, i = jax.lax.top_k(-am, k)
            v = -v
        return jnp.moveaxis(v, -1, ax), jnp.moveaxis(i, -1, ax).astype(jnp.int64)

    return op_call(f, x, name="topk")


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    ax = norm_axis(axis)

    def f(a):
        if p in ("fro", None) and (ax is None or isinstance(ax, tuple)):
            return jnp.sqrt(jnp.sum(a * a, axis=ax, keepdims=keepdim))
        if p == "nuc":
            return jnp.sum(jnp.linalg.svd(a, compute_uv=False), axis=-1, keepdims=keepdim)
        pv = float(p)
        if pv == float("inf"):
            return jnp.max(jnp.abs(a), axis=ax, keepdims=keepdim)
        if pv == float("-inf"):
            return jnp.min(jnp.abs(a), axis=ax, keepdims=keepdim)
        if pv == 0:
            return jnp.sum(a != 0, axis=ax, keepdims=keepdim).astype(a.dtype)
        return jnp.power(jnp.sum(jnp.power(jnp.abs(a), pv), axis=ax, keepdims=keepdim),
                         1.0 / pv)

    return op_call(f, x, name="norm")


def dist(x, y, p=2, name=None):
    return norm(x - y, p=p)


def histogram(x, bins=100, min=0, max=0, name=None):
    def f(a):
        lo, hi = (min, max) if (min != 0 or max != 0) else (a.min(), a.max())
        h, _ = jnp.histogram(a, bins=bins, range=(lo, hi))
        return h.astype(jnp.int64)

    return op_call(f, x, name="histogram", n_diff=0)


def bincount(x, weights=None, minlength=0, name=None):
    if weights is None:
        return op_call(lambda a: jnp.bincount(a, minlength=minlength), x,
                       name="bincount", n_diff=0)
    return op_call(lambda a, w: jnp.bincount(a, w, minlength=minlength), x, weights,
                   name="bincount", n_diff=0)
