"""Tensor creation ops (≙ python/paddle/tensor/creation.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtypes
from ..core.dispatch import op_call
from ..core.tensor import Tensor, to_tensor
from ._helpers import raw


def _dt(dtype):
    return dtypes.convert_dtype(dtype) if dtype is not None else dtypes.get_default_dtype()


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.tolist())
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(raw(s)) if not isinstance(s, int) else s for s in shape)


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape(shape), _dt(dtype)), _internal=True)


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape(shape), _dt(dtype)), _internal=True)


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None and isinstance(fill_value, bool):
        dtype = dtypes.bool_
    elif dtype is None and isinstance(fill_value, int):
        dtype = dtypes.int64
    return Tensor(jnp.full(_shape(shape), fill_value, _dt(dtype)), _internal=True)


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype, name)


def zeros_like(x, dtype=None, name=None):
    return op_call(lambda a: jnp.zeros_like(a, dtype=dtypes.convert_dtype(dtype)), x,
                   name="zeros_like", n_diff=0)


def ones_like(x, dtype=None, name=None):
    return op_call(lambda a: jnp.ones_like(a, dtype=dtypes.convert_dtype(dtype)), x,
                   name="ones_like", n_diff=0)


def full_like(x, fill_value, dtype=None, name=None):
    return op_call(lambda a: jnp.full_like(a, fill_value, dtype=dtypes.convert_dtype(dtype)),
                   x, name="full_like", n_diff=0)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype, name)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    start, end, step = (v.item() if isinstance(v, Tensor) else v for v in (start, end, step))
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = dtypes.int64 if all(
            isinstance(v, (int, np.integer)) for v in (start, end, step)) else dtypes.get_default_dtype()
    return Tensor(jnp.arange(start, end, step, dtypes.convert_dtype(dtype)), _internal=True)


def linspace(start, stop, num, dtype=None, name=None):
    start, stop = (v.item() if isinstance(v, Tensor) else v for v in (start, stop))
    return Tensor(jnp.linspace(start, stop, int(num), dtype=_dt(dtype)), _internal=True)


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor(jnp.logspace(raw(start), raw(stop), int(num), base=base, dtype=_dt(dtype)),
                  _internal=True)


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(int(num_rows), num_columns and int(num_columns), dtype=_dt(dtype)),
                  _internal=True)


def diag(x, offset=0, padding_value=0, name=None):
    def f(a):
        if a.ndim == 1:
            out = jnp.diag(a, k=offset)
            if padding_value != 0:
                mask = jnp.diag(jnp.ones_like(a, bool), k=offset)
                out = jnp.where(mask, out, padding_value)
            return out
        return jnp.diagonal(a, offset=offset)

    return op_call(f, x, name="diag")


def diagflat(x, offset=0, name=None):
    return op_call(lambda a: jnp.diagflat(a, k=offset), x, name="diagflat")


def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None):
    def f(a):
        n = a.shape[-1] + abs(offset)
        out = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
        idx = jnp.arange(a.shape[-1])
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        out = out.at[..., r, c].set(a)
        src = list(range(out.ndim))
        d1 = dim1 % out.ndim
        d2 = dim2 % out.ndim
        perm = [d for d in src if d not in (out.ndim - 2, out.ndim - 1)]
        # place last two dims at dim1/dim2
        res = []
        it = iter(perm)
        for d in range(out.ndim):
            if d == d1:
                res.append(out.ndim - 2)
            elif d == d2:
                res.append(out.ndim - 1)
            else:
                res.append(next(it))
        return jnp.transpose(out, res) if res != src else out

    return op_call(f, x, name="diag_embed")


def tril(x, diagonal=0, name=None):
    return op_call(lambda a: jnp.tril(a, k=diagonal), x, name="tril")


def triu(x, diagonal=0, name=None):
    return op_call(lambda a: jnp.triu(a, k=diagonal), x, name="triu")


def tril_indices(row, col, offset=0, dtype="int64", name=None):
    r, c = jnp.tril_indices(row, k=offset, m=col)
    return Tensor(jnp.stack([r, c]).astype(dtypes.convert_dtype(dtype)), _internal=True)


def triu_indices(row, col=None, offset=0, dtype="int64", name=None):
    r, c = jnp.triu_indices(row, k=offset, m=col or row)
    return Tensor(jnp.stack([r, c]).astype(dtypes.convert_dtype(dtype)), _internal=True)


def meshgrid(*args, name=None):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = args[0]
    # differentiable (grad of each grid = sum over the broadcast axes),
    # like the reference meshgrid_grad
    out = op_call(lambda *a: tuple(jnp.meshgrid(*a, indexing="ij")), *args,
                  name="meshgrid", n_diff=len(args))
    return list(out) if isinstance(out, tuple) else [out]


def assign(x, output=None, name=None):
    out = op_call(lambda a: a + 0 if hasattr(a, "dtype") else jnp.asarray(a), x, name="assign") \
        if isinstance(x, Tensor) else Tensor(x)
    if output is not None:
        output._assign_raw(out._data)
        return output
    return out


def clone(x, name=None):
    return assign(x)


def complex(real, imag, name=None):
    return op_call(jax.lax.complex, real, imag, name="complex")


def polar(abs_, angle, name=None):
    return op_call(lambda r, t: jax.lax.complex(r * jnp.cos(t), r * jnp.sin(t)),
                   abs_, angle, name="polar")


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..core.tensor import Parameter

    attr_init = getattr(attr, "initializer", None) if attr is not None \
        else None
    if default_initializer is not None and attr_init is None:
        t = default_initializer(shape, dtype)
        data = t._data if isinstance(t, Tensor) else jnp.asarray(t)
    else:
        data = jnp.zeros(_shape(shape), dtypes.convert_dtype(dtype)) if is_bias else \
            jax.random.normal(jax.random.PRNGKey(0), _shape(shape)).astype(
                dtypes.convert_dtype(dtype)) * 0.02
    p = Parameter(data, _internal=True)
    if attr_init is not None:
        # ParamAttr initializer takes priority (reference semantics);
        # nn.initializer instances mutate the parameter in place
        attr_init(p)
    if attr is not None and getattr(attr, "trainable", True) is False:
        p.stop_gradient = True
    return p
