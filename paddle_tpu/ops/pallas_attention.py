"""Pallas TPU flash attention (fwd + bwd), the fusion-library equivalent.

Reference parity: paddle's flash attention surface
(python/paddle/nn/functional/flash_attention.py:358 `flash_attention`,
:1139 `scaled_dot_product_attention`) backed by the CUDA fusion library
(paddle/phi/kernels/fusion/gpu). Here the kernel is written directly for the
TPU memory hierarchy: Q/K/V tiles are streamed HBM->VMEM by the Pallas grid
pipeline, the online-softmax running state (m, l, acc) lives in VMEM scratch
that persists across the innermost (kv) grid steps, and every matmul hits the
MXU in f32 accumulation.

Layout convention at this level is [batch, heads, seq, head_dim]; the public
wrapper accepts paddle's [batch, seq, heads, head_dim] and transposes.

On non-TPU backends the same kernels run in Pallas interpreter mode, which is
how tests/test_pallas_attention.py checks numerics against the XLA softmax
composition on the CPU mesh.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

# ONE copy of the platform/x64 rules shared with pallas_norm.py — the
# x64-toggle behavior is subtle (real-TPU-only; see _pallas_common)
from ._pallas_common import ceil_to as _ceil_to
from ._pallas_common import interpret as _interpret
from ._pallas_common import pltpu
from ._pallas_common import x64_guard as _x64_guard

# measured on v5e (b8 h16 s1024 d64): 128x128 blocks ran at 3.0 TFLOP/s —
# grid-overhead/VPU-bound; 512x1024 reached 5.9 before mask specialization
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 1024
# paddle_tpu enables jax x64 globally, so bare python floats would trace as
# STRONG f64 constants inside the kernels — Mosaic cannot legalize the
# resulting f64->f32 truncf on real TPUs. Every scalar here must therefore
# be an explicitly-typed np.float32.
_NEG_INF = np.float32(-1e30)
_ZERO = np.float32(0.0)
_ONE = np.float32(1.0)


def _block_dispatch(compute, *, causal, qi, ki, nk, sq, sk,
                    block_q, block_k, force_masked=False):
    """Shared interior/boundary dispatch for the three flash kernels.

    compute(masked): masked=False runs the lean path (no iota/compare/
    where — most causal blocks sit strictly below the diagonal and need no
    masking; the VPU softmax chain is the kernel's cost). Blocks entirely
    above the diagonal are skipped. `qi`/`ki` are the q-block / kv-block
    program ids; causal visibility is `col <= row + (sk - sq)` (last q row
    aligned with last kv col). force_masked (varlen): the kv bound is a
    runtime value — every surviving block masks."""
    if force_masked:
        if causal:
            row1_off = qi * block_q + block_q - 1 + (sk - sq)

            @pl.when(ki * block_k <= row1_off)
            def _fm():
                compute(True)
        else:
            compute(True)
        return
    sk_aligned = (sk % block_k) == 0
    if causal:
        row0_off = qi * block_q + (sk - sq)
        row1_off = qi * block_q + block_q - 1 + (sk - sq)
        col0 = ki * block_k
        col1 = col0 + block_k - 1
        # interior: every column visible from every row AND fully in range
        interior = (col1 <= row0_off) & \
            ((col1 < sk) if not sk_aligned else (col0 >= 0))

        @pl.when(col0 <= row1_off)
        def _():  # not entirely above the diagonal
            @pl.when(interior)
            def _i():
                compute(False)

            @pl.when(~interior)
            def _b():
                compute(True)
    else:
        if sk_aligned:
            compute(False)
        else:
            @pl.when(ki < nk - 1)
            def _i():
                compute(False)

            @pl.when(ki == nk - 1)
            def _b():
                compute(True)


# ----------------------------------------------------------------- forward

def _fwd_kernel(q_ref, k_ref, v_ref, *refs,
                scale, causal, sq, sk, block_q, block_k, has_lens=False):
    # NOTE: program_id(2) is only materialized under `causal` — Mosaic on
    # real TPUs fails to legalize kernels carrying unused program-id-derived
    # values ('tpu.truncf'/'func.return'), so nothing dead may be traced.
    # has_lens (varlen): an extra [1,128] lens_ref input carries this
    # batch's kv length; every block takes the masked path with the dynamic
    # bound (the flash-varlen kernel the reference ships as a CUDA variant,
    # flash_attention.py:358).
    if has_lens:
        lens_ref, o_ref, lse_ref, acc, m_s, l_s = refs
    else:
        o_ref, lse_ref, acc, m_s, l_s = refs
    ki = pl.program_id(3)
    nk = pl.num_programs(3)
    # only bound under causal (used in mask + block-skip predicate): an
    # unused program_id value fails Mosaic legalization, and program_id
    # cannot be called inside a pl.when body in interpreter mode
    qi = pl.program_id(2) if causal else None

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_s[:] = jnp.full_like(m_s, _NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)

    def compute(masked):
        """masked=False → interior block: no iota/compare/where — the VPU
        cost of flash attention is the softmax chain, and on a causal
        S=1024 run ~80% of blocks need no masking at all (the FlashAttention
        block-specialization; the reference fusion library does the same on
        CUDA)."""
        q = q_ref[0, 0].astype(jnp.float32) * np.float32(scale)  # [bq, d]
        k = k_ref[0, 0]                                      # [bk, d]
        s = jax.lax.dot_general(
            q, k.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)              # [bq, bk]
        if masked:
            cols = ki * block_k + \
                jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            if has_lens:
                mask = cols < lens_ref[0, 0, 0]
            else:
                mask = cols < sk
            if causal:
                # causal offset aligns the last q row with the last kv col
                rows = qi * block_q + \
                    jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
                mask = mask & (cols <= rows + (sk - sq))
            s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_s[:, :1]                                  # [bq, 1]
        l_prev = l_s[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                               # [bq, bk]
        if masked:
            # a FULLY-masked row has m_new == -1e30, which cancels in
            # exp(s - m_new) → p = 1; zero it explicitly (empty rows must
            # produce l == 0 → output 0). Interior blocks can't be empty.
            p = jnp.where(mask, p, _ZERO)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        v = v_ref[0, 0]                                      # [bk, d]
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # [bq, d]
        acc[:] = acc[:] * alpha + pv
        m_s[:] = jnp.broadcast_to(m_new, m_s.shape)
        l_s[:] = jnp.broadcast_to(l_new, l_s.shape)

    _block_dispatch(compute, causal=causal, qi=qi, ki=ki, nk=nk,
                    sq=sq, sk=sk, block_q=block_q, block_k=block_k,
                    force_masked=has_lens)

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_s[:, :1]
        safe_l = jnp.where(l == _ZERO, _ONE, l)
        o_ref[0, 0] = (acc[:] / safe_l).astype(o_ref.dtype)
        # lse is lane-replicated [bq, 128]: TPU block tiling requires the
        # last two block dims be (8k, 128)-aligned, so per-row stats ride a
        # full lane dim (the standard TPU flash-kernel layout)
        lse_ref[0, 0] = jnp.broadcast_to(
            m_s[:, :1] + jnp.log(safe_l), lse_ref[0, 0].shape)


def _lens_lanes(lens, b):
    """[B] int32 kv lengths -> [B, 8, 128] tile-replicated block input
    (Mosaic requires the last two block dims be (8, 128)-aligned)."""
    return jnp.broadcast_to(lens.astype(jnp.int32)[:, None, None],
                            (b, 8, 128))


def _flash_forward(q, k, v, causal, block_q, block_k, lens=None):
    """q,k,v: [B, H, S, D] (same H — GQA expanded by caller).

    Returns (o [B,H,S,D], lse_lanes [B,H,Sq_padded,1]) — per-row softmax
    stats (lane-replication for the TPU tiling happens inside the kernel
    and is sliced away here to keep residuals small). lens: optional [B]
    per-batch kv length (varlen)."""
    # paddle_tpu runs jax with x64 enabled; trace the pallas program with
    # x64 OFF so index-map/kernel literals stay i32/f32 (Mosaic cannot
    # legalize stray i64/f64 values on real TPUs)
    with _x64_guard():
        return _flash_forward_x32(q, k, v, causal, block_q, block_k, lens)


def _flash_forward_x32(q, k, v, causal, block_q, block_k, lens=None):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    scale = 1.0 / math.sqrt(d)
    sq_p = _ceil_to(sq, block_q)
    sk_p = _ceil_to(sk, block_k)
    d_p = _ceil_to(d, 128)
    qp = jnp.pad(q, ((0, 0), (0, 0), (0, sq_p - sq), (0, d_p - d)))
    kp = jnp.pad(k, ((0, 0), (0, 0), (0, sk_p - sk), (0, d_p - d)))
    vp = jnp.pad(v, ((0, 0), (0, 0), (0, sk_p - sk), (0, d_p - d)))
    nq, nk = sq_p // block_q, sk_p // block_k
    has_lens = lens is not None

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, sq=sq, sk=sk,
        block_q=block_q, block_k=block_k, has_lens=has_lens)
    in_specs = [
        pl.BlockSpec((1, 1, block_q, d_p), lambda b, h, qi, ki: (b, h, qi, 0)),
        pl.BlockSpec((1, 1, block_k, d_p), lambda b, h, qi, ki: (b, h, ki, 0)),
        pl.BlockSpec((1, 1, block_k, d_p), lambda b, h, qi, ki: (b, h, ki, 0)),
    ]
    args = [qp, kp, vp]
    if has_lens:
        in_specs.append(
            pl.BlockSpec((1, 8, 128), lambda b, h, qi, ki: (b, 0, 0)))
        args.append(_lens_lanes(lens, b))
    o, lse = pl.pallas_call(
        kernel,
        grid=(b, h, nq, nk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d_p), lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 128), lambda b, h, qi, ki: (b, h, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq_p, d_p), q.dtype),
            jax.ShapeDtypeStruct((b, h, sq_p, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d_p), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=_interpret(),
    )(*args)
    # keep one lane in the residuals (128x smaller); backward re-broadcasts
    return o[:, :, :sq, :d], lse[:, :, :, :1]


# ----------------------------------------------------------------- backward

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *refs,
                   scale, causal, sq, sk, block_q, block_k, has_lens=False):
    if has_lens:
        lens_ref, dq_ref, dq_acc = refs
    else:
        dq_ref, dq_acc = refs
    # like _fwd_kernel: nothing dead may be traced (Mosaic legalization)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)
    qi = pl.program_id(2) if causal else None

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def compute(masked):
        q = q_ref[0, 0].astype(jnp.float32) * np.float32(scale)
        k = k_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        lse = lse_ref[0, 0][:, :1]                            # [bq, 1] of lanes
        if masked:
            cols = ki * block_k + \
                jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            mask = (cols < lens_ref[0, 0, 0]) if has_lens else (cols < sk)
            if causal:
                rows = qi * block_q + \
                    jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
                mask = mask & (cols <= rows + (sk - sq))
        p = jnp.exp(s - lse)                                  # [bq, bk]
        if masked:
            # empty rows have lse == -1e30 (cancels the mask value): zero p
            p = jnp.where(mask, p, _ZERO)
        do = do_ref[0, 0].astype(jnp.float32)                 # [bq, d]
        v = v_ref[0, 0].astype(jnp.float32)                   # [bk, d]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        delta = delta_ref[0, 0][:, :1]
        ds = p * (dp - delta) * np.float32(scale)             # [bq, bk]
        dq_acc[:] = dq_acc[:] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    _block_dispatch(compute, causal=causal, qi=qi, ki=ki, nk=nk,
                    sq=sq, sk=sk, block_q=block_q, block_k=block_k,
                    force_masked=has_lens)

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *refs,
                    scale, causal, sq, sk, block_q, block_k, has_lens=False):
    if has_lens:
        lens_ref, dk_ref, dv_ref, dk_acc, dv_acc = refs
    else:
        dk_ref, dv_ref, dk_acc, dv_acc = refs
    # grid here is (b, h, ki, qi): kv blocks outer, q blocks inner
    ki = pl.program_id(2)
    qi = pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    k_start = ki * block_k
    nk = pl.num_programs(2)

    def compute(masked):
        q = q_ref[0, 0].astype(jnp.float32) * np.float32(scale)  # [bq, d]
        k = k_ref[0, 0].astype(jnp.float32)                   # [bk, d]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        lse = lse_ref[0, 0][:, :1]
        if masked:
            cols = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            mask = (cols < lens_ref[0, 0, 0]) if has_lens else (cols < sk)
            if causal:
                rows = qi * block_q + \
                    jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
                mask = mask & (cols <= rows + (sk - sq))
            s = jnp.where(mask, s, _NEG_INF)
        p = jnp.exp(s - lse)                                  # [bq, bk]
        if masked:
            # empty q rows have lse == -1e30 (cancels the mask value): p
            # must be zeroed or they pollute dk/dv accumulations
            p = jnp.where(mask, p, _ZERO)
        do = do_ref[0, 0].astype(jnp.float32)                 # [bq, d]
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        delta = delta_ref[0, 0][:, :1]
        # `q` here is pre-scaled by 1/sqrt(d), which is exactly dk's scale
        # factor — so ds must NOT be scaled again
        ds = p * (dp - delta)                                 # [bq, bk]
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    _block_dispatch(compute, causal=causal, qi=qi, ki=ki, nk=nk,
                    sq=sq, sk=sk, block_q=block_q, block_k=block_k,
                    force_masked=has_lens)

    @pl.when(qi == nq - 1)
    def _finish():
        # dk picked up the q-side 1/sqrt(d) scale through `q`; already applied
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_backward(q, k, v, o, lse_lanes, do, causal, block_q, block_k,
                    lens=None):
    with _x64_guard():  # see _flash_forward
        return _flash_backward_x32(q, k, v, o, lse_lanes, do, causal,
                                   block_q, block_k, lens)


def _flash_backward_x32(q, k, v, o, lse_lanes, do, causal, block_q, block_k,
                        lens=None):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    scale = 1.0 / math.sqrt(d)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)

    sq_p = _ceil_to(sq, block_q)
    sk_p = _ceil_to(sk, block_k)
    d_p = _ceil_to(d, 128)
    pad4 = lambda x, s: jnp.pad(x, ((0, 0), (0, 0), (0, s - x.shape[2]), (0, d_p - d)))
    qp, kp, vp = pad4(q, sq_p), pad4(k, sk_p), pad4(v, sk_p)
    dop = pad4(do, sq_p)
    lsep = jnp.broadcast_to(lse_lanes, (b, h, lse_lanes.shape[2], 128))
    deltap = jnp.broadcast_to(
        jnp.pad(delta, ((0, 0), (0, 0), (0, sq_p - sq)))[..., None],
        (b, h, sq_p, 128))
    nq, nk = sq_p // block_q, sk_p // block_k

    has_lens = lens is not None
    common = dict(scale=scale, causal=causal, sq=sq, sk=sk,
                  block_q=block_q, block_k=block_k, has_lens=has_lens)
    q_spec = pl.BlockSpec((1, 1, block_q, d_p), lambda b, h, qi, ki: (b, h, qi, 0))
    k_spec = pl.BlockSpec((1, 1, block_k, d_p), lambda b, h, qi, ki: (b, h, ki, 0))
    r_spec = pl.BlockSpec((1, 1, block_q, 128), lambda b, h, qi, ki: (b, h, qi, 0))
    lens_spec = pl.BlockSpec((1, 8, 128), lambda b, h, qi, ki: (b, 0, 0))
    extra = [_lens_lanes(lens, b)] if has_lens else []

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **common),
        grid=(b, h, nq, nk),
        in_specs=[q_spec, k_spec, k_spec, q_spec, r_spec, r_spec]
        + ([lens_spec] if has_lens else []),
        out_specs=[q_spec],
        out_shape=[jax.ShapeDtypeStruct((b, h, sq_p, d_p), q.dtype)],
        scratch_shapes=[pltpu.VMEM((block_q, d_p), jnp.float32)],
        interpret=_interpret(),
    )(qp, kp, vp, dop, lsep, deltap, *extra)[0]

    # dkv kernel: kv blocks outer, q blocks inner
    q_spec2 = pl.BlockSpec((1, 1, block_q, d_p), lambda b, h, ki, qi: (b, h, qi, 0))
    k_spec2 = pl.BlockSpec((1, 1, block_k, d_p), lambda b, h, ki, qi: (b, h, ki, 0))
    r_spec2 = pl.BlockSpec((1, 1, block_q, 128), lambda b, h, ki, qi: (b, h, qi, 0))
    lens_spec2 = pl.BlockSpec((1, 8, 128), lambda b, h, ki, qi: (b, 0, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, **common),
        grid=(b, h, nk, nq),
        in_specs=[q_spec2, k_spec2, k_spec2, q_spec2, r_spec2, r_spec2]
        + ([lens_spec2] if has_lens else []),
        out_specs=[k_spec2, k_spec2],
        out_shape=[jax.ShapeDtypeStruct((b, h, sk_p, d_p), k.dtype),
                   jax.ShapeDtypeStruct((b, h, sk_p, d_p), v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d_p), jnp.float32),
                        pltpu.VMEM((block_k, d_p), jnp.float32)],
        interpret=_interpret(),
    )(qp, kp, vp, dop, lsep, deltap, *extra)
    return (dq[:, :, :sq, :d], dk[:, :, :sk, :d], dv[:, :, :sk, :d])


# ----------------------------------------------------------- differentiable op

#: residual names consulted by the attention-resident remat policy
#: (fleet recompute(policy="flash_resident")): under
#: jax.checkpoint(save_only_these_names(*FLASH_RESIDUAL_NAMES)) the flash
#: outputs + softmax stats are SAVED across fwd/bwd, so the rematerialized
#: backward never re-runs the forward flash kernel — only the cheap
#: surrounding GEMM/pointwise chains are recomputed (q/k/v regenerate from
#: the qkv projections). Outside a checkpoint context checkpoint_name is
#: the identity, so naming costs nothing on the normal path.
FLASH_RESIDUAL_NAMES = ("flash_attn_out", "flash_attn_lse")


def _name_flash_residuals(o, lse):
    from jax.ad_checkpoint import checkpoint_name

    return (checkpoint_name(o, FLASH_RESIDUAL_NAMES[0]),
            checkpoint_name(lse, FLASH_RESIDUAL_NAMES[1]))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, block_q, block_k, bwd_block_q=None,
           bwd_block_k=None):
    # bwd_block_q/bwd_block_k: block sizes for the dq/dkv kernels — the
    # backward's best block shape differs from the forward's at long
    # sequence (round-6 autotune), defaulting to the forward's choice
    o, _ = _flash_forward(q, k, v, causal, block_q, block_k)
    return o


def _flash_fwd_rule(q, k, v, causal, block_q, block_k, bwd_block_q=None,
                    bwd_block_k=None):
    o, lse = _flash_forward(q, k, v, causal, block_q, block_k)
    o, lse = _name_flash_residuals(o, lse)
    return o, (q, k, v, o, lse)


def _flash_bwd_rule(causal, block_q, block_k, bwd_block_q, bwd_block_k,
                    res, g):
    q, k, v, o, lse = res
    return _flash_backward(q, k, v, o, lse, g, causal,
                           bwd_block_q or block_q, bwd_block_k or block_k)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash_varlen(q, k, v, lens, causal, block_q, block_k):
    o, _ = _flash_forward(q, k, v, causal, block_q, block_k, lens=lens)
    return o


def _flash_varlen_fwd(q, k, v, lens, causal, block_q, block_k):
    o, lse = _flash_forward(q, k, v, causal, block_q, block_k, lens=lens)
    o, lse = _name_flash_residuals(o, lse)
    return o, (q, k, v, o, lse, lens)


def _flash_varlen_bwd(causal, block_q, block_k, res, g):
    q, k, v, o, lse, lens = res
    dq, dk, dv = _flash_backward(q, k, v, o, lse, g, causal, block_q,
                                 block_k, lens=lens)
    return dq, dk, dv, jnp.zeros(lens.shape, jax.dtypes.float0)


_flash_varlen.defvjp(_flash_varlen_fwd, _flash_varlen_bwd)


_TUNE_CACHE: dict = {}
#: candidate (block_q, block_k) pairs, ordered by prior; the autotuner
#: measures each on the first sighting of a shape family and pins the best
#: (≙ reference conv/attention runtime autotuning,
#: /root/reference/paddle/phi/kernels/autotune/auto_tune_base.h)
_TUNE_CANDIDATES = ((512, 1024), (256, 1024), (512, 512), (1024, 1024),
                    (256, 512))
#: long-sequence candidates (sq or sk >= 4096): the 512x1024 default was
#: tuned at s1024 and is wrong at s4096/s8192 — longer kv blocks amortize
#: the per-grid-step overhead over the much larger kv axis, and the probe
#: machinery discards anything that overflows VMEM on this chip
_TUNE_CANDIDATES_LONG = ((512, 1024), (1024, 1024), (512, 2048),
                         (1024, 2048), (256, 2048), (2048, 1024),
                         (512, 512))
#: ceiling accepted from the DISK cache: a poisoned/corrupt entry may not
#: force Mosaic failures (ADVICE round 5) — anything outside
#: [128, _TUNE_BLOCK_MAX] multiples of 128 is dropped on load
_TUNE_BLOCK_MAX = 4096


def _tune_candidates(sq, sk):
    return _TUNE_CANDIDATES_LONG if max(sq, sk) >= 4096 else _TUNE_CANDIDATES


def _valid_blocks(vv):
    """True iff vv is a loadable tune-cache value: a (block_q, block_k) or
    (fwd_q, fwd_k, bwd_q, bwd_k) sequence of positive multiples of 128 no
    larger than _TUNE_BLOCK_MAX (the validated shape of every candidate the
    tuner itself can emit)."""
    if not isinstance(vv, (list, tuple)) or len(vv) not in (2, 4):
        return False
    return all(isinstance(x, int) and not isinstance(x, bool)
               and 0 < x <= _TUNE_BLOCK_MAX and x % 128 == 0 for x in vv)


def _norm4(hit):
    """Normalize a tune-cache value to the 4-tuple (fwd_q, fwd_k, bwd_q,
    bwd_k) contract — legacy 2-element entries reuse the fwd pair for the
    backward. None passes through (caller falls back to defaults)."""
    if hit is None:
        return None
    return tuple(hit) if len(hit) == 4 else (*hit, *hit)
#: probe failures that mean "this candidate doesn't compile/fit here"
#: (Mosaic lowering rejections, VMEM overflow) — anything else propagates
try:
    from jax.errors import JaxRuntimeError as _PROBE_RT_ERROR
except ImportError:  # pragma: no cover — older jax
    _PROBE_RT_ERROR = RuntimeError
_PROBE_ERRORS = (ValueError, NotImplementedError, _PROBE_RT_ERROR)


def _tune_cache_path():
    """Disk location of the tune cache. USER-scoped by default
    (~/.cache/paddle_tpu) rather than the world-writable /tmp compile-cache
    dir — a cross-user-poisoned entry must not be able to pin bad block
    shapes (ADVICE round 5); override with PADDLE_TPU_TUNE_CACHE_DIR."""
    import os

    base = os.environ.get("PADDLE_TPU_TUNE_CACHE_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "paddle_tpu")
    return os.path.join(base, "flash_tune_cache_v2.json")


_TUNE_DISK_LOADED = False


def _parse_tune_entries(payload):
    """{key-string: blocks} pairs -> validated {key-tuple: blocks-tuple}.
    Keys are 'kind|sq|sk|d|dtype|causal'; values must pass _valid_blocks
    (positive multiples of 128) — anything else is dropped, never raised:
    a poisoned disk entry costs at most a re-tune."""
    out = {}
    if not isinstance(payload, dict):
        return out
    for ks, vv in payload.items():
        try:
            kind, sq, sk, d, dt, causal = ks.split("|")
            key = (kind, int(sq), int(sk), int(d), dt, causal == "True")
        except (ValueError, AttributeError):
            continue
        if _valid_blocks(vv):
            out[key] = tuple(vv)
    return out


def _tune_cache_load():
    global _TUNE_DISK_LOADED
    if _TUNE_DISK_LOADED:
        return
    _TUNE_DISK_LOADED = True
    import json

    try:
        with open(_tune_cache_path()) as f:
            payload = json.load(f)
    except (OSError, ValueError):
        return  # missing or corrupt/concurrent write: re-tune
    for key, vv in _parse_tune_entries(payload).items():
        _TUNE_CACHE.setdefault(key, vv)


def _tune_cache_store():
    import json
    import os
    import tempfile

    path = _tune_cache_path()
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        # merge-on-store: re-load and union so concurrent tuners working on
        # different shape families stop dropping each other's entries
        # (ADVICE round 5); in-process results win on conflict
        merged = {}
        try:
            with open(path) as f:
                merged.update(_parse_tune_entries(json.load(f)))
        except (OSError, ValueError):
            pass
        merged.update(_TUNE_CACHE)
        payload = {"|".join(map(str, k)): list(v) for k, v in merged.items()}
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
        with os.fdopen(fd, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)  # atomic vs concurrent processes
    except OSError:  # read-only fs: cache stays per-process
        pass


def _probe_time(fn, *args):
    """Median-of-groups timing of a compiled probe (single 2-iteration
    timings over the axon tunnel swing ±3x — bench.py:55). Returns inf when
    the candidate doesn't compile/fit (Mosaic rejection, VMEM overflow)."""
    import statistics
    import time as _time

    try:
        out = fn(*args)
        jax.device_get(jnp.ravel(out)[0])  # compile + warm
        groups = []
        for _ in range(3):
            t0 = _time.perf_counter()
            for _ in range(2):
                out = fn(*args)
            jax.device_get(jnp.ravel(out)[0])
            groups.append(_time.perf_counter() - t0)
        return statistics.median(groups)
    except _PROBE_ERRORS:
        return float("inf")


def _rank_candidates(sq, sk, probe):
    """Measure every (clamped, deduped) candidate pair with `probe(bq, bk)`
    and return the fastest, or None when none compiled."""
    cands = _tune_candidates(sq, sk)
    seen = set()
    best, best_t = None, float("inf")
    for bq_c, bk_c in cands:
        bq = min(bq_c, _ceil_to(sq, 128))
        bk = min(bk_c, _ceil_to(sk, 128))
        if (bq, bk) in seen:
            continue  # clamping collapsed this candidate into an earlier one
        seen.add((bq, bk))
        dt = probe(bq, bk)
        if dt < best_t:
            best, best_t = (bq, bk), dt
    return best


def _autotune_blocks(q, k, v, causal):
    """Pick (fwd_block_q, fwd_block_k, bwd_block_q, bwd_block_k) for this
    (sq, sk, d, dtype, causal) family. Off the TPU (interpret mode) or when
    FLAGS_flash_autotune is off, the measured v5e default is used.

    Round-6 shape: candidates are SEQ-LENGTH-KEYED (the 512x1024 default
    was tuned at s1024 and loses at s4096/s8192 where longer kv blocks
    amortize grid overhead), and with FLAGS_flash_tune_bwd_split the
    backward dq/dkv kernels are tuned separately — stage 1 ranks
    forward-only probes, stage 2 ranks fwd+bwd probes with the forward
    pinned to its winner (the bwd kernels' arithmetic-intensity profile
    differs: 5 matmuls per block pair vs the forward's 2). Winners are
    cached in-process AND in the user-scoped disk cache."""
    from ..core.flags import flag

    sq, sk, d = q.shape[2], k.shape[2], q.shape[3]
    key = ("flash", sq, sk, d, str(q.dtype), causal)
    hit = _norm4(_TUNE_CACHE.get(key))
    if hit is not None:
        return hit
    if _interpret() or isinstance(q, jax.core.Tracer) \
            or not flag("FLAGS_flash_autotune"):
        return (DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K,
                DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K)
    _tune_cache_load()
    hit = _norm4(_TUNE_CACHE.get(key))
    if hit is not None:
        return hit

    def probe_fwd(bq, bk):
        fn = jax.jit(lambda a, b, c2: _flash(a, b, c2, causal, bq, bk))
        return _probe_time(fn, q, k, v)

    fwd = _rank_candidates(sq, sk, probe_fwd) \
        or (DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K)
    bwd = fwd
    if flag("FLAGS_flash_tune_bwd_split"):
        def probe_bwd(bq, bk):
            fn = jax.jit(lambda a, b, c2: jax.grad(
                lambda aa: jnp.sum(
                    _flash(aa, b, c2, causal, fwd[0], fwd[1], bq, bk)
                    .astype(jnp.float32)))(a))
            return _probe_time(fn, q, k, v)

        bwd = _rank_candidates(sq, sk, probe_bwd) or fwd
    best = (*fwd, *bwd)
    _TUNE_CACHE[key] = best
    _tune_cache_store()
    return best


def flash_attention_raw(q, k, v, causal=False,
                        block_q=None, block_k=None):
    """jax-level flash attention on [B, H, S, D] arrays (GQA expanded here).
    block_q/block_k default to the per-shape autotuned choice — the
    autotuner keys candidates by sequence length and tunes the backward
    dq/dkv block pair separately from the forward's (explicit block_q/
    block_k pin BOTH directions, the pre-round-6 behavior)."""
    hq, hk = q.shape[1], k.shape[1]
    if hq != hk:
        rep = hq // hk
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    cap_q = _ceil_to(q.shape[2], 128)
    cap_k = _ceil_to(k.shape[2], 128)
    if block_q is None or block_k is None:
        tq, tk, tbq, tbk = _autotune_blocks(q, k, v, causal)
        return _flash(q, k, v, causal,
                      min(block_q or tq, cap_q), min(block_k or tk, cap_k),
                      min(block_q or tbq, cap_q), min(block_k or tbk, cap_k))
    bq = min(block_q, cap_q)
    bk = min(block_k, cap_k)
    return _flash(q, k, v, causal, bq, bk)


def flash_attention_varlen_raw(q, k, v, kv_lens, causal=False,
                               block_q=DEFAULT_BLOCK_Q,
                               block_k=DEFAULT_BLOCK_K):
    """Varlen flash: [B, H, S, D] padded batch + [B] int32 kv lengths —
    key columns >= kv_lens[b] are masked INSIDE the kernel (the flash-varlen
    path the reference ships as a CUDA variant, flash_attention.py:358).
    Query rows beyond a sequence's length produce zeros; callers drop them.
    """
    hq, hk = q.shape[1], k.shape[1]
    if hq != hk:
        rep = hq // hk
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    bq = min(block_q, _ceil_to(q.shape[2], 128))
    bk = min(block_k, _ceil_to(k.shape[2], 128))
    return _flash_varlen(q, k, v, jnp.asarray(kv_lens, jnp.int32), causal,
                         bq, bk)


def ensure_tuned(b, h, sq, sk, d, dtype, causal):
    """Eagerly autotune the block choice for a shape family using synthetic
    operands. Called from framework code BEFORE entering any trace (jit
    traces can only consult the cache); a no-op off-TPU, on repeat shapes,
    or with FLAGS_flash_autotune off."""
    from ..core.flags import flag

    key = ("flash", sq, sk, d, str(jnp.dtype(dtype)), causal)
    if key in _TUNE_CACHE or _interpret() or not flag("FLAGS_flash_autotune"):
        hit = _norm4(_TUNE_CACHE.get(key))
        if hit is not None:
            return hit
        return (DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K,
                DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K)
    kk = jax.random.PRNGKey(0)
    # one head is enough to rank block choices; keeps probe cost tiny
    q = jax.random.normal(kk, (1, 1, sq, d), jnp.dtype(dtype))
    k = jax.random.normal(kk, (1, 1, sk, d), jnp.dtype(dtype))
    v = jax.random.normal(kk, (1, 1, sk, d), jnp.dtype(dtype))
    return _autotune_blocks(q, k, v, causal)


def flash_attention_op(query, key, value, is_causal=False):
    """Framework-level op on paddle-layout [B, S, H, D] Tensors; tape-recorded."""
    from ..core.dispatch import op_call

    qd = query._data if hasattr(query, "_data") else query
    if not isinstance(qd, jax.core.Tracer) and not _interpret():
        kd = key._data if hasattr(key, "_data") else key
        ensure_tuned(int(qd.shape[0]), int(qd.shape[2]), int(qd.shape[1]),
                     int(kd.shape[1]), int(qd.shape[3]), qd.dtype, is_causal)

    def f(q, k, v):
        qt = jnp.swapaxes(q, 1, 2)
        kt = jnp.swapaxes(k, 1, 2)
        vt = jnp.swapaxes(v, 1, 2)
        out = flash_attention_raw(qt, kt, vt, causal=is_causal)
        return jnp.swapaxes(out, 1, 2)

    return op_call(f, query, key, value, name="flash_attention", n_diff=3)


# ------------------------------------------------- flashmask (block-sparse)

def _fm_block_dispatch(compute, *, causal, row0, row1, col0, col1,
                       smin, smax, sq, sk, block_k):
    """Shared fwd/dq/dkv FlashMask block dispatch: skip kv blocks whose
    max start row precedes the q block entirely; run the lean no-mask path
    when the whole block is visible (its LAST row precedes every start);
    only straddling blocks pay the iota/where chain. ONE definition so the
    forward's visibility can never desynchronize from the backward's."""
    run = row0 < smax
    if causal:
        run = run & (col0 <= row1 + (sk - sq))
    sk_aligned = (sk % block_k) == 0
    interior = (row1 < smin) & ((col1 < sk) if not sk_aligned else
                                (col0 >= 0))
    if causal:
        interior = interior & (col1 <= row0 + (sk - sq))

    @pl.when(run)
    def _run():
        @pl.when(interior)
        def _i():
            compute(False)

        @pl.when(~interior)
        def _b():
            compute(True)


def _fm_mask(start_ref, shape, row0, col0, causal, sq, sk):
    """Per-element FlashMask visibility for a straddling block: key column
    j visible to query row i iff i < start[j] (and in range / causal)."""
    cols = col0 + jax.lax.broadcasted_iota(jnp.int32, shape, 1)
    rows = row0 + jax.lax.broadcasted_iota(jnp.int32, shape, 0)
    starts = start_ref[0, 0, 0:1, :]
    mask = (cols < sk) & (rows < starts)
    if causal:
        mask = mask & (cols <= rows + (sk - sq))
    return mask


def _fm_fwd_kernel(q_ref, k_ref, v_ref, start_ref, smin_ref, smax_ref,
                   o_ref, lse_ref, acc, m_s, l_s, *,
                   scale, causal, sq, sk, block_q, block_k):
    """FlashMask forward: per-COLUMN start rows (causal LTS form — key col
    j is blocked for query rows i >= start[j]) consulted at BLOCK
    granularity: kv blocks whose max start row is <= the block's first
    query row are skipped outright (no MXU work, the splash/FlashMask
    block-skip idea); blocks fully visible take the lean no-mask path;
    only straddling blocks pay the iota/where chain."""
    ki = pl.program_id(3)
    nk = pl.num_programs(3)
    qi = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_s[:] = jnp.full_like(m_s, _NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)

    row0 = qi * block_q
    row1 = row0 + block_q - 1
    col0 = ki * block_k
    col1 = col0 + block_k - 1
    smax = smax_ref[0, 0, 0, 0, 0]
    smin = smin_ref[0, 0, 0, 0, 0]

    def compute(masked):
        q = q_ref[0, 0].astype(jnp.float32) * np.float32(scale)
        k = k_ref[0, 0]
        s = jax.lax.dot_general(
            q, k.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if masked:
            mask = _fm_mask(start_ref, s.shape, row0, col0, causal, sq, sk)
            s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_s[:, :1]
        l_prev = l_s[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        if masked:
            p = jnp.where(mask, p, _ZERO)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        v = v_ref[0, 0]
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc[:] = acc[:] * alpha + pv
        m_s[:] = jnp.broadcast_to(m_new, m_s.shape)
        l_s[:] = jnp.broadcast_to(l_new, l_s.shape)

    _fm_block_dispatch(compute, causal=causal, row0=row0, row1=row1,
                       col0=col0, col1=col1, smin=smin, smax=smax,
                       sq=sq, sk=sk, block_k=block_k)

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_s[:, :1]
        safe_l = jnp.where(l == _ZERO, _ONE, l)
        o_ref[0, 0] = (acc[:] / safe_l).astype(o_ref.dtype)
        lse_ref[0, 0] = jnp.broadcast_to(
            m_s[:, :1] + jnp.log(safe_l), lse_ref[0, 0].shape)


def _fm_starts_prep(start_rows, b, h, sk, sk_p, nk, block_k):
    """Shared fwd/bwd prep of the per-column start rows: tile-replicated
    per-column starts [B,H,8,Sk_p] plus per-kv-block min/max start
    [B,H,nk,8,128] driving the block-skip / lean-path predicates."""
    sr = start_rows.astype(jnp.int32)                  # [B, H, Sk]
    # padded key columns get start 0 => visible to no row (blocked)
    sr_p = jnp.pad(sr, ((0, 0), (0, 0), (0, sk_p - sk)))
    # per-column starts, sublane-replicated: [B, H, 8, Sk_p]
    sr_lanes = jnp.broadcast_to(sr_p[:, :, None, :], (b, h, 8, sk_p))
    # per-kv-block min/max start: [B, H, nk] -> tile-replicated
    blk = sr_p.reshape(b, h, nk, block_k)
    smin = jnp.min(jnp.where(jnp.arange(block_k)[None, None, None, :]
                             + jnp.arange(nk)[None, None, :, None]
                             * block_k < sk, blk, jnp.int32(2**30)), axis=-1)
    smax = jnp.max(blk, axis=-1)
    smin_l = jnp.broadcast_to(smin[:, :, :, None, None], (b, h, nk, 8, 128))
    smax_l = jnp.broadcast_to(smax[:, :, :, None, None], (b, h, nk, 8, 128))
    return sr_lanes, smin_l, smax_l


def _fm_forward_x32(q, k, v, start_rows, causal, block_q, block_k):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    scale = 1.0 / math.sqrt(d)
    sq_p = _ceil_to(sq, block_q)
    sk_p = _ceil_to(sk, block_k)
    d_p = _ceil_to(d, 128)
    qp = jnp.pad(q, ((0, 0), (0, 0), (0, sq_p - sq), (0, d_p - d)))
    kp = jnp.pad(k, ((0, 0), (0, 0), (0, sk_p - sk), (0, d_p - d)))
    vp = jnp.pad(v, ((0, 0), (0, 0), (0, sk_p - sk), (0, d_p - d)))
    nq, nk = sq_p // block_q, sk_p // block_k
    sr_lanes, smin_l, smax_l = _fm_starts_prep(start_rows, b, h, sk, sk_p,
                                               nk, block_k)

    kernel = functools.partial(
        _fm_fwd_kernel, scale=scale, causal=causal, sq=sq, sk=sk,
        block_q=block_q, block_k=block_k)
    o, lse = pl.pallas_call(
        kernel,
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d_p),
                         lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d_p),
                         lambda b, h, qi, ki: (b, h, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d_p),
                         lambda b, h, qi, ki: (b, h, ki, 0)),
            pl.BlockSpec((1, 1, 8, block_k),
                         lambda b, h, qi, ki: (b, h, 0, ki)),
            pl.BlockSpec((1, 1, 1, 8, 128),
                         lambda b, h, qi, ki: (b, h, ki, 0, 0)),
            pl.BlockSpec((1, 1, 1, 8, 128),
                         lambda b, h, qi, ki: (b, h, ki, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d_p),
                         lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 128),
                         lambda b, h, qi, ki: (b, h, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq_p, d_p), q.dtype),
            jax.ShapeDtypeStruct((b, h, sq_p, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d_p), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=_interpret(),
    )(qp, kp, vp, sr_lanes, smin_l, smax_l)
    # keep one lane of the softmax stats for the backward (see _flash_forward)
    return o[:, :, :sq, :d], lse[:, :, :, :1]


def _fm_dense_ref(q, k, v, start_rows, causal):
    """Dense O(S^2) reference of the flashmask semantics. NOT on any
    production path — kept as the numerics oracle for
    tests/test_pallas_attention.py; fwd AND bwd run the block-skipping
    Pallas kernels."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(d)
    rows = jnp.arange(sq)[None, None, :, None]
    mask = rows < start_rows[:, :, None, :]
    if causal:
        cols = jnp.arange(sk)[None, None, None, :]
        mask = mask & (cols <= rows + (sk - sq))
    scores = jnp.where(mask, scores, jnp.finfo(scores.dtype).min)
    p = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    empty = ~jnp.any(mask, axis=-1, keepdims=True)
    p = jnp.where(empty, jnp.zeros_like(p), p)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _fm_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      start_ref, smin_ref, smax_ref, dq_ref, dq_acc, *,
                      scale, causal, sq, sk, block_q, block_k):
    """dq with the SAME block-skip predicates as the flashmask forward:
    kv blocks fully blocked for this q block contribute nothing and are
    skipped before touching the MXU; fully-visible blocks take the lean
    no-iota path; only straddling blocks pay the mask chain. The fwd LSE
    is reused — no dense [Sq,Sk] softmax is ever materialized."""
    ki = pl.program_id(3)
    nk = pl.num_programs(3)
    qi = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    row0 = qi * block_q
    row1 = row0 + block_q - 1
    col0 = ki * block_k
    col1 = col0 + block_k - 1
    smax = smax_ref[0, 0, 0, 0, 0]
    smin = smin_ref[0, 0, 0, 0, 0]

    def compute(masked):
        q = q_ref[0, 0].astype(jnp.float32) * np.float32(scale)
        k = k_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        lse = lse_ref[0, 0][:, :1]
        if masked:
            mask = _fm_mask(start_ref, s.shape, row0, col0, causal, sq, sk)
        p = jnp.exp(s - lse)
        if masked:
            # fully-blocked rows carry lse == -1e30 which cancels in the
            # exp; zero them (and padded/blocked columns) explicitly
            p = jnp.where(mask, p, _ZERO)
        do = do_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        delta = delta_ref[0, 0][:, :1]
        ds = p * (dp - delta) * np.float32(scale)
        dq_acc[:] = dq_acc[:] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    _fm_block_dispatch(compute, causal=causal, row0=row0, row1=row1,
                       col0=col0, col1=col1, smin=smin, smax=smax,
                       sq=sq, sk=sk, block_k=block_k)

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _fm_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                       start_ref, smin_ref, smax_ref, dk_ref, dv_ref,
                       dk_acc, dv_acc, *,
                       scale, causal, sq, sk, block_q, block_k):
    # grid is (b, h, ki, qi): kv blocks outer, q blocks inner
    ki = pl.program_id(2)
    qi = pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    row0 = qi * block_q
    row1 = row0 + block_q - 1
    col0 = ki * block_k
    col1 = col0 + block_k - 1
    smax = smax_ref[0, 0, 0, 0, 0]
    smin = smin_ref[0, 0, 0, 0, 0]

    def compute(masked):
        q = q_ref[0, 0].astype(jnp.float32) * np.float32(scale)
        k = k_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        lse = lse_ref[0, 0][:, :1]
        if masked:
            mask = _fm_mask(start_ref, s.shape, row0, col0, causal, sq, sk)
            s = jnp.where(mask, s, _NEG_INF)
        p = jnp.exp(s - lse)
        if masked:
            # blocked/padded rows have lse == -1e30 (cancels the mask
            # value): p must be zeroed or they pollute dk/dv
            p = jnp.where(mask, p, _ZERO)
        do = do_ref[0, 0].astype(jnp.float32)
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        delta = delta_ref[0, 0][:, :1]
        # `q` is pre-scaled by 1/sqrt(d) = dk's scale; ds NOT scaled again
        ds = p * (dp - delta)
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    _fm_block_dispatch(compute, causal=causal, row0=row0, row1=row1,
                       col0=col0, col1=col1, smin=smin, smax=smax,
                       sq=sq, sk=sk, block_k=block_k)

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _fm_backward_x32(q, k, v, o, lse_lanes, do, start_rows, causal,
                     block_q, block_k):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    scale = 1.0 / math.sqrt(d)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)

    sq_p = _ceil_to(sq, block_q)
    sk_p = _ceil_to(sk, block_k)
    d_p = _ceil_to(d, 128)
    pad4 = lambda x, s: jnp.pad(
        x, ((0, 0), (0, 0), (0, s - x.shape[2]), (0, d_p - d)))
    qp, kp, vp = pad4(q, sq_p), pad4(k, sk_p), pad4(v, sk_p)
    dop = pad4(do, sq_p)
    lsep = jnp.broadcast_to(lse_lanes, (b, h, lse_lanes.shape[2], 128))
    deltap = jnp.broadcast_to(
        jnp.pad(delta, ((0, 0), (0, 0), (0, sq_p - sq)))[..., None],
        (b, h, sq_p, 128))
    nq, nk = sq_p // block_q, sk_p // block_k
    sr_lanes, smin_l, smax_l = _fm_starts_prep(start_rows, b, h, sk, sk_p,
                                               nk, block_k)

    common = dict(scale=scale, causal=causal, sq=sq, sk=sk,
                  block_q=block_q, block_k=block_k)
    q_spec = pl.BlockSpec((1, 1, block_q, d_p),
                          lambda b, h, qi, ki: (b, h, qi, 0))
    k_spec = pl.BlockSpec((1, 1, block_k, d_p),
                          lambda b, h, qi, ki: (b, h, ki, 0))
    r_spec = pl.BlockSpec((1, 1, block_q, 128),
                          lambda b, h, qi, ki: (b, h, qi, 0))
    sr_spec = pl.BlockSpec((1, 1, 8, block_k),
                           lambda b, h, qi, ki: (b, h, 0, ki))
    mm_spec = pl.BlockSpec((1, 1, 1, 8, 128),
                           lambda b, h, qi, ki: (b, h, ki, 0, 0))
    dq = pl.pallas_call(
        functools.partial(_fm_bwd_dq_kernel, **common),
        grid=(b, h, nq, nk),
        in_specs=[q_spec, k_spec, k_spec, q_spec, r_spec, r_spec,
                  sr_spec, mm_spec, mm_spec],
        out_specs=[q_spec],
        out_shape=[jax.ShapeDtypeStruct((b, h, sq_p, d_p), q.dtype)],
        scratch_shapes=[pltpu.VMEM((block_q, d_p), jnp.float32)],
        interpret=_interpret(),
    )(qp, kp, vp, dop, lsep, deltap, sr_lanes, smin_l, smax_l)[0]

    # dkv kernel: kv blocks outer, q blocks inner
    q_spec2 = pl.BlockSpec((1, 1, block_q, d_p),
                           lambda b, h, ki, qi: (b, h, qi, 0))
    k_spec2 = pl.BlockSpec((1, 1, block_k, d_p),
                           lambda b, h, ki, qi: (b, h, ki, 0))
    r_spec2 = pl.BlockSpec((1, 1, block_q, 128),
                           lambda b, h, ki, qi: (b, h, qi, 0))
    sr_spec2 = pl.BlockSpec((1, 1, 8, block_k),
                            lambda b, h, ki, qi: (b, h, 0, ki))
    mm_spec2 = pl.BlockSpec((1, 1, 1, 8, 128),
                            lambda b, h, ki, qi: (b, h, ki, 0, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_fm_bwd_dkv_kernel, **common),
        grid=(b, h, nk, nq),
        in_specs=[q_spec2, k_spec2, k_spec2, q_spec2, r_spec2, r_spec2,
                  sr_spec2, mm_spec2, mm_spec2],
        out_specs=[k_spec2, k_spec2],
        out_shape=[jax.ShapeDtypeStruct((b, h, sk_p, d_p), k.dtype),
                   jax.ShapeDtypeStruct((b, h, sk_p, d_p), v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d_p), jnp.float32),
                        pltpu.VMEM((block_k, d_p), jnp.float32)],
        interpret=_interpret(),
    )(qp, kp, vp, dop, lsep, deltap, sr_lanes, smin_l, smax_l)
    return (dq[:, :, :sq, :d], dk[:, :, :sk, :d], dv[:, :, :sk, :d])


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flashmask(q, k, v, start_rows, causal, block_q, block_k,
               bwd_block_q=None, bwd_block_k=None):
    with _x64_guard():
        o, _ = _fm_forward_x32(q, k, v, start_rows, causal, block_q, block_k)
    return o


def _flashmask_fwd(q, k, v, start_rows, causal, block_q, block_k,
                   bwd_block_q=None, bwd_block_k=None):
    with _x64_guard():
        o, lse = _fm_forward_x32(q, k, v, start_rows, causal,
                                 block_q, block_k)
    o, lse = _name_flash_residuals(o, lse)
    return o, (q, k, v, o, lse, start_rows)


def _flashmask_bwd(causal, block_q, block_k, bwd_block_q, bwd_block_k,
                   res, g):
    q, k, v, o, lse, start_rows = res
    with _x64_guard():
        dq, dk, dv = _fm_backward_x32(q, k, v, o, lse, g, start_rows,
                                      causal, bwd_block_q or block_q,
                                      bwd_block_k or block_k)
    return dq, dk, dv, jnp.zeros(start_rows.shape, jax.dtypes.float0)


_flashmask.defvjp(_flashmask_fwd, _flashmask_bwd)


def _autotune_blocks_fm(q, k, v, start_rows, causal):
    """FlashMask twin of _autotune_blocks (cache kind 'flashmask'): the
    block-sparse kernels' best shape depends on the mask's blocked fraction
    as well as seq length, so they get their own probe family. Defaults
    (512, 512) off-TPU/in-trace — smaller kv blocks keep skippable
    granularity fine for sliding-window patterns."""
    from ..core.flags import flag

    sq, sk, d = q.shape[2], k.shape[2], q.shape[3]
    key = ("flashmask", sq, sk, d, str(q.dtype), causal)
    hit = _norm4(_TUNE_CACHE.get(key))
    if hit is not None:
        return hit
    if _interpret() or isinstance(q, jax.core.Tracer) \
            or isinstance(start_rows, jax.core.Tracer) \
            or not flag("FLAGS_flash_autotune"):
        return (DEFAULT_BLOCK_Q, 512, DEFAULT_BLOCK_Q, 512)
    _tune_cache_load()
    hit = _norm4(_TUNE_CACHE.get(key))
    if hit is not None:
        return hit

    def probe_fwd(bq, bk):
        fn = jax.jit(lambda a, b, c2, sr: _flashmask(a, b, c2, sr, causal,
                                                     bq, bk))
        return _probe_time(fn, q, k, v, start_rows)

    fwd = _rank_candidates(sq, sk, probe_fwd) or (DEFAULT_BLOCK_Q, 512)
    bwd = fwd
    if flag("FLAGS_flash_tune_bwd_split"):
        def probe_bwd(bq, bk):
            fn = jax.jit(lambda a, b, c2, sr: jax.grad(
                lambda aa: jnp.sum(
                    _flashmask(aa, b, c2, sr, causal, fwd[0], fwd[1],
                               bq, bk).astype(jnp.float32)))(a))
            return _probe_time(fn, q, k, v, start_rows)

        bwd = _rank_candidates(sq, sk, probe_bwd) or fwd
    best = (*fwd, *bwd)
    _TUNE_CACHE[key] = best
    _tune_cache_store()
    return best


def ensure_tuned_flashmask(sq, sk, d, dtype, causal, start_rows):
    """Eagerly autotune the FlashMask block choice for a shape family
    BEFORE entering a trace (the functional flashmask_attention path runs
    the kernel under jit, where only the cache can be consulted). Probes
    one head with the caller's actual start rows so the blocked fraction
    the tuner sees matches the workload; no-op off-TPU / on repeat shapes /
    with FLAGS_flash_autotune off."""
    from ..core.flags import flag

    key = ("flashmask", sq, sk, d, str(jnp.dtype(dtype)), causal)
    if key in _TUNE_CACHE or _interpret() or not flag("FLAGS_flash_autotune"):
        hit = _norm4(_TUNE_CACHE.get(key))
        if hit is not None:
            return hit
        return (DEFAULT_BLOCK_Q, 512, DEFAULT_BLOCK_Q, 512)
    kk = jax.random.PRNGKey(0)
    q = jax.random.normal(kk, (1, 1, sq, d), jnp.dtype(dtype))
    k = jax.random.normal(kk, (1, 1, sk, d), jnp.dtype(dtype))
    v = jax.random.normal(kk, (1, 1, sk, d), jnp.dtype(dtype))
    sr = jnp.asarray(start_rows, jnp.int32)[:1, :1, :]
    return _autotune_blocks_fm(q, k, v, sr, causal)


def flashmask_attention_raw(q, k, v, start_rows, causal=False,
                            block_q=None, block_k=None):
    """Block-sparse FlashMask attention on [B, H, S, D] arrays with
    per-column start rows [B, H, S_k] (causal LTS form). Forward AND
    backward skip fully-blocked kv blocks in Pallas kernels; the backward
    reuses the forward's LSE so no [Sq,Sk] softmax is ever materialized
    (≙ the reference's fused fwd+bwd flashmask CUDA family,
    nn/functional/flash_attention.py flashmask_attention). Block sizes
    default to the per-shape autotuned choice (cache kind 'flashmask',
    fwd and bwd tuned separately); explicit block_q/block_k pin both."""
    cap_q = _ceil_to(q.shape[2], 128)
    cap_k = _ceil_to(k.shape[2], 128)
    if block_q is None or block_k is None:
        tq, tk, tbq, tbk = _autotune_blocks_fm(q, k, v, start_rows, causal)
        return _flashmask(q, k, v, start_rows, causal,
                          min(block_q or tq, cap_q),
                          min(block_k or tk, cap_k),
                          min(block_q or tbq, cap_q),
                          min(block_k or tbk, cap_k))
    bq = min(block_q, cap_q)
    bk = min(block_k, cap_k)
    return _flashmask(q, k, v, start_rows, causal, bq, bk)
