"""Pallas TPU flash attention (fwd + bwd), the fusion-library equivalent.

Reference parity: paddle's flash attention surface
(python/paddle/nn/functional/flash_attention.py:358 `flash_attention`,
:1139 `scaled_dot_product_attention`) backed by the CUDA fusion library
(paddle/phi/kernels/fusion/gpu). Here the kernel is written directly for the
TPU memory hierarchy: Q/K/V tiles are streamed HBM->VMEM by the Pallas grid
pipeline, the online-softmax running state (m, l, acc) lives in VMEM scratch
that persists across the innermost (kv) grid steps, and every matmul hits the
MXU in f32 accumulation.

Layout convention at this level is [batch, heads, seq, head_dim]; the public
wrapper accepts paddle's [batch, seq, heads, head_dim] and transposes.

On non-TPU backends the same kernels run in Pallas interpreter mode, which is
how tests/test_pallas_attention.py checks numerics against the XLA softmax
composition on the CPU mesh.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # pltpu imports fail cleanly on backends without TPU support
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

# measured on v5e (b8 h16 s1024 d64): 128x128 blocks ran at 3.0 TFLOP/s —
# grid-overhead/VPU-bound; 512x1024 reached 5.9 before mask specialization
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 1024
# paddle_tpu enables jax x64 globally, so bare python floats would trace as
# STRONG f64 constants inside the kernels — Mosaic cannot legalize the
# resulting f64->f32 truncf on real TPUs. Every scalar here must therefore
# be an explicitly-typed np.float32.
_NEG_INF = np.float32(-1e30)
_ZERO = np.float32(0.0)
_ONE = np.float32(1.0)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _ceil_to(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _block_dispatch(compute, *, causal, qi, ki, nk, sq, sk,
                    block_q, block_k, force_masked=False):
    """Shared interior/boundary dispatch for the three flash kernels.

    compute(masked): masked=False runs the lean path (no iota/compare/
    where — most causal blocks sit strictly below the diagonal and need no
    masking; the VPU softmax chain is the kernel's cost). Blocks entirely
    above the diagonal are skipped. `qi`/`ki` are the q-block / kv-block
    program ids; causal visibility is `col <= row + (sk - sq)` (last q row
    aligned with last kv col). force_masked (varlen): the kv bound is a
    runtime value — every surviving block masks."""
    if force_masked:
        if causal:
            row1_off = qi * block_q + block_q - 1 + (sk - sq)

            @pl.when(ki * block_k <= row1_off)
            def _fm():
                compute(True)
        else:
            compute(True)
        return
    sk_aligned = (sk % block_k) == 0
    if causal:
        row0_off = qi * block_q + (sk - sq)
        row1_off = qi * block_q + block_q - 1 + (sk - sq)
        col0 = ki * block_k
        col1 = col0 + block_k - 1
        # interior: every column visible from every row AND fully in range
        interior = (col1 <= row0_off) & \
            ((col1 < sk) if not sk_aligned else (col0 >= 0))

        @pl.when(col0 <= row1_off)
        def _():  # not entirely above the diagonal
            @pl.when(interior)
            def _i():
                compute(False)

            @pl.when(~interior)
            def _b():
                compute(True)
    else:
        if sk_aligned:
            compute(False)
        else:
            @pl.when(ki < nk - 1)
            def _i():
                compute(False)

            @pl.when(ki == nk - 1)
            def _b():
                compute(True)


# ----------------------------------------------------------------- forward

def _fwd_kernel(q_ref, k_ref, v_ref, *refs,
                scale, causal, sq, sk, block_q, block_k, has_lens=False):
    # NOTE: program_id(2) is only materialized under `causal` — Mosaic on
    # real TPUs fails to legalize kernels carrying unused program-id-derived
    # values ('tpu.truncf'/'func.return'), so nothing dead may be traced.
    # has_lens (varlen): an extra [1,128] lens_ref input carries this
    # batch's kv length; every block takes the masked path with the dynamic
    # bound (the flash-varlen kernel the reference ships as a CUDA variant,
    # flash_attention.py:358).
    if has_lens:
        lens_ref, o_ref, lse_ref, acc, m_s, l_s = refs
    else:
        o_ref, lse_ref, acc, m_s, l_s = refs
    ki = pl.program_id(3)
    nk = pl.num_programs(3)
    # only bound under causal (used in mask + block-skip predicate): an
    # unused program_id value fails Mosaic legalization, and program_id
    # cannot be called inside a pl.when body in interpreter mode
    qi = pl.program_id(2) if causal else None

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_s[:] = jnp.full_like(m_s, _NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)

    def compute(masked):
        """masked=False → interior block: no iota/compare/where — the VPU
        cost of flash attention is the softmax chain, and on a causal
        S=1024 run ~80% of blocks need no masking at all (the FlashAttention
        block-specialization; the reference fusion library does the same on
        CUDA)."""
        q = q_ref[0, 0].astype(jnp.float32) * np.float32(scale)  # [bq, d]
        k = k_ref[0, 0]                                      # [bk, d]
        s = jax.lax.dot_general(
            q, k.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)              # [bq, bk]
        if masked:
            cols = ki * block_k + \
                jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            if has_lens:
                mask = cols < lens_ref[0, 0, 0]
            else:
                mask = cols < sk
            if causal:
                # causal offset aligns the last q row with the last kv col
                rows = qi * block_q + \
                    jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
                mask = mask & (cols <= rows + (sk - sq))
            s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_s[:, :1]                                  # [bq, 1]
        l_prev = l_s[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                               # [bq, bk]
        if masked:
            # a FULLY-masked row has m_new == -1e30, which cancels in
            # exp(s - m_new) → p = 1; zero it explicitly (empty rows must
            # produce l == 0 → output 0). Interior blocks can't be empty.
            p = jnp.where(mask, p, _ZERO)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        v = v_ref[0, 0]                                      # [bk, d]
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # [bq, d]
        acc[:] = acc[:] * alpha + pv
        m_s[:] = jnp.broadcast_to(m_new, m_s.shape)
        l_s[:] = jnp.broadcast_to(l_new, l_s.shape)

    _block_dispatch(compute, causal=causal, qi=qi, ki=ki, nk=nk,
                    sq=sq, sk=sk, block_q=block_q, block_k=block_k,
                    force_masked=has_lens)

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_s[:, :1]
        safe_l = jnp.where(l == _ZERO, _ONE, l)
        o_ref[0, 0] = (acc[:] / safe_l).astype(o_ref.dtype)
        # lse is lane-replicated [bq, 128]: TPU block tiling requires the
        # last two block dims be (8k, 128)-aligned, so per-row stats ride a
        # full lane dim (the standard TPU flash-kernel layout)
        lse_ref[0, 0] = jnp.broadcast_to(
            m_s[:, :1] + jnp.log(safe_l), lse_ref[0, 0].shape)


def _lens_lanes(lens, b):
    """[B] int32 kv lengths -> [B, 8, 128] tile-replicated block input
    (Mosaic requires the last two block dims be (8, 128)-aligned)."""
    return jnp.broadcast_to(lens.astype(jnp.int32)[:, None, None],
                            (b, 8, 128))


def _flash_forward(q, k, v, causal, block_q, block_k, lens=None):
    """q,k,v: [B, H, S, D] (same H — GQA expanded by caller).

    Returns (o [B,H,S,D], lse_lanes [B,H,Sq_padded,1]) — per-row softmax
    stats (lane-replication for the TPU tiling happens inside the kernel
    and is sliced away here to keep residuals small). lens: optional [B]
    per-batch kv length (varlen)."""
    # paddle_tpu runs jax with x64 enabled; trace the pallas program with
    # x64 OFF so index-map/kernel literals stay i32/f32 (Mosaic cannot
    # legalize stray i64/f64 values on real TPUs)
    with jax.enable_x64(False):
        return _flash_forward_x32(q, k, v, causal, block_q, block_k, lens)


def _flash_forward_x32(q, k, v, causal, block_q, block_k, lens=None):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    scale = 1.0 / math.sqrt(d)
    sq_p = _ceil_to(sq, block_q)
    sk_p = _ceil_to(sk, block_k)
    d_p = _ceil_to(d, 128)
    qp = jnp.pad(q, ((0, 0), (0, 0), (0, sq_p - sq), (0, d_p - d)))
    kp = jnp.pad(k, ((0, 0), (0, 0), (0, sk_p - sk), (0, d_p - d)))
    vp = jnp.pad(v, ((0, 0), (0, 0), (0, sk_p - sk), (0, d_p - d)))
    nq, nk = sq_p // block_q, sk_p // block_k
    has_lens = lens is not None

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, sq=sq, sk=sk,
        block_q=block_q, block_k=block_k, has_lens=has_lens)
    in_specs = [
        pl.BlockSpec((1, 1, block_q, d_p), lambda b, h, qi, ki: (b, h, qi, 0)),
        pl.BlockSpec((1, 1, block_k, d_p), lambda b, h, qi, ki: (b, h, ki, 0)),
        pl.BlockSpec((1, 1, block_k, d_p), lambda b, h, qi, ki: (b, h, ki, 0)),
    ]
    args = [qp, kp, vp]
    if has_lens:
        in_specs.append(
            pl.BlockSpec((1, 8, 128), lambda b, h, qi, ki: (b, 0, 0)))
        args.append(_lens_lanes(lens, b))
    o, lse = pl.pallas_call(
        kernel,
        grid=(b, h, nq, nk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d_p), lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 128), lambda b, h, qi, ki: (b, h, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq_p, d_p), q.dtype),
            jax.ShapeDtypeStruct((b, h, sq_p, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d_p), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=_interpret(),
    )(*args)
    # keep one lane in the residuals (128x smaller); backward re-broadcasts
    return o[:, :, :sq, :d], lse[:, :, :, :1]


# ----------------------------------------------------------------- backward

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *refs,
                   scale, causal, sq, sk, block_q, block_k, has_lens=False):
    if has_lens:
        lens_ref, dq_ref, dq_acc = refs
    else:
        dq_ref, dq_acc = refs
    # like _fwd_kernel: nothing dead may be traced (Mosaic legalization)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)
    qi = pl.program_id(2) if causal else None

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def compute(masked):
        q = q_ref[0, 0].astype(jnp.float32) * np.float32(scale)
        k = k_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        lse = lse_ref[0, 0][:, :1]                            # [bq, 1] of lanes
        if masked:
            cols = ki * block_k + \
                jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            mask = (cols < lens_ref[0, 0, 0]) if has_lens else (cols < sk)
            if causal:
                rows = qi * block_q + \
                    jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
                mask = mask & (cols <= rows + (sk - sq))
        p = jnp.exp(s - lse)                                  # [bq, bk]
        if masked:
            # empty rows have lse == -1e30 (cancels the mask value): zero p
            p = jnp.where(mask, p, _ZERO)
        do = do_ref[0, 0].astype(jnp.float32)                 # [bq, d]
        v = v_ref[0, 0].astype(jnp.float32)                   # [bk, d]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        delta = delta_ref[0, 0][:, :1]
        ds = p * (dp - delta) * np.float32(scale)             # [bq, bk]
        dq_acc[:] = dq_acc[:] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    _block_dispatch(compute, causal=causal, qi=qi, ki=ki, nk=nk,
                    sq=sq, sk=sk, block_q=block_q, block_k=block_k,
                    force_masked=has_lens)

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *refs,
                    scale, causal, sq, sk, block_q, block_k, has_lens=False):
    if has_lens:
        lens_ref, dk_ref, dv_ref, dk_acc, dv_acc = refs
    else:
        dk_ref, dv_ref, dk_acc, dv_acc = refs
    # grid here is (b, h, ki, qi): kv blocks outer, q blocks inner
    ki = pl.program_id(2)
    qi = pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    k_start = ki * block_k
    nk = pl.num_programs(2)

    def compute(masked):
        q = q_ref[0, 0].astype(jnp.float32) * np.float32(scale)  # [bq, d]
        k = k_ref[0, 0].astype(jnp.float32)                   # [bk, d]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        lse = lse_ref[0, 0][:, :1]
        if masked:
            cols = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            mask = (cols < lens_ref[0, 0, 0]) if has_lens else (cols < sk)
            if causal:
                rows = qi * block_q + \
                    jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
                mask = mask & (cols <= rows + (sk - sq))
            s = jnp.where(mask, s, _NEG_INF)
        p = jnp.exp(s - lse)                                  # [bq, bk]
        if masked:
            # empty q rows have lse == -1e30 (cancels the mask value): p
            # must be zeroed or they pollute dk/dv accumulations
            p = jnp.where(mask, p, _ZERO)
        do = do_ref[0, 0].astype(jnp.float32)                 # [bq, d]
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        delta = delta_ref[0, 0][:, :1]
        # `q` here is pre-scaled by 1/sqrt(d), which is exactly dk's scale
        # factor — so ds must NOT be scaled again
        ds = p * (dp - delta)                                 # [bq, bk]
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    _block_dispatch(compute, causal=causal, qi=qi, ki=ki, nk=nk,
                    sq=sq, sk=sk, block_q=block_q, block_k=block_k,
                    force_masked=has_lens)

    @pl.when(qi == nq - 1)
    def _finish():
        # dk picked up the q-side 1/sqrt(d) scale through `q`; already applied
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_backward(q, k, v, o, lse_lanes, do, causal, block_q, block_k,
                    lens=None):
    with jax.enable_x64(False):  # see _flash_forward
        return _flash_backward_x32(q, k, v, o, lse_lanes, do, causal,
                                   block_q, block_k, lens)


def _flash_backward_x32(q, k, v, o, lse_lanes, do, causal, block_q, block_k,
                        lens=None):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    scale = 1.0 / math.sqrt(d)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)

    sq_p = _ceil_to(sq, block_q)
    sk_p = _ceil_to(sk, block_k)
    d_p = _ceil_to(d, 128)
    pad4 = lambda x, s: jnp.pad(x, ((0, 0), (0, 0), (0, s - x.shape[2]), (0, d_p - d)))
    qp, kp, vp = pad4(q, sq_p), pad4(k, sk_p), pad4(v, sk_p)
    dop = pad4(do, sq_p)
    lsep = jnp.broadcast_to(lse_lanes, (b, h, lse_lanes.shape[2], 128))
    deltap = jnp.broadcast_to(
        jnp.pad(delta, ((0, 0), (0, 0), (0, sq_p - sq)))[..., None],
        (b, h, sq_p, 128))
    nq, nk = sq_p // block_q, sk_p // block_k

    has_lens = lens is not None
    common = dict(scale=scale, causal=causal, sq=sq, sk=sk,
                  block_q=block_q, block_k=block_k, has_lens=has_lens)
    q_spec = pl.BlockSpec((1, 1, block_q, d_p), lambda b, h, qi, ki: (b, h, qi, 0))
    k_spec = pl.BlockSpec((1, 1, block_k, d_p), lambda b, h, qi, ki: (b, h, ki, 0))
    r_spec = pl.BlockSpec((1, 1, block_q, 128), lambda b, h, qi, ki: (b, h, qi, 0))
    lens_spec = pl.BlockSpec((1, 8, 128), lambda b, h, qi, ki: (b, 0, 0))
    extra = [_lens_lanes(lens, b)] if has_lens else []

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **common),
        grid=(b, h, nq, nk),
        in_specs=[q_spec, k_spec, k_spec, q_spec, r_spec, r_spec]
        + ([lens_spec] if has_lens else []),
        out_specs=[q_spec],
        out_shape=[jax.ShapeDtypeStruct((b, h, sq_p, d_p), q.dtype)],
        scratch_shapes=[pltpu.VMEM((block_q, d_p), jnp.float32)],
        interpret=_interpret(),
    )(qp, kp, vp, dop, lsep, deltap, *extra)[0]

    # dkv kernel: kv blocks outer, q blocks inner
    q_spec2 = pl.BlockSpec((1, 1, block_q, d_p), lambda b, h, ki, qi: (b, h, qi, 0))
    k_spec2 = pl.BlockSpec((1, 1, block_k, d_p), lambda b, h, ki, qi: (b, h, ki, 0))
    r_spec2 = pl.BlockSpec((1, 1, block_q, 128), lambda b, h, ki, qi: (b, h, qi, 0))
    lens_spec2 = pl.BlockSpec((1, 8, 128), lambda b, h, ki, qi: (b, 0, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, **common),
        grid=(b, h, nk, nq),
        in_specs=[q_spec2, k_spec2, k_spec2, q_spec2, r_spec2, r_spec2]
        + ([lens_spec2] if has_lens else []),
        out_specs=[k_spec2, k_spec2],
        out_shape=[jax.ShapeDtypeStruct((b, h, sk_p, d_p), k.dtype),
                   jax.ShapeDtypeStruct((b, h, sk_p, d_p), v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d_p), jnp.float32),
                        pltpu.VMEM((block_k, d_p), jnp.float32)],
        interpret=_interpret(),
    )(qp, kp, vp, dop, lsep, deltap, *extra)
    return (dq[:, :, :sq, :d], dk[:, :, :sk, :d], dv[:, :, :sk, :d])


# ----------------------------------------------------------- differentiable op

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, block_q, block_k):
    o, _ = _flash_forward(q, k, v, causal, block_q, block_k)
    return o


def _flash_fwd_rule(q, k, v, causal, block_q, block_k):
    o, lse = _flash_forward(q, k, v, causal, block_q, block_k)
    return o, (q, k, v, o, lse)


def _flash_bwd_rule(causal, block_q, block_k, res, g):
    q, k, v, o, lse = res
    return _flash_backward(q, k, v, o, lse, g, causal, block_q, block_k)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash_varlen(q, k, v, lens, causal, block_q, block_k):
    o, _ = _flash_forward(q, k, v, causal, block_q, block_k, lens=lens)
    return o


def _flash_varlen_fwd(q, k, v, lens, causal, block_q, block_k):
    o, lse = _flash_forward(q, k, v, causal, block_q, block_k, lens=lens)
    return o, (q, k, v, o, lse, lens)


def _flash_varlen_bwd(causal, block_q, block_k, res, g):
    q, k, v, o, lse, lens = res
    dq, dk, dv = _flash_backward(q, k, v, o, lse, g, causal, block_q,
                                 block_k, lens=lens)
    return dq, dk, dv, jnp.zeros(lens.shape, jax.dtypes.float0)


_flash_varlen.defvjp(_flash_varlen_fwd, _flash_varlen_bwd)


_TUNE_CACHE: dict = {}
#: candidate (block_q, block_k) pairs, ordered by prior; the autotuner
#: measures each on the first sighting of a shape family and pins the best
#: (≙ reference conv/attention runtime autotuning,
#: /root/reference/paddle/phi/kernels/autotune/auto_tune_base.h)
_TUNE_CANDIDATES = ((512, 1024), (256, 1024), (512, 512), (1024, 1024),
                    (256, 512))
#: probe failures that mean "this candidate doesn't compile/fit here"
#: (Mosaic lowering rejections, VMEM overflow) — anything else propagates
try:
    from jax.errors import JaxRuntimeError as _PROBE_RT_ERROR
except ImportError:  # pragma: no cover — older jax
    _PROBE_RT_ERROR = RuntimeError
_PROBE_ERRORS = (ValueError, NotImplementedError, _PROBE_RT_ERROR)


def _tune_cache_path():
    """Disk location of the tune cache — next to the XLA compile cache so
    a fresh process reuses both (no re-probe, no re-compile)."""
    import os

    base = jax.config.jax_compilation_cache_dir or "/tmp/jax_ccache"
    return os.path.join(base, "flash_tune_cache.json")


_TUNE_DISK_LOADED = False


def _tune_cache_load():
    global _TUNE_DISK_LOADED
    if _TUNE_DISK_LOADED:
        return
    _TUNE_DISK_LOADED = True
    import json
    import os

    path = _tune_cache_path()
    if not os.path.exists(path):
        return
    try:
        with open(path) as f:
            for ks, vv in json.load(f).items():
                sq, sk, d, dt, causal = ks.split("|")
                _TUNE_CACHE.setdefault(
                    (int(sq), int(sk), int(d), dt, causal == "True"),
                    tuple(vv))
    except (OSError, ValueError, TypeError, AttributeError):
        # corrupt/concurrent write OR structurally-wrong-but-valid JSON
        # (non-dict top level, non-list values): fall back to re-tuning
        pass


def _tune_cache_store():
    import json
    import os
    import tempfile

    path = _tune_cache_path()
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        payload = {"|".join(map(str, k)): list(v)
                   for k, v in _TUNE_CACHE.items()}
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
        with os.fdopen(fd, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)  # atomic vs concurrent processes
    except OSError:  # read-only fs: cache stays per-process
        pass


def _autotune_blocks(q, k, v, causal):
    """Pick (block_q, block_k) for this (sq, sk, d, dtype, causal) family.
    Off the TPU (interpret mode) or when FLAGS_flash_autotune is off, the
    measured v5e default is used. Probes run fwd+bwd per candidate on first
    sighting using the bench median-of-groups protocol (single 2-iteration
    timings over the axon tunnel swing ±3x — bench.py:55); the winner is
    cached in-process AND on disk next to the XLA compile cache."""
    from ..core.flags import flag

    sq, sk, d = q.shape[2], k.shape[2], q.shape[3]
    key = (sq, sk, d, str(q.dtype), causal)
    hit = _TUNE_CACHE.get(key)
    if hit is not None:
        return hit
    if _interpret() or isinstance(q, jax.core.Tracer) \
            or not flag("FLAGS_flash_autotune"):
        return (DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K)
    _tune_cache_load()
    hit = _TUNE_CACHE.get(key)
    if hit is not None:
        return hit
    import statistics
    import time as _time

    best, best_t = (DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K), float("inf")
    for bq_c, bk_c in _TUNE_CANDIDATES:
        bq = min(bq_c, _ceil_to(sq, 128))
        bk = min(bk_c, _ceil_to(sk, 128))
        if (bq, bk) in {(min(c[0], _ceil_to(sq, 128)),
                         min(c[1], _ceil_to(sk, 128)))
                        for c in _TUNE_CANDIDATES[:_TUNE_CANDIDATES.index(
                            (bq_c, bk_c))]}:
            continue  # clamping collapsed this candidate into an earlier one
        try:
            fn = jax.jit(lambda a, b, c2, _bq=bq, _bk=bk: jax.grad(
                lambda aa: jnp.sum(_flash(aa, b, c2, causal, _bq, _bk)
                                   .astype(jnp.float32)))(a))
            out = fn(q, k, v)
            jax.device_get(jnp.ravel(out)[0])  # compile + warm
            groups = []
            for _ in range(3):
                t0 = _time.perf_counter()
                for _ in range(2):
                    out = fn(q, k, v)
                jax.device_get(jnp.ravel(out)[0])
                groups.append(_time.perf_counter() - t0)
            dt = statistics.median(groups)
        except _PROBE_ERRORS:
            continue
        if dt < best_t:
            best, best_t = (bq, bk), dt
    _TUNE_CACHE[key] = best
    _tune_cache_store()
    return best


def flash_attention_raw(q, k, v, causal=False,
                        block_q=None, block_k=None):
    """jax-level flash attention on [B, H, S, D] arrays (GQA expanded here).
    block_q/block_k default to the per-shape autotuned choice."""
    hq, hk = q.shape[1], k.shape[1]
    if hq != hk:
        rep = hq // hk
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    if block_q is None or block_k is None:
        tq, tk = _autotune_blocks(q, k, v, causal)
        block_q = block_q or tq
        block_k = block_k or tk
    bq = min(block_q, _ceil_to(q.shape[2], 128))
    bk = min(block_k, _ceil_to(k.shape[2], 128))
    return _flash(q, k, v, causal, bq, bk)


def flash_attention_varlen_raw(q, k, v, kv_lens, causal=False,
                               block_q=DEFAULT_BLOCK_Q,
                               block_k=DEFAULT_BLOCK_K):
    """Varlen flash: [B, H, S, D] padded batch + [B] int32 kv lengths —
    key columns >= kv_lens[b] are masked INSIDE the kernel (the flash-varlen
    path the reference ships as a CUDA variant, flash_attention.py:358).
    Query rows beyond a sequence's length produce zeros; callers drop them.
    """
    hq, hk = q.shape[1], k.shape[1]
    if hq != hk:
        rep = hq // hk
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    bq = min(block_q, _ceil_to(q.shape[2], 128))
    bk = min(block_k, _ceil_to(k.shape[2], 128))
    return _flash_varlen(q, k, v, jnp.asarray(kv_lens, jnp.int32), causal,
                         bq, bk)


def ensure_tuned(b, h, sq, sk, d, dtype, causal):
    """Eagerly autotune the block choice for a shape family using synthetic
    operands. Called from framework code BEFORE entering any trace (jit
    traces can only consult the cache); a no-op off-TPU, on repeat shapes,
    or with FLAGS_flash_autotune off."""
    from ..core.flags import flag

    key = (sq, sk, d, str(jnp.dtype(dtype)), causal)
    if key in _TUNE_CACHE or _interpret() or not flag("FLAGS_flash_autotune"):
        return _TUNE_CACHE.get(key, (DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K))
    kk = jax.random.PRNGKey(0)
    # one head is enough to rank block choices; keeps probe cost tiny
    q = jax.random.normal(kk, (1, 1, sq, d), jnp.dtype(dtype))
    k = jax.random.normal(kk, (1, 1, sk, d), jnp.dtype(dtype))
    v = jax.random.normal(kk, (1, 1, sk, d), jnp.dtype(dtype))
    return _autotune_blocks(q, k, v, causal)


def flash_attention_op(query, key, value, is_causal=False):
    """Framework-level op on paddle-layout [B, S, H, D] Tensors; tape-recorded."""
    from ..core.dispatch import op_call

    qd = query._data if hasattr(query, "_data") else query
    if not isinstance(qd, jax.core.Tracer) and not _interpret():
        kd = key._data if hasattr(key, "_data") else key
        ensure_tuned(int(qd.shape[0]), int(qd.shape[2]), int(qd.shape[1]),
                     int(kd.shape[1]), int(qd.shape[3]), qd.dtype, is_causal)

    def f(q, k, v):
        qt = jnp.swapaxes(q, 1, 2)
        kt = jnp.swapaxes(k, 1, 2)
        vt = jnp.swapaxes(v, 1, 2)
        out = flash_attention_raw(qt, kt, vt, causal=is_causal)
        return jnp.swapaxes(out, 1, 2)

    return op_call(f, query, key, value, name="flash_attention", n_diff=3)


# ------------------------------------------------- flashmask (block-sparse)

def _fm_block_dispatch(compute, *, causal, row0, row1, col0, col1,
                       smin, smax, sq, sk, block_k):
    """Shared fwd/dq/dkv FlashMask block dispatch: skip kv blocks whose
    max start row precedes the q block entirely; run the lean no-mask path
    when the whole block is visible (its LAST row precedes every start);
    only straddling blocks pay the iota/where chain. ONE definition so the
    forward's visibility can never desynchronize from the backward's."""
    run = row0 < smax
    if causal:
        run = run & (col0 <= row1 + (sk - sq))
    sk_aligned = (sk % block_k) == 0
    interior = (row1 < smin) & ((col1 < sk) if not sk_aligned else
                                (col0 >= 0))
    if causal:
        interior = interior & (col1 <= row0 + (sk - sq))

    @pl.when(run)
    def _run():
        @pl.when(interior)
        def _i():
            compute(False)

        @pl.when(~interior)
        def _b():
            compute(True)


def _fm_mask(start_ref, shape, row0, col0, causal, sq, sk):
    """Per-element FlashMask visibility for a straddling block: key column
    j visible to query row i iff i < start[j] (and in range / causal)."""
    cols = col0 + jax.lax.broadcasted_iota(jnp.int32, shape, 1)
    rows = row0 + jax.lax.broadcasted_iota(jnp.int32, shape, 0)
    starts = start_ref[0, 0, 0:1, :]
    mask = (cols < sk) & (rows < starts)
    if causal:
        mask = mask & (cols <= rows + (sk - sq))
    return mask


def _fm_fwd_kernel(q_ref, k_ref, v_ref, start_ref, smin_ref, smax_ref,
                   o_ref, lse_ref, acc, m_s, l_s, *,
                   scale, causal, sq, sk, block_q, block_k):
    """FlashMask forward: per-COLUMN start rows (causal LTS form — key col
    j is blocked for query rows i >= start[j]) consulted at BLOCK
    granularity: kv blocks whose max start row is <= the block's first
    query row are skipped outright (no MXU work, the splash/FlashMask
    block-skip idea); blocks fully visible take the lean no-mask path;
    only straddling blocks pay the iota/where chain."""
    ki = pl.program_id(3)
    nk = pl.num_programs(3)
    qi = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_s[:] = jnp.full_like(m_s, _NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)

    row0 = qi * block_q
    row1 = row0 + block_q - 1
    col0 = ki * block_k
    col1 = col0 + block_k - 1
    smax = smax_ref[0, 0, 0, 0, 0]
    smin = smin_ref[0, 0, 0, 0, 0]

    def compute(masked):
        q = q_ref[0, 0].astype(jnp.float32) * np.float32(scale)
        k = k_ref[0, 0]
        s = jax.lax.dot_general(
            q, k.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if masked:
            mask = _fm_mask(start_ref, s.shape, row0, col0, causal, sq, sk)
            s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_s[:, :1]
        l_prev = l_s[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        if masked:
            p = jnp.where(mask, p, _ZERO)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        v = v_ref[0, 0]
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc[:] = acc[:] * alpha + pv
        m_s[:] = jnp.broadcast_to(m_new, m_s.shape)
        l_s[:] = jnp.broadcast_to(l_new, l_s.shape)

    _fm_block_dispatch(compute, causal=causal, row0=row0, row1=row1,
                       col0=col0, col1=col1, smin=smin, smax=smax,
                       sq=sq, sk=sk, block_k=block_k)

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_s[:, :1]
        safe_l = jnp.where(l == _ZERO, _ONE, l)
        o_ref[0, 0] = (acc[:] / safe_l).astype(o_ref.dtype)
        lse_ref[0, 0] = jnp.broadcast_to(
            m_s[:, :1] + jnp.log(safe_l), lse_ref[0, 0].shape)


def _fm_starts_prep(start_rows, b, h, sk, sk_p, nk, block_k):
    """Shared fwd/bwd prep of the per-column start rows: tile-replicated
    per-column starts [B,H,8,Sk_p] plus per-kv-block min/max start
    [B,H,nk,8,128] driving the block-skip / lean-path predicates."""
    sr = start_rows.astype(jnp.int32)                  # [B, H, Sk]
    # padded key columns get start 0 => visible to no row (blocked)
    sr_p = jnp.pad(sr, ((0, 0), (0, 0), (0, sk_p - sk)))
    # per-column starts, sublane-replicated: [B, H, 8, Sk_p]
    sr_lanes = jnp.broadcast_to(sr_p[:, :, None, :], (b, h, 8, sk_p))
    # per-kv-block min/max start: [B, H, nk] -> tile-replicated
    blk = sr_p.reshape(b, h, nk, block_k)
    smin = jnp.min(jnp.where(jnp.arange(block_k)[None, None, None, :]
                             + jnp.arange(nk)[None, None, :, None]
                             * block_k < sk, blk, jnp.int32(2**30)), axis=-1)
    smax = jnp.max(blk, axis=-1)
    smin_l = jnp.broadcast_to(smin[:, :, :, None, None], (b, h, nk, 8, 128))
    smax_l = jnp.broadcast_to(smax[:, :, :, None, None], (b, h, nk, 8, 128))
    return sr_lanes, smin_l, smax_l


def _fm_forward_x32(q, k, v, start_rows, causal, block_q, block_k):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    scale = 1.0 / math.sqrt(d)
    sq_p = _ceil_to(sq, block_q)
    sk_p = _ceil_to(sk, block_k)
    d_p = _ceil_to(d, 128)
    qp = jnp.pad(q, ((0, 0), (0, 0), (0, sq_p - sq), (0, d_p - d)))
    kp = jnp.pad(k, ((0, 0), (0, 0), (0, sk_p - sk), (0, d_p - d)))
    vp = jnp.pad(v, ((0, 0), (0, 0), (0, sk_p - sk), (0, d_p - d)))
    nq, nk = sq_p // block_q, sk_p // block_k
    sr_lanes, smin_l, smax_l = _fm_starts_prep(start_rows, b, h, sk, sk_p,
                                               nk, block_k)

    kernel = functools.partial(
        _fm_fwd_kernel, scale=scale, causal=causal, sq=sq, sk=sk,
        block_q=block_q, block_k=block_k)
    o, lse = pl.pallas_call(
        kernel,
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d_p),
                         lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d_p),
                         lambda b, h, qi, ki: (b, h, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d_p),
                         lambda b, h, qi, ki: (b, h, ki, 0)),
            pl.BlockSpec((1, 1, 8, block_k),
                         lambda b, h, qi, ki: (b, h, 0, ki)),
            pl.BlockSpec((1, 1, 1, 8, 128),
                         lambda b, h, qi, ki: (b, h, ki, 0, 0)),
            pl.BlockSpec((1, 1, 1, 8, 128),
                         lambda b, h, qi, ki: (b, h, ki, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d_p),
                         lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 128),
                         lambda b, h, qi, ki: (b, h, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq_p, d_p), q.dtype),
            jax.ShapeDtypeStruct((b, h, sq_p, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d_p), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=_interpret(),
    )(qp, kp, vp, sr_lanes, smin_l, smax_l)
    # keep one lane of the softmax stats for the backward (see _flash_forward)
    return o[:, :, :sq, :d], lse[:, :, :, :1]


def _fm_dense_ref(q, k, v, start_rows, causal):
    """Dense O(S^2) reference of the flashmask semantics. NOT on any
    production path — kept as the numerics oracle for
    tests/test_pallas_attention.py; fwd AND bwd run the block-skipping
    Pallas kernels."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(d)
    rows = jnp.arange(sq)[None, None, :, None]
    mask = rows < start_rows[:, :, None, :]
    if causal:
        cols = jnp.arange(sk)[None, None, None, :]
        mask = mask & (cols <= rows + (sk - sq))
    scores = jnp.where(mask, scores, jnp.finfo(scores.dtype).min)
    p = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    empty = ~jnp.any(mask, axis=-1, keepdims=True)
    p = jnp.where(empty, jnp.zeros_like(p), p)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _fm_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      start_ref, smin_ref, smax_ref, dq_ref, dq_acc, *,
                      scale, causal, sq, sk, block_q, block_k):
    """dq with the SAME block-skip predicates as the flashmask forward:
    kv blocks fully blocked for this q block contribute nothing and are
    skipped before touching the MXU; fully-visible blocks take the lean
    no-iota path; only straddling blocks pay the mask chain. The fwd LSE
    is reused — no dense [Sq,Sk] softmax is ever materialized."""
    ki = pl.program_id(3)
    nk = pl.num_programs(3)
    qi = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    row0 = qi * block_q
    row1 = row0 + block_q - 1
    col0 = ki * block_k
    col1 = col0 + block_k - 1
    smax = smax_ref[0, 0, 0, 0, 0]
    smin = smin_ref[0, 0, 0, 0, 0]

    def compute(masked):
        q = q_ref[0, 0].astype(jnp.float32) * np.float32(scale)
        k = k_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        lse = lse_ref[0, 0][:, :1]
        if masked:
            mask = _fm_mask(start_ref, s.shape, row0, col0, causal, sq, sk)
        p = jnp.exp(s - lse)
        if masked:
            # fully-blocked rows carry lse == -1e30 which cancels in the
            # exp; zero them (and padded/blocked columns) explicitly
            p = jnp.where(mask, p, _ZERO)
        do = do_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        delta = delta_ref[0, 0][:, :1]
        ds = p * (dp - delta) * np.float32(scale)
        dq_acc[:] = dq_acc[:] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    _fm_block_dispatch(compute, causal=causal, row0=row0, row1=row1,
                       col0=col0, col1=col1, smin=smin, smax=smax,
                       sq=sq, sk=sk, block_k=block_k)

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _fm_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                       start_ref, smin_ref, smax_ref, dk_ref, dv_ref,
                       dk_acc, dv_acc, *,
                       scale, causal, sq, sk, block_q, block_k):
    # grid is (b, h, ki, qi): kv blocks outer, q blocks inner
    ki = pl.program_id(2)
    qi = pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    row0 = qi * block_q
    row1 = row0 + block_q - 1
    col0 = ki * block_k
    col1 = col0 + block_k - 1
    smax = smax_ref[0, 0, 0, 0, 0]
    smin = smin_ref[0, 0, 0, 0, 0]

    def compute(masked):
        q = q_ref[0, 0].astype(jnp.float32) * np.float32(scale)
        k = k_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        lse = lse_ref[0, 0][:, :1]
        if masked:
            mask = _fm_mask(start_ref, s.shape, row0, col0, causal, sq, sk)
            s = jnp.where(mask, s, _NEG_INF)
        p = jnp.exp(s - lse)
        if masked:
            # blocked/padded rows have lse == -1e30 (cancels the mask
            # value): p must be zeroed or they pollute dk/dv
            p = jnp.where(mask, p, _ZERO)
        do = do_ref[0, 0].astype(jnp.float32)
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        delta = delta_ref[0, 0][:, :1]
        # `q` is pre-scaled by 1/sqrt(d) = dk's scale; ds NOT scaled again
        ds = p * (dp - delta)
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    _fm_block_dispatch(compute, causal=causal, row0=row0, row1=row1,
                       col0=col0, col1=col1, smin=smin, smax=smax,
                       sq=sq, sk=sk, block_k=block_k)

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _fm_backward_x32(q, k, v, o, lse_lanes, do, start_rows, causal,
                     block_q, block_k):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    scale = 1.0 / math.sqrt(d)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)

    sq_p = _ceil_to(sq, block_q)
    sk_p = _ceil_to(sk, block_k)
    d_p = _ceil_to(d, 128)
    pad4 = lambda x, s: jnp.pad(
        x, ((0, 0), (0, 0), (0, s - x.shape[2]), (0, d_p - d)))
    qp, kp, vp = pad4(q, sq_p), pad4(k, sk_p), pad4(v, sk_p)
    dop = pad4(do, sq_p)
    lsep = jnp.broadcast_to(lse_lanes, (b, h, lse_lanes.shape[2], 128))
    deltap = jnp.broadcast_to(
        jnp.pad(delta, ((0, 0), (0, 0), (0, sq_p - sq)))[..., None],
        (b, h, sq_p, 128))
    nq, nk = sq_p // block_q, sk_p // block_k
    sr_lanes, smin_l, smax_l = _fm_starts_prep(start_rows, b, h, sk, sk_p,
                                               nk, block_k)

    common = dict(scale=scale, causal=causal, sq=sq, sk=sk,
                  block_q=block_q, block_k=block_k)
    q_spec = pl.BlockSpec((1, 1, block_q, d_p),
                          lambda b, h, qi, ki: (b, h, qi, 0))
    k_spec = pl.BlockSpec((1, 1, block_k, d_p),
                          lambda b, h, qi, ki: (b, h, ki, 0))
    r_spec = pl.BlockSpec((1, 1, block_q, 128),
                          lambda b, h, qi, ki: (b, h, qi, 0))
    sr_spec = pl.BlockSpec((1, 1, 8, block_k),
                           lambda b, h, qi, ki: (b, h, 0, ki))
    mm_spec = pl.BlockSpec((1, 1, 1, 8, 128),
                           lambda b, h, qi, ki: (b, h, ki, 0, 0))
    dq = pl.pallas_call(
        functools.partial(_fm_bwd_dq_kernel, **common),
        grid=(b, h, nq, nk),
        in_specs=[q_spec, k_spec, k_spec, q_spec, r_spec, r_spec,
                  sr_spec, mm_spec, mm_spec],
        out_specs=[q_spec],
        out_shape=[jax.ShapeDtypeStruct((b, h, sq_p, d_p), q.dtype)],
        scratch_shapes=[pltpu.VMEM((block_q, d_p), jnp.float32)],
        interpret=_interpret(),
    )(qp, kp, vp, dop, lsep, deltap, sr_lanes, smin_l, smax_l)[0]

    # dkv kernel: kv blocks outer, q blocks inner
    q_spec2 = pl.BlockSpec((1, 1, block_q, d_p),
                           lambda b, h, ki, qi: (b, h, qi, 0))
    k_spec2 = pl.BlockSpec((1, 1, block_k, d_p),
                           lambda b, h, ki, qi: (b, h, ki, 0))
    r_spec2 = pl.BlockSpec((1, 1, block_q, 128),
                           lambda b, h, ki, qi: (b, h, qi, 0))
    sr_spec2 = pl.BlockSpec((1, 1, 8, block_k),
                            lambda b, h, ki, qi: (b, h, 0, ki))
    mm_spec2 = pl.BlockSpec((1, 1, 1, 8, 128),
                            lambda b, h, ki, qi: (b, h, ki, 0, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_fm_bwd_dkv_kernel, **common),
        grid=(b, h, nk, nq),
        in_specs=[q_spec2, k_spec2, k_spec2, q_spec2, r_spec2, r_spec2,
                  sr_spec2, mm_spec2, mm_spec2],
        out_specs=[k_spec2, k_spec2],
        out_shape=[jax.ShapeDtypeStruct((b, h, sk_p, d_p), k.dtype),
                   jax.ShapeDtypeStruct((b, h, sk_p, d_p), v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d_p), jnp.float32),
                        pltpu.VMEM((block_k, d_p), jnp.float32)],
        interpret=_interpret(),
    )(qp, kp, vp, dop, lsep, deltap, sr_lanes, smin_l, smax_l)
    return (dq[:, :, :sq, :d], dk[:, :, :sk, :d], dv[:, :, :sk, :d])


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flashmask(q, k, v, start_rows, causal, block_q, block_k):
    with jax.enable_x64(False):
        o, _ = _fm_forward_x32(q, k, v, start_rows, causal, block_q, block_k)
    return o


def _flashmask_fwd(q, k, v, start_rows, causal, block_q, block_k):
    with jax.enable_x64(False):
        o, lse = _fm_forward_x32(q, k, v, start_rows, causal,
                                 block_q, block_k)
    return o, (q, k, v, o, lse, start_rows)


def _flashmask_bwd(causal, block_q, block_k, res, g):
    q, k, v, o, lse, start_rows = res
    with jax.enable_x64(False):
        dq, dk, dv = _fm_backward_x32(q, k, v, o, lse, g, start_rows,
                                      causal, block_q, block_k)
    return dq, dk, dv, jnp.zeros(start_rows.shape, jax.dtypes.float0)


_flashmask.defvjp(_flashmask_fwd, _flashmask_bwd)


def flashmask_attention_raw(q, k, v, start_rows, causal=False,
                            block_q=None, block_k=None):
    """Block-sparse FlashMask attention on [B, H, S, D] arrays with
    per-column start rows [B, H, S_k] (causal LTS form). Forward AND
    backward skip fully-blocked kv blocks in Pallas kernels; the backward
    reuses the forward's LSE so no [Sq,Sk] softmax is ever materialized
    (≙ the reference's fused fwd+bwd flashmask CUDA family,
    nn/functional/flash_attention.py flashmask_attention)."""
    bq = min(block_q or DEFAULT_BLOCK_Q, _ceil_to(q.shape[2], 128))
    bk = min(block_k or 512, _ceil_to(k.shape[2], 128))
    return _flashmask(q, k, v, start_rows, causal, bq, bk)
