"""Shape/layout/indexing ops (≙ python/paddle/tensor/manipulation.py).

TPU note: all of these lower to XLA reshape/transpose/gather/scatter/dynamic
-slice which are free or fused on TPU when shapes are static; nothing here
materializes host-side.
"""
from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtypes
from ..core.dispatch import op_call
from ..core.tensor import Tensor
from ._helpers import inplace_variant, norm_axis, raw


def _static_shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(v) for v in shape.tolist())
    out = []
    for s in shape:
        out.append(int(s.item()) if isinstance(s, Tensor) else int(s))
    return tuple(out)


def cast(x, dtype, name=None):
    dt = dtypes.convert_dtype(dtype)
    if x.dtype == dt:
        return x
    if dtypes.is_floating_point(dt):
        return op_call(lambda a: a.astype(dt), x, name="cast")
    return op_call(lambda a: a.astype(dt), x, name="cast", n_diff=0)


def reshape(x, shape, name=None):
    shp = _static_shape(shape)
    return op_call(lambda a: jnp.reshape(a, shp), x, name="reshape")


def transpose(x, perm, name=None):
    perm = [int(p) for p in perm]
    return op_call(lambda a: jnp.transpose(a, perm), x, name="transpose")


def t(x, name=None):
    def f(a):
        return a.T if a.ndim >= 2 else a

    return op_call(f, x, name="t")


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def f(a):
        nd = a.ndim
        if nd == 0:
            return a.reshape(1)
        s0 = start_axis % nd
        s1 = stop_axis % nd
        newshape = a.shape[:s0] + (-1,) + a.shape[s1 + 1:]
        return a.reshape(newshape)

    return op_call(f, x, name="flatten")


def squeeze(x, axis=None, name=None):
    ax = norm_axis(axis)

    def f(a):
        if ax is None:
            return jnp.squeeze(a)
        axes = ax if isinstance(ax, tuple) else (ax,)
        axes = tuple(a_ % a.ndim for a_ in axes if a.shape[a_ % a.ndim] == 1)
        return jnp.squeeze(a, axis=axes) if axes else a

    return op_call(f, x, name="squeeze")


def unsqueeze(x, axis, name=None):
    ax = norm_axis(axis)

    def f(a):
        axes = ax if isinstance(ax, tuple) else (ax,)
        for a_ in sorted(a_ % (a.ndim + 1) for a_ in axes):
            a = jnp.expand_dims(a, a_)
        return a

    return op_call(f, x, name="unsqueeze")


def concat(x, axis=0, name=None):
    tensors = list(x)
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    return op_call(lambda *arrs: jnp.concatenate(arrs, axis=ax), *tensors, name="concat")


def stack(x, axis=0, name=None):
    return op_call(lambda *arrs: jnp.stack(arrs, axis=axis), *list(x), name="stack")


def split(x, num_or_sections, axis=0, name=None):
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    dim = x.shape[ax]
    if isinstance(num_or_sections, int):
        sizes = [dim // num_or_sections] * num_or_sections
    else:
        sizes = [int(s) for s in num_or_sections]
        n_unknown = builtins.sum(1 for s in sizes if s < 0)
        if n_unknown:
            known = builtins.sum(s for s in sizes if s >= 0)
            sizes = [s if s >= 0 else dim - known for s in sizes]
    offsets = np.cumsum([0] + sizes[:-1])
    outs = []
    for off, sz in zip(offsets, sizes):
        outs.append(op_call(lambda a, o=int(off), s=int(sz): jax.lax.slice_in_dim(a, o, o + s, axis=ax),
                            x, name="split"))
    return outs


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def unbind(x, axis=0, name=None):
    n = x.shape[axis]
    return [op_call(lambda a, i=i: jnp.take(a, i, axis=axis), x, name="unbind")
            for i in range(n)]


def tile(x, repeat_times, name=None):
    reps = _static_shape(repeat_times)
    return op_call(lambda a: jnp.tile(a, reps), x, name="tile")


def expand(x, shape, name=None):
    shp = _static_shape(shape)

    def f(a):
        target = list(shp)
        # -1 keeps original dim
        off = len(target) - a.ndim
        for i in range(len(target)):
            if target[i] == -1:
                target[i] = a.shape[i - off]
        return jnp.broadcast_to(a, target)

    return op_call(f, x, name="expand")


def expand_as(x, y, name=None):
    return op_call(lambda a, b: jnp.broadcast_to(a, b.shape), x, y, name="expand_as", n_diff=1)


broadcast_to = expand


def broadcast_tensors(inputs, name=None):
    shapes = [tuple(t.shape) for t in inputs]
    target = np.broadcast_shapes(*shapes)
    # differentiable (grad of broadcast = sum over the expanded axes), like
    # the reference broadcast_tensors_grad
    return [op_call(lambda a: jnp.broadcast_to(a, target), t,
                    name="broadcast_tensors", n_diff=1)
            for t in inputs]


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def flip(x, axis, name=None):
    ax = norm_axis(axis)
    return op_call(lambda a: jnp.flip(a, axis=ax), x, name="flip")


def rot90(x, k=1, axes=(0, 1), name=None):
    return op_call(lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), x, name="rot90")


def roll(x, shifts, axis=None, name=None):
    ax = norm_axis(axis)
    sh = tuple(shifts) if isinstance(shifts, (list, tuple)) else int(raw(shifts)) if not isinstance(shifts, int) else shifts
    return op_call(lambda a: jnp.roll(a, sh, axis=ax), x, name="roll")


def moveaxis(x, source, destination, name=None):
    return op_call(lambda a: jnp.moveaxis(a, source, destination), x, name="moveaxis")


def swapaxes(x, axis0, axis1, name=None):
    return op_call(lambda a: jnp.swapaxes(a, axis0, axis1), x, name="swapaxes")


def gather(x, index, axis=0, name=None):
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    return op_call(lambda a, i: jnp.take(a, i.astype(jnp.int32), axis=ax),
                   x, index, name="gather", n_diff=1)


def gather_nd(x, index, name=None):
    def f(a, idx):
        idx = idx.astype(jnp.int32)
        k = idx.shape[-1]
        flat = idx.reshape(-1, k)
        out = a[tuple(flat[:, i] for i in range(k))]
        return out.reshape(idx.shape[:-1] + a.shape[k:])

    return op_call(f, x, index, name="gather_nd", n_diff=1)


def scatter(x, index, updates, overwrite=True, name=None):
    def f(a, idx, upd):
        idx = idx.astype(jnp.int32).reshape(-1)
        if overwrite:
            return a.at[idx].set(upd)
        return a.at[idx].add(upd)

    return op_call(f, x, index, updates, name="scatter", n_diff=3)


def scatter_(x, index, updates, overwrite=True, name=None):
    out = scatter(x, index, updates, overwrite)
    x._assign_raw(out._data)
    x._node, x._out_idx = out._node, out._out_idx
    return x


def scatter_nd_add(x, index, updates, name=None):
    def f(a, idx, upd):
        idx = idx.astype(jnp.int32)
        k = idx.shape[-1]
        flat = idx.reshape(-1, k)
        updf = upd.reshape((-1,) + a.shape[k:])
        return a.at[tuple(flat[:, i] for i in range(k))].add(updf)

    return op_call(f, x, index, updates, name="scatter_nd_add", n_diff=3)


def scatter_nd(index, updates, shape, name=None):
    from .creation import zeros

    z = zeros(shape, dtype=updates.dtype)
    return scatter_nd_add(z, index, updates)


def index_select(x, index, axis=0, name=None):
    return gather(x, index, axis)


def index_sample(x, index, name=None):
    def f(a, idx):
        return jnp.take_along_axis(a, idx.astype(jnp.int32), axis=1)

    return op_call(f, x, index, name="index_sample", n_diff=1)


def index_add(x, index, axis, value, name=None):
    def f(a, idx, v):
        am = jnp.moveaxis(a, axis, 0)
        vm = jnp.moveaxis(v, axis, 0)
        out = am.at[idx.astype(jnp.int32)].add(vm)
        return jnp.moveaxis(out, 0, axis)

    return op_call(f, x, index, value, name="index_add", n_diff=3)


def index_put(x, indices, value, accumulate=False, name=None):
    idxs = tuple(raw(i) for i in indices)

    def f(a, v):
        if accumulate:
            return a.at[idxs].add(v)
        return a.at[idxs].set(v)

    return op_call(f, x, value, name="index_put", n_diff=2)


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    def f(a, idx):
        idx = idx.astype(jnp.int32)
        if broadcast:
            return jnp.take_along_axis(a, idx, axis=axis)
        # broadcast=False ≙ torch.gather: output takes indices' exact
        # shape, size-1 dims are NOT expanded against arr
        ax = axis % a.ndim
        ii = jnp.meshgrid(*[jnp.arange(s) for s in idx.shape], indexing="ij")
        ii[ax] = idx
        return a[tuple(ii)]

    return op_call(f, arr, indices, name="take_along_axis", n_diff=1)


def put_along_axis(arr, indices, values, axis, reduce="assign", include_self=True,
                   broadcast=True, name=None):
    def f(a, idx, v):
        ax = axis % a.ndim
        idx = idx.astype(jnp.int32)
        if not isinstance(v, jnp.ndarray) or v.ndim == 0:
            v = jnp.broadcast_to(v, idx.shape).astype(a.dtype)
        if broadcast:
            # reference semantics: indices/values broadcast against arr's
            # shape on every dim except `axis`
            tgt = a.shape[:ax] + (idx.shape[ax] if idx.ndim == a.ndim
                                  else idx.shape[-1],) + a.shape[ax + 1:]
            idx = jnp.broadcast_to(idx, tgt)
            v = jnp.broadcast_to(v, tgt).astype(a.dtype)
        if not include_self and reduce != "assign":
            # excluded original values: scattered positions start from the
            # reduction identity instead of a's value
            flt = jnp.issubdtype(a.dtype, jnp.floating)
            lo = -jnp.inf if flt else jnp.iinfo(a.dtype).min
            hi = jnp.inf if flt else jnp.iinfo(a.dtype).max
            ident = {"add": 0, "sum": 0, "mul": 1, "multiply": 1,
                     "amax": lo, "amin": hi, "mean": 0}[reduce]
            a = _along_axis_at(a, idx, ax).set(
                jnp.full(idx.shape, ident, a.dtype))
        at = _along_axis_at(a, idx, ax)
        if reduce == "assign":
            return at.set(v)
        if reduce in ("add", "sum"):
            return at.add(v)
        if reduce in ("mul", "multiply"):
            return at.multiply(v)
        if reduce == "amax":
            return at.max(v)
        if reduce == "amin":
            return at.min(v)
        if reduce == "mean":
            summed = at.add(v)
            base = jnp.full(a.shape, 1 if include_self else 0, jnp.int32)
            cnt = _along_axis_at(base, idx, ax).add(jnp.ones(idx.shape,
                                                             jnp.int32))
            return summed / jnp.maximum(cnt, 1).astype(a.dtype)
        raise ValueError(reduce)

    if isinstance(values, Tensor):
        return op_call(f, arr, indices, values, name="put_along_axis", n_diff=3)
    return op_call(lambda a, i: f(a, i, values), arr, indices, name="put_along_axis", n_diff=1)


def _along_axis_at(a, idx, axis):
    axis = axis % a.ndim
    ii = jnp.meshgrid(*[jnp.arange(s) for s in idx.shape], indexing="ij")
    ii[axis] = idx
    return a.at[tuple(ii)]


def take(x, index, mode="raise", name=None):
    def f(a, idx):
        flat = a.reshape(-1)
        i = idx.astype(jnp.int32)
        if mode == "wrap":
            i = jnp.mod(i, flat.shape[0])
        elif mode == "clip":
            i = jnp.clip(i, 0, flat.shape[0] - 1)
        else:
            i = jnp.where(i < 0, i + flat.shape[0], i)
        return flat[i]

    return op_call(f, x, index, name="take", n_diff=1)


def masked_select(x, mask, name=None):
    # dynamic output shape: eager-only op (documented; same limit as XLA)
    data = np.asarray(x._data)[np.asarray(raw(mask))]
    return Tensor(jnp.asarray(data), _internal=True)


def masked_fill(x, mask, value, name=None):
    v = raw(value) if isinstance(value, Tensor) else value
    return op_call(lambda a, m: jnp.where(m, jnp.asarray(v, a.dtype), a), x, mask,
                   name="masked_fill", n_diff=1)


def masked_scatter(x, mask, value, name=None):
    data = np.asarray(x._data).copy()
    m = np.asarray(raw(mask))
    vals = np.asarray(raw(value)).reshape(-1)
    data[m] = vals[: int(m.sum())]
    return Tensor(jnp.asarray(data), _internal=True)


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return op_call(lambda c, a, b: jnp.where(c, a, b), condition, x, y,
                   name="where", n_diff=3)


def nonzero(x, as_tuple=False, name=None):
    idx = np.nonzero(np.asarray(raw(x)))
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i), _internal=True) for i in idx)
    return Tensor(jnp.asarray(np.stack(idx, axis=1)), _internal=True)


def slice(input, axes, starts, ends, name=None):
    def f(a):
        out = a
        for ax, s, e in zip(axes, starts, ends):
            s = int(raw(s)) if not isinstance(s, int) else s
            e = int(raw(e)) if not isinstance(e, int) else e
            dim = out.shape[ax]
            s = builtins.max(s + dim, 0) if s < 0 else builtins.min(s, dim)
            e = builtins.max(e + dim, 0) if e < 0 else builtins.min(e, dim)
            out = jax.lax.slice_in_dim(out, s, e, axis=ax)
        return out

    return op_call(f, input, name="slice")


def strided_slice(x, axes, starts, ends, strides, name=None):
    def f(a):
        idx = [builtins.slice(None)] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            idx[ax] = builtins.slice(int(raw(s)), int(raw(e)), int(raw(st)))
        return a[tuple(idx)]

    return op_call(f, x, name="strided_slice")


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    padv = _static_shape(pad)

    def f(a):
        nd = a.ndim
        if len(padv) == 2 * nd:
            width = [(padv[2 * i], padv[2 * i + 1]) for i in range(nd)]
        elif len(padv) == 2 * (nd - 2) and nd >= 3 \
                and not data_format.startswith("NC"):
            # channel-last (NLC/NHWC/NDHWC): the spatial dims sit at 1..nd-2
            k = len(padv) // 2
            width = [(0, 0)] + [(padv[2 * i], padv[2 * i + 1])
                                for i in range(k)][::-1] + [(0, 0)]
        else:
            # paddle convention: pair i applies to the i-th dim from the end
            k = len(padv) // 2
            width = [(0, 0)] * (nd - k) + [
                (padv[2 * i], padv[2 * i + 1]) for i in range(k)
            ][::-1]
        if mode == "constant":
            return jnp.pad(a, width, constant_values=value)
        jmode = {"reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
        return jnp.pad(a, width, mode=jmode)

    return op_call(f, x, name="pad")


def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        reps = np.asarray(repeats._data)
        data = np.repeat(np.asarray(x._data), reps, axis=axis)
        return Tensor(jnp.asarray(data), _internal=True)
    return op_call(lambda a: jnp.repeat(a, repeats, axis=axis), x, name="repeat_interleave")


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    a = np.asarray(raw(x))
    res = np.unique(a, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        res = (res,)
    # `dtype` governs the index-typed outputs (indices/inverse/counts),
    # not the values (reference tensor/manipulation.py unique)
    idt = np.dtype(dtype)
    outs = [Tensor(jnp.asarray(r if i == 0 else r.astype(idt)),
                   _internal=True) for i, r in enumerate(res)]
    return outs[0] if len(outs) == 1 else tuple(outs)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    a = np.asarray(raw(x))
    if axis is None:
        a = a.reshape(-1)
        keep = np.concatenate([[True], a[1:] != a[:-1]])
        out = a[keep]
        idt = np.dtype(dtype)
        outs = [Tensor(jnp.asarray(out), _internal=True)]
        if return_inverse:
            inv = (np.cumsum(keep) - 1).astype(idt)
            outs.append(Tensor(jnp.asarray(inv), _internal=True))
        if return_counts:
            idx = np.flatnonzero(keep)
            cnt = np.diff(np.append(idx, a.size)).astype(idt)
            outs.append(Tensor(jnp.asarray(cnt), _internal=True))
        return outs[0] if len(outs) == 1 else tuple(outs)
    raise NotImplementedError("unique_consecutive with axis")


def sort(x, axis=-1, descending=False, stable=False, name=None):
    def f(a):
        out = jnp.sort(a, axis=axis, stable=True)
        return jnp.flip(out, axis=axis) if descending else out

    return op_call(f, x, name="sort")


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    def f(a):
        if not descending:
            return jnp.argsort(a, axis=axis, stable=True).astype(jnp.int64)
        if stable:
            # flipping a stable ascending argsort reverses tie order; a
            # stable DESCENDING sort must keep ties in original order.
            # The negate trick is float-only: for unsigned ints -a wraps
            # (0 stays the minimum) and INT_MIN negates to itself. Bitwise
            # NOT (~a = -a-1) is a wrap-free order-reversing bijection for
            # every integer dtype, incl. bool.
            if jnp.issubdtype(a.dtype, jnp.integer) or a.dtype == jnp.bool_:
                key = jnp.invert(a)
            else:
                key = -a
            return jnp.argsort(key, axis=axis, stable=True).astype(jnp.int64)
        return jnp.flip(jnp.argsort(a, axis=axis, stable=True),
                        axis=axis).astype(jnp.int64)

    return op_call(f, x, name="argsort", n_diff=0)


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    def f(seq, v):
        side = "right" if right else "left"
        if seq.ndim == 1:
            out = jnp.searchsorted(seq, v, side=side)
        else:
            out = jax.vmap(lambda s, vv: jnp.searchsorted(s, vv, side=side))(
                seq.reshape(-1, seq.shape[-1]), v.reshape(-1, v.shape[-1])
            ).reshape(v.shape)
        return out.astype(jnp.int32 if out_int32 else jnp.int64)

    return op_call(f, sorted_sequence, values, name="searchsorted", n_diff=0)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32, right)


def one_hot(x, num_classes, name=None):
    return op_call(lambda a: jax.nn.one_hot(a, num_classes, dtype=jnp.float32), x,
                   name="one_hot", n_diff=0)


def tensordot(x, y, axes=2, name=None):
    def f(a, b):
        ax = axes
        if isinstance(ax, (list, tuple)):
            ax = tuple(tuple(int(i) for i in part) if isinstance(part, (list, tuple)) else int(part)
                       for part in ax)
        return jnp.tensordot(a, b, axes=ax)

    return op_call(f, x, y, name="tensordot")


def as_strided(x, shape, stride, offset=0, name=None):
    def f(a):
        flat = a.reshape(-1)
        idx = np.zeros(tuple(shape), dtype=np.int64) + offset
        for d, (s, st) in enumerate(zip(shape, stride)):
            r = np.arange(s) * st
            idx += r.reshape([-1 if i == d else 1 for i in range(len(shape))])
        return flat[jnp.asarray(idx)]

    return op_call(f, x, name="as_strided")


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    dt = dtypes.convert_dtype(shape_or_dtype)
    return op_call(lambda a: jax.lax.bitcast_convert_type(a, dt), x, name="view", n_diff=0)


def view_as(x, other, name=None):
    return reshape(x, other.shape)


def unfold(x, axis, size, step, name=None):
    def f(a):
        dim = a.shape[axis]
        n = (dim - size) // step + 1
        starts = jnp.arange(n) * step
        idx = starts[:, None] + jnp.arange(size)[None, :]
        out = jnp.take(a, idx.reshape(-1), axis=axis)
        am = jnp.moveaxis(out, axis, 0).reshape((n, size) + tuple(
            s for i, s in enumerate(a.shape) if i != axis % a.ndim))
        # paddle returns windows appended as last dim, original axis replaced by n
        am = jnp.moveaxis(am, 0, axis)  # (..., n at axis, size first)
        return jnp.moveaxis(am, 1 if axis != 0 else 1, a.ndim)

    return op_call(f, x, name="unfold")


def crop(x, shape=None, offsets=None, name=None):
    shp = _static_shape(shape)
    offs = _static_shape(offsets) if offsets is not None else (0,) * len(shp)

    def f(a):
        idx = tuple(builtins.slice(o, o + (s if s != -1 else a.shape[i] - o))
                    for i, (o, s) in enumerate(zip(offs, shp)))
        return a[idx]

    return op_call(f, x, name="crop")


def atleast_1d(*xs, name=None):
    outs = [op_call(jnp.atleast_1d, x, name="atleast_1d") for x in xs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*xs, name=None):
    outs = [op_call(jnp.atleast_2d, x, name="atleast_2d") for x in xs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*xs, name=None):
    outs = [op_call(jnp.atleast_3d, x, name="atleast_3d") for x in xs]
    return outs[0] if len(outs) == 1 else outs


def hsplit(x, num_or_indices, name=None):
    return split(x, num_or_indices, axis=1 if x.ndim > 1 else 0)


def vsplit(x, num_or_indices, name=None):
    return split(x, num_or_indices, axis=0)


def dsplit(x, num_or_indices, name=None):
    return split(x, num_or_indices, axis=2)


def hstack(x, name=None):
    return concat(x, axis=1 if x[0].ndim > 1 else 0)


def vstack(x, name=None):
    xs = [unsqueeze(t, 0) if t.ndim == 1 else t for t in x]
    return concat(xs, axis=0)


def dstack(x, name=None):
    xs = [reshape(t, list(t.shape) + [1]) if t.ndim <= 2 else t for t in x]
    return concat(xs, axis=2)


def column_stack(x, name=None):
    xs = [unsqueeze(t, 1) if t.ndim == 1 else t for t in x]
    return concat(xs, axis=1)


def row_stack(x, name=None):
    return vstack(x)


def number_of_elements(x):
    return x.size


# in-place variants
reshape_ = inplace_variant(reshape)
squeeze_ = inplace_variant(squeeze)
unsqueeze_ = inplace_variant(unsqueeze)
flatten_ = inplace_variant(flatten)
transpose_ = inplace_variant(transpose)
cast_ = inplace_variant(cast)
