"""Shared op-definition helpers."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import op_call
from ..core.tensor import Tensor


def ensure_tensor(x, ref: Tensor | None = None):
    if isinstance(x, Tensor):
        return x
    dtype = None
    if ref is not None and isinstance(x, (int, float, bool)) and not isinstance(x, bool):
        # scalar operand adopts the tensor operand's dtype family (paddle promotion)
        dtype = ref.dtype
    return Tensor(x, dtype=dtype)


def raw(x):
    return x._data if isinstance(x, Tensor) else x


def unary(jfn, opname):
    def op(x, name=None):
        return op_call(jfn, x, name=opname)

    op.__name__ = opname
    return op


def binary(jfn, opname):
    def op(x, y, name=None):
        return op_call(jfn, x, y, name=opname)

    op.__name__ = opname
    return op


def logical(jfn, opname):
    """Comparison/logical op: never differentiated (bool/int output)."""

    def op(x, y=None, name=None):
        if y is None:
            return op_call(jfn, x, name=opname, n_diff=0)
        return op_call(jfn, x, y, name=opname, n_diff=0)

    op.__name__ = opname
    return op


def norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def inplace_variant(fn):
    """Build the paddle `op_`(in-place) from the functional op.

    Autograd semantics match the reference's inplace handling
    (eager/auto_code_generator inplace ad_funcs + version counters):
    - leaf tensor requiring grad → error (torch/paddle both forbid it);
    - non-leaf: the recorded node must link to the PRODUCER of the
      pre-mutation value, so the mutated tensor object is swapped out of
      the new node's input list for a shadow alias carrying the old
      (node, out_idx) link — otherwise the node would point at itself.
    """

    def op_(x, *args, **kwargs):
        from ..core.dispatch import grad_enabled

        old_node, old_idx = x._node, x._out_idx
        if not x.stop_gradient and old_node is None and grad_enabled():
            raise RuntimeError(
                f"{fn.__name__}_(): an in-place operation on a leaf Tensor "
                "that requires grad is not allowed — operate on a "
                "computed value or use the out-of-place op")
        out = fn(x, *args, **kwargs)
        if out._node is not None and old_node is not None:
            shadow = Tensor(x._data, _internal=True,
                            stop_gradient=x.stop_gradient)
            shadow._node = old_node
            shadow._out_idx = old_idx
            out._node.inputs = [shadow if t is x else t
                                for t in out._node.inputs]
        x._assign_raw(out._data)
        # in-place on a graph-recorded tensor keeps the new node (paddle semantics)
        x._node = out._node
        x._out_idx = out._out_idx
        x.stop_gradient = x.stop_gradient and out.stop_gradient
        return x

    op_.__name__ = fn.__name__ + "_"
    return op_
