"""Random ops threaded through the global trace-aware PRNG key
(≙ python/paddle/tensor/random.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import dtype as dtypes
from ..core.dispatch import op_call
from ..core.rng import next_key
from ..core.tensor import Tensor
from .creation import _dt, _shape


def _mk(data):
    return Tensor(data, _internal=True)


def _key(seed):
    """seed=0 means "draw from the global stateful stream" (reference
    convention, python/paddle/tensor/random.py); a nonzero seed pins the
    op to a reproducible key independent of global RNG state."""
    return next_key() if not seed else jax.random.PRNGKey(int(seed))


def rand(shape, dtype=None, name=None):
    return _mk(jax.random.uniform(next_key(), _shape(shape), _dt(dtype)))


def randn(shape, dtype=None, name=None):
    return _mk(jax.random.normal(next_key(), _shape(shape), _dt(dtype)))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    return _mk(jax.random.uniform(_key(seed), _shape(shape), _dt(dtype),
                                  minval=min, maxval=max))


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    x._assign_raw(jax.random.uniform(_key(seed), tuple(x.shape), x._data.dtype,
                                     minval=min, maxval=max))
    return x


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._data if isinstance(mean, Tensor) else mean
        s = std._data if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s))
        return _mk(jax.random.normal(next_key(), shp) * s + m)
    return _mk(jax.random.normal(next_key(), _shape(shape)) * std + mean)


def normal_(x, mean=0.0, std=1.0, name=None):
    x._assign_raw(jax.random.normal(next_key(), tuple(x.shape), x._data.dtype) * std + mean)
    return x


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype=None, name=None):
    return _mk(jax.random.normal(_key(seed), _shape(shape), _dt(dtype)) * std + mean)


def standard_normal(shape, dtype=None, name=None):
    return randn(shape, dtype)


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    return _mk(jax.random.randint(next_key(), _shape(shape), low, high,
                                  dtypes.convert_dtype(dtype)))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    if high is None:
        low, high = 0, low
    dt = dtypes.convert_dtype(dtype) if dtype else x.dtype
    return _mk(jax.random.randint(next_key(), tuple(x.shape), low, high, dt))


def randperm(n, dtype="int64", name=None):
    return _mk(jax.random.permutation(next_key(), n).astype(dtypes.convert_dtype(dtype)))


def shuffle(x, axis=0, name=None):
    return op_call(lambda a, k: jax.random.permutation(k, a, axis=axis, independent=False),
                   x, next_key(), name="shuffle", n_diff=1)


def bernoulli(x, name=None):
    return op_call(lambda a, k: jax.random.bernoulli(k, a).astype(a.dtype),
                   x, next_key(), name="bernoulli", n_diff=0)


def bernoulli_(x, p=0.5, name=None):
    x._assign_raw(jax.random.bernoulli(next_key(), p, tuple(x.shape)).astype(x._data.dtype))
    return x


def poisson(x, name=None):
    return op_call(lambda a, k: jax.random.poisson(k, a).astype(a.dtype),
                   x, next_key(), name="poisson", n_diff=0)


def multinomial(x, num_samples=1, replacement=False, name=None):
    def f(a, k):
        logits = jnp.log(jnp.maximum(a, 1e-30))
        if a.ndim == 1:
            return jax.random.choice(k, a.shape[0], (num_samples,),
                                     replace=replacement, p=a / a.sum()).astype(jnp.int64)
        keys = jax.random.split(k, a.shape[0])
        return jax.vmap(lambda kk, p: jax.random.choice(
            kk, a.shape[-1], (num_samples,), replace=replacement, p=p / p.sum()))(
            keys, a).astype(jnp.int64)

    return op_call(f, x, next_key(), name="multinomial", n_diff=0)


def rand_like(x, dtype=None, name=None):
    dt = dtypes.convert_dtype(dtype) if dtype else x.dtype
    return _mk(jax.random.uniform(next_key(), tuple(x.shape), dt))


def randn_like(x, dtype=None, name=None):
    dt = dtypes.convert_dtype(dtype) if dtype else x.dtype
    return _mk(jax.random.normal(next_key(), tuple(x.shape), dt))


def exponential_(x, lam=1.0, name=None):
    x._assign_raw(jax.random.exponential(next_key(), tuple(x.shape), x._data.dtype) / lam)
    return x


def binomial(count, prob, name=None):
    def f(n, p, k):
        # same x64 literal-dtype hazard as distribution/extended.py
        # _binomial_sample: sample at the x64-consistent width
        dt = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
        return jax.random.binomial(k, n.astype(dt),
                                   p.astype(dt)).astype(jnp.int64)

    return op_call(f, count, prob, next_key(), name="binomial", n_diff=0)


def log_normal(mean=1.0, std=2.0, shape=None, name=None):
    return _mk(jnp.exp(jax.random.normal(next_key(), _shape(shape)) * std + mean))
