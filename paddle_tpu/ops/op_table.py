"""Single-source op table: name -> (impl, n_diff, test spec).

Reference parity: the YAML op suite (/root/reference/paddle/phi/ops/yaml/
ops.yaml, 5,446 lines) is the reference's single source of truth from which
API/kernels/tests are generated; SURVEY §7-1 prescribes the same for this
framework. This table IS that registry for the python-surface ops: each
entry records the public callable, its differentiability, an input-domain
test spec, and (where one exists) an independent NumPy reference — from
which tests/test_op_table_sweep.py AUTO-GENERATES the OpTest-style sweep
(forward parity + analytic-vs-numeric grad checks across fp32/bf16,
≙ test/legacy_test/op_test.py:418) and tools/op_coverage.py derives the
coverage report vs ops.yaml.
"""
from __future__ import annotations

import math as _math
from dataclasses import dataclass, field
from typing import Any, Callable

import numpy as np

__all__ = ["OpSpec", "OPS", "register", "testable_specs"]


@dataclass
class OpSpec:
    name: str
    fn: Callable                     # public op over Tensors
    n_inputs: int = 1
    diff: bool = True                # has a meaningful gradient
    domain: tuple = (-2.0, 2.0)      # sample range for float inputs
    domains: tuple | None = None     # per-input ranges (overrides domain)
    int_inputs: tuple = ()           # positions sampled as ints
    no_grad_inputs: tuple = ()       # float positions with no defined grad
    ref: Callable | None = None      # independent NumPy reference
    shape: tuple = (2, 3)
    shapes: tuple | None = None      # per-input shapes
    kwargs: dict = field(default_factory=dict)
    rtol: float = 1e-5
    atol: float = 1e-6
    bf16: bool = True                # include in the bf16 sweep
    int_high: int = 5                # exclusive upper bound for int samples
    tags: tuple = ()                 # e.g. ("reduction", "activation")

    def sample_inputs(self, seed=0, dtype="float32"):
        rs = np.random.RandomState(seed)
        outs = []
        shapes = self.shapes or (self.shape,) * self.n_inputs
        for i in range(self.n_inputs):
            shp = shapes[i]
            if i in self.int_inputs:
                outs.append(rs.randint(0, self.int_high, shp).astype("int64"))
                continue
            lo, hi = (self.domains[i] if self.domains else self.domain)
            outs.append((lo + (hi - lo) * rs.rand(*shp)).astype(dtype))
        return tuple(outs)


OPS: dict[str, OpSpec] = {}


def register(spec: OpSpec):
    OPS[spec.name] = spec
    return spec


def testable_specs(diff_only=False):
    out = [s for s in OPS.values()]
    if diff_only:
        out = [s for s in out if s.diff]
    return sorted(out, key=lambda s: s.name)


# --------------------------------------------------------------------------
# table population: pulls the module-level op groups so there is ONE place
# that knows every op; domains/refs are the per-op test metadata.

_POS = (0.2, 2.0)           # strictly positive
_UNIT = (-0.95, 0.95)       # open (-1, 1)
_GT1 = (1.05, 3.0)          # > 1
_SAFE = (-2.0, 2.0)

_erf_np = np.vectorize(_math.erf)
_gamma_ln = np.vectorize(_math.lgamma)

#: unary: name -> (domain, diff, numpy ref or None)
_UNARY_META = {
    "exp": (_SAFE, True, np.exp), "expm1": (_SAFE, True, np.expm1),
    "log": (_POS, True, np.log), "log2": (_POS, True, np.log2),
    "log10": (_POS, True, np.log10), "log1p": (_POS, True, np.log1p),
    "sqrt": (_POS, True, np.sqrt),
    "rsqrt": (_POS, True, lambda x: 1.0 / np.sqrt(x)),
    "square": (_SAFE, True, np.square), "abs": (_SAFE, True, np.abs),
    "neg": (_SAFE, True, np.negative),
    "sin": (_SAFE, True, np.sin), "cos": (_SAFE, True, np.cos),
    "tan": ((-1.0, 1.0), True, np.tan),
    "asin": (_UNIT, True, np.arcsin), "acos": (_UNIT, True, np.arccos),
    "atan": (_SAFE, True, np.arctan),
    "sinh": (_SAFE, True, np.sinh), "cosh": (_SAFE, True, np.cosh),
    "tanh": (_SAFE, True, np.tanh),
    "asinh": (_SAFE, True, np.arcsinh), "acosh": (_GT1, True, np.arccosh),
    "atanh": (_UNIT, True, np.arctanh),
    "ceil": (_SAFE, False, np.ceil), "floor": (_SAFE, False, np.floor),
    "round": (_SAFE, False, np.round), "trunc": (_SAFE, False, np.trunc),
    "frac": (_SAFE, False, lambda x: x - np.trunc(x)),
    "sign": (_SAFE, False, np.sign),
    "sigmoid": (_SAFE, True, lambda x: 1 / (1 + np.exp(-x))),
    "reciprocal": (_POS, True, np.reciprocal),
    "erf": (_SAFE, True, _erf_np),
    "erfinv": (_UNIT, True, None),
    "lgamma": (_POS, True, _gamma_ln),
    "digamma": (_POS, True, None),
    "i0": (_SAFE, True, np.i0),
    "rad2deg": (_SAFE, True, np.rad2deg),
    "deg2rad": (_SAFE, True, np.deg2rad),
}

#: binary: name -> (per-input domains, diff, ref)
_BINARY_META = {
    "add": ((_SAFE, _SAFE), True, np.add),
    "subtract": ((_SAFE, _SAFE), True, np.subtract),
    "multiply": ((_SAFE, _SAFE), True, np.multiply),
    "divide": ((_SAFE, _POS), True, np.divide),
    "floor_divide": ((_SAFE, _POS), False, np.floor_divide),
    "mod": ((_SAFE, _POS), False, np.mod),
    "pow": ((_POS, _SAFE), True, np.power),
    "maximum": ((_SAFE, _SAFE), True, np.maximum),
    "minimum": ((_SAFE, _SAFE), True, np.minimum),
    "fmax": ((_SAFE, _SAFE), True, np.fmax),
    "fmin": ((_SAFE, _SAFE), True, np.fmin),
    "atan2": ((_SAFE, _POS), True, np.arctan2),
    "heaviside": ((_SAFE, _SAFE), False, np.heaviside),
    "hypot": ((_SAFE, _SAFE), True, np.hypot),
    "copysign": ((_SAFE, _SAFE), True, np.copysign),
    "nextafter": ((_SAFE, _SAFE), False, np.nextafter),
    "logaddexp": ((_SAFE, _SAFE), True, np.logaddexp),
    "ldexp": ((_SAFE, (-2.0, 2.0)), True, None),
}

#: logical / comparison (never differentiable); int-valued ops get int inputs
_LOGICAL_META = {
    "equal": np.equal, "not_equal": np.not_equal,
    "less_than": np.less, "less_equal": np.less_equal,
    "greater_than": np.greater, "greater_equal": np.greater_equal,
    "logical_and": None, "logical_or": None, "logical_xor": None,
    "logical_not": None,
    "isnan": np.isnan, "isinf": np.isinf, "isfinite": np.isfinite,
    "signbit": np.signbit,
}
_INT_LOGICAL = {"bitwise_and": np.bitwise_and, "bitwise_or": np.bitwise_or,
                "bitwise_xor": np.bitwise_xor, "bitwise_not": np.invert,
                "gcd": np.gcd, "lcm": np.lcm,
                "left_shift": np.left_shift, "right_shift": np.right_shift}


def _populate():
    import paddle_tpu as pd

    from . import math as m
    from . import reduction as r
    from . import manipulation as mp
    from . import linalg as la
    from .. import nn

    F = nn.functional

    for name, (dom, diff, ref) in _UNARY_META.items():
        register(OpSpec(name, getattr(m, name), 1, diff, domain=dom, ref=ref,
                        tags=("unary",)))
    for name, (doms, diff, ref) in _BINARY_META.items():
        register(OpSpec(name, getattr(m, name), 2, diff, domains=doms,
                        ref=ref, tags=("binary",)))
    for name, ref in _LOGICAL_META.items():
        n = 1 if name in ("logical_not", "isnan", "isinf", "isfinite",
                          "signbit") else 2
        register(OpSpec(name, getattr(m, name), n, False, ref=ref,
                        bf16=False, tags=("logical",)))
    for name, ref in _INT_LOGICAL.items():
        n = 1 if name == "bitwise_not" else 2
        register(OpSpec(name, getattr(m, name), n, False, ref=ref,
                        int_inputs=tuple(range(n)), bf16=False,
                        tags=("logical",)))

    # ---- reductions
    for name, ref in (("sum", np.sum), ("mean", np.mean),
                      ("prod", np.prod), ("max", np.max), ("min", np.min),
                      ("amax", np.max), ("amin", np.min)):
        register(OpSpec(name, getattr(r, name), 1, True, ref=ref,
                        shape=(3, 4), tags=("reduction",)))
    register(OpSpec("logsumexp", r.logsumexp, 1, True,
                    ref=lambda x: np.log(np.sum(np.exp(x))), shape=(3, 4),
                    tags=("reduction",)))
    register(OpSpec("all", r.all, 1, False, ref=np.all, bf16=False,
                    int_inputs=(0,), tags=("reduction",)))
    register(OpSpec("any", r.any, 1, False, ref=np.any, bf16=False,
                    int_inputs=(0,), tags=("reduction",)))
    register(OpSpec("nansum", r.nansum, 1, True, ref=np.nansum,
                    tags=("reduction",)))
    register(OpSpec("nanmean", r.nanmean, 1, True, ref=np.nanmean,
                    tags=("reduction",)))
    register(OpSpec("median", r.median, 1, True, ref=np.median,
                    shape=(3, 5), tags=("reduction",)))
    register(OpSpec("std", r.std, 1, True,
                    ref=lambda x: np.std(x, ddof=1), shape=(3, 4),
                    rtol=1e-4, tags=("reduction",)))
    register(OpSpec("var", r.var, 1, True,
                    ref=lambda x: np.var(x, ddof=1), shape=(3, 4),
                    rtol=1e-4, tags=("reduction",)))

    # ---- manipulation (shape ops; grads are pure data movement)
    register(OpSpec("reshape", lambda x: mp.reshape(x, [3, 2]), 1, True,
                    ref=lambda x: np.reshape(x, (3, 2)),
                    tags=("manipulation",)))
    register(OpSpec("transpose", lambda x: mp.transpose(x, [1, 0]), 1, True,
                    ref=lambda x: np.transpose(x, (1, 0)),
                    tags=("manipulation",)))
    register(OpSpec("flatten", mp.flatten, 1, True,
                    ref=lambda x: np.reshape(x, (-1,)),
                    tags=("manipulation",)))
    register(OpSpec("squeeze", lambda x: mp.squeeze(x, 0), 1, True,
                    shape=(1, 4), ref=lambda x: np.squeeze(x, 0),
                    tags=("manipulation",)))
    register(OpSpec("unsqueeze", lambda x: mp.unsqueeze(x, 0), 1, True,
                    ref=lambda x: x[None], tags=("manipulation",)))
    register(OpSpec("flip", lambda x: mp.flip(x, [0]), 1, True,
                    ref=lambda x: np.flip(x, 0), tags=("manipulation",)))
    register(OpSpec("roll", lambda x: mp.roll(x, 1), 1, True,
                    ref=lambda x: np.roll(x, 1), tags=("manipulation",)))
    register(OpSpec("tile", lambda x: mp.tile(x, [2, 1]), 1, True,
                    ref=lambda x: np.tile(x, (2, 1)), tags=("manipulation",)))
    register(OpSpec("concat", lambda x, y: mp.concat([x, y]), 2, True,
                    ref=lambda x, y: np.concatenate([x, y]),
                    tags=("manipulation",)))
    register(OpSpec("stack", lambda x, y: mp.stack([x, y]), 2, True,
                    ref=lambda x, y: np.stack([x, y]),
                    tags=("manipulation",)))
    register(OpSpec("split", lambda x: mp.split(x, 2, axis=1)[0], 1, True,
                    shape=(2, 4), ref=lambda x: np.split(x, 2, axis=1)[0],
                    tags=("manipulation",)))
    register(OpSpec("chunk", lambda x: mp.chunk(x, 2, axis=0)[1], 1, True,
                    shape=(4, 3),
                    ref=lambda x: np.split(x, 2, axis=0)[1],
                    tags=("manipulation",)))
    register(OpSpec("cast", lambda x: x.astype("float64").astype("float32"),
                    1, True, ref=lambda x: x, tags=("manipulation",)))
    register(OpSpec("clip", lambda x: x.clip(-1.0, 1.0), 1, True,
                    ref=lambda x: np.clip(x, -1, 1), tags=("manipulation",)))
    register(OpSpec("cumsum", lambda x: pd.cumsum(x, 0), 1, True,
                    ref=lambda x: np.cumsum(x, 0), tags=("manipulation",)))
    register(OpSpec("cumprod", lambda x: pd.cumprod(x, 0), 1, True,
                    domain=_POS, ref=lambda x: np.cumprod(x, 0),
                    tags=("manipulation",)))
    register(OpSpec("gather", lambda x, i: mp.gather(x, i), 2, True,
                    shapes=((4, 3), (2,)), int_inputs=(1,), int_high=4,
                    ref=lambda x, i: x[i], tags=("manipulation",)))
    register(OpSpec("index_select",
                    lambda x, i: mp.index_select(x, i, axis=0), 2, True,
                    shapes=((4, 3), (2,)), int_inputs=(1,), int_high=4,
                    ref=lambda x, i: x[i], tags=("manipulation",)))
    register(OpSpec("broadcast_to", lambda x: mp.broadcast_to(x, [4, 2, 3]),
                    1, True, ref=lambda x: np.broadcast_to(x, (4, 2, 3)),
                    tags=("manipulation",)))

    # ---- linalg
    register(OpSpec("matmul", la.matmul, 2, True,
                    shapes=((2, 3), (3, 4)),
                    ref=lambda a, b: a @ b, tags=("linalg",)))
    register(OpSpec("matmul_batched", la.matmul, 2, True,
                    shapes=((2, 2, 3), (2, 3, 4)),
                    ref=lambda a, b: a @ b, tags=("linalg",)))
    register(OpSpec("dot", la.dot, 2, True, shapes=((4,), (4,)),
                    ref=np.dot, tags=("linalg",)))
    register(OpSpec("t", lambda x: mp.t(x), 1, True,
                    ref=lambda x: x.T, tags=("linalg",)))
    register(OpSpec("norm_fro", lambda x: la.norm(x), 1, True,
                    ref=np.linalg.norm, tags=("linalg",)))
    register(OpSpec("outer", la.outer, 2, True, shapes=((3,), (4,)),
                    ref=np.outer, tags=("linalg",)))

    # ---- activations / nn functional
    def _np_softmax(x):
        e = np.exp(x - x.max(-1, keepdims=True))
        return e / e.sum(-1, keepdims=True)

    acts = [
        ("relu", F.relu, _SAFE, lambda x: np.maximum(x, 0)),
        ("relu6", F.relu6, (-8.0, 8.0),
         lambda x: np.clip(x, 0, 6)),
        ("elu", F.elu, _SAFE,
         lambda x: np.where(x > 0, x, np.exp(x) - 1)),
        ("selu", F.selu, _SAFE, None),
        ("celu", F.celu, _SAFE, None),
        ("gelu", F.gelu, _SAFE, None),
        ("silu", F.silu, _SAFE, lambda x: x / (1 + np.exp(-x))),
        ("mish", F.mish, _SAFE, None),
        ("softplus", F.softplus, _SAFE,
         lambda x: np.log1p(np.exp(x))),
        ("softsign", F.softsign, _SAFE, lambda x: x / (1 + np.abs(x))),
        ("hardtanh", F.hardtanh, _SAFE, lambda x: np.clip(x, -1, 1)),
        ("hardsigmoid", F.hardsigmoid, (-8.0, 8.0), None),
        ("hardswish", F.hardswish, (-8.0, 8.0), None),
        ("leaky_relu", F.leaky_relu, _SAFE,
         lambda x: np.where(x > 0, x, 0.01 * x)),
        ("log_sigmoid", F.log_sigmoid, _SAFE,
         lambda x: -np.log1p(np.exp(-x))),
        ("tanhshrink", F.tanhshrink, _SAFE, lambda x: x - np.tanh(x)),
        ("softshrink", F.softshrink, _SAFE, None),
        ("hardshrink", F.hardshrink, _SAFE, None),
        ("softmax", F.softmax, _SAFE, _np_softmax),
        ("log_softmax", F.log_softmax, _SAFE,
         lambda x: np.log(_np_softmax(x))),
    ]
    for name, fn, dom, ref in acts:
        register(OpSpec(f"act_{name}", fn, 1, True, domain=dom, ref=ref,
                        tags=("activation",)))

    # ---- more linalg / tensor algebra
    register(OpSpec("bmm", pd.bmm, 2, True, shapes=((2, 2, 3), (2, 3, 4)),
                    ref=lambda a, b: a @ b, tags=("linalg",)))
    register(OpSpec("mv", pd.mv, 2, True, shapes=((3, 4), (4,)),
                    ref=lambda a, b: a @ b, tags=("linalg",)))
    register(OpSpec("kron", pd.kron, 2, True, shapes=((2, 2), (2, 3)),
                    ref=np.kron, tags=("linalg",)))
    register(OpSpec("cross", lambda a, b: pd.cross(a, b, axis=-1), 2, True,
                    shapes=((2, 3), (2, 3)),
                    ref=lambda a, b: np.cross(a, b), tags=("linalg",)))
    register(OpSpec("trace_op", pd.trace, 1, True, shape=(3, 3),
                    ref=np.trace, tags=("linalg",)))
    register(OpSpec("diag", pd.diag, 1, True, shape=(4,),
                    ref=np.diag, tags=("linalg",)))
    register(OpSpec("diagonal", pd.diagonal, 1, True, shape=(3, 3),
                    ref=np.diagonal, tags=("linalg",)))
    register(OpSpec("tril", pd.tril, 1, True, shape=(3, 3),
                    ref=np.tril, tags=("linalg",)))
    register(OpSpec("triu", pd.triu, 1, True, shape=(3, 3),
                    ref=np.triu, tags=("linalg",)))
    register(OpSpec("einsum_ij_jk", lambda a, b: pd.einsum("ij,jk->ik", a, b),
                    2, True, shapes=((2, 3), (3, 4)),
                    ref=lambda a, b: a @ b, tags=("linalg",)))
    register(OpSpec("addmm", lambda x, a, b: pd.addmm(x, a, b), 3, True,
                    shapes=((2, 4), (2, 3), (3, 4)),
                    ref=lambda x, a, b: x + a @ b, tags=("linalg",)))

    # ---- losses / similarity (functional)
    register(OpSpec("mse_loss", F.mse_loss, 2, True,
                    ref=lambda a, b: np.mean((a - b) ** 2), tags=("loss",)))
    register(OpSpec("l1_loss", F.l1_loss, 2, True,
                    ref=lambda a, b: np.mean(np.abs(a - b)), tags=("loss",)))
    register(OpSpec("smooth_l1", F.smooth_l1_loss, 2, True, ref=None,
                    tags=("loss",)))
    register(OpSpec("kl_div", lambda a, b: F.kl_div(a, b), 2, True,
                    domains=(((-3.0, -0.1)), (0.1, 1.0)), ref=None,
                    tags=("loss",)))
    register(OpSpec("cosine_similarity",
                    lambda a, b: F.cosine_similarity(a, b), 2, True,
                    ref=lambda a, b: np.sum(a * b, -1) /
                    (np.linalg.norm(a, axis=-1) * np.linalg.norm(b, axis=-1)),
                    tags=("loss",)))
    register(OpSpec("normalize", lambda x: F.normalize(x), 1, True,
                    ref=lambda x: x / np.linalg.norm(x, axis=-1,
                                                     keepdims=True),
                    tags=("loss",)))

    # ---- more manipulation
    register(OpSpec("pad", lambda x: pd.nn.functional.pad(x, [1, 1]), 1,
                    True, ref=lambda x: np.pad(x, ((0, 0), (1, 1))),
                    tags=("manipulation",)))
    register(OpSpec("take_along_axis",
                    lambda x, i: pd.take_along_axis(x, i, axis=1), 2, True,
                    shapes=((3, 4), (3, 2)), int_inputs=(1,), int_high=4,
                    ref=lambda x, i: np.take_along_axis(x, i, 1),
                    tags=("manipulation",)))
    register(OpSpec("repeat_interleave",
                    lambda x: pd.repeat_interleave(x, 2, axis=0), 1, True,
                    ref=lambda x: np.repeat(x, 2, axis=0),
                    tags=("manipulation",)))
    register(OpSpec("searchsorted", lambda s, v: pd.searchsorted(s, v), 2,
                    False, shapes=((5,), (3,)),
                    domains=((0.0, 1.0), (0.0, 1.0)), bf16=False, ref=None,
                    tags=("search",)))
    register(OpSpec("masked_fill",
                    lambda x, m: pd.masked_fill(x, m > 2, 0.5), 2, True,
                    int_inputs=(1,),
                    ref=lambda x, m: np.where(m > 2, 0.5, x),
                    tags=("manipulation",)))

    # sort/search (grads flow through sort)
    register(OpSpec("sort", lambda x: mp.sort(x, axis=-1), 1, True,
                    ref=lambda x: np.sort(x, axis=-1), tags=("search",)))
    register(OpSpec("argsort", lambda x: mp.argsort(x, axis=-1), 1, False,
                    ref=lambda x: np.argsort(x, axis=-1), bf16=False,
                    tags=("search",)))
    register(OpSpec("argmax", lambda x: pd.argmax(x, axis=-1), 1, False,
                    ref=lambda x: np.argmax(x, -1), bf16=False,
                    tags=("search",)))
    register(OpSpec("argmin", lambda x: pd.argmin(x, axis=-1), 1, False,
                    ref=lambda x: np.argmin(x, -1), bf16=False,
                    tags=("search",)))
    register(OpSpec("topk", lambda x: pd.topk(x, 2)[0], 1, True,
                    shape=(3, 5),
                    ref=lambda x: np.sort(x, -1)[:, ::-1][:, :2],
                    tags=("search",)))
    register(OpSpec("kthvalue", lambda x: pd.kthvalue(x, 2)[0], 1, True,
                    shape=(3, 5),
                    ref=lambda x: np.sort(x, -1)[:, 1], tags=("search",)))
    register(OpSpec("where", lambda c, x, y: mp.where((c > 2), x, y), 3,
                    True, int_inputs=(0,),
                    ref=lambda c, x, y: np.where(c > 2, x, y),
                    tags=("search",)))


_populated = False


def ensure_populated():
    global _populated
    if not _populated:
        _populated = True
        _populate()
        from .op_table_ext import populate_ext

        populate_ext()
        from .op_table_more import populate_more

        populate_more()


#: Reference ops whose public surface is a layer / optimizer / random /
#: framework API rather than a pure tensor-in/tensor-out op: the generic
#: grad-checked sweep cannot drive them; each waiver names the dedicated
#: coverage that does (VERDICT r4 Missing #4 "or a written waiver per op").
SWEEP_WAIVERS: dict[str, str] = {}


def waive(name: str, why: str):
    SWEEP_WAIVERS[name] = why
